"""ModelSelection — best-subset GLM search (maxr / forward / backward).

Reference: hex/modelselection/ModelSelection.java:24 — modes maxr,
maxrsweep, forward, backward over GLM; reports the best predictor subset
per model size with R²/deviance, using sweep operators on the Gram.

TPU re-design: maxr/forward/backward fit each candidate with one MXU
Gram + Cholesky solve (gaussian: exact in one IRLS step) on a shared
design. maxrsweep is the REAL sweep-operator mode: the augmented
weighted Gram [[X'WX, X'Wy], [y'WX, y'Wy]] is computed ONCE on device,
each candidate's SSE-if-added reads off the swept matrix in O(1)
(a_yy − a_jy²/a_jj), and accepting a predictor is one O(p²) sweep — no
per-candidate refits at all (ModelSelection.java maxrsweep, gaussian
only like the reference)."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import GLM_DEFAULTS, H2OGeneralizedLinearEstimator
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.persist import (model_from_meta, model_to_meta,
                              register_model_class)

MS_DEFAULTS: Dict = dict(
    mode="maxr", max_predictor_number=1, min_predictor_number=1,
    # reference ModelSelection defaults tweedie_link_power to 0.0
    # (h2o-py h2o/estimators/model_selection.py:51)
    tweedie_link_power=0.0,
)


class ModelSelectionModel(Model):
    algo = "modelselection"

    def __init__(self, key, params, spec, best_model, results):
        super().__init__(key, params, spec)
        self.best_model = best_model
        self.results = results          # per-size rows

    def predict(self, frame):
        return self.best_model.predict(frame)

    def _predict_matrix(self, X, offset=None):
        return self.best_model._predict_matrix(X, offset=offset)

    def result(self):
        return self.results

    def coef(self):
        return self.best_model.coef()

    def _save_arrays(self):
        return {f"inner__{k}": v
                for k, v in self.best_model._save_arrays().items()}

    def _save_extra_meta(self):
        return {"inner_meta": model_to_meta(self.best_model),
                "results": self.results}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        inner_arrays = {k[len("inner__"):]: v for k, v in arrays.items()
                        if k.startswith("inner__")}
        m.best_model = model_from_meta(ex["inner_meta"], inner_arrays)
        m.results = ex["results"]
        return m


def _sweep(A: np.ndarray, k: int) -> np.ndarray:
    """The SWEEP operator on pivot k (hex/modelselection sweep-vector
    machinery / Goodnight 1979): after sweeping pivots S of the
    augmented Gram [[X'X, X'y], [y'X, y'y]], the bottom-right cell is
    the SSE of regressing y on X_S and the X'y column holds β_S."""
    d = A[k, k]
    if abs(d) < 1e-12:
        return A          # singular pivot: skip (collinear column)
    B = A - np.outer(A[:, k], A[k, :]) / d
    B[:, k] = A[:, k] / d
    B[k, :] = A[k, :] / d
    B[k, k] = -1.0 / d
    return B


def _maxrsweep_gaussian(Xe: np.ndarray, yv: np.ndarray, w: np.ndarray,
                        names: List[str], max_k: int):
    """Forward maxrsweep: ONE augmented weighted Gram, then each
    candidate's SSE-if-added reads off the current swept matrix in O(1)
    (a_yy − a_jy²/a_jj) — no per-candidate refits, the reference's
    maxrsweep efficiency trick (hex/modelselection/ModelSelection.java
    maxrsweep mode, gaussian only)."""
    n, p = Xe.shape
    ones = np.ones((n, 1))
    Z = np.concatenate([ones, Xe, yv[:, None]], axis=1)  # [n, p+2]
    Wz = Z * w[:, None]
    A = Z.T @ Wz                                          # augmented Gram
    A = _sweep(A, 0)                                      # intercept always in
    yy = p + 1
    chosen: List[int] = []
    steps = []
    for _ in range(max_k):
        best_j, best_sse = None, None
        for j in range(p):
            if j in chosen:
                continue
            jj = A[1 + j, 1 + j]
            if jj <= 1e-12:
                continue
            sse = A[yy, yy] - A[1 + j, yy] ** 2 / jj
            if best_sse is None or sse < best_sse:
                best_sse, best_j = sse, j
        if best_j is None:
            break
        A = _sweep(A, 1 + best_j)
        chosen.append(best_j)
        beta = {names[j]: float(A[1 + j, yy]) for j in chosen}
        beta["Intercept"] = float(A[0, yy])
        steps.append({"size": len(chosen),
                      "predictors": [names[j] for j in chosen],
                      "sse": float(A[yy, yy]),
                      "coefficients": beta})
    return steps


class H2OModelSelectionEstimator(ModelBuilder):
    algo = "modelselection"

    def __init__(self, **params):
        merged = dict(GLM_DEFAULTS)
        merged.update(MS_DEFAULTS)
        merged.update(params)
        for alias in ("lambda_", "lambda"):
            if alias in merged:
                merged["Lambda"] = merged.pop(alias)
        super().__init__(**merged)

    def _fit(self, cols: List[str], y, frame) -> Model:
        p = {k: v for k, v in self.params.items() if k not in MS_DEFAULTS}
        p.setdefault("Lambda", [0.0])
        est = H2OGeneralizedLinearEstimator(**p)
        est.train(x=cols, y=y, training_frame=frame)
        return est.model

    @staticmethod
    def _crit(model: Model) -> float:
        """Selection criterion: residual deviance (lower = better) —
        equals (1-R²)·TSS for gaussian, matches the reference's R² order."""
        return model.residual_deviance

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        p = self.params
        y = y or p.get("response_column")
        if training_frame is None or y is None:
            raise ValueError("ModelSelection needs training_frame and y")
        special = {y, p.get("weights_column"), p.get("offset_column")}
        preds = list(x) if x else [n for n in training_frame.names
                                   if n not in special]
        mode = (p.get("mode") or "maxr").lower()
        max_k = min(int(p.get("max_predictor_number", 1)), len(preds))
        min_k = max(1, int(p.get("min_predictor_number", 1)))
        job = Job("modelselection", work=float(max_k))

        def body(job):
            results = []
            fitted: Dict[Tuple[str, ...], Model] = {}

            def fit(cols: List[str]) -> Model:
                key = tuple(sorted(cols))
                if key not in fitted:
                    fitted[key] = self._fit(list(key), y, training_frame)
                return fitted[key]

            fam = (p.get("family") or "auto").lower()
            if mode == "maxrsweep":
                if fam not in ("auto", "gaussian"):
                    raise ValueError(
                        "maxrsweep supports gaussian only (the reference's "
                        "sweep-operator mode, ModelSelection.java)")
                import jax as _jax
                from h2o3_tpu.models.glm import expand_design
                from h2o3_tpu.models.model_base import build_training_spec
                spec = build_training_spec(
                    training_frame, y, x=preds,
                    weights_column=p.get("weights_column"),
                    classification=False)
                Xe, exp_names, _means = expand_design(spec)
                nrow = spec.nrow
                Xh = np.asarray(_jax.device_get(Xe),
                                np.float64)[:nrow]
                yh = np.asarray(_jax.device_get(spec.y),
                                np.float64)[:nrow]
                wh = np.asarray(_jax.device_get(spec.w),
                                np.float64)[:nrow]
                steps = _maxrsweep_gaussian(Xh, yh, wh, exp_names, max_k)
                tss = float((wh * (yh - np.average(yh, weights=wh))
                             ** 2).sum())
                for s in steps:
                    s["r2"] = 1.0 - s["sse"] / max(tss, 1e-30)
                    s["deviance"] = s["sse"]
                    results.append(s)
                    job.update(1.0)
                # final model: plain GLM refit on the best subset's BASE
                # columns (expanded enum levels 'col.lvl' collapse back)
                # — keeps the Model surface: predict/metrics/persist
                best_sz = min(results, key=lambda r: r["deviance"])
                base_cols = []
                for c in best_sz["predictors"]:
                    b = c.split(".")[0] if c.split(".")[0] in preds else c
                    if b not in base_cols:
                        base_cols.append(b)
                m = fit(base_cols)
            elif mode in ("maxr", "forward"):
                chosen: List[str] = []
                for k in range(1, max_k + 1):
                    # greedy add
                    cands = [c for c in preds if c not in chosen]
                    scored = [(self._crit(fit(chosen + [c])), c)
                              for c in cands]
                    _, addc = min(scored)
                    chosen = chosen + [addc]
                    if mode in ("maxr", "maxrsweep") and len(chosen) > 1:
                        # replacement sweeps: apply the BEST single swap,
                        # restart the scan, stop when none improves (the
                        # candidate lists must rebuild after every accepted
                        # swap or trials drift to a different subset size)
                        for _ in range(10):
                            best_c = self._crit(fit(chosen))
                            best_swap = None
                            for out_c in chosen:
                                for in_c in (c for c in preds
                                             if c not in chosen):
                                    trial = [c for c in chosen
                                             if c != out_c] + [in_c]
                                    cr = self._crit(fit(trial))
                                    if cr < best_c - 1e-10:
                                        best_c = cr
                                        best_swap = trial
                            if best_swap is None:
                                break
                            chosen = best_swap
                    m = fit(chosen)
                    results.append(self._row(k, chosen, m))
                    job.update(1.0)
            elif mode == "backward":
                chosen = list(preds)
                m = fit(chosen)
                results.append(self._row(len(chosen), chosen, m))
                while len(chosen) > min_k:
                    scored = [(self._crit(fit([c for c in chosen
                                               if c != drop])), drop)
                              for drop in chosen]
                    _, dropc = min(scored)
                    chosen = [c for c in chosen if c != dropc]
                    m = fit(chosen)
                    results.append(self._row(len(chosen), chosen, m))
                    job.update(1.0)
                results.reverse()
            else:
                raise ValueError(f"unsupported mode '{mode}'")
            best = min(results, key=lambda r: r["deviance"])
            best_model = fitted[tuple(sorted(best["predictors"]))]
            model = ModelSelectionModel(
                f"ms_{id(self) & 0xffffff:x}", self.params,
                _spec_of(best_model), best_model, results)
            model.training_metrics = best_model.training_metrics
            model.output["results"] = results
            model.output["best_predictors"] = best["predictors"]
            return model

        job.run(body)
        self.model = job.join()
        self.job = job
        from h2o3_tpu import dkv
        dkv.put(self.model.key, "model", self.model)
        return self

    @staticmethod
    def _row(k: int, chosen: List[str], m: Model) -> Dict:
        r2 = getattr(m.training_metrics, "r2", None)
        return {"size": k, "predictors": list(chosen),
                "deviance": m.residual_deviance,
                "r2": r2, "coefficients": m.coef()}

    def _train_impl(self, spec, valid_spec, job: Job):
        raise RuntimeError("ModelSelection overrides train() directly")


def _spec_of(model: Model):
    class _S:
        names = model.feature_names
        is_cat = model.feature_is_cat
        cat_domains = model.cat_domains
        response = model.response
        response_domain = model.response_domain
        nclasses = model.nclasses
    return _S()


register_model_class("modelselection", ModelSelectionModel)
