"""TreeSHAP (predict_contributions) for the stacked complete-array trees.

Reference: h2o-genmodel/src/main/java/hex/genmodel/algos/tree/TreeSHAP.java
(Lundberg & Lee path-dependent TreeSHAP: recursive EXTEND/UNWIND over the
tree with cover fractions), surfaced as ``model.predict_contributions``
via hex/Model.java scoring options + hex/genmodel/.../PredictContributions.

TPU re-design: the reference walks each tree recursively per row with a
mutable path array. Complete binary-array trees (models/tree.py) make the
whole computation static-shaped and batchable instead:

- every node m has a STATIC depth and ancestor list, so all (leaf, path)
  pairs become constant index matrices [M, D] computed once on host;
- the polynomial EXTEND over a leaf's path is a product of D factors
  (r_j + o_j z) — r = cover fraction, o = 1 iff the row follows the
  edge — with neutral (1 + 0z) factors padding inactive/duplicate slots,
  so coefficients are an unrolled static loop on [rows, M, D+1] tensors;
- UNWIND (synthetic division) runs per path slot as another unrolled
  loop, vectorized over rows × leaves on the VPU;
- contributions scatter into features via a one-hot einsum (MXU), not a
  scatter-add.

Duplicate features on a path are merged exactly as the reference's
EXTEND/UNWIND sequence nets out: their cover fractions multiply and the
row must follow ALL edges (o = product), with a single Shapley slot for
the merged feature.

Property (asserted in tests/test_treeshap.py): for every row,
sum(contributions) + bias == margin(x) to float tolerance, where bias =
sum over trees of the cover-weighted expected leaf value (+ the model's
init f0, added by callers).
"""
from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np


@lru_cache(maxsize=32)
def _path_constants(D: int):
    """Static path structure of a complete binary tree of depth D:
    for each node m (M = 2^(D+1)-1): its depth, the ancestor at each
    level j (path_par[m, j], root at j=0), the path child at level j+1
    (path_child[m, j]), whether that child is a right child, and the
    active-edge mask (j < depth[m])."""
    M = 2 ** (D + 1) - 1
    depth = np.zeros(M, np.int32)
    path_par = np.zeros((M, max(D, 1)), np.int32)
    path_child = np.zeros((M, max(D, 1)), np.int32)
    child_is_right = np.zeros((M, max(D, 1)), bool)
    active = np.zeros((M, max(D, 1)), bool)
    for m in range(M):
        d = int(np.floor(np.log2(m + 1)))
        depth[m] = d
        # ancestors root..m: (m+1) >> (d - j) - 1
        for j in range(d):
            p = ((m + 1) >> (d - j)) - 1
            c = ((m + 1) >> (d - j - 1)) - 1
            path_par[m, j] = p
            path_child[m, j] = c
            child_is_right[m, j] = (c % 2) == 0   # children 2p+1 (L), 2p+2 (R)
            active[m, j] = True
    # numpy (not jnp): these are lru-cached and may first be built inside
    # a jit trace — caching device arrays created there leaks tracers
    return depth, path_par, path_child, child_is_right, active


def _shapley_weight_table(D: int) -> jnp.ndarray:
    """wgt[k, s] = s! (k-1-s)! / k! for 1 <= k <= D, 0 <= s <= k-1
    (Shapley size weights over a path with k unique features)."""
    fact = [1.0]
    for i in range(1, D + 2):
        fact.append(fact[-1] * i)
    w = np.zeros((D + 1, max(D, 1)), np.float64)
    for k in range(1, D + 1):
        for s in range(k):
            w[k, s] = fact[s] * fact[k - 1 - s] / fact[k]
    return w.astype(np.float32)


def _one_tree_phi(X, feat, thr, na_left, is_split, node_w, value,
                  *, D: int, F: int):
    """Contributions of ONE tree: returns (phi [rows, F], bias scalar)."""
    rows = X.shape[0]
    depth, par, chd, cir, active = _path_constants(D)
    M = feat.shape[0]

    # per-node routing decision of every row: go_right[r, m]
    fcl = jnp.maximum(feat, 0)
    xf = jnp.take(X, fcl, axis=1)                      # [rows, M]
    go_right = jnp.where(jnp.isnan(xf), ~na_left[None, :],
                         xf >= thr[None, :])

    # per-edge data (leaf candidate m, edge slot j)
    f_e = jnp.where(active, feat[par], -1)             # [M, D]
    wp = node_w[par]
    wc = node_w[chd]
    r_e = jnp.where(active & (wp > 0), wc / jnp.maximum(wp, 1e-30), 1.0)
    r_e = jnp.clip(r_e, 0.0, 1.0)
    o_e = (jnp.take(go_right, par, axis=1) == cir[None, :, :])  # [rows, M, D]
    o_e = jnp.where(active[None, :, :], o_e, True)

    # effective-leaf validity: m is scored iff it is NOT split and every
    # ancestor IS split (rows can actually terminate there)
    anc_split = jnp.where(active, is_split[par], True).all(axis=1)
    valid = (~is_split) & anc_split                    # [M]

    # merge duplicate features on the path: first-occurrence grouping
    Dj = f_e.shape[1]
    same = (f_e[:, :, None] == f_e[:, None, :]) & active[:, :, None] \
        & active[:, None, :]                           # [M, D, D] j x j'
    lower = jnp.tril(jnp.ones((Dj, Dj), bool))         # j' <= j
    first = jnp.argmax(same & lower[None], axis=2)     # [M, D] first j'==f_j
    rep = active & (first == jnp.arange(Dj)[None, :])  # slot is representative
    group = (first[:, None, :] == jnp.arange(Dj)[None, :, None]) \
        & active[:, None, :]                           # [M, rep j0, member j]
    r_m = jnp.where(group, r_e[:, None, :], 1.0).prod(axis=2)   # [M, D]
    o_f = o_e.astype(jnp.float32)
    o_m = jnp.where(group[None], o_f[:, :, None, :], 1.0).prod(axis=3)
    # neutral factors for non-representative slots: (1 + 0 z)
    a = jnp.where(rep, r_m, 1.0)                       # [M, D]
    b_ = jnp.where(rep[None], o_m, 0.0)                # [rows, M, D]
    k = rep.sum(axis=1)                                # [M] unique count

    # EXTEND: P(z) = prod_j (a_j + b_j z), coeffs [rows, M, D+1]
    coef = jnp.zeros((rows, M, Dj + 1), jnp.float32).at[:, :, 0].set(1.0)
    for j in range(Dj):
        shifted = jnp.concatenate(
            [jnp.zeros((rows, M, 1), jnp.float32), coef[:, :, :-1]], axis=2)
        coef = a[None, :, j, None] * coef + b_[:, :, j, None] * shifted

    wgt_t = jnp.asarray(_shapley_weight_table(Dj))     # [D+1, D]
    wk = wgt_t[k]                                      # [M, D] weights per leaf
    leaf_val = jnp.where(valid, value, 0.0)            # [M]

    phi = jnp.zeros((rows, F), jnp.float32)
    for i in range(Dj):
        ri = a[:, i]                                   # merged r (neutral=1)
        oi = b_[:, :, i]                               # [rows, M]
        # UNWIND: divide P by (ri + oi z) -> Q coeffs q_0..q_{D-1}
        hot = oi > 0.5
        # hot branch: q_{D-1} = p_D; q_{j-1} = p_j - ri q_j
        q_hot = [None] * Dj
        run = coef[:, :, Dj]
        for s in range(Dj - 1, -1, -1):
            q_hot[s] = run
            run = coef[:, :, s] - ri[None, :] * run
        # cold branch: q_j = p_j / ri
        inv_r = 1.0 / jnp.maximum(ri, 1e-30)
        q = [jnp.where(hot, q_hot[s], coef[:, :, s] * inv_r[None, :])
             for s in range(Dj)]
        ssum = sum(q[s] * wk[None, :, s] for s in range(Dj))
        phi_i = (oi - ri[None, :]) * ssum * leaf_val[None, :]
        phi_i = jnp.where(rep[None, :, i], phi_i, 0.0)
        onehot = (f_e[:, i, None] == jnp.arange(F)[None, :]
                  ).astype(jnp.float32)                # [M, F]
        phi = phi + jax.lax.dot_general(
            phi_i, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    # bias: cover-weighted expected value over effective leaves
    w0 = jnp.maximum(node_w[0], 1e-30)
    bias = (leaf_val * node_w / w0).sum()
    return phi, bias


@partial(jax.jit, static_argnames=("D", "F"))
def _shap_stack(X, feat, thr, na_left, is_split, node_w, value, D: int,
                F: int):
    """Sum contributions over a [T, M] stack of trees (lax.scan)."""
    def body(carry, t):
        phi_acc, bias_acc = carry
        phi, bias = _one_tree_phi(X, feat[t], thr[t], na_left[t],
                                  is_split[t], node_w[t], value[t], D=D, F=F)
        return (phi_acc + phi, bias_acc + bias), 0
    init = (jnp.zeros((X.shape[0], F), jnp.float32), jnp.float32(0.0))
    (phi, bias), _ = jax.lax.scan(body, init, jnp.arange(feat.shape[0]))
    return phi, bias


def tree_shap_contributions(X, feat, thr, na_left, is_split, node_w, value,
                            max_depth: int, n_features: int,
                            row_chunk: int = 8192,
                            tree_scale=None):
    """Per-row feature contributions for a stacked tree ensemble.

    X [rows, F] f32 (NaN = NA); tree arrays [T, M]. ``tree_scale``
    optionally scales every tree's phi/bias (DRF averaging = 1/T).
    Returns (phi [rows, F] np.float32, bias float) with
    sum(phi[r]) + bias == ensemble margin(r) (+ f0, added by callers).
    """
    rows = X.shape[0]
    F = n_features
    # per-chunk intermediates scale as rows·M·(D+1); shrink the chunk for
    # deep trees so depth-10+ models stay inside device memory
    M = 2 ** (max_depth + 1) - 1
    row_chunk = max(64, min(row_chunk, int(6e7 / (M * (max_depth + 1)))))
    out = np.zeros((rows, F), np.float32)
    bias = 0.0
    feat = jnp.asarray(feat)
    thr = jnp.asarray(thr)
    na_left = jnp.asarray(na_left)
    is_split = jnp.asarray(is_split)
    node_w = jnp.asarray(node_w)
    value = jnp.asarray(value)
    if tree_scale is not None:
        value = value * jnp.float32(tree_scale)
    for s in range(0, rows, row_chunk):
        e = min(s + row_chunk, rows)
        phi, b = _shap_stack(jnp.asarray(X[s:e]), feat, thr, na_left,
                             is_split, node_w, value, max_depth, F)
        out[s:e] = np.asarray(jax.device_get(phi))
        bias = float(jax.device_get(b))
    return out, bias


# ---------------- scoring options sharing the stacked layout ------------

@partial(jax.jit, static_argnames=("D",))
def _leaf_nodes_stack(X, feat, thr, na_left, is_split, D: int):
    rows = X.shape[0]

    def one_tree(carry, t):
        nid = jnp.zeros(rows, jnp.int32)
        path = jnp.zeros(rows, jnp.int32)  # bit d: went right at depth d
        plen = jnp.zeros(rows, jnp.int32)  # splits actually taken
        for d in range(D):
            f = jnp.maximum(feat[t], 0)[nid]
            s = is_split[t][nid]
            th = thr[t][nid]
            nl = na_left[t][nid]
            xv = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            go_right = jnp.where(jnp.isnan(xv), ~nl, xv >= th)
            path = jnp.where(s, path | (go_right.astype(jnp.int32) << d),
                             path)
            plen = plen + s.astype(jnp.int32)
            nid = jnp.where(s, 2 * nid + 1 + go_right.astype(jnp.int32), nid)
        return carry, (nid, path, plen)

    _, (nids, paths, plens) = jax.lax.scan(one_tree, None,
                                           jnp.arange(feat.shape[0]))
    return nids.T, paths.T, plens.T   # [rows, T]


def leaf_node_assignment(X, feat, thr, na_left, is_split, max_depth: int,
                         kind: str = "Path"):
    """predict_leaf_node_assignment (hex/Model.java LeafNodeAssignment):
    kind='Node_ID' returns terminal node indices [rows, T] (complete-array
    node ids); 'Path' returns 'LRLR...' strings."""
    nids, paths, plens = _leaf_nodes_stack(
        jnp.asarray(X), jnp.asarray(feat), jnp.asarray(thr),
        jnp.asarray(na_left), jnp.asarray(is_split), max_depth)
    nids = np.asarray(jax.device_get(nids))
    if kind.lower() in ("node_id", "node_ids"):
        return nids
    paths = np.asarray(jax.device_get(paths))
    plens = np.asarray(jax.device_get(plens))
    out = np.empty(paths.shape, dtype=object)
    for (r, t), p in np.ndenumerate(paths):
        out[r, t] = "".join("R" if (p >> d) & 1 else "L"
                            for d in range(plens[r, t]))
    return out


class TreeScoringOptionsMixin:
    """predict_contributions / leaf assignment / staged probabilities for
    models holding stacked tree arrays (_feat/_thr/_na_left/_is_split/
    _value/_node_w). Mirrors hex/Model.java scoring options + h2o-py's
    model.predict_contributions / predict_leaf_node_assignment /
    staged_predict_proba."""

    def _contrib_scale(self):
        return None                      # GBM: leaf values already lr-scaled

    def _contrib_f0(self) -> float:
        return 0.0

    def predict_contributions(self, frame, output_format: str = "original",
                              top_n: int = 0, bottom_n: int = 0,
                              compare_abs: bool = False):
        """TreeSHAP contributions Frame: one column per feature +
        BiasTerm; sum of each row == margin (GBM: link space; DRF:
        probability/response space), matching
        hex/genmodel/algos/tree/TreeSHAP.java via /3/Predictions
        predict_contributions.

        ``output_format`` 'original' and 'compact' coincide here: trees
        split on original columns directly (enum codes as floats), so
        there is no one-hot expansion to compact — unlike the reference's
        XGBoost path where 'original' re-expands 1-hot contributions."""
        if str(output_format).lower() not in ("original", "compact"):
            raise ValueError(f"unknown output_format '{output_format}'")
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        if self.nclasses > 2:
            raise ValueError(
                "predict_contributions supports regression and binomial "
                "models only (reference restriction, hex/Model.java)")
        if getattr(self, "_node_w", None) is None:
            raise ValueError(
                "this model artifact predates contributions support "
                "(no per-node cover weights); retrain to enable")
        X = adapt_test_matrix(self, frame)
        phi, bias = tree_shap_contributions(
            np.asarray(jax.device_get(X)), self._feat, self._thr,
            self._na_left, self._is_split, self._node_w, self._value,
            self.max_depth, len(self.feature_names),
            tree_scale=self._contrib_scale())
        phi = phi[:frame.nrow]
        bias = bias + self._contrib_f0()
        names = list(self.feature_names) + ["BiasTerm"]
        cols = [phi[:, i] for i in range(phi.shape[1])]
        cols.append(np.full(phi.shape[0], bias, np.float32))
        if top_n or bottom_n:
            return _ranked_contrib_frame(names[:-1], phi, bias, top_n,
                                         bottom_n, compare_abs)
        return Frame(names, [Vec.from_numpy(c) for c in cols])

    def h(self, frame, variables):
        """Friedman-Popescu H statistic of `variables` on this model
        (hex/tree/FriedmanPopescusH.java; h2o-py model.h() via
        POST /3/FriedmansPopescusH). 0 = additive, larger = stronger
        interaction, NaN when spoiled by weak main effects."""
        from h2o3_tpu.models.hstat import friedman_popescu_h
        return friedman_popescu_h(self, frame, variables)

    def predict_leaf_node_assignment(self, frame, type: str = "Path"):
        """Terminal-node assignment per tree (hex/Model.java
        LeafNodeAssignment): type='Path' → 'LRLR' strings, 'Node_ID' →
        complete-array node indices."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        X = adapt_test_matrix(self, frame)
        out = leaf_node_assignment(
            np.asarray(jax.device_get(X)), self._feat, self._thr,
            self._na_left, self._is_split, self.max_depth, kind=type)
        out = out[:frame.nrow]
        T = out.shape[1]
        K = getattr(self, "_K", 1)
        names = [(f"T{t // K + 1}.C{t % K + 1}" if K > 1 else f"T{t + 1}")
                 for t in range(T)]
        if type.lower() in ("node_id", "node_ids"):
            vecs = [Vec.from_numpy(out[:, t].astype(np.float64))
                    for t in range(T)]
        else:
            from h2o3_tpu.frame.vec import T_STR
            vecs = [Vec.from_numpy(np.asarray(
                [str(v) for v in out[:, t]], dtype=object), vtype=T_STR)
                for t in range(T)]
        return Frame(names, vecs)

    def staged_predict_proba(self, frame):
        """Class probabilities after each boosting stage (binomial only,
        hex/Model.java staged_predict_proba)."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        if self.nclasses != 2:
            raise ValueError("staged_predict_proba is binomial-only")
        X = adapt_test_matrix(self, frame)
        margins = staged_margins(np.asarray(jax.device_get(X)), self._feat,
                                 self._thr, self._na_left, self._is_split,
                                 self._value, self.max_depth,
                                 getattr(self, "f0", 0.0))
        p1 = np.asarray(jax.device_get(
            1.0 / (1.0 + jnp.exp(-margins))))[:frame.nrow]
        T = p1.shape[1]
        names, vecs = [], []
        for t in range(T):
            names += [f"p0_T{t + 1}", f"p1_T{t + 1}"]
            vecs += [Vec.from_numpy(1.0 - p1[:, t]), Vec.from_numpy(p1[:, t])]
        return Frame(names, vecs)


def _ranked_contrib_frame(names, phi, bias, top_n, bottom_n, compare_abs):
    """top_n/bottom_n ranked output (h2o-py predict_contributions args):
    interleaved (feature, value) columns, ranked per row."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.frame.vec import Vec
    rows, F = phi.shape
    keys = np.abs(phi) if compare_abs else phi
    order = np.argsort(-keys, axis=1)
    if top_n < 0 or top_n > F:
        top_n = F
    if bottom_n < 0 or bottom_n > F:
        bottom_n = F
    # each feature appears at most once: when top_n + bottom_n covers all
    # features the bottom block only takes ranks the top block didn't
    sel = list(range(top_n)) + [F - 1 - i for i in range(bottom_n)
                                if F - 1 - i >= top_n]
    out_names, vecs = [], []
    arr_names = np.asarray(names, dtype=object)
    for rank, pos in enumerate(sel):
        idx = order[:, pos]
        lab = "top" if rank < top_n else "bottom"
        n = rank + 1 if rank < top_n else rank - top_n + 1
        out_names += [f"{lab}_feature_{n}", f"{lab}_value_{n}"]
        from h2o3_tpu.frame.vec import T_STR
        vecs.append(Vec.from_numpy(np.asarray(
            [str(s) for s in arr_names[idx]], dtype=object), vtype=T_STR))
        vecs.append(Vec.from_numpy(phi[np.arange(rows), idx]))
    out_names.append("BiasTerm")
    vecs.append(Vec.from_numpy(np.full(rows, bias, np.float32)))
    return Frame(out_names, vecs)


def staged_margins(X, feat, thr, na_left, is_split, value, max_depth: int,
                   f0, K: int = 1):
    """Cumulative margin after each boosting iteration
    (hex/Model.java staged_predict_proba): returns [rows, n_stages] (K=1)
    or [rows, n_stages, K]."""
    from h2o3_tpu.models.tree import predict_raw_stacked
    contribs = predict_raw_stacked(jnp.asarray(X), jnp.asarray(feat),
                                   jnp.asarray(thr), jnp.asarray(na_left),
                                   jnp.asarray(is_split), jnp.asarray(value),
                                   max_depth)                 # [rows, T]
    if K == 1:
        return jnp.asarray(f0) + jnp.cumsum(contribs, axis=1)
    rows = contribs.shape[0]
    T = contribs.shape[1] // K
    per = contribs.reshape(rows, T, K)
    return jnp.asarray(f0)[None, None, :] + jnp.cumsum(per, axis=1)
