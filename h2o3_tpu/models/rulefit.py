"""RuleFit — tree-derived rules + sparse linear model.

Reference: hex/rulefit/RuleFit.java:36 — grows tree ensembles over a
range of depths, converts every tree path into a conjunctive rule,
assembles a binary rule design (+ winsorized linear terms), and fits an
L1 GLM; nonzero-coefficient rules form the interpretable model.

TPU re-design: trees come from the existing histogram GBM (complete
binary arrays), rule extraction walks those arrays on host (bounded by
ntrees·2^depth, not rows), and rule-membership evaluation is a batched
device kernel: gather feature values per (rule, condition) and AND the
condition mask — rows stream through in blocks. The sparse fit is the
existing coordinate-descent elastic net on the MXU Gram."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.persist import (model_from_meta, model_to_meta,
                              register_model_class)

RULEFIT_DEFAULTS: Dict = dict(
    seed=-1, algorithm="auto", min_rule_length=1, max_rule_length=3,
    max_num_rules=-1, model_type="rules_and_linear",
    rule_generation_ntrees=50, distribution="auto",
)


def _extract_rules(feat, thr, na_left, is_split, max_depth: int):
    """Walk one tree's complete-binary arrays → list of rules, each a
    list of (feat, thr, na_left, go_right) conditions from the root."""
    rules = []

    def walk(node: int, path: List[Tuple[int, float, bool, bool]]):
        if node < len(is_split) and is_split[node]:
            c = (int(feat[node]), float(thr[node]), bool(na_left[node]))
            walk(2 * node + 1, path + [c + (False,)])
            walk(2 * node + 2, path + [c + (True,)])
        else:
            if path:
                rules.append(path)

    walk(0, [])
    return rules


def _rule_membership(X, cf, ct, cnl, cdir, active, block: int = 64):
    """[rows, R] float32 membership matrix. Conditions follow the tree
    routing semantics (tree.py predict_raw_stacked): NA goes right iff
    not na_left; numeric right iff x >= thr."""
    R, D = cf.shape
    outs = []
    for s in range(0, R, block):
        f = jnp.asarray(cf[s:s + block])          # [r, D]
        t = jnp.asarray(ct[s:s + block])
        nl = jnp.asarray(cnl[s:s + block])
        dr = jnp.asarray(cdir[s:s + block])
        ac = jnp.asarray(active[s:s + block])
        x = X[:, f]                                # [rows, r, D]
        isna = jnp.isnan(x)
        went_right = jnp.where(isna, ~nl[None], x >= t[None])
        sat = jnp.where(dr[None], went_right, ~went_right)
        member = jnp.where(ac[None], sat, True).all(axis=2)
        outs.append(member.astype(jnp.float32))
    return jnp.concatenate(outs, axis=1) if outs else \
        jnp.zeros((X.shape[0], 0), jnp.float32)


def _describe_rule(conds, names: List[str]) -> str:
    parts = []
    for (f, t, nl, right) in conds:
        n = names[f] if f < len(names) else f"f{f}"
        op = ">=" if right else "<"
        na = "" if (right != nl) else " or NA"  # NA routes with this side
        parts.append(f"({n} {op} {t:.6g}{na})")
    return " & ".join(parts)


class RuleFitModel(Model):
    algo = "rulefit"

    def __init__(self, key, params, spec, inner, cond_arrays, rule_names,
                 linear_cols, lin_lo, lin_hi):
        super().__init__(key, params, spec)
        self.inner = inner                        # GLMModel over rule design
        self.cf, self.ct, self.cnl, self.cdir, self.cactive = cond_arrays
        self.rule_names = list(rule_names)
        self.linear_cols = list(linear_cols)      # indices into feature_names
        self.lin_lo = np.asarray(lin_lo)          # winsorize bounds
        self.lin_hi = np.asarray(lin_hi)

    def _design(self, X):
        cols = []
        if len(self.rule_names):
            cols.append(_rule_membership(X, self.cf, self.ct, self.cnl,
                                         self.cdir, self.cactive))
        if self.linear_cols:
            lin = X[:, jnp.asarray(self.linear_cols)]
            lin = jnp.clip(jnp.nan_to_num(lin, nan=0.0),
                           jnp.asarray(self.lin_lo)[None],
                           jnp.asarray(self.lin_hi)[None])
            cols.append(lin)
        return jnp.concatenate(cols, axis=1) if cols else \
            jnp.zeros((X.shape[0], 0), jnp.float32)

    def _predict_matrix(self, X, offset=None):
        return self.inner._predict_matrix(self._design(X), offset=offset)

    def rule_importance(self):
        coefs = self.inner.coef()
        if self.inner.nclasses > 2:
            # multinomial: per-class coefficient maps — rank rules by the
            # largest |coefficient| across classes
            agg = {}
            for cls_map in coefs.values():
                for n, v in cls_map.items():
                    if abs(v) > abs(agg.get(n, 0.0)):
                        agg[n] = v
            coefs = agg
        rows = []
        for i, rn in enumerate(self.inner.feature_names):
            c = coefs.get(rn, 0.0)
            if abs(c) > 1e-10:
                rows.append({"variable": rn, "coefficient": c,
                             "rule": self.output.get("rule_descriptions",
                                                     {}).get(rn, rn)})
        rows.sort(key=lambda r: -abs(r["coefficient"]))
        return rows

    def _save_arrays(self):
        d = {f"inner__{k}": v for k, v in self.inner._save_arrays().items()}
        d.update({"cf": self.cf, "ct": self.ct, "cnl": self.cnl,
                  "cdir": self.cdir, "cactive": self.cactive,
                  "lin_cols": np.asarray(self.linear_cols, np.int32),
                  "lin_lo": self.lin_lo, "lin_hi": self.lin_hi})
        return d

    def _save_extra_meta(self):
        return {"inner_meta": model_to_meta(self.inner),
                "rule_names": self.rule_names}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        inner_arrays = {k[len("inner__"):]: v for k, v in arrays.items()
                        if k.startswith("inner__")}
        m.inner = model_from_meta(ex["inner_meta"], inner_arrays)
        m.rule_names = list(ex["rule_names"])
        m.cf = arrays["cf"]; m.ct = arrays["ct"]; m.cnl = arrays["cnl"]
        m.cdir = arrays["cdir"]; m.cactive = arrays["cactive"]
        m.linear_cols = [int(v) for v in arrays["lin_cols"]]
        m.lin_lo = arrays["lin_lo"]; m.lin_hi = arrays["lin_hi"]
        return m


class H2ORuleFitEstimator(ModelBuilder):
    algo = "rulefit"

    def __init__(self, **params):
        merged = dict(RULEFIT_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec, valid_spec, job: Job):
        from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
        p = self.params
        model_type = (p.get("model_type") or "rules_and_linear").lower()
        min_d = max(1, int(p.get("min_rule_length", 1)))
        max_d = max(min_d, int(p.get("max_rule_length", 3)))
        depths = list(range(min_d, max_d + 1))
        total_trees = int(p.get("rule_generation_ntrees", 50))
        per_depth = max(1, total_trees // len(depths))
        seed = int(p.get("seed", -1) or -1)
        X = spec.X
        rules = []          # (conds, name)
        if model_type in ("rules_and_linear", "rules"):
            frame = self._frame_from_spec(spec)
            for d in depths:
                gbm = H2OGradientBoostingEstimator(
                    ntrees=per_depth, max_depth=d, seed=seed,
                    learn_rate=0.1, distribution=p.get("distribution",
                                                       "auto"),
                    weights_column="__w" if "__w" in frame else None)
                gbm.train(y="__response", x=list(spec.names),
                          training_frame=frame)
                gm = gbm.model
                feat = np.asarray(jax.device_get(gm._feat))
                thr = np.asarray(jax.device_get(gm._thr))
                nal = np.asarray(jax.device_get(gm._na_left))
                spl = np.asarray(jax.device_get(gm._is_split))
                for t in range(feat.shape[0]):
                    for conds in _extract_rules(feat[t], thr[t], nal[t],
                                                spl[t], d):
                        rules.append((conds,
                                      f"M{d}T{t}N{len(rules)}"))
                job.update(0.0)
        # condition arrays padded to the max rule length
        D = max([len(c) for c, _ in rules], default=1)
        R = len(rules)
        cf = np.zeros((R, D), np.int32)
        ct = np.zeros((R, D), np.float32)
        cnl = np.zeros((R, D), bool)
        cdir = np.zeros((R, D), bool)
        act = np.zeros((R, D), bool)
        for i, (conds, _) in enumerate(rules):
            for j, (f, t, nl, right) in enumerate(conds):
                cf[i, j] = f; ct[i, j] = t; cnl[i, j] = nl
                cdir[i, j] = right; act[i, j] = True
        rule_names = [n for _, n in rules]
        # dedupe identical / constant rule columns on a sample
        if R:
            M = np.asarray(jax.device_get(_rule_membership(
                X, cf, ct, cnl, cdir, act)))
            live = np.asarray(jax.device_get(spec.w)) > 0
            Ms = M[live]
            keep = []
            seen = set()
            for i in range(R):
                col = Ms[:, i]
                mu = col.mean()
                if mu <= 1e-9 or mu >= 1 - 1e-9:
                    continue
                h = col.tobytes()
                if h in seen:
                    continue
                seen.add(h)
                keep.append(i)
            max_rules = int(p.get("max_num_rules", -1))
            if max_rules > 0 and len(keep) > max_rules:
                # keep the rules with support closest to 0.5 (highest
                # variance → most informative prior to the L1 fit)
                keep.sort(key=lambda i: abs(Ms[:, i].mean() - 0.5))
                keep = keep[:max_rules]
            cf, ct, cnl, cdir, act = (a[keep] for a in
                                      (cf, ct, cnl, cdir, act))
            rule_names = [rule_names[i] for i in keep]
            M = M[:, keep]
        else:
            M = np.zeros((X.shape[0], 0), np.float32)
        # linear block: winsorized numerics
        linear_cols, lin_lo, lin_hi = [], [], []
        if model_type in ("rules_and_linear", "linear"):
            live = np.asarray(jax.device_get(spec.w)) > 0
            Xh = np.asarray(jax.device_get(X))
            for i, (n, is_cat) in enumerate(zip(spec.names, spec.is_cat)):
                if is_cat:
                    continue
                v = Xh[live, i]
                v = v[~np.isnan(v)]
                if len(v) == 0:
                    continue
                linear_cols.append(i)
                lin_lo.append(float(np.quantile(v, 0.025)))
                lin_hi.append(float(np.quantile(v, 0.975)))
        # assemble the GLM training frame
        cols: Dict[str, np.ndarray] = {}
        names: List[str] = []
        for i, rn in enumerate(rule_names):
            cols[rn] = M[:, i]
            names.append(rn)
        Xh = np.asarray(jax.device_get(X))
        for i, ci in enumerate(linear_cols):
            nm = f"linear.{spec.names[ci]}"
            v = np.nan_to_num(Xh[:, ci], nan=0.0)
            cols[nm] = np.clip(v, lin_lo[i], lin_hi[i])
            names.append(nm)
        if not names:
            raise ValueError("rulefit produced no features (no rules and "
                             "no numeric linear terms)")
        nrow = spec.nrow
        data = {n: c[:nrow].astype(np.float32) for n, c in cols.items()}
        resp = self._response_values(spec)
        data["__response"] = resp[:nrow]
        wvals = np.asarray(jax.device_get(spec.w))[:nrow]
        data["__w"] = wvals.astype(np.float32)
        glm_frame = Frame(list(data.keys()),
                          [Vec.from_numpy(v) for v in data.values()])
        if spec.nclasses > 2:
            # multinomial path takes a single lambda (no search)
            glm = H2OGeneralizedLinearEstimator(
                alpha=1.0, Lambda=[1e-3], family="multinomial",
                weights_column="__w")
        else:
            glm = H2OGeneralizedLinearEstimator(
                alpha=1.0, lambda_search=True, nlambdas=30,
                family="binomial" if spec.nclasses == 2 else "gaussian",
                weights_column="__w")
        glm.train(y="__response", x=names, training_frame=glm_frame)
        inner = glm.model
        model = RuleFitModel(
            f"rf_{id(self) & 0xffffff:x}", self.params, spec, inner,
            (cf, ct, cnl, cdir, act), rule_names, linear_cols,
            np.asarray(lin_lo, np.float32), np.asarray(lin_hi, np.float32))
        descriptions = {rn: _describe_rule(rules_by_name, list(spec.names))
                        for rn, rules_by_name in
                        zip(rule_names,
                            (self._conds_of(cf, ct, cnl, cdir, act, i)
                             for i in range(len(rule_names))))}
        model.output["rule_descriptions"] = descriptions
        model.training_metrics = inner.training_metrics
        model.output["rule_importance"] = model.rule_importance()
        return model

    @staticmethod
    def _conds_of(cf, ct, cnl, cdir, act, i):
        return [(int(cf[i, j]), float(ct[i, j]), bool(cnl[i, j]),
                 bool(cdir[i, j]))
                for j in range(cf.shape[1]) if act[i, j]]

    def _frame_from_spec(self, spec) -> Frame:
        """Rebuild a Frame view of the spec for the internal tree fits."""
        nrow = spec.nrow
        data: Dict[str, np.ndarray] = {}
        Xh = np.asarray(jax.device_get(spec.X))[:nrow]
        for i, (n, is_cat) in enumerate(zip(spec.names, spec.is_cat)):
            col = Xh[:, i]
            if is_cat:
                dom = spec.cat_domains.get(n) or ()
                codes = np.where(np.isnan(col), -1,
                                 col).astype(np.int32)
                data[n] = Vec.from_numpy(codes, vtype="enum",
                                         domain=tuple(dom))
            else:
                data[n] = Vec.from_numpy(col.astype(np.float32))
        data["__response"] = Vec.from_numpy(self._response_values(spec))
        w = np.asarray(jax.device_get(spec.w))[:nrow]
        if not np.all(w == 1.0):
            data["__w"] = Vec.from_numpy(w.astype(np.float32))
        return Frame(list(data.keys()), list(data.values()))

    @staticmethod
    def _response_values(spec) -> np.ndarray:
        nrow = spec.nrow
        y = np.asarray(jax.device_get(spec.y))[:nrow]
        if spec.nclasses >= 2 and spec.response_domain:
            dom = np.asarray(spec.response_domain, dtype=object)
            return dom[np.clip(y.astype(np.int64), 0, len(dom) - 1)]
        return y.astype(np.float32)


register_model_class("rulefit", RuleFitModel)
