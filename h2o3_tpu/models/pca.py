"""PCA — principal components via the Gram matrix + on-device eigh.

Reference: hex/pca/PCA.java:41 — methods GramSVD (distributed Gram then
JAMA/MTJ eigensolver on the driver), Power, Randomized, GLRM. DataInfo
handles expansion/standardization.

TPU re-design: the Gram is ONE MXU matmul over the row-sharded design
(GSPMD psums across shards — the GramTask reduce, hex/gram/Gram.java:1017)
and the eigendecomposition runs on device with jnp.linalg.eigh — no
driver-side JAMA. Covers GramSVD semantics; Power/Randomized collapse
into the same path (eigh of an F x F matrix is cheap at any F the dense
design supports)."""
from __future__ import annotations

import time
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import expand_design, expand_scoring_matrix
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        pack_impute_means,
                                        unpack_impute_means)
from h2o3_tpu.persist import register_model_class

PCA_DEFAULTS: Dict = dict(
    k=2, transform="standardize", pca_method="gram_s_v_d", seed=-1,
    use_all_factor_levels=False, max_iterations=1000,
)


class PCAModel(Model):
    algo = "pca"
    supervised = False

    def __init__(self, key, params, spec, eigvec, eigval, xm, xs, exp_names,
                 impute_means, importance):
        super().__init__(key, params, spec)
        self.eigvec = np.asarray(eigvec)    # [Fe, k] columns = components
        self.eigval = np.asarray(eigval)    # [k] variances
        self.xm = np.asarray(xm)
        self.xs = np.asarray(xs)
        self.exp_names = list(exp_names)
        self.impute_means = {k_: float(v) for k_, v in impute_means.items()}
        self.importance = importance
        self.use_all_levels = bool(params.get("use_all_factor_levels", False))

    def rotation(self):
        """Loadings table (h2o .rotation()): {exp_name: [k loadings]}."""
        return {n: self.eigvec[i].tolist()
                for i, n in enumerate(self.exp_names)}

    def _predict_matrix(self, X, offset=None):
        Xe = expand_scoring_matrix(self, X)
        Xs = (Xe - jnp.asarray(self.xm)[None, :]) / jnp.asarray(self.xs)[None, :]
        return Xs @ jnp.asarray(self.eigvec)

    def predict(self, frame):
        """Project onto the principal components (scores frame PC1..PCk)."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        X = adapt_test_matrix(self, frame)
        S = np.asarray(jax.device_get(self._predict_matrix(X)))[: frame.nrow]
        k = S.shape[1]
        return Frame([f"PC{i + 1}" for i in range(k)],
                     [Vec.from_numpy(S[:, i]) for i in range(k)])

    transform = predict  # h2o-py calls model.transform(frame) too

    # -- persistence ----------------------------------------------------

    def _save_arrays(self):
        return {"eigvec": self.eigvec, "eigval": self.eigval, "xm": self.xm,
                "xs": self.xs,
                **pack_impute_means(self.impute_means)}

    def _save_extra_meta(self):
        return {"exp_names": self.exp_names, "importance": self.importance}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.exp_names = list(ex["exp_names"])
        m.importance = ex["importance"]
        m.eigvec = arrays["eigvec"]
        m.eigval = arrays["eigval"]
        m.xm = arrays["xm"]
        m.xs = arrays["xs"]
        m.impute_means = unpack_impute_means(arrays)
        m.use_all_levels = bool((meta.get("params") or {}).get(
            "use_all_factor_levels", False))
        return m


class H2OPrincipalComponentAnalysisEstimator(ModelBuilder):
    algo = "pca"
    supervised = False

    def __init__(self, **params):
        merged = dict(PCA_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        k = int(p.get("k", 2))
        use_all = bool(p.get("use_all_factor_levels", False))
        Xe, exp_names, means = expand_design(spec, use_all_levels=use_all)
        Fe = Xe.shape[1]
        k = min(k, Fe)
        w = spec.w
        wsum = w.sum()
        xm = (Xe * w[:, None]).sum(0) / wsum
        transform = (p.get("transform") or "standardize").lower()
        if transform in ("standardize",):
            xv = (w[:, None] * (Xe - xm[None, :]) ** 2).sum(0) / wsum
            xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
        elif transform in ("demean", "center"):
            xs = jnp.ones(Fe, jnp.float32)
        elif transform in ("none",):
            xm = jnp.zeros(Fe, jnp.float32)
            xs = jnp.ones(Fe, jnp.float32)
        else:
            raise ValueError(f"unsupported transform '{transform}'")
        Xs = ((Xe - xm[None, :]) / xs[None, :]) * (w > 0)[:, None]
        # Gram: one sharded MXU matmul + implicit psum (GramTask analog)
        G = jax.lax.dot_general(Xs, Xs * w[:, None], (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32) / wsum
        vals, vecs = jnp.linalg.eigh(G)            # ascending
        order = jnp.argsort(-vals)
        vals = jnp.maximum(vals[order][:k], 0.0)
        vecs = vecs[:, order][:, :k]
        job.set_progress(1.0)
        vals_h = np.asarray(jax.device_get(vals))
        vecs_h = np.asarray(jax.device_get(vecs))
        tot = float(np.asarray(jax.device_get(jnp.trace(G))))
        sdev = np.sqrt(vals_h)
        prop = vals_h / max(tot, 1e-30)
        importance = {
            "sdev": sdev.tolist(),
            "proportion_of_variance": prop.tolist(),
            "cumulative_proportion": np.cumsum(prop).tolist(),
        }
        model = PCAModel(f"pca_{id(self) & 0xffffff:x}", self.params, spec,
                         vecs_h, vals_h, jax.device_get(xm),
                         jax.device_get(xs), exp_names,
                         {k_: float(jax.device_get(v))
                          for k_, v in means.items()}, importance)
        model.output["importance"] = importance
        model.output["eigenvectors"] = model.rotation()
        return model


register_model_class("pca", PCAModel)
