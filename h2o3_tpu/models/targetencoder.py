"""Target Encoder — out-of-fold categorical target statistics.

Reference: h2o-extensions/target-encoder
(ai/h2o/targetencoding/TargetEncoder.java) — per-level target mean with
blending λ(n) = 1/(1+exp(-(n-inflection)/smoothing)), data-leakage
handling none / leave-one-out / kfold, optional noise.

TPU re-design: the distributed group-by target stats are one scatter-add
per column (codes → [card] sums/counts on device, psum'd by GSPMD when
sharded — the broadcast-join collapses into a gather); LOO and kfold are
the same gather with per-row corrections, no join needed."""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, TrainingSpec
from h2o3_tpu.persist import register_model_class

TE_DEFAULTS: Dict = dict(
    blending=True, inflection_point=10.0, smoothing=20.0,
    data_leakage_handling="none", noise=0.01, seed=-1, fold_column=None,
)


def _blend(level_sum, level_cnt, prior, blending, infl, smooth):
    mean = level_sum / jnp.maximum(level_cnt, 1e-12)
    if not blending:
        return jnp.where(level_cnt > 0, mean, prior)
    lam = jax.nn.sigmoid((level_cnt - infl) / max(smooth, 1e-12))
    return jnp.where(level_cnt > 0,
                     lam * mean + (1.0 - lam) * prior, prior)


class TargetEncoderModel(Model):
    algo = "targetencoder"

    def __init__(self, key, params, spec, encodings, prior):
        super().__init__(key, params, spec)
        # encodings: {col: (sum [card], cnt [card])} over the FULL data
        self.encodings = {c: (np.asarray(s), np.asarray(n))
                          for c, (s, n) in encodings.items()}
        self.prior = float(prior)

    def transform(self, frame: Frame, as_training: bool = False,
                  noise: Optional[float] = None,
                  seed: Optional[int] = None) -> Frame:
        """Append '<col>_te' columns. as_training=True applies the
        trained leakage handling (LOO subtracts the row's own target;
        kfold uses out-of-fold statistics)."""
        p = self.params
        handling = (p.get("data_leakage_handling") or "none").lower()
        blending = bool(p.get("blending", True))
        infl = float(p.get("inflection_point", 10.0))
        smooth = float(p.get("smoothing", 20.0))
        noise = float(p.get("noise", 0.01)) if noise is None else noise
        rng = np.random.default_rng(
            seed if seed is not None else
            (None if int(p.get("seed", -1) or -1) == -1 else int(p["seed"])))
        names = list(frame.names)
        vecs = list(frame.vecs)
        y = (frame.vec(self.response).asnumeric().to_numpy()
             if as_training and self.response in frame else None)
        fold = None
        if as_training and handling == "kfold":
            fc = p.get("fold_column")
            if fc and fc in frame:
                fold = frame.vec(fc).asnumeric().to_numpy().astype(int)
        # row weights: the training stats are weight-accumulated, so the
        # LOO/kfold corrections must subtract WEIGHTED contributions
        wc = p.get("weights_column")
        wrow = (frame.vec(wc).asnumeric().to_numpy()
                if as_training and wc and wc in frame else None)
        # one shared domain remap for all encoded columns (the
        # adaptTestForTrain path — no per-column hand-rolled LUTs)
        from h2o3_tpu.models.model_base import adapt_test_matrix
        import jax as _jax
        Xadapt = np.asarray(_jax.device_get(adapt_test_matrix(self, frame)))
        for col in self.encodings:
            if col not in frame or col not in self.feature_names:
                continue
            codes = Xadapt[: frame.nrow, self.feature_names.index(col)]
            s, n = self.encodings[col]
            card = len(s)
            c = np.where(np.isnan(codes), card, codes).astype(int)
            c = np.clip(c, 0, card)
            s_ext = np.concatenate([s, [0.0]])
            n_ext = np.concatenate([n, [0.0]])
            row_s = s_ext[c]
            row_n = n_ext[c]
            if as_training and y is not None:
                yv = np.nan_to_num(y, nan=self.prior)
                wv = (wrow.copy() if wrow is not None
                      else np.ones_like(yv))
                # rows the TRAINING stats excluded (NaN response) must
                # not be subtracted back out
                wv[np.isnan(y)] = 0.0
                if handling in ("leave_one_out", "loo"):
                    row_s = row_s - wv * yv
                    row_n = row_n - wv
                elif handling == "kfold" and fold is not None:
                    # out-of-fold: subtract this fold's per-level stats
                    for f in np.unique(fold):
                        m = fold == f
                        fs = np.bincount(c[m], weights=(wv * yv)[m],
                                         minlength=card + 1)
                        fn = np.bincount(c[m], weights=wv[m],
                                         minlength=card + 1)
                        row_s[m] = row_s[m] - fs[c[m]]
                        row_n[m] = row_n[m] - fn[c[m]]
            enc = np.asarray(jax.device_get(_blend(
                jnp.asarray(row_s), jnp.asarray(row_n), self.prior,
                blending, infl, smooth)))
            if as_training and noise > 0:
                enc = enc + rng.uniform(-noise, noise, len(enc))
            names.append(f"{col}_te")
            vecs.append(Vec.from_numpy(enc.astype(np.float32)))
        return Frame(names, vecs)

    def predict(self, frame: Frame) -> Frame:
        return self.transform(frame, as_training=False)

    def _predict_matrix(self, X, offset=None):
        raise NotImplementedError("TargetEncoder scores via transform()")

    def _save_arrays(self):
        d = {}
        for c, (s, n) in self.encodings.items():
            d[f"sum__{c}"] = s
            d[f"cnt__{c}"] = n
        return d

    def _save_extra_meta(self):
        return {"prior": self.prior, "cols": list(self.encodings)}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.prior = ex["prior"]
        m.encodings = {c: (arrays[f"sum__{c}"], arrays[f"cnt__{c}"])
                       for c in ex["cols"]}
        return m


class H2OTargetEncoderEstimator(ModelBuilder):
    algo = "targetencoder"

    def __init__(self, **params):
        merged = dict(TE_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _cross_validate(self, model, frame, y, x, spec, job, nfolds,
                        fold_column):
        """fold_column selects the kfold ENCODING folds — the encoder is
        not a predictive model, generic CV does not apply."""
        return None

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        y = spec.y.astype(jnp.float32)
        if spec.nclasses == 2:
            yv = y                        # binomial: encode P(class 1)
        elif spec.nclasses > 2:
            raise NotImplementedError(
                "multinomial target encoding is not supported (encode "
                "one-vs-rest targets explicitly)")
        else:
            yv = y
        w = spec.w
        live = (w > 0) & ~jnp.isnan(yv)
        wl = jnp.where(live, w, 0.0)
        prior = float(jax.device_get(
            (wl * yv).sum() / jnp.maximum(wl.sum(), 1e-12)))
        encodings = {}
        for i, (name, is_cat) in enumerate(zip(spec.names, spec.is_cat)):
            if not is_cat:
                continue
            card = max(len(spec.cat_domains.get(name, ())), 1)
            codes = spec.X[:, i]
            c = jnp.where(jnp.isnan(codes), card, codes).astype(jnp.int32)
            c = jnp.clip(c, 0, card)      # NA bucket = card (dropped)
            s = jnp.zeros(card + 1, jnp.float32).at[c].add(wl * yv)
            n = jnp.zeros(card + 1, jnp.float32).at[c].add(wl)
            encodings[name] = (np.asarray(jax.device_get(s))[:card],
                               np.asarray(jax.device_get(n))[:card])
        if not encodings:
            raise ValueError("target encoder needs at least one "
                             "categorical column in x")
        model = TargetEncoderModel(
            f"te_{id(self) & 0xffffff:x}", self.params, spec, encodings,
            prior)
        model.output["prior_mean"] = prior
        model.output["encoded_columns"] = list(encodings)
        return model


register_model_class("targetencoder", TargetEncoderModel)
