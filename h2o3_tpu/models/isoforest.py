"""Isolation Forest — anomaly detection by random-split isolation trees.

Reference: hex/tree/isofor/IsolationForest.java:33 — trees of RANDOM
(feature, threshold) splits over per-tree row subsamples; anomaly score
from the average path length normalized by c(n) = 2·H(n−1) − 2(n−1)/n
(the expected BST path length).

TPU re-design: no histograms at all — a level-synchronous build where
each level draws a random feature and a random threshold uniformly
inside each node's CURRENT value box (tracked exactly from the split
points, like the adaptive GBM kernel's range narrowing), then routes
rows with one gather. The whole forest builds inside one jitted scan;
trees are complete binary arrays like the rest of the tree stack."""
from __future__ import annotations

import time
from functools import lru_cache, partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, TrainingSpec
from h2o3_tpu.persist import register_model_class

IF_DEFAULTS: Dict = dict(
    ntrees=50, sample_size=256, max_depth=8, seed=-1,
)


def _avg_path(n):
    """c(n): expected unsuccessful-search path length in a BST."""
    n = jnp.maximum(n, 2.0)
    H = jnp.log(n - 1.0) + 0.5772156649
    return 2.0 * H - 2.0 * (n - 1.0) / n


def _grow_random_tree(X, in_sample, lo0, hi0, depth, key):
    """One isolation tree: returns (feat[M], thr[M], is_split[M]) with
    M = 2^(depth+1) - 1 (splits at internal nodes only where >1 sampled
    row remains)."""
    rows, F = X.shape
    M = 2 ** (depth + 1) - 1
    feat = jnp.zeros(M, jnp.int32)
    thr = jnp.zeros(M, jnp.float32)
    is_split = jnp.zeros(M, bool)
    nid = jnp.zeros(rows, jnp.int32)
    lo = jnp.broadcast_to(lo0[None, :], (1, F))
    hi = jnp.broadcast_to(hi0[None, :], (1, F))
    for d in range(depth):
        N = 2 ** d
        base = N - 1
        key, kf, kt = jax.random.split(key, 3)
        f_sel = jax.random.randint(kf, (N,), 0, F)
        u = jax.random.uniform(kt, (N,))
        lo_f = jnp.take_along_axis(lo, f_sel[:, None], axis=1)[:, 0]
        hi_f = jnp.take_along_axis(hi, f_sel[:, None], axis=1)[:, 0]
        t_sel = lo_f + u * (hi_f - lo_f)
        # only split nodes holding >= 2 sampled rows
        local = nid - base
        in_lvl = (local >= 0) & (local < N) & in_sample
        lid = jnp.clip(local, 0, N - 1)
        cnt = jnp.zeros(N, jnp.float32).at[lid].add(
            jnp.where(in_lvl, 1.0, 0.0))
        can = (cnt >= 2) & (hi_f > lo_f)
        idx = base + jnp.arange(N)
        feat = feat.at[idx].set(f_sel)
        thr = thr.at[idx].set(t_sel)
        is_split = is_split.at[idx].set(can)
        # route
        xf = jnp.take_along_axis(X, f_sel[lid][:, None], axis=1)[:, 0]
        go_right = jnp.where(jnp.isnan(xf), False, xf >= t_sel[lid])
        child = 2 * nid + 1 + go_right.astype(jnp.int32)
        route = (local >= 0) & (local < N) & can[lid]
        nid = jnp.where(route, child, nid)
        # children boxes: split feature's range cut at the threshold
        fsel_oh = (jnp.arange(F)[None, :] == f_sel[:, None])
        lo_l, hi_l = lo, jnp.where(fsel_oh, jnp.minimum(t_sel[:, None], hi),
                                   hi)
        lo_r, hi_r = jnp.where(fsel_oh, jnp.maximum(t_sel[:, None], lo),
                               lo), hi
        lo = jnp.stack([lo_l, lo_r], axis=1).reshape(2 * N, F)
        hi = jnp.stack([hi_l, hi_r], axis=1).reshape(2 * N, F)
    return {"feat": feat, "thr": thr, "is_split": is_split}


def _path_lengths(X, feat, thr, is_split, depth):
    """Per-row path length through one tree (depth of the reached leaf)."""
    rows = X.shape[0]
    nid = jnp.zeros(rows, jnp.int32)
    length = jnp.zeros(rows, jnp.float32)
    for _ in range(depth):
        f = feat[nid]
        s = is_split[nid]
        t = thr[nid]
        xf = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        go_right = jnp.where(jnp.isnan(xf), False, xf >= t)
        nid = jnp.where(s, 2 * nid + 1 + go_right.astype(jnp.int32), nid)
        length = length + s.astype(jnp.float32)
    return length


class IsolationForestModel(Model):
    algo = "isolationforest"
    supervised = False

    def __init__(self, key, params, spec, trees, depth, sample_size,
                 min_len, max_len):
        super().__init__(key, params, spec)
        self._feat = jnp.asarray(trees["feat"])       # [T, M]
        self._thr = jnp.asarray(trees["thr"])
        self._is_split = jnp.asarray(trees["is_split"])
        self.max_depth = depth
        self.sample_size = sample_size
        self.min_path_length = min_len
        self.max_path_length = max_len

    def _mean_length(self, X):
        T = self._feat.shape[0]

        def one(carry, t):
            return carry, _path_lengths(X, self._feat[t], self._thr[t],
                                        self._is_split[t], self.max_depth)

        _, L = jax.lax.scan(one, None, jnp.arange(T))
        return L.mean(axis=0)

    def _predict_matrix(self, X, offset=None):
        ml = self._mean_length(X)
        # s(x) = 2^(-E[h(x)]/c(n)) — the standard isolation-forest score
        # (outliers near 1); min/max path lengths stay in output for the
        # reference's range-normalized variant
        c = _avg_path(jnp.float32(self.sample_size))
        return jnp.exp2(-ml / c)

    def predict(self, frame):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        X = adapt_test_matrix(self, frame)
        # one forest traversal: score derives from the same mean lengths
        ml = np.asarray(jax.device_get(self._mean_length(X)))[: frame.nrow]
        c = float(np.asarray(_avg_path(jnp.float32(self.sample_size))))
        score = np.exp2(-ml / c)
        return Frame(["predict", "mean_length"],
                     [Vec.from_numpy(score.astype(np.float32)),
                      Vec.from_numpy(ml.astype(np.float32))])

    def _save_arrays(self):
        return {"feat": np.asarray(jax.device_get(self._feat)),
                "thr": np.asarray(jax.device_get(self._thr)),
                "is_split": np.asarray(jax.device_get(self._is_split))}

    def _save_extra_meta(self):
        return {"max_depth": self.max_depth,
                "sample_size": self.sample_size,
                "min_path_length": self.min_path_length,
                "max_path_length": self.max_path_length}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.max_depth = ex["max_depth"]
        m.sample_size = ex["sample_size"]
        m.min_path_length = ex["min_path_length"]
        m.max_path_length = ex["max_path_length"]
        m._feat = jnp.asarray(arrays["feat"])
        m._thr = jnp.asarray(arrays["thr"])
        m._is_split = jnp.asarray(arrays["is_split"])
        return m


class H2OIsolationForestEstimator(ModelBuilder):
    algo = "isolationforest"
    supervised = False

    def __init__(self, **params):
        merged = dict(IF_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        ntrees = int(p.get("ntrees", 50))
        depth = int(p.get("max_depth", 8))
        sample_size = int(p.get("sample_size", 256))
        X = spec.X
        w = spec.w
        rows = X.shape[0]
        Xf = jnp.where(jnp.isfinite(X), X, jnp.nan)
        lo0 = jnp.nan_to_num(jnp.nanmin(Xf, axis=0), nan=0.0)
        hi0 = jnp.nan_to_num(jnp.nanmax(Xf, axis=0), nan=0.0)
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1
                                 else int(time.time() * 1e3) % (2 ** 31))

        @jax.jit
        def build_forest(key, X, w, lo0, hi0):
            def one_tree(carry, i):
                k = jax.random.fold_in(key, i)
                k1, k2 = jax.random.split(k)
                # per-tree subsample without replacement ~ top-k of
                # uniform draws among live rows
                u = jax.random.uniform(k1, (rows,))
                u = jnp.where(w > 0, u, 2.0)
                kth = jnp.sort(u)[jnp.minimum(sample_size, rows) - 1]
                in_sample = (u <= kth) & (w > 0)
                tree = _grow_random_tree(X, in_sample, lo0, hi0, depth, k2)
                return carry, tree

            _, trees = jax.lax.scan(one_tree, None, jnp.arange(ntrees))
            return trees

        trees = build_forest(key, X, w, lo0, hi0)
        trees_host = {k: np.asarray(jax.device_get(v))
                      for k, v in trees.items()}
        model = IsolationForestModel(
            f"if_{id(self) & 0xffffff:x}", self.params, spec, trees_host,
            depth, sample_size, 0.0, 0.0)
        # normalize scores by the TRAINING path-length range
        ml = np.asarray(jax.device_get(model._mean_length(X)))
        live = np.asarray(jax.device_get(w)) > 0
        model.min_path_length = float(ml[live].min())
        model.max_path_length = float(ml[live].max())
        model.output["min_path_length"] = model.min_path_length
        model.output["max_path_length"] = model.max_path_length
        from h2o3_tpu.models.metrics import make_anomaly_metrics
        c = float(np.asarray(_avg_path(jnp.float32(sample_size))))
        model.training_metrics = make_anomaly_metrics(
            np.exp2(-ml[live] / c), ml[live] / max(depth, 1))
        return model


register_model_class("isolationforest", IsolationForestModel)
