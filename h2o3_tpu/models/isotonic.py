"""Isotonic regression — weighted pool-adjacent-violators.

Reference: hex/isotonic/IsotonicRegression.java:14 — distributed PAV over
(feature, response, weight) triples; the model keeps the fitted threshold
knots and predicts by linear interpolation with out-of-range clipping
(hex/genmodel/algos/isotonic scoring semantics).

TPU re-design: the data-sized work (sort by x, per-unique-x weighted
aggregation) is one device sort + segment-sum; the PAV merge itself runs
on the collapsed unique-x knots on host (knot count ≪ rows — same shape
as the reference's driver-side final merge of per-chunk PAV results)."""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu import telemetry
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        compute_metrics)
from h2o3_tpu.persist import register_model_class

ISO_DEFAULTS: Dict = dict(out_of_bounds="clip")


@jax.jit
def _sorted_aggregate(x, y, w):
    """Sort by x; return sorted x, w·y, w (segment collapse happens host
    side on the boundary mask to keep shapes static)."""
    order = jnp.argsort(x)
    xs = x[order]
    return xs, (w * y)[order], w[order]


def _pav(x, wy, w):
    """Weighted PAV on pre-aggregated unique-x knots (host, O(n) stack)."""
    n = len(x)
    # block stack: level value = wy/w, merged while decreasing
    bx0 = np.empty(n); bx1 = np.empty(n)
    bwy = np.empty(n); bw = np.empty(n)
    top = 0
    for i in range(n):
        bx0[top] = x[i]; bx1[top] = x[i]
        bwy[top] = wy[i]; bw[top] = w[i]
        top += 1
        while top > 1 and (bwy[top - 2] * bw[top - 1]
                           >= bwy[top - 1] * bw[top - 2]):
            bwy[top - 2] += bwy[top - 1]
            bw[top - 2] += bw[top - 1]
            bx1[top - 2] = bx1[top - 1]
            top -= 1
    vals = bwy[:top] / bw[:top]
    # knots: each block contributes its [x0, x1] endpoints at its value
    tx, ty = [], []
    for i in range(top):
        tx.append(bx0[i]); ty.append(vals[i])
        if bx1[i] != bx0[i]:
            tx.append(bx1[i]); ty.append(vals[i])
    return np.asarray(tx), np.asarray(ty)


class IsotonicRegressionModel(Model):
    algo = "isotonicregression"

    def __init__(self, key, params, spec, tx, ty):
        super().__init__(key, params, spec)
        self.thresholds_x = np.asarray(tx)
        self.thresholds_y = np.asarray(ty)

    def _predict_matrix(self, X, offset=None):
        x = X[:, 0]
        tx = jnp.asarray(self.thresholds_x)
        ty = jnp.asarray(self.thresholds_y)
        pred = jnp.interp(x, tx, ty)  # interp clips outside the range
        if self.params.get("out_of_bounds") == "na":
            pred = jnp.where((x < tx[0]) | (x > tx[-1]), jnp.nan, pred)
        return pred

    def _save_arrays(self):
        return {"tx": self.thresholds_x, "ty": self.thresholds_y}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.thresholds_x = arrays["tx"]
        m.thresholds_y = arrays["ty"]
        return m


class H2OIsotonicRegressionEstimator(ModelBuilder):
    algo = "isotonicregression"

    def __init__(self, **params):
        merged = dict(ISO_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        if spec.n_features != 1:
            raise ValueError("Isotonic regression expects exactly one "
                             "feature column")
        x = spec.X[:, 0]
        live = (spec.w > 0) & ~jnp.isnan(x) & ~jnp.isnan(spec.y)
        w = jnp.where(live, spec.w, 0.0)
        xs, wys, ws = _sorted_aggregate(
            jnp.where(live, x, jnp.inf), spec.y, w)
        xs = np.asarray(telemetry.device_get(xs))
        wys = np.asarray(telemetry.device_get(wys))
        ws = np.asarray(telemetry.device_get(ws))
        keep = np.isfinite(xs) & (ws > 0)
        xs, wys, ws = xs[keep], wys[keep], ws[keep]
        if len(xs) == 0:
            raise ValueError("no usable rows for isotonic regression")
        # collapse equal-x runs before PAV
        ux, inv = np.unique(xs, return_inverse=True)
        uwy = np.bincount(inv, weights=wys)
        uw = np.bincount(inv, weights=ws)
        tx, ty = _pav(ux, uwy, uw)
        model = IsotonicRegressionModel(
            f"iso_{id(self) & 0xffffff:x}", self.params, spec, tx, ty)
        pred = model._predict_matrix(spec.X)
        # metrics on the NaN-filtered weights: rows with missing x score
        # NaN and must not poison MSE/R2
        model.training_metrics = compute_metrics(pred, spec.y, w, 1)
        model.output["thresholds_x"] = tx.tolist()
        model.output["thresholds_y"] = ty.tolist()
        return model


register_model_class("isotonicregression", IsotonicRegressionModel)
