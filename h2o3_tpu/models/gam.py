"""GAM — generalized additive model: spline basis expansion + GLM.

Reference: hex/gam/GAM.java:53 — gam_columns are expanded into smooth
basis functions (CubicRegression/ISpline/MSpline/ThinPlate in
hex/gam/MatrixFrameUtils), the penalized design is handed to GLM, and
the model scores by re-expanding incoming frames.

TPU re-design: the basis here is the truncated-power cubic spline
(x, x², x³, (x−k_j)³₊ at interior quantile knots) — it spans the same
cubic-spline function space as the reference's CR basis — and the
smoothing penalty is the GLM's own elastic-net ridge on the basis block.
The expansion is columnar device math; the solve is the existing MXU
Gram IRLS (hex/glm path)."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import GLM_DEFAULTS, H2OGeneralizedLinearEstimator
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.persist import (model_from_meta, model_to_meta,
                              register_model_class)

GAM_DEFAULTS: Dict = dict(
    gam_columns=None, num_knots=6, bs=None, scale=None,
    keep_gam_cols=False,
)


def _spline_basis(x: np.ndarray, knots: np.ndarray) -> Dict[str, np.ndarray]:
    """Truncated-power cubic basis for one smooth term. NAs are imputed
    to the knot median (the basis is built post-imputation, matching the
    reference's DataInfo-imputed gam columns)."""
    mid = float(knots[len(knots) // 2])
    xv = np.where(np.isnan(x), mid, x.astype(np.float64))
    # scale to knot span for conditioning (pure reparameterization)
    span = max(float(knots[-1] - knots[0]), 1e-12)
    z = (xv - float(knots[0])) / span
    cols = {"l": z, "q": z * z, "c": z * z * z}
    for j, k in enumerate(knots[1:-1]):
        zk = (float(k) - float(knots[0])) / span
        cols[f"k{j}"] = np.maximum(z - zk, 0.0) ** 3
    return cols


def _expand_gam_frame(frame: Frame, gam_columns: Sequence[str],
                      knots: Dict[str, np.ndarray],
                      keep_gam_cols: bool) -> (Frame, List[str]):
    names = []
    vecs = []
    basis_names: List[str] = []
    for n in frame.names:
        if n in gam_columns and not keep_gam_cols:
            continue
        names.append(n)
        vecs.append(frame.vec(n))
    for gc in gam_columns:
        x = frame.vec(gc).to_numpy()
        for suffix, col in _spline_basis(x, knots[gc]).items():
            bn = f"{gc}_tp_{suffix}"
            names.append(bn)
            vecs.append(Vec.from_numpy(col.astype(np.float32)))
            basis_names.append(bn)
    return Frame(names, vecs), basis_names


class GAMModel(Model):
    algo = "gam"

    def __init__(self, key, params, spec, inner, gam_columns, knots):
        super().__init__(key, params, spec)
        self.inner = inner                      # GLMModel on expanded frame
        self.gam_columns = list(gam_columns)
        self.knots = {k: np.asarray(v) for k, v in knots.items()}

    def coef(self):
        return self.inner.coef()

    def _expand(self, frame: Frame) -> Frame:
        fr, _ = _expand_gam_frame(frame, self.gam_columns, self.knots,
                                  bool(self.params.get("keep_gam_cols")))
        return fr

    def predict(self, frame: Frame) -> Frame:
        return self.inner.predict(self._expand(frame))

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        return self.inner.model_performance(self._expand(frame))

    def _predict_matrix(self, X, offset=None):
        raise NotImplementedError(
            "GAM scores through predict(frame) — the basis expansion is "
            "frame-level")

    def _save_arrays(self):
        d = {f"inner__{k}": v
             for k, v in self.inner._save_arrays().items()}
        for c, kn in self.knots.items():
            d[f"knots__{c}"] = kn
        return d

    def _save_extra_meta(self):
        return {"inner_meta": model_to_meta(self.inner),
                "gam_columns": self.gam_columns}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        inner_arrays = {k[len("inner__"):]: v for k, v in arrays.items()
                        if k.startswith("inner__")}
        m.inner = model_from_meta(ex["inner_meta"], inner_arrays)
        m.gam_columns = list(ex["gam_columns"])
        m.knots = {k[len("knots__"):]: v for k, v in arrays.items()
                   if k.startswith("knots__")}
        return m


class H2OGeneralizedAdditiveEstimator(ModelBuilder):
    algo = "gam"

    def __init__(self, **params):
        merged = dict(GLM_DEFAULTS)
        merged.update(GAM_DEFAULTS)
        merged.update(params)
        for alias in ("lambda_", "lambda"):
            if alias in merged:
                merged["Lambda"] = merged.pop(alias)
        super().__init__(**merged)

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        p = self.params
        gam_cols = p.get("gam_columns") or []
        if isinstance(gam_cols, str):
            gam_cols = [gam_cols]
        gam_cols = [c[0] if isinstance(c, (list, tuple)) else c
                    for c in gam_cols]
        if not gam_cols:
            raise ValueError("GAM requires gam_columns")
        nk = p.get("num_knots", 6)
        nk_list = (list(nk) if isinstance(nk, (list, tuple))
                   else [nk] * len(gam_cols))
        # knots at weighted-less quantiles of each gam column (reference
        # default: quantile-spaced knots, GamUtils.generateKnotsFromKeys)
        knots: Dict[str, np.ndarray] = {}
        for gc, k in zip(gam_cols, nk_list):
            xv = training_frame.vec(gc).to_numpy()
            xv = xv[~np.isnan(xv)]
            if len(np.unique(xv)) < int(k):
                raise ValueError(
                    f"gam column '{gc}' has fewer distinct values than "
                    f"num_knots={k}")
            qs = np.linspace(0, 1, int(k))
            kn = np.quantile(xv, qs)
            # strictly increasing knots
            kn = np.maximum.accumulate(kn + np.arange(len(kn)) * 1e-12)
            knots[gc] = kn
        train_x, basis_names = _expand_gam_frame(
            training_frame, gam_cols, knots, bool(p.get("keep_gam_cols")))
        vf = None
        if validation_frame is not None:
            vf, _ = _expand_gam_frame(validation_frame, gam_cols, knots,
                                      bool(p.get("keep_gam_cols")))
        if x is None:
            glm_x = None
        else:
            glm_x = [c for c in x if c not in gam_cols] + basis_names
        glm_params = {k_: v for k_, v in p.items()
                      if k_ not in GAM_DEFAULTS}
        # default smoothing: ridge on the spline block via elastic net
        # (only when lambda is genuinely UNSET — an explicit 0 means the
        # user asked for an unpenalized fit)
        if glm_params.get("Lambda") is None and not glm_params.get(
                "lambda_search"):
            glm_params["Lambda"] = [1e-4]
            glm_params.setdefault("alpha", 0.0)
        inner_est = H2OGeneralizedLinearEstimator(**glm_params)
        inner_est.train(x=glm_x, y=y, training_frame=train_x,
                        validation_frame=vf, **kw)
        inner = inner_est.model
        model = GAMModel(f"gam_{id(self) & 0xffffff:x}", self.params,
                         _SpecShim(training_frame, y, inner), inner,
                         gam_cols, knots)
        model.training_metrics = inner.training_metrics
        model.validation_metrics = inner.validation_metrics
        model.scoring_history = inner.scoring_history
        model.output["knots"] = {k_: v.tolist() for k_, v in knots.items()}
        model.output["basis_names"] = basis_names
        model.output["coefficients"] = inner.coef()
        self.model = model
        self.job = inner_est.job
        from h2o3_tpu import dkv
        dkv.put(model.key, "model", model)
        return self

    def _train_impl(self, spec, valid_spec, job: Job):
        raise RuntimeError("GAM overrides train() directly")


class _SpecShim:
    """Minimal TrainingSpec stand-in for the wrapper Model base ctor:
    GAM's real spec lives in the inner GLM (the wrapper only needs the
    original frame's schema for save/load)."""

    def __init__(self, frame: Frame, y, inner):
        self.names = [n for n in frame.names if n != y]
        self.is_cat = [frame.vec(n).is_categorical for n in self.names]
        self.cat_domains = {n: tuple(frame.vec(n).domain or ())
                            for n in self.names
                            if frame.vec(n).is_categorical}
        self.response = y
        self.response_domain = inner.response_domain
        self.nclasses = inner.nclasses


register_model_class("gam", GAMModel)
