"""GAM — generalized additive model: spline basis expansion + GLM.

Reference: hex/gam/GAM.java:53 — gam_columns are expanded into smooth
basis functions (CubicRegression/ISpline/MSpline/ThinPlate in
hex/gam/MatrixFrameUtils), the penalized design is handed to GLM, and
the model scores by re-expanding incoming frames.

TPU re-design: the basis here is the truncated-power cubic spline
(x, x², x³, (x−k_j)³₊ at interior quantile knots) — it spans the same
cubic-spline function space as the reference's CR basis — and the
smoothing penalty is the GLM's own elastic-net ridge on the basis block.
The expansion is columnar device math; the solve is the existing MXU
Gram IRLS (hex/glm path)."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import GLM_DEFAULTS, H2OGeneralizedLinearEstimator
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.persist import (model_from_meta, model_to_meta,
                              register_model_class)

GAM_DEFAULTS: Dict = dict(
    gam_columns=None, num_knots=6, bs=None, scale=None,
    keep_gam_cols=False,
    # reference GAM defaults tweedie_link_power to 0.0 (log), unlike
    # GLM's 1.0 (h2o-py h2o/estimators/gam.py:59)
    tweedie_link_power=0.0,
)


def _impute(x: np.ndarray, knots: np.ndarray) -> np.ndarray:
    mid = float(knots[len(knots) // 2])
    return np.where(np.isnan(x), mid, x.astype(np.float64))


def _basis_trunc_power(x, knots):
    """Truncated-power cubic basis (spans the same cubic-spline space as
    the reference's CR basis)."""
    xv = _impute(x, knots)
    span = max(float(knots[-1] - knots[0]), 1e-12)
    z = (xv - float(knots[0])) / span
    cols = {"l": z, "q": z * z, "c": z * z * z}
    for j, k in enumerate(knots[1:-1]):
        zk = (float(k) - float(knots[0])) / span
        cols[f"k{j}"] = np.maximum(z - zk, 0.0) ** 3
    return cols


def _basis_cr(x, knots):
    """Natural cubic regression spline basis (bs=0, the reference
    default — hex/gam CubicRegressionSpline): R's ns() parameterization
    N_j(x) = d_j(x) − d_{K−1}(x), d_j(x) = ((x−k_j)³₊ − (x−k_K)³₊)
    / (k_K − k_j), plus the linear term; linear beyond the boundary
    knots (the 'natural' constraint CR shares)."""
    xv = _impute(x, knots)
    k = np.asarray(knots, np.float64)
    K = len(k)
    span = max(float(k[-1] - k[0]), 1e-12)
    z = (xv - k[0]) / span
    kz = (k - k[0]) / span

    def d(j):
        return (np.maximum(z - kz[j], 0.0) ** 3
                - np.maximum(z - kz[-1], 0.0) ** 3) / max(
                    kz[-1] - kz[j], 1e-12)

    cols = {"l": z}
    dK1 = d(K - 2)
    for j in range(K - 2):
        cols[f"n{j}"] = d(j) - dK1
    return cols


def _bspline_design(x, knots, order=4, antideriv=False):
    from scipy.interpolate import BSpline
    k = np.asarray(knots, np.float64)
    t = np.concatenate([[k[0]] * (order - 1), k, [k[-1]] * (order - 1)])
    nb = len(t) - order
    cols = {}
    for j in range(nb):
        c = np.zeros(nb)
        c[j] = 1.0
        sp = BSpline(t, c, order - 1, extrapolate=False)
        if antideriv:
            sp = sp.antiderivative()
            total = float(sp(k[-1]))
            v = np.asarray(sp(np.clip(x, k[0], k[-1]))) / max(total, 1e-12)
        else:
            v = np.nan_to_num(np.asarray(sp(np.clip(x, k[0], k[-1]))))
        cols[f"b{j}"] = v
    return cols


def _basis_ms(x, knots):
    """M-spline (normalized B-spline) basis — bs=3 (hex/gam
    NBSplinesTypeI)."""
    return _bspline_design(_impute(x, knots), knots, order=4,
                           antideriv=False)


def _basis_is(x, knots):
    """I-spline basis (integrated M-splines) — bs=2; paired with
    non-negative coefficients this yields MONOTONE smooths
    (hex/gam ISplines)."""
    return _bspline_design(_impute(x, knots), knots, order=3,
                           antideriv=True)


def _basis_tp(x, knots):
    """Thin-plate regression spline basis — bs=1 (hex/gam
    MatrixFrameUtils/ThinPlate* machinery: polyharmonic kernel +
    polynomial null space). For the 1-D smooths this estimator supports
    the TPS kernel with m=2 is eta(r) = r^3 (up to a constant), so the
    basis is [ |x-k_1|^3 … |x-k_K|^3, x ]: K radial columns plus the
    linear null-space term (the constant rides the GLM intercept).

    Deviations from the reference, documented: columns are scaled to
    unit sd (standardize_tp_gam_cols default semantics) instead of the
    reference's penalty-matrix Cholesky absorption, and the smoothing
    penalty is the GLM's ridge on the block (scale knob) like the other
    bases here — the reference builds an explicit TPS penalty matrix
    (scale_tp_penalty_mat)."""
    xi = _impute(x, knots)
    # scales derive from the KNOTS ONLY so train- and score-time bases
    # agree exactly (a per-frame sd would shift the design between
    # frames); |knots - k_j|^3 spans the kernel's dynamic range
    kk = np.asarray(knots, np.float64)
    cols = {}
    for j, k in enumerate(kk):
        s = max(float(np.mean(np.abs(kk - k) ** 3)), 1e-12)
        cols[f"r{j}"] = np.abs(xi - k) ** 3 / s
    cols["l"] = xi / max(float(kk.std()), 1e-12)
    return cols


_BASES = {None: _basis_trunc_power, -1: _basis_trunc_power,
          0: _basis_cr, 1: _basis_tp, 2: _basis_is, 3: _basis_ms}


def _spline_basis(x: np.ndarray, knots: np.ndarray,
                  bs: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Basis dispatch by the reference's ``bs`` codes (hex/gam
    GAMModelParameters: 0=cubic regression, 1=thin-plate,
    2=I-spline monotone, 3=M-spline). NAs impute to the
    knot median (DataInfo-imputed gam columns)."""
    fn = _BASES.get(bs)
    if fn is None:
        raise ValueError(f"unsupported spline type bs={bs} "
                         f"(supported: 0=cr, 1=tp, 2=is, 3=ms)")
    return fn(x, knots)


def _expand_gam_frame(frame: Frame, gam_columns: Sequence[str],
                      knots: Dict[str, np.ndarray],
                      keep_gam_cols: bool,
                      bs_map: Optional[Dict[str, Optional[int]]] = None,
                      ) -> (Frame, List[str]):
    names = []
    vecs = []
    basis_names: List[str] = []
    for n in frame.names:
        if n in gam_columns and not keep_gam_cols:
            continue
        names.append(n)
        vecs.append(frame.vec(n))
    for gc in gam_columns:
        x = frame.vec(gc).to_numpy()
        bs_gc = (bs_map or {}).get(gc)
        for suffix, col in _spline_basis(x, knots[gc], bs_gc).items():
            bn = f"{gc}_tp_{suffix}"
            names.append(bn)
            vecs.append(Vec.from_numpy(col.astype(np.float32)))
            basis_names.append(bn)
    return Frame(names, vecs), basis_names


class GAMModel(Model):
    algo = "gam"

    def __init__(self, key, params, spec, inner, gam_columns, knots,
                 bs_map=None):
        super().__init__(key, params, spec)
        self.inner = inner                      # GLMModel on expanded frame
        self.gam_columns = list(gam_columns)
        self.knots = {k: np.asarray(v) for k, v in knots.items()}
        self.bs_map = dict(bs_map or {})

    def coef(self):
        return self.inner.coef()

    def _expand(self, frame: Frame) -> Frame:
        fr, _ = _expand_gam_frame(frame, self.gam_columns, self.knots,
                                  bool(self.params.get("keep_gam_cols")),
                                  self.bs_map)
        return fr

    def predict(self, frame: Frame) -> Frame:
        return self.inner.predict(self._expand(frame))

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        return self.inner.model_performance(self._expand(frame))

    def _predict_matrix(self, X, offset=None):
        raise NotImplementedError(
            "GAM scores through predict(frame) — the basis expansion is "
            "frame-level")

    def _save_arrays(self):
        d = {f"inner__{k}": v
             for k, v in self.inner._save_arrays().items()}
        for c, kn in self.knots.items():
            d[f"knots__{c}"] = kn
        return d

    def _save_extra_meta(self):
        return {"inner_meta": model_to_meta(self.inner),
                "gam_columns": self.gam_columns,
                "bs_map": {k: (None if v is None else int(v))
                           for k, v in self.bs_map.items()}}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        inner_arrays = {k[len("inner__"):]: v for k, v in arrays.items()
                        if k.startswith("inner__")}
        m.inner = model_from_meta(ex["inner_meta"], inner_arrays)
        m.gam_columns = list(ex["gam_columns"])
        m.knots = {k[len("knots__"):]: v for k, v in arrays.items()
                   if k.startswith("knots__")}
        m.bs_map = dict(ex.get("bs_map") or {})
        return m


class H2OGeneralizedAdditiveEstimator(ModelBuilder):
    algo = "gam"

    def __init__(self, **params):
        merged = dict(GLM_DEFAULTS)
        merged.update(GAM_DEFAULTS)
        merged.update(params)
        for alias in ("lambda_", "lambda"):
            if alias in merged:
                merged["Lambda"] = merged.pop(alias)
        super().__init__(**merged)

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        p = self.params
        gam_cols = p.get("gam_columns") or []
        if isinstance(gam_cols, str):
            gam_cols = [gam_cols]
        gam_cols = [c[0] if isinstance(c, (list, tuple)) else c
                    for c in gam_cols]
        if not gam_cols:
            raise ValueError("GAM requires gam_columns")
        nk = p.get("num_knots", 6)
        nk_list = (list(nk) if isinstance(nk, (list, tuple))
                   else [nk] * len(gam_cols))
        # knots at weighted-less quantiles of each gam column (reference
        # default: quantile-spaced knots, GamUtils.generateKnotsFromKeys)
        knots: Dict[str, np.ndarray] = {}
        for gc, k in zip(gam_cols, nk_list):
            xv = training_frame.vec(gc).to_numpy()
            xv = xv[~np.isnan(xv)]
            if len(np.unique(xv)) < int(k):
                raise ValueError(
                    f"gam column '{gc}' has fewer distinct values than "
                    f"num_knots={k}")
            qs = np.linspace(0, 1, int(k))
            kn = np.quantile(xv, qs)
            # strictly increasing knots
            kn = np.maximum.accumulate(kn + np.arange(len(kn)) * 1e-12)
            knots[gc] = kn
        bs = p.get("bs")
        bs_list = (list(bs) if isinstance(bs, (list, tuple))
                   else [bs] * len(gam_cols))
        bs_map = {gc: (None if b is None else int(b))
                  for gc, b in zip(gam_cols, bs_list)}
        train_x, basis_names = _expand_gam_frame(
            training_frame, gam_cols, knots, bool(p.get("keep_gam_cols")),
            bs_map)
        vf = None
        if validation_frame is not None:
            vf, _ = _expand_gam_frame(validation_frame, gam_cols, knots,
                                      bool(p.get("keep_gam_cols")), bs_map)
        if x is None:
            glm_x = None
        else:
            glm_x = [c for c in x if c not in gam_cols] + basis_names
        glm_params = {k_: v for k_, v in p.items()
                      if k_ not in GAM_DEFAULTS}
        # I-spline smooths are monotone only with non-negative
        # coefficients ON THEIR OWN BASIS BLOCK (hex/gam ISplines): the
        # constraint rides as a per-column mask so other predictors and
        # other smooths keep unconstrained signs
        is_basis = [bn for bn in basis_names
                    if bs_map.get(bn.split("_tp_")[0]) == 2]
        if is_basis:
            glm_params["non_negative_columns"] = is_basis
        # default smoothing: ridge on the spline block via elastic net
        # (only when lambda is genuinely UNSET — an explicit 0 means the
        # user asked for an unpenalized fit)
        if glm_params.get("Lambda") is None and not glm_params.get(
                "lambda_search"):
            glm_params["Lambda"] = [1e-4]
            glm_params.setdefault("alpha", 0.0)
        inner_est = H2OGeneralizedLinearEstimator(**glm_params)
        inner_est.train(x=glm_x, y=y, training_frame=train_x,
                        validation_frame=vf, **kw)
        inner = inner_est.model
        model = GAMModel(f"gam_{id(self) & 0xffffff:x}", self.params,
                         _SpecShim(training_frame, y, inner), inner,
                         gam_cols, knots, bs_map=bs_map)
        model.training_metrics = inner.training_metrics
        model.validation_metrics = inner.validation_metrics
        model.scoring_history = inner.scoring_history
        model.output["knots"] = {k_: v.tolist() for k_, v in knots.items()}
        model.output["basis_names"] = basis_names
        model.output["coefficients"] = inner.coef()
        self.model = model
        self.job = inner_est.job
        from h2o3_tpu import dkv
        dkv.put(model.key, "model", model)
        return self

    def _train_impl(self, spec, valid_spec, job: Job):
        raise RuntimeError("GAM overrides train() directly")


class _SpecShim:
    """Minimal TrainingSpec stand-in for the wrapper Model base ctor:
    GAM's real spec lives in the inner GLM (the wrapper only needs the
    original frame's schema for save/load)."""

    def __init__(self, frame: Frame, y, inner):
        self.names = [n for n in frame.names if n != y]
        self.is_cat = [frame.vec(n).is_categorical for n in self.names]
        self.cat_domains = {n: tuple(frame.vec(n).domain or ())
                            for n in self.names
                            if frame.vec(n).is_categorical}
        self.response = y
        self.response_domain = inner.response_domain
        self.nclasses = inner.nclasses


register_model_class("gam", GAMModel)
