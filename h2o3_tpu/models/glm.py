"""GLM — generalized linear models with elastic-net regularization.

Reference: hex/glm/GLM.java:70 (solvers at hex/glm/GLMModel.java:814:
IRLSM, L-BFGS, coordinate descent), per-iteration distributed Gram+gradient
MRTask (hex/glm/GLMTask.java:1509 GLMIterationTask — per-row outer-product
accumulate), Cholesky solve on the driver (hex/gram/Gram.java:452-533),
ADMM/lambda-search elastic net (hex/optimization/ADMM.java), DataInfo
one-hot expansion + standardization (h2o-algos/.../hex/DataInfo.java:16).

TPU re-design: the Gram is ONE MXU matmul per IRLS iteration —
``Xᵀ·(w∘X)`` over the row-sharded feature matrix; GSPMD inserts the
cross-shard psum (the MRTask reduce-tree analog). The elastic-net solve on
the quadratic subproblem is glmnet-style cyclic coordinate descent ON THE
GRAM (O(F²) per sweep, on device, lax.fori_loop) — no per-row work in the
inner loop, which is where the reference burns its time. Lambda search
warm-starts down a log-spaced path from λ_max exactly like
hex/glm/GLM.java's lambda path.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        compute_metrics, pack_impute_means,
                                        unpack_impute_means)
from h2o3_tpu.persist import register_model_class

def _max_iter_of(p, default=50) -> int:
    """max_iterations <= 0 means AUTO in the reference clients (h2o-py
    sends -1): fall back to the default instead of a zero-length loop."""
    v = int(p.get("max_iterations", default) or default)
    return v if v > 0 else default


GLM_DEFAULTS: Dict = dict(
    family="auto", solver="auto", alpha=None, Lambda=None,
    lambda_search=False, nlambdas=30, lambda_min_ratio=1e-4,
    standardize=True, intercept=True, max_iterations=50,
    beta_epsilon=1e-5, gradient_epsilon=1e-6, link="family_default",
    seed=-1, tweedie_power=1.5, non_negative=False,
    # Family.tweedie (GLMModel.java:376-377 defaults: var power 0, link
    # power 1 — clients set e.g. 1.5/0 for compound Poisson-gamma + log)
    tweedie_variance_power=0.0, tweedie_link_power=1.0,
    missing_values_handling="mean_imputation",
    # round-5 closure: NB dispersion, box constraints, DataInfo
    # interactions (hex/glm/GLMModel.java:814, hex/DataInfo.java:16)
    theta=1e-10, beta_constraints=None, interactions=None,
    interaction_pairs=None, plug_values=None,
    startval=None, cold_start=False, prior=-1.0,
    max_active_predictors=-1,
    compute_p_values=False,
    # HGLM (GLMModel.java:390): gaussian mixed model, one categorical
    # random-intercept column
    HGLM=False, random_columns=None, rand_family=None, rand_link=None,
)


# ---------------- link functions --------------------------------------
# hex/glm/GLMModel.java Link enum (identity/log/logit/inverse/tweedie).
# Links are separate objects composed into families so non-canonical
# pairs (gaussian+log, gamma+inverse, poisson+identity, …) flow through
# the same IRLS working-response code (GLMModel.java:560-591 validates
# the family↔link compatibility matrix reproduced in _make_family).

class _IdentityLink:
    name = "identity"

    def link(self, mu):
        return mu

    def linkinv(self, eta):
        return eta

    def mu_eta(self, eta):
        """dμ/dη at eta."""
        return jnp.ones_like(eta)


class _LogLink(_IdentityLink):
    name = "log"

    def link(self, mu):
        return jnp.log(jnp.maximum(mu, 1e-10))

    def linkinv(self, eta):
        return jnp.exp(jnp.clip(eta, -30, 30))

    def mu_eta(self, eta):
        return self.linkinv(eta)


class _LogitLink(_IdentityLink):
    name = "logit"

    def link(self, mu):
        mu = jnp.clip(mu, 1e-7, 1 - 1e-7)
        return jnp.log(mu / (1.0 - mu))

    def linkinv(self, eta):
        return 1.0 / (1.0 + jnp.exp(-eta))

    def mu_eta(self, eta):
        mu = self.linkinv(eta)
        return jnp.maximum(mu * (1 - mu), 1e-10)


class _InverseLink(_IdentityLink):
    """η = 1/μ (the gamma canonical link in GLMModel.java:647)."""
    name = "inverse"

    @staticmethod
    def _safe(x):
        return jnp.where(jnp.abs(x) < 1e-10,
                         jnp.where(x < 0, -1e-10, 1e-10), x)

    def link(self, mu):
        return 1.0 / self._safe(mu)

    def linkinv(self, eta):
        return 1.0 / self._safe(eta)

    def mu_eta(self, eta):
        e = self._safe(eta)
        return -1.0 / (e * e)


class _TweedieLink(_IdentityLink):
    """Power link η = μ^q with q = tweedie_link_power; q = 0 is log
    (GLMModel.java:690,734 tweedie link/linkInv/linkInvDeriv)."""
    name = "tweedie"

    def __init__(self, link_power: float = 1.0):
        self.q = float(link_power)

    def link(self, mu):
        if self.q == 0.0:
            return jnp.log(jnp.maximum(mu, 1e-10))
        return jnp.power(jnp.maximum(mu, 1e-10), self.q)

    def linkinv(self, eta):
        if self.q == 0.0:
            return jnp.exp(jnp.clip(eta, -30, 30))
        return jnp.power(jnp.maximum(eta, 1e-10), 1.0 / self.q)

    def mu_eta(self, eta):
        if self.q == 0.0:
            return self.linkinv(eta)
        e = jnp.maximum(eta, 1e-10)
        d = (1.0 / self.q) * jnp.power(e, 1.0 / self.q - 1.0)
        # below the clamp μ is pinned at the floor → dμ/dη = 0: those
        # rows must drop out of the working LS or their 1/μ^p variance
        # weight explodes and IRLS diverges
        return jnp.where(eta > 1e-10, d, 0.0)


_LINKS = {"identity": _IdentityLink, "log": _LogLink, "logit": _LogitLink,
          "inverse": _InverseLink, "tweedie": _TweedieLink}


def _ordinal_cdf_fns(link: str):
    """Cumulative-link pair (cdf, inverse-cdf) for Family.ordinal —
    GLMModel.java:589 allows ologit / oprobit / ologlog. The cdf maps
    (θ_k − η) to P(y ≤ k); the inverse initializes thresholds from the
    marginal class distribution."""
    link = (link or "family_default").lower()
    if link in ("family_default", "", "ologit"):
        return (jax.nn.sigmoid,
                lambda c: jnp.log(c / (1.0 - c)))
    if link == "oprobit":
        from jax.scipy.special import ndtri
        from jax.scipy.stats import norm
        return norm.cdf, ndtri
    if link == "ologlog":
        # complementary log-log cumulative: P = 1 − exp(−exp(z))
        return (lambda z: 1.0 - jnp.exp(-jnp.exp(jnp.clip(z, -30, 3))),
                lambda c: jnp.log(-jnp.log(1.0 - c)))
    raise ValueError(
        "Incompatible link function for selected family. Only ologit, "
        f"oprobit or ologlog links allowed for family=ordinal. Got {link}")


# ---------------- family variance/deviance providers -------------------

class _Family:
    name = "gaussian"
    default_link = "identity"
    valid_links = ("identity", "log", "inverse")

    def __init__(self, link=None):
        if link is None or isinstance(link, str):
            link = _LINKS[link or self.default_link]()
        self._link = link

    @property
    def link_name(self):
        return self._link.name

    def link(self, mu):
        return self._link.link(mu)

    def linkinv(self, eta):
        return self._link.linkinv(eta)

    def mu_eta(self, eta):
        """dμ/dη at eta."""
        return self._link.mu_eta(eta)

    def variance(self, mu):
        return jnp.ones_like(mu)

    def clamp_mu(self, mu):
        """Project μ back into the response domain — non-canonical links
        (poisson+identity, gamma+inverse) can step η outside it, which
        is where naive IRLS blows up."""
        return mu

    def deviance(self, w, y, mu):
        return (w * (y - mu) ** 2).sum()

    def init_mu(self, y, w):
        return (w * y).sum() / w.sum()


class _PositiveFamily(_Family):
    """μ > 0 response domain (poisson/gamma/negbinomial/tweedie)."""

    def clamp_mu(self, mu):
        return jnp.maximum(mu, 1e-6)


class _Gaussian(_Family):
    name = "gaussian"


class _Binomial(_Family):
    name = "binomial"
    default_link = "logit"
    valid_links = ("logit",)

    def clamp_mu(self, mu):
        return jnp.clip(mu, 1e-7, 1 - 1e-7)

    def variance(self, mu):
        return jnp.maximum(mu * (1 - mu), 1e-10)

    def deviance(self, w, y, mu):
        eps = 1e-7
        mu = jnp.clip(mu, eps, 1 - eps)
        return -2.0 * (w * (y * jnp.log(mu)
                            + (1 - y) * jnp.log1p(-mu))).sum()

    def init_mu(self, y, w):
        return jnp.clip((w * y).sum() / w.sum(), 1e-4, 1 - 1e-4)


class _Poisson(_PositiveFamily):
    name = "poisson"
    default_link = "log"
    valid_links = ("log", "identity")

    def variance(self, mu):
        return jnp.maximum(mu, 1e-10)

    def deviance(self, w, y, mu):
        mu = jnp.maximum(mu, 1e-10)
        yl = jnp.where(y > 0, y * jnp.log(y / mu), 0.0)
        return 2.0 * (w * (yl - (y - mu))).sum()

    def init_mu(self, y, w):
        return jnp.maximum((w * y).sum() / w.sum(), 1e-4)


class _Gamma(_PositiveFamily):
    name = "gamma"
    # the canonical link, matching the reference default
    # (hex/glm/GLMModel.java:803 gamma -> Link.inverse)
    default_link = "inverse"
    valid_links = ("inverse", "log", "identity")

    def variance(self, mu):
        return jnp.maximum(mu * mu, 1e-10)

    def deviance(self, w, y, mu):
        mu = jnp.maximum(mu, 1e-10)
        r = jnp.maximum(y, 1e-10) / mu
        return 2.0 * (w * (-jnp.log(r) + r - 1.0)).sum()

    def init_mu(self, y, w):
        return jnp.maximum((w * y).sum() / w.sum(), 1e-4)


class _Quasibinomial(_Binomial):
    """Quasi-likelihood binomial (hex/glm GLMModel.Family.quasibinomial):
    the binomial working model with a numeric response not restricted to
    {0,1} — same IRLS weights/deviance formula evaluated at real y."""
    name = "quasibinomial"


class _FractionalBinomial(_Binomial):
    """Fractional logit (Family.fractionalbinomial): y in [0,1]
    proportions under the binomial likelihood (Papke-Wooldridge)."""
    name = "fractionalbinomial"


class _NegativeBinomial(_PositiveFamily):
    """Family.negativebinomial with log link: Var(μ) = μ + θμ²
    (hex/glm/GLMModel.java NB theta = inverse dispersion parameter)."""
    name = "negativebinomial"
    default_link = "log"
    valid_links = ("log", "identity")

    def __init__(self, theta: float = 1.0, link=None):
        super().__init__(link)
        self.theta = max(float(theta), 1e-10)

    def variance(self, mu):
        return jnp.maximum(mu + self.theta * mu * mu, 1e-10)

    def deviance(self, w, y, mu):
        t = self.theta
        mu = jnp.maximum(mu, 1e-10)
        yl = jnp.where(y > 0, y * jnp.log(jnp.maximum(y, 1e-10) / mu), 0.0)
        tail = (y + 1.0 / t) * jnp.log((1.0 + t * y) / (1.0 + t * mu))
        return 2.0 * (w * (yl - tail)).sum()

    def init_mu(self, y, w):
        return jnp.maximum((w * y).sum() / w.sum(), 1e-4)


class _Tweedie(_PositiveFamily):
    """Family.tweedie: Var(μ) = φ·μ^p with p = tweedie_variance_power
    (GLMModel.java:648) and the power link (tweedie_link_power).
    Compound Poisson-gamma for 1 < p < 2: y ≥ 0 with a point mass at 0.
    Deviance is the unit tweedie deviance with the usual p→1 / p→2
    limits (matches GLMModel.java:765-795 tweedie deviance cases)."""
    name = "tweedie"
    default_link = "tweedie"
    valid_links = ("tweedie",)

    def __init__(self, var_power: float = 0.0, link_power: float = 1.0,
                 link=None):
        super().__init__(link if link is not None
                         else _TweedieLink(link_power))
        self.p = float(var_power)

    def variance(self, mu):
        return jnp.maximum(jnp.power(jnp.maximum(mu, 1e-10), self.p),
                           1e-10)

    def deviance(self, w, y, mu):
        p = self.p
        mu = jnp.maximum(mu, 1e-10)
        if p == 0.0:
            return (w * (y - mu) ** 2).sum()
        if p == 1.0:
            yl = jnp.where(y > 0, y * jnp.log(jnp.maximum(y, 1e-10) / mu),
                           0.0)
            return 2.0 * (w * (yl - (y - mu))).sum()
        if p == 2.0:
            r = jnp.maximum(y, 1e-10) / mu
            return 2.0 * (w * (-jnp.log(r) + r - 1.0)).sum()
        yp = jnp.power(jnp.maximum(y, 0.0), 2.0 - p)
        # y^(2-p)/((1-p)(2-p)) − y·μ^(1-p)/(1-p) + μ^(2-p)/(2-p)
        term = (yp / ((1.0 - p) * (2.0 - p))
                - y * jnp.power(mu, 1.0 - p) / (1.0 - p)
                + jnp.power(mu, 2.0 - p) / (2.0 - p))
        return 2.0 * (w * term).sum()

    def init_mu(self, y, w):
        return jnp.maximum((w * y).sum() / w.sum(), 1e-4)


_FAMILIES = {"gaussian": _Gaussian, "binomial": _Binomial,
             "poisson": _Poisson, "gamma": _Gamma,
             "quasibinomial": _Quasibinomial,
             "fractionalbinomial": _FractionalBinomial,
             "negativebinomial": _NegativeBinomial,
             "tweedie": _Tweedie}

# family -> the link at which PLAIN (unguarded) IRLS is monotone-safe
# and the L-BFGS closed-form objectives in _nll_mean are written. This
# used to be spelled `type(fam).default_link`, which held only by
# coincidence: with gamma's default now the canonical `inverse` (the
# ADVICE r5 / GLMModel.java:803 fix), the gamma closed form still
# assumes LOG (mu = exp(eta): per-row y·e^{-eta} + eta), and
# gamma+inverse IRLS can step eta <= 0 (mu < 0 — the clamp_mu blowup
# case) so it needs the halving guard / is unsafe for the guardless
# streaming loop. Keying the three guards off this map instead of
# default_link keeps each solver honest about what it implements.
_PLAIN_IRLS_LINK = {"gaussian": "identity", "binomial": "logit",
                    "quasibinomial": "logit",
                    "fractionalbinomial": "logit", "poisson": "log",
                    "gamma": "log", "negativebinomial": "log"}


def _make_family(family: str, p: Dict) -> _Family:
    """Construct the family with its (validated) link from builder params
    — the GLMParameters.validate family↔link matrix
    (hex/glm/GLMModel.java:560-591)."""
    link = (p.get("link") or "family_default").lower()
    cls = _FAMILIES[family]
    if link not in ("family_default", "") and link not in cls.valid_links:
        raise ValueError(
            f"Incompatible link function for selected family. Only "
            f"{'/'.join(cls.valid_links)} allowed for family={family}. "
            f"Got {link}")
    if family == "tweedie":
        # NB: 0.0 is a meaningful link power (log) — no `or` defaulting
        twv = p.get("tweedie_variance_power")
        twl = p.get("tweedie_link_power")
        fam = _Tweedie(0.0 if twv is None else float(twv),
                       1.0 if twl is None else float(twl))
    elif family == "negativebinomial":
        fam = _NegativeBinomial(
            float(p.get("theta", 1.0) or 1.0),
            link=None if link in ("family_default", "") else link)
    else:
        fam = cls(link=None if link in ("family_default", "") else link)
    return fam


# ---------------- device kernels --------------------------------------

def _gram_kernel(Xe, w_irls, z):
    """Weighted Gram and right-hand side in one fused pass:
    G = Xᵀ(w∘X)  [Fe, Fe],  b = Xᵀ(w∘z)  [Fe].
    Under jit on row-sharded Xe, GSPMD turns the contraction into
    per-shard matmuls + psum (GLMIterationTask's reduce, GLMTask.java:1509)."""
    Xw = Xe * w_irls[:, None]
    G = jax.lax.dot_general(Xe, Xw, (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    b = Xw.T @ z
    return G, b


def _cd_elastic_net(G, b, beta0, lam_l1, lam_l2, pen_mask, n_sweeps: int,
                    non_negative=False, nn_mask=None, lo=None, hi=None):
    """Cyclic coordinate descent on ½βᵀGβ − bᵀβ + λ₁|β|₁ + ½λ₂|β|₂²
    (glmnet 'covariance updates' — hex/glm coordinate_descent analog but on
    the reduced Gram, so each sweep is O(F²) device work, no row pass).
    ``pen_mask`` is 0 for the intercept (never penalized)."""
    Fe = G.shape[0]
    diag = jnp.diag(G)

    def one_coord(j, state):
        beta, Gb = state  # Gb = G @ beta (maintained incrementally)
        gj = Gb[j] - diag[j] * beta[j]
        rho = b[j] - gj
        l1 = lam_l1 * pen_mask[j]
        bj = jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - l1, 0.0)
        bj = bj / (diag[j] + lam_l2 * pen_mask[j] + 1e-12)
        if nn_mask is not None:
            # per-COLUMN bound (GAM I-spline terms constrain only their
            # own basis block)
            bj = jnp.where(nn_mask[j] > 0, jnp.maximum(bj, 0.0), bj)
        elif non_negative:
            # bound applies to feature coefficients only, not the
            # intercept (pen_mask 0)
            bj = jnp.where(pen_mask[j] > 0, jnp.maximum(bj, 0.0), bj)
        if lo is not None:
            # beta_constraints box bounds: coordinate-wise projection is
            # exact for CD (hex/glm GLM.BetaConstraint; the reference
            # enforces via ADMM — same fixed point for box constraints)
            bj = jnp.clip(bj, lo[j], hi[j])
        delta = bj - beta[j]
        Gb = Gb + G[:, j] * delta
        beta = beta.at[j].set(bj)
        return beta, Gb

    def one_sweep(_, state):
        return jax.lax.fori_loop(0, Fe, one_coord, state)

    beta, _ = jax.lax.fori_loop(0, n_sweeps, one_sweep,
                                (beta0, G @ beta0))
    return beta


def _lbfgs_minimize(vg_fn, beta0, max_iter: int = 200, tol: float = 1e-7,
                    m: int = 10):
    """Jitted L-BFGS (two-loop recursion + Armijo backtracking), the
    hex/optimization/L_BFGS.java analog. ``vg_fn`` returns (f, grad);
    everything runs in one lax.while_loop on device — history ring
    buffers are fixed [m, P] arrays so shapes stay static.

    Reference: hex/optimization/L_BFGS.java (solve at :116, ginfo history
    :250); the reference evaluates gradients with a distributed MRTask —
    here the gradient is a GSPMD-sharded matvec, so the same code path
    scales over the ('data','model') mesh for wide designs."""
    P = beta0.shape[0]

    def two_loop(g, S, Y, rho, k):
        q = g
        alphas = jnp.zeros(m, jnp.float32)

        def bl1(i, qa):
            q, al = qa
            idx = (k - 1 - i) % m
            valid = (i < jnp.minimum(k, m)).astype(jnp.float32)
            a = valid * rho[idx] * (S[idx] @ q)
            return q - a * Y[idx], al.at[i].set(a)

        q, alphas = jax.lax.fori_loop(0, m, bl1, (q, alphas))
        il = (k - 1) % m
        sy = S[il] @ Y[il]
        yy = Y[il] @ Y[il]
        gamma = jnp.where(k > 0, sy / jnp.maximum(yy, 1e-20), 1.0)
        r = jnp.maximum(gamma, 1e-8) * q

        def bl2(i, r):
            j = m - 1 - i
            idx = (k - 1 - j) % m
            valid = (j < jnp.minimum(k, m)).astype(jnp.float32)
            b = valid * rho[idx] * (Y[idx] @ r)
            return r + valid * S[idx] * (alphas[j] - b)

        return jax.lax.fori_loop(0, m, bl2, r)

    def linesearch(beta, f, g, d):
        gtd = g @ d

        def cond(st):
            t, fn, tries, ok = st
            return (~ok) & (tries < 24)

        def body(st):
            t, fn, tries, ok = st
            fn2, _ = vg_fn(beta + t * d)
            ok2 = fn2 <= f + 1e-4 * t * gtd
            return (jnp.where(ok2, t, t * 0.5), jnp.where(ok2, fn2, fn),
                    tries + 1, ok2)

        t, fn, tries, ok = jax.lax.while_loop(
            cond, body, (jnp.float32(1.0), f, 0, False))
        return jnp.where(ok, t, 0.0)

    f0, g0 = vg_fn(beta0)
    state = (0, beta0, f0, g0, jnp.zeros((m, P), jnp.float32),
             jnp.zeros((m, P), jnp.float32), jnp.zeros(m, jnp.float32),
             0, False)

    def cond(st):
        it, beta, f, g, S, Y, rho, k, done = st
        return (~done) & (it < max_iter)

    def body(st):
        it, beta, f, g, S, Y, rho, k, done = st
        d = -two_loop(g, S, Y, rho, k)
        # safeguard: fall back to steepest descent on non-descent dirs
        d = jnp.where(g @ d < 0, d, -g)
        t = linesearch(beta, f, g, d)
        beta2 = beta + t * d
        f2, g2 = vg_fn(beta2)
        s = beta2 - beta
        yv = g2 - g
        sy = s @ yv
        upd = sy > 1e-12
        idx = k % m
        S2 = jnp.where(upd, S.at[idx].set(s), S)
        Y2 = jnp.where(upd, Y.at[idx].set(yv), Y)
        rho2 = jnp.where(upd, rho.at[idx].set(1.0 / jnp.maximum(sy, 1e-20)),
                         rho)
        k2 = jnp.where(upd, k + 1, k)
        gmax = jnp.max(jnp.abs(g2))
        done2 = (gmax < tol) | (t == 0.0)
        return (it + 1, beta2, f2, g2, S2, Y2, rho2, k2, done2)

    out = jax.lax.while_loop(cond, body, state)
    return out[1], out[2], out[0]


def _cholesky_solve(G, b, lam_l2, pen_mask):
    """Ridge/no-penalty exact solve (hex/gram/Gram.java:452 cholesky).
    A rank-deficient Gram (p > n unpenalized, collinear columns) makes
    the plain Cholesky produce NaN — mirror Gram.java's addDiag retry:
    fall back to a trace-scaled diagonal jitter when the first solve is
    non-finite (minimum-norm-ish solution instead of a NaN model)."""
    A = G + jnp.diag(lam_l2 * pen_mask + 1e-8)
    L = jnp.linalg.cholesky(A)
    x = jax.scipy.linalg.cho_solve((L, True), b)

    def _retry(_):
        # trace-scaled: eigmax <= trace, so the ridged system's
        # condition is bounded by ~1e6 — safely factorizable in f32
        # (a trace/F scale sat below f32 eps relative to eigmax and
        # still factored to NaN)
        jitter = 1e-6 * jnp.trace(G) + 1e-8
        L2 = jnp.linalg.cholesky(A + jitter * jnp.eye(G.shape[0]))
        return jax.scipy.linalg.cho_solve((L2, True), b)

    return jax.lax.cond(jnp.all(jnp.isfinite(x)),
                        lambda _: x, _retry, operand=None)


# ---------------- expansion + standardization --------------------------

def _batched_impute(X, names, is_cat, mean_of):
    """ONE masked whole-matrix impute over the numeric block (shared by
    expand_design / expand_scoring_matrix). Per-column imputes over a
    row-sharded X would each be their own cross-device program — and a
    per-column REDUCTION is its own all-reduce, which is how wide
    frames starved XLA:CPU's rendezvous (see expand_design). Returns
    (num_imp [padded, Fn] or None, {col_idx: block_pos})."""
    num_idx = [i for i, c in enumerate(is_cat) if not c]
    if not num_idx:
        return None, {}
    Xn = X[:, jnp.asarray(num_idx)]
    mh = np.asarray([mean_of(names[i]) for i in num_idx], np.float32)
    num_imp = jnp.where(jnp.isnan(Xn), jnp.asarray(mh)[None, :], Xn)
    return num_imp, {i: j for j, i in enumerate(num_idx)}

def _interaction_cols(X, names, is_cat, cat_domains, means, interactions,
                      first: int, pairs=None, cat_plugs=None):
    """DataInfo interaction terms (hex/DataInfo.java:16 _interactions /
    InteractionPair): all pairwise products among ``interactions``
    columns — num×num one product column, cat×num a per-level indicator
    × value block, cat×cat the indicator outer block (first levels
    dropped like the main one-hot). ``pairs`` gives the reference's
    explicit interaction_pairs list instead of all-combinations."""
    import itertools
    cols, out_names = [], []

    def col_of(n):
        i = names.index(n)
        x = X[:, i]
        if is_cat[i]:
            dom = cat_domains.get(n) or ()
            na_code = float((cat_plugs or {}).get(n, -1))
            codes = jnp.where(jnp.isnan(x), na_code, x).astype(jnp.int32)
            return [( (codes == lvl).astype(jnp.float32),
                      f"{n}.{dom[lvl]}") for lvl in range(first, len(dom))]
        m = means.get(n, 0.0)
        return [(jnp.where(jnp.isnan(x), m, x), n)]

    pair_iter = ([tuple(pr) for pr in pairs] if pairs
                 else itertools.combinations(interactions or (), 2))
    for a, b in pair_iter:
        if a not in names or b not in names:
            raise ValueError(f"interactions column '{a if a not in names else b}'"
                             f" is not a training feature")
        for ca, na in col_of(a):
            for cb, nb in col_of(b):
                cols.append(ca * cb)
                out_names.append(f"{na}_{nb}")
    return cols, out_names


def expand_design(spec: TrainingSpec, impute_means=None,
                  use_all_levels: bool = False, interactions=None,
                  interaction_pairs=None):
    """DataInfo analog: enum columns → one-hot indicator blocks (all
    levels except the first unless ``use_all_levels``,
    useAllFactorLevels=False default), numerics mean-imputed for NAs,
    plus pairwise interaction terms among the ``interactions`` columns
    (hex/DataInfo.java _interactions). Returns (Xe [padded, Fe] device,
    names, and the per-column imputation means for scoring reuse)."""
    cols = []
    names: List[str] = []
    means = {} if impute_means is None else impute_means
    first = 0 if use_all_levels else 1
    # Numeric means are ONE batched masked reduction over the whole
    # numeric block, not a per-column nansum: each per-column reduction
    # over the row-sharded X is its own cross-device all-reduce, and a
    # wide frame (10k columns) would enqueue 10k tiny rendezvous
    # collectives — observed starving XLA:CPU's 8-participant
    # rendezvous past its 40s termination timeout (process abort) on a
    # small host, and it is exactly the fusion TPU wants anyway.
    if impute_means is None:
        num_idx = [i for i, c in enumerate(spec.is_cat) if not c]
        if num_idx:
            Xn = spec.X[:, jnp.asarray(num_idx)]        # [padded, Fn]
            nan_n = jnp.isnan(Xn)
            wn = spec.w[:, None]
            msum = jnp.where(nan_n, 0.0, Xn * wn).sum(axis=0)
            mcnt = jnp.maximum((wn * (~nan_n)).sum(axis=0), 1e-12)
            mh = np.asarray(jax.device_get(msum / mcnt), np.float32)
            for j, i in enumerate(num_idx):
                means[spec.names[i]] = float(mh[j])

    def _mean_of(n):
        # means values may be floats or device scalars
        return float(np.asarray(jax.device_get(means.get(n, 0.0))))

    num_imp, num_pos = _batched_impute(spec.X, spec.names, spec.is_cat,
                                       _mean_of)
    for i, (n, is_cat) in enumerate(zip(spec.names, spec.is_cat)):
        x = spec.X[:, i]
        if is_cat:
            card = len(spec.cat_domains.get(n, ())) or int(
                jnp.nanmax(jnp.where(jnp.isnan(x), 0.0, x))) + 1
            dom = spec.cat_domains.get(n) or tuple(str(k) for k in range(card))
            codes = jnp.where(jnp.isnan(x), -1, x).astype(jnp.int32)
            for lvl in range(first, card):
                cols.append((codes == lvl).astype(jnp.float32))
                names.append(f"{n}.{dom[lvl]}")
        else:
            cols.append(num_imp[:, num_pos[i]])
            names.append(n)
    if interactions or interaction_pairs:
        icols, inames = _interaction_cols(
            spec.X, list(spec.names), list(spec.is_cat), spec.cat_domains,
            means, list(interactions or ()), first,
            pairs=interaction_pairs)
        cols += icols
        names += inames
    Xe = jnp.stack(cols, axis=1) if cols else jnp.zeros((spec.X.shape[0], 0))
    return Xe, names, means


def expand_scoring_matrix(model, X):
    """Expand a raw adapt_test_matrix output with a model's training-time
    design (enum indicator blocks + mean imputation). Shared by GLM/
    DeepLearning/KMeans/PCA (any model carrying feature_names/
    feature_is_cat/cat_domains/impute_means, plus an optional
    use_all_levels flag)."""
    cols = []
    first = 0 if getattr(model, "use_all_levels", False) else 1
    num_imp, num_pos = _batched_impute(
        X, model.feature_names, model.feature_is_cat,
        lambda n: float(model.impute_means.get(n, 0.0)))
    cat_plugs = getattr(model, "cat_plugs", None) or {}
    for i, (n, is_cat) in enumerate(zip(model.feature_names,
                                        model.feature_is_cat)):
        x = X[:, i]
        if is_cat:
            card = len(model.cat_domains.get(n, ()))
            # PlugValues-trained models substitute the plug level for
            # NA enums at scoring (hex/DataInfo PlugValues)
            codes = jnp.where(jnp.isnan(x), float(cat_plugs.get(n, -1)),
                              x).astype(jnp.int32)
            for lvl in range(first, card):
                cols.append((codes == lvl).astype(jnp.float32))
        else:
            cols.append(num_imp[:, num_pos[i]])
    mp = (model.params or {}) if hasattr(model, "params") else {}
    inter = mp.get("interactions")
    ipairs = mp.get("interaction_pairs")
    if inter or ipairs:
        icols, _ = _interaction_cols(
            X, list(model.feature_names), list(model.feature_is_cat),
            model.cat_domains, model.impute_means, list(inter or ()),
            first, pairs=ipairs, cat_plugs=cat_plugs)
        cols += icols
    return jnp.stack(cols, axis=1) if cols else jnp.zeros((X.shape[0], 0))


def _parse_beta_constraints(bc):
    """Accept the reference's beta_constraints shapes: a Frame with
    names/lower_bounds/upper_bounds columns (h2o-py passes a frame), a
    list of {names, lower_bounds, upper_bounds} dicts, or a
    {name: (lo, hi)} mapping. Returns [(name, lo, hi), ...]."""
    out = []
    if hasattr(bc, "vec") and hasattr(bc, "names"):       # Frame
        names = bc.vec("names").to_strings()
        lo = (bc.vec("lower_bounds").to_numpy()
              if "lower_bounds" in bc.names else [-np.inf] * len(names))
        hi = (bc.vec("upper_bounds").to_numpy()
              if "upper_bounds" in bc.names else [np.inf] * len(names))
        for n, l, h in zip(names, lo, hi):
            out.append((str(n),
                        -np.inf if l is None or (isinstance(l, float)
                                                 and np.isnan(l)) else float(l),
                        np.inf if h is None or (isinstance(h, float)
                                                and np.isnan(h)) else float(h)))
    elif isinstance(bc, dict):
        for n, (l, h) in bc.items():
            out.append((str(n), float(l), float(h)))
    else:                                                 # list of dicts
        for e in bc:
            out.append((str(e["names"]),
                        float(e.get("lower_bounds", -np.inf)),
                        float(e.get("upper_bounds", np.inf))))
    return out


# ---------------- model -------------------------------------------------

class GLMModel(Model):
    algo = "glm"

    def __init__(self, key, params, spec, family, beta, intercept_val,
                 exp_names, impute_means, lambda_best, null_dev, res_dev,
                 nobs, rank):
        super().__init__(key, params, spec)
        self.family = family
        self.beta = np.asarray(beta)           # raw-scale, [Fe] or [Fe, K]
        self.intercept_value = (np.asarray(intercept_val)
                                if np.ndim(intercept_val) else
                                float(intercept_val))
        self.exp_names = list(exp_names)
        self.impute_means = {k: float(v) for k, v in impute_means.items()}
        self.lambda_best = lambda_best
        self.null_deviance = null_dev
        self.residual_deviance = res_dev
        self.nobs = nobs
        self.rank = rank

    def coef(self) -> Dict[str, float]:
        if self.family == "multinomial":
            # per-class coefficient maps keyed by response level
            dom = self.response_domain or tuple(
                str(k) for k in range(self.nclasses))
            out: Dict[str, Dict[str, float]] = {}
            for k, lbl in enumerate(dom):
                d = {"Intercept": float(self.intercept_value[k])}
                d.update({n: float(self.beta[j, k])
                          for j, n in enumerate(self.exp_names)})
                out[str(lbl)] = d
            return out
        if self.family == "ordinal":
            # per-threshold intercepts (cumulative-logit cutpoints)
            d = {f"Intercept_{k}": float(v)
                 for k, v in enumerate(np.atleast_1d(self.intercept_value))}
            d.update({n: float(b) for n, b in zip(self.exp_names, self.beta)})
            return d
        d = {"Intercept": self.intercept_value}
        d.update({n: float(b) for n, b in zip(self.exp_names, self.beta)})
        return d

    def coef_with_p_values(self) -> Dict[str, Dict[str, float]]:
        """Std errors / z / p per coefficient (requires
        compute_p_values=True at train; hex/glm/GLMModel computePValues)."""
        pv = self.output.get("p_values")
        if not pv:
            raise ValueError(
                "p-values were not computed — train with "
                "compute_p_values=True (and no L1 penalty)")
        return {"coefficients": self.coef(),
                "std_errs": self.output["std_errs"],
                "z_values": self.output["z_values"],
                "p_values": pv}

    def _predict_matrix(self, X, offset=None):
        Xe = expand_scoring_matrix(self, X)
        if self.family == "ordinal":
            eta = Xe @ jnp.asarray(self.beta)
            if offset is not None:
                eta = eta + offset
            th = jnp.asarray(self.intercept_value)          # [K-1] ascending
            ocdf, _ = _ordinal_cdf_fns(self.params.get("link"))
            cdf = ocdf(th[None, :] - eta[:, None])
            K = th.shape[0] + 1
            probs = jnp.concatenate(
                [cdf[:, :1],
                 cdf[:, 1:] - cdf[:, :-1],
                 1.0 - cdf[:, -1:]], axis=1)
            return jnp.clip(probs, 1e-9, 1.0)
        if self.family == "multinomial":
            eta = Xe @ jnp.asarray(self.beta) + \
                jnp.asarray(self.intercept_value)[None, :]
            if offset is not None:
                eta = eta + offset[:, None]
            return jax.nn.softmax(eta, axis=1)
        eta = Xe @ jnp.asarray(self.beta) + self.intercept_value
        if offset is not None:
            eta = eta + offset
        fam = _make_family(self.family, self.params)
        mu = fam.linkinv(eta)
        if self.nclasses == 2:
            return jnp.stack([1.0 - mu, mu], axis=1)
        return mu

    # -- persistence ----------------------------------------------------

    def _save_arrays(self):
        return {"beta": self.beta,
                **pack_impute_means(self.impute_means)}

    def _save_extra_meta(self):
        icpt = (self.intercept_value.tolist()
                if isinstance(self.intercept_value, np.ndarray)
                else self.intercept_value)
        return {"family": self.family, "intercept": icpt,
                "cat_plugs": getattr(self, "cat_plugs", None),
                "exp_names": self.exp_names, "lambda_best": self.lambda_best,
                "null_deviance": self.null_deviance,
                "residual_deviance": self.residual_deviance,
                "nobs": self.nobs, "rank": self.rank}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.family = ex["family"]
        m.intercept_value = (np.asarray(ex["intercept"])
                             if isinstance(ex["intercept"], list)
                             else ex["intercept"])
        m.exp_names = list(ex["exp_names"])
        m.cat_plugs = ex.get("cat_plugs")
        m.lambda_best = ex["lambda_best"]
        m.null_deviance = ex["null_deviance"]
        m.residual_deviance = ex["residual_deviance"]
        m.nobs = ex["nobs"]
        m.rank = ex["rank"]
        m.beta = arrays["beta"]
        m.impute_means = unpack_impute_means(arrays)
        return m


class HGLMModel(GLMModel):
    """HGLM fit: gaussian mean model + ONE gaussian random-intercept
    component (hex/glm/GLMModel.java:390 _HGLM; validation at
    GLMModel.java:519-546 restricts to gaussian/gaussian + identity
    links + one categorical random column). Prediction adds the
    per-level BLUP u to the fixed linear predictor; unseen/NA levels
    contribute u = 0 (the random effect's prior mean)."""
    algo = "hglm"

    # extra attrs set by the trainer: rand_column, rand_domain,
    # ubeta (np [q]), varfix, varranef

    def _predict_matrix(self, X, offset=None):
        from types import SimpleNamespace
        ridx = self.feature_names.index(self.rand_column)
        keep = [i for i in range(len(self.feature_names)) if i != ridx]
        proxy = SimpleNamespace(
            feature_names=[self.feature_names[i] for i in keep],
            feature_is_cat=[self.feature_is_cat[i] for i in keep],
            cat_domains=self.cat_domains,
            cat_plugs=getattr(self, "cat_plugs", None),
            impute_means=self.impute_means, params={})
        Xe = expand_scoring_matrix(proxy, X[:, keep])
        eta = Xe @ jnp.asarray(self.beta) + self.intercept_value
        if offset is not None:
            eta = eta + offset
        u = jnp.asarray(self.ubeta, jnp.float32)
        codes = jnp.where(jnp.isnan(X[:, ridx]), -1,
                          X[:, ridx]).astype(jnp.int32)
        ok = (codes >= 0) & (codes < u.shape[0])
        uz = jnp.where(ok, u[jnp.clip(codes, 0, u.shape[0] - 1)], 0.0)
        return eta + uz

    def coef_random(self) -> Dict[str, float]:
        """Random-effect BLUPs keyed by level (reference 'ubeta')."""
        return {str(lvl): float(v)
                for lvl, v in zip(self.rand_domain, self.ubeta)}

    def _save_arrays(self):
        d = super()._save_arrays()
        d["ubeta"] = np.asarray(self.ubeta)
        return d

    def _save_extra_meta(self):
        d = super()._save_extra_meta()
        d.update({"rand_column": self.rand_column,
                  "rand_domain": list(self.rand_domain),
                  "varfix": self.varfix, "varranef": self.varranef})
        return d

    @classmethod
    def _restore(cls, meta, arrays):
        m = super()._restore(meta, arrays)
        ex = meta["extra"]
        m.rand_column = ex["rand_column"]
        m.rand_domain = tuple(ex["rand_domain"])
        m.varfix = ex["varfix"]
        m.varranef = ex["varranef"]
        m.ubeta = arrays["ubeta"]
        return m


class H2OGeneralizedLinearEstimator(ModelBuilder):
    algo = "glm"
    supports_streaming = True

    def __init__(self, **params):
        merged = dict(GLM_DEFAULTS)
        merged.update(params)
        # h2o-py spells it lambda_ in python and "lambda" on the wire
        for alias in ("lambda_", "lambda"):
            if alias in merged:
                merged["Lambda"] = merged.pop(alias)
        super().__init__(**merged)

    def _resolve_family(self, spec) -> str:
        fam = (self.params.get("family") or "auto").lower()
        if fam in ("auto", ""):
            if spec.nclasses == 2:
                return "binomial"
            if spec.nclasses > 2:
                return "multinomial"
            return "gaussian"
        return fam

    def _train_streaming(self, spec: TrainingSpec, job: Job) -> "GLMModel":
        """Memory-pressure IRLS: the design exceeded the device budget,
        so each IRLS iteration streams host row-chunks, expanding +
        standardizing per chunk and accumulating the weighted Gram and
        RHS on device (hex/gram/Gram.java chunk-wise accumulate is the
        same shape; here the 'chunks' are host-resident). Supports the
        core families with ridge/no penalty; lambda search, elastic-net
        CD and multinomial need the dense path."""
        from dataclasses import replace as dc_replace
        from h2o3_tpu import memman
        p = self.params
        family = (p.get("family") or "gaussian").lower()
        if spec.nclasses > 2 or family == "multinomial":
            raise NotImplementedError(
                "multinomial GLM is not supported in streaming mode")
        if spec.offset is not None:
            raise NotImplementedError(
                "offset_column is not supported in streaming mode")
        if not bool(p.get("intercept", True)):
            raise NotImplementedError(
                "intercept=False is not supported in streaming mode")
        if p.get("interactions") or p.get("beta_constraints"):
            raise NotImplementedError(
                "interactions/beta_constraints are not supported in "
                "streaming mode")
        alpha = p.get("alpha")
        if isinstance(alpha, (list, tuple)):
            alpha = alpha[0] if alpha else None
        lam_set = p.get("Lambda") or p.get("lambda", 0.0)
        if isinstance(lam_set, (list, tuple)):
            lam_any = any(float(v) > 0 for v in lam_set)
        else:
            lam_any = float(lam_set or 0.0) > 0
        # dense semantics default alpha to 0.5 when unset: an L1
        # component with lambda>0 needs the dense CD path
        if lam_any and (alpha is None or float(alpha) > 0):
            raise NotImplementedError(
                "elastic-net/lasso (alpha>0, the default when unset) is "
                "not supported in streaming mode; set alpha=0 for ridge")
        if p.get("lambda_search"):
            raise NotImplementedError(
                "lambda_search is not supported in streaming mode")
        if family not in _FAMILIES:
            raise NotImplementedError(
                f"family '{family}' is not supported in streaming mode")
        fam = _make_family(family, p)
        if fam.link_name != _PLAIN_IRLS_LINK.get(family) \
                or family == "tweedie":
            # the chunked IRLS loop has no line-search guard; without it
            # links outside the monotone-safe set can diverge to NaN
            # silently (the dense path guards them with step halving).
            # Note gamma's DEFAULT link is now the canonical 'inverse'
            # (unsafe here) — streamed gamma needs link='log' explicitly
            raise NotImplementedError(
                "only the monotone-safe family/link pairs "
                "(gaussian/identity, binomial/logit, poisson/log, "
                "gamma/log, negativebinomial/log) are supported in "
                "streaming (memory-pressure) mode")
        rows = spec.nrow
        Xh = spec.X_host[:rows]
        yh = np.asarray(jax.device_get(spec.y))[:rows].astype(np.float32)
        wh = np.asarray(jax.device_get(spec.w))[:rows].astype(np.float32)
        F0 = Xh.shape[1]
        # chunk sizing must use the EXPANDED width: one-hot blocks can
        # dwarf the raw column count (a 2000-level enum is 2000 columns)
        Fe_est = sum(max(len(spec.cat_domains.get(n, ())) - 1, 1)
                     if c else 1
                     for n, c in zip(spec.names, spec.is_cat)) or 1
        budget = memman.manager().budget
        chunk = int(max(min(budget // max(Fe_est * 4 * 6, 1), rows), 1024))
        # pass 0: imputation means + expanded-design standardization
        # stats (weighted), accumulated host-side
        means = {n: float(np.nansum(Xh[:rows, i] * wh)
                          / max(float((wh * ~np.isnan(Xh[:rows, i])).sum()),
                                1e-12))
                 for i, (n, c) in enumerate(zip(spec.names, spec.is_cat))
                 if not c}

        def chunk_spec(s, e):
            return dc_replace(spec, X=jnp.asarray(Xh[s:e]),
                              w=jnp.asarray(wh[s:e]), stream=False,
                              X_host=None)

        sums = sumsq = None
        wsum = 0.0
        exp_names = None
        for s in range(0, rows, chunk):
            e = min(s + chunk, rows)
            Xe, exp_names, _ = expand_design(chunk_spec(s, e),
                                             impute_means=means)
            wv = jnp.asarray(wh[s:e])
            cs = (Xe * wv[:, None]).sum(axis=0)
            cq = (Xe * Xe * wv[:, None]).sum(axis=0)
            sums = cs if sums is None else sums + cs
            sumsq = cq if sumsq is None else sumsq + cq
            wsum += float(wv.sum())
        standardize = bool(p.get("standardize", True))
        xm = sums / max(wsum, 1e-12)
        xv = jnp.maximum(sumsq / max(wsum, 1e-12) - xm * xm, 1e-12)
        xs = jnp.sqrt(xv) if standardize else jnp.ones_like(xv)
        if not standardize:
            xm = jnp.zeros_like(xm)
        Fe = int(xm.shape[0])
        ncoef = Fe + 1                       # + intercept
        lam = float((p.get("Lambda") or [0.0])[0]
                    if isinstance(p.get("Lambda"), (list, tuple))
                    else (p.get("Lambda") or 0.0))
        pen_mask = jnp.concatenate([jnp.ones(Fe), jnp.zeros(1)])
        beta = jnp.zeros(ncoef, jnp.float32)
        # null model intercept init
        mu0 = float(np.sum(yh * wh) / max(wh.sum(), 1e-12))
        beta = beta.at[-1].set(fam.link(jnp.float32(mu0)))
        max_iter = _max_iter_of(p, 30)
        for it in range(max_iter):
            G = jnp.zeros((ncoef, ncoef), jnp.float32)
            b = jnp.zeros(ncoef, jnp.float32)
            for s in range(0, rows, chunk):
                e = min(s + chunk, rows)
                memman.manager().request((e - s) * Fe * 4)
                Xe, _, _ = expand_design(chunk_spec(s, e),
                                         impute_means=means)
                Xs = (Xe - xm[None, :]) / xs[None, :]
                Xs = jnp.concatenate(
                    [Xs, jnp.ones((Xs.shape[0], 1), jnp.float32)], axis=1)
                yv = jnp.asarray(yh[s:e])
                wv = jnp.asarray(wh[s:e])
                eta = Xs @ beta
                mu = fam.clamp_mu(fam.linkinv(eta))
                dmu = fam.mu_eta(eta)
                var = fam.variance(mu)
                w_irls = wv * dmu * dmu / var
                z = eta + (yv - mu) * dmu / jnp.maximum(dmu * dmu, 1e-12)
                Gc, bc = _gram_kernel(Xs, w_irls, z)
                G = G + Gc
                b = b + bc
            # dense-path penalty scaling: lam2 = lam * nobs against the
            # UNNORMALIZED Gram (see the dense IRLS at lam2 = lam *
            # (1-alpha) * nobs); alpha is 0 here by the guard above
            nb = _cholesky_solve(G, b, lam * max(wsum, 1.0), pen_mask)
            delta = float(jnp.max(jnp.abs(nb - beta)))
            beta = nb
            job.set_progress(min(0.9, (it + 1) / max_iter))
            if delta < float(p.get("beta_epsilon", 1e-5) or 1e-5):
                break
            if job.cancel_requested:
                # watchdog max_runtime / REST cancel: keep the current
                # beta as the partial fit instead of running out the
                # remaining IRLS sweeps over every host chunk
                break
        # final pass: deviances + metrics
        mu_host = np.zeros(rows, np.float32)
        for s in range(0, rows, chunk):
            e = min(s + chunk, rows)
            Xe, _, _ = expand_design(chunk_spec(s, e), impute_means=means)
            Xs = (Xe - xm[None, :]) / xs[None, :]
            Xs = jnp.concatenate(
                [Xs, jnp.ones((Xs.shape[0], 1), jnp.float32)], axis=1)
            mu_host[s:e] = np.asarray(jax.device_get(
                fam.linkinv(Xs @ beta)))
        yj = jnp.asarray(yh)
        wj = jnp.asarray(wh)
        muj = jnp.asarray(mu_host)
        res_dev = float(jax.device_get(fam.deviance(wj, yj, muj)))
        null_dev = float(jax.device_get(fam.deviance(
            wj, yj, jnp.full(rows, mu0, jnp.float32))))
        # raw-scale coefficients
        b_std = beta[:-1]
        b_raw = b_std / xs
        icpt = float(beta[-1] - jnp.sum(b_std * xm / xs))
        model = GLMModel(f"glm_{id(self) & 0xffffff:x}", p, spec, family,
                         np.asarray(jax.device_get(b_raw)), icpt,
                         exp_names, means, lam, null_dev, res_dev,
                         float(wh.sum()), int(Fe + 1))
        model.output["streamed"] = True
        if spec.nclasses == 2:
            probs = np.stack([1.0 - mu_host, mu_host], axis=1)
            model.training_metrics = compute_metrics(
                jnp.asarray(probs), yj, wj, 2, spec.response_domain)
        else:
            model.training_metrics = compute_metrics(
                muj, yj, wj, 1, deviance=res_dev / max(wh.sum(), 1e-12))
        return model

    def _train_hglm(self, spec: TrainingSpec, valid_spec,
                    job: Job) -> "HGLMModel":
        """HGLM (GLM.java HGLM mode / Lee & Nelder h-likelihood):
        y = Xβ + Zu + e with u ~ N(0, σ²_u I_q) over ONE categorical
        random-intercept column, e ~ N(0, σ²_e), identity links
        (validation mirrors GLMModel.java:519-546).

        TPU redesign: instead of the reference's per-chunk HGLM tasks,
        each EM step is Henderson's mixed-model equations solved by a
        Schur complement on the fixed block — Z'Z is diagonal so the
        random block inverts elementwise and the only dense solve is
        F×F. The Gram/group-sum reductions are one-hot matmuls (MXU)
        over the row-sharded design. Variance components update by
        EM-REML; the fixed point equals the directly optimized REML
        criterion (tests/test_hglm.py golden)."""
        from dataclasses import replace as dc_replace
        p = self.params
        family = self._resolve_family(spec)
        if family not in ("gaussian",):
            raise ValueError("HGLM only supports Gaussian distributions "
                             "for now.")
        link = (p.get("link") or "family_default").lower()
        if link not in ("family_default", "", "identity"):
            raise ValueError("HGLM only supports identity link functions "
                             "for now.")
        for rf in (p.get("rand_family") or []):
            if str(rf).lower() != "gaussian":
                raise ValueError("HGLM only supports Gaussian "
                                 "distributions for now.")
        for rl in (p.get("rand_link") or []):
            if str(rl).lower() not in ("identity", "family_default"):
                raise ValueError("HGLM only supports identity link "
                                 "functions for now.")
        if p.get("lambda_search"):
            raise ValueError("HGLM does not allow lambda search.")
        if spec.offset is not None:
            raise NotImplementedError(
                "offset_column is not supported with HGLM")
        rc = p.get("random_columns")
        if not rc:
            raise ValueError("Need to specify the random component "
                             "columns for HGLM.")
        if isinstance(rc, (str, int)):
            rc = [rc]
        if len(rc) != 1:
            raise ValueError("HGLM only supports ONE random component "
                             "for now.")
        r0 = rc[0]
        if isinstance(r0, int) or (isinstance(r0, str) and r0.isdigit()):
            ridx = int(r0)
            if not (0 <= ridx < len(spec.names)):
                raise ValueError(f"random_columns index {ridx} out of "
                                 f"range for predictors {spec.names}")
        else:
            if r0 not in spec.names:
                raise ValueError(f"random_columns '{r0}' is not a "
                                 f"predictor column")
            ridx = spec.names.index(r0)
        rname = spec.names[ridx]
        if not spec.is_cat[ridx]:
            raise ValueError("HGLM random_columns: Must contain "
                             "categorical columns.")
        rdom = spec.cat_domains.get(rname) or ()
        q = len(rdom)
        if q < 2:
            raise ValueError(f"random column '{rname}' needs >= 2 levels")

        codes = jnp.where(jnp.isnan(spec.X[:, ridx]), -1,
                          spec.X[:, ridx]).astype(jnp.int32)
        keep = [i for i in range(len(spec.names)) if i != ridx]
        fspec = dc_replace(
            spec, X=spec.X[:, jnp.asarray(keep)],
            names=[spec.names[i] for i in keep],
            is_cat=[spec.is_cat[i] for i in keep])
        Xe, exp_names, means = expand_design(fspec)
        n_pad = Xe.shape[0]
        Fe = Xe.shape[1]
        Xf = jnp.concatenate([Xe, jnp.ones((n_pad, 1), jnp.float32)],
                             axis=1)
        pf = Fe + 1
        y = spec.y.astype(jnp.float32)
        # NA random-column rows carry no group info: drop them (weight 0)
        w = spec.w * (codes >= 0)
        nobs = float(jax.device_get(w.sum()))

        # one-hot group reductions ride the MXU (q × n · n × F)
        onehot = (codes[:, None] == jnp.arange(q)[None, :]).astype(
            jnp.float32) * w[:, None]

        @jax.jit
        def _moments():
            Xw = Xf * w[:, None]
            XtX = Xw.T @ Xf
            Xty = Xw.T @ y
            counts = onehot.sum(axis=0)
            Zty = onehot.T @ y
            M = onehot.T @ Xf                       # [q, pf]
            return XtX, Xty, counts, Zty, M

        XtX, Xty, counts, Zty, M = _moments()

        @jax.jit
        def em_step(se2, su2):
            lam = se2 / jnp.maximum(su2, 1e-12)
            D = counts + lam
            Md = M / D[:, None]
            A = XtX - Md.T @ M
            rhs = Xty - M.T @ (Zty / D)
            beta = jnp.linalg.solve(A, rhs)
            u = (Zty - M @ beta) / D
            r = (y - Xf @ beta - u[jnp.clip(codes, 0, q - 1)]) * (w > 0)
            rss = (w * r * r).sum()
            Ainv_Mt = jnp.linalg.solve(A, Md.T)     # [pf, q]
            tr_uu = (1.0 / D).sum() + (Md * Ainv_Mt.T).sum()
            su2_new = ((u * u).sum() + se2 * tr_uu) / q
            se2_new = (rss + se2 * (pf + q - lam * tr_uu)) / nobs
            return beta, u, rss, tr_uu, su2_new, se2_new, A, D

        var_y = float(jax.device_get(
            (w * (y - (w * y).sum() / nobs) ** 2).sum() / nobs))
        se2, su2 = var_y, max(var_y / 2, 1e-6)
        max_iter = _max_iter_of(p, 100)
        eta_prev = None
        convergence = float("nan")
        it = 0
        converged = False
        for it in range(max_iter):
            beta, u, rss, tr_uu, su2_n, se2_n, A, D = em_step(
                jnp.float32(se2), jnp.float32(su2))
            se2_new = float(jax.device_get(se2_n))
            su2_new = float(jax.device_get(su2_n))
            done = (abs(se2_new - se2) < 1e-9 * (1 + se2)
                    and abs(su2_new - su2) < 1e-9 * (1 + su2))
            se2, su2 = max(se2_new, 1e-12), max(su2_new, 1e-12)
            # convergence diagnostic Σ(η_i−η_prev)²/Ση² (GLM.java:569)
            eta_i = Xf @ beta + u[jnp.clip(codes, 0, q - 1)] * (codes >= 0)
            if eta_prev is not None:
                convergence = float(jax.device_get(
                    ((eta_i - eta_prev) ** 2).sum()
                    / jnp.maximum((eta_i ** 2).sum(), 1e-12)))
            eta_prev = eta_i
            job.set_progress((it + 1) / max_iter)
            if done:
                converged = True
                break
            if job.cancel_requested:
                break
        beta, u = np.asarray(jax.device_get(beta)), np.asarray(
            jax.device_get(u))
        rss = float(jax.device_get(rss))
        tr_uu = float(jax.device_get(tr_uu))

        # standard errors from σ²_e·C⁻¹: fixed block = A⁻¹ (Schur),
        # random block diag = 1/D + rowwise M/D·A⁻¹·(M/D)'
        A_h = np.asarray(jax.device_get(A))
        D_h = np.asarray(jax.device_get(D))
        M_h = np.asarray(jax.device_get(M))
        Ainv = np.linalg.inv(A_h)
        sefe = np.sqrt(np.maximum(se2 * np.diag(Ainv), 0.0))
        Md_h = M_h / D_h[:, None]
        cuu_diag = 1.0 / D_h + np.einsum("qf,fg,qg->q", Md_h, Ainv, Md_h)
        sere = np.sqrt(np.maximum(se2 * cuu_diag, 0.0))

        # h-likelihood family (Lee & Nelder 1996): joint loglik + the
        # adjusted profiles; cAIC with effective dof p+q−λ·tr(C⁻¹uu)
        uu = float(u @ u)
        hlik = (-0.5 * nobs * np.log(2 * np.pi * se2) - rss / (2 * se2)
                - 0.5 * q * np.log(2 * np.pi * su2) - uu / (2 * su2))
        lam = se2 / su2
        log_det_D = float(np.sum(np.log(D_h)))
        sgn, log_det_A = np.linalg.slogdet(A_h)
        # pvh: profile over u → subtract ½·log det(D/(2π σ²_e))
        pvh = hlik - 0.5 * (log_det_D - q * np.log(2 * np.pi * se2))
        # pbvh: profile over (β,u) jointly
        pbvh = hlik - 0.5 * (log_det_A + log_det_D
                             - (pf + q) * np.log(2 * np.pi * se2))
        cond_ll = -0.5 * nobs * np.log(2 * np.pi * se2) - rss / (2 * se2)
        pd = pf + q - lam * tr_uu
        caic = -2.0 * cond_ll + 2.0 * pd
        dfrefe = nobs - pd

        null_dev = float(jax.device_get(
            (w * (y - (w * y).sum() / max(nobs, 1e-12)) ** 2).sum()))
        model = HGLMModel(f"hglm_{id(self) & 0xffffff:x}", self.params,
                          spec, "gaussian", beta[:Fe], float(beta[Fe]),
                          exp_names,
                          {k: float(jax.device_get(v))
                           for k, v in means.items()},
                          0.0, null_dev, rss, nobs, pf)
        model.rand_column = rname
        model.rand_domain = tuple(str(v) for v in rdom)
        model.ubeta = u
        model.varfix = se2
        model.varranef = su2
        from h2o3_tpu.models.metrics import (
            ModelMetricsHGLMGaussianGaussian)
        mse = rss / max(nobs, 1e-12)
        model.training_metrics = ModelMetricsHGLMGaussianGaussian(
            fixef=[float(v) for v in beta],
            ranef=[float(v) for v in u],
            sefe=[float(v) for v in sefe],
            sere=[float(v) for v in sere],
            varfix=se2, varranef=[su2], hlik=float(hlik),
            pvh=float(pvh), pbvh=float(pbvh), caic=float(caic),
            dfrefe=float(dfrefe), converge=converged,
            convergence=convergence, iterations=it + 1,
            mse=float(mse), nobs=int(nobs))
        model.output["coefficients"] = model.coef()
        model.output["random_coefficients"] = model.coef_random()
        model.output["varfix"] = se2
        model.output["varranef"] = su2
        return model

    def _apply_mvh(self, spec: TrainingSpec):
        """missing_values_handling (hex/DataInfo MissingValuesHandling +
        hex/glm GLMParameters): MeanImputation (default, downstream),
        Skip (NA rows get weight 0 — the reference drops them from the
        task), PlugValues (substitute user-provided per-column values
        into X up front; enum plugs are level names). Returns the
        possibly-rewritten spec; plug values are recorded on the
        builder so trainers pass them as the scoring impute table."""
        from dataclasses import replace as dc_replace
        p = self.params
        # clients spell these MeanImputation / Skip / PlugValues; the
        # python surface uses snake_case — normalize both
        mvh = str(p.get("missing_values_handling")
                  or "mean_imputation").lower().replace("_", "")
        self._plug_num = None
        self._cat_plugs = None
        if mvh in ("meanimputation", ""):
            return spec
        if spec.stream:
            raise NotImplementedError(
                f"missing_values_handling={mvh} is not supported in "
                f"streaming (memory-pressure) mode")
        if mvh == "skip":
            nanrow = jnp.isnan(spec.X).any(axis=1)
            return dc_replace(spec, w=spec.w * (~nanrow))
        if mvh != "plugvalues":
            raise ValueError(
                f"unknown missing_values_handling '{mvh}' (one of "
                f"MeanImputation, Skip, PlugValues)")
        pv = p.get("plug_values")
        if pv is None:
            raise ValueError(
                "missing_values_handling=PlugValues requires a "
                "plug_values frame")
        # accept a Frame (1 row) or a {column: value} mapping
        if hasattr(pv, "vec") and hasattr(pv, "names"):
            plug = {}
            for n in pv.names:
                v = pv.vec(n)
                if v.type == "enum":
                    plug[n] = v.domain[int(np.asarray(v.to_numpy())[0])]
                elif v.type == "string":
                    plug[n] = v.to_strings()[0]
                else:
                    plug[n] = float(np.asarray(v.to_numpy())[0])
        else:
            plug = dict(pv)
        self._plug_num, self._cat_plugs = {}, {}
        Xcols = []
        for i, n in enumerate(spec.names):
            x = spec.X[:, i]
            if n not in plug:
                Xcols.append(x)
                continue
            val = plug[n]
            if spec.is_cat[i]:
                dom = spec.cat_domains.get(n) or ()
                sval = str(val)
                if sval not in dom:
                    raise ValueError(
                        f"plug_values level '{sval}' is not in the "
                        f"domain of enum column '{n}'")
                code = dom.index(sval)
                self._cat_plugs[n] = code
                Xcols.append(jnp.where(jnp.isnan(x), float(code), x))
            else:
                fv = float(val)
                self._plug_num[n] = fv
                Xcols.append(jnp.where(jnp.isnan(x), fv, x))
        return dc_replace(spec, X=jnp.stack(Xcols, axis=1))

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job) -> GLMModel:
        spec = self._apply_mvh(spec)
        if valid_spec is not None:
            # the reference plugs/skips the validation frame the same
            # way (adaptTestForTrain + MissingValuesHandling)
            valid_spec = self._apply_mvh(valid_spec)
        if self.params.get("HGLM"):
            if spec.stream:
                raise NotImplementedError(
                    "HGLM does not support streaming (memory-pressure) "
                    "mode")
            return self._train_hglm(spec, valid_spec, job)
        if spec.stream:
            if valid_spec is not None:
                raise NotImplementedError(
                    "validation_frame is not supported in streaming mode")
            return self._train_streaming(spec, job)
        p = self.params
        family = self._resolve_family(spec)
        prior = float(p.get("prior", -1.0) or -1.0)
        if prior > 0:
            # validated BEFORE any training (GLMParameters validation)
            if family != "binomial":
                raise ValueError(
                    "prior is only supported for family=binomial "
                    "(hex/glm GLMParameters validation)")
            if prior >= 1.0:
                raise ValueError(f"prior must be in (0, 1), got {prior}")
        if family in ("ordinal", "multinomial"):
            sv = p.get("startval")
            if sv is not None and len(sv):
                raise NotImplementedError(
                    f"startval is not implemented for family={family} "
                    f"(supported for the single-response families)")
        if family == "ordinal":
            return self._train_ordinal(spec, valid_spec, job)
        if family == "multinomial":
            return self._train_multinomial(spec, valid_spec, job)
        if family not in _FAMILIES:
            raise ValueError(f"unsupported family '{family}'; have "
                             f"{sorted(_FAMILIES)}")
        fit_intercept = bool(p.get("intercept", True))
        fam = _make_family(family, p)
        if family == "tweedie":
            # response-domain validation (GLMModel.java tweedie checks):
            # y < 0 never valid; y = 0 has zero density for p >= 2 and
            # the deviance's y^(2-p) term is +inf → the fit would be
            # silently frozen at the null model by the line-search guard
            live = spec.w > 0
            if bool(jax.device_get((live & (spec.y < 0)).any())):
                raise ValueError(
                    "family=tweedie requires a non-negative response")
            if fam.p >= 2.0 and bool(jax.device_get(
                    (live & (spec.y == 0)).any())):
                raise ValueError(
                    f"tweedie_variance_power={fam.p} requires a strictly "
                    f"positive response (y=0 rows are only valid for "
                    f"1 < p < 2)")
        y = spec.y.astype(jnp.float32)
        w = spec.w
        offset = spec.offset
        interactions = p.get("interactions") or None
        ipairs = p.get("interaction_pairs") or None
        Xe, exp_names, means = expand_design(
            spec, interactions=interactions, interaction_pairs=ipairs)
        Fe = Xe.shape[1]
        nobs = float(jax.device_get(w.sum()))

        # weighted standardization (DataInfo standardize=true default)
        standardize = bool(p.get("standardize", True)) and fit_intercept
        wsum = w.sum()
        xm = (Xe * w[:, None]).sum(0) / wsum
        xv = (w[:, None] * (Xe - xm[None, :]) ** 2).sum(0) / wsum
        xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
        if standardize:
            Xs = (Xe - xm[None, :]) * (1.0 / xs)[None, :] * (w > 0)[:, None]
        else:
            Xs = Xe * (w > 0)[:, None]
        if fit_intercept:
            ones = (w > 0).astype(jnp.float32)
            Xs = jnp.concatenate([Xs, ones[:, None]], axis=1)
            pen_mask = jnp.concatenate([jnp.ones(Fe), jnp.zeros(1)])
        else:
            pen_mask = jnp.ones(Fe)
        ncoef = Xs.shape[1]

        alpha = p.get("alpha")
        if isinstance(alpha, (list, tuple)):
            alpha = alpha[0] if alpha else None
        alpha = 0.5 if alpha is None else float(alpha)
        lam_param = p.get("Lambda")
        if isinstance(lam_param, (list, tuple)):
            lambdas = [float(v) for v in lam_param] or None
        elif lam_param is not None:
            lambdas = [float(lam_param)]
        else:
            lambdas = None

        # initial state: η₀ = g(μ₀) through the model's actual link
        mu0 = fam.init_mu(y, w)
        eta = jnp.full_like(y, fam.link(mu0))
        if offset is not None:
            eta = eta + offset
        null_dev = float(jax.device_get(fam.deviance(w, y, fam.linkinv(eta))))

        if lambdas is None:
            if p.get("lambda_search"):
                # λ_max: smallest λ zeroing all penalized coefs —
                # score ∇ = Xᵀ(w·(y−μ)·μ'/V); for canonical links
                # μ' == V and this reduces to Xᵀw(y−μ)
                mu = fam.linkinv(eta)
                g0 = Xs[:, :Fe].T @ (w * (y - mu) * fam.mu_eta(eta)
                                     / fam.variance(mu))
                lmax = float(jax.device_get(
                    jnp.max(jnp.abs(g0)))) / max(nobs * max(alpha, 1e-3), 1e-12)
                nl = int(p.get("nlambdas", 30) or 30)
                if nl <= 0:
                    nl = 30
                lmr = float(p.get("lambda_min_ratio", 1e-4) or 1e-4)
                if lmr <= 0:
                    lmr = 1e-4
                lmin = lmr * lmax
                lambdas = list(np.geomspace(lmax, lmin, nl))
            else:
                lambdas = [0.0]

        # the wire clients send -1 sentinels for "auto" numerics
        # (GLMParameters defaults) — fall back to our defaults
        max_iter = _max_iter_of(p, 50)
        if max_iter <= 0:
            max_iter = 50
        beta_eps = float(p.get("beta_epsilon", 1e-5))
        non_neg = bool(p.get("non_negative", False))
        # per-column non-negativity (non_negative_columns names expanded
        # design columns, e.g. a GAM term's I-spline basis block)
        nn_cols = p.get("non_negative_columns") or None
        nn_mask = None
        if nn_cols:
            nn_host = np.zeros(ncoef, np.float32)
            for i, nme in enumerate(exp_names):
                if nme in nn_cols:
                    nn_host[i] = 1.0
            nn_mask = jnp.asarray(nn_host)
            if non_neg:
                # global non_negative composes with the column mask —
                # the mask must not silently NARROW the user's constraint
                nn_mask = jnp.maximum(nn_mask, pen_mask.astype(jnp.float32))
        solver = (str(p.get("solver") or "auto")
                  ).upper().replace("-", "_")
        use_lbfgs = solver in ("L_BFGS", "LBFGS")
        if use_lbfgs and (family == "tweedie"
                          or fam.link_name != _PLAIN_IRLS_LINK.get(
                              family)):
            # _nll_mean's closed-form objectives are written at the
            # _PLAIN_IRLS_LINK pairs (gamma's assumes LOG, not the
            # canonical inverse default); other pairs go through IRLSM
            use_lbfgs = False
        if p.get("beta_constraints") and use_lbfgs:
            # box bounds are enforced by the projected-CD IRLS solver
            use_lbfgs = False
        if use_lbfgs and alpha > 0 and any(l > 0 for l in lambdas):
            raise ValueError(
                "L1 penalty (alpha > 0 with lambda > 0) is not supported "
                "by solver L_BFGS (hex/glm/GLM.java:979 forces alpha=0 for "
                "L-BFGS); use IRLSM or COORDINATE_DESCENT")
        compute_pv = bool(p.get("compute_p_values", False))
        if compute_pv and (alpha > 0 and any(l > 0 for l in lambdas)):
            raise ValueError(
                "p-values cannot be computed with an L1 penalty "
                "(hex/glm/GLM.java compute_p_values restrictions)")

        # L-BFGS objective: mean penalized negative log-likelihood on the
        # standardized design — gradients are ONE sharded matvec pair, so
        # the same code path covers the wide ('model'-axis sharded) case
        # (SURVEY §7.1.7: Criteo-wide GLM)
        def _nll_mean(bs):
            eta_i = Xs @ bs
            if offset is not None:
                eta_i = eta_i + offset
            if family in ("binomial", "quasibinomial",
                          "fractionalbinomial"):
                per = jax.nn.softplus(eta_i) - y * eta_i
            elif family == "poisson":
                per = jnp.exp(eta_i) - y * eta_i
            elif family == "gamma":
                per = y * jnp.exp(-eta_i) + eta_i
            elif family == "negativebinomial":
                th_nb = fam.theta
                mu_i = jnp.exp(jnp.clip(eta_i, -30, 30))
                per = ((y + 1.0 / th_nb) * jnp.log1p(th_nb * mu_i)
                       - y * (jnp.log(th_nb) + eta_i))
            elif family == "gaussian":
                per = 0.5 * (y - eta_i) ** 2
            else:
                raise NotImplementedError(
                    f"solver L_BFGS has no objective for family "
                    f"'{family}'")
            return (w * per).sum() / nobs

        if use_lbfgs and ncoef >= 1024:
            # WIDE path (SURVEY §7.1.7): shard the design over BOTH mesh
            # axes — rows on 'data', features on 'model'. The L-BFGS
            # gradient is a matvec pair (Xs @ β, Xsᵀ r); GSPMD partials
            # them per shard and inserts the psums, so features never
            # gather on one device (the reference cannot shard features
            # at all — every JVM node holds all columns, SURVEY §5).
            from jax.sharding import NamedSharding, PartitionSpec as P
            from h2o3_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS,
                                                current_mesh)
            mesh = current_mesh()
            if mesh is not None and mesh.shape.get(MODEL_AXIS, 1) > 1:
                pad_f = (-ncoef) % mesh.shape[MODEL_AXIS]
                if pad_f == 0 and Xs.shape[0] % mesh.shape[DATA_AXIS] == 0:
                    Xs = jax.device_put(
                        Xs, NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)))

        @jax.jit
        def lbfgs_fit(beta_init, lam2_unit):
            def obj(bs):
                return (_nll_mean(bs)
                        + 0.5 * lam2_unit * ((bs * pen_mask) ** 2).sum())
            return _lbfgs_minimize(jax.value_and_grad(obj), beta_init,
                                   max_iter=max(max_iter * 6, 300),
                                   tol=float(p.get("gradient_epsilon", 1e-6)))

        def _make_step(use_cd: bool):
            @jax.jit
            def irls_step(beta_s, lam1, lam2):
                eta_i = Xs @ beta_s
                if offset is not None:
                    eta_i = eta_i + offset
                mu = fam.clamp_mu(fam.linkinv(eta_i))
                dmu = fam.mu_eta(eta_i)
                var = fam.variance(mu)
                w_irls = w * dmu * dmu / var
                z = (eta_i - (0.0 if offset is None else offset)
                     + (y - mu) * dmu / jnp.maximum(dmu * dmu, 1e-12))
                G, b = _gram_kernel(Xs, w_irls, z)
                if use_cd:
                    nb = _cd_elastic_net(G, b, beta_s, lam1, lam2, pen_mask,
                                         n_sweeps=10, non_negative=non_neg,
                                         nn_mask=nn_mask)
                else:
                    nb = _cholesky_solve(G, b, lam2, pen_mask)
                    if nn_mask is not None:
                        nb = jnp.where(nn_mask > 0, jnp.maximum(nb, 0.0), nb)
                    elif non_neg:
                        nb = jnp.where(pen_mask > 0, jnp.maximum(nb, 0.0), nb)
                return nb
            return irls_step

        # beta_constraints (hex/glm GLM.BetaConstraint): per-coefficient
        # box bounds on the RAW scale, converted to the standardized
        # scale (b_std = b_raw * sd) and enforced by projected CD
        bc = p.get("beta_constraints")
        bc_lo = bc_hi = None
        if bc:
            bc_lo = np.full(ncoef, -np.inf, np.float32)
            bc_hi = np.full(ncoef, np.inf, np.float32)
            entries = _parse_beta_constraints(bc)
            lut = {n: i for i, n in enumerate(exp_names)}
            for nme, lob, hib in entries:
                if nme not in lut:
                    raise ValueError(
                        f"beta_constraints name '{nme}' is not an expanded "
                        f"design column {exp_names}")
                bc_lo[lut[nme]] = lob
                bc_hi[lut[nme]] = hib
            if standardize:
                xs_h = np.asarray(jax.device_get(xs))
                bc_lo[:Fe] = bc_lo[:Fe] * xs_h
                bc_hi[:Fe] = bc_hi[:Fe] * xs_h
            bc_lo = jnp.asarray(bc_lo)
            bc_hi = jnp.asarray(bc_hi)

        def _make_step_bc():
            @jax.jit
            def irls_step(beta_s, lam1, lam2):
                eta_i = Xs @ beta_s
                if offset is not None:
                    eta_i = eta_i + offset
                mu = fam.clamp_mu(fam.linkinv(eta_i))
                dmu = fam.mu_eta(eta_i)
                var = fam.variance(mu)
                w_irls = w * dmu * dmu / var
                z = (eta_i - (0.0 if offset is None else offset)
                     + (y - mu) * dmu / jnp.maximum(dmu * dmu, 1e-12))
                G, b = _gram_kernel(Xs, w_irls, z)
                return _cd_elastic_net(G, b, beta_s, lam1, lam2, pen_mask,
                                       n_sweeps=10, non_negative=non_neg,
                                       nn_mask=nn_mask, lo=bc_lo, hi=bc_hi)
            return irls_step

        step_chol = _make_step(False)
        step_cd = _make_step(True) if alpha > 0 else None

        @jax.jit
        def _merit_kernel(bvec, l1, l2):
            """Penalized objective for the non-canonical-link line
            search: deviance/2 + λ₁‖β‖₁ + λ₂/2·‖β‖₂² on penalized
            coordinates (defined once — jit caches across the λ path)."""
            ef = Xs @ bvec + (0.0 if offset is None else offset)
            devm = fam.deviance(w, y, fam.clamp_mu(fam.linkinv(ef)))
            bp = bvec * pen_mask
            return (0.5 * devm + l1 * jnp.abs(bp).sum()
                    + 0.5 * l2 * (bp * bp).sum())
        if bc is not None and bc:
            step_bc = _make_step_bc()

        # validation design for lambda selection (the reference picks the
        # path's best submodel by validation deviance when a validation
        # frame is given; without one, training deviance degenerates to
        # the smallest lambda — same as the reference without CV)
        vXs = vy = vw = voff = None
        if valid_spec is not None:
            vXe, _, _ = expand_design(valid_spec, impute_means=means,
                                      interactions=interactions,
                                      interaction_pairs=ipairs)
            if standardize:
                vXs = (vXe - xm[None, :]) * (1.0 / xs)[None, :]
            else:
                vXs = vXe
            if fit_intercept:
                vXs = jnp.concatenate(
                    [vXs, jnp.ones((vXe.shape[0], 1), jnp.float32)], axis=1)
            vy = valid_spec.y.astype(jnp.float32)
            vw = valid_spec.w
            voff = valid_spec.offset

        beta_s = jnp.zeros(ncoef, jnp.float32)
        if fit_intercept:
            # start at the null model β=(0,…,0,g(μ₀)) — for links like
            # inverse, η=0 is outside the usable region and IRLS from a
            # zero vector cannot recover (GLM.java starts from the null
            # model the same way)
            beta_s = beta_s.at[Fe].set(fam.link(mu0))
        sv = p.get("startval")
        if sv is not None and len(sv):
            # user-specified starting coefficients on the RAW scale,
            # expanded-column order with the intercept LAST
            # (GLM.java _startval); convert to the standardized scale
            # (b_std = b_raw·sd, icpt_std = icpt + Σ b_raw·m)
            sv = np.asarray(sv, np.float32)
            want = Fe + (1 if fit_intercept else 0)
            if sv.shape[0] != want:
                raise ValueError(
                    f"startval needs {want} values (expanded "
                    f"coefficients{' + intercept' if fit_intercept else ''}"
                    f"), got {sv.shape[0]}")
            b0 = jnp.asarray(sv[:Fe])
            if standardize:
                bs0 = b0 * xs
                beta_s = beta_s.at[:Fe].set(bs0)
                if fit_intercept:
                    beta_s = beta_s.at[Fe].set(
                        jnp.float32(sv[Fe]) + (b0 * xm).sum())
            else:
                beta_s = beta_s.at[:Fe].set(b0)
                if fit_intercept:
                    beta_s = beta_s.at[Fe].set(jnp.float32(sv[Fe]))
        beta_init0 = beta_s
        cold_start = bool(p.get("cold_start", False))
        best = None
        submodels = []
        for li, lam in enumerate(lambdas):
            if cold_start and li > 0:
                # GLMParameters._cold_start: no warm-starting down the
                # lambda path — every λ refits from the initial state
                beta_s = beta_init0
            if use_lbfgs:
                beta_s, _fv, _its = lbfgs_fit(
                    beta_s, jnp.float32(lam * (1 - alpha)))
            else:
                use_cd = alpha > 0 and lam > 0
                irls_step = step_cd if use_cd else step_chol
                if bc:
                    # box bounds require the projected-CD solver
                    use_cd = True
                    irls_step = step_bc
                lam1 = jnp.float32(lam * alpha * nobs)
                lam2 = jnp.float32(lam * (1 - alpha) * nobs)
                # links outside the monotone-safe set (and tweedie's
                # power pair) are not guaranteed monotone under plain
                # IRLS — guard each step with halving on the PENALIZED
                # objective (deviance/2 + λ₁‖β‖₁ + λ₂/2‖β‖₂² on
                # penalized coords), the same merit hex/glm/GLM.java's
                # IRLSM line search uses; raw deviance alone would
                # reject legitimate shrinkage steps when warm-starting
                # up an ascending lambda list. gamma+inverse (now the
                # DEFAULT gamma link) is guarded: an unguarded step can
                # push eta <= 0 where mu leaves the response domain
                guard = (fam.link_name != _PLAIN_IRLS_LINK.get(family)
                         or family == "tweedie")

                def _merit_of(bvec):
                    return float(jax.device_get(
                        _merit_kernel(bvec, lam1, lam2)))

                prev_mer = _merit_of(beta_s) if guard else None
                for it in range(max_iter):
                    nb = irls_step(beta_s, lam1, lam2)
                    if guard:
                        mer_t = _merit_of(nb)
                        halvings = 0
                        while ((not np.isfinite(mer_t)
                                or mer_t > prev_mer * (1 + 1e-8))
                               and halvings < 8):
                            nb = 0.5 * (nb + beta_s)
                            mer_t = _merit_of(nb)
                            halvings += 1
                        if (not np.isfinite(mer_t)
                                or mer_t > prev_mer * (1 + 1e-8)):
                            break  # no descent direction left
                        prev_mer = mer_t
                    delta = float(jax.device_get(
                        jnp.max(jnp.abs(nb - beta_s))))
                    beta_s = nb
                    if delta < beta_eps:
                        break
                    if job.cancel_requested:
                        # poll INSIDE the IRLS loop, not just between
                        # lambdas: a single lambda's fit can outlive the
                        # watchdog's max_runtime_secs deadline on its own
                        break
                    if (family == "gaussian" and not use_cd
                            and fam.link_name == "identity"):
                        break  # weighted least squares: one solve is exact
                        # (non-identity links keep iterating — the working
                        # response changes with η)
            eta_f = Xs @ beta_s + (0.0 if offset is None else offset)
            dev = float(jax.device_get(fam.deviance(w, y, fam.linkinv(eta_f))))
            sel_dev = dev
            if vXs is not None:
                veta = vXs @ beta_s + (0.0 if voff is None else voff)
                sel_dev = float(jax.device_get(
                    fam.deviance(vw, vy, fam.linkinv(veta))))
            submodels.append({"lambda": float(lam), "deviance": dev,
                              "nonzero": int(jax.device_get(
                                  (jnp.abs(beta_s[:Fe]) > 1e-10).sum()))})
            if vXs is not None:
                submodels[-1]["validation_deviance"] = sel_dev
            if best is None or sel_dev <= best[1]:
                best = (beta_s, sel_dev, float(lam), dev)
            job.set_progress((li + 1) / len(lambdas))
            if job.cancel_requested:
                break
            map_ = int(p.get("max_active_predictors", -1) or -1)
            if (map_ > 0 and p.get("lambda_search")
                    and submodels[-1]["nonzero"] > map_):
                # hex/glm/GLM.java _max_active_predictors: stop
                # descending the lambda path once the active set
                # exceeds the cap (the just-fitted submodel still
                # participates in best-selection, as in the reference).
                # Gated to lambda_search: a user-supplied lambda list
                # keeps its order (may ascend) and is never truncated.
                break

        beta_s, _, lam_best, res_dev = best
        # destandardize: β_raw = β_std / sd;  b0_raw = b0 − Σ β_std·m/sd
        if standardize:
            beta_raw = beta_s[:Fe] / xs
            icpt = float(jax.device_get(
                beta_s[Fe] - (beta_s[:Fe] * xm / xs).sum()))
        else:
            beta_raw = beta_s[:Fe]
            icpt = (float(jax.device_get(beta_s[Fe])) if fit_intercept
                    else 0.0)
        prior = float(p.get("prior", -1.0) or -1.0)
        if family == "binomial" and 0.0 < prior < 1.0 and fit_intercept:
            # rare-event sampling correction (GLM.java _iceptAdjust):
            # shift the intercept so the average predicted probability
            # matches the true prior instead of the sampled ȳ
            ybar = float(jax.device_get(
                (w * y).sum() / jnp.maximum(w.sum(), 1e-12)))
            ybar = min(max(ybar, 1e-12), 1 - 1e-12)
            icpt += float(np.log(prior * (1 - ybar))
                          - np.log(ybar * (1 - prior)))
        rank = (int(jax.device_get((jnp.abs(beta_s[:Fe]) > 1e-10).sum()))
                + (1 if fit_intercept else 0))

        model = GLMModel(f"glm_{id(self) & 0xffffff:x}", self.params, spec,
                         family, np.asarray(jax.device_get(beta_raw)), icpt,
                         exp_names, {k: float(jax.device_get(v))
                                     for k, v in means.items()},
                         lam_best, null_dev, res_dev, nobs, rank)
        model.output["lambda_path"] = submodels
        model.output["coefficients"] = model.coef()
        if compute_pv:
            # standard errors / z / p from the unpenalized observed
            # information on the RAW design at the fitted coefficients
            # (hex/glm/GLMModel computePValues: cov = inv(X'WX)·φ̂)
            Xr = jnp.concatenate([Xe, jnp.ones((Xe.shape[0], 1),
                                               jnp.float32)], axis=1)
            beta_full = jnp.concatenate(
                [jnp.asarray(beta_raw), jnp.asarray([icpt], jnp.float32)])
            eta_r = Xr @ beta_full
            if offset is not None:
                eta_r = eta_r + offset
            mu_r = fam.linkinv(eta_r)
            dmu_r = fam.mu_eta(eta_r)
            var_r = fam.variance(mu_r)
            wi = w * dmu_r * dmu_r / jnp.maximum(var_r, 1e-12)
            Gr = (Xr * wi[:, None]).T @ Xr
            df = max(nobs - rank, 1.0)
            if family == "gaussian":
                dispersion = res_dev / df
            elif family in ("gamma", "tweedie"):
                # Pearson dispersion estimate
                pearson = float(jax.device_get(
                    (w * (y - mu_r) ** 2 / jnp.maximum(var_r, 1e-12)).sum()))
                dispersion = pearson / df
            else:
                dispersion = 1.0
            cov = np.asarray(jax.device_get(
                jnp.linalg.pinv(Gr + 1e-8 * jnp.eye(Gr.shape[0])))) * dispersion
            se = np.sqrt(np.maximum(np.diag(cov), 0.0))
            coefs_full = np.concatenate(
                [np.asarray(jax.device_get(beta_raw)), [icpt]])
            zval = np.where(se > 0, coefs_full / np.maximum(se, 1e-300), 0.0)
            from scipy import stats as _st
            if family == "gaussian":
                pval = 2.0 * _st.t.sf(np.abs(zval), df=max(df, 1.0))
            else:
                pval = 2.0 * _st.norm.sf(np.abs(zval))
            names_pv = list(exp_names) + ["Intercept"]
            model.output["std_errs"] = dict(zip(names_pv, se.tolist()))
            model.output["z_values"] = dict(zip(names_pv, zval.tolist()))
            model.output["p_values"] = dict(zip(names_pv, pval.tolist()))
            model.output["dispersion"] = float(dispersion)
        # training metrics
        out = model._predict_matrix(spec.X, offset=offset)
        model.training_metrics = compute_metrics(
            out, spec.y, w, spec.nclasses, spec.response_domain,
            deviance=res_dev / max(nobs, 1.0))
        if valid_spec is not None:
            vout = model._predict_matrix(valid_spec.X,
                                         offset=valid_spec.offset)
            model.validation_metrics = compute_metrics(
                vout, valid_spec.y, valid_spec.w, spec.nclasses,
                spec.response_domain)
        return model

    def _train_ordinal(self, spec: TrainingSpec, valid_spec, job: Job):
        """Ordinal (proportional-odds) logistic regression — the
        reference's Family.ordinal with solver GRADIENT_DESCENT_LH
        (hex/glm/GLMModel.java:814, GLM.java ordinal path): cumulative
        logits P(y<=k) = sigmoid(th_k - eta), monotone thresholds via a
        log-gap parameterization, full-batch Adam on the NLL (the GD_LH
        analog — one jitted lax.fori_loop, no per-row Java loop)."""
        p = self.params
        K = spec.nclasses
        y = spec.y.astype(jnp.int32)
        w = spec.w
        interactions = p.get("interactions") or None
        ipairs = p.get("interaction_pairs") or None
        Xe, exp_names, means = expand_design(
            spec, interactions=interactions, interaction_pairs=ipairs)
        Fe = Xe.shape[1]
        wsum = w.sum()
        xm = (Xe * w[:, None]).sum(0) / jnp.maximum(wsum, 1e-12)
        xv = (w[:, None] * (Xe - xm[None, :]) ** 2).sum(0) / \
            jnp.maximum(wsum, 1e-12)
        xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
        Xs = (Xe - xm[None, :]) / xs[None, :]
        lam2 = 0.0
        lam_in = p.get("Lambda")
        if lam_in:
            lam2 = float(lam_in[0] if isinstance(lam_in, (list, tuple))
                         else lam_in)

        # params: beta [Fe], th0 scalar, log-gaps [K-2]
        def unpack(params_v):
            beta = params_v[:Fe]
            th0 = params_v[Fe]
            gaps = jnp.exp(jnp.clip(params_v[Fe + 1:], -20.0, 10.0))
            th = th0 + jnp.concatenate(
                [jnp.zeros(1), jnp.cumsum(gaps)])           # [K-1]
            return beta, th

        ocdf, oicdf = _ordinal_cdf_fns(p.get("link"))

        # class-prior-based threshold init (inverse cumulative link of
        # the marginal distribution — the reference initializes the
        # same way for its ologit path)
        cnt = jnp.zeros(K).at[y].add(w)
        cum = jnp.cumsum(cnt)[:-1] / jnp.maximum(wsum, 1e-12)
        cum = jnp.clip(cum, 1e-4, 1 - 1e-4)
        th_init = oicdf(cum)
        gaps0 = jnp.log(jnp.maximum(jnp.diff(th_init), 1e-3))
        params0 = jnp.concatenate(
            [jnp.zeros(Fe), th_init[:1], gaps0]).astype(jnp.float32)

        def nll(params_v):
            beta, th = unpack(params_v)
            eta = Xs @ beta
            cdf = ocdf(th[None, :] - eta[:, None])             # [rows, K-1]
            probs = jnp.concatenate(
                [cdf[:, :1], cdf[:, 1:] - cdf[:, :-1],
                 1.0 - cdf[:, -1:]], axis=1)
            py = jnp.take_along_axis(probs, y[:, None], axis=1)[:, 0]
            reg = 0.5 * lam2 * (beta ** 2).sum()
            return -(w * jnp.log(jnp.clip(py, 1e-12, 1.0))).sum() \
                / jnp.maximum(wsum, 1e-12) + reg

        vg = jax.value_and_grad(nll)
        iters = _max_iter_of(p, 50) * 20
        lr0 = 0.05                  # Adam step for the GD_LH analog

        @jax.jit
        def fit(params_v):
            def body(i, st):
                pv, m, v = st
                _, g = vg(pv)
                m = 0.9 * m + 0.1 * g
                v = 0.999 * v + 0.001 * g * g
                mh = m / (1 - 0.9 ** (i + 1.0))
                vh = v / (1 - 0.999 ** (i + 1.0))
                pv = pv - lr0 * mh / (jnp.sqrt(vh) + 1e-8)
                return pv, m, v
            out, _, _ = jax.lax.fori_loop(
                0, iters, body,
                (params_v, jnp.zeros_like(params_v),
                 jnp.zeros_like(params_v)))
            return out

        pv = fit(params0)
        job.set_progress(0.9)
        beta_s, th = unpack(pv)
        # destandardize: thresholds absorb the mean shift
        beta_raw = beta_s / xs
        shift = (beta_s * xm / xs).sum()
        th_raw = np.asarray(jax.device_get(th + shift))
        model = GLMModel(f"glm_{id(self) & 0xffffff:x}", p, spec,
                         "ordinal", np.asarray(jax.device_get(beta_raw)),
                         th_raw, exp_names, {k: float(v) for k, v in
                                             means.items()},
                         lam2, 0.0, float(jax.device_get(
                             nll(pv) * wsum)), float(jax.device_get(wsum)),
                         Fe + K - 1)
        probs = model._predict_matrix(spec.X)
        model.training_metrics = compute_metrics(
            np.asarray(jax.device_get(probs)), y, w, K,
            spec.response_domain)
        if valid_spec is not None:
            vprobs = model._predict_matrix(valid_spec.X,
                                           offset=valid_spec.offset)
            model.validation_metrics = compute_metrics(
                np.asarray(jax.device_get(vprobs)),
                valid_spec.y.astype(jnp.int32), valid_spec.w, K,
                spec.response_domain)
        return model

    def _train_multinomial(self, spec: TrainingSpec, valid_spec,
                           job: Job) -> GLMModel:
        """Multinomial softmax GLM — class-cyclic IRLS.

        hex/glm multinomial solves the softmax likelihood with IRLSM on
        a per-class block-diagonal Hessian (GLMTask multinomial path):
        each pass updates class k's coefficients from the weighted Gram
        Xᵀdiag(w·p_k(1−p_k))X — one MXU matmul + Cholesky per class.
        Elastic net applies per class via the same CD kernel."""
        p = self.params
        K = spec.nclasses
        if spec.offset is not None:
            raise NotImplementedError(
                "offset_column is not supported for multinomial GLM "
                "(the class-cyclic IRLS path has no offset term yet)")
        if p.get("beta_constraints"):
            raise NotImplementedError(
                "beta_constraints are not supported for multinomial GLM")
        if p.get("lambda_search"):
            raise NotImplementedError(
                "lambda_search is not supported for multinomial GLM — "
                "pass an explicit Lambda")
        fit_intercept = bool(p.get("intercept", True))
        y = spec.y.astype(jnp.int32)
        w = spec.w
        Xe, exp_names, means = expand_design(
            spec, interactions=p.get("interactions") or None,
            interaction_pairs=p.get("interaction_pairs") or None)
        Fe = Xe.shape[1]
        nobs = float(jax.device_get(w.sum()))
        standardize = bool(p.get("standardize", True)) and fit_intercept
        wsum = w.sum()
        xm = (Xe * w[:, None]).sum(0) / wsum
        xv = (w[:, None] * (Xe - xm[None, :]) ** 2).sum(0) / wsum
        xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
        if standardize:
            Xs = (Xe - xm[None, :]) * (1.0 / xs)[None, :] * (w > 0)[:, None]
        else:
            Xs = Xe * (w > 0)[:, None]
        if fit_intercept:
            Xs = jnp.concatenate([Xs, (w > 0).astype(jnp.float32)[:, None]],
                                 axis=1)
            pen_mask = jnp.concatenate([jnp.ones(Fe), jnp.zeros(1)])
        else:
            pen_mask = jnp.ones(Fe)
        ncoef = Xs.shape[1]
        Y1 = jax.nn.one_hot(y, K) * (w > 0)[:, None]
        alpha = p.get("alpha")
        alpha = 0.5 if alpha is None else (
            alpha[0] if isinstance(alpha, (list, tuple)) else float(alpha))
        lam_param = p.get("Lambda")
        if isinstance(lam_param, (list, tuple)):
            lam = float(lam_param[0]) if lam_param else 0.0
        else:
            lam = float(lam_param) if lam_param is not None else 0.0
        lam1 = jnp.float32(lam * alpha * nobs)
        lam2 = jnp.float32(lam * (1 - alpha) * nobs)
        max_iter = _max_iter_of(p, 50)
        beta_eps = float(p.get("beta_epsilon", 1e-5))
        use_cd = lam > 0 and alpha > 0

        @jax.jit
        def class_pass(B):
            """One cyclic sweep over classes; returns updated B."""
            def one_class(k, B):
                eta = Xs @ B
                P = jax.nn.softmax(eta, axis=1)
                pk = P[:, k]
                yk = Y1[:, k]
                w_irls = w * pk * (1.0 - pk)
                z = eta[:, k] + (yk - pk) / jnp.maximum(
                    pk * (1.0 - pk), 1e-5)
                G, b = _gram_kernel(Xs, w_irls, z)
                if use_cd:
                    nb = _cd_elastic_net(G, b, B[:, k], lam1, lam2,
                                         pen_mask, n_sweeps=10)
                else:
                    nb = _cholesky_solve(G, b, lam2, pen_mask)
                return B.at[:, k].set(nb)

            return jax.lax.fori_loop(0, K, one_class, B)

        B = jnp.zeros((ncoef, K), jnp.float32)
        for it in range(max_iter):
            nB = class_pass(B)
            delta = float(jax.device_get(jnp.max(jnp.abs(nB - B))))
            B = nB
            job.set_progress((it + 1) / max_iter)
            if delta < beta_eps:
                break
            if job.cancel_requested:
                # cooperative watchdog/REST cancellation between class
                # sweeps (each sweep is K full Gram builds — the longest
                # uncancellable stretch without this poll)
                break
        # deviance bookkeeping
        eta = Xs @ B
        P = jax.nn.softmax(eta, axis=1)
        py = jnp.clip((P * Y1).sum(1), 1e-12, 1.0)
        res_dev = float(jax.device_get(
            -2.0 * (w * jnp.where(w > 0, jnp.log(py), 0.0)).sum()))
        prior = (Y1 * w[:, None]).sum(0) / jnp.maximum(wsum, 1e-30)
        null_dev = float(jax.device_get(
            -2.0 * (w * jnp.where(
                w > 0, jnp.log(jnp.clip(prior[y], 1e-12, 1.0)),
                0.0)).sum()))
        # destandardize per class
        if standardize:
            beta_raw = B[:Fe, :] / xs[:, None]
            icpt = B[Fe, :] - (B[:Fe, :] * (xm / xs)[:, None]).sum(0)
        else:
            beta_raw = B[:Fe, :]
            icpt = B[Fe, :] if fit_intercept else jnp.zeros(K)
        rank = int(jax.device_get(
            (jnp.abs(B[:Fe, :]) > 1e-10).sum())) + (K if fit_intercept
                                                    else 0)
        model = GLMModel(f"glm_{id(self) & 0xffffff:x}", self.params, spec,
                         "multinomial",
                         np.asarray(jax.device_get(beta_raw)),
                         np.asarray(jax.device_get(icpt)), exp_names,
                         {k_: float(jax.device_get(v))
                          for k_, v in means.items()},
                         lam, null_dev, res_dev, nobs, rank)
        model.output["coefficients"] = model.coef()
        out = model._predict_matrix(spec.X)
        model.training_metrics = compute_metrics(
            out, spec.y, w, K, spec.response_domain)
        if valid_spec is not None:
            vout = model._predict_matrix(valid_spec.X)
            model.validation_metrics = compute_metrics(
                vout, valid_spec.y, valid_spec.w, K, spec.response_domain)
        return model


register_model_class("glm", GLMModel)
register_model_class("hglm", HGLMModel)
