"""Naive Bayes — class-conditional counting in one device pass.

Reference: hex/naivebayes/NaiveBayes.java:26 — a single counting MRTask
accumulates per-class counts for enum levels and per-class mean/variance
for numerics; laplace smoothing; scoring multiplies log-likelihoods.

TPU re-design: the counting pass is one one-hot matmul per column group
(class-onehot × feature statistics contract on the MXU; GSPMD psums
across shards) — the single-MRTask structure maps to a single fused jit."""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu import telemetry
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        compute_metrics)
from h2o3_tpu.persist import register_model_class

NB_DEFAULTS: Dict = dict(laplace=0.0, min_sdev=0.001, eps_sdev=0.0)


class NaiveBayesModel(Model):
    algo = "naivebayes"

    def __init__(self, key, params, spec, priors, num_mean, num_sd,
                 cat_probs):
        super().__init__(key, params, spec)
        self.priors = np.asarray(priors)            # [K]
        self.num_mean = num_mean                    # [K, Fnum]
        self.num_sd = num_sd                        # [K, Fnum]
        self.cat_probs = cat_probs                  # {col: [K, card]}

    def _predict_matrix(self, X, offset=None):
        K = len(self.priors)
        logp = jnp.log(jnp.asarray(self.priors))[None, :]
        logp = jnp.broadcast_to(logp, (X.shape[0], K))
        num_i = 0
        for i, (n, is_cat) in enumerate(zip(self.feature_names,
                                            self.feature_is_cat)):
            x = X[:, i]
            ok = ~jnp.isnan(x)
            if is_cat:
                P = jnp.asarray(self.cat_probs[n])          # [K, card]
                card = P.shape[1]
                c = jnp.clip(jnp.where(ok, x, 0).astype(jnp.int32), 0,
                             card - 1)
                ll = jnp.log(jnp.maximum(P[:, c].T, 1e-30))  # [rows, K]
            else:
                mu = jnp.asarray(self.num_mean)[:, num_i][None, :]
                sd = jnp.asarray(self.num_sd)[:, num_i][None, :]
                ll = (-0.5 * jnp.log(2 * jnp.pi * sd * sd)
                      - 0.5 * ((x[:, None] - mu) / sd) ** 2)
                num_i += 1
            logp = logp + jnp.where(ok[:, None], ll, 0.0)
        return jax.nn.softmax(logp, axis=1)

    def _save_arrays(self):
        d = {"priors": self.priors,
             "num_mean": np.asarray(self.num_mean),
             "num_sd": np.asarray(self.num_sd)}
        for n, P in self.cat_probs.items():
            d[f"cat_{n}"] = np.asarray(P)
        return d

    def _save_extra_meta(self):
        return {"cat_cols": list(self.cat_probs)}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.priors = arrays["priors"]
        m.num_mean = arrays["num_mean"]
        m.num_sd = arrays["num_sd"]
        m.cat_probs = {n: arrays[f"cat_{n}"]
                       for n in meta["extra"]["cat_cols"]}
        return m


class H2ONaiveBayesEstimator(ModelBuilder):
    algo = "naivebayes"

    def __init__(self, **params):
        merged = dict(NB_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        if spec.nclasses < 2:
            raise ValueError("NaiveBayes requires a categorical response")
        p = self.params
        laplace = float(p.get("laplace", 0.0))
        min_sdev = float(p.get("min_sdev", 0.001))
        eps_sdev = float(p.get("eps_sdev", 0.0))
        K = spec.nclasses
        y = spec.y
        w = spec.w
        X = spec.X
        yoh = ((y[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
               * w[:, None])                                     # [rows, K]
        cls_w = yoh.sum(0)                                       # [K]
        priors = np.asarray(telemetry.device_get(cls_w / cls_w.sum()))
        num_idx = [i for i, c in enumerate(spec.is_cat) if not c]
        num_mean = np.zeros((K, len(num_idx)), np.float32)
        num_sd = np.ones((K, len(num_idx)), np.float32)
        if num_idx:
            Xn = X[:, jnp.asarray(num_idx)]
            okn = ~jnp.isnan(Xn)
            Xz = jnp.where(okn, Xn, 0.0)
            # per-class weighted moments via one MXU contraction each
            cw = jax.lax.dot_general(yoh, okn.astype(jnp.float32) ,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            s1 = jax.lax.dot_general(yoh, Xz, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            s2 = jax.lax.dot_general(yoh, Xz * Xz, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            cw = jnp.maximum(cw, 1e-9)
            mu = s1 / cw
            sd = jnp.sqrt(jnp.maximum(s2 / cw - mu * mu, 0.0))
            # eps_sdev: sdevs at/below the threshold are REPLACED by
            # min_sdev; min_sdev floors the rest (reference NB params)
            sd = jnp.where(sd <= eps_sdev, min_sdev,
                           jnp.maximum(sd, min_sdev))
            num_mean = np.asarray(telemetry.device_get(mu))
            num_sd = np.asarray(telemetry.device_get(sd))
        cat_probs: Dict[str, np.ndarray] = {}
        for i, (n, is_cat) in enumerate(zip(spec.names, spec.is_cat)):
            if not is_cat:
                continue
            card = len(spec.cat_domains.get(n, ())) or 1
            x = X[:, i]
            ok = ~jnp.isnan(x)
            c = jnp.clip(jnp.where(ok, x, 0).astype(jnp.int32), 0, card - 1)
            coh = ((c[:, None] == jnp.arange(card)[None, :])
                   .astype(jnp.float32) * ok[:, None].astype(jnp.float32))
            cnt = jax.lax.dot_general(yoh, coh, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            cnt = cnt + laplace
            P = cnt / jnp.maximum(cnt.sum(1, keepdims=True), 1e-30)
            cat_probs[n] = np.asarray(telemetry.device_get(P))
        model = NaiveBayesModel(f"nb_{id(self) & 0xffffff:x}", self.params,
                                spec, priors, num_mean, num_sd, cat_probs)
        out = model._predict_matrix(X)
        model.training_metrics = compute_metrics(out, y, w, K,
                                                 spec.response_domain)
        if valid_spec is not None:
            vout = model._predict_matrix(valid_spec.X)
            model.validation_metrics = compute_metrics(
                vout, valid_spec.y, valid_spec.w, K, spec.response_domain)
        return model


register_model_class("naivebayes", NaiveBayesModel)
