"""SQL table import — the JDBC ingest analog.

Reference: water/jdbc/SQLManager.java — ImportSQLTable splits the table
into key ranges and parallel MRTask chunks SELECT their range through
JDBC; columns land as Vecs.

TPU re-design: any Python DB-API connection factory plays the JDBC
driver's role (sqlite3 in tests; psycopg2/mysql connectors the same
way). Ranges split on an integer key column (or LIMIT/OFFSET without
one), fetched in a thread pool — network-bound, so threads suffice —
and concatenate into typed numpy columns → device-sharded Frame."""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec


def import_sql_table(connection_factory: Callable, table: str,
                     columns: Optional[Sequence[str]] = None,
                     key_column: Optional[str] = None,
                     fetch_chunks: int = 4, mesh=None) -> Frame:
    """Import `table` via DB-API connections from `connection_factory`
    (one fresh connection per worker, like one JDBC connection per
    chunk task)."""
    cols_sql = ", ".join(columns) if columns else "*"
    con = connection_factory()
    try:
        cur = con.cursor()
        cur.execute(f"SELECT {cols_sql} FROM {table} LIMIT 1")
        names = [d[0] for d in cur.description]
        cur.execute(f"SELECT COUNT(*) FROM {table}")
        nrow = int(cur.fetchone()[0])
        ranges: List[tuple] = []
        if key_column:
            cur.execute(f"SELECT MIN({key_column}), MAX({key_column}) "
                        f"FROM {table}")
            lo, hi = cur.fetchone()
            if lo is None or hi is None:
                # empty table or all-NULL keys: single full fetch
                key_column = None
        if key_column:
            lo, hi = int(lo), int(hi)
            span = max((hi - lo + 1) // max(fetch_chunks, 1), 1)
            s = lo
            while s <= hi:
                ranges.append(("key", s, min(s + span - 1, hi)))
                s += span
            # BETWEEN never matches NULL keys — fetch them explicitly
            ranges.append(("nullkey", 0, 0))
        else:
            # parallel LIMIT/OFFSET without a key column is unsound
            # (row order per query is undefined without ORDER BY), so
            # fall back to ONE full fetch — SQLManager requires a key
            # range for its chunking too
            ranges.append(("all", 0, 0))
    finally:
        con.close()

    # integer bounds are interpolated (they originate here, not from
    # user input) to stay DB-API paramstyle-agnostic: sqlite wants '?',
    # psycopg2/mysql want '%s'
    def fetch(rg) -> List[tuple]:
        c = connection_factory()
        try:
            cu = c.cursor()
            if rg[0] == "key":
                cu.execute(
                    f"SELECT {cols_sql} FROM {table} WHERE {key_column} "
                    f"BETWEEN {int(rg[1])} AND {int(rg[2])}")
            elif rg[0] == "nullkey":
                cu.execute(f"SELECT {cols_sql} FROM {table} "
                           f"WHERE {key_column} IS NULL")
            else:
                cu.execute(f"SELECT {cols_sql} FROM {table}")
            return cu.fetchall()
        finally:
            c.close()

    if len(ranges) > 1:
        import concurrent.futures as cf

        from h2o3_tpu.ingest.parse import ingest_workers
        with cf.ThreadPoolExecutor(
                max_workers=min(len(ranges), ingest_workers())) as ex:
            parts = list(ex.map(fetch, ranges))
    else:
        parts = [fetch(r) for r in ranges]
    rows = [r for p in parts for r in p]
    if len(rows) != nrow:
        from h2o3_tpu.log import warn
        warn("import_sql_table: fetched %d rows but COUNT(*)=%d "
             "(concurrent writes?)", len(rows), nrow)
    ncol = len(names)
    data: Dict[str, np.ndarray] = {}
    for j, n in enumerate(names):
        vals = [r[j] for r in rows]
        if all(v is None or isinstance(v, (int, float)) for v in vals):
            data[n] = np.asarray(
                [np.nan if v is None else float(v) for v in vals])
        else:
            data[n] = np.asarray(
                [None if v is None else str(v) for v in vals],
                dtype=object)
    return Frame.from_numpy(data, mesh=mesh)
