"""Two-phase distributed parse: guess, then parse.

Reference: water/parser/ParseSetup.java guesses separator/header/types from
sampled chunks; water/parser/ParseDataset.java:127 forkParseDataset runs a
MultiFileParseTask MRTask over raw-byte chunks, each node streaming its
chunks through CsvParser into per-column NewChunks, then unions categorical
domains across nodes and assembles the Frame.

TPU re-design: parsing is host work (TPUs don't parse bytes). Phase 2 is a
streaming, chunk-local pipeline: each byte-range worker tokenizes its
range (native C++ scan, fast_csv.cpp) and finishes every column as a
typed numpy array — numeric float64, time int64 millis, enum codes
against a chunk-local dictionary (csv_enum_encode) — so no global Python
token list ever materializes (ingest/chunk.py). The merge unions the
chunk-local enum domains (the reference's PackedDomains contract) and
remaps codes with a vectorized LUT; device placement batches one 2D
host→device transfer per dtype group, overlapping the remaining host
encode work (frame/frame.py Frame.from_typed_columns).
"""
from __future__ import annotations

import csv
import io
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import T_ENUM, T_INT, T_REAL, T_STR, T_TIME, Vec
from h2o3_tpu.ingest.chunk import (MAX_ENUM_CARDINALITY, SKIPPED,
                                   EncodedColumn, _skipped_set,
                                   encode_chunk_native, encode_token_column,
                                   merge_column)

DEFAULT_NA_STRINGS = {"", "NA", "N/A", "na", "NaN", "nan", "null", "NULL", "None", "?"}
_SEP_CANDIDATES = [",", "\t", ";", "|", " "]

# stage timings of the most recent parse() call (tools/profile_ingest.py
# and bench.py read this to attribute ingest regressions)
LAST_PROFILE: Dict[str, object] = {}


@dataclass
class ParseSetup:
    separator: str = ","
    header: bool = True
    column_names: List[str] = field(default_factory=list)
    column_types: List[str] = field(default_factory=list)
    na_strings: set = field(default_factory=lambda: set(DEFAULT_NA_STRINGS))
    skipped_columns: List[int] = field(default_factory=list)
    quotechar: str = '"'


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return False


_INT_RE = re.compile(r"[+-]?\d+\Z")


def _is_int(tok: str) -> bool:
    # lexical, not float-round-trip: float(t) is exact only to 2^53, so
    # a wide integer token must not be classified (or valued) through it
    return _INT_RE.match(tok.strip()) is not None


def _looks_time(tok: str) -> bool:
    if len(tok) < 8 or tok[4:5] != "-":
        return False
    try:
        np.datetime64(tok)
        return True
    except ValueError:
        return False


def _read_head(path: str, nbytes: int = 1 << 16) -> str:
    with open(path, "rb") as f:
        raw = f.read(nbytes)
    from h2o3_tpu.ingest.compress import detect_bytes, head_bytes
    if detect_bytes(raw[:8]):
        # compressed input: sample the DECOMPRESSED stream's head (the
        # setup guess must see CSV text, not deflate bytes)
        raw = head_bytes(path, nbytes)
    txt = raw.decode("utf-8", errors="replace")
    # drop a possibly-truncated last line
    if len(raw) == nbytes and "\n" in txt:
        txt = txt[: txt.rfind("\n")]
    return txt


def guess_separator(sample: str) -> str:
    lines = [l for l in sample.splitlines() if l.strip()][:50]
    best, best_score = ",", -1
    for sep in _SEP_CANDIDATES:
        counts = [len(next(csv.reader([l], delimiter=sep, quotechar='"'))) for l in lines]
        if not counts:
            continue
        ncol = max(set(counts), key=counts.count)
        consistent = sum(c == ncol for c in counts)
        score = consistent * 1000 + ncol
        if ncol > 1 and score > best_score:
            best, best_score = sep, score
    return best


def _guess_col_type(tokens: List[str], na_strings) -> str:
    vals = [t for t in tokens if t.strip() not in na_strings]
    if not vals:
        return T_REAL
    if all(_is_number(v) for v in vals):
        return T_INT if all(_is_int(v) for v in vals) else T_REAL
    if all(_looks_time(v) for v in vals):
        return T_TIME
    return T_ENUM


def parse_setup(paths: Union[str, Sequence[str]], separator: Optional[str] = None,
                header: Optional[bool] = None, column_names: Optional[Sequence[str]] = None,
                column_types: Optional[Sequence[str]] = None,
                na_strings: Optional[Sequence[str]] = None) -> ParseSetup:
    """Phase 1 — sample and guess (reference: ParseSetup.guessSetup)."""
    if isinstance(paths, str):
        paths = [paths]
    sample = _read_head(paths[0])
    sep = separator or guess_separator(sample)
    nas = set(na_strings) if na_strings is not None else set(DEFAULT_NA_STRINGS)
    rows = list(csv.reader(io.StringIO(sample), delimiter=sep, quotechar='"'))
    rows = [r for r in rows if r]
    if not rows:
        raise ValueError(f"empty file: {paths[0]}")
    first = rows[0]
    if header is None:
        # header iff some column's first cell is a bare string while the
        # body of that column is numeric or time-typed
        def tok_class(tok):
            t = tok.strip()
            if t in nas:
                return None
            if _is_number(t):
                return "num"
            if _looks_time(t):
                return "time"
            return "str"

        data_rows = rows[1:50]
        header = False
        for i, c in enumerate(first):
            if tok_class(c) != "str":
                continue
            body = [tok_class(r[i]) for r in data_rows if i < len(r)]
            body = [b for b in body if b is not None]
            if body and all(b in ("num", "time") for b in body):
                header = True
                break
        if not data_rows:
            header = all(not _is_number(c) for c in first)
    ncol = len(first)
    names = (list(first) if header else [f"C{i + 1}" for i in range(ncol)])
    if column_names:
        names = list(column_names)
    body = rows[1:] if header else rows
    body = body[:1000]
    types = []
    for i in range(ncol):
        toks = [r[i] for r in body if i < len(r)]
        types.append(_guess_col_type(toks, nas))
    if column_types:
        for i, t in enumerate(column_types):
            if t:
                types[i] = {"numeric": T_REAL, "categorical": T_ENUM, "factor": T_ENUM,
                            "string": T_STR, "time": T_TIME, "int": T_INT,
                            "real": T_REAL, "enum": T_ENUM}.get(t, t)
    return ParseSetup(separator=sep, header=bool(header), column_names=names,
                      column_types=types, na_strings=nas)


def _parse_csv_text(text: str, setup: ParseSetup, skip_header: bool):
    """Tokenise one file's text into per-column python lists (the
    quote-correct fallback tokenizer; NA strings become None)."""
    reader = csv.reader(io.StringIO(text), delimiter=setup.separator,
                        quotechar=setup.quotechar)
    rows = [r for r in reader if r]
    if skip_header and rows:
        rows = rows[1:]
    ncol = len(setup.column_names)
    cols = [[None] * len(rows) for _ in range(ncol)]
    nas = setup.na_strings
    # skipped columns keep their all-None placeholder list (alignment for
    # the caller's zip) but never pay the per-cell strip/NA loop
    skipped = _skipped_set(setup)
    active = [ci for ci in range(ncol) if ci not in skipped]
    for ri, r in enumerate(rows):
        for ci in active:
            tok = r[ci].strip() if ci < len(r) else ""
            cols[ci][ri] = None if tok in nas else tok
    return cols


_PARALLEL_PARSE_BYTES = 16 << 20   # byte-range fan-out above 16 MB
_TARGET_RANGE_BYTES = 32 << 20     # preferred range size for huge files
_MIN_RANGE_BYTES = 2 << 20         # never split finer than this


def ingest_workers() -> int:
    """Parse/fetch fan-out width — every ingest worker pool sizes off
    this one knob (native thread pool, Python fallback process pool,
    SQL fetch threads). ``H2O3_INGEST_WORKERS`` overrides; the default
    is every core (the old hard cap of 16 left a third of a 24-core
    host idle)."""
    env = os.environ.get("H2O3_INGEST_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, os.cpu_count() or 4)


def _range_count(size: int, workers: int, shards: int) -> int:
    """Adaptive fan-out: at least one range per worker AND per data
    shard (so ChunkDeviceStreamer home-placement still aligns), growing
    with file size toward ``_TARGET_RANGE_BYTES`` ranges — but never so
    many that a range drops under ``_MIN_RANGE_BYTES`` (per-range
    dispatch overhead would eat the scan)."""
    n = max(workers, shards)
    n = max(n, min(4 * workers, size // _TARGET_RANGE_BYTES))
    # the floor tracks the fan-out threshold so a lowered
    # _PARALLEL_PARSE_BYTES (tests force chunking on tiny fixtures)
    # still yields multiple ranges
    floor = max(1, min(_MIN_RANGE_BYTES, _PARALLEL_PARSE_BYTES))
    return int(max(1, min(n, max(1, size // floor))))


_QUOTE_PROBE_BYTES = 8 << 20   # how far the range scan looks for quoting


def _byte_ranges(mm, n_chunks: int, setup,
                 force_quote_scan: bool = False) -> List[tuple]:
    """Split an mmapped file into row-aligned byte ranges by scanning
    the map directly — no per-boundary seek+readline storm. Boundaries
    are newlines OUTSIDE quoted fields: when the file's head
    (``_QUOTE_PROBE_BYTES``) contains the quote char (or the caller
    forces it), one native state-machine pass (``csv_chunk_bounds``)
    picks them, so a quoted field with embedded newlines cannot
    straddle two ranges; a quote-free head keeps the boundaries at
    ``mm.find`` newline probes (memchr speed — no full-file scan).
    A file whose FIRST quote sits past the probe window may split a
    quoted-newline field mid-quote — those boundaries are QUOTE-BLIND,
    and when a range then declines, ``parse`` detects the late quote
    and retries the whole file once with ``force_quote_scan`` (exact,
    full-pass boundaries) instead of letting per-range csv.reader
    fallbacks silently mis-split the field. The full state-machine
    pass stays a single-threaded prologue (quote state is not locally
    decidable), so only quoted files pay it, and only once."""
    size = len(mm)
    if n_chunks <= 1 or size == 0:
        return [(0, size)]
    targets = [size * i // n_chunks for i in range(1, n_chunks)]
    quote = getattr(setup, "quotechar", '"') or '"'
    bounds = None
    if force_quote_scan or mm.find(quote.encode()[0:1], 0,
                                   min(size, _QUOTE_PROBE_BYTES)) != -1:
        from h2o3_tpu import native
        qb = native.chunk_bounds(mm, setup.separator, quote, targets)
        if qb is not None:
            bounds = [int(b) for b in qb]
        else:
            # quotes present but no native state machine to place the
            # boundaries: ONE range (serial, quote-correct Python parse)
            # — blind newline cuts could split a quoted-newline field
            # and csv.reader would mis-parse both halves SILENTLY
            return [(0, size)]
    if bounds is None:
        bounds = []
        for t in targets:
            pos = mm.find(b"\n", t)
            bounds.append(size if pos < 0 else pos + 1)
    cuts = sorted({b for b in bounds if 0 < b < size})
    edges = [0] + cuts + [size]
    return [(edges[i], edges[i + 1]) for i in range(len(edges) - 1)
            if edges[i + 1] > edges[i]]


class _StageStats:
    """Thread-safe tokenize/encode CPU-second accumulator, summed across
    the worker pool (tools/profile_ingest.py per-stage attribution)."""
    __slots__ = ("_lock", "tokenize_s", "encode_s")

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self.tokenize_s = 0.0
        self.encode_s = 0.0

    def add(self, tokenize_s: float, encode_s: float) -> None:
        with self._lock:
            self.tokenize_s += tokenize_s
            self.encode_s += encode_s


def _native_available() -> bool:
    from h2o3_tpu.native import lib as _native_lib
    return _native_lib() is not None


def _na_strings_native_safe(setup: ParseSetup) -> bool:
    """The native tokenizer maps any non-numeric token in a numeric
    column to NaN — equivalent to the Python path ONLY when no na_string
    is itself numeric (a numeric NA sentinel like '-999' must go through
    the token path)."""
    import math
    for s in (setup.na_strings or ()):
        try:
            v = float(s)
        except ValueError:
            continue
        if not math.isnan(v):       # "nan"/"NaN" parse to NaN == NA anyway
            return False
    return True


def _encode_range_native(buf, start: int, end: int, setup: ParseSetup,
                         skip_header: bool, stats=None, pack_cols=None):
    """Byte-range worker on the native tokenizer: tokenizes a borrowed
    ``memoryview`` slice of the file's shared mmap — ZERO copy, no seek,
    the C scans read the page cache in place (ctypes releases the GIL,
    so a THREAD pool runs tokenize and the numpy/native encode
    concurrently with no process-spawn or pickle cost). Returns
    ``(typed columns, PrepackedChunk-or-None)``, or a decline-reason
    string — the caller re-parses only THIS range through the Python
    tokenizer. ``pack_cols`` asks the worker to also build the chunk's
    f32 streaming matrix HERE, so the pack rides the pool instead of
    serializing through the tokenize consumer."""
    out = encode_chunk_native(memoryview(buf)[start:end], setup,
                              skip_header, stats=stats)
    if isinstance(out, str):
        return out
    pack = None
    if pack_cols:
        from h2o3_tpu.ingest.stream import prepack_chunk
        t0 = time.perf_counter()
        pack = prepack_chunk(pack_cols, out)
        if stats is not None:
            stats.add(0.0, time.perf_counter() - t0)
    return out, pack


def _encode_range_python(src, start: int, end: int, setup: ParseSetup,
                         skip_header: bool):
    """Python-tokenizer worker (quote-correct csv.reader); the encode is
    still chunk-local and typed, so process workers pickle compact numpy
    arrays back, never token lists. ``src`` is a file path OR a bytes
    buffer — compressed inputs have no on-disk plaintext to reopen, so
    their fallback ranges slice the decompressed buffer instead."""
    if isinstance(src, (bytes, bytearray, memoryview)):
        text = bytes(src[start:end]).decode("utf-8", errors="replace")
    else:
        with open(src, "rb") as f:
            f.seek(start)
            text = f.read(end - start).decode("utf-8", errors="replace")
    tokens = _parse_csv_text(text, setup, skip_header=skip_header)
    skipped = _skipped_set(setup)
    return [SKIPPED if j in skipped else encode_token_column(toks, vt)
            for j, (toks, vt) in enumerate(zip(tokens, setup.column_types))]


def _proc_conf():
    """(process_count, process_index) — the multihost seam. A separate
    function so the parity test can monkeypatch it to force the
    multi-process range plan on the single-process virtual-device mesh
    (tests/test_ingest_pipeline.py)."""
    import jax
    return jax.process_count(), jax.process_index()


def _multihost_plan(jobs, setup, mesh_cur, nproc: int, pidx: int,
                    native_ok: bool, active):
    """Shard-local range ownership for a multi-process parse: count each
    byte range's rows natively (``csv_count_rows``, nogil), derive the
    global row layout, and keep only the ranges whose rows land in THIS
    process's data shards — closing the PR-7 every-host-parses-everything
    gap. Returns None (counted by reason) when the plan cannot apply;
    the parse then degrades to the full per-process parse, which is
    always correct."""
    from h2o3_tpu import native, telemetry
    from h2o3_tpu.parallel.mesh import padded_len, partitioner

    def _no(reason):
        telemetry.counter(
            "h2o3_ingest_fallback_total", {"reason": reason},
            help="byte ranges re-parsed through the Python "
                 "tokenizer, by decline reason").inc()
        return None

    if not native_ok:
        return _no("multihost_no_native")
    if any(setup.column_types[i] not in (T_REAL, T_INT, T_TIME)
           for i in active):
        # enum/str domains need a cross-process union exchange the
        # assembly plane doesn't have yet — every process parses the
        # full byte set (domain union stays process-local-complete)
        return _no("multihost_schema")
    counts = []
    for p, buf, s, e, skip in jobs:
        n = native.count_rows(memoryview(buf)[s:e], setup.separator,
                              setup.quotechar or '"')
        if n is None or n < 0:
            return _no("multihost_uncountable")
        counts.append(n - (1 if skip and n > 0 else 0))
    nrow = sum(counts)
    if nrow <= 0:
        return _no("multihost_empty")
    part = partitioner(mesh_cur)
    plen = padded_len(nrow, mesh_cur)
    bounds = part.row_bounds(plen)
    mine = [d for d in range(part.n_data)
            if part.shard_process(d, nproc) == pidx]
    if not mine:
        return _no("multihost_no_local_shard")
    lo = min(bounds[d][0] for d in mine)
    hi = max(bounds[d][1] for d in mine)
    if hi - lo != sum(bounds[d][1] - bounds[d][0] for d in mine):
        # a device order interleaving processes would make the local
        # row set non-contiguous; process-local-data wants one block
        return _no("multihost_noncontiguous")
    local_jobs, trims = [], []
    r0 = 0
    for job, c in zip(jobs, counts):
        r1 = r0 + c
        a, b = max(r0, lo), min(r1, hi)
        if a < b:
            local_jobs.append(job)
            trims.append((a - r0, b - r0))
        r0 = r1
    return {"jobs": local_jobs, "trims": trims,
            "ranges_total": len(jobs), "nrow": nrow, "plen": plen,
            "lo": lo, "hi": hi, "nproc": nproc, "pidx": pidx,
            "local_bytes": sum(j[3] - j[2] for j in local_jobs)}


def _trim_chunk(cols, a: int, b: int):
    """Row-slice every column of one chunk's encode result to the
    [a, b) rows this process owns (boundary ranges shared with a
    neighbor process). Sliced columns drop their ``fmax`` reduction —
    it covered rows the slice removed."""
    out = []
    for c in cols:
        if c is SKIPPED:
            out.append(c)
            continue
        out.append(EncodedColumn(
            c.vtype, c.data[a:b], domain=c.domain,
            exact=None if c.exact is None else c.exact[a:b]))
    return out


def parse(paths: Union[str, Sequence[str]], setup: Optional[ParseSetup] = None,
          mesh=None, key: Optional[str] = None) -> Frame:
    """Phase 2 — streaming chunk-local parse into a row-sharded Frame.

    Large files fan out over newline-aligned byte ranges of one shared
    mmap per file (the MultiFileParseTask fan-out,
    ParseDataset.java:623) — workers tokenize ``memoryview`` slices of
    the map in place, zero copy; every worker returns finished typed
    columns with chunk-local enum dictionaries, the merge unions
    domains + LUT-remaps codes, and device placement batches one 2D
    transfer per dtype group. A range the native tokenizer declines
    re-parses through the Python tokenizer ALONE (range-scoped
    fallback): the native scan bit-matches the Python tokenizer on
    every accepted token class, so a column may mix tokenizers across
    its ranges without divergence (tests/test_ingest_pipeline.py parity
    matrix). Residual fallbacks are visible, never silent:
    ``h2o3_ingest_fallback_total{reason=}`` counts them and a warning
    names the offending range."""
    import concurrent.futures as cf
    import mmap as _mmap

    from h2o3_tpu import telemetry
    if isinstance(paths, str):
        paths = [paths]
    setup = setup or parse_setup(paths)
    root = telemetry.open_span("ingest.parse",
                               path=os.path.basename(paths[0]))
    maps = []                          # (file, mmap) keepalives
    try:
        t_wall = time.time()
        from h2o3_tpu.parallel.mesh import current_mesh, n_data_shards
        mesh_cur = mesh or current_mesh()   # one-time device init lands
        nw = ingest_workers()               # outside the scan stage
        t_all0 = time.perf_counter()
        jobs = []                      # (path, buf, start, end, skip_header)
        mm_by_path: Dict[str, object] = {}
        comp_info: List[dict] = []
        from h2o3_tpu.ingest.compress import decompress_path
        from h2o3_tpu.ingest.compress import detect as _detect_comp
        for p in paths:
            ckind = _detect_comp(p)
            if ckind:
                # compressed input plane: inflate to ONE contiguous host
                # buffer (member-parallel when the format carries member
                # boundaries — multi-member gzip, multi-frame zstd) and
                # run the unchanged range planner / native tokenizer /
                # RANGE-scoped fallback over the decompressed bytes.
                # Degrades are visible, never silent: a single-stream
                # gzip (no member boundaries to inflate in parallel) is
                # counted by reason, not hidden in a slower parse.
                data, cinfo = decompress_path(p, nw)
                comp_info.append(cinfo)
                if cinfo.get("reason"):
                    telemetry.counter(
                        "h2o3_ingest_fallback_total",
                        {"reason": cinfo["reason"]},
                        help="byte ranges re-parsed through the Python "
                             "tokenizer, by decline reason").inc()
                size = len(data)
                if size >= _PARALLEL_PARSE_BYTES:
                    mm_by_path[p] = data   # bytes quack like the mmap
                    ranges = _byte_ranges(
                        data,
                        _range_count(size, nw, n_data_shards(mesh_cur)),
                        setup)
                    jobs += [(p, data, s, e, setup.header and s == 0)
                             for s, e in ranges]
                else:
                    jobs.append((p, data, 0, size, setup.header))
                continue
            size = os.path.getsize(p)
            if size >= _PARALLEL_PARSE_BYTES:
                f = open(p, "rb")
                mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
                try:
                    mm.madvise(_mmap.MADV_WILLNEED)   # async readahead
                except (AttributeError, OSError):
                    pass
                maps.append((f, mm))
                mm_by_path[p] = mm
                ranges = _byte_ranges(
                    mm, _range_count(size, nw, n_data_shards(mesh_cur)),
                    setup)
                jobs += [(p, mm, s, e, setup.header and s == 0)
                         for s, e in ranges]
            else:
                with open(p, "rb") as f:
                    data = f.read()
                jobs.append((p, data, 0, size, setup.header))
        scan_s = time.perf_counter() - t_all0
        telemetry.record_span("ingest.scan", t_wall, scan_s, parent=root,
                              files=len(paths), chunks=len(jobs))
        t0 = time.perf_counter()
        native_ok = _native_available() and _na_strings_native_safe(setup)
        skipped = _skipped_set(setup)
        active = [i for i in range(len(setup.column_names)) if i not in skipped]
        # multi-host shard-local parse: on a multi-process mesh each
        # process keeps only the byte ranges whose rows land in its own
        # data shards (native row counts drive the ownership map) and
        # assembles via make_array_from_process_local_data — no plan
        # (counted by reason) means every process parses everything,
        # which is the always-correct PR-7 behavior
        nproc, pidx = _proc_conf()
        mh = None
        if nproc > 1 and jobs:
            mh = _multihost_plan(jobs, setup, mesh_cur, nproc, pidx,
                                 native_ok, active)
            if mh is not None:
                jobs = mh["jobs"]
        # per-chunk H2D streaming (ROADMAP "per-CHUNK device_put" lever):
        # numeric/time/enum columns transfer the moment their chunk
        # finishes tokenizing, double-buffered, and assemble device-side
        # — the host-side full-column concat disappears for those
        # groups. Enum lanes carry chunk-LOCAL codes (exact in f32);
        # only the domain union stays host-side, the code remap into the
        # union runs on device at assembly (ingest/stream.py). String
        # columns and enum columns that promote to string keep the host
        # merge.
        stream_cols = [i for i in active
                       if setup.column_types[i] in (T_REAL, T_INT, T_TIME,
                                                    T_ENUM)]
        # streaming engages on ANY single-process mesh: single-shard
        # meshes use the device-concat path, multi-data-shard meshes
        # place each chunk's put on its HOME shard device and stitch the
        # sharded array with make_array_from_single_device_arrays
        # (shard-aligned placement, ingest/stream.py) — no single-device
        # staging of the numeric group. Multi-PROCESS meshes fall back
        # to the host merge: most home devices belong to other
        # processes, so a chunk device_put there is not addressable.
        # '1' forces, '0' disables, 'auto' = on when single-process.
        import jax as _jax
        stream_env = os.environ.get("H2O3_INGEST_STREAM", "auto")
        if stream_env in ("0", "false", ""):
            stream_ok = False
        elif stream_env == "1":
            stream_ok = True
        else:
            stream_ok = _jax.process_count() == 1
        if mh is not None:
            # the multihost assembly owns device placement (process-
            # local row blocks); per-chunk streaming targets global rows
            stream_ok = False
        stats = _StageStats()

        def _tokenize_native(jobs_):
            """One native tokenize round over ``jobs_``: returns
            (results, decline reasons, streamer)."""
            res: List[Optional[List[EncodedColumn]]] = [None] * len(jobs_)
            rsn: Dict[int, str] = {}
            strm = None
            if len(jobs_) == 1:
                p_, buf_, s_, e_, skip_ = jobs_[0]
                out = _encode_range_native(buf_, s_, e_, setup, skip_,
                                           stats)
                if isinstance(out, str):
                    rsn[0] = out
                else:
                    res[0] = out[0]
                return res, rsn, strm
            from h2o3_tpu.ingest.stream import ChunkDeviceStreamer
            want_stream = bool(stream_cols and stream_ok)
            if want_stream:
                strm = ChunkDeviceStreamer(
                    stream_cols, list(setup.column_types), len(jobs_),
                    mesh_cur,
                    input_bytes=sum(e - s for _, _, s, e, _ in jobs_))
            workers = min(len(jobs_), nw)
            pack_cols = stream_cols if want_stream else None
            with cf.ThreadPoolExecutor(max_workers=workers) as ex:
                futs = {ex.submit(_encode_range_native, buf, s, e,
                                  setup, skip, stats, pack_cols): k
                        for k, (p, buf, s, e, skip) in enumerate(jobs_)}
                for fu in cf.as_completed(futs):
                    k = futs[fu]
                    out = fu.result()
                    if isinstance(out, str):
                        rsn[k] = out
                        continue
                    res[k], pack = out
                    if strm is not None:
                        # chunk's DMA issued NOW, under the remaining
                        # workers' tokenize time; the f32 pack was
                        # built in the worker (prepack_chunk)
                        strm.add(k, res[k], pack)
            return res, rsn, strm

        streamer = None
        reasons: Dict[int, str] = {}
        results: List[Optional[List[EncodedColumn]]] = [None] * len(jobs)
        if native_ok:
            results, reasons, streamer = _tokenize_native(jobs)
            if reasons and mm_by_path and mh is None:
                # quote-blind retry: a decline on a file whose quote
                # probe came up empty, but which DOES hold a quote past
                # the probe window, means the naive newline boundaries
                # may have cut a quoted field mid-quote — the per-range
                # Python fallback would then silently mis-split it. Redo
                # discovery with the exact full-pass state machine and
                # re-tokenize ONCE; genuinely malformed ranges still
                # decline on the retry and fall back per range.
                qb = (getattr(setup, "quotechar", '"') or '"').encode()[0:1]
                declined_paths = {jobs[k][0] for k in reasons}
                late_quote = {
                    p2 for p2, mm2 in mm_by_path.items()
                    if p2 in declined_paths   # only scan files that declined
                    and mm2.find(qb, 0, min(len(mm2), _QUOTE_PROBE_BYTES))
                    == -1 and mm2.find(qb, _QUOTE_PROBE_BYTES) != -1}
                if late_quote:
                    from h2o3_tpu.log import warn
                    warn("ingest: decline with a quote past the %d MB "
                         "probe window in %s — re-splitting with exact "
                         "quote-aware boundaries and re-tokenizing",
                         _QUOTE_PROBE_BYTES >> 20, sorted(
                             os.path.basename(p2) for p2 in late_quote))
                    if streamer is not None:
                        streamer.discard()   # counted: wasted uploads
                    # rebuild preserving path order (job order IS row
                    # order — the streamer's chunk-home map relies on it)
                    small = {j[0]: j for j in jobs
                             if j[0] not in mm_by_path}
                    jobs = []
                    for p2 in paths:
                        if p2 in mm_by_path:
                            mm2 = mm_by_path[p2]
                            ranges = _byte_ranges(
                                mm2, _range_count(len(mm2), nw,
                                                  n_data_shards(mesh_cur)),
                                setup, force_quote_scan=p2 in late_quote)
                            jobs += [(p2, mm2, s, e,
                                      setup.header and s == 0)
                                     for s, e in ranges]
                        elif p2 in small:
                            jobs.append(small[p2])
                    results, reasons, streamer = _tokenize_native(jobs)
        todo = [k for k, r in enumerate(results) if r is None]
        n_fallback = len(todo)
        if todo:
            # RANGE-scoped fallback: only the declined ranges re-parse
            # through the Python tokenizer. The native scan bit-matches
            # the Python tokenizer on every accepted token class
            # (RFC-4180 quotes, long numerics, unicode whitespace), so
            # a column keeps the equivalence contract even when its
            # ranges mix tokenizers — the old import-scoped all-ranges
            # re-parse (and its streamer.discard() of already-uploaded
            # device chunks) is gone. Every fallback is observable:
            # counted per reason, warned with the offending range.
            from h2o3_tpu.log import warn
            if not native_ok:
                setup_reason = ("numeric_na_sentinel" if _native_available()
                                else "no_toolchain")
                for k in todo:
                    reasons.setdefault(k, setup_reason)
            for k in todo:
                telemetry.counter(
                    "h2o3_ingest_fallback_total",
                    {"reason": reasons.get(k, "unknown")},
                    help="byte ranges re-parsed through the Python "
                         "tokenizer, by decline reason").inc()
            k0 = todo[0]
            warn("ingest: %d/%d byte range(s) fell back to the Python "
                 "tokenizer — first: %s[%d:%d) reason=%s (all reasons: %s)",
                 len(todo), len(jobs), os.path.basename(jobs[k0][0]),
                 jobs[k0][2], jobs[k0][3], reasons.get(k0, "unknown"),
                 sorted({reasons.get(k, "unknown") for k in todo}))
            total = sum(jobs[k][3] - jobs[k][2] for k in todo)
            if len(todo) > 1 and total >= _PARALLEL_PARSE_BYTES:
                # Python fallback in PROCESSES — spawn, not fork: this
                # process is multithreaded (JAX/XLA), and forking while
                # another thread holds an XLA mutex deadlocks the child.
                # Workers reopen the file by path (an mmap won't pickle).
                import multiprocessing as mp
                ctx = mp.get_context("spawn")
                workers = min(len(todo), nw)
                with cf.ProcessPoolExecutor(max_workers=workers,
                                            mp_context=ctx) as ex:
                    # mmapped files reopen by path in the worker (an
                    # mmap won't pickle); decompressed buffers have no
                    # on-disk plaintext, so their bytes ship instead
                    futs = {k: ex.submit(
                        _encode_range_python,
                        jobs[k][1] if isinstance(jobs[k][1], bytes)
                        else jobs[k][0],
                        jobs[k][2], jobs[k][3], setup, jobs[k][4])
                            for k in todo}
                    for k, fu in futs.items():
                        results[k] = fu.result()
            else:
                for k in todo:
                    p, buf, s, e, skip = jobs[k]
                    src = buf if isinstance(buf, bytes) else p
                    results[k] = _encode_range_python(src, s, e, setup, skip)
            if streamer is not None:
                # the re-parsed ranges join the stream late; every other
                # range's already-uploaded device chunk SURVIVES (the
                # wasted-work seam tests/test_ingest_pipeline.py guards)
                for k in todo:
                    streamer.add(k, results[k])
        if mh is not None:
            # boundary ranges share rows with a neighbor process — keep
            # only the rows this process's shards own (exact counts came
            # from the native count pass, so trims are deterministic)
            for k, (a, b) in enumerate(mh["trims"]):
                cols = results[k]
                if cols is None:
                    continue
                nr = next((len(c.data) for c in cols if c is not SKIPPED), 0)
                if a > 0 or b < nr:
                    results[k] = _trim_chunk(cols, a, b)
        t1 = time.perf_counter()
        # the streamed transfers ran INSIDE the tokenize window — report
        # tokenize net of that hidden transfer time so the two stages
        # stay additive (ONE clock still feeds both LAST_PROFILE and the
        # spans, so REST- and tool-reported splits cannot disagree)
        hidden_put_s = streamer.add_seconds if streamer is not None else 0.0
        telemetry.record_span("ingest.tokenize_encode", t_wall,
                              t1 - t0 - hidden_put_s,
                              parent=root, chunks=len(jobs))
        names = [n for i, n in enumerate(setup.column_names) if i not in skipped]
        pos = {orig: j for j, orig in enumerate(active)}   # filtered index
        merge_s = [0.0]

        def _merged(idx):
            # merge one dtype group; time attributed to the merge stage even
            # though it runs interleaved with the previous group's DMA
            tm_wall = time.time()
            tm = time.perf_counter()
            out = [(pos[i], merge_column([cr[i] for cr in results],
                                         setup.column_types[i]))
                   for i in idx]
            dt = time.perf_counter() - tm
            merge_s[0] += dt
            telemetry.record_span("ingest.domain_union", tm_wall, dt,
                                  parent=root, cols=len(idx))
            return out

        preset = None
        streamed = frozenset()
        if streamer is not None:
            # block on the outstanding per-chunk DMAs and assemble the
            # numeric/time columns device-side (no host full-column
            # concat); wide-int exact columns fall back to the merge
            vec_map = streamer.assemble()
            streamed = frozenset(vec_map)
            preset = {pos[i]: v for i, v in vec_map.items()}

        def _groups():
            # numeric/time/str first: their merge is a cheap concat, and
            # issuing their device DMA NOW lets the transfer run underneath
            # the enum group's domain union + LUT remap (the expensive host
            # half of the merge) instead of after it. Streamed columns are
            # already on device and skip the merge entirely.
            yield _merged([i for i in active if i not in streamed
                           and setup.column_types[i] != T_ENUM])
            yield _merged([i for i in active if i not in streamed
                           and setup.column_types[i] == T_ENUM])

        t2_wall = time.time()
        if mh is not None:
            # shard-local assembly: this process packs + transfers ONLY
            # its own padded row block; the global array assembles from
            # process-local data (ingest/stream.py multihost target)
            from h2o3_tpu.ingest.stream import assemble_process_local
            vec_map = assemble_process_local(
                _merged(list(active)), mh["lo"], mh["hi"], mh["nrow"],
                mesh_cur, simulate=_jax.process_count() != mh["nproc"])
            mh["h2d_bytes"] = (mh["hi"] - mh["lo"]) * len(active) * 4
            fr = Frame(names, [vec_map[j] for j in range(len(active))],
                       key=key or os.path.basename(paths[0]))
        else:
            fr = Frame.from_typed_column_groups(
                names, _groups(), len(active), mesh=mesh,
                key=key or os.path.basename(paths[0]), preset=preset)
        t3 = time.perf_counter()
        # device_put = hidden per-chunk streaming + visible assembly/group
        # DMA, net of the interleaved domain-union work (the union spans
        # are children of the same root and reported separately)
        visible_put_s = t3 - t1 - merge_s[0]
        put_total_s = hidden_put_s + visible_put_s
        overlap = (hidden_put_s / put_total_s
                   if streamer is not None and put_total_s > 0 else None)
        telemetry.record_span("ingest.device_put", t2_wall, put_total_s,
                              parent=root, hidden_s=round(hidden_put_s, 4),
                              overlap_ratio=overlap)
        if overlap is not None:
            telemetry.gauge("h2o3_ingest_h2d_overlap_ratio",
                            help="share of the ingest pack+transfer "
                            "(device_put) stage hidden under tokenize"
                            ).set(overlap)
        shard_stats = None
        if streamer is not None and streamer.nd > 1:
            # per-shard placement/overlap stats (shard-aligned streamed
            # ingest): one labeled gauge per data shard + the aligned-row
            # ratio (share of rows whose chunk H2D landed on its final
            # home shard — the rest moved D2D at assembly)
            shard_stats = streamer.shard_profile()
            for s in shard_stats:
                if s["overlap_ratio"] is not None:
                    telemetry.gauge(
                        "h2o3_ingest_h2d_overlap_ratio",
                        {"shard": str(s["shard"])},
                        help="per-data-shard share of the streamed chunk "
                        "pack+transfer hidden under tokenize").set(
                        s["overlap_ratio"])
        if root is not None:
            root.attrs.update(rows=fr.nrow, chunks=len(jobs))
            root.finish()
        fb_reasons: Dict[str, int] = {}
        for r in reasons.values():
            fb_reasons[r] = fb_reasons.get(r, 0) + 1
        # in-place so `from h2o3_tpu.ingest.parse import LAST_PROFILE` stays live
        LAST_PROFILE.clear()
        LAST_PROFILE.update({"rows": fr.nrow, "chunks": len(jobs),
                             "native": bool(native_ok and not n_fallback),
                             "native_ranges": len(jobs) - n_fallback,
                             "fallback_ranges": n_fallback,
                             "fallback_reasons": fb_reasons,
                             "streamed": streamer is not None,
                             # compressed-input plane: per-file member
                             # index + whether inflate ran member-parallel
                             "compressed": comp_info or None,
                             # multihost shard-local plan: which ranges
                             # THIS process parsed and transferred
                             "multihost": (None if mh is None else {
                                 "nproc": mh["nproc"], "pidx": mh["pidx"],
                                 "ranges_total": mh["ranges_total"],
                                 "ranges_local": len(mh["jobs"]),
                                 "rows_total": mh["nrow"],
                                 "row_span": [mh["lo"], mh["hi"]],
                                 "local_bytes": mh["local_bytes"],
                                 "h2d_bytes": mh.get("h2d_bytes")}),
                             "scan_s": round(scan_s, 4),
                             "tokenize_cpu_s": round(stats.tokenize_s, 4),
                             "encode_cpu_s": round(stats.encode_s, 4),
                             "tokenize_encode_s": round(t1 - t0 - hidden_put_s, 4),
                             "merge_s": round(merge_s[0], 4),
                             "device_put_s": round(put_total_s, 4),
                             "h2d_overlap_ratio": (round(overlap, 4)
                                                   if overlap is not None
                                                   else None),
                             "h2d_shards": shard_stats,
                             "aligned_row_ratio": (
                                 round(streamer.aligned_row_ratio, 4)
                                 if streamer is not None and streamer.nd > 1
                                 and streamer.aligned_row_ratio is not None
                                 else None)})
        return fr
    finally:
        # a parse that raises mid-pipeline still closes its root span,
        # so failures show in the trace instead of vanishing
        if root is not None and root.duration_s is None:
            root.attrs["error"] = True
            root.finish()
        for f, mm in maps:
            try:
                mm.close()
            except BufferError:
                pass           # a straggler view still borrows the map;
            f.close()          # the GC closes it when the last view dies


def import_file(path: Union[str, Sequence[str]], destination_frame: Optional[str] = None,
                header: Optional[bool] = None, sep: Optional[str] = None,
                col_names: Optional[Sequence[str]] = None,
                col_types: Optional[Sequence[str]] = None,
                na_strings: Optional[Sequence[str]] = None, mesh=None) -> Frame:
    """One-shot import (mirrors h2o.import_file, h2o-py/h2o/h2o.py).
    Dispatches on URI scheme (persist layer) and file format
    (ParserProvider SPI analog): csv/arff/svmlight/parquet/orc + gated
    avro/xls."""
    from h2o3_tpu.ingest.formats import FORMAT_PARSERS, sniff_format
    from h2o3_tpu.ingest.persist_uri import localize
    if isinstance(path, str):
        path = localize(path)
        first = path
    else:
        path = [localize(p) for p in path]
        first = path[0]
    fmt = sniff_format(first)
    if fmt != "csv":
        paths = [path] if isinstance(path, str) else list(path)
        frames = [FORMAT_PARSERS[fmt](p, mesh=mesh,
                                      key=destination_frame)
                  for p in paths]
        fr = frames[0]
        for extra in frames[1:]:
            fr = _rbind(fr, extra, mesh)
        if destination_frame:
            fr.key = destination_frame
        return fr
    setup = parse_setup(path, separator=sep, header=header, column_names=col_names,
                        column_types=col_types, na_strings=na_strings)
    return parse(path, setup, mesh=mesh, key=destination_frame)


def _rbind(a: Frame, b: Frame, mesh=None) -> Frame:
    """Row-concatenate two frames for multi-file import. Enum columns
    union their two domains and LUT-remap the integer codes (the
    PackedDomains contract) instead of round-tripping every cell through
    label strings and a full re-encode; time columns stay time."""
    from h2o3_tpu.ingest.chunk import _merge_enum, _merge_numeric
    if a.names != b.names:
        raise ValueError("multi-file import needs identical schemas")

    def _num_chunk(v):
        d = v.to_numpy()
        if d.dtype == np.int64:     # exact wide-int host shadow
            return EncodedColumn(T_INT, d.astype(np.float64), exact=d)
        return EncodedColumn(v.type, d)

    names, vecs = [], []
    for n in a.names:
        va, vb = a.vec(n), b.vec(n)
        names.append(n)
        if va.type == T_ENUM and vb.type == T_ENUM:
            # the chunk merger IS the PackedDomains contract — same
            # union + LUT remap (and cardinality degrade) as the parse
            col = _merge_enum([
                EncodedColumn(T_ENUM, v.to_numpy().astype(np.int32),
                              domain=list(v.domain or ()))
                for v in (va, vb)])
            vecs.append(Vec.from_numpy(col.data, vtype=col.vtype,
                                       domain=col.domain, mesh=mesh))
        elif va.type == T_STR and vb.type == T_STR:
            data = np.concatenate([va.to_strings(), vb.to_strings()])
            vecs.append(Vec.from_numpy(np.asarray(data, dtype=object),
                                       vtype=T_STR, mesh=mesh))
        elif va.type == T_TIME and vb.type == T_TIME:
            ms = np.concatenate([va.to_numpy(), vb.to_numpy()])
            vecs.append(Vec.from_numpy(ms.astype(np.int64), vtype=T_TIME,
                                       mesh=mesh))
        elif va.is_numeric and vb.is_numeric:
            vt = T_REAL if T_REAL in (va.type, vb.type) else T_INT
            # via the chunk merger so an exact-int64 side never gets
            # munged by a float64 concat promotion
            col = _merge_numeric([_num_chunk(va), _num_chunk(vb)], vt)
            vecs.append(Vec.from_numpy(col.data, vtype=col.vtype,
                                       mesh=mesh))
        else:
            # mixed types across files (one file guessed enum, the other
            # string/numeric): degrade through labels like the reference
            data = np.concatenate([np.asarray(va.to_strings(), dtype=object),
                                   np.asarray(vb.to_strings(), dtype=object)])
            vecs.append(Vec.from_numpy(data, mesh=mesh))
    return Frame(names, vecs, key=a.key)


def upload_numpy(data, names=None, mesh=None) -> Frame:
    return Frame.from_numpy(data, names=names, mesh=mesh)
