"""Two-phase distributed parse: guess, then parse.

Reference: water/parser/ParseSetup.java guesses separator/header/types from
sampled chunks; water/parser/ParseDataset.java:127 forkParseDataset runs a
MultiFileParseTask MRTask over raw-byte chunks, each node streaming its
chunks through CsvParser into per-column NewChunks, then unions categorical
domains across nodes and assembles the Frame.

TPU re-design: parsing is host work (TPUs don't parse bytes); each host
reads its byte ranges, tokenises to typed numpy columns, unions enum
domains, and the columns are device_put row-sharded. The two-phase
guess-then-parse contract and the type system are preserved. A C++
tokeniser can slot under ``_parse_csv_text`` later without changing the
interface.
"""
from __future__ import annotations

import csv
import io
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import ENUM_NA, T_ENUM, T_INT, T_REAL, T_STR, T_TIME, Vec

DEFAULT_NA_STRINGS = {"", "NA", "N/A", "na", "NaN", "nan", "null", "NULL", "None", "?"}
_SEP_CANDIDATES = [",", "\t", ";", "|", " "]
# max enum cardinality before a column falls back to string
# (reference: Categorical.MAX_CATEGORICAL_COUNT ~ 10M; we cap lower since
# domains are host-side python lists)
MAX_ENUM_CARDINALITY = 1_000_000


@dataclass
class ParseSetup:
    separator: str = ","
    header: bool = True
    column_names: List[str] = field(default_factory=list)
    column_types: List[str] = field(default_factory=list)
    na_strings: set = field(default_factory=lambda: set(DEFAULT_NA_STRINGS))
    skipped_columns: List[int] = field(default_factory=list)
    quotechar: str = '"'


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return False


def _is_int(tok: str) -> bool:
    try:
        f = float(tok)
        return f == int(f) and "e" not in tok.lower() and "." not in tok
    except (ValueError, OverflowError):
        return False


def _looks_time(tok: str) -> bool:
    if len(tok) < 8 or tok[4:5] != "-":
        return False
    try:
        np.datetime64(tok)
        return True
    except ValueError:
        return False


def _read_head(path: str, nbytes: int = 1 << 16) -> str:
    with open(path, "rb") as f:
        raw = f.read(nbytes)
    txt = raw.decode("utf-8", errors="replace")
    # drop a possibly-truncated last line
    if len(raw) == nbytes and "\n" in txt:
        txt = txt[: txt.rfind("\n")]
    return txt


def guess_separator(sample: str) -> str:
    lines = [l for l in sample.splitlines() if l.strip()][:50]
    best, best_score = ",", -1
    for sep in _SEP_CANDIDATES:
        counts = [len(next(csv.reader([l], delimiter=sep, quotechar='"'))) for l in lines]
        if not counts:
            continue
        ncol = max(set(counts), key=counts.count)
        consistent = sum(c == ncol for c in counts)
        score = consistent * 1000 + ncol
        if ncol > 1 and score > best_score:
            best, best_score = sep, score
    return best


def _guess_col_type(tokens: List[str], na_strings) -> str:
    vals = [t for t in tokens if t.strip() not in na_strings]
    if not vals:
        return T_REAL
    if all(_is_number(v) for v in vals):
        return T_INT if all(_is_int(v) for v in vals) else T_REAL
    if all(_looks_time(v) for v in vals):
        return T_TIME
    return T_ENUM


def parse_setup(paths: Union[str, Sequence[str]], separator: Optional[str] = None,
                header: Optional[bool] = None, column_names: Optional[Sequence[str]] = None,
                column_types: Optional[Sequence[str]] = None,
                na_strings: Optional[Sequence[str]] = None) -> ParseSetup:
    """Phase 1 — sample and guess (reference: ParseSetup.guessSetup)."""
    if isinstance(paths, str):
        paths = [paths]
    sample = _read_head(paths[0])
    sep = separator or guess_separator(sample)
    nas = set(na_strings) if na_strings is not None else set(DEFAULT_NA_STRINGS)
    rows = list(csv.reader(io.StringIO(sample), delimiter=sep, quotechar='"'))
    rows = [r for r in rows if r]
    if not rows:
        raise ValueError(f"empty file: {paths[0]}")
    first = rows[0]
    if header is None:
        # header iff some column's first cell is a bare string while the
        # body of that column is numeric or time-typed
        def tok_class(tok):
            t = tok.strip()
            if t in nas:
                return None
            if _is_number(t):
                return "num"
            if _looks_time(t):
                return "time"
            return "str"

        data_rows = rows[1:50]
        header = False
        for i, c in enumerate(first):
            if tok_class(c) != "str":
                continue
            body = [tok_class(r[i]) for r in data_rows if i < len(r)]
            body = [b for b in body if b is not None]
            if body and all(b in ("num", "time") for b in body):
                header = True
                break
        if not data_rows:
            header = all(not _is_number(c) for c in first)
    ncol = len(first)
    names = (list(first) if header else [f"C{i + 1}" for i in range(ncol)])
    if column_names:
        names = list(column_names)
    body = rows[1:] if header else rows
    body = body[:1000]
    types = []
    for i in range(ncol):
        toks = [r[i] for r in body if i < len(r)]
        types.append(_guess_col_type(toks, nas))
    if column_types:
        for i, t in enumerate(column_types):
            if t:
                types[i] = {"numeric": T_REAL, "categorical": T_ENUM, "factor": T_ENUM,
                            "string": T_STR, "time": T_TIME, "int": T_INT,
                            "real": T_REAL, "enum": T_ENUM}.get(t, t)
    return ParseSetup(separator=sep, header=bool(header), column_names=names,
                      column_types=types, na_strings=nas)


def _parse_csv_text(text: str, setup: ParseSetup, skip_header: bool):
    """Tokenise one file's text into per-column python lists."""
    reader = csv.reader(io.StringIO(text), delimiter=setup.separator,
                        quotechar=setup.quotechar)
    rows = [r for r in reader if r]
    if skip_header and rows:
        rows = rows[1:]
    ncol = len(setup.column_names)
    cols = [[None] * len(rows) for _ in range(ncol)]
    nas = setup.na_strings
    for ri, r in enumerate(rows):
        for ci in range(ncol):
            tok = r[ci].strip() if ci < len(r) else ""
            cols[ci][ri] = None if tok in nas else tok
    return cols


def _column_to_vec(tokens, vtype: str, mesh=None) -> Vec:
    n = len(tokens)
    if vtype in (T_REAL, T_INT):
        if isinstance(tokens, np.ndarray):
            # native tokenizer output: already-parsed float64 (NA = NaN)
            return Vec.from_numpy(tokens, vtype=vtype, mesh=mesh)
        arr = np.full(n, np.nan, dtype=np.float64)
        for i, t in enumerate(tokens):
            if t is not None:
                try:
                    arr[i] = float(t)
                except ValueError:
                    pass  # stray non-numeric in a numeric column → NA
        return Vec.from_numpy(arr, vtype=vtype, mesh=mesh)
    if vtype == T_TIME:
        ms = np.full(n, Vec.TIME_NA, dtype=np.int64)
        for i, t in enumerate(tokens):
            if t is not None:
                try:
                    ms[i] = np.datetime64(t, "ms").astype(np.int64)
                except ValueError:
                    pass
        return Vec.from_numpy(ms, vtype=T_TIME, mesh=mesh)
    if vtype == T_STR:
        return Vec.from_numpy(np.array(tokens, dtype=object), vtype=T_STR, mesh=mesh)
    # enum: union domain then encode (reference: PackedDomains union across nodes)
    vals = sorted({t for t in tokens if t is not None})
    if len(vals) > MAX_ENUM_CARDINALITY:
        return Vec.from_numpy(np.array(tokens, dtype=object), vtype=T_STR, mesh=mesh)
    lut = {v: i for i, v in enumerate(vals)}
    codes = np.fromiter((ENUM_NA if t is None else lut[t] for t in tokens),
                        dtype=np.int32, count=n)
    return Vec.from_numpy(codes, vtype=T_ENUM, domain=vals, mesh=mesh)


def _native_token_columns(data: bytes, setup: ParseSetup,
                          skip_header: bool):
    """Native-tokenizer fast path: C++ scans the bytes once
    (h2o3_tpu/native/fast_csv.cpp — the CsvParser hot loop), numeric
    columns come back pre-parsed, and Python touches only the cells of
    enum/string/time columns. Returns token-column compatible output: a list
    with a numpy float64 array per numeric column and a list of
    Optional[str] per other column — or None to use the Python path."""
    from h2o3_tpu.native import parse_bytes
    out = parse_bytes(data, setup.separator)
    if out is None:
        return None
    starts, lens, vals, ok = out
    r0 = 1 if skip_header else 0
    ncols = vals.shape[1]
    if ncols != len(setup.column_types):
        return None
    na = setup.na_strings if setup.na_strings is not None else \
        DEFAULT_NA_STRINGS
    cols = []
    for j, vt in enumerate(setup.column_types):
        if vt in (T_REAL, T_INT):
            # pre-parsed doubles; non-numeric tokens (NA strings or
            # strays) are already NaN — identical to _column_to_vec
            cols.append(vals[r0:, j].copy())
        else:
            s = starts[r0:, j]
            ln = lens[r0:, j]
            o = ok[r0:, j]
            toks: List[Optional[str]] = []
            for i in range(len(s)):
                if o[i] == 2:
                    toks.append(None)
                    continue
                t = data[s[i]: s[i] + ln[i]].decode("utf-8",
                                                    errors="replace")
                toks.append(None if t in na else t)
            cols.append(toks)
    return cols


_PARALLEL_PARSE_BYTES = 16 << 20   # byte-range fan-out above 16 MB


def _byte_ranges(path: str, n_chunks: int) -> List[tuple]:
    """Split a file into newline-aligned byte ranges (the reference
    parses raw-byte chunks, water/parser/ParseDataset.java:623)."""
    size = os.path.getsize(path)
    bounds = [0]
    with open(path, "rb") as f:
        for i in range(1, n_chunks):
            target = size * i // n_chunks
            f.seek(target)
            f.readline()                 # advance to the next newline
            bounds.append(min(f.tell(), size))
    bounds.append(size)
    return [(bounds[i], bounds[i + 1]) for i in range(n_chunks)
            if bounds[i + 1] > bounds[i]]


def _parse_range(path: str, start: int, end: int, setup: ParseSetup,
                 skip_header: bool):
    with open(path, "rb") as f:
        f.seek(start)
        text = f.read(end - start).decode("utf-8", errors="replace")
    return _parse_csv_text(text, setup, skip_header=skip_header)


def _na_strings_native_safe(setup: ParseSetup) -> bool:
    """The native tokenizer maps any non-numeric token in a numeric
    column to NaN — equivalent to the Python path ONLY when no na_string
    is itself numeric (a numeric NA sentinel like '-999' must go through
    the token path)."""
    import math
    for s in (setup.na_strings or ()):
        try:
            v = float(s)
        except ValueError:
            continue
        if not math.isnan(v):       # "nan"/"NaN" parse to NaN == NA anyway
            return False
    return True


def _parse_range_native(path: str, start: int, end: int, setup: ParseSetup,
                        skip_header: bool):
    """Byte-range worker on the native tokenizer (ctypes releases the
    GIL during the C scan, so a THREAD pool parallelises it without the
    process-spawn + pickle cost of the Python fallback). Returns per-
    column numpy float64 arrays (numeric) / token lists, or None."""
    with open(path, "rb") as f:
        f.seek(start)
        data = f.read(end - start)
    return _native_token_columns(data, setup, skip_header=skip_header)


def parse(paths: Union[str, Sequence[str]], setup: Optional[ParseSetup] = None,
          mesh=None, key: Optional[str] = None) -> Frame:
    """Phase 2 — full parse into a row-sharded Frame. Large files are
    tokenised in parallel over newline-aligned byte ranges (the
    MultiFileParseTask fan-out, ParseDataset.java:623; processes stand
    in for nodes since CPython tokenisation doesn't share the GIL)."""
    if isinstance(paths, str):
        paths = [paths]
    setup = setup or parse_setup(paths)
    parts: Optional[List[list]] = None     # per column: list of chunks

    def merge(cols):
        nonlocal parts
        if parts is None:
            parts = [[c] for c in cols]
        else:
            for ps, extra in zip(parts, cols):
                ps.append(extra)

    from h2o3_tpu.native import lib as _native_lib
    native_ok = _native_lib() is not None and _na_strings_native_safe(setup)
    for p in paths:
        size = os.path.getsize(p)
        if size >= _PARALLEL_PARSE_BYTES:
            import concurrent.futures as cf
            n_chunks = min(os.cpu_count() or 4, 16)
            ranges = _byte_ranges(p, n_chunks)
            results = [None] * len(ranges)
            if native_ok:
                # native tokenizer + THREADS: the ctypes call releases
                # the GIL, so workers scan byte ranges concurrently with
                # no process-spawn or result-pickle overhead
                with cf.ThreadPoolExecutor(max_workers=len(ranges)) as ex:
                    futs = [ex.submit(_parse_range_native, p, s, e, setup,
                                      setup.header and s == 0)
                            for (s, e) in ranges]
                    results = [fu.result() for fu in futs]
            if any(r is None for r in results):
                # Python fallback in PROCESSES — spawn, not fork: this
                # process is multithreaded (JAX/XLA), and forking while
                # another thread holds an XLA mutex deadlocks the child
                import multiprocessing as mp
                ctx = mp.get_context("spawn")
                with cf.ProcessPoolExecutor(max_workers=len(ranges),
                                            mp_context=ctx) as ex:
                    futs = [ex.submit(_parse_range, p, s, e, setup,
                                      setup.header and s == 0)
                            for (s, e) in ranges]
                    results = [fu.result() for fu in futs]
            for r in results:
                merge(r)
        else:
            with open(p, "rb") as f:
                data = f.read()
            cols = (_native_token_columns(data, setup,
                                          skip_header=setup.header)
                    if native_ok else None)
            if cols is None:
                cols = _parse_csv_text(data.decode("utf-8",
                                                   errors="replace"),
                                       setup, skip_header=setup.header)
            merge(cols)
    skipped = set(setup.skipped_columns)
    names, vecs = [], []
    for i, t in enumerate(setup.column_types):
        if i in skipped:
            continue
        ps = parts[i]
        if all(isinstance(c, np.ndarray) for c in ps):
            col = ps[0] if len(ps) == 1 else np.concatenate(ps)
        else:
            col = []
            for c in ps:
                if isinstance(c, np.ndarray):
                    # repr(float(v)), not repr(v): numpy 2.x scalar repr
                    # is 'np.float64(1.5)', which float() can't parse
                    col.extend(None if np.isnan(v) else repr(float(v))
                               for v in c)
                else:
                    col.extend(c)
        names.append(setup.column_names[i])
        vecs.append(_column_to_vec(col, t, mesh=mesh))
    return Frame(names, vecs, key=key or os.path.basename(paths[0]))


def import_file(path: Union[str, Sequence[str]], destination_frame: Optional[str] = None,
                header: Optional[bool] = None, sep: Optional[str] = None,
                col_names: Optional[Sequence[str]] = None,
                col_types: Optional[Sequence[str]] = None,
                na_strings: Optional[Sequence[str]] = None, mesh=None) -> Frame:
    """One-shot import (mirrors h2o.import_file, h2o-py/h2o/h2o.py).
    Dispatches on URI scheme (persist layer) and file format
    (ParserProvider SPI analog): csv/arff/svmlight/parquet/orc + gated
    avro/xls."""
    from h2o3_tpu.ingest.formats import FORMAT_PARSERS, sniff_format
    from h2o3_tpu.ingest.persist_uri import localize
    if isinstance(path, str):
        path = localize(path)
        first = path
    else:
        path = [localize(p) for p in path]
        first = path[0]
    fmt = sniff_format(first)
    if fmt != "csv":
        paths = [path] if isinstance(path, str) else list(path)
        frames = [FORMAT_PARSERS[fmt](p, mesh=mesh,
                                      key=destination_frame)
                  for p in paths]
        fr = frames[0]
        for extra in frames[1:]:
            fr = _rbind(fr, extra, mesh)
        if destination_frame:
            fr.key = destination_frame
        return fr
    setup = parse_setup(path, separator=sep, header=header, column_names=col_names,
                        column_types=col_types, na_strings=na_strings)
    return parse(path, setup, mesh=mesh, key=destination_frame)


def _rbind(a: Frame, b: Frame, mesh=None) -> Frame:
    if a.names != b.names:
        raise ValueError("multi-file import needs identical schemas")
    data = {}
    for n in a.names:
        va, vb = a.vec(n), b.vec(n)
        if (va.type == T_ENUM or vb.type == T_ENUM
                or va.type == T_STR or vb.type == T_STR):
            data[n] = np.concatenate([np.asarray(va.to_strings(),
                                                 dtype=object),
                                      np.asarray(vb.to_strings(),
                                                 dtype=object)])
        else:
            data[n] = np.concatenate([va.to_numpy(), vb.to_numpy()])
    return Frame.from_numpy(data, mesh=mesh)


def upload_numpy(data, names=None, mesh=None) -> Frame:
    return Frame.from_numpy(data, names=names, mesh=mesh)
