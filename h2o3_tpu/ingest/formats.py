"""Format-dispatched parsers beyond CSV.

Reference: the ParserProvider SPI (water/parser/ParserService.java) with
in-core ARFF (water/parser/ARFFParser.java), SVMLight
(water/parser/SVMLightParser.java), XLS (water/parser/XlsParser.java)
and the h2o-parsers modules (orc/parquet/avro).

TPU re-design: columnar formats (parquet/ORC) decode through pyarrow
straight into numpy columns → device shards (no row-wise NewChunk
stage); ARFF/SVMLight are host tokenisers feeding the same column →
Vec pipeline as CSV. Avro and XLS are gated on optional libraries that
this image does not carry (fastavro / openpyxl) with explicit errors —
the dispatch seam matches the reference's pluggable ParserProvider."""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import T_ENUM, T_INT, T_REAL, T_STR, Vec


def sniff_format(path: str) -> str:
    ext = os.path.splitext(path)[1].lower()
    if ext == ".arff":
        return "arff"
    if ext in (".svm", ".svmlight"):
        return "svmlight"
    if ext in (".parquet", ".pq"):
        return "parquet"
    if ext == ".orc":
        return "orc"
    if ext == ".avro":
        return "avro"
    if ext in (".xls", ".xlsx"):
        return "xls"
    return "csv"


# -------------------------------------------------------------------- ARFF

_ARFF_ATTR = re.compile(r"@attribute\s+('(?:[^']*)'|\"(?:[^\"]*)\"|\S+)\s+"
                        r"(.+)", re.IGNORECASE)


def parse_arff(path: str, mesh=None, key: Optional[str] = None) -> Frame:
    """water/parser/ARFFParser.java: @relation/@attribute header drives
    the column schema; @data is CSV with ? as NA."""
    names: List[str] = []
    kinds: List[str] = []          # numeric | nominal | string | date
    domains: List[Optional[List[str]]] = []
    data_lines: List[str] = []
    in_data = False
    with open(path, "r", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("%"):
                continue
            if in_data:
                data_lines.append(line)
                continue
            low = line.lower()
            if low.startswith("@relation"):
                continue
            if low.startswith("@data"):
                in_data = True
                continue
            m = _ARFF_ATTR.match(line)
            if m:
                nm = m.group(1).strip("'\"")
                spec = m.group(2).strip()
                names.append(nm)
                if spec.startswith("{"):
                    kinds.append("nominal")
                    levels = [t.strip().strip("'\"")
                              for t in spec.strip("{}").split(",")]
                    domains.append(levels)
                elif spec.lower() in ("numeric", "real", "integer"):
                    kinds.append("numeric")
                    domains.append(None)
                elif spec.lower().startswith("date"):
                    kinds.append("date")
                    domains.append(None)
                else:
                    kinds.append("string")
                    domains.append(None)
    if not names:
        raise ValueError(f"{path}: no @attribute declarations found")
    ncol = len(names)
    cols: List[List[Optional[str]]] = [[] for _ in range(ncol)]
    import csv as _csv
    for row in _csv.reader(data_lines):
        if len(row) != ncol:
            row = (row + [None] * ncol)[:ncol]
        for i, tok in enumerate(row):
            t = tok.strip().strip("'\"") if tok is not None else None
            cols[i].append(None if t in (None, "?", "") else t)
    vecs = []
    for i in range(ncol):
        col = cols[i]
        if kinds[i] == "numeric":
            arr = np.asarray([np.nan if t is None else float(t)
                              for t in col])
            vecs.append(Vec.from_numpy(arr, mesh=mesh))
        elif kinds[i] == "date":
            # epoch millis in UTC (machine-independent; naive
            # .timestamp() would shift with the host timezone);
            # numeric tokens are taken as epoch millis already
            from datetime import datetime, timezone

            def _epoch(t):
                if t is None:
                    return np.nan
                for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%d",
                            "%m/%d/%Y", "%Y-%m-%dT%H:%M:%S"):
                    try:
                        dt = datetime.strptime(t, fmt).replace(
                            tzinfo=timezone.utc)
                        return dt.timestamp() * 1e3
                    except ValueError:
                        continue
                try:
                    return float(t)
                except ValueError:
                    return np.nan

            arr = np.asarray([_epoch(t) for t in col])
            vecs.append(Vec.from_numpy(arr, mesh=mesh))
        elif kinds[i] == "nominal":
            dom = domains[i]
            lut = {lvl: j for j, lvl in enumerate(dom)}
            codes = np.asarray([-1 if t is None else lut.get(t, -1)
                                for t in col], np.int32)
            vecs.append(Vec.from_numpy(codes, vtype=T_ENUM,
                                       domain=tuple(dom), mesh=mesh))
        else:
            arr = np.asarray([t if t is not None else None for t in col],
                             dtype=object)
            vecs.append(Vec.from_numpy(arr, mesh=mesh))
    return Frame(names, vecs, key=key or os.path.basename(path))


# ---------------------------------------------------------------- SVMLight

def parse_svmlight(path: str, mesh=None,
                   key: Optional[str] = None) -> Frame:
    """water/parser/SVMLightParser.java: `target idx:value ...` rows,
    1-based indices; absent features are ZERO (not NA) per the format.
    The TPU build densifies (no CSR on device — SURVEY §7.3)."""
    targets: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = 0
    with open(path, "r", errors="replace") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            targets.append(float(parts[0]))
            d: Dict[int, float] = {}
            for p in parts[1:]:
                k, _, v = p.partition(":")
                if k == "qid":     # optional ranking-group token
                    continue
                idx = int(k)
                if idx < 1:
                    raise ValueError(
                        f"{path}: svmlight indices are 1-based, got {idx}")
                d[idx - 1] = float(v)
                max_idx = max(max_idx, idx)
            rows.append(d)
    n = len(rows)
    X = np.zeros((n, max_idx), np.float32)
    for r, d in enumerate(rows):
        for j, v in d.items():
            X[r, j] = v
    names = ["C1"] + [f"C{j + 2}" for j in range(max_idx)]
    vecs = [Vec.from_numpy(np.asarray(targets, np.float64), mesh=mesh)]
    vecs += [Vec.from_numpy(X[:, j], mesh=mesh) for j in range(max_idx)]
    return Frame(names, vecs, key=key or os.path.basename(path))


# ------------------------------------------------------------ arrow-backed

def _arrow_table_to_frame(table, mesh=None,
                          key: Optional[str] = None) -> Frame:
    import pyarrow as pa
    names = []
    vecs = []
    for cname in table.column_names:
        col = table.column(cname)
        typ = col.type
        names.append(cname)
        if pa.types.is_dictionary(typ):
            combined = col.combine_chunks()
            if isinstance(combined, pa.ChunkedArray):
                combined = combined.chunk(0)
            dom = [str(v) for v in combined.dictionary.to_pylist()]
            idx = combined.indices.to_numpy(zero_copy_only=False)
            codes = np.where(np.isnan(idx.astype(np.float64)), -1,
                             idx).astype(np.int32) \
                if idx.dtype.kind == "f" else idx.astype(np.int32)
            vecs.append(Vec.from_numpy(codes, vtype=T_ENUM,
                                       domain=tuple(dom), mesh=mesh))
        elif (pa.types.is_string(typ) or pa.types.is_large_string(typ)):
            vals = np.asarray(col.to_pylist(), dtype=object)
            vecs.append(Vec.from_numpy(vals, mesh=mesh))
        elif pa.types.is_boolean(typ):
            arr = col.to_numpy(zero_copy_only=False).astype(np.float64)
            vecs.append(Vec.from_numpy(arr, mesh=mesh))
        elif pa.types.is_timestamp(typ) or pa.types.is_date(typ):
            arr = col.cast(pa.int64()).to_numpy(zero_copy_only=False)
            vecs.append(Vec.from_numpy(arr.astype(np.float64), mesh=mesh))
        else:
            arr = col.to_numpy(zero_copy_only=False)
            vecs.append(Vec.from_numpy(np.asarray(arr, np.float64),
                                       mesh=mesh))
    return Frame(names, vecs, key=key)


def parse_parquet(path: str, mesh=None,
                  key: Optional[str] = None) -> Frame:
    import pyarrow.parquet as pq
    table = pq.read_table(path)
    return _arrow_table_to_frame(table, mesh=mesh,
                                 key=key or os.path.basename(path))


def parse_orc(path: str, mesh=None, key: Optional[str] = None) -> Frame:
    import pyarrow.orc as po
    table = po.ORCFile(path).read()
    return _arrow_table_to_frame(table, mesh=mesh,
                                 key=key or os.path.basename(path))


class _AvroReader:
    """Pure-stdlib Avro Object Container File decoder
    (h2o-parsers/h2o-avro-parser AvroParser analog — the reference
    flattens top-level record fields into frame columns the same way).
    Supports null/deflate codecs and flat record schemas of primitives,
    2-branch nullable unions, and enum fields; nested records/arrays/
    maps raise, matching the reference parser's tabular restriction."""

    MAGIC = b"Obj\x01"

    def __init__(self, buf: bytes):
        self.b = buf
        self.pos = 0

    def _long(self) -> int:
        # zigzag varint
        shift, acc = 0, 0
        while True:
            byte = self.b[self.pos]
            self.pos += 1
            acc |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def _bytes(self) -> bytes:
        n = self._long()
        out = self.b[self.pos:self.pos + n]
        self.pos += n
        return out

    def _raw(self, n: int) -> bytes:
        out = self.b[self.pos:self.pos + n]
        self.pos += n
        return out

    def read_header(self):
        import json
        if self._raw(4) != self.MAGIC:
            raise ValueError("not an Avro object container file")
        meta = {}
        while True:
            n = self._long()
            if n == 0:
                break
            if n < 0:          # block with byte-size prefix
                self._long()
                n = -n
            for _ in range(n):
                k = self._bytes().decode()
                meta[k] = self._bytes()
        self.sync = self._raw(16)
        self.codec = meta.get("avro.codec", b"null").decode()
        self.schema = json.loads(meta["avro.schema"].decode())
        if self.schema.get("type") != "record":
            raise NotImplementedError(
                "only record-schema avro files parse to frames")
        return self.schema

    def _decode_value(self, typ):
        if isinstance(typ, dict):
            t = typ.get("type")
            if t == "enum":
                return typ["symbols"][self._long()]
            if t in ("record", "array", "map", "fixed"):
                raise NotImplementedError(
                    f"nested avro type '{t}' is not tabular")
            typ = t
        if isinstance(typ, list):            # union
            branch = typ[self._long()]
            return self._decode_value(branch)
        if typ == "null":
            return None
        if typ == "boolean":
            v = self.b[self.pos]
            self.pos += 1
            return bool(v)
        if typ in ("int", "long"):
            return self._long()
        if typ == "float":
            import struct
            return struct.unpack("<f", self._raw(4))[0]
        if typ == "double":
            import struct
            return struct.unpack("<d", self._raw(8))[0]
        if typ == "string":
            return self._bytes().decode()
        if typ == "bytes":
            return self._bytes()
        raise NotImplementedError(f"avro type '{typ}'")

    def records(self):
        import zlib
        fields = self.schema["fields"]
        while self.pos < len(self.b):
            n_obj = self._long()
            n_bytes = self._long()
            block = self._raw(n_bytes)
            if self._raw(16) != self.sync:
                raise ValueError("avro sync marker mismatch")
            if self.codec == "deflate":
                block = zlib.decompress(block, -15)
            elif self.codec != "null":
                raise NotImplementedError(
                    f"avro codec '{self.codec}' (null/deflate supported)")
            sub = _AvroReader(block)
            for _ in range(n_obj):
                yield {f["name"]: sub._decode_value(f["type"])
                       for f in fields}


def parse_avro(path: str, mesh=None, key: Optional[str] = None) -> Frame:
    with open(path, "rb") as f:
        rd = _AvroReader(f.read())
    try:
        rd.read_header()
        records = list(rd.records())
    except (IndexError, KeyError) as e:
        raise ValueError(f"{path}: truncated or malformed avro "
                         f"container file") from e
    if not records:
        raise ValueError(f"{path}: empty avro file")
    names = [f["name"] for f in rd.schema["fields"]]
    cols = {}
    for n in names:
        vals = [r.get(n) for r in records]
        if any(isinstance(v, (str, bytes)) for v in vals):
            cols[n] = np.asarray(
                ["" if v is None
                 else (v.decode("utf-8", "replace")
                       if isinstance(v, bytes) else str(v))
                 for v in vals])
        else:
            cols[n] = np.asarray([np.nan if v is None else float(v)
                                  for v in vals])
    return Frame.from_numpy(cols, mesh=mesh)


def parse_xls(path: str, mesh=None, key: Optional[str] = None) -> Frame:
    """xlsx ingest (water/parser/XlsxParser.java analog) — xlsx is a
    zip of XML sheets; decode sheet1 + sharedStrings with stdlib only.
    Legacy BIFF .xls still requires the absent xlrd and stays gated."""
    import re
    import xml.etree.ElementTree as ET
    import zipfile as zf
    if path.lower().endswith(".xls"):
        raise NotImplementedError(
            "legacy BIFF .xls needs the optional 'xlrd' package, which "
            "this image does not carry; convert to .xlsx or csv")
    ns = {"m": ("http://schemas.openxmlformats.org/spreadsheetml/2006/"
                "main")}
    with zf.ZipFile(path) as z:
        shared = []
        if "xl/sharedStrings.xml" in z.namelist():
            root = ET.fromstring(z.read("xl/sharedStrings.xml"))
            for si in root.findall("m:si", ns):
                shared.append("".join(t.text or ""
                                      for t in si.iter(
                                          "{%s}t" % ns["m"])))
        sheets = sorted(n for n in z.namelist()
                        if re.match(r"xl/worksheets/sheet\d*\.xml$", n))
        if not sheets:
            raise ValueError(f"{path}: xlsx archive has no worksheet "
                             f"part (xl/worksheets/sheet*.xml)")
        root = ET.fromstring(z.read(sheets[0]))
    rows = []
    for row in root.iter("{%s}row" % ns["m"]):
        cells = {}
        seq = 0
        for c in row.findall("m:c", ns):
            # c/@r is optional in OOXML — position falls back to the
            # next sequential column when the writer omits it
            ref = c.get("r") or ""
            mref = re.match(r"([A-Z]+)", ref)
            if mref:
                ci = 0
                for ch in mref.group(1):
                    ci = ci * 26 + (ord(ch) - 64)
            else:
                ci = seq + 1
            seq = ci
            v = c.find("m:v", ns)
            raw = v.text if v is not None else None
            if c.get("t") == "s" and raw is not None:
                try:
                    raw = shared[int(raw)]
                except (ValueError, IndexError) as e:
                    # a shared-string index that isn't an int or points
                    # past the table is a corrupt archive, not a value
                    raise ValueError(
                        f"{path}: malformed xlsx (shared-string index "
                        f"{raw!r} in cell {ref or seq}: {e})") from e
            elif c.get("t") == "inlineStr":
                raw = "".join(t.text or "" for t in c.iter(
                    "{%s}t" % ns["m"]))
            cells[ci - 1] = raw
        rows.append(cells)
    if not rows or all(not r for r in rows):
        # all-empty row dicts used to fall through to a bare
        # `max() arg is an empty sequence` — a sheet of empty <row>
        # elements is as empty as no rows at all
        raise ValueError(f"{path}: empty sheet")
    ncol = max(max(r) for r in rows if r) + 1
    header = [str(rows[0].get(i, f"C{i + 1}")) for i in range(ncol)]
    body = rows[1:]
    cols = {}
    for i, name in enumerate(header):
        vals = [r.get(i) for r in body]
        try:
            cols[name] = np.asarray(
                [np.nan if v in (None, "") else float(v) for v in vals])
        except (TypeError, ValueError):
            cols[name] = np.asarray(["" if v is None else str(v)
                                     for v in vals])
    return Frame.from_numpy(cols, mesh=mesh)


FORMAT_PARSERS = {
    "arff": parse_arff,
    "svmlight": parse_svmlight,
    "parquet": parse_parquet,
    "orc": parse_orc,
    "avro": parse_avro,
    "xls": parse_xls,
}
