from h2o3_tpu.ingest.parse import import_file, parse_setup, parse, upload_numpy

__all__ = ["import_file", "parse_setup", "parse", "upload_numpy"]
