"""URI-scheme-dispatched persist backends.

Reference: water/persist/PersistManager.java routes file/NFS/S3/GCS/
HDFS/HTTP by URI scheme (backends in h2o-persist-{s3,gcs,hdfs,http}).

TPU re-design: ingest always funnels through `localize(uri)` — remote
objects download to a local cache file, then the format parsers run on
the local copy (per-host byte-range reads). S3/GCS are gated on their
optional SDKs; http(s) uses the standard library. The seam matches the
reference's Persist.importFiles contract."""
from __future__ import annotations

import hashlib
import os
import tempfile
import urllib.parse
import urllib.request

_CACHE_DIR = os.path.join(tempfile.gettempdir(), "h2o3_tpu_persist")


def _cache_path(uri: str) -> str:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    h = hashlib.sha1(uri.encode()).hexdigest()[:16]
    base = os.path.basename(urllib.parse.urlparse(uri).path) or "object"
    return os.path.join(_CACHE_DIR, f"{h}_{base}")


def _fill_cache(out: str, download_to) -> None:
    """Download via a PROCESS-UNIQUE temp file then rename atomically:
    a partial or concurrently-interleaved download must never land at
    the final cache path."""
    fd, tmp = tempfile.mkstemp(dir=_CACHE_DIR, suffix=".part")
    os.close(fd)
    try:
        download_to(tmp)
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def localize(uri: str) -> str:
    """Return a local filesystem path for `uri`, downloading if remote."""
    scheme = urllib.parse.urlparse(uri).scheme.lower()
    if scheme in ("", "file"):
        return uri[len("file://"):] if uri.startswith("file://") else uri
    if scheme in ("http", "https"):
        out = _cache_path(uri)
        if not os.path.exists(out):
            _fill_cache(out, lambda tmp: urllib.request.urlretrieve(
                uri, tmp))
        return out
    if scheme == "s3":
        try:
            import boto3
        except ImportError as e:
            raise NotImplementedError(
                "s3:// import needs the optional 'boto3' package "
                "(h2o-persist-s3 analog is gated on it)") from e
        out = _cache_path(uri)
        if not os.path.exists(out):
            p = urllib.parse.urlparse(uri)
            _fill_cache(out, lambda tmp: boto3.client("s3").download_file(
                p.netloc, p.path.lstrip("/"), tmp))
        return out
    if scheme == "gs":
        try:
            from google.cloud import storage
        except ImportError as e:
            raise NotImplementedError(
                "gs:// import needs the optional 'google-cloud-storage' "
                "package (h2o-persist-gcs analog is gated on it)") from e
        out = _cache_path(uri)
        if not os.path.exists(out):
            p = urllib.parse.urlparse(uri)
            _fill_cache(out, lambda tmp: storage.Client().bucket(
                p.netloc).blob(p.path.lstrip("/")).download_to_filename(
                tmp))
        return out
    if scheme == "hdfs":
        raise NotImplementedError(
            "hdfs:// import needs a pyarrow HadoopFileSystem environment "
            "(h2o-persist-hdfs analog; mount or copy the file locally)")
    raise ValueError(f"unsupported URI scheme '{scheme}' in {uri}")
