"""URI-scheme-dispatched persist backends.

Reference: water/persist/PersistManager.java routes file/NFS/S3/GCS/
HDFS/HTTP by URI scheme (backends in h2o-persist-{s3,gcs,hdfs,http}).

TPU re-design: ingest always funnels through `localize(uri)` — remote
objects download to a local cache file, then the format parsers run on
the local copy (per-host byte-range reads). s3/gs/hdfs ride pyarrow.fs
(S3FileSystem/GcsFileSystem/HadoopFileSystem — one dependency this
image ships, replacing the reference's three persist jars); http(s)
uses the standard library. `_remote_fs` is the injection seam the
persist tests stub with pyarrow's mock filesystem. The seam matches the
reference's Persist.importFiles contract."""
from __future__ import annotations

import hashlib
import os
import tempfile
import urllib.parse
import urllib.request

_CACHE_DIR = os.path.join(tempfile.gettempdir(), "h2o3_tpu_persist")


def _cache_path(uri: str) -> str:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    h = hashlib.sha1(uri.encode()).hexdigest()[:16]
    base = os.path.basename(urllib.parse.urlparse(uri).path) or "object"
    return os.path.join(_CACHE_DIR, f"{h}_{base}")


def _fill_cache(out: str, download_to) -> None:
    """Download via a PROCESS-UNIQUE temp file then rename atomically:
    a partial or concurrently-interleaved download must never land at
    the final cache path. The whole download attempt rides the shared
    retry/backoff helper (bounded attempts, jittered exponential
    backoff) so a flaky remote store — a reset connection, a 5xx burst —
    retries instead of failing the whole parse; each attempt restarts
    from its own temp file, so a partial read never survives."""
    from h2o3_tpu import faults
    from h2o3_tpu.resilience import is_transient_io, retry_transient

    def _attempt():
        fd, tmp = tempfile.mkstemp(dir=_CACHE_DIR, suffix=".part")
        os.close(fd)
        try:
            if faults.ACTIVE:
                faults.check("persist", key=out)
            download_to(tmp)
            os.replace(tmp, out)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    retry_transient(_attempt, site="persist.localize",
                    classify=is_transient_io, base_delay_s=0.2)


def _remote_fs(uri: str):
    """(filesystem, path) for a remote object URI via pyarrow.fs —
    the PersistS3/PersistGcs/PersistHdfs analogs collapse into arrow's
    own backends. Kept as a module-level seam so tests can monkeypatch
    it with pyarrow's mock filesystem (VERDICT r4 weak-8: the remote
    persist paths must be CI-exercised, not import-gated)."""
    p = urllib.parse.urlparse(uri)
    scheme = p.scheme.lower()
    # opt-in unsigned access (public buckets); the default leaves
    # pyarrow's normal credential chain (env, config files, instance
    # roles) intact — the chains boto3/google-cloud-storage honored
    anon = os.environ.get("H2O3_PERSIST_ANONYMOUS", "") == "1"
    try:
        from pyarrow import fs as pafs
        if scheme == "s3":
            # explicit region: construction must not do a network lookup
            return (pafs.S3FileSystem(
                region=os.environ.get("AWS_DEFAULT_REGION", "us-east-1"),
                anonymous=anon),
                p.netloc + p.path)
        if scheme in ("gs", "gcs"):
            return (pafs.GcsFileSystem(anonymous=anon),
                    p.netloc + p.path)
        if scheme == "hdfs":
            return (pafs.HadoopFileSystem(
                p.hostname or "default", p.port or 8020),
                p.path)
    except (OSError, ImportError) as e:
        raise NotImplementedError(
            f"{scheme}:// backend unavailable in this environment "
            f"(pyarrow.fs: {e})") from e
    raise ValueError(f"no remote filesystem for scheme '{scheme}'")


def localize(uri: str) -> str:
    """Return a local filesystem path for `uri`, downloading if remote."""
    scheme = urllib.parse.urlparse(uri).scheme.lower()
    if scheme in ("", "file"):
        return uri[len("file://"):] if uri.startswith("file://") else uri
    if scheme in ("http", "https"):
        out = _cache_path(uri)
        if not os.path.exists(out):
            _fill_cache(out, lambda tmp: urllib.request.urlretrieve(
                uri, tmp))
        return out
    if scheme in ("s3", "gs", "gcs", "hdfs"):
        out = _cache_path(uri)
        if not os.path.exists(out):
            f, path = _remote_fs(uri)

            def dl(tmp, _f=f, _path=path):
                with _f.open_input_stream(_path) as src, \
                        open(tmp, "wb") as dst:
                    while True:
                        block = src.read(8 << 20)
                        if not block:
                            break
                        dst.write(block)
            _fill_cache(out, dl)
        return out
    raise ValueError(f"unsupported URI scheme '{scheme}' in {uri}")
