"""Per-chunk streamed host→device ingest transfer.

The PR-1/PR-2 pipeline tokenized every byte-range chunk, merged full
columns host-side (``np.concatenate`` in ingest/chunk.py), and only then
issued one batched DMA per dtype group — so the whole transfer waited on
the slowest tokenize worker and the merge paid a full extra pass over
every numeric byte. This module closes that ROADMAP gap: as each chunk's
numeric/time columns finish encoding, its float32 pack matrix is
``device_put`` IMMEDIATELY (bounded in-flight depth, double-buffer
style), and the sharded column arrays are assembled DEVICE-side with one
``jnp.concatenate`` — the host-side full-column merge disappears for
numeric/time groups.

Enum columns stream too (ROADMAP ingest tail): each chunk's CHUNK-LOCAL
int32 codes ride the same f32 pack matrix (exact — codes are bounded by
MAX_ENUM_CARDINALITY = 1M < 2^24, NA = -1), so their H2D overlaps the
tokenize window like the numeric lanes and is attributed to the same
counters. Only the DOMAIN UNION stays host-side (it is inherently
global, and domains are tiny next to codes); the code remap into the
union happens device-side at assembly via a per-chunk-sectioned LUT
gather — NOT numpy's trailing ``lut[-1]`` NA trick, which does not port
(JAX clamps negative gather indices), but a +1-shifted LUT whose slot 0
per chunk section holds ENUM_NA. Chunks that blow a chunk-local
cardinality cap (T_STR surprise) or whose union exceeds
MAX_ENUM_CARDINALITY condemn the column to the host merge
(``fallback_cols``), which promotes it to string exactly as before.
String columns never stream.

Host shadows stay exact: time columns concatenate their int64 millis
(8B/row, the only remaining host concat), integral columns beyond
float32's 2^24 mantissa keep the float64 host copy the Vec contract
requires, and wide-int columns (an ``exact`` int64 shadow anywhere)
fall back to the host merge entirely — their device value must come
from the resolved int64, not a chunkwise f64 rounding.

Equivalence: per-chunk f64→f32 conversion followed by device concat is
elementwise identical to the old full-column concat + one conversion;
tests/test_transfer_budget.py asserts the parse-equivalence.

Shard-aligned placement (multi-data-shard meshes): streaming used to
disable itself when the mesh's data axis was wider than one device —
every chunk's put landed on device 0 and the final reshard staged the
whole numeric group there. Now each chunk's H2D is issued to its HOME
data-shard device (chunk order is row order for a byte-range CSV
fan-out, so ``DataParallelPartitioner.chunk_home`` maps chunks to the
shard that will own their rows), and assembly builds the global sharded
array with ``jax.make_array_from_single_device_arrays`` — only the
fragments straddling a shard boundary move device-to-device. Per-shard
placement/overlap stats land in ``shard_profile()`` →
``LAST_PROFILE['h2d_shards']``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from h2o3_tpu.frame.vec import ENUM_NA, T_ENUM, T_INT, T_REAL, T_TIME, Vec

# max chunk pack matrices with an un-awaited device_put in flight: chunk
# k+1 tokenizes/packs while chunk k's DMA drains, chunk k+2 waits — the
# double-buffer bound that keeps pinned-host pressure flat
_INFLIGHT_DEPTH = 2

_EXACT_F32_BOUND = float(1 << 24)   # f32 mantissa: integral values above
                                    # this need the f64 host shadow


class PrepackedChunk:
    """The f32 pack matrix of one chunk's streamed columns, built IN THE
    WORKER thread (bulk numpy ops release the GIL and run concurrently)
    instead of serialized through the tokenize consumer. ``fmax[j]`` is
    the column's finite |max| (feeds the host-shadow decision; -inf for
    time lanes, whose shadow is the int64 ms kept on the EncodedColumn)."""
    __slots__ = ("mat", "fmax")

    def __init__(self, mat, fmax):
        self.mat = mat
        self.fmax = fmax


def prepack_chunk(col_ids, cols) -> PrepackedChunk:
    """Pack ``cols[i]`` for i in ``col_ids`` into the [rows, C] float32
    streaming matrix + per-lane finite |max| — called by the byte-range
    worker right after the encode, so the pack rides the worker pool's
    parallelism and ``ChunkDeviceStreamer.add`` does bookkeeping only."""
    import warnings
    rows = len(cols[col_ids[0]].data) if col_ids else 0
    mat = np.empty((rows, len(col_ids)), np.float32)
    fmax = np.full(len(col_ids), -np.inf)
    for j, i in enumerate(col_ids):
        c = cols[i]
        if c.vtype == T_TIME:
            ms = np.asarray(c.data, dtype=np.int64)
            # same arithmetic as Vec.from_numpy's time path: f64
            # seconds, converted to f32 by the pack assignment
            mat[:, j] = np.where(ms == Vec.TIME_NA, np.nan, ms / 1000.0)
            continue
        if c.vtype == T_ENUM:
            # chunk-LOCAL int32 codes as exact f32 (|code| < 2^24 by the
            # MAX_ENUM_CARDINALITY cap; NA = -1); remap to the global
            # domain happens device-side at assembly
            mat[:, j] = c.data
            continue
        if c.data.dtype == object:
            # a declared-enum lane that blew the chunk-local cardinality
            # cap and came back as strings: lane is dead weight, add()
            # condemns the column to the host merge
            mat[:, j] = np.nan
            continue
        f64 = c.data
        mat[:, j] = f64              # assignment converts f64 -> f32
        # duck-typed column contract: fmax is optional (the native
        # encoder sets it; test fakes and the Python fallback may not)
        cmax = getattr(c, "fmax", None)
        if cmax is not None:         # encoder already reduced it
            fmax[j] = cmax
        elif f64.size:
            finite = np.isfinite(f64)
            if finite.any():
                with np.errstate(invalid="ignore"), \
                        warnings.catch_warnings():
                    warnings.simplefilter("ignore", RuntimeWarning)
                    fmax[j] = float(np.abs(f64[finite]).max())
    return PrepackedChunk(mat, fmax)


class ChunkDeviceStreamer:
    """Streams one parse's numeric/time columns to device per chunk.

    ``add(chunk_idx, cols)`` is called from the tokenize consumer as
    each byte-range worker completes (any order); ``assemble`` blocks on
    the remaining transfers and returns finished Vecs keyed by original
    column index. Columns that turn out to need the host merge (wide-int
    ``exact`` shadows) are reported in ``fallback_cols`` instead."""

    # above this input size the CPU backend goes back to per-chunk puts:
    # the final single-copy stops fitting cache and the per-chunk
    # dispatch overhead amortizes, while per-chunk puts hide under the
    # (now much longer) tokenize window
    _HOST_ASSEMBLE_MAX_BYTES = 256 << 20

    def __init__(self, col_ids: List[int], col_types: List[str],
                 n_chunks: int, mesh, input_bytes: Optional[int] = None):
        from h2o3_tpu.parallel.mesh import n_data_shards, partitioner
        self.col_ids = list(col_ids)          # original column indices
        self.col_types = col_types            # full setup.column_types
        self.n_chunks = n_chunks
        self.mesh = mesh
        self.part = partitioner(mesh)
        self.nd = n_data_shards(mesh)
        # host-assemble mode (ISSUE 14): on a single-data-shard CPU-
        # backend mesh there is no PCIe DMA to hide — for SMALL inputs
        # the per-chunk jax.device_put dispatch (milliseconds each) and
        # the per-chunk-count concat compiles cost more than the copies
        # they organize, so chunks pack host-side (still under the
        # tokenize window), assemble into per-column arrays and upload
        # once with zero compiled programs. Large inputs keep per-chunk
        # puts even on CPU (the fixed overhead amortizes and the copy
        # hides under tokenize); accelerator meshes always keep the
        # streamed DMA, which is the whole point of this class.
        try:
            dev0 = next(iter(mesh.devices.flat))
            self.host_assemble = (
                self.nd == 1 and dev0.platform == "cpu"
                and (input_bytes is None
                     or input_bytes <= self._HOST_ASSEMBLE_MAX_BYTES))
        except (AttributeError, StopIteration):
            self.host_assemble = False
        self._home: Dict[int, int] = {}       # chunk_idx -> home data shard
        # per-shard placement accounting (shard_profile)
        self._shard_bytes = [0] * self.nd
        self._shard_chunks = [0] * self.nd
        self._shard_hidden_s = [0.0] * self.nd
        self._shard_assemble_s = [0.0] * self.nd
        self._aligned_rows = 0                # rows landing on their home
        self._moved_rows = 0                  # boundary fragments moved D2D
        self._devs: Dict[int, object] = {}    # chunk_idx -> [rows_c, C] dev
        self._rows: Dict[int, int] = {}
        self._inflight: deque = deque()
        self._time_ms: Dict[int, Dict[int, np.ndarray]] = {}  # col -> chunk -> ms
        self._f64: Dict[int, Dict[int, np.ndarray]] = {}      # shadow candidates
        # per-column finite |max| reduction — gates the (rare) host-shadow
        # decision, which is then delegated to _numeric_host_copy on the
        # concatenated column so the rule stays identical to the merge path
        self._fmax: Dict[int, float] = {i: float("-inf") for i in col_ids}
        self._exact: set = set()              # cols forced to host merge
        # enum streaming: chunk-local domains (col -> chunk -> labels);
        # the union + device remap happen at assemble. Columns whose
        # chunks carry a T_STR surprise (chunk-local cardinality blowout)
        # or whose union blows MAX_ENUM_CARDINALITY join the host merge.
        self._domains: Dict[int, Dict[int, List[str]]] = {}
        self._enum_fb: set = set()            # enum cols forced to host merge
        self.add_seconds = 0.0                # transfer time hidden under tokenize
        self.assemble_seconds = 0.0           # visible (post-tokenize) time
        self.h2d_bytes = 0
        self._discarded = False

    # -- per-chunk feed --------------------------------------------------

    def add(self, chunk_idx: int, cols, pack: "PrepackedChunk" = None
            ) -> None:
        """Register this chunk's f32 pack and issue its (async) DMA.
        ``pack`` is the worker-built :class:`PrepackedChunk` (the normal
        streamed path); without one (e.g. a fallback range re-parsed in
        Python joining the stream late) the pack is built here."""
        import jax
        from h2o3_tpu import telemetry
        if self._discarded:
            return
        t0 = time.perf_counter()
        if pack is None:
            pack = prepack_chunk(self.col_ids, cols)
        mat = pack.mat
        rows_c = mat.shape[0]
        # bookkeeping per column: time-ms host shadows, wide-int exact
        # condemnation, the f64 references the (rare) host-shadow concat
        # reads, and the per-column finite |max| reduction — the heavy
        # pack/convert/stat work already ran in the worker thread
        for j, i in enumerate(self.col_ids):
            c = cols[i]
            if c.vtype == T_TIME:
                self._time_ms.setdefault(i, {})[chunk_idx] = np.asarray(
                    c.data, dtype=np.int64)
                continue
            if self.col_types[i] == T_ENUM:
                if c.vtype != T_ENUM:
                    # chunk blew the chunk-local cardinality cap → the
                    # merged column promotes to string; host merge owns it
                    self._enum_fb.add(i)
                elif i not in self._enum_fb:
                    self._domains.setdefault(i, {})[chunk_idx] = list(
                        c.domain or ())
                continue
            if i in self._exact:
                continue
            if c.exact is not None:
                self._exact.add(i)
            if pack.fmax[j] > self._fmax[i]:
                self._fmax[i] = float(pack.fmax[j])
            self._f64.setdefault(i, {})[chunk_idx] = c.data
        self._rows[chunk_idx] = rows_c or 0
        home = self.part.chunk_home(chunk_idx, self.n_chunks)
        self._home[chunk_idx] = home
        if self.host_assemble:
            # CPU-backend fast path: the packed matrix stays host-side;
            # assemble() concatenates and uploads ONCE (per-chunk
            # dispatch + per-chunk-count concat compiles disappear)
            self._devs[chunk_idx] = mat
            self._shard_bytes[home] += mat.nbytes
            self._shard_chunks[home] += 1
            dt = time.perf_counter() - t0
            self.add_seconds += dt
            self._shard_hidden_s[home] += dt
            return
        # shard-aligned placement: the chunk's DMA targets its HOME
        # data-shard device (chunk order == row order for byte ranges),
        # so on a wide mesh the upload already lands ~where the rows
        # will live; single-shard meshes keep the default device.
        # A transient chunk-upload failure retries with backoff instead
        # of failing the whole parse (the fault-matrix test drives this)
        from h2o3_tpu.resilience import resilient_device_put
        target = self.part.home_device(home) if self.nd > 1 else None
        dev = resilient_device_put(mat, target, pipeline="ingest")
        telemetry.record_h2d(mat.nbytes, pipeline="ingest")
        self.h2d_bytes += mat.nbytes
        self._shard_bytes[home] += mat.nbytes
        self._shard_chunks[home] += 1
        self._devs[chunk_idx] = dev
        self._inflight.append(dev)
        while len(self._inflight) > _INFLIGHT_DEPTH:
            # double-buffer bound: block on the OLDEST transfer so at
            # most _INFLIGHT_DEPTH pack matrices are pinned at once
            jax.block_until_ready(  # h2o3-lint: allow[transfer-seam,host-sync-hot-loop] deliberate depth bound: blocking on the OLDEST DMA is the double-buffer backpressure
                self._inflight.popleft())
        dt = time.perf_counter() - t0
        self.add_seconds += dt
        self._shard_hidden_s[home] += dt

    def discard(self) -> None:
        """Drop every streamed chunk. NO normal path calls this anymore:
        the fallback seam is range-scoped (a declined range re-parses
        alone and ``add``s late; its neighbors' uploads survive), where
        it used to blanket-discard the whole stream on one declined
        range. Kept for abnormal teardown — and any use is VISIBLE:
        the thrown-away upload bytes land in
        ``h2o3_ingest_h2d_bytes_discarded_total``, so silent re-upload
        can't hide."""
        from h2o3_tpu import telemetry
        if self.h2d_bytes:
            telemetry.counter(
                "h2o3_ingest_h2d_bytes_discarded_total",
                help="streamed ingest H2D bytes discarded before "
                     "assembly (wasted upload work)").inc(self.h2d_bytes)
        self._discarded = True
        self._devs.clear()
        self._inflight.clear()
        self._time_ms.clear()
        self._f64.clear()
        self._domains.clear()

    # -- final assembly --------------------------------------------------

    @property
    def fallback_cols(self) -> set:
        """Columns the host merge must finish: wide-int ``exact``
        shadows (device value must come from the resolved int64) and
        enum columns with a string surprise or a domain-union blowout."""
        return set(self._exact) | set(self._enum_fb)

    def _resolve_enum_unions(self) -> Dict[int, tuple]:
        """Union every streamed enum column's chunk-local domains (the
        host half of _merge_enum — domains are tiny next to codes).
        Returns ``{col: (union, [per-chunk domains in row order])}``;
        columns whose union blows MAX_ENUM_CARDINALITY move to
        ``_enum_fb`` instead (the host merge promotes them to string)."""
        from h2o3_tpu.ingest.chunk import MAX_ENUM_CARDINALITY
        unions: Dict[int, tuple] = {}
        for i in self.col_ids:
            if self.col_types[i] != T_ENUM or i in self._enum_fb:
                continue
            per = self._domains.get(i, {})
            doms = [per[k] for k in sorted(per)]
            union = sorted(set().union(*doms)) if doms else []
            if len(union) > MAX_ENUM_CARDINALITY:
                self._enum_fb.add(i)
                continue
            unions[i] = (union, doms)
        return unions

    def _enum_remap_aux(self, union, doms):
        """Host-side LUT for the device-side enum remap: one section per
        chunk, ``1 + len(domain)`` slots each, slot 0 = ENUM_NA. A local
        code ``c`` in chunk ``k`` resolves at ``lut[base[k] + 1 + c]`` —
        the +1 shift serves the NA code (-1) as slot 0, because JAX
        clamps negative gather indices (numpy's trailing ``lut[-1]`` NA
        trick in _merge_enum does NOT port). Returns (lut, base) or
        (None, None) when every chunk already matches the union (codes
        are global already — _merge_enum's fast path)."""
        if all(d == union for d in doms):
            return None, None
        gidx = {lab: g for g, lab in enumerate(union)}
        luts, base, off = [], [], 0
        for d in doms:
            base.append(off)
            sec = np.empty(1 + len(d), np.int32)
            sec[0] = ENUM_NA
            for j, lab in enumerate(d):
                sec[1 + j] = gidx[lab]
            luts.append(sec)
            off += len(sec)
        return np.concatenate(luts), np.asarray(base, np.int32)

    def _host_shadow(self, i: int):
        """Exact float64 host copy when the column needs one — decided by
        THE SAME rule as the merge path (frame/vec.py _numeric_host_copy
        over the whole column), so streamed and host-merge parses agree
        bit-for-bit on Vec.to_numpy. The concat only happens for the rare
        columns whose finite |max| crosses the f32 mantissa bound; the
        per-chunk f64 stays referenced by the caller's results anyway."""
        if not (np.isfinite(self._fmax[i])
                and self._fmax[i] > _EXACT_F32_BOUND):
            return None
        from h2o3_tpu.frame.vec import _numeric_host_copy
        parts = [self._f64[i][k] for k in sorted(self._f64[i])]
        full = parts[0] if len(parts) == 1 else np.concatenate(parts)
        return _numeric_host_copy(full, self.col_types[i])

    def _assemble_sharded(self, nrow: int, C: int):
        """Multi-data-shard assembly: per shard, gather the chunk
        fragments covering its row range (chunks already live on their
        home device — only boundary-straddling fragments move D2D),
        concatenate ON the shard's device, then stitch the global
        row-sharded array with ``jax.make_array_from_single_device_arrays``
        (no single-device staging of the whole numeric group)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from h2o3_tpu import telemetry
        from h2o3_tpu.parallel.mesh import DATA_AXIS, padded_len
        order = sorted(self._devs)
        offs: Dict[int, int] = {}
        off = 0
        for k in order:
            offs[k] = off
            off += self._rows[k]
        plen = padded_len(nrow, self.mesh)
        S = plen // self.nd
        by_dev = {}
        for d in range(self.nd):
            td0 = time.perf_counter()
            dev_d = self.part.home_device(d)
            lo, hi = d * S, (d + 1) * S
            parts = []
            for k in order:
                ck_lo = offs[k]
                ck_hi = ck_lo + self._rows[k]
                s, e = max(lo, ck_lo), min(hi, ck_hi)
                if s >= e:
                    continue
                piece = self._devs[k][s - ck_lo: e - ck_lo]
                if self._home[k] == d:
                    self._aligned_rows += e - s
                else:
                    # boundary fragment (or a home misprediction from
                    # uneven rows-per-byte): one D2D move, not H2D —
                    # counted (ISSUE 8): these moves used to escape the
                    # transfer counters, hiding a chunk-home mismap
                    self._moved_rows += e - s
                    telemetry.record_d2d(piece.nbytes, pipeline="ingest")
                    piece = jax.device_put(piece, dev_d)  # h2o3-lint: allow[transfer-seam] D2D boundary-fragment move, counted via record_d2d above
                parts.append(piece)
            if hi > nrow:          # pad tail rows of the last shard(s)
                pad = np.full((hi - max(lo, nrow), C), np.nan, np.float32)
                telemetry.record_h2d(pad.nbytes, pipeline="ingest")
                parts.append(jax.device_put(pad, dev_d))  # h2o3-lint: allow[transfer-seam] pad-tail upload, counted via record_h2d above
            shard = (parts[0] if len(parts) == 1
                     else jnp.concatenate(parts, axis=0))
            shard = jax.device_put(shard, dev_d)  # h2o3-lint: allow[transfer-seam] blessed commit site: on-device concat pinned to the shard's home device (D2D, no host bytes)
            for dev in self.part.shard_devices(d):  # model-axis replicas
                if dev != dev_d:
                    telemetry.record_d2d(shard.nbytes, pipeline="ingest")
                by_dev[dev] = (shard if dev == dev_d
                               else jax.device_put(shard, dev))  # h2o3-lint: allow[transfer-seam] model-axis replica copy (D2D), counted via record_d2d above
            self._shard_assemble_s[d] += time.perf_counter() - td0
        self._devs.clear()
        sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        bufs = [by_dev[d] for d in sharding.addressable_devices]
        return jax.make_array_from_single_device_arrays(
            (plen, C), sharding, bufs)

    def shard_profile(self) -> List[Dict[str, object]]:
        """Per-data-shard placement stats for LAST_PROFILE /
        the ``h2o3_ingest_h2d_overlap_ratio{shard=}`` gauges."""
        out = []
        for d in range(self.nd):
            tot = self._shard_hidden_s[d] + self._shard_assemble_s[d]
            out.append({
                "shard": d, "chunks": self._shard_chunks[d],
                "h2d_bytes": self._shard_bytes[d],
                "hidden_s": round(self._shard_hidden_s[d], 4),
                "assemble_s": round(self._shard_assemble_s[d], 4),
                "overlap_ratio": (round(self._shard_hidden_s[d] / tot, 4)
                                  if tot > 0 else None)})
        return out

    @property
    def aligned_row_ratio(self) -> Optional[float]:
        """Share of streamed rows whose chunk H2D already landed on the
        row's final home shard (the rest moved D2D at assembly)."""
        tot = self._aligned_rows + self._moved_rows
        return self._aligned_rows / tot if tot else None

    def _assemble_host(self, nrow: int) -> Dict[int, Vec]:
        """CPU-backend assembly (``host_assemble``): per-column host
        concat of the packed chunk matrices + ONE batched ``device_put``
        of every column. No per-chunk puts, no device concat, no column
        slicing — a cold parse compiles ZERO XLA programs here, which on
        the CPU backend cost more than the byte copies they organized
        (ISSUE 14 measured ~0.2 s of compiles on a 0.4 s parse)."""
        import jax
        from h2o3_tpu import telemetry
        from h2o3_tpu.parallel.mesh import padded_len
        from h2o3_tpu.resilience import resilient_device_put
        order = sorted(self._devs)
        mats = [self._devs.pop(k) for k in order]
        unions = self._resolve_enum_unions()
        plen = padded_len(nrow, self.mesh)
        pad = (np.full(plen - nrow, np.nan, np.float32)
               if plen > nrow else None)
        keep = [(j, i) for j, i in enumerate(self.col_ids)
                if i not in self._exact and i not in self._enum_fb]
        host_cols = []
        for j, i in keep:
            if self.col_types[i] == T_ENUM:
                # chunk-local f32 codes → int32, remap into the union
                # with the sectioned LUT (exact _merge_enum semantics),
                # pad with ENUM_NA; uploads int32 in the same batch
                union, doms = unions[i]
                lut, base = self._enum_remap_aux(union, doms)
                parts = []
                for k, m in enumerate(mats):
                    codes = m[:, j].astype(np.int32)
                    parts.append(codes if lut is None
                                 else lut[base[k] + 1 + codes])
                if pad is not None:
                    parts.append(np.full(plen - nrow, ENUM_NA, np.int32))
                host_cols.append(np.concatenate(parts) if len(parts) > 1
                                 else parts[0])
                continue
            parts = [m[:, j] for m in mats]
            if pad is not None:
                parts.append(pad)
            host_cols.append(np.concatenate(parts) if len(parts) > 1
                             else parts[0])
        del mats
        nbytes = sum(c.nbytes for c in host_cols)
        telemetry.record_h2d(nbytes, pipeline="ingest")
        self.h2d_bytes += nbytes
        self._shard_bytes[0] += nbytes
        devs = resilient_device_put(host_cols, self.part.data_sharding,
                                    pipeline="ingest")
        out: Dict[int, Vec] = {}
        for (j, i), col in zip(keep, devs):
            vt = self.col_types[i]
            if vt == T_TIME:
                parts = [self._time_ms[i][k] for k in sorted(self._time_ms[i])]
                ms = parts[0] if len(parts) == 1 else np.concatenate(parts)
                out[i] = Vec(col, nrow, T_TIME, host_data=ms)
            elif vt == T_ENUM:
                out[i] = Vec(col, nrow, T_ENUM, domain=tuple(unions[i][0]))
            else:
                out[i] = Vec(col, nrow, vt, host_data=self._host_shadow(i))
        self._f64.clear()
        jax.block_until_ready(devs)  # h2o3-lint: allow[transfer-seam,host-sync-hot-loop] assemble() contract: callers receive finished Vecs, this is the one visible barrier the overlap metric measures
        return out

    def assemble(self) -> Dict[int, Vec]:
        """Block on outstanding DMAs, concatenate chunk matrices on
        device, pad + reshard to the mesh row layout, and return one Vec
        per streamed column (minus ``fallback_cols``)."""
        import jax
        import jax.numpy as jnp
        from h2o3_tpu.parallel.mesh import padded_len, partitioner
        assert not self._discarded
        nrow = sum(self._rows.values())
        t0 = time.perf_counter()
        C = len(self.col_ids)
        if self.host_assemble:
            out = self._assemble_host(nrow)
            self.assemble_seconds = time.perf_counter() - t0
            from h2o3_tpu.telemetry import costmodel
            costmodel.record(
                "ingest.assemble",
                costmodel.Cost(0.0, float(self.h2d_bytes)),
                seconds=sum(self._shard_hidden_s) + self.assemble_seconds)
            return out
        if self.nd > 1:
            full = self._assemble_sharded(nrow, C)
            self._inflight.clear()
        else:
            devs = [self._devs.pop(k) for k in sorted(self._devs)]
            self._inflight.clear()
            full = (devs[0] if len(devs) == 1
                    else jnp.concatenate(devs, axis=0))
            # drop the per-chunk refs as soon as the concat is dispatched
            # — holding them through the reshard would keep THREE copies
            # of the numeric group live (chunks + concat + sharded)
            # instead of two, an avoidable dataset-sized device-memory
            # transient
            del devs
            plen = padded_len(nrow, self.mesh)
            if plen > nrow:
                full = jnp.concatenate(
                    [full, jnp.full((plen - nrow, C), jnp.nan, jnp.float32)],
                    axis=0)
            full = jax.device_put(  # h2o3-lint: allow[transfer-seam] blessed commit site: reshard of already-device-resident data (D2D, no host bytes)
                full, partitioner(self.mesh).data_sharding)
        from h2o3_tpu import telemetry
        from h2o3_tpu.frame.vec import split_columns
        from h2o3_tpu.resilience import resilient_device_put
        unions = self._resolve_enum_unions()
        cols = split_columns(full, C)   # one compiled dispatch, not C
        out: Dict[int, Vec] = {}
        cv_dev = None                   # row -> chunk index, built lazily
        for j, i in enumerate(self.col_ids):
            if i in self._exact or i in self._enum_fb:
                continue
            col = cols[j]
            vt = self.col_types[i]
            if vt == T_TIME:
                parts = [self._time_ms[i][k] for k in sorted(self._time_ms[i])]
                ms = parts[0] if len(parts) == 1 else np.concatenate(parts)
                out[i] = Vec(col, nrow, T_TIME, host_data=ms)
            elif vt == T_ENUM:
                union, doms = unions[i]
                lut, base = self._enum_remap_aux(union, doms)
                # NaN pad rows -> -1 -> slot 0 of chunk 0's LUT section
                # (ENUM_NA) — same sentinel the int32 Vec pad contract uses
                codes = jnp.nan_to_num(
                    col, nan=float(ENUM_NA)).astype(jnp.int32)
                if lut is not None:
                    if cv_dev is None:
                        ordr = sorted(self._rows)
                        cv = np.zeros(full.shape[0], np.int32)
                        cv[:nrow] = np.repeat(
                            np.arange(len(ordr), dtype=np.int32),
                            [self._rows[k] for k in ordr])
                        telemetry.record_h2d(cv.nbytes, pipeline="ingest")
                        self.h2d_bytes += cv.nbytes
                        cv_dev = resilient_device_put(
                            cv, self.part.data_sharding, pipeline="ingest")
                    telemetry.record_h2d(lut.nbytes + base.nbytes,
                                         pipeline="ingest")
                    self.h2d_bytes += lut.nbytes + base.nbytes
                    lut_dev = resilient_device_put(lut, None,
                                                   pipeline="ingest")
                    base_dev = resilient_device_put(base, None,
                                                    pipeline="ingest")
                    codes = jnp.take(lut_dev,
                                     codes + 1 + jnp.take(base_dev, cv_dev))
                out[i] = Vec(codes, nrow, T_ENUM, domain=tuple(union))
            else:
                out[i] = Vec(col, nrow, vt, host_data=self._host_shadow(i))
        self._f64.clear()
        jax.block_until_ready(full)  # h2o3-lint: allow[transfer-seam] assemble() contract: callers receive finished Vecs, this is the one visible barrier the overlap metric measures
        self.assemble_seconds = time.perf_counter() - t0
        # performance accounting (ISSUE 11): the ingest assembly is
        # bandwidth work — zero flops, the streamed columns' bytes over
        # the observed transfer wall (per-shard hidden time + the
        # visible assemble barrier). Memory-bound by construction; the
        # achieved_bytes/s is the number to trend against HBM peak.
        from h2o3_tpu.telemetry import costmodel
        costmodel.record(
            "ingest.assemble",
            costmodel.Cost(0.0, float(self.h2d_bytes)),
            seconds=sum(self._shard_hidden_s) + self.assemble_seconds)
        return out

    # NOTE on the overlap metric: parse.py is the single source of truth
    # for h2d_overlap_ratio — hidden (add_seconds: f32 pack + async put
    # issue + depth-bound waits, interleaved with the pool's tokenize)
    # over the WHOLE pack+transfer stage including the grouped enum DMA.
    # That stage scope matches what the pre-streaming pipeline reported
    # as device_put_s, not pure DMA time (jax.device_put returns before
    # the copy drains, so a pure transfer clock is not observable
    # portably).


# ------------------------------------------------------------- multihost

def assemble_process_local(merged, row_lo: int, row_hi: int,
                           nrow_global: int, mesh=None,
                           simulate: bool = False) -> Dict[int, Vec]:
    """Shard-local streamer target for the multi-host parse (ISSUE 16):
    assemble this process's OWN padded row block of each numeric/time
    column into the global row-sharded array via
    ``make_array_from_process_local_data`` (frame/vec.py
    ``batch_device_put_local``). ``merged`` is the parse merge's
    ``[(column_position, EncodedColumn), ...]`` holding only the LOCAL
    rows ``[row_lo, min(row_hi, nrow_global))`` — each process packs,
    transfers and accounts only its own bytes (the per-process
    ``h2o3_ingest_h2d_bytes`` attribution the parity test asserts).

    Host shadows: exact host copies (time int64 millis, wide-int f64)
    are kept ONLY under ``simulate`` (the single-process parity mesh),
    scattered into a full-length NA-filled array — on a real
    multi-process mesh a host shadow could cover only local rows, and a
    partial shadow violating the Vec contract is worse than none."""
    from h2o3_tpu.frame.vec import (_numeric_host_copy,
                                    batch_device_put_local)
    cols_f32, meta = [], []
    for j, col in merged:
        if col.vtype == T_TIME:
            ms = np.asarray(col.data, dtype=np.int64)
            sec = np.where(ms == Vec.TIME_NA, np.nan,
                           ms / 1000.0).astype(np.float32)
            cols_f32.append(sec)
            meta.append((j, T_TIME, ms, np.int64(Vec.TIME_NA)))
        else:
            f64 = col.data
            host = (f64 if f64.dtype == np.int64
                    else _numeric_host_copy(f64, col.vtype))
            cols_f32.append(f64)
            meta.append((j, col.vtype, host,
                         None if host is None else
                         (np.int64(0) if host.dtype == np.int64
                          else np.float64(np.nan))))
    devs = batch_device_put_local(cols_f32, np.float32(np.nan), np.float32,
                                  row_lo, row_hi, nrow_global, mesh,
                                  simulate=simulate)
    out: Dict[int, Vec] = {}
    for (j, vt, host, na), dev in zip(meta, devs):
        if host is not None and simulate:
            full = np.full(nrow_global, na, dtype=host.dtype)
            full[row_lo:row_lo + len(host)] = host
            host = full
        elif not simulate:
            host = None
        out[j] = Vec(dev, nrow_global, vt, host_data=host)
    return out
