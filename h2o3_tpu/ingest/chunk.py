"""Chunk-local columnar encode — the streaming half of the parse pipeline.

Reference: water/parser/ParseDataset.java MultiFileParseTask streams each
raw-byte chunk through CsvParser into typed per-column NewChunks, each
with a chunk-local categorical dictionary; ParseDataset then unions the
domains (water/parser/PackedDomains) and a second MRTask remaps every
chunk's codes into the global domain. This module is that contract for
the TPU rebuild: a byte-range worker returns finished typed numpy
columns (never global Python token lists), and ``merge_columns`` unions
enum domains and LUT-remaps the codes.

Per-cell Python loops only survive on rare fallback edges (malformed
time tokens, wide-int re-parse); the hot paths are the native tokenizer
(fast_csv.cpp), the native hash dictionary (csv_enum_encode), and
vectorized numpy over the (starts, lens) offset arrays.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Union

import numpy as np

from h2o3_tpu.frame.vec import ENUM_NA, T_ENUM, T_INT, T_REAL, T_STR, T_TIME, Vec

# max enum cardinality before a column falls back to string
# (reference: Categorical.MAX_CATEGORICAL_COUNT ~ 10M; we cap lower since
# domains are host-side python lists)
MAX_ENUM_CARDINALITY = 1_000_000

# |v| >= 2^53 no longer round-trips exactly through float64
_EXACT_F64_BOUND = float(1 << 53)

_SLAB = 1 << 18  # rows per token-extraction slab (bounds the index matrix)


def _skipped_set(setup) -> frozenset:
    return frozenset(getattr(setup, "skipped_columns", ()) or ())


@dataclass
class EncodedColumn:
    """One column of one chunk, fully typed (the NewChunk analog).

    ``data`` by vtype: real/int → float64 (NA=NaN); int with ``exact``
    set → the float64 view plus an exact int64 shadow (values beyond
    2^53); time → int64 epoch millis (NA=Vec.TIME_NA); enum → int32
    codes (NA=-1) against the sorted chunk-local ``domain``; string →
    object array of str/None. ``fmax`` is the finite |max| of a numeric
    column when the encoder already reduced it (the streamer's
    host-shadow decision reuses it instead of re-scanning)."""
    vtype: str
    data: np.ndarray
    domain: Optional[List[str]] = None
    exact: Optional[np.ndarray] = None  # int64, only for wide int columns
    fmax: Optional[float] = None        # finite |max| of a numeric column


# placeholder for a skipped column: never encoded, never merged — the
# tokenizer still scans the cell (rows are parsed whole), but no
# dictionary/decode/union work is spent on it
SKIPPED = EncodedColumn(T_STR, np.empty(0, dtype=object))


def _tokens_sarr(data: bytes, starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Token extraction: gather each cell's bytes into a fixed-width S
    array. Native single-pass gather when the toolchain is up (a view
    into the thread-local gather arena — consumed before the next gather
    by every caller here), else the vectorized numpy slab loop."""
    from h2o3_tpu import native
    n = len(starts)
    if n == 0:
        return np.empty(0, dtype="S1")
    toks = native.gather_tokens(data, starts, lens)
    if toks is not None:
        return toks
    width = max(int(lens.max()), 1)
    buf = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(n, dtype=f"S{width}")
    span = np.arange(width, dtype=np.int64)[None, :]
    # bound rows*width, not rows: one long cell (free-text note) must
    # not turn the slab's index matrix into gigabytes — and up to 16
    # worker threads run this concurrently
    slab = max(1, min(_SLAB, (1 << 24) // width))
    for lo in range(0, n, slab):
        hi = min(lo + slab, n)
        idx = starts[lo:hi, None] + span
        np.clip(idx, 0, max(len(buf) - 1, 0), out=idx)
        mat = np.where(span < lens[lo:hi, None], buf[idx], 0)
        out[lo:hi] = np.ascontiguousarray(mat.astype(np.uint8)).view(
            f"S{width}").ravel()
    return out


def _na_bytes(nas) -> np.ndarray:
    vals = [s.encode("utf-8") for s in (nas or ())]
    return np.array(vals, dtype="S") if vals else np.empty(0, dtype="S1")


def _unescape(tok: str) -> str:
    """Collapse RFC-4180 ``""`` escapes — applied to tokens whose cell
    the native tokenizer flagged (esc), matching csv.reader's output."""
    return tok.replace('""', '"')


def _codes_from_labels(codes: np.ndarray, labels: List[str], nas) -> EncodedColumn:
    """Finish a dictionary encode: NA-string labels map to the NA code,
    the rest rank against the SORTED chunk domain (the reference sorts
    each chunk's categorical domain before PackedDomains union)."""
    # distinct byte tokens can collide after errors='replace' decoding
    # (or after ""-unescape) — dedupe on the decoded string like the
    # Python tokenizer would
    keep = sorted({lab for lab in labels if lab not in nas})
    rank = {lab: k for k, lab in enumerate(keep)}
    if labels:
        lut = np.fromiter(
            (ENUM_NA if lab in nas else rank[lab] for lab in labels),
            dtype=np.int32, count=len(labels))
        out = lut[codes]
    else:
        out = np.full(len(codes), ENUM_NA, dtype=np.int32)
    return EncodedColumn(T_ENUM, out, domain=keep)


def _encode_enum_offsets(data, starts: np.ndarray, lens: np.ndarray,
                         nas, max_card: int,
                         esc: Optional[np.ndarray] = None
                         ) -> Optional[EncodedColumn]:
    """Enum column from (starts, lens): native hash dictionary when
    available, else vectorized numpy unique. None → string fallback.
    ``esc`` flags cells whose raw bytes carry ``""`` escapes — their
    decoded labels unescape, and the decoded-label dedupe merges any
    raw-byte aliases the escape created."""
    from h2o3_tpu import native
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    has_esc = esc is not None and bool(esc.any())
    # fast path: ONE released-GIL call does dictionary build, unescape,
    # NA map, sorted-domain dedupe and final code remap; the only
    # remaining Python is decoding the card domain labels
    full = native.enum_encode_full(data, starts, lens, nas, max_card,
                                   ENUM_NA, esc=esc if has_esc else None)
    if full is not None:
        codes, dom_rows, dom_esc = full
        domain = []
        for r, e in zip(dom_rows.tolist(), dom_esc.tolist()):
            # native validated UTF-8 (else it declines) — strict decode
            lab = bytes(data[starts[r]: starts[r] + lens[r]]).decode("utf-8")
            domain.append(_unescape(lab) if e else lab)
        return EncodedColumn(T_ENUM, codes, domain=domain)
    res = native.enum_encode(data, starts, lens,
                             max_card + len(nas or ()) + 1)
    if res is not None:
        codes, uniq_rows = res
        labels = []
        for r in uniq_rows:
            lab = bytes(data[starts[r]: starts[r] + lens[r]]).decode(
                "utf-8", errors="replace")
            labels.append(_unescape(lab) if has_esc and esc[r] else lab)
        col = _codes_from_labels(codes, labels, nas)
        return col if len(col.domain) <= max_card else None
    toks = _tokens_sarr(data, starts, lens)
    if has_esc:
        # rare: route the escaped cells' tokens through their unescaped
        # form so the byte-level unique can't split one label in two
        toks = toks.astype(object)
        for i in np.flatnonzero(esc):
            toks[i] = toks[i].replace(b'""', b'"')
        toks = np.array(toks.tolist())
    uniq, inv = np.unique(toks, return_inverse=True)
    if len(uniq) > max_card + len(nas or ()) + 1:
        return None
    labels = [u.decode("utf-8", errors="replace") for u in uniq]
    col = _codes_from_labels(inv.astype(np.int32), labels, nas)
    return col if len(col.domain) <= max_card else None


def _decode_str_offsets(data, starts: np.ndarray,
                        lens: np.ndarray, nas,
                        esc: Optional[np.ndarray] = None) -> np.ndarray:
    """Object array of str (None for NA strings) from (starts, lens)."""
    from h2o3_tpu import native
    # NA membership straight off the offsets (nogil) — no token
    # materialization; falls back to isin over the gathered S array
    isna = native.match_any(data, starts, lens,
                            [s.encode("utf-8") for s in (nas or ())])
    toks = _tokens_sarr(data, starts, lens)
    if isna is None:
        isna = np.isin(toks, _na_bytes(nas))
    try:
        out = np.char.decode(toks, "utf-8").astype(object)
    except UnicodeDecodeError:
        out = np.array([t.decode("utf-8", errors="replace") for t in toks],
                       dtype=object)
    if esc is not None:
        for i in np.flatnonzero(esc):
            out[i] = _unescape(out[i])
    out[isna] = None
    return out


def _time_from_u(u: np.ndarray, isna: np.ndarray) -> Optional[np.ndarray]:
    """Vectorized datetime parse of a U array → int64 millis, or None
    when a malformed token needs the tolerant per-cell path."""
    try:
        u = np.where(isna, np.array("NaT", dtype="U3"), u)
        ms = u.astype("datetime64[ms]").astype(np.int64)
    except ValueError:
        return None
    return ms  # NaT → int64 min == Vec.TIME_NA


def _time_per_cell(tokens) -> np.ndarray:
    ms = np.full(len(tokens), Vec.TIME_NA, dtype=np.int64)
    for i, t in enumerate(tokens):
        if t is not None:
            try:
                ms[i] = np.datetime64(t, "ms").astype(np.int64)
            except ValueError:
                pass
    return ms


def _fast_iso_dates(toks: np.ndarray, isna: np.ndarray) -> Optional[np.ndarray]:
    """Vectorized ``YYYY-MM-DD`` → epoch millis straight off the token
    BYTES (days-from-civil, the Hinnant algorithm) — datetime64's string
    parser ran at ~1.3M cells/s and dominated time-column encode. Bails
    to the generic path (None) unless EVERY non-NA token is a valid
    zero-padded ISO date, so results are bit-identical to
    ``astype('datetime64[ms]')`` wherever this path engages."""
    if toks.dtype.itemsize != 10 or len(toks) == 0:
        return None
    act = ~isna
    if not act.any():
        return np.full(len(toks), Vec.TIME_NA, dtype=np.int64)
    b = toks.view(np.uint8).reshape(len(toks), 10)[act]
    dig = (b >= 48) & (b <= 57)
    if not (dig[:, [0, 1, 2, 3, 5, 6, 8, 9]].all()
            and (b[:, 4] == 45).all() and (b[:, 7] == 45).all()):
        return None
    v = b.astype(np.int64) - 48
    year = v[:, 0] * 1000 + v[:, 1] * 100 + v[:, 2] * 10 + v[:, 3]
    month = v[:, 5] * 10 + v[:, 6]
    day = v[:, 8] * 10 + v[:, 9]
    if ((month < 1) | (month > 12)).any():
        return None
    leap = (year % 4 == 0) & ((year % 100 != 0) | (year % 400 == 0))
    mdays = np.array([0, 31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                     dtype=np.int64)[month]
    mdays = np.where((month == 2) & leap, 29, mdays)
    if ((day < 1) | (day > mdays)).any():
        return None
    y = year - (month <= 2)
    era = y // 400                      # floor division, negatives exact
    yoe = y - era * 400
    doy = (153 * ((month + 9) % 12) + 2) // 5 + day - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    days = era * 146097 + doe - 719468  # days since 1970-01-01
    out = np.full(len(toks), Vec.TIME_NA, dtype=np.int64)
    out[act] = days * 86400000
    return out


def _encode_time_offsets(data, starts, lens, nas) -> np.ndarray:
    from h2o3_tpu import native
    isna = native.match_any(data, starts, lens,
                            [s.encode("utf-8") for s in (nas or ())])
    toks = _tokens_sarr(data, starts, lens)
    if isna is None:
        isna = np.isin(toks, _na_bytes(nas))
    ms = _fast_iso_dates(toks, isna)
    if ms is not None:
        return ms
    try:
        u = toks.astype("U")
    except UnicodeDecodeError:
        u = None
    if u is not None:
        ms = _time_from_u(u, isna)
        if ms is not None:
            return ms
    dec = [None if isna[i] else toks[i].decode("utf-8", errors="replace")
           for i in range(len(toks))]
    return _time_per_cell(dec)


def _exact_int_from_tokens(tokens) -> Optional[np.ndarray]:
    """Exact int64 parse of a wide-int column (beyond float64's 2^53).
    None when any cell is NA, non-integer, or outside int64 range —
    the column then falls back to float64/real."""
    out = np.empty(len(tokens), dtype=np.int64)
    for i, t in enumerate(tokens):
        if t is None:
            return None
        try:
            v = int(t)
        except ValueError:
            return None
        if not (-(1 << 63) <= v < (1 << 63)):
            return None
        out[i] = v
    return out


def _maybe_exact(vals: np.ndarray, vtype: str, tokens_fn) -> Optional[np.ndarray]:
    """Wide-int detection: only when a T_INT column holds finite values
    at/above 2^53 is the (rare) exact re-parse worth a token pass."""
    if vtype != T_INT or vals.size == 0:
        return None
    finite = np.isfinite(vals)
    if not finite.all():
        return None  # NA/stray cells: no exact representation
    if not np.any(np.abs(vals) >= _EXACT_F64_BOUND):
        return None
    return _exact_int_from_tokens(tokens_fn())


def encode_chunk_native(data, setup, skip_header: bool, stats=None
                        ) -> Union[List[EncodedColumn], str]:
    """Native-tokenizer chunk encode: one C scan emits column-major
    offsets + eagerly parsed doubles (fast_csv.cpp, zero-copy over an
    mmap view), then every column finishes as a typed numpy array
    without materializing Python token lists. Returns a decline-REASON
    string (the caller re-parses only this range through the Python
    tokenizer and counts the reason). ``stats``, when given, receives
    ``add(tokenize_s, encode_s)`` calls for the per-stage attribution in
    tools/profile_ingest.py."""
    import time as _time

    from h2o3_tpu.native import parse_bytes
    skipped_pre = _skipped_set(setup)
    # offsets are only read back for columns that decode tokens (enum/
    # str/time) or may need the exact wide-int re-parse (int); float64
    # columns' values come straight from vals, so their starts/lens
    # writes (and arena page faults) are suppressed in the C scan
    want = np.fromiter(
        (0 if (j in skipped_pre or vt == T_REAL) else 1
         for j, vt in enumerate(setup.column_types)),
        dtype=np.uint8, count=len(setup.column_types))
    t0 = _time.perf_counter()
    out = parse_bytes(data, setup.separator,
                      getattr(setup, "quotechar", '"') or '"',
                      ncols=len(setup.column_types), want_offsets=want)
    t1 = _time.perf_counter()
    if isinstance(out, str):
        return out
    starts, lens, vals, ok, esc = out
    r0 = 1 if skip_header else 0
    if vals.shape[0] != len(setup.column_types):
        return "column_count_mismatch"
    nas = setup.na_strings if setup.na_strings is not None else set()
    skipped = skipped_pre
    # numeric columns detach from the scratch arena in ONE fancy-index
    # gather (then per-column contiguous row views of the owned block):
    # 29 separate per-column copies held the GIL 29 times per range,
    # which serialized the whole worker pool. The wide-int probe's
    # finite/|max| reductions are likewise one vectorized pass.
    num_idx = [j for j, vt in enumerate(setup.column_types)
               if j not in skipped and vt in (T_REAL, T_INT)]
    num_pos = {j: t for t, j in enumerate(num_idx)}
    if num_idx:
        from h2o3_tpu import native
        # one nogil pass gathers the selected columns out of the arena
        # AND reduces finite/|max| per column (the fancy-index copy plus
        # three full numpy re-walks it replaces all held the GIL)
        nstats = native.numeric_stats(
            vals, vals.strides[0] // vals.itemsize, num_idx, r0,
            vals.shape[1] - r0)
        if nstats is not None:
            block, colmax, allfin = nstats
        else:
            block = vals[num_idx, r0:]
            fin = np.isfinite(block)
            allfin = (fin.all(axis=1) if block.size
                      else np.ones(len(num_idx), bool))
            with np.errstate(invalid="ignore"):
                colmax = (np.abs(block).max(axis=1, initial=-np.inf,
                                            where=fin)
                          if block.size else np.full(len(num_idx), -np.inf))
    cols: List[EncodedColumn] = []
    for j, vt in enumerate(setup.column_types):
        if j in skipped:
            cols.append(SKIPPED)
            continue
        if vt in (T_REAL, T_INT):
            t = num_pos[j]
            v = block[t]
            exact = None
            if (vt == T_INT and v.size and allfin[t]
                    and colmax[t] >= _EXACT_F64_BOUND):
                # tokens_fn only runs for all-finite wide-int columns,
                # so every cell is numeric ASCII text
                exact = _exact_int_from_tokens(np.char.decode(
                    _tokens_sarr(data, starts[j, r0:], lens[j, r0:]),
                    "utf-8").tolist())
            cols.append(EncodedColumn(vt, v, exact=exact,
                                      fmax=float(colmax[t])))
            continue
        s, ln = starts[j, r0:], lens[j, r0:]
        esc_j = esc[j, r0:] if esc is not None else None
        if vt == T_TIME:
            cols.append(EncodedColumn(T_TIME,
                                      _encode_time_offsets(data, s, ln, nas)))
        elif vt == T_ENUM:
            col = _encode_enum_offsets(data, s, ln, nas,
                                       MAX_ENUM_CARDINALITY, esc=esc_j)
            if col is None:  # cardinality blowout → string column
                col = EncodedColumn(T_STR,
                                    _decode_str_offsets(data, s, ln, nas,
                                                        esc=esc_j))
            cols.append(col)
        else:
            cols.append(EncodedColumn(T_STR,
                                      _decode_str_offsets(data, s, ln, nas,
                                                          esc=esc_j)))
    if stats is not None:
        stats.add(t1 - t0, _time.perf_counter() - t1)
    return cols


def encode_token_column(tokens: Sequence[Optional[str]],
                        vtype: str) -> EncodedColumn:
    """Python-tokenizer fallback encode of one column (tokens carry None
    for NA — the tokenizer already applied the na_strings). Still
    vectorized where numpy can parse; per-cell loops only when a stray
    token defeats the bulk conversion — so the fallback produces the
    same typed chunk shape as the native path."""
    n = len(tokens)
    if vtype in (T_REAL, T_INT):
        u = np.array([t if t is not None else "nan" for t in tokens],
                     dtype="U")
        try:
            vals = u.astype(np.float64) if n else np.empty(0, np.float64)
        except ValueError:
            vals = np.full(n, np.nan, dtype=np.float64)
            for i, t in enumerate(tokens):
                if t is not None:
                    try:
                        vals[i] = float(t)
                    except ValueError:
                        pass  # stray non-numeric → NA
        exact = _maybe_exact(vals, vtype, lambda: list(tokens))
        return EncodedColumn(vtype, vals, exact=exact)
    if vtype == T_TIME:
        isna = np.array([t is None for t in tokens], dtype=bool)
        u = np.array([t if t is not None else "NaT" for t in tokens],
                     dtype="U")
        ms = _time_from_u(u, isna) if n else np.empty(0, np.int64)
        if ms is None:
            ms = _time_per_cell(tokens)
        return EncodedColumn(T_TIME, ms)
    if vtype == T_ENUM:
        isna = np.array([t is None for t in tokens], dtype=bool)
        u = np.array([t if t is not None else "" for t in tokens], dtype="U")
        uniq = np.unique(u[~isna]) if (~isna).any() else np.empty(0, "U1")
        if len(uniq) <= MAX_ENUM_CARDINALITY:
            codes = np.searchsorted(uniq, u).astype(np.int32)
            codes[isna] = ENUM_NA
            return EncodedColumn(T_ENUM, codes,
                                 domain=[str(x) for x in uniq])
        # cardinality blowout → string column
    return EncodedColumn(T_STR, np.array(list(tokens), dtype=object))


def _chunk_to_strings(col: EncodedColumn) -> np.ndarray:
    if col.vtype == T_STR:
        return col.data
    dom = np.array(list(col.domain) + [None], dtype=object)
    return dom[np.where(col.data < 0, len(col.domain), col.data)]


def _merge_numeric(chunks: List[EncodedColumn], vtype: str) -> EncodedColumn:
    datas = [c.data for c in chunks]
    if vtype == T_INT and any(c.exact is not None for c in chunks):
        exacts = []
        for c in chunks:
            if c.exact is not None:
                exacts.append(c.exact)
                continue
            f = c.data
            if (f.size == 0 or (np.isfinite(f).all()
                                and np.all(f == np.round(f))
                                and np.all(np.abs(f) < _EXACT_F64_BOUND))):
                exacts.append(f.astype(np.int64))
            else:
                exacts = None
                break
        if exacts is not None:
            return EncodedColumn(T_INT, np.concatenate(exacts)
                                 if len(exacts) > 1 else exacts[0])
        # wide ints coexist with NAs/strays: no exact representation —
        # the column degrades to real rather than silently munging
        vtype = T_REAL
    return EncodedColumn(vtype, np.concatenate(datas)
                         if len(datas) > 1 else datas[0])


def _merge_enum(chunks: List[EncodedColumn]) -> EncodedColumn:
    if any(c.vtype == T_STR for c in chunks):
        return EncodedColumn(T_STR, np.concatenate(
            [_chunk_to_strings(c) for c in chunks]))
    union = sorted(set().union(*(c.domain for c in chunks)))
    if len(union) > MAX_ENUM_CARDINALITY:
        return EncodedColumn(T_STR, np.concatenate(
            [_chunk_to_strings(c) for c in chunks]))
    gidx = {lab: k for k, lab in enumerate(union)}
    parts = []
    for c in chunks:
        if c.domain == union:
            parts.append(c.data)  # common fast path: no remap needed
            continue
        # vectorized LUT remap (the PackedDomains second pass); the
        # trailing -1 serves the NA code, which indexes it as lut[-1]
        lut = np.fromiter((gidx[lab] for lab in c.domain), dtype=np.int32,
                          count=len(c.domain))
        lut = np.append(lut, np.int32(ENUM_NA))
        parts.append(lut[c.data])
    return EncodedColumn(T_ENUM, np.concatenate(parts)
                         if len(parts) > 1 else parts[0], domain=union)


def merge_column(chunks: List[EncodedColumn], vt: str) -> EncodedColumn:
    """Union ONE column's chunk-local pieces (enum domain union + remap,
    numeric/time concat, wide-int exactness resolution). Split out of
    :func:`merge_columns` so the parse pipeline can merge dtype groups
    independently and overlap each group's device transfer with the next
    group's (host) merge work."""
    if vt in (T_REAL, T_INT):
        return _merge_numeric(chunks, vt)
    if vt == T_TIME:
        datas = [c.data for c in chunks]
        return EncodedColumn(T_TIME, np.concatenate(datas)
                             if len(datas) > 1 else datas[0])
    if vt == T_ENUM:
        return _merge_enum(chunks)
    datas = [c.data for c in chunks]
    return EncodedColumn(T_STR, np.concatenate(datas)
                         if len(datas) > 1 else datas[0])


def merge_columns(chunk_results: List[List[EncodedColumn]],
                  column_types: Sequence[str],
                  skipped: Sequence[int] = ()) -> List[Optional[EncodedColumn]]:
    """Union chunk-local columns into full columns: enum domains union +
    code remap, numeric/time concatenate, wide-int exactness resolved
    across chunks. Never round-trips values through strings. Columns in
    ``skipped`` come back as None (their chunks are never touched)."""
    skip = frozenset(skipped)
    out: List[Optional[EncodedColumn]] = []
    for i, vt in enumerate(column_types):
        if i in skip:
            out.append(None)
            continue
        out.append(merge_column([cr[i] for cr in chunk_results], vt))
    return out
