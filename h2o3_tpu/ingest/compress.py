"""Compressed-input plane for the CSV ingest pipeline (gzip / zstd).

Reference: water/parser/ParseDataset.java decompresses inside the chunk
task — the reference's ZipUtil sniffs gzip/zip magic and the parse
MRTask streams through the decompressor per chunk, so compressed import
is parallel for free. TPU re-design: the parse fan-out works on byte
RANGES of one host buffer, so the compressed plane's job is to hand
``ingest/parse.py`` a decompressed buffer fast and then get out of the
way — range planning, quote discovery, the native tokenizer, and the
RANGE-scoped fallback all run unchanged on the result.

Member-parallel where the format allows it:

- **gzip**: a multi-member file (bgzip, pigz-cat, our own
  ``gzip_compress_members``) concatenates independent deflate streams;
  member offsets are discovered by a validated magic scan and each
  worker inflates its own member slice (zlib verifies each member's
  CRC32, so a false-positive magic hit inside compressed data cannot
  corrupt silently — the mis-split slice fails to decode and the whole
  file degrades to the serial path, counted by reason). A
  single-member file has no parallelism to find: it degrades
  gracefully to one serial decompress (``gzip_single_stream``).
- **zstd**: the frame format carries exact sizes in its headers, so
  member discovery is a cheap header walk (no content scan, no false
  positives). Frames decode in parallel. Store-mode frames (raw/RLE
  blocks — what ``zstd_compress_store`` writes and what the parity
  tests/bench use) decode in pure Python; entropy-coded frames are
  gated on the optional ``zstandard`` module with a clear error
  instead of a silent wrong answer.

The ``decompress`` fault site (faults.py) fires at the front door so
chaos specs can exercise the degrade/fallback seams.
"""
from __future__ import annotations

import zlib
from typing import List, Optional, Tuple

GZIP_MAGIC = b"\x1f\x8b"
ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"          # LE 0xFD2FB528
_ZSTD_MAGIC_LE = 0xFD2FB528
_ZSTD_SKIP_LO, _ZSTD_SKIP_HI = 0x184D2A50, 0x184D2A5F


def detect_bytes(head: bytes) -> Optional[str]:
    """Compression format from magic bytes (extension-blind, like the
    reference's ZipUtil sniff) — ``"gzip"``, ``"zstd"`` or None."""
    if head[:2] == GZIP_MAGIC:
        return "gzip"
    if head[:4] == ZSTD_MAGIC:
        return "zstd"
    if len(head) >= 8:
        magic = int.from_bytes(head[:4], "little")
        if _ZSTD_SKIP_LO <= magic <= _ZSTD_SKIP_HI:
            return "zstd"                 # leading skippable frame
    return None


def detect(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as f:
            return detect_bytes(f.read(8))
    except OSError:
        return None


# ------------------------------------------------------------------ gzip

_GZ_XFL_OK = (0, 2, 4)                    # values real writers emit


def _gzip_member_offsets(raw: bytes) -> List[int]:
    """Candidate member start offsets: validated magic hits. Validation
    (CM=deflate, reserved FLG bits zero, plausible XFL) prunes most
    magic bytes that occur INSIDE compressed data; survivors that are
    still false positives fail their CRC during the parallel decode and
    the caller falls back to the serial whole-stream path."""
    offs, i, n = [0], 0, len(raw)
    while True:
        i = raw.find(GZIP_MAGIC, i + 1)
        if i < 0 or i + 10 > n:
            return offs
        if (raw[i + 2] == 8 and raw[i + 3] & 0xE0 == 0
                and raw[i + 8] in _GZ_XFL_OK):
            offs.append(i)


def _gzip_inflate_slice(raw: bytes, start: int, end: int) -> bytes:
    """Inflate the complete gzip member(s) in ``raw[start:end)``. Raises
    ``zlib.error`` when the slice does not hold whole members (a
    mis-detected boundary) — the poison-safety contract."""
    out, pos = [], start
    while pos < end:
        d = zlib.decompressobj(31)        # gzip wrapper, CRC verified
        chunk = d.decompress(raw[pos:end])
        chunk += d.flush()
        if not d.eof:
            raise zlib.error("member extends past the slice boundary")
        out.append(chunk)
        pos = end - len(d.unused_data)
        if d.unused_data and not d.unused_data.startswith(GZIP_MAGIC):
            raise zlib.error("trailing garbage after gzip member")
    return b"".join(out)


def _gzip_decompress(raw: bytes, workers: int) -> Tuple[bytes, dict]:
    offs = _gzip_member_offsets(raw)
    info = {"format": "gzip", "members": len(offs), "parallel": False,
            "reason": None}
    if len(offs) > 1:
        import concurrent.futures as cf
        edges = offs + [len(raw)]
        slices = list(zip(edges[:-1], edges[1:]))
        try:
            with cf.ThreadPoolExecutor(
                    max_workers=min(len(slices), max(workers, 1))) as ex:
                parts = list(ex.map(
                    lambda se: _gzip_inflate_slice(raw, se[0], se[1]),
                    slices))
            info["parallel"] = True
            return b"".join(parts), info
        except zlib.error:
            # a magic hit inside compressed data mis-split a member —
            # every CRC seam catches it; degrade to the serial path
            info["members"] = 1
            info["reason"] = "gzip_member_misdetect"
    elif info["reason"] is None:
        info["reason"] = "gzip_single_stream"
    return _gzip_inflate_slice(raw, 0, len(raw)), info


# ------------------------------------------------------------------ zstd

def _zstd_walk_frame(raw: bytes, off: int):
    """Walk ONE frame starting at ``off`` using only header-carried
    sizes. Returns ``(end_off, blocks, skippable)`` where ``blocks`` is
    ``[(kind, payload_off, size), ...]`` (kind: 0 raw / 1 RLE /
    2 entropy-coded). Raises ValueError on malformed headers."""
    n = len(raw)
    if off + 4 > n:
        raise ValueError("truncated zstd magic")
    magic = int.from_bytes(raw[off:off + 4], "little")
    if _ZSTD_SKIP_LO <= magic <= _ZSTD_SKIP_HI:
        if off + 8 > n:
            raise ValueError("truncated skippable frame")
        size = int.from_bytes(raw[off + 4:off + 8], "little")
        return off + 8 + size, [], True
    if magic != _ZSTD_MAGIC_LE:
        raise ValueError(f"bad zstd magic at {off}")
    fhd = raw[off + 4]
    if fhd & 0x08:
        raise ValueError("reserved FHD bit set")
    single = (fhd >> 5) & 1
    pos = off + 5 + (0 if single else 1)                 # window byte
    pos += (0, 1, 2, 4)[fhd & 3]                         # dictionary id
    pos += ((1 if single else 0), 2, 4, 8)[fhd >> 6]     # content size
    blocks = []
    while True:
        if pos + 3 > n:
            raise ValueError("truncated block header")
        bh = int.from_bytes(raw[pos:pos + 3], "little")
        pos += 3
        last, btype, bsize = bh & 1, (bh >> 1) & 3, bh >> 3
        if btype == 3:
            raise ValueError("reserved block type")
        blocks.append((btype, pos, bsize))
        pos += 1 if btype == 1 else bsize
        if last:
            break
    if (fhd >> 2) & 1:
        pos += 4                                         # xxh64 checksum
    if pos > n:
        raise ValueError("frame overruns the buffer")
    return pos, blocks, False


def _zstd_decode_frame(raw: bytes, off: int, end: int, blocks) -> bytes:
    """Decode one walked frame: raw/RLE blocks in pure Python;
    entropy-coded blocks through the optional ``zstandard`` module."""
    if any(k == 2 for k, _, _ in blocks):
        try:
            import zstandard
        except ImportError:
            raise RuntimeError(
                "entropy-coded zstd frame needs the optional 'zstandard' "
                "module (only store-mode raw/RLE frames decode without "
                "it); re-compress with zstd_compress_store or install "
                "zstandard") from None
        return zstandard.ZstdDecompressor().decompress(
            raw[off:end], max_output_size=1 << 31)
    out = []
    for kind, p, size in blocks:
        if kind == 0:
            out.append(raw[p:p + size])
        else:                             # RLE: one byte, repeated
            out.append(raw[p:p + 1] * size)
    return b"".join(out)


def _zstd_decompress(raw: bytes, workers: int) -> Tuple[bytes, dict]:
    frames, off = [], 0
    while off < len(raw):
        end, blocks, skippable = _zstd_walk_frame(raw, off)
        if not skippable:
            frames.append((off, end, blocks))
        off = end
    info = {"format": "zstd", "members": len(frames),
            "parallel": len(frames) > 1,
            "reason": "zstd_single_frame" if len(frames) <= 1 else None}
    if len(frames) > 1 and workers > 1:
        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(
                max_workers=min(len(frames), workers)) as ex:
            parts = list(ex.map(
                lambda f: _zstd_decode_frame(raw, f[0], f[1], f[2]),
                frames))
    else:
        parts = [_zstd_decode_frame(raw, o, e, b) for o, e, b in frames]
    return b"".join(parts), info


# ------------------------------------------------------------ front door

def decompress_bytes(raw: bytes, workers: int = 1) -> Tuple[bytes, dict]:
    kind = detect_bytes(raw[:8])
    if kind == "gzip":
        return _gzip_decompress(raw, workers)
    if kind == "zstd":
        return _zstd_decompress(raw, workers)
    raise ValueError("buffer is not gzip or zstd compressed")


def decompress_path(path: str, workers: int = 1) -> Tuple[bytes, dict]:
    """Read + decompress a whole compressed file into one contiguous
    bytes buffer (the parse range planner then splits IT, so quote
    discovery / native tokenize / fallback all run unchanged). The
    ``decompress`` fault site fires here, and flaky reads retry through
    the shared backoff (persist.load_model semantics — a transient
    storage hiccup must not fail the import)."""
    from h2o3_tpu import faults, resilience

    def _read_and_inflate() -> Tuple[bytes, dict]:
        if faults.ACTIVE:
            faults.check("decompress", pipeline="ingest")
        with open(path, "rb") as f:
            raw = f.read()
        data, info = decompress_bytes(raw, workers)
        info["ratio"] = round(len(data) / max(len(raw), 1), 2)
        return data, info

    data, info = resilience.retry_transient(
        _read_and_inflate, site="ingest.decompress",
        classify=resilience.is_transient_io)
    info["path"] = path
    return data, info


def head_bytes(path: str, nbytes: int) -> bytes:
    """First ``nbytes`` of the DECOMPRESSED stream (parse_setup's
    sampling head). gzip streams incrementally; zstd decodes leading
    frames until enough bytes accumulate."""
    kind = detect(path)
    if kind == "gzip":
        import gzip
        with gzip.open(path, "rb") as f:
            return f.read(nbytes)
    with open(path, "rb") as f:
        raw = f.read()
    out, off = b"", 0
    while off < len(raw) and len(out) < nbytes:
        end, blocks, skippable = _zstd_walk_frame(raw, off)
        if not skippable:
            out += _zstd_decode_frame(raw, off, end, blocks)
        off = end
    return out[:nbytes]


# --------------------------------------------- writers (tests / bench)

def gzip_compress_members(data: bytes, member_bytes: int = 1 << 20) -> bytes:
    """Multi-member gzip (the pigz/bgzip concatenation shape): each
    ``member_bytes`` slice becomes an independent member, so ingest can
    inflate members in parallel. ``mtime=0`` keeps output deterministic."""
    import gzip
    if not data:
        return gzip.compress(data, 6, mtime=0)
    return b"".join(
        gzip.compress(data[s:s + member_bytes], 6, mtime=0)
        for s in range(0, len(data), member_bytes))


def zstd_compress_store(data: bytes, frame_bytes: int = 1 << 20) -> bytes:
    """Store-mode zstd writer: single-segment frames of raw blocks (no
    entropy coding, so `_zstd_decode_frame` round-trips it without the
    ``zstandard`` module). FHD 0xA0 = 4-byte content size +
    single-segment; raw block headers are ``size<<3 | type<<1 | last``."""
    frames = []
    for s in range(0, max(len(data), 1), frame_bytes):
        seg = data[s:s + frame_bytes]
        hdr = ZSTD_MAGIC + bytes([0xA0]) + len(seg).to_bytes(4, "little")
        blocks = []
        blk = 1 << 16                     # <= the 128 KiB block ceiling
        if not seg:
            blocks.append((1).to_bytes(3, "little"))      # empty last raw
        for b in range(0, len(seg), blk):
            piece = seg[b:b + blk]
            last = 1 if b + blk >= len(seg) else 0
            blocks.append(((len(piece) << 3) | last).to_bytes(3, "little")
                          + piece)
        frames.append(hdr + b"".join(blocks))
    return b"".join(frames)
