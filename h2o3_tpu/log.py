"""Structured logging + per-phase timing.

Reference: water/util/Log.java (leveled log4j-backed logging, per-node
files, buffered pre-init, served at /3/Logs) and MRTask's MRProfile
(water/MRTask.java:190-194,321 — per-phase timings surfaced with the
task).

TPU re-design: one stdlib logger with an in-memory ring buffer (the
/3/Logs source — there is one controller process, no per-node files) and
a ``Profile`` that accumulates named phase durations; builders attach it
to ``model.output['profile']`` so timings travel with the model the way
MRProfile travels with the task."""
from __future__ import annotations

import collections
import logging
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

_BUFFER = collections.deque(maxlen=10000)
_BUF_LOCK = threading.Lock()


class _RingHandler(logging.Handler):
    def emit(self, record):
        with _BUF_LOCK:
            _BUFFER.append(self.format(record))


def _build_logger() -> logging.Logger:
    lg = logging.getLogger("h2o3_tpu")
    if lg.handlers:
        return lg
    level = os.environ.get("H2O3_LOG_LEVEL", "INFO").upper()
    lg.setLevel(getattr(logging, level, logging.INFO))
    fmt = logging.Formatter(
        "%(asctime)s.%(msecs)03d %(levelname)-5s %(name)s: %(message)s",
        datefmt="%H:%M:%S")
    ring = _RingHandler()
    ring.setFormatter(fmt)
    lg.addHandler(ring)
    if os.environ.get("H2O3_LOG_STDERR", "1") != "0":
        sh = logging.StreamHandler(sys.stderr)
        sh.setFormatter(fmt)
        lg.addHandler(sh)
    lg.propagate = False
    return lg


logger = _build_logger()
debug = logger.debug
info = logger.info
warn = logger.warning
error = logger.error


def buffered_lines(n: int = 1000) -> List[str]:
    """Recent log lines (the /3/Logs source)."""
    with _BUF_LOCK:
        return list(_BUFFER)[-n:]


# ---------------------------------------------------------------- timeline

# water/TimeLine.java: a lock-free per-node ring buffer of runtime events
# snapshotted at /3/Timeline. Here: a bounded deque of (ts, kind, detail)
# fed by training drivers / REST handlers; thread-safe via one lock (the
# single-controller design has no per-node rings to merge).
_TIMELINE: "deque" = None  # type: ignore[assignment]
_TL_LOCK = threading.Lock()
_TL_CAP = 2048


def timeline_record(kind: str, detail: str) -> None:
    global _TIMELINE
    with _TL_LOCK:
        if _TIMELINE is None:
            from collections import deque
            _TIMELINE = deque(maxlen=_TL_CAP)
        _TIMELINE.append({"ts": time.time(), "kind": kind,
                          "detail": detail})


def timeline_events(n: int = 2048) -> List[Dict]:
    with _TL_LOCK:
        return list(_TIMELINE or [])[-n:]


class Profile:
    """Per-phase wall-time accumulator (MRProfile analog). Phases may
    repeat; durations accumulate. Not thread-safe by design — one Profile
    per training driver, like one MRProfile per MRTask.

    Telemetry: every phase also lands as a ``{prefix}{name}`` span in
    h2o3_tpu.telemetry (same clock, one measurement) so the stage split
    that travels with the model and the one /metrics exports are the
    same numbers. ``parent_span`` is the training driver's root span —
    set by ModelBuilder.train and handed across the job thread."""

    def __init__(self, prefix: str = "train.", parent_span=None):
        self.phases: Dict[str, float] = {}
        self._order: List[str] = []
        self.prefix = prefix
        self.parent_span = parent_span

    @contextmanager
    def phase(self, name: str):
        from h2o3_tpu import telemetry
        t0 = time.perf_counter()
        # enter a REAL span (thread-local) so nested stage spans inside
        # the phase (gbm's bin/loop/score/finalize) parent implicitly
        cm = telemetry.span(self.prefix + name, parent=self.parent_span)
        cm.__enter__()
        try:
            yield
        except BaseException as e:
            # hand the exception to the span exit so the failed stage
            # is noted (jobs.py reads it for /3/Jobs failed_stage)
            cm.__exit__(type(e), e, e.__traceback__)
            self._accumulate(name, time.perf_counter() - t0)
            raise
        cm.__exit__(None, None, None)
        self._accumulate(name, time.perf_counter() - t0)

    def _accumulate(self, name: str, dt: float):
        if name not in self.phases:
            self._order.append(name)
        self.phases[name] = self.phases.get(name, 0.0) + dt

    def add(self, name: str, seconds: float):
        from h2o3_tpu import telemetry
        telemetry.record_span(
            self.prefix + name,
            time.time() - seconds, seconds,  # h2o3-lint: allow[monotonic-durations] wall START anchor reconstructed from an already-measured duration, for span reporting
            parent=self.parent_span)
        self._accumulate(name, seconds)

    def to_dict(self) -> Dict[str, float]:
        return {k: round(self.phases[k], 4) for k in self._order}

    def summary(self) -> str:
        total = sum(self.phases.values())
        parts = [f"{k}={self.phases[k]:.2f}s" for k in self._order]
        return f"total={total:.2f}s " + " ".join(parts)


def stack_samples(depth: int = 10, samples: int = 20,
                  interval: float = 0.01) -> List[Dict]:
    """Aggregated thread-stack samples — the water/util/JProfile analog
    behind GET /3/Profiler (water/api/ProfilerHandler.java samples JVM
    stacktraces per node and aggregates identical traces with counts).
    Here: sys._current_frames() sampled `samples` times; identical
    truncated traces aggregate; entries sort by count descending."""
    import sys
    import traceback
    agg: Dict[str, int] = {}
    me = threading.get_ident()
    for _ in range(max(samples, 1)):
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = traceback.extract_stack(frame)[-depth:]
            text = "\n".join(
                f"{f.filename}:{f.lineno} in {f.name}" for f in stack)
            agg[text] = agg.get(text, 0) + 1
        time.sleep(interval)
    return [{"stacktrace": k, "count": v}
            for k, v in sorted(agg.items(), key=lambda kv: -kv[1])]
