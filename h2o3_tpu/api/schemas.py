"""JSON emitters matching the reference's schemas3 wire shapes.

Reference: water/api/schemas3/ — CloudV3, JobV3, FrameV3/FramesV3,
ModelsV3, ModelMetrics*V3, ParseSetupV3, ParseV3, ImportFilesV3,
RapidsSchemaV3. Only the fields the Python/R clients actually read are
emitted (h2o-py/h2o/backend/connection.py, frame.py, estimator_base.py);
extra fields are additive later."""
from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional

import numpy as np

_START_MS = int(time.time() * 1000)


def keyref(name: Optional[str], ktype: str = "Key<Keyed>") -> Optional[Dict]:
    if name is None:
        return None
    return {"name": name, "type": ktype, "URL": None}


def cloud_v3() -> Dict:
    import jax
    from h2o3_tpu.parallel.mesh import current_mesh
    mesh = current_mesh()
    n_dev = int(np.prod(list(mesh.shape.values()))) if mesh else 1
    return {
        "__meta": {"schema_version": 3, "schema_name": "CloudV3",
                   "schema_type": "Iced"},
        "version": "3.46.0.tpu",
        "branch_name": "tpu-native",
        "build_number": "0",
        "build_age": "0 days",
        "build_too_old": False,
        "node_idx": 0,
        "cloud_name": "h2o3-tpu",
        "cloud_size": 1,
        "cloud_uptime_millis": int(time.time() * 1000) - _START_MS,
        "cloud_internal_timezone": "UTC",
        "cloud_healthy": True,
        "bad_nodes": 0,
        "consensus": True,
        "locked": True,
        "is_client": False,
        "nodes": [{
            "h2o": "127.0.0.1:54321", "ip_port": "127.0.0.1:54321",
            "healthy": True, "last_ping": int(time.time() * 1000),
            "num_cpus": 1, "cpus_allowed": 1,
            "gflops": None, "mem_bw": None,
            "tpu_devices": [str(d) for d in jax.devices()],
        }],
        "internal_security_enabled": False,
        "web_ip": "127.0.0.1",
    }


def job_v3(job, dest_key: Optional[str] = None, dest_type: str = "Key<Model>") -> Dict:
    from h2o3_tpu import jobs as jobs_mod
    status_map = {jobs_mod.RUNNING: "RUNNING", jobs_mod.DONE: "DONE",
                  jobs_mod.FAILED: "FAILED", jobs_mod.CANCELLED: "CANCELLED"}
    msec = int(((job.end_time or time.time()) - job.start_time) * 1000)
    return {
        "__meta": {"schema_version": 3, "schema_name": "JobV3",
                   "schema_type": "Job"},
        "key": keyref(job.key, "Key<Job>"),
        "description": job.description,
        "status": status_map.get(job.status, str(job.status)),
        "progress": float(job.progress),
        "progress_msg": "Running" if job.status == jobs_mod.RUNNING else "Done",
        "start_time": int(job.start_time * 1000),
        "msec": msec,
        "dest": keyref(dest_key, dest_type),
        "warnings": [],
        "exception": job.exception,
        "stacktrace": job.exception,
        "ready_for_view": job.status == jobs_mod.DONE,
        "auto_recoverable": False,
    }


def _col_v3(name: str, vec, preview_rows: int) -> Dict:
    from h2o3_tpu.frame.vec import T_ENUM, T_INT, T_REAL, T_STR, T_TIME
    r = vec.rollups() if vec.type not in (T_STR,) else {}
    tmap = {T_INT: "int", T_REAL: "real", T_ENUM: "enum", T_STR: "string",
            T_TIME: "time"}
    if vec.type == T_STR:
        data = None
        strs = [s for s in vec.to_strings()[:preview_rows]]
    elif vec.type == T_ENUM:
        # enum NA is code -1 (ENUM_NA), which IS finite — emit None so
        # clients don't render domain[-1] (the last level) for NA cells
        codes = np.asarray(vec.to_numpy()[:preview_rows])
        data = [None if (not np.isfinite(c) or c < 0) else float(c)
                for c in codes]
        strs = None
    else:
        vals = np.asarray(vec.to_numpy()[:preview_rows], dtype=np.float64)
        data = [None if not np.isfinite(v) else float(v) for v in vals]
        strs = None

    def fin(x):
        if x is None:
            return None
        x = float(x)
        return x if math.isfinite(x) else None

    return {
        "__meta": {"schema_version": 3, "schema_name": "ColV3",
                   "schema_type": "Vec"},
        "label": name,
        "type": tmap.get(vec.type, "real"),
        "missing_count": int(r.get("na_count", 0)),
        # nz_count counts NON-ZERO entries; zero_count = rows − NA − nz
        "zero_count": (int(r["rows"] - r["na_count"] - r["nz_count"])
                       if "nz_count" in r else 0),
        "positive_infinity_count": int(r.get("pinfs", 0)),
        "negative_infinity_count": int(r.get("ninfs", 0)),
        "mins": [fin(r.get("min"))] if r else [],
        "maxs": [fin(r.get("max"))] if r else [],
        "mean": fin(r.get("mean")) if r else None,
        "sigma": fin(r.get("sigma")) if r else None,
        "percentiles": (list(map(fin, vec.percentiles()))
                        if r and vec.type not in (T_ENUM,) else None),
        "domain": list(vec.domain) if vec.domain else None,
        "domain_cardinality": len(vec.domain) if vec.domain else 0,
        "data": data,
        "string_data": strs,
        "precision": -1,
        "histogram_bins": None,
        "histogram_base": 0,
        "histogram_stride": 0,
    }


def frame_v3(frame, key: str, row_count: int = 10,
             column_count: Optional[int] = None) -> Dict:
    ncols = frame.ncol if column_count in (None, 0, -1) else min(
        column_count, frame.ncol)
    preview = min(row_count, frame.nrow)
    return {
        "__meta": {"schema_version": 3, "schema_name": "FrameV3",
                   "schema_type": "Frame"},
        "frame_id": keyref(key, "Key<Frame>"),
        "rows": frame.nrow,
        "row_count": preview,
        "row_offset": 0,
        "column_count": ncols,
        "column_offset": 0,
        "total_column_count": frame.ncol,
        "byte_size": int(frame.nrow) * frame.ncol * 4,
        "is_text": False,
        "num_columns": frame.ncol,
        "default_percentiles": [0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75,
                                0.9, 0.99],
        "columns": [_col_v3(n, frame.vec(n), preview)
                    for n in frame.names[:ncols]],
        "compatible_models": [],
        "chunk_summary": None,
        "distribution_summary": None,
    }


def frames_v3(entries: List) -> Dict:
    return {
        "__meta": {"schema_version": 3, "schema_name": "FramesV3",
                   "schema_type": "Frames"},
        "frames": entries,
    }


def _metrics_v3(m, kind_hint: str) -> Optional[Dict]:
    if m is None:
        return None
    d = {"__meta": {"schema_version": 3,
                    "schema_name": "ModelMetrics%sV3" % kind_hint,
                    "schema_type": "ModelMetrics"}}
    for f in ("mse", "rmse", "mae", "rmsle", "r2", "logloss", "auc",
              "aucpr", "mean_per_class_error", "mean_residual_deviance",
              "error", "nobs"):
        v = getattr(m, f, None)
        if v is not None:
            d[f] = None if (isinstance(v, float) and not math.isfinite(v)) else v
    cm = getattr(m, "confusion_matrix", None)
    if cm is not None:
        d["cm"] = {"table": np.asarray(cm).tolist()}
    return d


def model_v3(model, key: str) -> Dict:
    kind = ("Binomial" if model.nclasses == 2 else
            "Multinomial" if model.nclasses > 2 else "Regression")
    out: Dict[str, Any] = {
        "model_category": kind,
        "training_metrics": _metrics_v3(model.training_metrics, kind),
        "validation_metrics": _metrics_v3(model.validation_metrics, kind),
        "cross_validation_metrics": _metrics_v3(
            model.cross_validation_metrics, kind),
        "scoring_history": model.scoring_history,
        "run_time": int(model.run_time * 1000),
        "help": {},
    }
    vi = model.output.get("variable_importances")
    if vi:
        out["variable_importances"] = {
            "name": "Variable Importances",
            "columns": [{"name": "variable"}, {"name": "relative_importance"},
                        {"name": "scaled_importance"}, {"name": "percentage"}],
            "data": [vi["variable"], vi["relative_importance"],
                     vi["scaled_importance"], vi["percentage"]],
        }
    for k, v in model.output.items():
        if k not in out and isinstance(v, (int, float, str, bool, list, dict,
                                           type(None))):
            out[k] = v
    coef_fn = getattr(model, "coef", None)
    if callable(coef_fn):
        try:
            coefs = coef_fn()
            out["coefficients_table"] = {
                "name": "Coefficients", "data": [list(coefs.keys()),
                                                 list(coefs.values())]}
        except Exception:
            pass
    return {
        "__meta": {"schema_version": 3, "schema_name": "ModelSchemaV3",
                   "schema_type": "Model"},
        "model_id": keyref(key, "Key<Model>"),
        "algo": model.algo,
        "algo_full_name": model.algo.upper(),
        "response_column_name": model.response,
        "data_frame": None,
        "timestamp": int(time.time() * 1000),
        "have_pojo": False,
        "have_mojo": False,
        "parameters": [
            {"name": k, "actual_value": v, "default_value": None,
             "label": k, "type": type(v).__name__}
            for k, v in model.params.items()
            if isinstance(v, (int, float, str, bool, list, type(None)))],
        "output": out,
    }


def models_v3(entries: List) -> Dict:
    return {
        "__meta": {"schema_version": 3, "schema_name": "ModelsV3",
                   "schema_type": "Models"},
        "models": entries,
    }
