"""JSON emitters matching the reference's schemas3 wire shapes.

Reference: water/api/schemas3/ — CloudV3, JobV3, FrameV3/FramesV3,
ModelsV3, ModelMetrics*V3, ParseSetupV3, ParseV3, ImportFilesV3,
RapidsSchemaV3. Only the fields the Python/R clients actually read are
emitted (h2o-py/h2o/backend/connection.py, frame.py, estimator_base.py);
extra fields are additive later."""
from __future__ import annotations

import json
import math
import time
from typing import Any, Dict, List, Optional

import numpy as np

_START_MONO = time.monotonic()    # uptime is a duration, not an epoch


def uptime_ms() -> int:
    """Process uptime for /3/Cloud, /3/Ping and /3/SteamMetrics — one
    anchor, so the three endpoints can never report diverging values."""
    return int((time.monotonic() - _START_MONO) * 1000)


def keyref(name: Optional[str], ktype: str = "Key<Keyed>") -> Optional[Dict]:
    if name is None:
        return None
    return {"name": name, "type": ktype, "URL": None}


def _mem_report() -> Dict:
    from h2o3_tpu import memman
    s = memman.manager().stats()
    s["free_mem"] = (max(s["device_budget_bytes"]
                         - s["device_resident_bytes"], 0)
                     if s["device_budget_bytes"] > 0 else -1)
    return s


def cloud_v3() -> Dict:
    import jax
    from h2o3_tpu.parallel.mesh import current_mesh
    mesh = current_mesh()
    n_dev = int(np.prod(list(mesh.shape.values()))) if mesh else 1
    return {
        "__meta": {"schema_version": 3, "schema_name": "CloudV3",
                   "schema_type": "Iced"},
        "version": "3.46.0.tpu",
        "branch_name": "tpu-native",
        "build_number": "0",
        "build_age": "0 days",
        "build_too_old": False,
        "node_idx": 0,
        "cloud_name": "h2o3-tpu",
        "cloud_size": 1,
        "cloud_uptime_millis": uptime_ms(),
        "cloud_internal_timezone": "UTC",
        "cloud_healthy": True,
        "bad_nodes": 0,
        "consensus": True,
        "locked": True,
        "is_client": False,
        "nodes": [{
            "h2o": "127.0.0.1:54321", "ip_port": "127.0.0.1:54321",
            "healthy": True, "last_ping": int(time.time() * 1000),
            "num_cpus": 1, "cpus_allowed": 1,
            "gflops": None, "mem_bw": None,
            "tpu_devices": [str(d) for d in jax.devices()],
            # device-memory report (water/Cleaner.java watermarks + the
            # free_mem field the reference's Cloud page shows)
            **_mem_report(),
        }],
        "internal_security_enabled": False,
        "web_ip": "127.0.0.1",
    }


def job_v3(job, dest_key: Optional[str] = None, dest_type: str = "Key<Model>") -> Dict:
    from h2o3_tpu import jobs as jobs_mod
    status_map = {jobs_mod.RUNNING: "RUNNING",
                  jobs_mod.RECOVERING: "RECOVERING",
                  jobs_mod.QUEUED: "QUEUED",
                  jobs_mod.DONE: "DONE",
                  jobs_mod.FAILED: "FAILED", jobs_mod.CANCELLED: "CANCELLED"}
    msec = job.duration_ms()
    return {
        "__meta": {"schema_version": 3, "schema_name": "JobV3",
                   "schema_type": "Job"},
        "key": keyref(job.key, "Key<Job>"),
        "description": job.description,
        "status": status_map.get(job.status, str(job.status)),
        "progress": float(job.progress),
        "progress_msg": ("Recovering" if job.status == jobs_mod.RECOVERING
                         else "Queued" if job.status == jobs_mod.QUEUED
                         else "Running" if job.status == jobs_mod.RUNNING
                         else "Done"),
        "start_time": int(job.start_time * 1000),
        "msec": msec,
        "dest": keyref(dest_key, dest_type),
        "warnings": [],
        "exception": job.exception,
        "stacktrace": job.exception,
        # structured failure info (ISSUE 6): class + message + the
        # pipeline stage from the open span, so clients stop parsing
        # stack-trace text to find out WHAT failed
        "exception_type": getattr(job, "exception_type", None),
        "exception_msg": getattr(job, "exception_msg", None),
        "failed_stage": getattr(job, "failed_stage", None),
        "stalled": bool(getattr(job, "stalled", False)),
        "cancel_reason": getattr(job, "cancel_reason", None),
        # the propagated trace id (ISSUE 8): links this job's spans in
        # /3/Timeline back to the request that started it
        "trace_id": getattr(job, "trace_id", None),
        # scheduler visibility (ISSUE 15): total seconds spent waiting
        # in the run queue (across preempt/resume cycles) + how many
        # times the job was checkpoint-preempted and requeued
        "queue_wait_s": getattr(job, "queue_wait_s", None),
        "preempt_count": getattr(job, "preempt_count", 0),
        "ready_for_view": job.status == jobs_mod.DONE,
        "auto_recoverable": False,
    }


def _col_v3(name: str, vec, preview_rows: int, row_offset: int = 0) -> Dict:
    from h2o3_tpu.frame.vec import T_ENUM, T_INT, T_REAL, T_STR, T_TIME
    r = vec.rollups() if vec.type not in (T_STR,) else {}
    tmap = {T_INT: "int", T_REAL: "real", T_ENUM: "enum", T_STR: "string",
            T_TIME: "time"}
    lo, hi = row_offset, row_offset + preview_rows
    if vec.type == T_STR:
        data = None
        strs = [s for s in vec.to_strings()[lo:hi]]
    elif vec.type == T_ENUM:
        # enum NA is code -1 (ENUM_NA), which IS finite — emit None so
        # clients don't render domain[-1] (the last level) for NA cells
        codes = np.asarray(vec.to_numpy()[lo:hi])
        data = [None if (not np.isfinite(c) or c < 0) else float(c)
                for c in codes]
        strs = None
    else:
        vals = np.asarray(vec.to_numpy()[lo:hi], dtype=np.float64)
        data = [None if not np.isfinite(v) else float(v) for v in vals]
        strs = None

    def fin(x):
        if x is None:
            return None
        x = float(x)
        return x if math.isfinite(x) else None

    return {
        "__meta": {"schema_version": 3, "schema_name": "ColV3",
                   "schema_type": "Vec"},
        "label": name,
        "type": tmap.get(vec.type, "real"),
        "missing_count": int(r.get("na_count", 0)),
        # nz_count counts NON-ZERO entries; zero_count = rows − NA − nz
        "zero_count": (int(r["rows"] - r["na_count"] - r["nz_count"])
                       if "nz_count" in r else 0),
        "positive_infinity_count": int(r.get("pinfs", 0)),
        "negative_infinity_count": int(r.get("ninfs", 0)),
        "mins": [fin(r.get("min"))] if r else [],
        "maxs": [fin(r.get("max"))] if r else [],
        "mean": fin(r.get("mean")) if r else None,
        "sigma": fin(r.get("sigma")) if r else None,
        "percentiles": (list(map(fin, vec.percentiles()))
                        if r and vec.type not in (T_ENUM,) else None),
        "domain": list(vec.domain) if vec.domain else None,
        "domain_cardinality": len(vec.domain) if vec.domain else 0,
        "data": data,
        "string_data": strs,
        "precision": -1,
        "histogram_bins": None,
        "histogram_base": 0,
        "histogram_stride": 0,
    }


def frame_v3(frame, key: str, row_count: int = 10,
             column_count: Optional[int] = None, row_offset: int = 0,
             column_offset: int = 0) -> Dict:
    """FrameV3 with the reference's pagination contract
    (water/api/FramesHandler row_offset/row_count/column_offset/
    column_count windows — h2o-py pages wide/long frames this way)."""
    row_offset = max(0, min(int(row_offset), frame.nrow))
    column_offset = max(0, min(int(column_offset), frame.ncol))
    ncols = (frame.ncol - column_offset if column_count in (None, 0, -1)
             else min(column_count, frame.ncol - column_offset))
    preview = min(row_count, frame.nrow - row_offset)
    sel = frame.names[column_offset:column_offset + ncols]
    return {
        "__meta": {"schema_version": 3, "schema_name": "FrameV3",
                   "schema_type": "Frame"},
        "frame_id": keyref(key, "Key<Frame>"),
        "rows": frame.nrow,
        "row_count": preview,
        "row_offset": row_offset,
        "column_count": ncols,
        "column_offset": column_offset,
        "total_column_count": frame.ncol,
        "byte_size": int(frame.nrow) * frame.ncol * 4,
        "is_text": False,
        "num_columns": frame.ncol,
        "default_percentiles": [0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75,
                                0.9, 0.99],
        "columns": [_col_v3(n, frame.vec(n), preview, row_offset)
                    for n in sel],
        "compatible_models": [],
        "chunk_summary": None,
        "distribution_summary": None,
    }


def frames_v3(entries: List) -> Dict:
    return {
        "__meta": {"schema_version": 3, "schema_name": "FramesV3",
                   "schema_type": "Frames"},
        "frames": entries,
    }


def twodim(name: str, col_names: List[str], data_cols: List[List],
           col_types: Optional[List[str]] = None,
           description: str = "") -> Dict:
    """TwoDimTableV3 wire shape (water/api/schemas3/TwoDimTableV3) —
    data is COLUMN-major; h2o-py H2OTwoDimTable.make consumes columns[]
    name/type/format and raw data."""
    if col_types is None:
        col_types = ["double"] * len(col_names)
    fmt = {"double": "%.5f", "float": "%.5f", "int": "%d", "long": "%d",
           "string": "%s"}
    return {
        "__meta": {"schema_version": 3, "schema_name": "TwoDimTableV3",
                   "schema_type": "TwoDimTable"},
        "name": name, "description": description,
        "columns": [{"__meta": {"schema_name": "ColumnSpecsBase"},
                     "name": n, "type": t, "format": fmt.get(t, "%s"),
                     "description": n}
                    for n, t in zip(col_names, col_types)],
        "rowcount": len(data_cols[0]) if data_cols else 0,
        "data": [[_fin_or_none(v) if isinstance(v, float) else v
                  for v in col] for col in data_cols],
    }


def _fin_or_none(v):
    try:
        f = float(v)
    except (TypeError, ValueError):
        return v
    return f if math.isfinite(f) else None


def _cm_table(cm: np.ndarray, domain: Optional[List[str]]) -> Dict:
    """ConfusionMatrixV3: {table: TwoDimTable} with per-class rows,
    Error and Rate columns (hex/ConfusionMatrix.java toTable)."""
    cm = np.asarray(cm, dtype=np.float64)
    k = cm.shape[0]
    labels = ([str(d) for d in domain] if domain and len(domain) == k
              else [str(i) for i in range(k)])
    rows_tot = cm.sum(axis=1)
    err = np.where(rows_tot > 0, 1.0 - np.diag(cm) / np.maximum(rows_tot, 1),
                   0.0)
    cols = [list(cm[:, j]) + [float(cm[:, j].sum())] for j in range(k)]
    err_col = list(err) + [float(1.0 - np.trace(cm) / max(cm.sum(), 1))]
    rate_col = [f"{int(rows_tot[i] - cm[i, i]):,} / {int(rows_tot[i]):,}"
                for i in range(k)]
    rate_col.append(f"{int(cm.sum() - np.trace(cm)):,} / {int(cm.sum()):,}")
    table = twodim("Confusion Matrix", labels + ["Error", "Rate"],
                   cols + [err_col, rate_col],
                   ["long"] * k + ["double", "string"])
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ConfusionMatrixV3",
                       "schema_type": "ConfusionMatrix"},
            "table": table}


def _metrics_v3(m, kind_hint: str, domain: Optional[List[str]] = None,
                algo: str = "", frame_key: Optional[str] = None,
                model_key: Optional[str] = None) -> Optional[Dict]:
    """ModelMetrics*V3 with the REFERENCE's field names (AUC, pr_auc,
    Gini, MSE, RMSE — capitalization matters: h2o-py metrics_base.py
    reads _metric_json['AUC'] etc.)."""
    if m is None:
        return None
    d = {"__meta": {"schema_version": 3,
                    "schema_name": "ModelMetrics%sV3" % kind_hint,
                    "schema_type": "ModelMetrics%s" % kind_hint},
         "model_category": kind_hint,
         "description": None,
         "scoring_time": int(time.time() * 1000),
         "frame": keyref(frame_key, "Key<Frame>") if frame_key else None,
         "model": keyref(model_key, "Key<Model>") if model_key else None}
    td = m.to_dict() if hasattr(m, "to_dict") else {}
    for k, v in td.items():
        if k == "cm":
            continue
        if isinstance(v, float):
            d[k] = None if not math.isfinite(v) else v
        else:
            d[k] = v
    cm = getattr(m, "confusion_matrix", None)
    if cm is not None:
        d["cm"] = _cm_table(cm, domain)
    thr = getattr(m, "thresholds_and_metric_scores", None)
    if thr:
        thr = dict(thr)
        max_crit = thr.pop("max_criteria_and_metric_scores", None)
        gl = thr.pop("gains_lift", None)
        names = list(thr.keys())
        d["thresholds_and_metric_scores"] = twodim(
            "Metrics for Thresholds", names,
            [list(np.asarray(thr[n], dtype=np.float64)) for n in names])
        if max_crit:
            crits = ["max " + c for c in max_crit]
            d["max_criteria_and_metric_scores"] = twodim(
                "Maximum Metrics", ["metric", "threshold", "value", "idx"],
                [crits,
                 [float(v["threshold"]) for v in max_crit.values()],
                 [float(v["value"]) for v in max_crit.values()],
                 [int(v["idx"]) for v in max_crit.values()]],
                ["string", "double", "double", "long"])
        if isinstance(gl, dict) and gl:
            names = [n for n in gl if isinstance(gl[n], (list, np.ndarray))]
            nr = len(gl[names[0]]) if names else 0
            cols = [list(np.asarray(gl[n]).tolist()) for n in names]
            for n in gl:           # scalar stats (KS) broadcast per row
                if not isinstance(gl[n], (list, np.ndarray)):
                    names.append(n)
                    cols.append([_fin_or_none(gl[n])] * nr)
            d["gains_lift_table"] = twodim("Gains/Lift Table", names, cols)
    ht = getattr(m, "hit_ratios", None)
    if ht is not None:
        hr = np.asarray(ht, dtype=np.float64)
        d["hit_ratio_table"] = twodim(
            "Top-K Hit Ratios", ["k", "hit_ratio"],
            [list(range(1, len(hr) + 1)), list(hr)], ["long", "double"])
    return d


def _scoring_history_table(model) -> Optional[Dict]:
    """ScoringHistory as the TwoDimTable the clients consume
    (hex/ScoreKeeper + water/api ModelSchemaV3 scoring_history;
    h2o-py learning_curve_plot reads number_of_trees/training_* columns,
    h2o/explanation/_explain.py:2500)."""
    hist = model.scoring_history
    if not hist or not isinstance(hist, list) or not isinstance(
            hist[0], dict):
        return None
    step_key = next((k for k in ("ntrees", "iterations", "iteration",
                                 "epochs") if k in hist[0]), None)
    step_name = {"ntrees": "number_of_trees", "iteration": "iterations",
                 None: "iterations"}.get(step_key, step_key)
    metric_keys = [k for k in hist[0]
                   if k != step_key and isinstance(hist[0][k],
                                                   (int, float))]
    # learning_curve_plot always reads training_<metric>
    # (h2o/explanation/_explain.py:2668); when the entries were scored
    # on a validation frame they ALSO serve as validation_<metric>
    has_valid = model.validation_metrics is not None
    names = ["timestamp", "duration", step_name] + \
        ["training_" + k for k in metric_keys] + \
        (["validation_" + k for k in metric_keys] if has_valid else [])
    cols: List[list] = [["" for _ in hist], ["" for _ in hist],
                        [e.get(step_key, i) for i, e in enumerate(hist)]]
    series = [[_fin_or_none(e.get(k)) for e in hist] for k in metric_keys]
    cols += series
    if has_valid:
        cols += [list(sv) for sv in series]
    types = ["string", "string", "long"] + \
        ["double"] * (len(metric_keys) * (2 if has_valid else 1))
    return twodim("Scoring History", names, cols, types)


def model_v3(model, key: str) -> Dict:
    kind = ("Binomial" if model.nclasses == 2 else
            "Multinomial" if model.nclasses > 2 else "Regression")
    # uplift models carry ModelMetricsBinomialUplift — a distinct wire
    # category (hex/ModelMetricsBinomialUplift; a Binomial schema with
    # only AUUC fields would break the client's .auc()/show())
    if type(model.training_metrics).__name__ == "ModelMetricsBinomialUplift":
        kind = "BinomialUplift"
    dom = list(getattr(model, "response_domain", None) or []) or None
    # names/domains: feature columns + response last (hex/Model.Output
    # _names/_domains; h2o-py H2OTree categorical decode reads these)
    names_nd = list(model.feature_names) + ([model.response]
                                            if model.response else [])
    domains_nd = [list(model.cat_domains[n]) if n in model.cat_domains
                  else None for n in model.feature_names]
    if model.response:
        domains_nd.append(dom)
    out: Dict[str, Any] = {
        "model_category": kind,
        "names": names_nd,
        "original_names": names_nd,     # pre-expansion == names here
        "column_types": [("Enum" if n in model.cat_domains else "Numeric")
                         for n in model.feature_names]
        + (["Enum" if model.response_domain else "Numeric"]
           if model.response else []),
        "domains": domains_nd,
        "training_metrics": _metrics_v3(model.training_metrics, kind,
                                        domain=dom, model_key=key),
        "validation_metrics": _metrics_v3(model.validation_metrics, kind,
                                          domain=dom, model_key=key),
        "cross_validation_metrics": _metrics_v3(
            model.cross_validation_metrics, kind, domain=dom, model_key=key),
        "scoring_history": _scoring_history_table(model),
        "run_time": int(model.run_time * 1000),
        "help": {},
    }
    vi = model.output.get("variable_importances")
    if vi:
        out["variable_importances"] = twodim(
            "Variable Importances",
            ["variable", "relative_importance", "scaled_importance",
             "percentage"],
            [list(vi["variable"]),
             [float(v) for v in vi["relative_importance"]],
             [float(v) for v in vi["scaled_importance"]],
             [float(v) for v in vi["percentage"]]],
            ["string", "double", "double", "double"])
    cvm = model.output.get("cross_validation_models")
    if cvm:
        # fold models ride as key references (ModelSchemaV3 output);
        # h2o-py _resolve_model reads [{"name": ...}]
        out["cross_validation_models"] = [
            keyref(getattr(m, "key", None) or f"{key}_cv_{i + 1}",
                   "Key<Model>") for i, m in enumerate(cvm)]
    for k, v in model.output.items():
        if k in out or k == "cross_validation_models":
            continue
        if isinstance(v, (int, float, str, bool, type(None))):
            out[k] = v
        elif isinstance(v, (list, dict)):
            try:
                json.dumps(v)
            except (TypeError, ValueError):
                continue
            out[k] = v
    coef_fn = getattr(model, "coef", None)
    if callable(coef_fn):
        try:
            coefs = coef_fn()
            if coefs and isinstance(next(iter(coefs.values())), dict):
                # multinomial: {class: {name: coef}} → per-class raw +
                # standardized column halves (h2o-py _fillMultinomialDict
                # slices the header in half, model_base.py:843)
                classes = list(coefs)
                names_c = list(next(iter(coefs.values())).keys())
                raw_cols = [[float(coefs[c].get(n, 0.0)) for n in names_c]
                            for c in classes]
                tbl = twodim(
                    "Coefficients",
                    ["names"] + [f"coefs_class_{c}" for c in classes]
                    + [f"std_coefs_class_{c}" for c in classes],
                    [names_c] + raw_cols + raw_cols,
                    ["string"] + ["double"] * (2 * len(classes)))
                out["coefficients_table"] = tbl
                out["coefficients_table_multinomials_with_class_names"] = tbl
                raise StopIteration   # skip the flat-table path below
            if model.nclasses > 2:
                # ordinal: flat coef map; the client slices header halves
                names_c = list(coefs.keys())
                vals = [float(v) for v in coefs.values()]
                tbl = twodim("Coefficients",
                             ["names", "coefficients",
                              "standardized_coefficients"],
                             [names_c, vals, vals],
                             ["string", "double", "double"])
                out["coefficients_table"] = tbl
                out["coefficients_table_multinomials_with_class_names"] = tbl
                raise StopIteration
            norm_fn = getattr(model, "coef_norm", None)
            norm = norm_fn() if callable(norm_fn) else coefs
            # GlmV3 coefficients_table shape (hex/schemas/GLMModelV3) —
            # h2o-py coef()/coef_norm() zip tbl["names"] against
            # tbl["coefficients"]/["standardized_coefficients"]
            names_c = list(coefs.keys())
            cols = [names_c, [float(v) for v in coefs.values()],
                    [float(norm.get(k, v)) if isinstance(norm, dict)
                     else float(v) for k, v in coefs.items()]]
            headers = ["names", "coefficients", "standardized_coefficients"]
            types = ["string", "double", "double"]
            pv = model.output.get("p_values")
            if pv:     # compute_p_values=True: GLM coef table gains cols
                for field, label in (("std_errs", "std_error"),
                                     ("z_values", "z_value"),
                                     ("p_values", "p_value")):
                    src = model.output[field]
                    cols.append([float(src.get(n, float("nan")))
                                 for n in names_c])
                    headers.append(label)
                    types.append("double")
            out["coefficients_table"] = twodim("Coefficients", headers,
                                               cols, types)
        except Exception:
            pass
    return {
        "__meta": {"schema_version": 3, "schema_name": "ModelSchemaV3",
                   "schema_type": "Model"},
        "model_id": keyref(key, "Key<Model>"),
        # HGLM models persist under their own algo tag but are GLM on
        # the wire (the reference builds them through the glm builder
        # and h2o-py resolves estimator classes by this field)
        "algo": "glm" if model.algo == "hglm" else model.algo,
        "algo_full_name": ("GLM" if model.algo == "hglm"
                           else model.algo.upper()),
        "response_column_name": model.response,
        "data_frame": None,
        "timestamp": int(time.time() * 1000),
        # gate flags the client checks before download_pojo/download_mojo
        # (h2o-py h2o.py:1397): POJO for tree + GLM codegen, MOJO for
        # every algo with a writer registered in mojo.py/genmodel.py
        "have_pojo": model.algo in ("gbm", "drf", "isolationforest",
                                    "xgboost", "glm"),
        "have_mojo": hasattr(model, "download_mojo"),
        "parameters": [
            {"name": k, "actual_value": v, "default_value": None,
             "label": k, "type": type(v).__name__, "input_value": v}
            for k, v in model.params.items()
            if isinstance(v, (int, float, str, bool, list, type(None)))
            and k not in ("model_id", "response_column", "training_frame",
                          "validation_frame")
        ] + [
            # special params carry STRUCTURED actual_values — h2o-py's
            # ModelBase.actual_params reads actual_value["column_name"] /
            # ["name"] (ColSpecifierV3/KeyV3); a bare string makes the
            # property raise and the compat metaclass then returns the
            # raw descriptor (h2o/utils/metaclass.py:345)
            {"name": "response_column",
             "actual_value": {"column_name": model.response},
             "default_value": None, "label": "response_column",
             "type": "VecSpecifier", "input_value": None},
            {"name": "model_id", "actual_value": {"name": key},
             "default_value": None, "label": "model_id", "type": "Key",
             "input_value": None},
            {"name": "training_frame",
             "actual_value": ({"name": str(model.params["training_frame"])}
                              if model.params.get("training_frame")
                              else None),
             "default_value": None, "label": "training_frame",
             "type": "Key", "input_value": None},
            {"name": "validation_frame",
             "actual_value": ({"name": str(model.params[
                 "validation_frame"])}
                 if model.params.get("validation_frame") else None),
             "default_value": None, "label": "validation_frame",
             "type": "Key", "input_value": None},
        ],
        "output": out,
    }


def serve_deployment_v3(dep) -> Dict:
    """One deployed model's serving config + warm-compile record
    (no reference analog — h2o-3 has no online row-serving surface;
    schema shape follows ModelsV3 conventions)."""
    info = dep.info()
    return {
        "__meta": {"schema_version": 3, "schema_name": "ServeDeploymentV3",
                   "schema_type": "ServeDeployment"},
        "model_id": keyref(dep.key, "Key<Model>"),
        **info,
    }


def serve_stats_v3(snapshot: Dict) -> Dict:
    """GET /3/Serve/stats payload: per-model latency percentiles, stage
    attribution, queue depth, batch occupancy and counters."""
    return {
        "__meta": {"schema_version": 3, "schema_name": "ServeStatsV3",
                   "schema_type": "ServeStats"},
        **snapshot,
    }


def models_v3(entries: List) -> Dict:
    return {
        "__meta": {"schema_version": 3, "schema_name": "ModelsV3",
                   "schema_type": "Models"},
        "models": entries,
    }


def known_schema_names():
    """Names served by /3/Metadata/schemas (MetadataHandler.listSchemas
    analog): scraped from this module's literal schema_name strings so
    the list cannot drift from what handlers actually emit."""
    import re as _re
    src = open(__file__.rstrip("c")).read()
    names = set(_re.findall(r'"schema_name":\s*"([A-Za-z0-9._]+)"', src))
    from h2o3_tpu.api import server as _srv
    ssrc = open(_srv.__file__.rstrip("c")).read()
    names |= set(_re.findall(r'"schema_name":\s*"([A-Za-z0-9._]+)"', ssrc))
    return sorted(names)
