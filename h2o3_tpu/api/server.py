"""REST server + routing — the RequestServer analog.

Reference: water/api/RequestServer.java:38 (route table, versioned
paths), water/api/ModelBuilderHandler.java (schema fill → trainModel),
water/api/RapidsHandler.java, ParseHandler/ParseSetupHandler,
FramesHandler, ModelsHandler, JobsHandler; Jetty at :54321.

TPU re-design: one stdlib ThreadingHTTPServer; routes are (method,
pattern) pairs dispatching to plain functions; training runs as
background Jobs (h2o3_tpu.jobs) the client polls via GET /3/Jobs/{key}
exactly like h2o-py's H2OJob.poll. Parameter coercion replaces the
reflection-driven Schema.fillFromParms: form values arrive as strings
and are json/number/bool-coerced against the estimator defaults."""
from __future__ import annotations

import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu import dkv
from h2o3_tpu.api import schemas
from h2o3_tpu.jobs import Job, get_job

_ROUTES: List[Tuple[str, re.Pattern, Callable]] = []


def route(method: str, pattern: str):
    rx = re.compile("^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$")

    def deco(fn):
        _ROUTES.append((method, rx, fn))
        return fn
    return deco


class ApiError(Exception):
    def __init__(self, status: int, msg: str, headers=None):
        super().__init__(msg)
        self.status = status
        self.headers = dict(headers or {})   # e.g. Retry-After on 503


# ---------------- algo registry ---------------------------------------

def _builders() -> Dict[str, Any]:
    from h2o3_tpu import estimators as est
    return {"gbm": est.H2OGradientBoostingEstimator,
            "drf": est.H2ORandomForestEstimator,
            "glm": est.H2OGeneralizedLinearEstimator,
            "deeplearning": est.H2ODeepLearningEstimator,
            "kmeans": est.H2OKMeansEstimator,
            "pca": est.H2OPrincipalComponentAnalysisEstimator,
            "xgboost": est.H2OXGBoostEstimator,
            "isolationforest": est.H2OIsolationForestEstimator,
            "extendedisolationforest":
                est.H2OExtendedIsolationForestEstimator,
            "isotonicregression": est.H2OIsotonicRegressionEstimator,
            "svd": est.H2OSingularValueDecompositionEstimator,
            "aggregator": est.H2OAggregatorEstimator,
            "naivebayes": est.H2ONaiveBayesEstimator,
            "gam": est.H2OGeneralizedAdditiveEstimator,
            "glrm": est.H2OGeneralizedLowRankEstimator,
            "anovaglm": est.H2OANOVAGLMEstimator,
            "coxph": est.H2OCoxProportionalHazardsEstimator,
            "psvm": est.H2OSupportVectorMachineEstimator,
            "upliftdrf": est.H2OUpliftRandomForestEstimator,
            "word2vec": est.H2OWord2vecEstimator,
            "targetencoder": est.H2OTargetEncoderEstimator,
            "infogram": est.H2OInfogram,
            "grep": est.H2OGrepEstimator,
            "generic": est.H2OGenericEstimator,
            "modelselection": est.H2OModelSelectionEstimator,
            "rulefit": est.H2ORuleFitEstimator,
            "stackedensemble": est.H2OStackedEnsembleEstimator}


def _strlist(v) -> list:
    """Parse h2o-py's stringify_list output — '[AGE,PSA]' with UNQUOTED
    items (h2o-py/h2o/utils/shared_utils.py:213) — or JSON, or an
    actual list."""
    if isinstance(v, list):
        return v
    if v is None:
        return []
    s = str(v).strip()
    if s.startswith("["):
        try:
            return json.loads(s)
        except json.JSONDecodeError:
            inner = s[1:-1].strip()
            return ([t.strip().strip('"').strip("'")
                     for t in inner.split(",")] if inner else [])
    return [s]


def _coerce(v: str) -> Any:
    """Schema.fillFromParms analog: h2o-py sends everything as strings."""
    if not isinstance(v, str):
        return v
    s = v.strip()
    if s.lower() in ("true", "false"):
        return s.lower() == "true"
    if s.lower() in ("null", "none", ""):
        return None
    if s.startswith("[") or s.startswith("{"):
        try:
            return json.loads(s)
        except json.JSONDecodeError:
            pass
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


def _coerce_typed(name: str, v: Any, defaults: dict) -> Any:
    """Schema-typed parse (water/api/Schema.java fillFromParms semantics):
    the declared field type — here the builder's default-value type from
    the same registry `/3/ModelBuilders/{algo}` metadata and the bindings
    codegen consume — drives parsing, so a string-typed parameter is
    NEVER int/bool-mangled by guessing. Falls back to the untyped
    ``_coerce`` only for parameters the builder doesn't declare."""
    if not isinstance(v, str):
        return v
    d = defaults.get(name)
    if name not in defaults or d is None:
        return _coerce(v)
    s = v.strip()
    if isinstance(d, str):
        # declared string: pass through verbatim (an enum value like
        # "none" or a column named "123" must survive)
        return s
    if s.lower() in ("null", "none", ""):
        return None
    if isinstance(d, bool):
        return s.lower() == "true" if s.lower() in ("true", "false") \
            else _coerce(s)
    if isinstance(d, int):
        try:
            f = float(s)
            return int(f) if f == int(f) else f
        except ValueError:
            return _coerce(s)
    if isinstance(d, float):
        try:
            return float(s)
        except ValueError:
            return _coerce(s)
    if isinstance(d, (list, tuple)):
        got = _coerce(s)
        return list(got) if isinstance(got, (list, tuple)) else \
            _bracket_list(s)
    return _coerce(s)


# ---------------- handlers --------------------------------------------

@route("GET", "/")
@route("GET", "/flow/index.html")
def _flow_ui(params, body):
    """The built-in web UI (h2o-web Flow analog — api/flow.py): one
    self-contained page over the same REST surface the clients use."""
    from h2o3_tpu.api.flow import FLOW_HTML
    return {"__raw": FLOW_HTML.encode(),
            "__content_type": "text/html; charset=utf-8"}


@route("GET", "/3/Cloud")
@route("HEAD", "/3/Cloud")
def _cloud(params, body):
    return schemas.cloud_v3()


@route("GET", "/3/About")
def _about(params, body):
    return {"entries": [{"name": "Build project version",
                         "value": "3.46.0.tpu"}]}


@route("POST", "/4/sessions")
def _new_session(params, body):
    sid = "_sid_" + uuid.uuid4().hex[:10]
    dkv.put(sid, "session", {"frames": []})
    return {"session_key": sid, "name": sid}


@route("DELETE", "/4/sessions/{sid}")
def _end_session(params, body, sid):
    dkv.remove(sid)
    return {"session_key": sid}


@route("POST", "/3/ImportFiles")
def _import_files(params, body):
    path = params.get("path")
    if not path or not os.path.exists(path):
        raise ApiError(404, f"path not found: {path}")
    key = "nfs://" + path.lstrip("/")
    dkv.put(key, "rawfile", path)
    return {"__meta": {"schema_version": 3, "schema_name": "ImportFilesV3"},
            "path": path, "files": [path], "destination_frames": [key],
            "fails": [], "dels": []}


def _bracket_list(v) -> List[str]:
    """h2o-py stringifies list params as '[a,b,c]' WITHOUT quotes
    (connection.py helpers) — json.loads can't touch them."""
    if isinstance(v, list):
        return [str(x) for x in v]
    s = str(v or "").strip()
    if s.startswith("[") and s.endswith("]"):
        s = s[1:-1]
    return [p.strip().strip('"') for p in s.split(",") if p.strip()]


@route("POST", "/3/ImportFilesMulti")
def _import_files_multi(params, body):
    paths = _bracket_list(params.get("paths"))
    dests, fails = [], []
    for path in paths:
        if not os.path.exists(path):
            fails.append(path)
            continue
        key = "nfs://" + path.lstrip("/")
        dkv.put(key, "rawfile", path)
        dests.append(key)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ImportFilesMultiV3"},
            "paths": paths, "files": [p for p in paths if os.path.exists(p)],
            "destination_frames": dests, "fails": fails, "dels": []}


@route("POST", "/3/PostFile")
def _post_file(params, body):
    """h2o.upload_file: multipart body → temp file → raw key."""
    fname = params.get("filename", "upload.csv")
    data = body if isinstance(body, (bytes, bytearray)) else b""
    # strip a multipart envelope if present
    if data.startswith(b"--"):
        try:
            head, rest = data.split(b"\r\n\r\n", 1)
            boundary = data.split(b"\r\n", 1)[0]
            data = rest.rsplit(b"\r\n" + boundary, 1)[0]
        except ValueError:
            pass
    tmp = os.path.join(tempfile.gettempdir(),
                       f"h2o_upload_{uuid.uuid4().hex[:8]}_"
                       f"{os.path.basename(fname)}")
    with open(tmp, "wb") as f:
        f.write(data)
    key = "nfs://" + tmp.lstrip("/")
    dkv.put(key, "rawfile", tmp)
    return {"destination_frame": key, "total_bytes": len(data)}


def _raw_paths(source_frames) -> List[str]:
    if isinstance(source_frames, str):
        source_frames = [source_frames]
    paths = []
    for sf in source_frames:
        name = sf["name"] if isinstance(sf, dict) else sf
        ent = dkv.get_opt(name)
        if ent and ent[0] == "rawfile":
            paths.append(ent[1])
        elif os.path.exists(str(name)):
            paths.append(str(name))
        else:
            raise ApiError(404, f"source frame not found: {name}")
    return paths


@route("POST", "/3/ParseSetup")
def _parse_setup(params, body):
    from h2o3_tpu.ingest.parse import parse_setup
    src = _coerce(params.get("source_frames", "[]"))
    paths = _raw_paths(src)
    sep = params.get("separator")
    if sep and str(sep).isdigit():
        sep = chr(int(sep))
    setup = parse_setup(paths[0], separator=sep)
    dest = os.path.basename(paths[0]).replace(".csv", "") + ".hex"
    return {
        "__meta": {"schema_version": 3, "schema_name": "ParseSetupV3"},
        "source_frames": [schemas.keyref(p if isinstance(p, str) else p["name"])
                          for p in (src if isinstance(src, list) else [src])],
        "parse_type": "CSV",
        "separator": ord(setup.separator),
        "single_quotes": False,
        "check_header": 1 if setup.header else -1,
        "number_columns": len(setup.column_names),
        "column_names": list(setup.column_names),
        "column_types": [t.capitalize() for t in setup.column_types],
        "na_strings": None,
        "destination_frame": dest,
        "chunk_size": 4194304,
        "total_filtered_column_count": len(setup.column_names),
    }


@route("POST", "/3/Parse")
def _parse(params, body):
    from h2o3_tpu.ingest.parse import parse, parse_setup
    src = _coerce(params.get("source_frames", "[]"))
    paths = _raw_paths(src)
    dest = params.get("destination_frame") or (
        os.path.basename(paths[0]) + ".hex")
    col_names = _coerce(params.get("column_names")) or None
    col_types = _coerce(params.get("column_types")) or None
    if col_types:
        col_types = [str(t).lower() for t in col_types]
    sep = params.get("separator")
    if sep and str(sep).isdigit():
        sep = chr(int(sep))
    chk = params.get("check_header")
    header = None if chk in (None, "0") else (str(chk) == "1")

    job = Job(f"Parse {paths[0]}", key=None)
    # write-lock the destination against double-parses
    # (water/Lockable.java:25 "Parser should write-lock the output Frame")
    dkv.write_lock(dest, job.key)

    def body_fn(j):
        try:
            setup = parse_setup(paths, separator=sep, header=header,
                                column_names=col_names,
                                column_types=col_types)
            fr = parse(paths, setup, key=dest)
            dkv.put(dest, "frame", fr)
            return fr
        finally:
            dkv.unlock_all(j.key)

    job.run(body_fn, background=True)
    return {"__meta": {"schema_version": 3, "schema_name": "ParseV3"},
            "job": schemas.job_v3(job, dest, "Key<Frame>"),
            "destination_frame": schemas.keyref(dest, "Key<Frame>")}


@route("GET", "/3/Jobs/{key}")
def _get_job(params, body, key):
    job = get_job(key)
    if job is None:
        raise ApiError(404, f"job not found: {key}")
    dest = getattr(job, "dest_key", None)
    return {"__meta": {"schema_version": 3, "schema_name": "JobsV3"},
            "jobs": [schemas.job_v3(job, dest)]}


@route("POST", "/3/Jobs/{key}/cancel")
def _cancel_job(params, body, key):
    job = get_job(key)
    if job is None:
        raise ApiError(404, f"job not found: {key}")
    job.cancel()
    return {"jobs": [schemas.job_v3(job, getattr(job, "dest_key", None))]}


@route("GET", "/3/Frames/{key}")
def _get_frame(params, body, key):
    fr = dkv.get(key, "frame")
    rc = int(params.get("row_count", 10) or 10)
    cc = int(params.get("column_count", -1) or -1)
    ro = int(params.get("row_offset", 0) or 0)
    co = int(params.get("column_offset", 0) or 0)
    return schemas.frames_v3([schemas.frame_v3(fr, key, rc, cc, ro, co)])


@route("GET", "/3/Frames/{key}/summary")
def _frame_summary(params, body, key):
    fr = dkv.get(key, "frame")
    return schemas.frames_v3([schemas.frame_v3(fr, key, 0)])


@route("GET", "/3/Frames")
def _list_frames(params, body):
    return schemas.frames_v3(
        [schemas.frame_v3(dkv.get(k, "frame"), k, 0)
         for k in dkv.keys("frame")])


@route("DELETE", "/3/Frames/{key}")
def _del_frame(params, body, key):
    dkv.check_unlocked(key)    # refuse deleting a job's in-use frame
    dkv.remove(key)
    return {}


@route("DELETE", "/3/DKV/{key}")
def _del_key(params, body, key):
    dkv.check_unlocked(key)
    dkv.remove(key)
    return {}


@route("DELETE", "/3/DKV")
def _del_keys(params, body):
    retained = set(_coerce(params.get("retained_keys", "[]")) or [])
    for k in list(dkv.keys()):
        if k not in retained:
            try:
                dkv.check_unlocked(k)
            except dkv.KeyLockedError:
                continue       # bulk clear skips in-use keys
            dkv.remove(k)
    return {}


@route("GET", "/3/Models")
def _list_models(params, body):
    return schemas.models_v3(
        [schemas.model_v3(dkv.get(k, "model"), k)
         for k in dkv.keys("model")])


@route("GET", "/3/Models/{key}")
def _get_model(params, body, key):
    m = dkv.get(key, "model")
    return schemas.models_v3([schemas.model_v3(m, key)])


@route("DELETE", "/3/Models/{key}")
def _del_model(params, body, key):
    dkv.check_unlocked(key)
    dkv.remove(key)
    return {}


@route("POST", "/3/ModelBuilders/{algo}")
def _train(params, body, algo):
    builders = _builders()
    if algo not in builders:
        raise ApiError(404, f"unknown algorithm '{algo}'; have "
                            f"{sorted(builders)}")
    # key-like and name-like params stay raw strings — _coerce would turn
    # model_id="123" into an int DKV key and response_column="none" to None
    raw_keep = {k: params[k] for k in ("model_id", "training_frame",
                                       "validation_frame",
                                       "response_column", "fold_column",
                                       "weights_column", "offset_column",
                                       "regex", "path")
                if k in params}
    defaults = builders[algo]().params
    parms = {k: _coerce_typed(k, v, defaults) for k, v in params.items()}
    parms.update(raw_keep)
    train_key = parms.pop("training_frame", None)
    if isinstance(train_key, dict):
        train_key = train_key.get("name")
    if not train_key:
        # Generic imports an artifact — the only builder with no frame
        if algo != "generic":
            raise ApiError(400, "training_frame is required")
        frame = None
    else:
        frame = dkv.get(str(train_key), "frame")
    valid = None
    vk = parms.pop("validation_frame", None)
    if vk:
        valid = dkv.get(str(vk if not isinstance(vk, dict) else vk["name"]),
                        "frame")
    y = parms.pop("response_column", None)
    ignored = parms.pop("ignored_columns", None)
    model_id = parms.pop("model_id", None) or dkv.unique_key(f"{algo}_model")
    parms = {k: v for k, v in parms.items() if v is not None}
    if ignored:
        parms["ignored_columns"] = ignored
    est = builders[algo](**parms)

    # cooperative locking (water/Lockable.java:25): inputs read-locked,
    # output model write-locked for the build's duration — a concurrent
    # DELETE of the training frame now fails instead of racing the job.
    # The owner is a synthetic key (the training job doesn't exist yet);
    # partial acquisition must release what it took.
    lock_owner = f"$train_{model_id}"
    try:
        if train_key:
            dkv.read_lock(str(train_key), lock_owner)
        if vk:
            dkv.read_lock(str(vk if not isinstance(vk, dict)
                              else vk["name"]), lock_owner)
        dkv.write_lock(model_id, lock_owner)
    except dkv.KeyLockedError:
        dkv.unlock_all(lock_owner)
        raise
    # the client polls the TRAINING job itself (no wrapper Job): the
    # scheduler's QUEUED state, queue_wait_s and preempt_count surface
    # on the key this response returns (ISSUE 15 — a wrapper job showed
    # RUNNING with msec growing through the whole queue wait). Builders
    # that override train() and swallow background= complete
    # synchronously; est.job exists either way.
    try:
        est.train(y=y, training_frame=frame, validation_frame=valid,
                  background=True)
    except BaseException:
        dkv.unlock_all(lock_owner)
        raise
    job = est.job
    job.dest_key = model_id

    def _register():
        try:
            model = job.join()    # raises RuntimeError on FAILED
            if model is None:
                return            # cancelled before any result
            model.key = model_id
            # frame-first metric lookups + FeatureInteraction default
            # frame resolve through this backref
            model.training_frame_key = str(train_key) if train_key \
                else None
            # fold models get DKV keys so the advertised
            # cross_validation_models keyrefs resolve (ModelSchemaV3)
            for i, fm in enumerate(
                    model.output.get("cross_validation_models") or []):
                fm.key = f"{model_id}_cv_{i + 1}"
                dkv.put(fm.key, "model", fm)
            dkv.put(model_id, "model", model)
        except RuntimeError:
            pass   # FAILED: the job carries the structured failure info
        finally:
            dkv.unlock_all(lock_owner)

    threading.Thread(target=_register, daemon=True,
                     name=f"train-register-{model_id}").start()
    return {
        "__meta": {"schema_version": 3,
                   "schema_name": "%sV3" % algo.upper()},
        "job": schemas.job_v3(job, model_id),
        "algo": algo,
        "messages": [],
        "error_count": 0,
        "parameters": [{"name": k, "actual_value": v}
                       for k, v in est.params.items()
                       if isinstance(v, (int, float, str, bool, list,
                                         type(None)))],
        "__http_status": 200,
    }


def _kind_of(m) -> str:
    return ("Binomial" if m.nclasses == 2 else
            "Multinomial" if m.nclasses > 2 else "Regression")


def _start_predict_job(model, frame, dest=None, options=None):
    """Scoring job honoring hex/Model.java scoring options: plain
    predictions, predict_contributions (TreeSHAP), leaf_node_assignment,
    predict_staged_proba (water/api/ModelMetricsHandler.java predict)."""
    m = dkv.get(model, "model")
    fr = dkv.get(frame, "frame")
    dest = dest or dkv.unique_key("prediction")
    job = Job(f"prediction {model} on {frame}")
    job.dest_key = dest
    job.dest_type = "Key<Frame>"
    # h2o-py serializes booleans via str() — route every option through
    # _coerce so "False" doesn't arrive truthy
    opts = {k: _coerce(v) for k, v in (options or {}).items()}

    def body_fn(j):
        if opts.get("predict_contributions"):
            of = str(opts.get("predict_contributions_output_format")
                     or "Original").lower()
            pred = m.predict_contributions(
                fr, output_format=of,
                top_n=int(opts.get("top_n") or 0),
                bottom_n=int(opts.get("bottom_n") or 0),
                compare_abs=bool(opts.get("compare_abs")))
        elif opts.get("leaf_node_assignment"):
            pred = m.predict_leaf_node_assignment(
                fr, type=str(opts.get("leaf_node_assignment_type")
                             or "Path"))
        elif opts.get("predict_staged_proba"):
            pred = m.staged_predict_proba(fr)
        else:
            pred = m.predict(fr)
        dkv.put(dest, "frame", pred)
        return pred

    job.run(body_fn, background=True)
    return m, fr, dest, job


@route("POST", "/4/Predictions/models/{model}/frames/{frame}")
def _predict_async(params, body, model, frame):
    """Async bulk scoring: the reference returns a BARE JobV3
    (water/api/RegisterV3Api.java:363 → ModelMetricsHandler.predictAsync
    :467); h2o-py wraps it in H2OJob, polls, then fetches the dest frame.
    Returning a ModelMetricsListSchemaV3 here instead breaks the client:
    H2OResponse dispatches any schema starting with 'ModelMetrics' to a
    metrics object and H2OJob.__init__ chokes on it."""
    m, fr, dest, job = _start_predict_job(
        model, frame, params.get("predictions_frame"), options=params)
    return schemas.job_v3(job, dest, "Key<Frame>")


@route("POST", "/3/Predictions/models/{model}/frames/{frame}")
def _predict(params, body, model, frame):
    """Sync scoring + metrics (hex/Model.java:1919 score → BigScore)."""
    m, fr, dest, job = _start_predict_job(
        model, frame, params.get("predictions_frame"), options=params)
    job.join()
    perf = None
    try:
        mm = m.model_performance(fr)
        perf = schemas._metrics_v3(mm, _kind_of(m),
                                   domain=list(m.response_domain or []) or None,
                                   frame_key=frame, model_key=model)
    except Exception:
        perf = None
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelMetricsListSchemaV3"},
            "model_metrics": [perf] if perf else [],
            "job": schemas.job_v3(job, dest, "Key<Frame>"),
            "predictions_frame": schemas.keyref(dest, "Key<Frame>")}


# ---------------- serving subsystem (h2o3_tpu.serve) -------------------
# No reference analog: h2o-3's only online path is frame-batch predict.
# deploy warms per-bucket compiled predict executables; rows score
# through the micro-batching queue (ISSUE 3).


def _lane_of(params, default: str = "interactive") -> str:
    """The request's deadline class (ISSUE 20): explicit ``X-H2O3-Lane``
    header (injected as ``_lane`` by the dispatcher) > ``lane``
    body/query param > the endpoint's path default. Unknown lane names
    are a 400 — a typo must not silently ride the highest class."""
    from h2o3_tpu.serve import lanes as lanes_mod
    lane = params.get("_lane") or params.get("lane")
    try:
        return lanes_mod.normalize(str(lane)) if lane else default
    except ValueError as e:
        raise ApiError(400, str(e))


def _fleet_epoch_headers() -> Optional[Dict[str, str]]:
    """``X-H2O3-Fleet-Epoch`` on scoring responses: the membership
    epoch this replica last heard — the affinity client's staleness
    signal (a mismatch with its pinned ring triggers a refresh).
    None outside a fleet: solo deployments add no header."""
    from h2o3_tpu.serve import fleet as serve_fleet
    ep = serve_fleet.fleet_epoch()
    return {"X-H2O3-Fleet-Epoch": str(ep)} if ep is not None else None


def _ndjson(rows) -> bytes:
    """Streamed scoring body: one JSON object per line (NDJSON). The
    shape is the per-row dict of the ``rows`` format — a streamed and
    a batched response decode to bit-identical values."""
    return ("\n".join(json.dumps(r, default=_json_default)
                      for r in rows) + "\n").encode()


def _serve_config_from_params(params) -> Dict[str, Any]:
    cfg: Dict[str, Any] = {}
    for k, cast in (("max_batch", int), ("max_delay_ms", float),
                    ("queue_limit", int), ("timeout_ms", float),
                    ("circuit_failures", int), ("circuit_open_ms", float)):
        v = _coerce(params.get(k)) if params.get(k) is not None else None
        if v is not None:
            cfg[k] = cast(v)
    b = _coerce(params.get("buckets")) if params.get("buckets") else None
    if b:
        cfg["buckets"] = [int(x) for x in
                          (b if isinstance(b, list) else _bracket_list(b))]
    return cfg


@route("POST", "/3/Serve/models/{model}")
def _serve_deploy(params, body, model):
    """Deploy a model for low-latency row serving: pre-encode the
    column/domain spec, warm compiled predict executables at the batch
    buckets, start the micro-batcher. Knobs: max_batch, max_delay_ms,
    queue_limit, timeout_ms, buckets."""
    from h2o3_tpu import serve
    try:
        dep = serve.deploy(model, **_serve_config_from_params(params))
    except KeyError as e:
        raise ApiError(404, str(e))
    except ValueError as e:
        raise ApiError(400, str(e))
    return schemas.serve_deployment_v3(dep)


@route("DELETE", "/3/Serve/models/{model}")
def _serve_undeploy(params, body, model):
    from h2o3_tpu import serve
    if not serve.undeploy(model):
        raise ApiError(404, f"model '{model}' is not deployed")
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ServeDeploymentV3"},
            "model_id": schemas.keyref(model, "Key<Model>"),
            "undeployed": True}


@route("GET", "/3/Serve/models")
def _serve_list(params, body):
    from h2o3_tpu import serve
    return {"__meta": {"schema_version": 3, "schema_name": "ServeModelsV3"},
            "deployments": [schemas.serve_deployment_v3(d)
                            for d in serve.deployments()]}


@route("GET", "/3/Serve/models/{model}")
def _serve_get(params, body, model):
    from h2o3_tpu import serve
    dep = serve.deployment(model)
    if dep is None:
        raise ApiError(404, f"model '{model}' is not deployed")
    return schemas.serve_deployment_v3(dep)


@route("GET", "/3/Serve/stats")
def _serve_stats(params, body):
    from h2o3_tpu import serve
    return schemas.serve_stats_v3(serve.stats())


# ---------------- fleet front door (h2o3_tpu.fleet) --------------------
# Membership + routing: replicas join/heartbeat/leave against THIS
# process's member table (the SURVEY §L1 heartbeat-cloud shape over
# REST), and /3/Fleet/models/{m}/rows proxies a scoring request to the
# consistent-hash home replica with single failover (ISSUE 13).


def _fleet_body(params, body) -> Dict[str, Any]:
    """Fleet control-plane payloads arrive as JSON bodies (the agent's
    spelling) or form/query params (curl-friendly)."""
    out: Dict[str, Any] = {}
    if body:
        try:
            out.update(json.loads(body.decode()))
        except (json.JSONDecodeError, UnicodeDecodeError):
            pass
    for k, v in params.items():
        out.setdefault(k, _coerce(v) if isinstance(v, str) else v)
    return out


@route("GET", "/3/Fleet")
def _fleet_view(params, body):
    """Membership view: epoch, members with per-member phi suspicion /
    load / deployments, recent departures."""
    from h2o3_tpu import fleet
    return {"__meta": {"schema_version": 3, "schema_name": "FleetV3"},
            **fleet.router().table.view()}


@route("POST", "/3/Fleet/join")
def _fleet_join(params, body):
    """Admit (or re-admit) a replica. Response carries the incarnation
    token fencing its heartbeats, the current epoch, and the registry
    snapshot the replica pre-warms from before marking routable."""
    from h2o3_tpu import fleet, serve
    b = _fleet_body(params, body)
    member_id = b.get("member_id")
    base_url = b.get("base_url")
    if not member_id or not base_url:
        raise ApiError(400, "join requires member_id and base_url")
    hb_ms = b.get("heartbeat_ms")
    m = fleet.router().table.join(
        str(member_id), str(base_url),
        heartbeat_s=(float(hb_ms) / 1000.0 if hb_ms else None),
        deployments=tuple(b.get("deployments") or ()),
        routable=bool(b.get("routable", False)))
    # elastic membership (ISSUE 18): a replica joining mid-grid absorbs
    # queued children — throttled, off-thread, never fails the join
    from h2o3_tpu.fleet import sched as fleet_sched
    fleet_sched.maybe_rebalance("join")
    return {"__meta": {"schema_version": 3, "schema_name": "FleetJoinV3"},
            "member_id": m.member_id, "incarnation": m.incarnation,
            "epoch": fleet.router().table.epoch,
            "heartbeat_ms": m.heartbeat_s * 1000.0,
            "registry": serve.registry_snapshot()}


@route("POST", "/3/Fleet/heartbeat")
def _fleet_heartbeat(params, body):
    """One member beat. 404 = unknown member (join first), 409 = stale
    incarnation (a dead epoch cannot resurrect a member — rejoin).
    The response piggybacks every OTHER member's circuit states — the
    push-gossip channel that replaced the telemetry-scrape pull."""
    from h2o3_tpu import fleet
    b = _fleet_body(params, body)
    member_id = str(b.get("member_id") or "")
    table = fleet.router().table
    try:
        table.heartbeat(
            member_id, int(b.get("incarnation") or 0),
            load=float(b.get("load") or 0.0),
            deployments=tuple(b["deployments"])
            if b.get("deployments") is not None else None,
            circuit=b.get("circuit"),
            routable=b.get("routable"),
            sched=b.get("sched") if isinstance(b.get("sched"), dict)
            else None,
            wall=float(b["wall"]) if b.get("wall") is not None else None)
    except fleet.UnknownMemberError as e:
        raise ApiError(404, f"{e} — POST /3/Fleet/join")
    except fleet.StaleEpochError as e:
        raise ApiError(409, str(e))
    gossip = []
    for m in table.members():
        if m.member_id == member_id:
            continue
        for st in m.circuit:
            gossip.append({**st, "source": m.member_id})
    # the fleet-scheduler placement view rides every beat response —
    # each replica learns every peer's headroom at heartbeat latency
    from h2o3_tpu.fleet import sched as fleet_sched
    return {"__meta": {"schema_version": 3,
                       "schema_name": "FleetHeartbeatV3"},
            "ok": True, "epoch": table.epoch, "gossip": gossip,
            "fleet_sched": fleet_sched.fleet_view_from_table(table)}


@route("POST", "/3/Fleet/leave")
def _fleet_leave(params, body):
    from h2o3_tpu import fleet
    b = _fleet_body(params, body)
    left = fleet.router().table.leave(str(b.get("member_id") or ""))
    return {"__meta": {"schema_version": 3, "schema_name": "FleetLeaveV3"},
            "left": bool(left), "epoch": fleet.router().table.epoch}


@route("GET", "/3/Fleet/registry")
def _fleet_registry(params, body):
    """The warm cold-start snapshot: every deployment's model key +
    deploy config (also piggybacked on the join response)."""
    from h2o3_tpu import serve
    return {"__meta": {"schema_version": 3,
                       "schema_name": "FleetRegistryV3"},
            **serve.registry_snapshot()}


@route("POST", "/3/Fleet/models/{model}/rows")
def _fleet_predict(params, body, model):
    """Routed scoring: consistent-hash home-replica dispatch with
    least-loaded fallback and single failover; 503 + Retry-After when
    the live set cannot absorb the request. ``key`` pins the routing
    key (default: the model — all of one model's traffic shares a
    home until it falls back). ``format`` (rows | columnar | stream)
    and ``lane`` (interactive | bulk | background) ride the SAME
    failover path — before ISSUE 20 only the row shape failed over."""
    from h2o3_tpu import fleet
    b = _fleet_body(params, body)
    rows = b.get("rows")
    if not isinstance(rows, list) or not all(
            isinstance(r, dict) for r in rows):
        raise ApiError(400, 'expected {"rows": [{column: value, ...}]}')
    tmo = b.get("timeout_ms")
    fmt = str(b.get("format") or "rows").lower()
    if fmt not in ("rows", "columnar", "stream"):
        raise ApiError(400, f"unknown format '{fmt}' — use 'rows', "
                       f"'columnar' or 'stream'")
    lane = _lane_of(b)
    try:
        out = fleet.router().predict_rows(
            model, rows,
            key=str(b["key"]) if b.get("key") is not None else None,
            timeout_ms=float(tmo) if tmo is not None else None,
            fmt=fmt, lane=lane)
    except fleet.FleetUnavailableError as e:
        import math
        raise ApiError(503, str(e), headers={
            "Retry-After": str(max(int(math.ceil(e.retry_after_s)), 1))})
    except fleet.RouterError as e:
        raise ApiError(getattr(e, "http_status", 500), str(e))
    epoch_headers = {"X-H2O3-Fleet-Epoch": str(fleet.router().table.epoch)}
    if "__raw" in out:
        # streamed scoring passes through opaque — routed and direct
        # NDJSON stay byte-identical
        raw = out["__raw"]
        return {"__raw": raw.encode() if isinstance(raw, str) else raw,
                "__content_type": out.get("__content_type",
                                          "application/x-ndjson"),
                "__headers": epoch_headers}
    out.setdefault("__meta", {"schema_version": 3,
                              "schema_name": "FleetPredictionsV3"})
    out["__headers"] = epoch_headers
    return out


@route("GET", "/3/Fleet/ring")
def _fleet_ring(params, body):
    """The consistent-hash ring view (ISSUE 20): live routable members
    + virtual-point count + epoch. Clients hash keys with the SAME
    blake2b scheme and dispatch straight to the home replica — the
    zero-hop path — refreshing when a scoring response's
    ``X-H2O3-Fleet-Epoch`` disagrees with the epoch pinned here."""
    from h2o3_tpu import fleet
    return {"__meta": {"schema_version": 3, "schema_name": "FleetRingV3"},
            **fleet.router().ring_snapshot()}


@route("GET", "/3/Fleet/snapshot")
def _fleet_snapshot(params, body):
    """Warm-boot source for a (re)starting peer router (ISSUE 20): the
    full member-table snapshot (incarnations included) plus the
    deployment registry — everything a bounced router needs to answer
    its first routed request without waiting for replica beats."""
    from h2o3_tpu import fleet, serve
    return {"__meta": {"schema_version": 3,
                       "schema_name": "FleetSnapshotV3"},
            "epoch": fleet.router().table.epoch,
            "snapshot": fleet.router().table.snapshot(),
            "registry": serve.registry_snapshot()}


@route("POST", "/3/Fleet/gossip")
def _fleet_gossip(params, body):
    """Router-tier anti-entropy (ISSUE 20): absorb a peer router's
    table snapshot (epoch-fenced, incarnation-fenced — membership.py
    rules verbatim) and answer with ours, so one exchange converges
    both sides. The sender's url is adopted as a peer (elastic tier
    membership)."""
    from h2o3_tpu import fleet
    b = _fleet_body(params, body)
    snap = b.get("snapshot")
    if not isinstance(snap, dict):
        raise ApiError(400, 'expected {"snapshot": {...}, "source": url}')
    r = fleet.router()
    absorbed = r.table.absorb(snap, source=str(b.get("source") or "?"))
    if r.tier is not None and b.get("source"):
        r.tier.note_peer(str(b["source"]))
    return {"__meta": {"schema_version": 3,
                       "schema_name": "FleetGossipV3"},
            "absorbed": absorbed, "epoch": r.table.epoch,
            "snapshot": r.table.snapshot()}


@route("POST", "/3/FleetSched/submit")
def _fleet_sched_submit(params, body):
    """Fleet scheduler hand-off target (ISSUE 18): accept a training
    submission placed here by another replica — fresh placement, a
    preempt-migrated checkpoint resume, or an evict-requeue — and run
    it through THIS process's scheduler under the original priority
    class, share group and trace id."""
    from h2o3_tpu import sched
    from h2o3_tpu.fleet import sched as fleet_sched
    if not sched.enabled():
        raise ApiError(503, "this replica's training scheduler is "
                            "disabled (H2O3_SCHED=0)")
    b = _fleet_body(params, body)
    try:
        out = fleet_sched.handle_remote_submit(b)
    except sched.SchedulerSaturatedError as e:
        raise ApiError(503, str(e))
    except ValueError as e:
        raise ApiError(400, str(e))
    out["__meta"] = {"schema_version": 3,
                     "schema_name": "FleetSchedSubmitV3"}
    return out


# ---------------- fault injection admin (h2o3_tpu.faults) --------------
# Chaos tooling surface: inspect/set/clear the deterministic fault spec
# (same grammar as the H2O3_FAULTS env var). No reference analog.


@route("GET", "/3/Faults")
def _faults_get(params, body):
    from h2o3_tpu import faults
    return {"__meta": {"schema_version": 3, "schema_name": "FaultsV3"},
            "spec": faults.spec(), "rules": faults.describe(),
            "fired_total": faults.fired_total()}


@route("POST", "/3/Faults")
def _faults_set(params, body):
    from h2o3_tpu import faults
    spec = params.get("spec")
    if spec is None and body:
        try:
            spec = json.loads(body.decode()).get("spec")
        except (json.JSONDecodeError, UnicodeDecodeError):
            spec = body.decode(errors="replace").strip() or None
    if not spec:
        # a typo'd body must not silently DISARM a live chaos run —
        # clearing is DELETE's job, setting requires a spec
        raise ApiError(400, "POST /3/Faults requires spec=<grammar> "
                            "(use DELETE /3/Faults to clear)")
    try:
        faults.configure(spec)
    except ValueError as e:
        raise ApiError(400, f"bad fault spec: {e}")
    return {"__meta": {"schema_version": 3, "schema_name": "FaultsV3"},
            "spec": faults.spec(), "rules": faults.describe(),
            "fired_total": faults.fired_total()}


@route("DELETE", "/3/Faults")
def _faults_clear(params, body):
    from h2o3_tpu import faults
    faults.configure(None)
    return {"__meta": {"schema_version": 3, "schema_name": "FaultsV3"},
            "spec": None, "rules": [], "fired_total": 0}


# ---------------- training scheduler (h2o3_tpu.sched, ISSUE 15) ---------


@route("GET", "/3/Scheduler")
def _scheduler_get(params, body):
    """Training-scheduler state: queue contents per priority class with
    wait reasons, running entries with their admission estimates, the
    reserved-bytes ledger vs the memman budget, and the sched counters.
    ``?scope=cluster`` merges every replica's snapshot through the
    telemetry peer plane (dead peers flagged, never fatal)."""
    from h2o3_tpu import sched
    if str(params.get("scope") or "").lower() == "cluster":
        from h2o3_tpu.fleet import sched as fleet_sched
        snap = fleet_sched.cluster_scheduler_snapshot()
        snap["__meta"] = {"schema_version": 3,
                          "schema_name": "SchedulerClusterV3"}
        snap["enabled"] = sched.enabled()
        return snap
    snap = sched.scheduler().snapshot()
    snap["__meta"] = {"schema_version": 3, "schema_name": "SchedulerV3"}
    snap["enabled"] = sched.enabled()
    return snap


@route("POST", "/3/Scheduler")
def _scheduler_control(params, body):
    """Control: ``pause=true|false`` stops/starts dispatch (running
    entries finish; the queue holds), ``job=<key>&priority=<class>``
    moves a QUEUED entry to another priority class."""
    from h2o3_tpu import sched
    s = sched.scheduler()
    # validate EVERYTHING before applying ANYTHING: a request that is
    # half-bad must not half-execute (e.g. pause applied, then the
    # reprioritize half 400s — the client sees an error yet dispatch
    # is now paused)
    pause = params.get("pause")
    pause_action = None
    if pause is not None:
        val = str(pause).lower()
        if val in ("1", "true", "yes"):
            pause_action = True
        elif val in ("0", "false", "no"):
            pause_action = False
        else:
            # a typo'd value must not silently RESUME a paused queue
            raise ApiError(400, f"pause={pause!r} is not a boolean "
                                f"(true/false)")
    job_key = params.get("job")
    priority = params.get("priority")
    if (job_key or priority) and not (job_key and priority):
        raise ApiError(400, "reprioritizing needs BOTH job=<key> and "
                            "priority=<class>")
    if priority:
        priority = str(priority).lower()
        if priority not in sched.PRIORITY_LEVELS:
            raise ApiError(400, f"unknown priority '{priority}' (one of "
                                f"{sorted(sched.PRIORITY_LEVELS)})")
    if pause_action is None and not job_key:
        raise ApiError(400, "POST /3/Scheduler needs pause=true|false "
                            "and/or job=<key>&priority=<class>")
    actions = []
    # apply the fallible half FIRST: reprioritize can 404 (the job may
    # have dispatched since the client looked), and a combined request
    # that errors must not have half-executed by flipping pause state
    if job_key:
        if not s.reprioritize(str(job_key), priority):
            raise ApiError(404, f"no QUEUED scheduler entry for job "
                                f"'{job_key}'")
        actions.append(f"reprioritized {job_key} -> {priority}")
    if pause_action is True:
        s.pause()
        actions.append("paused")
    elif pause_action is False:
        s.resume()
        actions.append("resumed")
    snap = s.snapshot()
    snap["__meta"] = {"schema_version": 3, "schema_name": "SchedulerV3"}
    snap["actions"] = actions
    return snap


# ---------------- restart recovery (h2o3_tpu.recovery) ------------------


@route("GET", "/3/Recovery")
def _recovery_get(params, body):
    """Restart-recovery state: the durable dir, pending manifests (with
    their newest resumable checkpoint), and the last boot scan's report
    — what an operator checks after a pod restart to see which trains
    came back."""
    from h2o3_tpu import recovery
    manifests = []
    if recovery.enabled():
        # read-only scan: a monitoring poll must not quarantine corrupt
        # manifests aside before the next BOOT's scan reports them
        entries, corrupt = recovery.scan(quarantine=False)
        manifests = entries
    else:
        corrupt = []
    return {"__meta": {"schema_version": 3, "schema_name": "RecoveryV3"},
            "enabled": recovery.enabled(),
            "dir": recovery.recovery_dir(),
            "manifests": manifests,
            "corrupt": corrupt,
            "last_boot": recovery.last_report()}


@route("POST", "/3/Predictions/models/{model}/rows")
def _predict_rows(params, body, model):
    """Row-level scoring through the micro-batcher: JSON rows in
    ({"rows": [{col: value, ...}, ...]} or a bare list), predictions +
    per-class probabilities out. ``?format=columnar`` returns COLUMN
    arrays ({"columns": {"predict": [...], "p<label>": [...]}}) from
    the batch's one vectorized decode — bit-identical values to the
    per-row dict shape at a fraction of the decode cost for large
    batches. Admission control maps to HTTP: queue-full /
    deadline-expired → 503 (retryable), not-deployed → 404 with deploy
    guidance."""
    from h2o3_tpu import serve
    rows = params.get("rows")
    if rows is None and body:
        try:
            rows = json.loads(body.decode())
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise ApiError(400, f"request body is not JSON rows: {e}")
    if isinstance(rows, str):
        rows = _coerce(rows)
    if isinstance(rows, dict):
        rows = rows.get("rows")
    if not isinstance(rows, list) or not all(
            isinstance(r, dict) for r in rows):
        raise ApiError(400, 'expected {"rows": [{column: value, ...}]}')
    tmo = _coerce(params.get("timeout_ms")) \
        if params.get("timeout_ms") is not None else None
    fmt = (params.get("format") or "rows").lower()
    if fmt not in ("rows", "columnar", "stream"):
        raise ApiError(400, f"unknown format '{fmt}' — use 'rows', "
                       f"'columnar' or 'stream'")
    lane = _lane_of(params)
    epoch_headers = _fleet_epoch_headers()
    try:
        # explicit timeout_ms=0 means fail-fast, NOT the default
        if fmt == "columnar":
            cols = serve.predict_columnar(
                model, rows,
                timeout_ms=float(tmo) if tmo is not None else None,
                lane=lane)
            out = {"__meta": {"schema_version": 3,
                              "schema_name": "ServePredictionsColumnarV3"},
                   "model_id": schemas.keyref(model, "Key<Model>"),
                   "nrow": len(rows),
                   "columns": cols}
            if epoch_headers:
                out["__headers"] = epoch_headers
            return out
        preds = serve.predict_rows(
            model, rows, timeout_ms=float(tmo) if tmo is not None else None,
            lane=lane)
    except KeyError as e:
        raise ApiError(404, str(e))
    except serve.ServeError as e:
        headers = {}
        ra = getattr(e, "retry_after_s", None)
        if ra is not None:
            # circuit-open fast 503s tell clients WHEN to come back
            import math
            headers["Retry-After"] = str(max(int(math.ceil(ra)), 1))
        raise ApiError(getattr(e, "http_status", 500), str(e),
                       headers=headers)
    if fmt == "stream":
        # streamed scoring (NDJSON): same values, one row-dict per
        # line — and the same admission/failover semantics as 'rows'
        # because it IS the rows path up to serialization
        out = {"__raw": _ndjson(preds),
               "__content_type": "application/x-ndjson"}
        if epoch_headers:
            out["__headers"] = epoch_headers
        return out
    out = {"__meta": {"schema_version": 3,
                      "schema_name": "ServePredictionsV3"},
           "model_id": schemas.keyref(model, "Key<Model>"),
           "predictions": preds}
    if epoch_headers:
        out["__headers"] = epoch_headers
    return out


@route("POST", "/3/ModelMetrics/models/{model}/frames/{frame}")
def _model_metrics_score(params, body, model, frame):
    """ModelMetricsHandler.score (water/api/ModelMetricsHandler.java:288):
    score the frame with the model, return fresh metrics (h2o-py
    model_performance)."""
    m = dkv.get(model, "model")
    fr = dkv.get(frame, "frame")
    mm = m.model_performance(fr)
    perf = schemas._metrics_v3(mm, _kind_of(m),
                               domain=list(m.response_domain or []) or None,
                               frame_key=frame, model_key=model)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelMetricsListSchemaV3"},
            "model_metrics": [perf] if perf else []}


@route("GET", "/3/ModelMetrics/models/{model}")
def _model_metrics_list(params, body, model):
    m = dkv.get(model, "model")
    out = []
    for mm in (m.training_metrics, m.validation_metrics,
               m.cross_validation_metrics):
        if mm is not None:
            out.append(schemas._metrics_v3(
                mm, _kind_of(m),
                domain=list(m.response_domain or []) or None,
                model_key=model))
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelMetricsListSchemaV3"},
            "model_metrics": out}


@route("GET", "/99/Models.bin/{model}")
def _save_model_bin(params, body, model):
    """h2o.save_model → GET /99/Models.bin/{id}?dir=...&force=...
    (water/api/ModelsHandler importModel/exportModel pair)."""
    from h2o3_tpu.persist import save_model
    m = dkv.get(model, "model")
    path = params.get("dir") or model
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    if os.path.exists(path) and str(params.get("force", "")
                                    ).lower() != "true":
        raise ApiError(409, f"{path} exists; use force=True")
    out = save_model(m, path=os.path.dirname(path) or ".",
                     force=True, filename=os.path.basename(path))
    return {"__meta": {"schema_version": 3, "schema_name": "ModelExportV3"},
            "dir": out}


@route("POST", "/99/Models.bin/{model}")
@route("POST", "/99/Models.bin/")
def _load_model_bin(params, body, model=""):
    from h2o3_tpu.persist import load_model
    path = params.get("dir")
    if not path or not os.path.exists(path):
        raise ApiError(404, f"model artifact not found: {path}")
    m = load_model(path)
    key = m.key or dkv.unique_key("model")
    dkv.put(key, "model", m)
    return {"__meta": {"schema_version": 3, "schema_name": "ModelsV3"},
            "models": [{"model_id": schemas.keyref(key, "Key<Model>")}]}


@route("POST", "/3/LogAndEcho")
def _log_echo(params, body):
    return {"message": params.get("message", "")}


@route("GET", "/3/DownloadDataset")
@route("GET", "/3/DownloadDataset.bin")
def _download_dataset(params, body):
    """Frame → CSV stream (water/api/DownloadDataHandler); h2o-py
    as_data_frame/get_frame_data parse this client-side."""
    from h2o3_tpu.persist import export_file
    key = params.get("frame_id")
    if isinstance(key, dict):
        key = key.get("name")
    fr = dkv.get(str(key), "frame")
    tmp = os.path.join(tempfile.gettempdir(),
                       f"h2o_dl_{uuid.uuid4().hex[:8]}.csv")
    try:
        export_file(fr, tmp, force=True)
        with open(tmp, "rb") as f:
            data = f.read()
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return {"__raw": data, "__content_type": "text/csv"}


@route("GET", "/3/Metadata/endpoints")
def _endpoints(params, body):
    return {"routes": [{"http_method": m, "url_pattern": rx.pattern}
                       for m, rx, _ in _ROUTES]}


_ERROR_FIELDS = ["timestamp", "error_url", "msg", "dev_msg", "http_status",
                 "values", "exception_type", "exception_msg", "stacktrace"]


@route("GET", "/3/Metadata/schemas/{name}")
def _schema_meta(params, body, name):
    """Schema metadata (water/api/MetadataHandler fetchSchemaMetadata) —
    h2o-py defines H2OCluster/H2OErrorV3 properties from the field list
    at connect time (h2o-py/h2o/schemas/schema.py:29)."""
    if name == "CloudV3":
        keys = [k for k in schemas.cloud_v3() if k != "__meta"]
    elif name == "H2OErrorV3":
        keys = list(_ERROR_FIELDS)
    elif name == "H2OModelBuilderErrorV3":
        keys = _ERROR_FIELDS + ["parameters", "messages", "error_count"]
    else:
        keys = []
    fields = [{"name": k, "help": k, "type": "string", "is_schema": False,
               "schema_name": None} for k in keys]
    return {"__meta": {"schema_version": 3, "schema_name": "MetadataV3"},
            "schemas": [{"name": name, "fields": fields}], "routes": []}


@route("POST", "/99/Grid/{algo}")
def _grid_build(params, body, algo):
    """Grid search over REST (water/api/GridSearchHandler; h2o-py
    grid_search.py:414 wraps the returned job and then fetches
    /99/Grids/{id})."""
    from h2o3_tpu.models.grid import H2OGridSearch
    builders = _builders()
    if algo not in builders:
        raise ApiError(404, f"unknown algorithm '{algo}'")
    raw_keep = {k: params[k] for k in ("grid_id", "model_id",
                                       "training_frame", "validation_frame",
                                       "response_column", "fold_column",
                                       "weights_column", "offset_column")
                if k in params}
    defaults = builders[algo]().params
    parms = {k: _coerce_typed(k, v, defaults) for k, v in params.items()}
    parms.update(raw_keep)
    hyper = parms.pop("hyper_parameters", None) or {}
    if isinstance(hyper, str):
        hyper = json.loads(hyper)
    criteria = parms.pop("search_criteria", None) or {}
    if isinstance(criteria, str):
        criteria = json.loads(criteria)
    gid = parms.pop("grid_id", None) or dkv.unique_key(f"{algo}_grid")
    par = int(parms.pop("parallelism", 1) or 1)
    train_key = parms.pop("training_frame", None)
    frame = dkv.get(str(train_key), "frame")
    valid = None
    vk = parms.pop("validation_frame", None)
    if vk:
        valid = dkv.get(str(vk), "frame")
    y = parms.pop("response_column", None)
    parms = {k: v for k, v in parms.items() if v is not None}
    parms.pop("_rest_version", None)
    est = builders[algo](**parms)
    grid = H2OGridSearch(est, hyper, search_criteria=criteria or None,
                         parallelism=par)

    job = Job(f"{algo} grid search")
    job.dest_key = gid
    # same Lockable contract as /3/ModelBuilders: inputs read-locked,
    # output grid write-locked for the search's duration
    try:
        dkv.read_lock(str(train_key), job.key)
        if vk:
            dkv.read_lock(str(vk), job.key)
        dkv.write_lock(gid, job.key)
    except dkv.KeyLockedError:
        dkv.unlock_all(job.key)
        job.cancel()
        raise

    def body_fn(j):
        try:
            grid.train(y=y, training_frame=frame, validation_frame=valid)
            for i, m in enumerate(grid.models):
                mid = f"{gid}_model_{i}"
                m.key = mid
                dkv.put(mid, "model", m)
            dkv.put(gid, "grid", grid)
            return grid
        finally:
            dkv.unlock_all(j.key)

    job.run(body_fn, background=True)
    return {"__meta": {"schema_version": 99, "schema_name": "GridSearchV99"},
            "job": schemas.job_v3(job, gid, "Key<Grid>"),
            "grid_id": schemas.keyref(gid, "Key<Grid>")}


@route("GET", "/99/Grids/{gid}")
def _grid_get(params, body, gid):
    grid = dkv.get(gid, "grid")
    return {"__meta": {"schema_version": 99, "schema_name": "GridSchemaV99"},
            "grid_id": schemas.keyref(gid, "Key<Grid>"),
            "model_ids": [schemas.keyref(m.key, "Key<Model>")
                          for m in grid.models],
            "hyper_names": list(grid.hyper_params.keys()),
            "failed_params": [], "failure_details": [],
            "failure_stack_traces": [], "failed_raw_params": [],
            "warning_details": [],
            "export_checkpoints_dir": None,
            "summary_table": None, "scoring_history": None}


@route("GET", "/99/Grids")
def _grids_list(params, body):
    return {"grids": [{"grid_id": schemas.keyref(k, "Key<Grid>")}
                      for k in dkv.keys("grid")]}


@route("GET", "/99/Models/{key}")
def _get_model_99(params, body, key):
    return _get_model(params, body, key)


def _automl_tables(aml):
    lb = aml.leaderboard
    metric = lb.metric if lb.rows else "auc"
    table = schemas.twodim(
        "Leaderboard", ["model_id", metric],
        [[r["model_id"] for r in lb.rows],
         [r[metric] for r in lb.rows]], ["string", "double"])
    n_ev = len(aml.event_log)
    # EventLogEntry schema: timestamp/level/stage/message/name/value —
    # h2o-py _fetch() slices el[el['name'] != '', ['name', 'value']]
    ev = schemas.twodim(
        "Event Log",
        ["timestamp", "level", "stage", "message", "name", "value"],
        [[str(e["timestamp"]) for e in aml.event_log],
         ["Info"] * n_ev,
         [e["stage"] for e in aml.event_log],
         [e["message"] for e in aml.event_log],
         [""] * n_ev, [""] * n_ev],
        ["string"] * 6)
    return table, ev


@route("POST", "/99/AutoMLBuilder")
def _automl_build(params, body):
    """AutoML over REST (water/api + ai/h2o/automl; h2o-py
    _estimator.py:668 posts {build_control, input_spec, build_models} and
    polls the returned job)."""
    from h2o3_tpu.automl import H2OAutoML
    spec = params if isinstance(params, dict) else {}
    bc = spec.get("build_control") or {}
    ins = spec.get("input_spec") or {}
    bm = spec.get("build_models") or {}
    sc = bc.get("stopping_criteria") or {}

    def keyname(v):
        return v.get("name") if isinstance(v, dict) else v

    project = bc.get("project_name") or dkv.unique_key("automl")
    train_key = keyname(ins.get("training_frame"))
    frame = dkv.get(str(train_key), "frame")
    valid = None
    if ins.get("validation_frame"):
        valid = dkv.get(str(keyname(ins["validation_frame"])), "frame")
    lb_frame = None
    if ins.get("leaderboard_frame"):
        lb_frame = dkv.get(str(keyname(ins["leaderboard_frame"])), "frame")
    y = ins.get("response_column")
    if isinstance(y, dict):
        y = y.get("column_name")
    ignored = ins.get("ignored_columns") or None
    x = None
    if ignored:
        x = [n for n in frame.names if n not in ignored and n != y]
    def _num(v, default):
        # explicit 0 is a real value (seed=0 pins the RNG) — only
        # missing/empty falls back
        return default if v in (None, "") else v

    aml = H2OAutoML(
        max_models=sc.get("max_models"),
        max_runtime_secs=sc.get("max_runtime_secs"),
        max_runtime_secs_per_model=sc.get("max_runtime_secs_per_model"),
        nfolds=bc.get("nfolds", 3),
        seed=_num(sc.get("seed"), -1),
        sort_metric=ins.get("sort_metric"),
        include_algos=bm.get("include_algos"),
        exclude_algos=bm.get("exclude_algos"),
        project_name=project,
        exploitation_ratio=_num(bm.get("exploitation_ratio"), -1.0))
    dkv.put(project, "automl", aml)

    job = Job(f"AutoML {project}")
    job.dest_key = project
    try:
        dkv.read_lock(str(train_key), job.key)
        if ins.get("validation_frame"):
            dkv.read_lock(str(keyname(ins["validation_frame"])), job.key)
        if ins.get("leaderboard_frame"):
            dkv.read_lock(str(keyname(ins["leaderboard_frame"])), job.key)
    except dkv.KeyLockedError:
        dkv.unlock_all(job.key)
        job.cancel()
        raise

    def body_fn(j):
        try:
            aml.train(x=x, y=y, training_frame=frame,
                      validation_frame=valid, leaderboard_frame=lb_frame)
            return aml
        finally:
            dkv.unlock_all(j.key)

    job.run(body_fn, background=True)
    return {"__meta": {"schema_version": 99, "schema_name": "AutoMLBuilderV99"},
            "job": schemas.job_v3(job, project, "Key<AutoML>"),
            "build_control": {"project_name": project}}


@route("GET", "/99/AutoML/{project}")
def _automl_get(params, body, project):
    aml = dkv.get(project, "automl")
    table, ev = _automl_tables(aml)
    return {"__meta": {"schema_version": 99, "schema_name": "AutoMLV99"},
            "project_name": project,
            "leaderboard": {"models": [schemas.keyref(m.key, "Key<Model>")
                                       for m in aml.models]},
            "leaderboard_table": table,
            "event_log_table": ev}


@route("GET", "/99/Leaderboards/{project}")
def _leaderboard_get(params, body, project):
    aml = dkv.get(project, "automl")
    table, _ev = _automl_tables(aml)
    return {"__meta": {"schema_version": 99,
                       "schema_name": "LeaderboardV99"},
            "project_name": project, "table": table}


@route("GET", "/3/ModelBuilders")
def _model_builders(params, body):
    """Algo registry (water/api/ModelBuildersHandler list)."""
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelBuildersV3"},
            "model_builders": {a: {"algo": a, "visibility": "Stable",
                                   "algo_full_name": a.upper()}
                               for a in sorted(_builders())}}


@route("GET", "/3/ModelBuilders/{algo}")
def _model_builder_meta(params, body, algo):
    builders = _builders()
    if algo not in builders:
        raise ApiError(404, f"unknown algorithm '{algo}'")
    est = builders[algo]()
    parameters = [{"name": k,
                   "default_value": list(v) if isinstance(v, tuple) else v,
                   "actual_value": list(v) if isinstance(v, tuple) else v,
                   "label": k, "type": type(v).__name__, "level": "critical",
                   "values": []}
                  for k, v in est.params.items()
                  if isinstance(v, (int, float, str, bool, list, tuple,
                                    type(None)))]
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelBuildersV3"},
            "model_builders": {algo: {"algo": algo,
                                      "parameters": parameters}}}


@route("GET", "/3/Jobs")
def _jobs_list(params, body):
    from h2o3_tpu.jobs import list_jobs
    return {"__meta": {"schema_version": 3, "schema_name": "JobsV3"},
            "jobs": [schemas.job_v3(j, getattr(j, "dest_key", None))
                     for j in list_jobs()]}


@route("GET", "/3/Typeahead/files")
def _typeahead(params, body):
    """Path completion (water/api/TypeaheadHandler)."""
    src = params.get("src") or "/"
    limit = int(params.get("limit", 100) or 100)
    base = src if os.path.isdir(src) else os.path.dirname(src) or "/"
    prefix = "" if os.path.isdir(src) else os.path.basename(src)
    try:
        entries = sorted(os.listdir(base))
    except OSError:
        entries = []
    matches = [os.path.join(base, e) for e in entries
               if e.startswith(prefix)][:limit]
    return {"__meta": {"schema_version": 3, "schema_name": "TypeaheadV3"},
            "src": src, "limit": limit, "matches": matches}


@route("GET", "/3/Capabilities")
@route("GET", "/3/Capabilities/Core")
def _capabilities(params, body):
    return {"__meta": {"schema_version": 3,
                       "schema_name": "CapabilitiesV3"},
            "capabilities": [{"name": a, "category": "Algos"}
                             for a in sorted(_builders())]}


@route("POST", "/3/SplitFrame")
def _split_frame(params, body):
    """water/api/SplitFrameHandler: ratios → destination frames."""
    from h2o3_tpu.frame.frame import Frame
    key = _coerce(params.get("dataset"))
    if isinstance(key, dict):
        key = key.get("name")
    fr = dkv.get(str(key), "frame")
    ratios = _coerce(params.get("ratios", "[0.75]")) or [0.75]
    dests = _bracket_list(params.get("destination_frames", "")) or None
    seed_p = params.get("seed")
    seed = int(seed_p) if seed_p not in (None, "") else -1
    parts = fr.split_frame(ratios=[float(r) for r in ratios], seed=seed)
    keys = []
    for i, p in enumerate(parts):
        k = (dests[i] if dests and i < len(dests)
             else dkv.unique_key("split"))
        dkv.put(k, "frame", p)
        keys.append(k)
    job = Job("SplitFrame")
    job.dest_key = keys[0] if keys else None
    job.run(lambda j: None, background=False)
    return {"__meta": {"schema_version": 3, "schema_name": "SplitFrameV3"},
            "key": schemas.keyref(job.key, "Key<Job>"),
            "job": schemas.job_v3(job, job.dest_key, "Key<Frame>"),
            "destination_frames": [schemas.keyref(k, "Key<Frame>")
                                   for k in keys]}


@route("POST", "/3/GarbageCollect")
def _gc(params, body):
    import gc
    gc.collect()
    return {}


@route("GET", "/3/JStack")
def _jstack(params, body):
    """Thread dumps (water/util/JStackCollectorTask → /3/JStack)."""
    import traceback
    frames = sys._current_frames()
    traces = []
    for tid, frm in frames.items():
        traces.append({"thread_id": tid,
                       "stack": "".join(traceback.format_stack(frm))})
    return {"__meta": {"schema_version": 3, "schema_name": "JStackV3"},
            "traces": [{"node": "127.0.0.1:54321",
                        "thread_traces": traces}]}


@route("POST", "/3/Shutdown")
def _shutdown(params, body):
    """Accepted but ignored: single-controller process lifetime belongs
    to the host (the reference kills the JVM here)."""
    return {}


@route("POST", "/99/Rapids")
def _rapids(params, body):
    from h2o3_tpu.rapids import exec_rapids
    ast = params.get("ast", "")
    # numpy>=2 compatibility for the UNMODIFIED client: h2o-py pins
    # numpy<2 and str()-serializes column names; under numpy 2 a
    # np.str_ reprs as np.str_('name') and leaks into the ast
    ast = re.sub(r"np\.str_\('([^']*)'\)", r'"\1"', ast)
    session = params.get("session_id")
    try:
        return exec_rapids(ast, session)
    except Exception as e:
        # surface WHICH expression failed — rapids errors without the
        # ast are undebuggable from the client side (ValueError: not
        # every exception type reconstructs from one string)
        raise ValueError(
            f"{type(e).__name__}: {e} [ast: {str(ast)[:400]}]") from e


# ---------------- HTTP plumbing ----------------------------------------

class _Handler(BaseHTTPRequestHandler):
    server_version = "h2o3-tpu/3.46"

    def log_message(self, fmt, *args):  # quiet by default
        if os.environ.get("H2O3_API_LOG"):
            super().log_message(fmt, *args)

    def _dispatch(self, method):
        from h2o3_tpu.telemetry import trace as teletrace
        # trace propagation (ISSUE 8): accept a W3C traceparent header
        # (or mint a fresh id), bind it to this handler thread for the
        # whole request — every span/job the handler touches inherits
        # it — and echo it back on the response
        self._trace_id = teletrace.parse_traceparent(
            self.headers.get(teletrace.TRACEPARENT_HEADER)) \
            or teletrace.new_trace_id()
        with teletrace.trace_context(self._trace_id):
            self._dispatch_traced(method)

    def _dispatch_traced(self, method):
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path
        params = {k: v[0] for k, v in
                  urllib.parse.parse_qs(parsed.query).items()}
        body = b""
        try:
            clen = int(self.headers.get("Content-Length") or 0)
            if clen:
                body = self.rfile.read(clen)
            ctype = self.headers.get("Content-Type", "")
            if body and "application/x-www-form-urlencoded" in ctype:
                params.update({k: v[0] for k, v in
                               urllib.parse.parse_qs(body.decode()).items()})
            elif body and "application/json" in ctype:
                try:
                    params.update(json.loads(body.decode()))
                except json.JSONDecodeError:
                    pass
        except Exception as e:  # malformed body → JSON error, not a reset
            self._reply(400, {"__meta": {"schema_name": "H2OErrorV3"},
                              "http_status": 400, "msg": str(e),
                              "exception_type": type(e).__name__,
                              "values": {}, "stacktrace": []})
            return
        # deadline-class lane (ISSUE 20): an explicit X-H2O3-Lane header
        # outranks body/query params — the router's dispatch spelling
        lane_hdr = self.headers.get("X-H2O3-Lane")
        if lane_hdr:
            params["_lane"] = lane_hdr
        for m, rx, fn in _ROUTES:
            if m != method:
                continue
            match = rx.match(path)
            if match:
                try:
                    groups = {k: urllib.parse.unquote(v)
                              for k, v in match.groupdict().items()}
                    out = fn(params, body, **groups)
                    extra = out.pop("__headers", None) if isinstance(
                        out, dict) else None
                    if isinstance(out, dict) and "__raw" in out:
                        self._reply_raw(200, out["__raw"],
                                        out.get("__content_type",
                                                "application/octet-stream"),
                                        headers=extra)
                        return
                    status = out.pop("__http_status", 200) if isinstance(
                        out, dict) else 200
                    self._reply(status, out, headers=extra)
                except ApiError as e:
                    self._reply(e.status, {
                        "__meta": {"schema_name": "H2OErrorV3"},
                        "http_status": e.status, "msg": str(e),
                        "dev_msg": str(e), "exception_msg": str(e),
                        "exception_type": "ApiError", "values": {},
                        "stacktrace": []}, headers=e.headers)
                except dkv.KeyLockedError as e:
                    self._reply(409, {
                        "__meta": {"schema_name": "H2OErrorV3"},
                        "http_status": 409, "msg": str(e),
                        "dev_msg": str(e), "exception_msg": str(e),
                        "exception_type": "KeyLockedError", "values": {},
                        "stacktrace": []})
                except Exception as e:  # noqa: BLE001 — wire boundary
                    import traceback
                    self._reply(500, {
                        "__meta": {"schema_name": "H2OErrorV3"},
                        "http_status": 500, "msg": str(e),
                        "dev_msg": str(e), "exception_msg": str(e),
                        "exception_type": type(e).__name__, "values": {},
                        "stacktrace": traceback.format_exc().split("\n")})
                return
        self._reply(404, {"__meta": {"schema_name": "H2OErrorV3"},
                          "http_status": 404,
                          "msg": f"no route for {method} {path}",
                          "exception_type": "NotFound", "values": {},
                          "stacktrace": []})

    def _trace_headers(self):
        tid = getattr(self, "_trace_id", None)
        if tid:
            from h2o3_tpu.telemetry import trace as teletrace
            self.send_header(teletrace.TRACEPARENT_HEADER,
                             teletrace.format_traceparent(tid))
            self.send_header("X-H2O3-Trace-Id", tid)

    def _reply_raw(self, status, data: bytes, ctype: str, headers=None):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self._trace_headers()
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    def _reply(self, status, obj, headers=None):
        data = json.dumps(obj, default=_json_default).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self._trace_headers()
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")

    def do_HEAD(self):
        self._dispatch("HEAD")


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        v = float(o)
        return v if np.isfinite(v) else None
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


# ------------- analytics / tooling routes (reference parity set) -------


@route("POST", "/3/CreateFrame")
def _create_frame_route(params, body):
    """water/api/CreateFrameHandler → hex/createframe; h2o.create_frame."""
    from h2o3_tpu.analytics import create_frame
    p = {k: _coerce(v) for k, v in params.items()}
    p.pop("dest", None)
    dest = params.get("dest") or dkv.unique_key("create_frame")
    kw = {k: p[k] for k in ("rows", "cols", "categorical_fraction",
                            "integer_fraction", "binary_fraction",
                            "missing_fraction", "factors", "real_range",
                            "integer_range", "seed", "has_response")
          if p.get(k) is not None}
    job = Job("CreateFrame")
    job.dest_key = dest
    job.dest_type = "Key<Frame>"

    def body_fn(j):
        fr = create_frame(**kw)
        fr.key = dest
        dkv.put(dest, "frame", fr)
        return fr

    job.run(body_fn, background=True)
    return schemas.job_v3(job, dest, "Key<Frame>")


@route("POST", "/3/Interaction")
def _interaction_route(params, body):
    """hex/Interaction via water/api/InteractionHandler; h2o.interaction."""
    from h2o3_tpu.analytics import interaction_frame
    p = {k: _coerce(v) for k, v in params.items()}
    fr = dkv.get(str(params.get("source_frame")), "frame")
    factors = _strlist(params.get("factor_columns")
                       or params.get("factors"))
    dest = params.get("dest") or dkv.unique_key("interaction")
    job = Job("Interaction")
    job.dest_key = dest
    job.dest_type = "Key<Frame>"

    def body_fn(j):
        out = interaction_frame(
            fr, factors, pairwise=bool(p.get("pairwise")),
            max_factors=int(p.get("max_factors") or 100),
            min_occurrence=int(p.get("min_occurrence") or 1))
        out.key = dest
        dkv.put(dest, "frame", out)
        return out

    job.run(body_fn, background=True)
    return schemas.job_v3(job, dest, "Key<Frame>")


@route("POST", "/3/FriedmansPopescusH")
def _friedman_popescu_h(params, body):
    """Friedman-Popescu H statistic (hex/tree/FriedmanPopescusH.java,
    water/api/schemas3/FriedmanPopescusHV3.java; h2o-py model.h())."""
    m = dkv.get(str(params.get("model_id")), "model")
    fr = dkv.get(str(params.get("frame")), "frame")
    variables = _strlist(params.get("variables"))
    if not variables:
        raise ApiError(400, "variables is required")
    return {"__meta": {"schema_version": 3,
                       "schema_name": "FriedmanPopescusHV3"},
            "model_id": {"name": params.get("model_id")},
            "frame": {"name": params.get("frame")},
            "variables": variables,
            "h": m.h(fr, variables)}


@route("POST", "/3/PartialDependence/")
@route("POST", "/3/PartialDependence")
def _pdp_build(params, body):
    """hex/PartialDependence via water/api; h2o-py model.partial_plot."""
    from h2o3_tpu.analytics import partial_dependence
    p = {k: _coerce(v) for k, v in params.items()}
    m = dkv.get(str(params.get("model_id")), "model")
    fr = dkv.get(str(params.get("frame_id")), "frame")
    cols = _strlist(params.get("cols"))
    if not cols:
        cols = [c for c in m.feature_names][:3]
    dest = params.get("destination_key") or dkv.unique_key("pdp")
    job = Job("PartialDependencePlot")
    job.dest_key = dest
    job.dest_type = "Key<PartialDependence>"

    def body_fn(j):
        res = partial_dependence(m, fr, cols,
                                 nbins=int(p.get("nbins") or 20))
        dkv.put(dest, "pdp", {"cols": cols, "data": res})
        return res

    job.run(body_fn, background=True)
    return schemas.job_v3(job, dest, "Key<PartialDependence>")


@route("GET", "/3/PartialDependence/{key}")
def _pdp_get(params, body, key):
    obj = dkv.get(key, "pdp")
    tables = []
    for col in obj["cols"]:
        d = obj["data"][col]
        n_avg = max(int(d.get("n_rows", 1)), 1)   # rows averaged per point
        tables.append(schemas.twodim(
            f"PartialDependence for '{col}'",
            [col, "mean_response", "stddev_response", "std_error_mean_response"],
            [d["grid"], d["mean_response"], d["stddev_response"],
             [s / n_avg ** 0.5 for s in d["stddev_response"]]],
            ["string", "double", "double", "double"]))
    return {"__meta": {"schema_version": 3,
                       "schema_name": "PartialDependenceV3"},
            "destination_key": key,
            "partial_dependence_data": tables}


@route("POST", "/99/Tabulate")
@route("GET", "/99/Tabulate")
def _tabulate_route(params, body):
    """hex/Tabulate (Flow's tabulate cell); h2o.tabulate."""
    from h2o3_tpu.analytics import tabulate
    p = {k: _coerce(v) for k, v in params.items()}
    fr = dkv.get(str(params.get("dataset")), "frame")
    res = tabulate(fr, str(params.get("predictor")),
                   str(params.get("response")),
                   nbins_x=int(p.get("nbins_predictor") or 20),
                   nbins_y=int(p.get("nbins_response") or 20))
    ylab = [str(v) for v in res["y_labels"]]
    count_tbl = schemas.twodim(
        "Tabulate counts", ["predictor"] + ylab,
        [[str(v) for v in res["x_labels"]]]
        + [list(r) for r in np.asarray(res["counts"]).T.tolist()],
        ["string"] + ["double"] * len(ylab))
    means = res.get("mean_y_per_x")
    if means is None:       # categorical response: no per-bin mean
        means = [float("nan")] * len(res["x_labels"])
    resp_tbl = schemas.twodim(
        "Tabulate response", ["predictor", "mean_response"],
        [[str(v) for v in res["x_labels"]], means],
        ["string", "double"])
    return {"__meta": {"schema_version": 99, "schema_name": "TabulateV99"},
            "count_table": count_tbl, "response_table": resp_tbl}


@route("GET", "/3/Tree")
def _tree_route(params, body):
    """Tree inspection (hex/tree/TreeHandler → TreeV3; h2o-py H2OTree)."""
    p = {k: _coerce(v) for k, v in params.items()}
    m = dkv.get(str(params.get("model")), "model")
    if not hasattr(m, "_feat"):
        raise ApiError(400, f"model '{m.key}' is not tree-based")
    tree_no = int(p.get("tree_number") or 0)
    K = getattr(m, "_K", 1)
    cls = p.get("tree_class")
    cls_idx = 0
    if K > 1 and cls is not None:
        dom = list(m.response_domain or [])
        if str(cls) in dom:
            cls_idx = dom.index(str(cls))
        else:
            try:
                cls_idx = int(cls)
            except (TypeError, ValueError):
                raise ApiError(400, f"unknown tree_class '{cls}' "
                                    f"(domain: {dom})")
            if not 0 <= cls_idx < K:
                raise ApiError(400, f"tree_class index {cls_idx} out of "
                                    f"range for {K} classes")
    t = tree_no * K + cls_idx
    if t >= m._feat.shape[0] or tree_no < 0:
        raise ApiError(404, f"tree {tree_no} out of range")
    feat = np.asarray(m._feat[t])
    thr = np.asarray(m._thr[t])
    nal = np.asarray(m._na_left[t])
    spl = np.asarray(m._is_split[t])
    val = np.asarray(m._value[t])
    # BFS over reachable nodes of the complete array → compressed arrays
    idx_of = {}
    order = []
    stack = [0]
    while stack:
        n = stack.pop(0)
        idx_of[n] = len(order)
        order.append(n)
        if spl[n]:
            stack += [2 * n + 1, 2 * n + 2]
    left, right, feats, thrs, nas, preds, descs = [], [], [], [], [], [], []
    for n in order:
        if spl[n]:
            left.append(idx_of[2 * n + 1])
            right.append(idx_of[2 * n + 2])
            fname = m.feature_names[int(feat[n])]
            feats.append(fname)
            thrs.append(float(thr[n]))
            nas.append("LEFT" if nal[n] else "RIGHT")
            descs.append(f"{fname} < {thr[n]:.6g} goes left"
                         f" (NA {'left' if nal[n] else 'right'})")
        else:
            left.append(-1)
            right.append(-1)
            feats.append(None)
            thrs.append("NaN")
            nas.append(None)
            descs.append("leaf")
        preds.append(float(val[n]))
    return {"__meta": {"schema_version": 3, "schema_name": "TreeV3"},
            "model": schemas.keyref(m.key, "Key<Model>"),
            "tree_number": tree_no,
            "tree_class": cls if K > 1 else None,
            "left_children": left, "right_children": right,
            "root_node_id": 0, "descriptions": descs,
            "thresholds": thrs, "features": feats,
            "levels": [None] * len(order), "nas": nas,
            "predictions": preds,
            "tree_decision_path": None, "decision_paths": None}


@route("GET", "/3/TargetEncoderTransform")
def _te_transform_route(params, body):
    """TargetEncoder transform over REST (ai/h2o/targetencoding
    TargetEncoderHandler; h2o-py H2OTargetEncoderEstimator.transform)."""
    p = {k: _coerce(v) for k, v in params.items()}
    m = dkv.get(str(params.get("model")), "model")
    fr = dkv.get(str(params.get("frame")), "frame")
    out = m.transform(fr,
                      as_training=bool(p.get("as_training")),
                      noise=float(p["noise"]) if p.get("noise") not in
                      (None, -1) else None)
    dest = dkv.unique_key("te_transform")
    out.key = dest
    dkv.put(dest, "frame", out)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "TargetEncoderTransformV3"},
            "name": dest, "key": schemas.keyref(dest, "Key<Frame>")}


@route("GET", "/3/Word2VecSynonyms")
def _w2v_synonyms(params, body):
    m = dkv.get(str(params.get("model")), "model")
    word = str(params.get("word"))
    count = int(_coerce(params.get("count", 20)) or 20)
    syn = m.find_synonyms(word, count)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "Word2VecSynonymsV3"},
            "synonyms": list(syn.keys()), "scores": list(syn.values())}


@route("GET", "/3/Word2VecTransform")
def _w2v_transform(params, body):
    m = dkv.get(str(params.get("model")), "model")
    wf = dkv.get(str(params.get("words_frame")), "frame")
    agg = str(params.get("aggregate_method") or "NONE").lower()
    out = m.transform(wf, aggregate_method=agg)
    dest = dkv.unique_key("w2v_transform")
    out.key = dest
    dkv.put(dest, "frame", out)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "Word2VecTransformV3"},
            "vectors_frame": schemas.keyref(dest, "Key<Frame>")}


@route("POST", "/3/Grid.bin/import")
def _grid_import(params, body):
    """h2o.load_grid → reload a saved grid + its models (water/api/
    GridImportExportHandler)."""
    from h2o3_tpu.models.grid import load_grid_artifact
    path = str(params.get("grid_path"))
    gid, grid, models = load_grid_artifact(path)
    for m in models:
        dkv.put(m.key, "model", m)
    dkv.put(gid, "grid", grid)
    return {"__meta": {"schema_version": 3, "schema_name": "GridKeyV3"},
            "name": gid}


@route("POST", "/3/Grid.bin/{gid}/export")
def _grid_export(params, body, gid):
    """h2o.save_grid → persist a grid + models to a directory."""
    from h2o3_tpu.models.grid import save_grid_artifact
    grid = dkv.get(gid, "grid")
    d = params.get("grid_directory")
    if not d:
        raise ApiError(400, "grid_directory is required")
    save_grid_artifact(grid, gid, str(d))
    return {"__meta": {"schema_version": 3, "schema_name": "GridKeyV3"},
            "name": gid}


@route("POST", "/3/Frames/{fid}/save")
def _frame_save(params, body, fid):
    """Binary frame export (water/api/FramesHandler.saveFrame;
    h2o-py frame.save)."""
    from h2o3_tpu.persist import save_frame
    fr = dkv.get(fid, "frame")
    d = params.get("dir")
    if not d:
        raise ApiError(400, "dir is required")
    d = str(d)
    force = _coerce(params.get("force", "true"))
    job = Job(f"Save frame {fid}")
    job.dest_key = fid
    job.dest_type = "Key<Frame>"

    def body_fn(j):
        return save_frame(fr, d, force=bool(force), key=fid)

    job.run(body_fn, background=True)
    return schemas.job_v3(job, fid, "Key<Frame>")


@route("POST", "/3/Frames/load")
def _frame_load(params, body):
    """Binary frame import (FramesHandler.loadFrame; h2o.load_frame)."""
    from h2o3_tpu.persist import load_frame
    fid = str(params.get("frame_id"))
    d = params.get("dir")
    if not d:
        raise ApiError(400, "dir is required")
    d = str(d)
    job = Job(f"Load frame {fid}")
    job.dest_key = fid
    job.dest_type = "Key<Frame>"

    def body_fn(j):
        fr = load_frame(d, key=fid)
        dkv.put(fid, "frame", fr)
        return fr

    job.run(body_fn, background=True)
    return schemas.job_v3(job, fid, "Key<Frame>")


class _FrontDoorServer(ThreadingHTTPServer):
    # the stdlib default accept backlog (5) overflows under concurrent
    # scoring clients + fleet beats + router gossip on one socket,
    # surfacing as spurious connection-refused at the front door
    request_queue_size = 128


class H2OApiServer:
    """Embedded API server (the h2o.jar web server analog)."""

    def __init__(self, port: int = 54321, host: str = "127.0.0.1"):
        # any process that serves REST serves /metrics — make sure the
        # XLA compile/cache listeners are live before the first scrape
        from h2o3_tpu import telemetry
        telemetry.install()
        self.httpd = _FrontDoorServer((host, port), _Handler)
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def start_server(port: int = 54321, host: str = "127.0.0.1") -> H2OApiServer:
    return H2OApiServer(port=port, host=host).start()


@route("GET", "/3/Logs/download")
@route("GET", "/3/Logs")
def _logs(params, body):
    from h2o3_tpu.log import buffered_lines
    return {"__meta": {"schema_version": 3, "schema_name": "LogsV3"},
            "log": "\n".join(buffered_lines(int(params.get("n", 1000)
                                                or 1000)))}


@route("GET", "/3/Timeline")
def _timeline(params, body):
    """water/TimeLine.java ring-buffer snapshot (/3/Timeline).

    Default: the H2O event shape Flow expects — TimelineV3 has no
    nodeidx path parameter (water/api/TimelineHandler serves the whole
    cloud's merged ring); each event carries the EventV3 fields
    (date/nanos/who/io_flavor/event/bytes). The ring is now fed by
    every pipeline's finished ROOT telemetry spans (ingest.parse,
    train.*, serve.request/batch), not just model builds.

    ``?format=trace``: Chrome-trace/Perfetto JSON of the finished-span
    ring — the accelerator-aware timeline the JVM tools never had.

    ``?scope=cluster`` (ISSUE 19): the fleet-wide CAUSAL timeline from
    the flight-recorder rings instead of the local span ring — this
    process's ring, every live peer's ring (telemetry peer plane), and
    any DEAD member's mmap ring still readable under the shared
    blackbox dir. Events sort by (membership epoch, skew-corrected
    wall clock); members whose heartbeat skew exceeds the flag
    threshold are marked. ``format=trace`` renders the same merge as
    Chrome-trace instants, one process row per member, dead members
    labeled."""
    from h2o3_tpu import telemetry
    fmt = (params.get("format") or "").lower()
    if (params.get("scope") or "").lower() == "cluster":
        from h2o3_tpu.telemetry import blackbox
        n = int(params.get("n", 256) or 256)
        if fmt in ("trace", "perfetto", "chrome"):
            return {"__raw": blackbox.cluster_trace_bytes(n),
                    "__content_type": "application/json"}
        return {"__meta": {"schema_version": 3,
                           "schema_name": "TimelineClusterV3"},
                **blackbox.cluster_timeline(n)}
    if fmt in ("trace", "perfetto", "chrome"):
        limit = int(params.get("n", 0) or 0) or None
        return {"__raw": telemetry.chrome_trace_bytes(limit),
                "__content_type": "application/json"}
    from h2o3_tpu.log import timeline_events
    evs = timeline_events(int(params.get("n", 2048) or 2048))
    out = []
    for e in evs:
        ts = float(e.get("ts", 0.0))
        out.append({
            "date": time.strftime("%Y-%m-%d %H:%M:%S",
                                  time.localtime(ts)),
            "nanos": int(ts * 1e9),
            "who": "tpu-controller/0",
            "io_flavor": None,
            "event": e.get("kind", ""),
            "bytes": e.get("detail", ""),
            # legacy keys kept for the built-in Flow page
            "ts": ts, "kind": e.get("kind", ""),
            "detail": e.get("detail", ""),
        })
    return {"__meta": {"schema_version": 3, "schema_name": "TimelineV3"},
            "now": int(time.time() * 1000), "self": "tpu-controller/0",
            "events": out}


@route("GET", "/3/Blackbox")
def _blackbox(params, body):
    """This process's flight-recorder tail (ISSUE 19) — the wire format
    peers pull for ``/3/Timeline?scope=cluster``. Decoded events, not
    raw ring bytes: the reader never needs the writer's struct layout
    version."""
    from h2o3_tpu.telemetry import blackbox
    n = int(params.get("n", 256) or 256)
    return {"__meta": {"schema_version": 3, "schema_name": "BlackboxV3"},
            "member_id": blackbox._default_member_id(),
            "enabled": blackbox.ring_path() is not None,
            "events_recorded": blackbox.events_recorded(),
            "events": blackbox.local_events(n)}


def _cluster_prometheus_raw():
    """Merged cluster scrape rendered as exposition text — the one
    spelling behind ``/metrics?scope=cluster`` and
    ``/3/Telemetry/cluster?format=prometheus``."""
    from h2o3_tpu import telemetry
    samples, _meta = telemetry.cluster_samples()
    return {"__raw": telemetry.prometheus_text(samples=samples).encode(),
            "__content_type": "text/plain; version=0.0.4; charset=utf-8"}


@route("GET", "/metrics")
def _metrics(params, body):
    """Prometheus exposition of the process-wide telemetry registry
    (text format 0.0.4) — counters/gauges/histograms from every
    pipeline plus the XLA compile/cache/transfer collectors.

    ``?scope=cluster`` merges peer-process snapshots (peer list from
    H2O3_TELEMETRY_PEERS; counters sum, histograms bucket-merge, gauges
    get a ``process=`` label) through the SAME formatter. The default
    scope never touches the aggregation path — single-process output is
    bit-identical to PR 4/7."""
    from h2o3_tpu import telemetry
    telemetry.install()
    if (params.get("scope") or "").lower() == "cluster":
        return _cluster_prometheus_raw()
    return {"__raw": telemetry.prometheus_text().encode(),
            "__content_type": "text/plain; version=0.0.4; charset=utf-8"}


@route("GET", "/3/Telemetry")
def _telemetry_snapshot(params, body):
    """H2O-style JSON snapshot of the same registry /metrics exports:
    flat metric map, per-span stage aggregates, device memory, compile
    and transfer counters."""
    from h2o3_tpu import telemetry
    telemetry.install()
    return {"__meta": {"schema_version": 3, "schema_name": "TelemetryV3"},
            **telemetry.telemetry_snapshot()}


@route("GET", "/3/Telemetry/snapshot")
def _telemetry_process_snapshot(params, body):
    """THIS process's registry + finished-span ring as one mergeable
    snapshot — the wire format peers pull for the cluster aggregation
    (telemetry/snapshot.py). ``n`` bounds the serialized span count."""
    from h2o3_tpu import telemetry
    telemetry.install()
    n = int(params.get("n", 2048) or 2048)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "TelemetrySnapshotV3"},
            **telemetry.local_snapshot(max_spans=n)}


@route("GET", "/3/Telemetry/cluster")
def _telemetry_cluster(params, body):
    """Cluster-merged telemetry: this process + every peer in
    H2O3_TELEMETRY_PEERS (counters summed, histograms bucket-merged,
    gauges labeled ``process=``). ``?format=prometheus`` renders the
    merged samples as exposition text instead of the JSON map. Dead
    peers are reported in ``peers_failed``, never fatal."""
    from h2o3_tpu import telemetry
    telemetry.install()
    if (params.get("format") or "").lower() == "prometheus":
        return _cluster_prometheus_raw()
    return {"__meta": {"schema_version": 3,
                       "schema_name": "TelemetryClusterV3"},
            **telemetry.cluster_snapshot()}


@route("GET", "/3/Telemetry/perf")
def _telemetry_perf(params, body):
    """Performance accounting view (ISSUE 11): detected per-chip peaks
    (``peak_source`` provenance, ``informational`` flag on CPU/unknown
    hardware) plus a roofline point per phase — achieved flops/bytes
    per second, arithmetic intensity, MFU and compute- vs memory-bound
    regime — derived from the cumulative ``h2o3_achieved_*`` counters
    the cost-capture seams feed."""
    from h2o3_tpu import telemetry
    telemetry.install()
    return {"__meta": {"schema_version": 3,
                       "schema_name": "TelemetryPerfV3"},
            **telemetry.costmodel.summary()}


@route("GET", "/3/Profiler")
def _profiler(params, body):
    """water/api/ProfilerHandler: aggregated stack samples per node
    (ProfilerV3 -> ProfilerNodeV3 {node_name, timestamp, entries:
    [{stacktrace, count}]}). One controller process here, so one node."""
    import time as _time

    from h2o3_tpu.log import stack_samples
    depth = int(params.get("depth", 10) or 10)
    if depth < 1:
        raise ApiError(400, "depth must be >= 1")
    entries = stack_samples(depth=depth)
    return {"__meta": {"schema_version": 3, "schema_name": "ProfilerV3"},
            "depth": depth,
            "nodes": [{"node_name": "tpu-controller/0",
                       "timestamp": int(_time.time() * 1000),
                       "entries": entries}]}


@route("POST", "/3/Profiler/trace")
def _profiler_trace(params, body):
    """TPU-native device tracing (no reference analog — the JVM profiler
    cannot see the accelerator): start/stop a jax.profiler trace whose
    artifacts load in TensorBoard/Perfetto. action=start|stop."""
    import jax as _jax
    action = (params.get("action") or "").lower()
    if action == "start":
        log_dir = params.get("log_dir") or os.path.join(
            tempfile.gettempdir(), "h2o3_jax_trace")
        try:
            _jax.profiler.start_trace(log_dir)
        except RuntimeError as e:      # double-start: already tracing
            raise ApiError(400, f"trace already active: {e}")
        return {"__meta": {"schema_name": "ProfilerTraceV3"},
                "status": "started", "log_dir": log_dir}
    if action == "stop":
        try:
            _jax.profiler.stop_trace()
        except RuntimeError as e:
            raise ApiError(400, f"no active trace: {e}")
        return {"__meta": {"schema_name": "ProfilerTraceV3"},
                "status": "stopped"}
    raise ApiError(400, "action must be 'start' or 'stop'")


# ---------------- round-5 REST breadth batch 2 -------------------------
# The remaining RegisterV3Api.java registrations with real machinery
# behind them in this codebase; hive/decryption/steam are honest gates.

@route("GET", "/3/Ping")
def _ping(params, body):
    """water/api/PingHandler: liveness + a cloud snapshot."""
    import psutil
    vm = psutil.virtual_memory()
    return {"__meta": {"schema_version": 3, "schema_name": "PingV3"},
            "cloud_uptime_millis": schemas.uptime_ms(),
            "cloud_healthy": True,
            "nodes": [{"mem": int(vm.available),
                       "num_cpus": os.cpu_count() or 1}]}


@route("GET", "/3/InitID")
def _init_id(params, body):
    """water/api/InitIDHandler: issue a session key (h2o-py uses the
    /4/sessions flavor; R's h2o.init path hits this one)."""
    import uuid as _uuid
    sid = "_sid_" + _uuid.uuid4().hex[:10]
    dkv.put(sid, "session", {"frames": []})
    return {"__meta": {"schema_version": 3, "schema_name": "InitIDV3"},
            "session_key": sid}


@route("DELETE", "/3/InitID")
def _end_init_id(params, body):
    return {"__meta": {"schema_version": 3, "schema_name": "InitIDV3"}}


@route("GET", "/3/CloudLock")
def _cloud_lock(params, body):
    """water/api/CloudLockHandler. The single-controller cloud never
    re-forms after boot, so it is always locked-stable."""
    return {"__meta": {"schema_version": 3, "schema_name": "CloudLockV3"},
            "locked": True, "reason": "single-controller: cloud is "
            "fixed at boot (no Paxos re-formation to lock against)"}


@route("POST", "/3/UnlockKeys")
def _unlock_keys(params, body):
    """water/api/UnlockKeysHandler: force-release every cooperative
    lock (admin escape hatch)."""
    dkv.unlock_everything()
    return {"__meta": {"schema_version": 3, "schema_name": "UnlockKeysV3"}}


_SESSION_PROPS: Dict[str, str] = {}


@route("GET", "/3/SessionProperties")
def _session_props_get(params, body):
    k = params.get("key")
    return {"__meta": {"schema_version": 3,
                       "schema_name": "SessionPropertyV3"},
            "key": k, "value": _SESSION_PROPS.get(k)}


@route("POST", "/3/SessionProperties")
def _session_props_set(params, body):
    k = params.get("key")
    if not k:
        raise ApiError(400, "key is required")
    _SESSION_PROPS[k] = params.get("value")
    return {"__meta": {"schema_version": 3,
                       "schema_name": "SessionPropertyV3"},
            "key": k, "value": _SESSION_PROPS.get(k)}


@route("GET", "/3/Capabilities/API")
def _capabilities_api(params, body):
    return {"__meta": {"schema_version": 3,
                       "schema_name": "CapabilitiesV3"},
            "capabilities": [
                {"name": f"{m} {rx.pattern}", "category": "API"}
                for m, rx, _ in _ROUTES]}


@route("GET", "/3/Metadata/schemas")
def _metadata_schemas_list(params, body):
    """water/api/MetadataHandler.listSchemas."""
    from h2o3_tpu.api import schemas as _sch
    return {"__meta": {"schema_version": 3, "schema_name": "MetadataV3"},
            "schemas": [{"name": n, "version": 3}
                        for n in _sch.known_schema_names()]}


@route("GET", "/3/Metadata/endpoints/{num}")
def _metadata_endpoint_one(params, body, num):
    i = int(num)
    if not (0 <= i < len(_ROUTES)):
        raise ApiError(404, f"endpoint index {i} out of range")
    m, rx, fn = _ROUTES[i]
    return {"__meta": {"schema_version": 3, "schema_name": "MetadataV3"},
            "routes": [{"http_method": m, "url_pattern": rx.pattern,
                        "summary": (fn.__doc__ or "").strip()[:200]}]}


@route("GET", "/3/Frames/{key}/light")
def _frame_light(params, body, key):
    """FramesHandler.fetchLight: schema without data pages."""
    fr = dkv.get(key, "frame")
    return {"__meta": {"schema_version": 3, "schema_name": "FramesV3"},
            "frames": [schemas.frame_v3(fr, key, row_count=0)]}


@route("GET", "/3/Frames/{key}/columns")
def _frame_columns(params, body, key):
    fr = dkv.get(key, "frame")
    return {"__meta": {"schema_version": 3, "schema_name": "FramesV3"},
            "frames": [{"frame_id": {"name": key},
                        "columns": list(fr.names)}]}


def _one_column_v3(fr, key, col, row_count=10, row_offset=0):
    if col not in fr.names:
        raise ApiError(404, f"column '{col}' not in frame '{key}'")
    return schemas.frame_v3(fr, key, row_count=row_count,
                            row_offset=row_offset,
                            column_offset=fr.names.index(col),
                            column_count=1)


@route("GET", "/3/Frames/{key}/columns/{col}")
def _frame_column(params, body, key, col):
    fr = dkv.get(key, "frame")
    return {"__meta": {"schema_version": 3, "schema_name": "FramesV3"},
            "frames": [_one_column_v3(
                fr, key, col,
                row_count=int(params.get("row_count", 10) or 10),
                row_offset=int(params.get("row_offset", 0) or 0))]}


@route("GET", "/3/Frames/{key}/columns/{col}/summary")
def _frame_column_summary(params, body, key, col):
    fr = dkv.get(key, "frame")
    return {"__meta": {"schema_version": 3, "schema_name": "FramesV3"},
            "frames": [_one_column_v3(fr, key, col)]}


@route("GET", "/3/Frames/{key}/columns/{col}/domain")
def _frame_column_domain(params, body, key, col):
    fr = dkv.get(key, "frame")
    if col not in fr.names:
        raise ApiError(404, f"column '{col}' not in frame '{key}'")
    v = fr.vec(col)
    dom = list(v.domain) if v.domain else None
    return {"__meta": {"schema_version": 3,
                       "schema_name": "FrameV3.ColV3"},
            "domain": [dom] if dom else [None],
            "map_keys": {"string": dom or []}}


@route("POST", "/3/Frames/{key}/export")
@route("POST", "/3/Frames/{key}/export/{path}/overwrite/{force}")
def _frame_export(params, body, key, path=None, force=None):
    """FramesHandler.export: write the frame as CSV at `path` (job)."""
    from h2o3_tpu.persist import export_file
    fr = dkv.get(key, "frame")
    out_path = path or params.get("path")
    if not out_path:
        raise ApiError(400, "path is required")
    frc = (str(force if force is not None
               else params.get("force", "false")).lower() == "true")
    job = Job(f"Export frame {key}")
    job.dest_key = out_path

    def body_fn(j):
        export_file(fr, out_path, force=frc)
    job.run(body_fn, background=True)
    return schemas.job_v3(job, out_path)


@route("GET", "/3/ModelMetrics")
def _model_metrics_all(params, body):
    """ModelMetricsHandler.list with no filter: every model's stored
    metrics."""
    out = []
    for key in dkv.keys("model"):
        m = dkv.get(key, "model")
        for mm in (m.training_metrics, m.validation_metrics,
                   m.cross_validation_metrics):
            if mm is not None:
                v3 = schemas._metrics_v3(
                    mm, _kind_of(m),
                    domain=list(m.response_domain or []) or None,
                    model_key=key)
                if v3:
                    out.append(v3)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelMetricsListSchemaV3"},
            "model_metrics": out}


@route("POST", "/3/ModelMetrics/predictions_frame/{pred}/actuals_frame/{act}")
def _make_metrics(params, body, pred, act):
    """ModelMetricsHandler.make (h2o.make_metrics): metrics straight
    from a predictions frame + actuals frame, no model needed."""
    import numpy as _np

    from h2o3_tpu.models.model_base import compute_metrics
    pf = dkv.get(pred, "frame")
    af = dkv.get(act, "frame")
    domain = _coerce(params.get("domain", "null"))
    dist = (params.get("distribution") or "").lower() or None
    av = af.vec(0)
    if av.domain or domain:
        dom = list(domain or av.domain)
        nclasses = len(dom)
        if av.domain:
            yh = _np.asarray(av.to_numpy())[: af.nrow]
        else:
            lut = {d: i for i, d in enumerate(dom)}
            yh = _np.asarray(
                [lut.get(s, -1) for s in av.to_strings()[: af.nrow]])
    else:
        dom = None
        nclasses = 1
        yh = _np.asarray(av.to_numpy())[: af.nrow]
    # predictions frame: regression = 1 numeric col; classification =
    # [label, p0, p1, ...] or bare probability columns
    pcols = [pf.vec(n) for n in pf.names]
    if nclasses > 1:
        probs = [_np.asarray(v.to_numpy())[: pf.nrow]
                 for v in pcols if v.domain is None]
        if len(probs) < nclasses:
            raise ApiError(400, f"predictions frame needs {nclasses} "
                                f"probability columns")
        scores = _np.stack(probs[-nclasses:], axis=1)
    else:
        scores = _np.asarray(pcols[0].to_numpy())[: pf.nrow]
    w = _np.ones(len(yh), _np.float32)
    y_in = _np.asarray(yh, _np.float64)
    if nclasses > 1:
        # -1 marks a label outside the domain (lut miss) — excluded;
        # regression actuals pass through untouched (negatives are data)
        w[y_in == -1] = 0.0
        y_in = _np.maximum(y_in, 0)
    mm = compute_metrics(scores, y_in, w, nclasses,
                         response_domain=tuple(dom) if dom else None)
    kind = ("regression" if nclasses == 1 else
            "binomial" if nclasses == 2 else "multinomial")
    if dist in ("bernoulli",) and nclasses == 2:
        kind = "binomial"
    v3 = schemas._metrics_v3(mm, kind, domain=dom,
                             frame_key=act) or {}
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelMetricsListSchemaV3"},
            "model_metrics": v3}


@route("GET", "/3/Models.java/{model}")
def _pojo_download(params, body, model):
    """ModelsHandler.fetchJavaCode: the POJO source as java text."""
    from h2o3_tpu.genmodel import pojo_source, pojo_source_glm
    m = dkv.get(model, "model")
    try:
        src = (pojo_source_glm(m) if m.algo in ("glm",)
               else pojo_source(m))
    except (NotImplementedError, AttributeError) as e:
        raise ApiError(400, f"no POJO for algo '{m.algo}': {e}")
    return {"__raw": src.encode(), "__content_type": "text/java"}


@route("GET", "/3/Models.java/{model}/preview")
def _pojo_preview(params, body, model):
    out = _pojo_download(params, body, model)
    return {"__raw": out["__raw"][:4096], "__content_type": "text/java"}


@route("GET", "/3/Models/{model}/mojo")
@route("GET", "/99/Models.mojo/{model}")
def _mojo_download(params, body, model):
    """ModelsHandler.fetchMojo: the MOJO zip bytes (h2o-py
    model.download_mojo streams this)."""
    m = dkv.get(model, "model")
    with tempfile.TemporaryDirectory() as td:
        try:
            path = m.download_mojo(td)
        except (NotImplementedError, AttributeError) as e:
            raise ApiError(400, f"no MOJO for algo '{m.algo}': {e}")
        data = open(path, "rb").read()
    return {"__raw": data, "__content_type": "application/zip"}


@route("POST", "/3/ParseSVMLight")
def _parse_svmlight(params, body):
    """ParseHandler.parseSVMLight: svmlight files → frame (job)."""
    from h2o3_tpu.ingest.formats import parse_svmlight
    srcs = _raw_paths(_coerce(params.get("source_frames", "[]")))
    if not srcs:
        raise ApiError(400, "source_frames is required")
    dest = params.get("destination_frame") or dkv.unique_key("svmlight")
    job = Job("ParseSVMLight")
    job.dest_key = dest

    def body_fn(j):
        fr = parse_svmlight(srcs[0])
        dkv.put(dest, "frame", fr)
    job.run(body_fn, background=True)
    return schemas.job_v3(job, dest)


@route("GET", "/3/Find")
def _find(params, body):
    """water/api/FindHandler: first row >= `row` where `column`
    matches `match` (value or NA)."""
    import math as _math

    import numpy as _np
    key = _coerce(params.get("key"))
    if isinstance(key, dict):
        key = key.get("name")
    fr = dkv.get(str(key), "frame")
    col = params.get("column")
    if col not in fr.names:
        raise ApiError(404, f"column '{col}' not in frame")
    start = int(params.get("row", 0) or 0)
    match = params.get("match")
    v = fr.vec(col)
    if v.domain is not None or v.type == "str":
        vals = [None if s is None else str(s)
                for s in v.to_strings()[: fr.nrow]]
        hit = next((i for i in range(start, fr.nrow)
                    if (vals[i] is None if match in (None, "")
                        else vals[i] == match)), -1)
    else:
        a = _np.asarray(v.to_numpy())[: fr.nrow]
        if v.type == "time":
            # int64 millis with a sentinel NA (Vec.TIME_NA), not NaN
            from h2o3_tpu.frame.vec import Vec as _V
            na = a == _V.TIME_NA
            if match in (None, ""):
                idx = _np.nonzero(na[start:])[0]
            else:
                idx = _np.nonzero((a[start:] == int(float(match)))
                                  & ~na[start:])[0]
        elif match in (None, ""):
            idx = _np.nonzero(_np.isnan(a[start:]))[0]
        else:
            tgt = float(match)
            idx = _np.nonzero(a[start:] == tgt)[0] if not _math.isnan(tgt) \
                else _np.nonzero(_np.isnan(a[start:]))[0]
        hit = int(idx[0]) + start if len(idx) else -1
    if hit < 0:
        raise ApiError(404, f"no match for '{match}' in '{col}' from "
                            f"row {start}")
    return {"__meta": {"schema_version": 3, "schema_name": "FindV3"},
            "prev": -1, "next": hit}


@route("POST", "/3/MissingInserter")
def _missing_inserter(params, body):
    """water/api/MissingInserterHandler: corrupt a fraction of a frame
    to NAs in place (client test utility h2o.insert_missing_values)."""
    import numpy as _np

    from h2o3_tpu.frame.vec import Vec
    key = _coerce(params.get("dataset"))
    if isinstance(key, dict):
        key = key.get("name")
    fr = dkv.get(str(key), "frame")
    frac = float(params.get("fraction", 0.1) or 0.1)
    seed = int(params.get("seed", -1) or -1)
    rng = _np.random.default_rng(None if seed == -1 else seed)
    job = Job("MissingInserter")
    job.dest_key = str(key)

    def body_fn(j):
        from h2o3_tpu.frame.vec import T_ENUM, T_TIME
        for name in fr.names:
            v = fr.vec(name)
            if v.domain is not None:
                codes = _np.asarray(v.to_numpy(), _np.int32)[: fr.nrow]
                codes[rng.random(fr.nrow) < frac] = -1
                fr[name] = Vec.from_numpy(codes, vtype=T_ENUM,
                                          domain=v.domain)
            elif v.type == "str":
                continue              # reference skips string cols too
            elif v.type == T_TIME:
                ms = _np.asarray(v.to_numpy(), _np.int64)[: fr.nrow]
                ms[rng.random(fr.nrow) < frac] = Vec.TIME_NA
                fr[name] = Vec.from_numpy(ms, vtype=T_TIME)
            else:
                a = _np.asarray(v.to_numpy(), _np.float64)[: fr.nrow]
                a[rng.random(fr.nrow) < frac] = _np.nan
                fr[name] = Vec.from_numpy(a)
        dkv.put(str(key), "frame", fr)
    job.run(body_fn, background=True)
    return schemas.job_v3(job, str(key))


@route("GET", "/99/Rapids/help")
def _rapids_help(params, body):
    import re as _re

    import h2o3_tpu.rapids as _r
    prims = sorted(set(_re.findall(r'if op == "([^"]+)"',
                                   open(_r.__file__).read())))
    return {"__meta": {"schema_version": 99,
                       "schema_name": "RapidsHelpV3"},
            "syntax": [{"name": p} for p in prims]}


@route("GET", "/3/KillMinus3")
def _kill_minus3(params, body):
    """water/api/KillMinus3Handler (kill -3 = JVM stack dump): log the
    aggregated thread stacks, return OK."""
    from h2o3_tpu.log import info, stack_samples
    for e in stack_samples(depth=12, samples=1, interval=0.0):
        info("stack x%d:\n%s", e["count"], e["stacktrace"])
    return {"__meta": {"schema_version": 3,
                       "schema_name": "KillMinus3V3"}}


@route("GET", "/3/WaterMeterCpuTicks/{nodeidx}")
def _watermeter_cpu(params, body, nodeidx):
    """water/api/WaterMeterCpuTicksHandler: per-core cpu tick counters
    (Flow's CPU meter polls this)."""
    import psutil
    per = psutil.cpu_times(percpu=True)
    ticks = [[int(c.user * 100), int(getattr(c, "nice", 0) * 100),
              int(c.system * 100), int(c.idle * 100)] for c in per]
    return {"__meta": {"schema_version": 3,
                       "schema_name": "WaterMeterCpuTicksV3"},
            "cpu_ticks": ticks}


@route("GET", "/3/WaterMeterIo")
@route("GET", "/3/WaterMeterIo/{nodeidx}")
def _watermeter_io(params, body, nodeidx=None):
    import psutil
    io = psutil.disk_io_counters()
    return {"__meta": {"schema_version": 3,
                       "schema_name": "WaterMeterIoV3"},
            "persist_stats": [{
                "backend": "local",
                "store_bytes": int(getattr(io, "write_bytes", 0)),
                "load_bytes": int(getattr(io, "read_bytes", 0))}]}


@route("GET", "/3/NetworkTest")
def _network_test(params, body):
    """water/init/NetworkBench analog: a loopback TCP round-trip +
    bandwidth microbench (single-host cloud → one matrix cell)."""
    import socket
    import time as _t
    payload = os.urandom(1 << 20)
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    out = {}

    def _echo():
        conn, _ = srv.accept()
        with conn:
            got = 0
            while got < len(payload):
                b = conn.recv(1 << 16)
                if not b:
                    break
                got += len(b)
            conn.sendall(b"ok")
    t = threading.Thread(target=_echo, daemon=True)
    t.start()
    cli = socket.create_connection(("127.0.0.1", port))
    t0 = _t.time()
    cli.sendall(payload)
    cli.recv(2)
    dt = _t.time() - t0
    cli.close()
    srv.close()
    out["bandwidth_bytes_per_sec"] = len(payload) / max(dt, 1e-9)
    out["microseconds_collective"] = dt * 1e6
    return {"__meta": {"schema_version": 3,
                       "schema_name": "NetworkTestV3"},
            "nodes": ["tpu-controller/0"],
            "bandwidths_bytes_per_sec": [[out["bandwidth_bytes_per_sec"]]],
            "microseconds_collective": [out["microseconds_collective"]]}


@route("POST", "/3/FeatureInteraction")
def _feature_interaction_route(params, body):
    """hex/FeatureInteraction via water/api: pairwise interaction
    screen for a tree model (h2o-py model.feature_interaction)."""
    from h2o3_tpu.analytics import feature_interaction
    m = dkv.get(str(params.get("model_id")), "model")
    fkey = (params.get("frame") or params.get("frame_id")
            or getattr(m, "training_frame_key", None))
    if not fkey:
        raise ApiError(400, "frame is required (model has no recorded "
                            "training_frame_key)")
    fr = dkv.get(str(fkey), "frame")
    rows = feature_interaction(
        m, fr, max_pairs=int(params.get("max_interaction_depth", 10)
                             or 10))
    return {"__meta": {"schema_version": 3,
                       "schema_name": "FeatureInteractionV3"},
            "feature_interaction": rows}


@route("POST", "/3/SignificantRules")
def _significant_rules(params, body):
    """hex/rulefit SignificantRulesHandler: the nonzero-coefficient
    rule table of a RuleFit model."""
    m = dkv.get(str(params.get("model_id")), "model")
    if m.algo != "rulefit":
        raise ApiError(400, f"model '{m.key}' is {m.algo}, not rulefit")
    imp = m.rule_importance()
    return {"__meta": {"schema_version": 3,
                       "schema_name": "SignificantRulesV3"},
            "significant_rules_table": imp}


@route("POST", "/3/Recovery/resume")
def _recovery_resume(params, body):
    """hex/faulttolerance/Recovery: after a crash, reload every model
    artifact a recovery_dir holds back into the DKV (grid manifests +
    AutoML state files both point at artifacts saved there); training
    re-issued against the same recovery_dir then resumes from them."""
    from h2o3_tpu.persist import load_model
    rdir = params.get("recovery_dir")
    if not rdir or not os.path.isdir(rdir):
        raise ApiError(400, f"recovery_dir '{rdir}' does not exist")
    restored = []
    for mf in sorted(os.listdir(rdir)):
        if not mf.endswith(".json"):
            continue
        try:
            with open(os.path.join(rdir, mf)) as f:
                manifest = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        arts = manifest.get("completed", {})
        if isinstance(arts, dict):
            for art in arts.values():
                try:
                    model = load_model(art)
                    dkv.put(model.key, "model", model)
                    restored.append(model.key)
                except Exception:      # noqa: BLE001 - partial restore
                    continue
    return {"__meta": {"schema_version": 3, "schema_name": "RecoveryV3"},
            "restored_models": restored}


@route("POST", "/99/DCTTransformer")
def _dct_transformer(params, body):
    """util/DCTTransformer (TabToDct): per-row 2D DCT-II of
    [height x width x depth]-shaped rows. TPU re-design: the DCT is two
    dense cosine-matrix matmuls (MXU) instead of a per-chunk FFT."""
    import jax.numpy as jnp
    import numpy as _np
    key = _coerce(params.get("dataset"))
    if isinstance(key, dict):
        key = key.get("name")
    fr = dkv.get(str(key), "frame")
    dims = _coerce(params.get("dimensions", "[0,0,1]")) or [0, 0, 1]
    h, w_, d = (int(dims[0]) or 1), (int(dims[1]) or 1), (int(dims[2])
                                                          or 1)
    if h * w_ * d != fr.ncol:
        raise ApiError(400, f"dimensions {dims} do not multiply to "
                            f"ncol={fr.ncol}")
    dest = params.get("destination_frame") or dkv.unique_key("dct")

    def dct_mat(n):
        k = _np.arange(n)[:, None]
        i = _np.arange(n)[None, :]
        M = _np.sqrt(2.0 / n) * _np.cos(_np.pi * (2 * i + 1) * k /
                                        (2.0 * n))
        M[0] *= 1.0 / _np.sqrt(2.0)
        return jnp.asarray(M, jnp.float32)

    job = Job("DCTTransformer")
    job.dest_key = dest

    def body_fn(j):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        X = jnp.asarray(_np.nan_to_num(_np.asarray(
            fr.as_matrix()))[: fr.nrow]).reshape(fr.nrow, h, w_, d)
        Dh, Dw = dct_mat(h), dct_mat(w_)
        # rows x [h, w, d] -> DCT over h and w axes per depth slice
        Y = jnp.einsum("ab,rbwd->rawd", Dh, X)
        Z = jnp.einsum("cw,rawd->racd", Dw, Y)
        out = _np.asarray(Z.reshape(fr.nrow, -1))
        names = [f"C{i + 1}" for i in range(out.shape[1])]
        dkv.put(dest, "frame", Frame(
            names,
            [Vec.from_numpy(out[:, i]) for i in range(out.shape[1])]))
    job.run(body_fn, background=True)
    return schemas.job_v3(job, dest)


_NPS_ROOT = os.path.join(tempfile.gettempdir(), "h2o3_nps")


def _nps_path(cat: str, name: str = None) -> str:
    """Traversal-safe NPS path: route segments arrive URL-DECODED, so
    '..%2F..' style names must be rejected on every verb, not just
    POST."""
    for part in (cat,) + ((name,) if name is not None else ()):
        if (not part or "/" in part or "\\" in part or ".." in part
                or os.path.isabs(part)):
            raise ApiError(400, f"invalid category/name '{part}'")
    return os.path.join(_NPS_ROOT, cat, *((name,) if name is not None
                                          else ()))


@route("GET", "/3/NodePersistentStorage/configured")
def _nps_configured(params, body):
    return {"__meta": {"schema_version": 3,
                       "schema_name": "NodePersistentStorageV3"},
            "configured": True}


@route("GET", "/3/NodePersistentStorage/categories/{cat}/exists")
def _nps_cat_exists(params, body, cat):
    return {"__meta": {"schema_version": 3,
                       "schema_name": "NodePersistentStorageV3"},
            "exists": os.path.isdir(_nps_path(cat))}


@route("GET",
       "/3/NodePersistentStorage/categories/{cat}/names/{name}/exists")
def _nps_exists(params, body, cat, name):
    return {"__meta": {"schema_version": 3,
                       "schema_name": "NodePersistentStorageV3"},
            "exists": os.path.isfile(_nps_path(cat, name))}


@route("GET", "/3/NodePersistentStorage/{cat}")
def _nps_list(params, body, cat):
    """water/api/NodePersistentStorageHandler (Flow stores notebooks
    here): list entries of a category."""
    d = _nps_path(cat)
    entries = []
    if os.path.isdir(d):
        for n in sorted(os.listdir(d)):
            p = os.path.join(d, n)
            entries.append({"name": n, "size": os.path.getsize(p),
                            "timestamp_millis": int(
                                os.path.getmtime(p) * 1000)})
    return {"__meta": {"schema_version": 3,
                       "schema_name": "NodePersistentStorageV3"},
            "category": cat, "entries": entries}


@route("GET", "/3/NodePersistentStorage/{cat}/{name}")
def _nps_get(params, body, cat, name):
    p = _nps_path(cat, name)
    if not os.path.isfile(p):
        raise ApiError(404, f"no NPS entry {cat}/{name}")
    return {"__raw": open(p, "rb").read(),
            "__content_type": "application/octet-stream"}


@route("POST", "/3/NodePersistentStorage/{cat}/{name}")
def _nps_put(params, body, cat, name):
    d = _nps_path(cat)
    _nps_path(cat, name)
    os.makedirs(d, exist_ok=True)
    data = body if isinstance(body, (bytes, bytearray)) else \
        (params.get("value") or "").encode()
    with open(os.path.join(d, name), "wb") as f:
        f.write(data or b"")
    return {"__meta": {"schema_version": 3,
                       "schema_name": "NodePersistentStorageV3"},
            "category": cat, "name": name}


@route("DELETE", "/3/NodePersistentStorage/{cat}/{name}")
def _nps_delete(params, body, cat, name):
    p = _nps_path(cat, name)
    if os.path.isfile(p):
        os.unlink(p)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "NodePersistentStorageV3"}}


@route("POST", "/99/ImportSQLTable")
def _import_sql_table_route(params, body):
    """water/jdbc/SQLManager route (h2o.import_sql_table): DB-API
    import. sqlite:///path URLs work out of the box (stdlib driver);
    other engines need their driver package installed."""
    from h2o3_tpu.ingest.sql import import_sql_table
    url = params.get("connection_url") or ""
    table = params.get("table")
    if not table:
        raise ApiError(400, "table is required")
    if url.startswith(("sqlite:///", "jdbc:sqlite:")):
        if url.startswith("jdbc:"):
            # jdbc:sqlite:/abs/path or jdbc:sqlite:rel.db — verbatim
            dbpath = url[len("jdbc:sqlite:"):]
        else:
            # sqlite:///abs/path (3 slashes = absolute, SQLAlchemy form)
            dbpath = "/" + url[len("sqlite:///"):]
        import sqlite3

        def factory():
            return sqlite3.connect(dbpath)
    else:
        raise ApiError(501, f"no DB-API driver wired for '{url}' in "
                            f"this image (sqlite:/// is built in)")
    cols = _coerce(params.get("columns", "null"))
    dest = params.get("destination_frame") or dkv.unique_key("sql")
    job = Job("ImportSQLTable")
    job.dest_key = dest

    def body_fn(j):
        fr = import_sql_table(factory, table, columns=cols or None)
        dkv.put(dest, "frame", fr)
    job.run(body_fn, background=True)
    return schemas.job_v3(job, dest)


@route("POST", "/99/Sample")
def _sample_frame(params, body):
    """99/Sample: uniform row sample of a frame into a new key."""
    import numpy as _np
    key = _coerce(params.get("dataset"))
    if isinstance(key, dict):
        key = key.get("name")
    fr = dkv.get(str(key), "frame")
    n = int(params.get("rows", 0) or 0)
    if n <= 0 or n >= fr.nrow:
        raise ApiError(400, f"rows must be in (0, {fr.nrow})")
    seed = int(params.get("seed", -1) or -1)
    rng = _np.random.default_rng(None if seed == -1 else seed)
    sel = _np.sort(rng.choice(fr.nrow, size=n, replace=False))
    sub = fr.rows(sel)
    dest = params.get("destination_frame") or dkv.unique_key("sample")
    dkv.put(dest, "frame", sub)
    return {"__meta": {"schema_version": 99, "schema_name": "SampleV3"},
            "destination_frame": dest, "rows": n}


@route("POST", "/3/ImportHiveTable")
@route("POST", "/3/SaveToHiveTable")
def _hive_gate(params, body):
    raise ApiError(501, "Hive import/export needs a Hive metastore + "
                        "HDFS environment this image does not ship "
                        "(reference: h2o-hive); use JDBC "
                        "(/99/ImportSQLTable) or file ingest instead")


@route("POST", "/3/DecryptionSetup")
def _decryption_gate(params, body):
    raise ApiError(501, "encrypted-file ingest (water/parser/"
                        "DecryptionTool) is not wired in this build; "
                        "decrypt files before import")


@route("GET", "/3/h2o-genmodel.jar")
def _genmodel_jar(params, body):
    raise ApiError(501, "h2o-genmodel.jar is a JVM artifact this "
                        "TPU-native build does not ship; score POJO/"
                        "MOJO artifacts with h2o3_tpu.genmodel "
                        "(EasyPredict) or pass get_jar=False to "
                        "download_pojo")


@route("POST", "/99/Assembly")
def _assembly_fit(params, body):
    """water/api/AssemblyHandler.fit: replay munging steps (h2o-py
    H2OAssembly.fit) against a frame; returns assembly + result keys."""
    from h2o3_tpu.assembly import Assembly, parse_steps
    steps = parse_steps(params.get("steps") or "[]")
    fkey = str(params.get("frame"))
    try:
        fr = dkv.get(fkey, "frame")
    except KeyError:
        raise ApiError(404, f"frame '{fkey}' not found")
    akey = dkv.unique_key("assembly")
    asm = Assembly(akey, steps)
    out = asm.fit(fr)
    rkey = dkv.unique_key("assembly_result")
    dkv.put(rkey, "frame", out)
    dkv.put(akey, "assembly", asm)
    return {"__meta": {"schema_version": 99, "schema_name": "AssemblyV99"},
            "assembly": {"name": akey, "type": "Key<Assembly>"},
            "result": {"name": rkey, "type": "Key<Frame>"}}


@route("GET", "/99/Assembly.java/{aid}/{pojo_name}")
def _assembly_java(params, body, aid, pojo_name):
    """AssemblyHandler.toJava: the munging POJO source."""
    try:
        asm = dkv.get(aid, "assembly")
    except KeyError:
        raise ApiError(404, f"assembly '{aid}' not found")
    try:
        src = asm.to_java(pojo_name)
    except NotImplementedError as e:
        raise ApiError(501, str(e))
    return {"__raw": src.encode(), "__content_type": "text/java"}


@route("GET", "/3/Logs/nodes/{nodeidx}/files/{name}")
def _logs_file(params, body, nodeidx, name):
    """water/api/LogsHandler.fetch: a node's named log. One controller
    process here; every name view serves the in-memory ring buffer
    (water/util/Log analog in log.py)."""
    from h2o3_tpu.log import buffered_lines
    return {"__meta": {"schema_version": 3, "schema_name": "LogsV3"},
            "nodeidx": int(nodeidx), "name": name,
            "log": "\n".join(buffered_lines(5000))}


@route("GET", "/3/ModelBuilders/{algo}/model_id")
def _next_model_id(params, body, algo):
    """ModelBuildersHandler.calcModelId: a fresh unique model id."""
    if algo not in _builders():
        raise ApiError(404, f"unknown algorithm '{algo}'")
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelIdV3"},
            "model_id": {"name": dkv.unique_key(f"{algo}_model")}}


@route("POST", "/3/ModelBuilders/{algo}/parameters")
def _validate_parameters(params, body, algo):
    """ModelBuilderHandler.validate_parameters (Flow form validation):
    typed-coerce + construct the builder WITHOUT training; returns
    per-field messages + error_count."""
    builders = _builders()
    if algo not in builders:
        raise ApiError(404, f"unknown algorithm '{algo}'")
    defaults = builders[algo]().params
    messages = []
    parms = {}
    for k, v in params.items():
        if k in ("_rest_version", "model_id", "training_frame",
                 "validation_frame", "response_column"):
            continue
        if k not in defaults:
            messages.append({"message_type": "WARN", "field_name": k,
                             "message": f"unknown parameter '{k}' for "
                                        f"algo '{algo}'"})
            continue
        got = _coerce_typed(k, v, defaults)
        d = defaults.get(k)
        # strict check: _coerce_typed falls back to guessing instead of
        # raising, so validate the COERCED value against the declared
        # type here (bool is an int subtype — test it first)
        ok = True
        if isinstance(d, bool):
            ok = isinstance(got, bool)
        elif isinstance(d, (int, float)):
            ok = isinstance(got, (int, float)) \
                and not isinstance(got, bool) or got is None
        elif isinstance(d, (list, tuple)):
            ok = isinstance(got, (list, tuple)) or got is None
        if not ok:
            messages.append({
                "message_type": "ERRR", "field_name": k,
                "message": f"cannot parse '{v}' as "
                           f"{type(d).__name__} (default {d!r})"})
        else:
            parms[k] = got
    if not any(m["message_type"] == "ERRR" for m in messages):
        try:
            builders[algo](**parms)
        except Exception as e:  # noqa: BLE001 - surfaced as validation
            messages.append({"message_type": "ERRR",
                             "field_name": "_parms", "message": str(e)})
    errs = sum(1 for m in messages if m["message_type"] == "ERRR")
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelParametersSchemaV3"},
            "messages": messages, "error_count": errs}


@route("GET", "/3/FrameChunks/{frame_id}")
def _frame_chunks(params, body, frame_id):
    """water/api/FrameChunksHandler: the frame's physical distribution.
    Chunks map to mesh-shard row ranges in this design (SURVEY §2.5:
    rows shard over the 'data' axis; each shard is one 'chunk')."""
    from h2o3_tpu.parallel.mesh import current_mesh
    fr = dkv.get(frame_id, "frame")
    mesh = current_mesh()
    n_shards = int(mesh.shape.get("data", 1)) if mesh is not None else 1
    per = -(-fr.nrow // max(n_shards, 1))
    chunks = [{"chunk_id": i,
               "row_count": max(0, min(per, fr.nrow - i * per)),
               "node_idx": i}
              for i in range(n_shards)]
    return {"__meta": {"schema_version": 3,
                       "schema_name": "FrameChunksV3"},
            "frame_id": {"name": frame_id},
            "chunks": [c for c in chunks if c["row_count"] > 0]}


@route("GET", "/3/SteamMetrics")
def _steam_metrics(params, body):
    """water/api/SteamMetricsHandler: Enterprise Steam keepalive
    metrics — no Steam in this deployment, report idle truthfully."""
    return {"__meta": {"schema_version": 3,
                       "schema_name": "SteamMetricsV3"},
            "idle_millis": schemas.uptime_ms()}


@route("GET", "/3/Metadata/schemaclasses/{classname}")
def _metadata_schemaclass(params, body, classname):
    """MetadataHandler.fetchSchemaMetadataByClass — same payload as
    /3/Metadata/schemas/{name} (one schema namespace here)."""
    return _schema_meta(params, body, classname)


@route("GET", "/3/ModelMetrics/frames/{frame}")
def _metrics_by_frame(params, body, frame):
    """ModelMetricsHandler.list filtered by frame: stored metrics for
    every model that scored this frame (training-frame metrics here —
    the single-controller store does not index ad-hoc scores)."""
    try:
        dkv.get(frame, "frame")
    except KeyError:
        raise ApiError(404, f"frame '{frame}' not found")
    out = []
    for key in dkv.keys("model"):
        m = dkv.get(key, "model")
        if getattr(m, "training_frame_key", None) != frame:
            continue
        if m.training_metrics is not None:
            v3 = schemas._metrics_v3(
                m.training_metrics, _kind_of(m),
                domain=list(m.response_domain or []) or None,
                frame_key=frame, model_key=key)
            if v3:
                out.append(v3)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelMetricsListSchemaV3"},
            "model_metrics": out}


@route("POST", "/3/ModelMetrics/frames/{frame}/models/{model}")
def _metrics_frame_model(params, body, frame, model):
    """Frame-first spelling of models/{model}/frames/{frame} (POST =
    score)."""
    return _model_metrics_score(params, body, model, frame)


@route("GET", "/3/ModelMetrics/frames/{frame}/models/{model}")
def _metrics_frame_model_fetch(params, body, frame, model):
    """GET = fetch STORED metrics only (ModelMetricsHandler.fetch) —
    no scoring pass, works on frames lacking the response column."""
    m = dkv.get(model, "model")
    out = []
    for mm in (m.training_metrics, m.validation_metrics,
               m.cross_validation_metrics):
        if mm is not None:
            v3 = schemas._metrics_v3(
                mm, _kind_of(m),
                domain=list(m.response_domain or []) or None,
                frame_key=frame, model_key=model)
            if v3:
                out.append(v3)
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelMetricsListSchemaV3"},
            "model_metrics": out}


@route("GET", "/3/Models.fetch.bin/{model}")
def _fetch_model_bin(params, body, model):
    """ModelsHandler.fetchBinaryModel: stream the binary artifact
    (h2o.download_model)."""
    from h2o3_tpu.persist import save_model
    m = dkv.get(model, "model")
    with tempfile.TemporaryDirectory() as td:
        path = save_model(m, path=td, force=True, filename=model)
        data = open(path, "rb").read()
    return {"__raw": data, "__content_type": "application/octet-stream"}


@route("POST", "/99/Models.upload.bin/{model}")
@route("POST", "/99/Models.upload.bin/")
def _upload_model_bin(params, body, model=None):
    """ModelsHandler.uploadBinaryModel (h2o.upload_model): body bytes →
    artifact → live model in the DKV."""
    from h2o3_tpu.persist import load_model
    if not body:
        raise ApiError(400, "binary model body required")
    # accept the client's multipart envelope too (h2o.upload_model posts
    # a file upload): find the zip magic and strip everything before it,
    # and the trailing boundary after the payload
    if body[:2] != b"PK":
        start = body.find(b"PK\x03\x04")
        if start < 0:
            raise ApiError(400, "no zip artifact in request body")
        end = body.rfind(b"\r\n--")
        body = body[start:end if end > start else len(body)]
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "upload.zip")
        with open(p, "wb") as f:
            f.write(body)
        try:
            m = load_model(p)
        except Exception as e:  # noqa: BLE001 - bad artifact → 400
            raise ApiError(400, f"not a model artifact: {e}")
    if model:
        m.key = model
    dkv.put(m.key, "model", m)
    return {"__meta": {"schema_version": 99, "schema_name": "ModelsV3"},
            "models": [{"model_id": {"name": m.key}}]}


@route("GET", "/99/Models/{key}/json")
def _model_json(params, body, key):
    """ModelsHandler.fetch with full output (the /99 'json' spelling
    Flow downloads)."""
    m = dkv.get(key, "model")
    return {"__meta": {"schema_version": 99, "schema_name": "ModelsV3"},
            "models": [schemas.model_v3(m, key)]}
