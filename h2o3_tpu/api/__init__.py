"""REST API — the h2o-py/R/Flow wire surface.

Reference: water/api/RequestServer.java:38 (routing),
water/api/RegisterV3Api.java (~128 endpoints), water/api/schemas3/ (the
versioned JSON shapes), served by embedded Jetty at :54321.

TPU re-design: a stdlib ThreadingHTTPServer on the controller host (no
Jetty, no servlet stack) routing to plain-function handlers; the schema
layer is direct JSON emission matching the schemas3 field names the
clients read. Training runs as background Jobs polled via /3/Jobs.
"""
from h2o3_tpu.api.server import H2OApiServer, start_server

__all__ = ["H2OApiServer", "start_server"]
