"""Flow — the built-in web UI (h2o-web's Flow analog, minimal).

Reference: /root/reference/h2o-web (Flow: a CoffeeScript notebook UI
served by the jar at /flow/index.html). This framework has no node
toolchain in-image, so Flow is re-implemented as ONE self-contained
HTML+JS page speaking the same REST API the clients use: cluster
status + memory report, frame import/parse/preview, model training
across the registered algos, jobs, model metrics, and predictions.
Served at / and /flow/index.html by the embedded server."""

FLOW_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>H2O-3 TPU Flow</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1c2733}
 header{background:#123047;color:#fff;padding:10px 18px;display:flex;gap:18px;align-items:baseline}
 header h1{font-size:18px;margin:0}
 header span{font-size:12px;opacity:.8}
 main{display:grid;grid-template-columns:1fr 1fr;gap:14px;padding:14px}
 section{background:#fff;border:1px solid #dde3e9;border-radius:8px;padding:12px}
 h2{font-size:14px;margin:0 0 8px;border-bottom:1px solid #eef1f4;padding-bottom:6px}
 table{border-collapse:collapse;font-size:12px;width:100%}
 td,th{border:1px solid #e4e8ec;padding:3px 7px;text-align:left}
 th{background:#f0f3f6}
 button{background:#1c6ea4;color:#fff;border:0;border-radius:5px;padding:5px 11px;cursor:pointer;font-size:12px}
 input,select{border:1px solid #c6ccd2;border-radius:5px;padding:4px 7px;font-size:12px}
 pre{background:#0e1726;color:#d7e3f4;padding:8px;border-radius:6px;font-size:11px;overflow:auto;max-height:260px}
 .row{display:flex;gap:8px;margin:6px 0;flex-wrap:wrap;align-items:center}
 .full{grid-column:1/3}
</style></head><body>
<header><h1>H2O-3 TPU — Flow</h1><span id="cloud">connecting…</span></header>
<main>
<section><h2>Import &amp; Parse</h2>
 <div class="row"><input id="path" size="40" placeholder="/path/to/file.csv">
 <button onclick="importParse()">Import + Parse</button></div>
 <div id="parseout"></div></section>
<section><h2>Frames</h2><div class="row">
 <button onclick="listFrames()">Refresh</button></div>
 <div id="frames"></div></section>
<section><h2>Train a Model</h2>
 <div class="row">
  <select id="algo"></select>
  <select id="frame"></select>
  <input id="yresp" size="10" placeholder="response">
  <input id="mparams" size="24" placeholder='{"ntrees":20}'>
  <button onclick="train()">Train</button></div>
 <div id="trainout"></div></section>
<section><h2>Models</h2><div class="row">
 <button onclick="listModels()">Refresh</button></div>
 <div id="models"></div></section>
<section class="full"><h2>Inspector</h2><pre id="out">—</pre></section>
</main>
<script>
const esc = s => String(s).replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const J = async (m, u, body) => {
  const opt = {method: m};
  if (body) { opt.body = new URLSearchParams(body); }
  const r = await fetch(u, opt);
  const t = await r.text();
  try { return JSON.parse(t); } catch (e) { return {raw: t}; }
};
const show = o => document.getElementById('out').textContent =
  JSON.stringify(o, null, 1).slice(0, 20000);
async function cloud() {
  const c = await J('GET', '/3/Cloud');
  const n = (c.nodes || [{}])[0];
  document.getElementById('cloud').textContent =
    `${c.version} · ${(n.tpu_devices||[]).join(', ')} · spills ${n.spill_count ?? 0}`;
}
async function listFrames() {
  const f = await J('GET', '/3/Frames');
  const rows = (f.frames || []).map(fr => {
    const k = encodeURIComponent(fr.frame_id.name);
    return `<tr><td><a href="#" data-k="${esc(fr.frame_id.name)}"
     onclick="inspect(decodeURIComponent('${k}'));return false">${esc(fr.frame_id.name)}</a></td>
     <td>${fr.rows}</td><td>${fr.column_count ?? fr.total_column_count ?? ''}</td></tr>`;
  }).join('');
  document.getElementById('frames').innerHTML =
    `<table><tr><th>frame</th><th>rows</th><th>cols</th></tr>${rows}</table>`;
  const sel = document.getElementById('frame');
  sel.innerHTML = (f.frames || []).map(fr =>
    `<option>${esc(fr.frame_id.name)}</option>`).join('');
}
async function inspect(k) {
  show(await J('GET', '/3/Frames/' + encodeURIComponent(k)));
}
async function importParse() {
  const p = document.getElementById('path').value;
  const imp = await J('POST', '/3/ImportFiles', {path: p});
  if (!imp.destination_frames) { show(imp); return; }
  const setup = await J('POST', '/3/ParseSetup',
                        {source_frames: JSON.stringify(imp.destination_frames)});
  const parse = await J('POST', '/3/Parse', {
    source_frames: JSON.stringify(imp.destination_frames),
    destination_frame: setup.destination_frame,
    separator: setup.separator, check_header: setup.check_header});
  try {
    await poll(parse.job ? parse.job.key.name : (parse.key || {}).name);
    document.getElementById('parseout').textContent = 'parsed ✓';
  } catch (e) {
    document.getElementById('parseout').textContent = 'parse FAILED: ' + e.message;
    show(parse);
    return;
  }
  listFrames();
}
async function poll(jid) {
  if (!jid) throw new Error('no job key in response');
  for (let i = 0; i < 6000; i++) {
    const j = await J('GET', '/3/Jobs/' + encodeURIComponent(jid));
    const jj = j.jobs ? j.jobs[0] : j;
    const st = jj && jj.status;
    if (st === 'FAILED' || st === 'CANCELLED')
      throw new Error((jj.exception || st).toString().slice(0, 400));
    if (st && st !== 'RUNNING') return j;
    await new Promise(r => setTimeout(r, 300));
  }
  throw new Error('job still running after poll limit: ' + jid);
}
async function algos() {
  const b = await J('GET', '/3/ModelBuilders');
  const sel = document.getElementById('algo');
  sel.innerHTML = Object.keys(b.model_builders || {}).map(a =>
    `<option>${a}</option>`).join('');
  sel.value = 'gbm';
}
async function train() {
  const algo = document.getElementById('algo').value;
  const fr = document.getElementById('frame').value;
  const y = document.getElementById('yresp').value;
  let extra = {};
  try { extra = JSON.parse(document.getElementById('mparams').value || '{}'); }
  catch (e) {
    document.getElementById('trainout').textContent =
      'bad params JSON: ' + e.message;
    return;
  }
  const body = {training_frame: fr, response_column: y, ...extra};
  const r = await J('POST', '/3/ModelBuilders/' + algo, body);
  const jid = r.job ? r.job.key.name : (r.key || {}).name;
  document.getElementById('trainout').textContent = 'training…';
  try {
    const j = await poll(jid);
    document.getElementById('trainout').textContent =
      'done: ' + esc((((j.jobs ? j.jobs[0] : j).dest) || {}).name);
  } catch (e) {
    document.getElementById('trainout').textContent =
      'train FAILED: ' + e.message;
    show(r);
    return;
  }
  listModels();
}
async function listModels() {
  const m = await J('GET', '/3/Models');
  const rows = (m.models || []).map(md => {
    const k = encodeURIComponent(md.model_id.name);
    return `<tr><td><a href="#"
     onclick="inspectModel(decodeURIComponent('${k}'));return false">${esc(md.model_id.name)}</a></td>
     <td>${esc(md.algo)}</td></tr>`;
  }).join('');
  document.getElementById('models').innerHTML =
    `<table><tr><th>model</th><th>algo</th></tr>${rows}</table>`;
}
async function inspectModel(k) {
  show(await J('GET', '/3/Models/' + encodeURIComponent(k)));
}
cloud(); listFrames(); listModels(); algos();
setInterval(cloud, 5000);
</script></body></html>
"""
