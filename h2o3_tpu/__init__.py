"""h2o3_tpu — a TPU-native distributed ML platform with H2O-3's capabilities.

The reference (usefulalgorithm/h2o-3) is a JVM cluster holding a distributed
K/V store of columnar frame chunks, computed over with MRTask map/reduce
(see /root/repo/SURVEY.md). This package is the TPU-first re-design:

- the JVM cloud / Paxos / RPC / DKV collapse into single-controller JAX over a
  ``jax.sharding.Mesh`` (axes ``('data', 'model')``);
- Frame/Vec/Chunk become columnar containers over row-sharded ``jax.Array``s;
- MRTask's binary-tree map/reduce becomes ``shard_map`` + XLA collectives
  (``psum``/``all_gather``/``reduce_scatter``) over ICI;
- the native XGBoost ``gpu_hist`` path becomes a JAX/pallas histogram tree
  builder whose per-node grad/hess histograms all-reduce over ICI.

Public surface mirrors the h2o python client (reference h2o-py/h2o/h2o.py).
"""

import jax as _jax

# ``jax.shard_map`` is only public on newer jax; older jaxlib builds (e.g.
# 0.4.37) still keep it under jax.experimental. Alias it once here so every
# call site works on both (this package is always imported before use).
if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def _shard_map_compat(*args, **kwargs):
        if "check_vma" in kwargs:  # newer spelling of check_rep
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

    _jax.shard_map = _shard_map_compat

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.ingest.parse import import_file, parse_setup, upload_numpy
from h2o3_tpu.parallel.mesh import current_mesh, set_mesh, make_mesh
from h2o3_tpu.mojo import import_mojo
from h2o3_tpu.mojo import export_mojo as download_mojo
from h2o3_tpu.persist import export_file, load_model, save_model

__version__ = "0.2.0"

__all__ = [
    "Frame",
    "Vec",
    "import_file",
    "parse_setup",
    "upload_numpy",
    "current_mesh",
    "set_mesh",
    "make_mesh",
    "init",
    "save_model",
    "load_model",
    "export_file",
    "download_mojo",
    "import_mojo",
]


def init(n_data=None, n_model=1, distributed=False,
         coordinator_address=None, num_processes=None, process_id=None,
         port=None):
    """Initialise the runtime: build the global device mesh.

    Replaces the reference's cluster boot (water/H2O.java:2328 main →
    Paxos cloud formation): there is no membership protocol — the mesh is
    the cloud. Multi-chip SPMD is the default whenever more than one
    device is visible (``H2O3_SPMD=0`` collapses the default mesh to a
    single device — the escape hatch).

    ``distributed=True`` is the multi-host path (SURVEY §7.3): every host
    runs the SAME program, ``jax.distributed.initialize`` forms the
    process group (the cloud-formation step), the mesh spans all hosts'
    devices, and the REST server belongs on process 0 only
    (``is_coordinator()``). Worker loss is fatal — the reference's own
    locked-cloud failure model (water/Paxos.java:145), recovery is
    restart + checkpoint reload.
    """
    if distributed:
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    mesh = make_mesh(n_data=n_data, n_model=n_model)
    set_mesh(mesh)
    if distributed and port and is_coordinator():
        from h2o3_tpu.api import start_server
        start_server(port=port)
    return current_mesh()


def is_coordinator() -> bool:
    """True on the REST-serving process (host 0) — the reference's
    'node answering the web port' role (water/H2O.java boot)."""
    import jax
    return jax.process_index() == 0
