"""h2o3_tpu — a TPU-native distributed ML platform with H2O-3's capabilities.

The reference (usefulalgorithm/h2o-3) is a JVM cluster holding a distributed
K/V store of columnar frame chunks, computed over with MRTask map/reduce
(see /root/repo/SURVEY.md). This package is the TPU-first re-design:

- the JVM cloud / Paxos / RPC / DKV collapse into single-controller JAX over a
  ``jax.sharding.Mesh`` (axes ``('data', 'model')``);
- Frame/Vec/Chunk become columnar containers over row-sharded ``jax.Array``s;
- MRTask's binary-tree map/reduce becomes ``shard_map`` + XLA collectives
  (``psum``/``all_gather``/``reduce_scatter``) over ICI;
- the native XGBoost ``gpu_hist`` path becomes a JAX/pallas histogram tree
  builder whose per-node grad/hess histograms all-reduce over ICI.

Public surface mirrors the h2o python client (reference h2o-py/h2o/h2o.py).
"""

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.ingest.parse import import_file, parse_setup, upload_numpy
from h2o3_tpu.parallel.mesh import current_mesh, set_mesh, make_mesh
from h2o3_tpu.mojo import import_mojo
from h2o3_tpu.mojo import export_mojo as download_mojo
from h2o3_tpu.persist import export_file, load_model, save_model

__version__ = "0.2.0"

__all__ = [
    "Frame",
    "Vec",
    "import_file",
    "parse_setup",
    "upload_numpy",
    "current_mesh",
    "set_mesh",
    "make_mesh",
    "init",
    "save_model",
    "load_model",
    "export_file",
    "download_mojo",
    "import_mojo",
]


def init(n_data=None, n_model=1):
    """Initialise the runtime: build the global device mesh.

    Replaces the reference's cluster boot (water/H2O.java:2328 main →
    Paxos cloud formation): there is no membership protocol — the mesh is
    the cloud.
    """
    set_mesh(make_mesh(n_data=n_data, n_model=n_model))
    return current_mesh()
