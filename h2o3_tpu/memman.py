"""Device-memory manager: budget, watermarks, LRU spill-to-host.

Reference: water/Cleaner.java:4 (the background sweeper that swaps
least-recently-used Values to disk when the heap crosses a watermark)
+ water/MemoryManager.java (allocation gate that blocks/frees until
memory is available) + the /3/Cloud free_mem report.

TPU re-design: HBM is the scarce tier and host RAM is the spill target
(the reference spills heap→disk; a v5e host has ~16x the chip's HBM, so
host RAM plays the disk role and disk would be the third tier).
Spillable device blocks (Frame Vec payloads) register here; an
allocation request over the HIGH watermark evicts least-recently-used
blocks to host numpy until under the LOW watermark. Algorithms consult
``fits_device(bytes)`` to pick dense vs streaming execution — frames
beyond the budget stream through training in host-chunked blocks
instead of failing allocation (SURVEY §7.1.7's Criteo-scale config).

The budget defaults to the real device memory when the backend reports
it, and can be forced with H2O3_DEVICE_BUDGET_BYTES (the tests force a
tiny budget on the CPU mesh to exercise eviction + streaming).
"""
from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, Optional

_LOCK = threading.RLock()
_SEQ = 0

HIGH_WATERMARK = 0.90      # evict when a request would cross this
LOW_WATERMARK = 0.70       # ...down to this (Cleaner's DESIRED analog)


def _default_budget() -> int:
    env = os.environ.get("H2O3_DEVICE_BUDGET_BYTES")
    if env:
        return int(env)
    try:
        import jax
        d = jax.devices()[0]
        stats = d.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return 1 << 62             # effectively unlimited (CPU backend)


class _Block:
    """One registered spillable device payload."""

    __slots__ = ("nbytes", "spill", "last_use", "seq", "__weakref__")

    def __init__(self, nbytes: int, spill: Callable[[], None]):
        self.nbytes = nbytes
        self.spill = spill
        self.last_use = time.monotonic()
        self.seq = 0


class MemoryManager:
    def __init__(self, budget: Optional[int] = None):
        self.budget = budget if budget is not None else _default_budget()
        # residency is the sum over LIVE blocks: the WeakSet drops
        # garbage-collected payloads automatically, so no counter to
        # keep consistent across gc/spill/free paths
        self._blocks: "weakref.WeakSet[_Block]" = weakref.WeakSet()
        self.spill_count = 0
        self.spilled_bytes = 0

    @property
    def _resident(self) -> int:
        return sum(b.nbytes for b in self._blocks)

    # -- registry ------------------------------------------------------

    def register(self, nbytes: int, spill: Callable[[], None]) -> _Block:
        """Track a device-resident payload; ``spill`` must move it to
        host and drop the device reference."""
        with _LOCK:
            b = _Block(int(nbytes), spill)
            self._blocks.add(b)
            return b

    def touch(self, block: _Block) -> None:
        block.last_use = time.monotonic()

    def released(self, block: _Block) -> None:
        """The payload left the device (spilled or freed)."""
        with _LOCK:
            self._blocks.discard(block)

    # -- allocation gate (MemoryManager.java malloc-with-wait analog) --

    def request(self, nbytes: int) -> None:
        """Make room for an ``nbytes`` device allocation: evict LRU
        spillable blocks while the projected residency crosses the high
        watermark (down to the low one)."""
        with _LOCK:
            if self._resident + nbytes <= self.budget * HIGH_WATERMARK:
                return
            target = max(self.budget * LOW_WATERMARK - nbytes, 0)
            for b in sorted(self._blocks, key=lambda b: b.last_use):
                if self._resident <= target:
                    break
                try:
                    b.spill()
                finally:
                    self.spill_count += 1
                    self.spilled_bytes += b.nbytes
                    self.released(b)

    def fits_device(self, nbytes: int) -> bool:
        """Whether a dense allocation of this size is within budget —
        algorithms switch to host-chunked streaming when it is not."""
        return nbytes <= self.budget * HIGH_WATERMARK

    @property
    def unlimited(self) -> bool:
        """True on backends that report no real device limit (CPU) —
        the training scheduler's admission gate is a no-op there."""
        return self.budget >= (1 << 61)

    def admission_budget(self) -> int:
        """Bytes the training scheduler (h2o3_tpu.sched) may promise to
        concurrently RUNNING trains: the same high-watermark ceiling the
        allocation gate evicts toward, so admitted work and LRU spill
        agree on what 'full' means."""
        return int(self.budget * HIGH_WATERMARK)

    # -- reporting (/3/Cloud free_mem) ---------------------------------

    def stats(self) -> Dict[str, Any]:
        with _LOCK:
            return {
                "device_budget_bytes": self.budget
                if self.budget < (1 << 61) else -1,
                "device_resident_bytes": self._resident,
                "registered_blocks": len(self._blocks),
                "spill_count": self.spill_count,
                "spilled_bytes": self.spilled_bytes,
                "high_watermark": HIGH_WATERMARK,
                "low_watermark": LOW_WATERMARK,
            }


_MANAGER: Optional[MemoryManager] = None


def manager() -> MemoryManager:
    global _MANAGER
    with _LOCK:
        if _MANAGER is None:
            _MANAGER = MemoryManager()
        return _MANAGER


def reset(budget: Optional[int] = None) -> MemoryManager:
    """Tests: reinstall with an explicit budget."""
    global _MANAGER
    with _LOCK:
        _MANAGER = MemoryManager(budget)
        return _MANAGER
