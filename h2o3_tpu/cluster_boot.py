"""Kubernetes pod entrypoint for multi-host clusters.

Reference: h2o-k8s/ (assisted clustering: H2OKubernetesEmbeddedConfig
resolves peers from a headless-service DNS lookup and waits for cloud
stabilization) + h2o-helm/. TPU re-design: no peer discovery protocol —
``jax.distributed.initialize`` IS cloud formation, and the coordinator
address is a deterministic StatefulSet DNS name (pod ordinal 0), so the
"lookup + stabilize" machinery collapses into env-var resolution. Every
pod runs this module; process 0 additionally serves REST (the node
answering the web port, water/H2O.java boot).

Env contract (set by h2o-k8s/manifests or the h2o-helm chart):
  H2O3_COORDINATOR_ADDRESS  host:port of pod 0 (headless-service DNS)
  H2O3_NUM_PROCESSES        replica count
  H2O3_PROCESS_ID           this pod's ordinal; derived from the
                            StatefulSet hostname suffix when unset
  H2O3_REST_PORT            REST port on the coordinator (default 54321)
  H2O3_MESH_MODEL           'model' mesh axis size (default 1)
  H2O3_COMPILE_CACHE_DIR    persistent XLA compilation cache directory
                            (default ~/.cache/h2o3_tpu/xla; '0'/'off'
                            disables). Mount a PVC here so a pod
                            restart's time-to-first-model skips the
                            cold train-step compile (~2 minutes at the
                            10M-row bench shape).
  H2O3_RECOVERY_DIR         durable restart-recovery root (mount a PVC).
                            When set, boot scans it for trains the
                            PREVIOUS process left interrupted (crash /
                            kill -9 / pod eviction), re-registers them
                            as RECOVERING jobs and resumes them from
                            their in-training checkpoints under the new
                            process's mesh — plus age-based GC of
                            orphaned checkpoint artifacts. Unset =
                            checked no-op (h2o3_tpu/recovery.py).

Run: ``python -m h2o3_tpu.cluster_boot``
"""
from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Mapping, Optional


def setup_compilation_cache(env: Optional[Mapping[str, str]] = None) -> Optional[str]:
    """Wire JAX's persistent compilation cache so the cold train-step
    spec/compile amortises across process restarts (the reference JVM
    has no compile step; this cost is TPU-stack-specific and so is the
    fix). Returns the cache dir, or None when disabled / unsupported.

    Safe to call before OR after the first jax use in the process —
    compiles after the call hit the cache. Honors an explicit
    ``jax_compilation_cache_dir`` already set (e.g. the test conftest's
    per-worker cache) rather than overriding it."""
    env = dict(env if env is not None else os.environ)
    # boot is the earliest common chokepoint every entrypoint passes
    # through (k8s pod, bench, tools) — install the telemetry listeners
    # here so the production compile counter sees the FIRST compile
    from h2o3_tpu import telemetry
    telemetry.install()
    raw = env.get("H2O3_COMPILE_CACHE_DIR")
    raw = raw.strip() if raw is not None else None   # k8s YAML whitespace
    if raw is not None and raw.lower() in ("0", "off", "false"):
        return None
    # empty-but-set (blank helm value) means unset: fall through to the
    # default dir rather than silently disabling the cache
    import jax
    try:
        if jax.config.jax_compilation_cache_dir:
            return jax.config.jax_compilation_cache_dir
    except AttributeError:
        pass
    d = raw or os.path.join(os.path.expanduser("~"), ".cache",
                            "h2o3_tpu", "xla")
    try:
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        # the defaults skip sub-second compiles; the chunked train step
        # is minutes cold, so any threshold works — keep 1s to avoid
        # churning the cache with trivial eager-op executables
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except (OSError, AttributeError, ValueError):
        return None
    return d


@dataclass
class BootConfig:
    coordinator_address: str
    num_processes: int
    process_id: int
    rest_port: int
    n_model: int


def resolve_boot_config(env: Optional[Mapping[str, str]] = None,
                        hostname: Optional[str] = None) -> BootConfig:
    """Pure env → config resolution (unit-testable without a cluster).

    The pod ordinal falls back to the trailing ``-<n>`` of the
    StatefulSet hostname (``h2o3-2`` → 2) the way the reference's
    assisted clustering derives identity from pod metadata."""
    env = dict(env if env is not None else os.environ)
    addr = env.get("H2O3_COORDINATOR_ADDRESS")
    if not addr:
        raise ValueError("H2O3_COORDINATOR_ADDRESS is required "
                         "(pod-0 headless-service DNS, host:port)")
    n = int(env.get("H2O3_NUM_PROCESSES", "1"))
    pid_s = env.get("H2O3_PROCESS_ID")
    if pid_s is None or pid_s == "":
        host = hostname if hostname is not None else os.uname().nodename
        m = re.search(r"-(\d+)$", host)
        if not m:
            raise ValueError(
                f"H2O3_PROCESS_ID unset and hostname '{host}' has no "
                f"StatefulSet ordinal suffix")
        pid = int(m.group(1))
    else:
        pid = int(pid_s)
    if not (0 <= pid < n):
        raise ValueError(f"process_id {pid} outside [0, {n})")
    return BootConfig(
        coordinator_address=addr, num_processes=n, process_id=pid,
        rest_port=int(env.get("H2O3_REST_PORT", "54321")),
        n_model=int(env.get("H2O3_MESH_MODEL", "1")))


def run_boot_recovery(wait: bool = False) -> Optional[dict]:
    """Boot-time restart recovery (h2o3_tpu/recovery.py): rediscover
    trains a killed predecessor process left interrupted and resume
    them from their in-training checkpoints. Checked no-op when
    ``H2O3_RECOVERY_DIR`` is unset — the recovery module is not even
    imported. NEVER raises: a broken recovery dir must not wedge
    process startup (the scan itself already isolates per-manifest
    failures; this guard covers the rest)."""
    if not (os.environ.get("H2O3_RECOVERY_DIR") or "").strip():
        return None
    try:
        from h2o3_tpu import recovery
        return recovery.recover_at_boot(wait=wait)
    except Exception as e:   # noqa: BLE001 — boot must proceed
        from h2o3_tpu.log import warn
        warn("boot recovery failed (%s) — continuing boot without it", e)
        return None


def main() -> None:
    import h2o3_tpu as h2o
    setup_compilation_cache()
    cfg = resolve_boot_config()
    h2o.init(distributed=True,
             coordinator_address=cfg.coordinator_address,
             num_processes=cfg.num_processes,
             process_id=cfg.process_id,
             n_model=cfg.n_model,
             port=cfg.rest_port)
    import jax
    if cfg.process_id == 0:
        # the coordinator drives training, so it owns recovery; resumes
        # run in the background — the REST/readiness port must come up
        # immediately, recovered models appear on /3/Models as they land
        run_boot_recovery(wait=False)
    if cfg.process_id != 0:
        # workers answer the web port too — but only with a minimal
        # health responder so the /3/Cloud readiness probe passes on
        # every pod (the reference's every-node-answers-the-web-port
        # behavior; full REST stays coordinator-only by design)
        _serve_worker_health(cfg)
    print(f"h2o3_tpu pod {cfg.process_id}/{cfg.num_processes} up: "
          f"{len(jax.devices())} global devices"
          + (f", REST :{cfg.rest_port}" if cfg.process_id == 0 else ""),
          flush=True)
    # workers park forever; the coordinator's REST server owns the
    # process lifetime (SIGTERM from k8s ends the pod)
    import threading
    threading.Event().wait()


def _serve_worker_health(cfg: BootConfig) -> None:
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Health(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server contract
            body = json.dumps({
                "role": "worker", "process_id": cfg.process_id,
                "coordinator": cfg.coordinator_address}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("", cfg.rest_port), _Health)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()


if __name__ == "__main__":
    main()
