"""H2OAssembly — server-side munging pipeline (fit + POJO export).

Reference: water/rapids/transforms/{Transform,H2OColSelect,H2OColOp,
H2OBinaryOp}.java + water/api/AssemblyHandler — the client
(h2o-py/h2o/assembly.py:388) POSTs steps serialized as
``name__Class__(ast with the frame id 'dummy')__inplace__new|names``
and the server replays each step's Rapids ast against the live frame,
with H2OColOp splicing the single-column result back per `inplace`
(H2OColOp.java:48-68 transformImpl).

TPU re-design: the step asts run through this repo's Rapids engine
(device ops); the `dummy` placeholder is rewritten to a per-fit unique
DKV key (concurrent fits must not race a shared binding). POJO export (GenMunger analog) emits Java for the transform
subset with a closed Java form (column select, unary Math ops); ops
outside it raise with the op named — an honest gate, not a stub.
"""
from __future__ import annotations

import re
from typing import List, Optional

from h2o3_tpu import dkv
from h2o3_tpu.frame.frame import Frame

# unary rapids op -> java Math expression template (GenMunger subset)
_JAVA_UNARY = {
    "cos": "Math.cos(v)", "sin": "Math.sin(v)", "tan": "Math.tan(v)",
    "log": "Math.log(v)", "exp": "Math.exp(v)", "sqrt": "Math.sqrt(v)",
    "abs": "Math.abs(v)", "floor": "Math.floor(v)",
    "ceiling": "Math.ceil(v)", "cosh": "Math.cosh(v)",
    "sinh": "Math.sinh(v)", "tanh": "Math.tanh(v)",
}


class AssemblyStep:
    def __init__(self, raw: str):
        parts = raw.split("__", 4)
        if len(parts) != 5:
            raise ValueError(f"malformed assembly step '{raw}'")
        self.name, self.cls, self.ast, inplace, newc = parts
        self.inplace = str(inplace).lower() == "true"
        self.new_names: Optional[List[str]] = \
            None if newc in ("|", "") else newc.split("|")

    def old_col(self) -> Optional[str]:
        """The operated-on column: first (cols_py dummy 'col') in the
        ast (H2OColOp.java findOldName)."""
        m = re.search(r"\(cols_py\s+dummy\s+'([^']+)'\)", self.ast) or \
            re.search(r'\(cols_py\s+dummy\s+"([^"]+)"\)', self.ast)
        return m.group(1) if m else None


class Assembly:
    def __init__(self, key: str, steps: List[AssemblyStep]):
        self.key = key
        self.steps = steps

    def fit(self, frame: Frame) -> Frame:
        from h2o3_tpu.rapids import exec_rapids
        # shallow copy: steps splice columns into f, and the input frame
        # (a live DKV key) must not be mutated through the shared object
        f = Frame(list(frame.names), list(frame.vecs))
        for step in self.steps:
            # per-fit placeholder key: binding the literal 'dummy' would
            # race concurrent fits on the threading server and clobber a
            # user frame of that name — rewrite the ast instead
            ph = dkv.unique_key("_asm_ph")
            ast = re.sub(r"\bdummy\b", ph, step.ast)
            dkv.put(ph, "frame", f)
            try:
                res = exec_rapids(ast)
            finally:
                dkv.remove(ph)
            out = res.get("key")
            rf = dkv.get(out["name"], "frame") if out else None
            if out:
                dkv.remove(out["name"])  # intermediate; f keeps the vecs
            if rf is None:
                raise ValueError(f"step '{step.name}' did not produce "
                                 f"a frame")
            if step.cls == "H2OColSelect":
                f = rf
                continue
            old = step.old_col()
            if rf.ncol > 1:
                names = step.new_names or [
                    _uniquify(f, old or "C", i) for i in range(rf.ncol)]
                for i, n in enumerate(names[: rf.ncol]):
                    f[n] = rf.vec(i)
                if step.inplace and old in f.names:
                    f = f.drop(old)
            elif step.inplace:
                f[old] = rf.vec(0)
            else:
                n = (step.new_names[0] if step.new_names
                     else _uniquify(f, old or "C", 0))
                f[n] = rf.vec(0)
        return f

    def to_java(self, class_name: str) -> str:
        """GenMunger POJO: per-row double[] transform for the closed
        subset (select + unary Math col ops)."""
        body = []
        for s in self.steps:
            if s.cls == "H2OColSelect":
                cols = re.findall(r"'([^']+)'", s.ast)
                jlist = ", ".join(f'"{c}"' for c in cols)
                body.append(f"    // step {s.name}: select {cols}")
                body.append(f"    row = select(row, names, "
                            f"new String[]{{{jlist}}});")
                # row is re-indexed by keep[] — names must follow, or
                # later column lookups hit stale positions
                body.append(f"    names = new String[]{{{jlist}}};")
                continue
            op = s.ast.strip("( ").split()[0]
            if op not in _JAVA_UNARY:
                raise NotImplementedError(
                    f"POJO export for op '{op}' is not in the closed "
                    f"GenMunger subset ({sorted(_JAVA_UNARY)}); score "
                    f"through the REST pipeline instead")
            col = s.old_col()
            body.append(f"    // step {s.name}: {op}({col}) "
                        f"inplace={s.inplace}")
            if s.inplace:
                body.append(f"    row = unaryInplace(row, names, "
                            f"\"{col}\", \"{op}\");")
            else:
                newn = (s.new_names[0] if s.new_names
                        else f"{col}_{op}")
                body.append(f"    row = appendUnary(row, names, "
                            f"\"{col}\", \"{op}\");")
                body.append(f"    names = appendName(names, "
                            f"\"{newn}\");")
        steps_src = "\n".join(body)
        return _JAVA_TEMPLATE.format(cls=class_name, steps=steps_src,
                                     ops="\n".join(
                                         f'      case "{k}": return '
                                         f'{v};'
                                         for k, v in
                                         _JAVA_UNARY.items()))


def _uniquify(f: Frame, base: str, i: int) -> str:
    cand = f"{base}{i}" if i else base
    while cand in f.names:
        cand += "0"
    return cand


_JAVA_TEMPLATE = """// Generated munging POJO (water/rapids/transforms GenMunger analog)
public class {cls} {{
  public static double[] transform(double[] row, String[] names) {{
{steps}
    return row;
  }}  // names evolves locally when steps append columns

  static double[] select(double[] row, String[] names, String[] keep) {{
    double[] out = new double[keep.length];
    for (int i = 0; i < keep.length; i++)
      for (int j = 0; j < names.length; j++)
        if (names[j].equals(keep[i])) out[i] = row[j];
    return out;
  }}

  static double[] unaryInplace(double[] row, String[] names,
                               String col, String op) {{
    for (int j = 0; j < names.length; j++)
      if (names[j].equals(col)) row[j] = apply(op, row[j]);
    return row;
  }}

  static double[] appendUnary(double[] row, String[] names, String col,
                              String op) {{
    double v = Double.NaN;
    for (int j = 0; j < names.length; j++)
      if (names[j].equals(col)) v = row[j];
    double[] out = new double[row.length + 1];
    System.arraycopy(row, 0, out, 0, row.length);
    out[row.length] = apply(op, v);
    return out;
  }}

  static String[] appendName(String[] names, String n) {{
    String[] out = new String[names.length + 1];
    System.arraycopy(names, 0, out, 0, names.length);
    out[names.length] = n;
    return out;
  }}

  static double apply(String op, double v) {{
    switch (op) {{
{ops}
      default: throw new IllegalArgumentException(op);
    }}
  }}
}}
"""


def parse_steps(steps_param) -> List[AssemblyStep]:
    import json
    steps = steps_param
    if isinstance(steps, str):
        steps = json.loads(steps)
    return [AssemblyStep(s) for s in steps]
