"""Shared retry/backoff + failure classification for the resilience layer.

Reference: H2O-3 leans on its cloud runtime (L1/L2 heartbeats, job
supervision, water/Job retries at the task layer) for transient-failure
tolerance. Under single-controller JAX the equivalents are concentrated
at a handful of seams — host↔device transfers, XLA compile/execute,
persist reads, the serve batcher's device stage — and this module is the
one policy those seams share:

- ``is_transient``  — retryable device/transfer/storage hiccups
  (UNAVAILABLE / INTERNAL / DATA_LOSS / ABORTED status codes, socket
  resets, flaky-storage IO errors).
- ``is_oom``        — RESOURCE_EXHAUSTED / device OOM: NOT retryable
  (repeating the same allocation fails the same way); the training
  driver degrades dense→streamed instead.
- ``retry_transient`` — bounded exponential backoff with jitter around
  a callable, emitting ``h2o3_retry_total{site=...}`` per retry and a
  ``h2o3_recovery_ms`` histogram per recovered incident so recovery
  latency is a first-class telemetry series (the chaos bench reads it).

Classification is marker-based over the exception message PLUS
isinstance checks against the injected-fault taxonomy (faults.py), so
injected and organic failures take the same path.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

from h2o3_tpu import faults

T = TypeVar("T")

# grpc/XLA status-code spellings surfaced by jaxlib's XlaRuntimeError,
# plus common socket/storage phrasings from urllib/pyarrow
_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "INTERNAL", "DATA_LOSS", "ABORTED", "CANCELLED",
    "DEADLINE_EXCEEDED", "connection reset", "connection refused",
    "broken pipe", "temporarily unavailable", "timed out", "EAGAIN",
)

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "OOM",
                "Resource exhausted")


def is_oom(exc: BaseException) -> bool:
    """Device allocation failure — degrade, don't retry."""
    if isinstance(exc, faults.ResourceExhausted):
        return True
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def is_transient(exc: BaseException) -> bool:
    """Retryable transient failure. OOM and injected Fatal are
    explicitly NOT transient."""
    if is_oom(exc) or isinstance(exc, faults.Fatal):
        return False
    if isinstance(exc, (faults.Unavailable, faults.Internal,
                        faults.DataLoss, faults.InjectedIOError)):
        return True
    msg = str(exc)
    return any(m in msg for m in _TRANSIENT_MARKERS)


def is_transient_io(exc: BaseException) -> bool:
    """Storage flavor: OSError/IOError counts as retryable (flaky remote
    reads), on top of the generic transient markers — EXCEPT the
    deterministic ones (missing file, permissions), which fail the same
    way every attempt."""
    if isinstance(exc, faults.Fatal):
        return False
    if isinstance(exc, (FileNotFoundError, IsADirectoryError,
                        NotADirectoryError, PermissionError)):
        return False
    code = getattr(exc, "code", None)       # urllib HTTPError: 4xx is
    if isinstance(code, int) and 400 <= code < 500:   # deterministic
        return False
    if isinstance(exc, (OSError, IOError)):
        return True
    return is_transient(exc)


def resilient_device_put(arr, sharding=None, *, site: str = "h2d",
                         pipeline: Optional[str] = None):
    """``jax.device_put`` behind the ``h2d`` fault seam with the shared
    transient retry — the one policy every H2D call site
    (frame/vec.py grouped puts, the ingest chunk streamer, the
    streamed-GBM uploads) goes through, so backoff/fault semantics
    change in exactly one place."""
    import jax

    def _put():
        if faults.ACTIVE:
            faults.check("h2d", pipeline=pipeline)
        if sharding is not None:
            return jax.device_put(arr, sharding)
        return jax.device_put(arr)

    return retry_transient(
        _put, site=site if pipeline is None else f"{pipeline}.h2d")


def resilient_shard_rows(arr, mesh=None, *, pipeline: Optional[str] = None,
                         global_rows: Optional[int] = None):
    """Row-shard a padded host array over the mesh data axis behind the
    same ``h2d`` fault seam + transient retry as
    :func:`resilient_device_put`. This is the partitioner-aware spelling
    every frame-column placement goes through — on a multi-process mesh
    it assembles the global array from process-local rows
    (``jax.make_array_from_process_local_data``) instead of a plain
    ``device_put``. ``global_rows`` is the multihost-ingest spelling:
    ``arr`` is this process's LOCAL row block of a ``global_rows``-row
    global array (mesh.shard_rows docs)."""
    from h2o3_tpu.parallel.mesh import partitioner

    part = partitioner(mesh)

    def _put():
        if faults.ACTIVE:
            faults.check("h2d", pipeline=pipeline)
        return part.shard_rows(arr, global_rows=global_rows)

    return retry_transient(
        _put, site="h2d" if pipeline is None else f"{pipeline}.h2d")


def retry_transient(fn: Callable[[], T], *, site: str,
                    attempts: int = 3, base_delay_s: float = 0.05,
                    max_delay_s: float = 2.0,
                    classify: Callable[[BaseException], bool] = is_transient,
                    sleep: Callable[[float], None] = time.sleep) -> T:
    """Call ``fn`` with bounded exponential-backoff retries on transient
    failures. Non-transient exceptions (OOM, Fatal, client errors)
    propagate immediately. On recovery the incident's total duration
    lands in ``h2o3_recovery_ms{site=...}``."""
    if attempts <= 1:
        return fn()
    t_first_failure: Optional[float] = None
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            out = fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            if not classify(e) or attempt == attempts - 1:
                raise
            last = e
            if t_first_failure is None:
                t_first_failure = time.perf_counter()
            from h2o3_tpu import telemetry
            from h2o3_tpu.log import warn
            telemetry.counter(
                "h2o3_retry_total", {"site": site},
                help="transient-failure retries by call site").inc()
            # full-jitter exponential backoff (AWS architecture blog
            # shape): uniform in (0, base · 2^attempt], capped
            delay = min(base_delay_s * (2 ** attempt), max_delay_s)
            delay *= random.random() or 0.5
            warn("%s: transient failure (%s) — retry %d/%d in %.0fms",
                 site, type(e).__name__, attempt + 1, attempts - 1,
                 delay * 1e3)
            sleep(delay)
            continue
        if t_first_failure is not None:
            from h2o3_tpu import telemetry
            telemetry.histogram(
                "h2o3_recovery_ms", {"site": site},
                help="ms from first transient failure to recovery",
                bounds=(1.0, 5.0, 10.0, 50.0, 100.0, 500.0, 1000.0,
                        5000.0, 30_000.0)).observe(
                (time.perf_counter() - t_first_failure) * 1e3)
        return out
    raise last  # pragma: no cover — loop always returns or raises
