"""Device mesh management — the TPU replacement for the reference's "cloud".

The reference forms a cluster of JVMs via gossip heartbeats and a consensus
protocol (water/Paxos.java:27, water/H2O.java:1974 CLOUD membership). In
single-controller JAX none of that exists: the set of devices is known at
process start and never changes. The mesh has two axes:

- ``data``  — rows are sharded here (the analog of chunks round-robin'd
  across nodes, water/Key.java:117-138);
- ``model`` — features / parameters shard here for wide problems (the
  reference never shards the wide axis — SURVEY.md §5 long-context note —
  this is where the TPU design goes beyond it).
"""
from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_MESH: Mesh | None = None

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_data: int | None = None, n_model: int = 1, devices=None) -> Mesh:
    """Build a ('data', 'model') mesh over the available devices."""
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if n_data is None:
        n_data = max(1, n // n_model)
    if n_data * n_model > n:
        raise ValueError(
            f"mesh shape ({n_data},{n_model}) needs {n_data * n_model} devices, have {n}"
        )
    dev_array = np.array(devices[: n_data * n_model]).reshape(n_data, n_model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def set_mesh(mesh: Mesh) -> None:
    global _MESH
    _MESH = mesh


def current_mesh() -> Mesh:
    """The global mesh, lazily created over all devices (pure data axis)."""
    global _MESH
    if _MESH is None:
        _MESH = make_mesh()
    return _MESH


def n_data_shards(mesh: Mesh | None = None) -> int:
    mesh = mesh or current_mesh()
    return mesh.shape[DATA_AXIS]


def data_sharding(mesh: Mesh | None = None) -> NamedSharding:
    """Sharding for row-partitioned 1-D/2-D arrays (rows on 'data')."""
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh | None = None) -> NamedSharding:
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, P())


def padded_len(nrow: int, mesh: Mesh | None = None, multiple: int = 8) -> int:
    """Rows are padded so every data shard has the same length (static shapes
    for XLA) and each shard length is a multiple of ``multiple`` (TPU sublane
    alignment). Replaces the reference's variable-size ESPC chunk layout
    (water/fvec/Vec.java:163-171) with an even partition."""
    nd = n_data_shards(mesh)
    q = multiple * nd
    return max(q, int(math.ceil(nrow / q)) * q)
