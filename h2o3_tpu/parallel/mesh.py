"""Device mesh management — the TPU replacement for the reference's "cloud".

The reference forms a cluster of JVMs via gossip heartbeats and a consensus
protocol (water/Paxos.java:27, water/H2O.java:1974 CLOUD membership). In
single-controller JAX none of that exists: the set of devices is known at
process start and never changes. The mesh has two axes:

- ``data``  — rows are sharded here (the analog of chunks round-robin'd
  across nodes, water/Key.java:117-138);
- ``model`` — features / parameters shard here for wide problems (the
  reference never shards the wide axis — SURVEY.md §5 long-context note —
  this is where the TPU design goes beyond it).

Multi-chip SPMD is the DEFAULT whenever more than one device is visible:
the lazy mesh spans every device on the data axis, frame columns land
mesh-sharded (frame/vec.py routes through the partitioner below), and
the tree growers psum their histograms per level. ``H2O3_SPMD=0`` is the
escape hatch — the default mesh collapses to device 0 and every pipeline
behaves exactly like a single-chip run (an explicit ``set_mesh``/
``make_mesh(n_data=...)`` still wins: the knob gates the DEFAULT, not a
caller's deliberate choice).
"""
from __future__ import annotations

import math
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_MESH: Mesh | None = None

DATA_AXIS = "data"
MODEL_AXIS = "model"


def spmd_enabled() -> bool:
    """Whether multi-chip SPMD execution is allowed to engage. Checked
    wherever the DEFAULT behavior would span devices: the lazy mesh,
    model-axis split search, shard-aligned streamed ingest."""
    return os.environ.get("H2O3_SPMD", "1") not in ("0", "false", "")


def make_mesh(n_data: int | None = None, n_model: int = 1, devices=None) -> Mesh:
    """Build a ('data', 'model') mesh over the available devices.

    With ``H2O3_SPMD=0`` and no explicit shape/devices the mesh collapses
    to a single device — the escape hatch restoring single-chip
    behavior on any host. An explicit ``n_data``/``n_model``/``devices``
    is a deliberate caller choice and always wins over the knob."""
    if (devices is None and n_data is None and n_model == 1
            and not spmd_enabled()):
        devices = list(jax.devices())[:1]
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if n_data is None:
        n_data = max(1, n // n_model)
    if n_data * n_model > n:
        raise ValueError(
            f"mesh shape ({n_data},{n_model}) needs {n_data * n_model} devices, have {n}"
        )
    dev_array = np.array(devices[: n_data * n_model]).reshape(n_data, n_model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def set_mesh(mesh: Mesh) -> None:
    global _MESH
    _MESH = mesh


def current_mesh() -> Mesh:
    """The global mesh, lazily created over all devices (pure data axis)."""
    global _MESH
    if _MESH is None:
        _MESH = make_mesh()
    return _MESH


def n_data_shards(mesh: Mesh | None = None) -> int:
    mesh = mesh or current_mesh()
    return mesh.shape[DATA_AXIS]


def data_sharding(mesh: Mesh | None = None) -> NamedSharding:
    """Sharding for row-partitioned 1-D/2-D arrays (rows on 'data')."""
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh | None = None) -> NamedSharding:
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, P())


def n_model_shards(mesh: Mesh | None = None) -> int:
    mesh = mesh or current_mesh()
    return mesh.shape[MODEL_AXIS]


# logical→physical axis rules, highest priority first (the exemplar
# pattern from T5X/scaling codebases: a layer names its LOGICAL axes and
# the partitioner resolves them against the mesh). 'rows' is the
# chunk-homed axis (water/Key.java:117-138 round-robin analog);
# 'features' shards split-search work on the model axis; everything
# else replicates.
_AXIS_RULES = (
    ("rows", DATA_AXIS),
    ("features", MODEL_AXIS),
    ("trees", None),
    ("bins", None),
    ("classes", None),
)


def logical_to_physical(logical_axes) -> P:
    """Resolve a tuple of logical axis names to a PartitionSpec by rule
    priority; a physical axis is consumed by the first logical axis that
    claims it (so ('rows', 'rows') cannot double-map 'data')."""
    used = set()
    out = []
    for ax in logical_axes:
        phys = None
        for lname, pname in _AXIS_RULES:
            if lname == ax and pname is not None and pname not in used:
                phys = pname
                used.add(pname)
                break
        out.append(phys)
    return P(*out)


class DataParallelPartitioner:
    """The row-partitioning layer between host data and the mesh — the
    TPU analog of the reference's chunk-home assignment (a Key's home
    node, water/Key.java:117-138): every padded row block has exactly
    one home data shard, and placement helpers put host arrays there.

    Single-process: ``shard_rows`` is one sharded ``device_put``.
    Multi-process (jax.distributed): each process hands its LOCAL rows
    and the global array is assembled with
    ``jax.make_array_from_process_local_data`` (the exemplar
    DataParallelPartitioner shape) — no process ever materializes the
    full matrix.
    """

    def __init__(self, mesh: Mesh | None = None):
        self.mesh = mesh or current_mesh()

    @property
    def n_data(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    def spec(self, *logical_axes) -> P:
        return logical_to_physical(logical_axes)

    def sharding(self, *logical_axes) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec(*logical_axes))

    @property
    def data_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(DATA_AXIS))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- row placement --------------------------------------------------

    def shard_rows(self, arr, global_rows: int | None = None):
        """Place a host array row-sharded over the data axis. ``arr`` is
        padded (rows divisible by n_data).

        Under a multi-process mesh two spellings exist: with
        ``global_rows`` given, ``arr`` is this process's LOCAL row block
        (``make_array_from_process_local_data``, the multihost-worker
        shape); without it, ``arr`` is the GLOBAL array replicated on
        every process (the single-program frame paths — every host runs
        the same parse) and each process contributes only the row slices
        its devices own (``make_array_from_callback``)."""
        if jax.process_count() > 1:
            if global_rows is None:
                return jax.make_array_from_callback(
                    arr.shape, self.data_sharding, lambda idx: arr[idx])
            return jax.make_array_from_process_local_data(
                self.data_sharding, arr, (global_rows, *arr.shape[1:]))
        return jax.device_put(arr, self.data_sharding)

    def replicate(self, arr):
        return jax.device_put(arr, self.replicated)

    # -- chunk homing (shard-aligned streamed ingest) -------------------

    def shard_devices(self, shard: int):
        """The device column owning data-shard ``shard`` (one device per
        model-axis position; index 0 is the shard's primary home)."""
        devs = np.asarray(self.mesh.devices).reshape(self.n_data, -1)
        return list(devs[shard])

    def home_device(self, shard: int):
        return self.shard_devices(shard)[0]

    def chunk_home(self, chunk_idx: int, n_chunks: int) -> int:
        """Home data shard for byte-range chunk ``chunk_idx`` of
        ``n_chunks`` — chunks map to shards in row order (chunk order IS
        row order for a CSV byte-range fan-out), so a chunk's H2D lands
        on (or near) the device that will own its rows."""
        n_chunks = max(n_chunks, 1)
        return min(chunk_idx * self.n_data // n_chunks, self.n_data - 1)

    def row_bounds(self, padded_rows: int):
        """[(start, end)) row range per data shard of a padded array."""
        per = padded_rows // self.n_data
        return [(d * per, (d + 1) * per) for d in range(self.n_data)]

    def shard_process(self, shard: int, nproc: Optional[int] = None):
        """Process owning data-shard ``shard`` — the multihost parse's
        range-ownership map (each process tokenizes only byte ranges
        whose rows land in its own shards). On a real multi-process
        mesh this is the home device's ``process_index``; under a
        SIMULATED process count (the parity test forcing the
        multi-process range plan on the single-process virtual mesh)
        shards split evenly and contiguously across ``nproc``."""
        if nproc is None or nproc == jax.process_count():
            return int(getattr(self.home_device(shard), "process_index", 0))
        return shard * nproc // self.n_data

    # -- per-shard step observation (collective/straggler metrics) ------

    def observe_step(self, out, t_dispatch: float, *, algo: str = "train"):
        """Record per-shard completion/collective-wait metrics for one
        dispatched sharded step (parallel/shardstats.py); the seam the
        GBM/DRF chunk loops call at their commit points. No-op (None)
        on single-shard meshes or with telemetry disabled."""
        if self.n_data <= 1:
            return None
        from h2o3_tpu.parallel.shardstats import observe_sharded_step
        return observe_sharded_step(out, t_dispatch, algo=algo)


def partitioner(mesh: Mesh | None = None) -> DataParallelPartitioner:
    return DataParallelPartitioner(mesh or current_mesh())


def padded_len(nrow: int, mesh: Mesh | None = None, multiple: int = 8) -> int:
    """Rows are padded so every data shard has the same length (static shapes
    for XLA) and each shard length is a multiple of ``multiple`` (TPU sublane
    alignment). Replaces the reference's variable-size ESPC chunk layout
    (water/fvec/Vec.java:163-171) with an even partition."""
    nd = n_data_shards(mesh)
    q = multiple * nd
    return max(q, int(math.ceil(nrow / q)) * q)
