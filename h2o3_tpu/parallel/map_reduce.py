"""The compute combinator — TPU replacement for MRTask.

The reference expresses *all* distributed compute as
``new MRTask(){ map(Chunk); reduce(T) }.doAll(frame)`` — a binary tree
fan-out over nodes then ForkJoin threads, with pairwise reduction back up
(water/MRTask.java:65, :695, :871-926). Here the same contract is one
``shard_map``: ``map_fn`` runs per data-shard (the "chunk"), and the
reduction is an XLA collective over the ICI mesh axis instead of a
serialize-and-merge tree.

Two shapes, mirroring MRTask's two uses:
- ``map_reduce``  — map + associative reduce to a replicated result
  (MRTask with a ``reduce()``);
- ``map_cols``    — elementwise map producing new row-sharded columns
  (MRTask with NewChunk outputs → outputFrame).

Most algorithm code does NOT need these: plain jnp ops under ``jit`` on
sharded arrays auto-partition via GSPMD. The combinator exists for cases
where the collective placement should be explicit (histograms, Gram
accumulation) and as the parity point with the reference's one-primitive
compute model.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import DATA_AXIS, current_mesh

_REDUCERS = {
    "sum": jax.lax.psum,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


def map_reduce(map_fn, arrays, reduce_op="sum", mesh=None, donate=False):
    """Run ``map_fn`` over each data shard of ``arrays`` (a pytree of arrays
    sharded along their leading axis) and reduce the per-shard results with
    a collective. Result is replicated across devices.

    ``map_fn(shard_pytree) -> partial_pytree`` must return per-shard partial
    aggregates (e.g. a local histogram, a local (Gram, gradient) pair).
    """
    mesh = mesh or current_mesh()
    reducer = _REDUCERS[reduce_op] if isinstance(reduce_op, str) else reduce_op

    def wrapped(shards):
        out = map_fn(shards)
        return jax.tree.map(lambda x: reducer(x, DATA_AXIS), out)

    f = jax.shard_map(
        wrapped,
        mesh=mesh,
        in_specs=jax.tree.map(lambda _: P(DATA_AXIS), arrays),
        out_specs=P(),
    )
    return jax.jit(f, donate_argnums=(0,) if donate else ())(arrays)


def map_cols(map_fn, arrays, out_specs=None, mesh=None):
    """Elementwise map over data shards producing new row-sharded outputs —
    the NewChunk/outputFrame analog (water/MRTask.java:257-299 map overloads
    writing NewChunks)."""
    mesh = mesh or current_mesh()
    f = jax.shard_map(
        map_fn,
        mesh=mesh,
        in_specs=jax.tree.map(lambda _: P(DATA_AXIS), arrays),
        out_specs=out_specs if out_specs is not None else P(DATA_AXIS),
    )
    return jax.jit(f)(arrays)
