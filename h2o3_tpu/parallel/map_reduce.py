"""The compute combinator — TPU replacement for MRTask.

The reference expresses *all* distributed compute as
``new MRTask(){ map(Chunk); reduce(T) }.doAll(frame)`` — a binary tree
fan-out over nodes then ForkJoin threads, with pairwise reduction back up
(water/MRTask.java:65, :695, :871-926). Here the same contract is one
``shard_map``: ``map_fn`` runs per data-shard (the "chunk"), and the
reduction is an XLA collective over the ICI mesh axis instead of a
serialize-and-merge tree.

Two shapes, mirroring MRTask's two uses:
- ``map_reduce``  — map + associative reduce to a replicated result
  (MRTask with a ``reduce()``);
- ``map_cols``    — elementwise map producing new row-sharded columns
  (MRTask with NewChunk outputs → outputFrame).

Most algorithm code does NOT need these: plain jnp ops under ``jit`` on
sharded arrays auto-partition via GSPMD. The combinator exists for cases
where the collective placement should be explicit (histograms, Gram
accumulation) and as the parity point with the reference's one-primitive
compute model.
"""
from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import DATA_AXIS, current_mesh

_REDUCERS = {
    "sum": jax.lax.psum,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


@lru_cache(maxsize=32)
def _compiled_map_reduce(map_fn, mesh, reduce_op, treedef, donate):
    """Cache the shard_mapped+jitted combinator per (fn, mesh, structure).

    Building a fresh ``jax.jit(jax.shard_map(...))`` on every call (the
    old behavior) defeated jit's C++ fast path AND the persistent
    compilation cache's in-memory layer: each invocation re-traced the
    map_fn even at identical shapes — any repeat caller paid a retrace
    per call. (Algorithm code mostly uses GSPMD-auto-partitioned jnp
    directly; this combinator is the explicit-collective surface, so
    the cache mainly serves external/driver callers.)

    Only NAMED callables reach this cache (see ``_cacheable``): a lambda
    rebuilt per call could never hit on identity, and caching it would
    pin its closure until eviction — those build uncached, exactly the
    old cost. Pass a module-level function for the caching win."""
    reducer = _REDUCERS[reduce_op] if isinstance(reduce_op, str) else reduce_op

    def wrapped(shards):
        out = map_fn(shards)
        return jax.tree.map(lambda x: reducer(x, DATA_AXIS), out)

    f = jax.shard_map(
        wrapped,
        mesh=mesh,
        in_specs=jax.tree.unflatten(treedef,
                                    [P(DATA_AXIS)] * treedef.num_leaves),
        out_specs=P(),
    )
    return jax.jit(f, donate_argnums=(0,) if donate else ())


def _cacheable(*keys) -> bool:
    """True when every cache-key part is hashable AND every callable is
    a plain MODULE-LEVEL function. Identity-keyed lambdas, nested defs,
    bound methods, and per-call partials never hit the cache but would
    pin their closures until LRU eviction — they build uncached."""
    import types
    for k in keys:
        if callable(k):
            if not isinstance(k, types.FunctionType):
                return False
            if k.__name__ == "<lambda>" or "<locals>" in k.__qualname__:
                return False
        try:
            hash(k)
        except TypeError:
            return False
    return True


def map_reduce(map_fn, arrays, reduce_op="sum", mesh=None, donate=False):
    """Run ``map_fn`` over each data shard of ``arrays`` (a pytree of arrays
    sharded along their leading axis) and reduce the per-shard results with
    a collective. Result is replicated across devices.

    ``map_fn(shard_pytree) -> partial_pytree`` must return per-shard partial
    aggregates (e.g. a local histogram, a local (Gram, gradient) pair).
    Named ``map_fn``/``reduce_op`` callables hit the compiled-step cache;
    lambdas and unhashables build uncached (the pre-cache behavior)."""
    mesh = mesh or current_mesh()
    treedef = jax.tree.structure(arrays)
    if _cacheable(map_fn, reduce_op):
        f = _compiled_map_reduce(map_fn, mesh, reduce_op, treedef,
                                 bool(donate))
    else:
        f = _compiled_map_reduce.__wrapped__(map_fn, mesh, reduce_op,
                                             treedef, bool(donate))
    return f(arrays)


@lru_cache(maxsize=32)
def _compiled_map_cols(map_fn, mesh, out_specs, treedef):
    f = jax.shard_map(
        map_fn,
        mesh=mesh,
        in_specs=jax.tree.unflatten(treedef,
                                    [P(DATA_AXIS)] * treedef.num_leaves),
        out_specs=out_specs,
    )
    return jax.jit(f)


def map_cols(map_fn, arrays, out_specs=None, mesh=None):
    """Elementwise map over data shards producing new row-sharded outputs —
    the NewChunk/outputFrame analog (water/MRTask.java:257-299 map overloads
    writing NewChunks). Named map_fns with hashable out_specs hit the
    compiled-step cache; anything else builds uncached as before."""
    mesh = mesh or current_mesh()
    treedef = jax.tree.structure(arrays)
    specs = out_specs if out_specs is not None else P(DATA_AXIS)
    if _cacheable(map_fn, specs):
        f = _compiled_map_cols(map_fn, mesh, specs, treedef)
    else:
        f = _compiled_map_cols.__wrapped__(map_fn, mesh, specs, treedef)
    return f(arrays)
