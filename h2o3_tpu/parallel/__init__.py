from h2o3_tpu.parallel.mesh import (
    current_mesh,
    data_sharding,
    make_mesh,
    replicated_sharding,
    set_mesh,
)
from h2o3_tpu.parallel.map_reduce import map_reduce, map_cols

__all__ = [
    "current_mesh",
    "data_sharding",
    "make_mesh",
    "replicated_sharding",
    "set_mesh",
    "map_reduce",
    "map_cols",
]
