"""On-device distributed sort / merge primitives.

Reference: the MSB radix sort-merge stack water/rapids/RadixOrder.java:20
(per-node MSB histogram → SplitByMSBLocal shuffle → per-MSB sorts),
water/rapids/Merge.java:27 + BinaryMerge.java (sorted-run joins).

TPU re-design (SURVEY §2.5 'distributed shuffle'): keys are mapped to
ORDER-PRESERVING unsigned bit patterns (IEEE-754 total-order trick), the
256-way MSB partition of the reference becomes a P-way partition over
the mesh 'data' axis chosen from a GLOBAL psum'd histogram of the top
radix byte, rows move with ONE jax.lax.all_to_all over ICI, and each
shard finishes with a local on-device sort. Multi-key orders compose by
iterated stable argsorts (minor → major), the jnp analog of np.lexsort.

Static-shape contract: every (src → dst) exchange lane is padded to the
full shard length (pads carry the reserved PAD pattern, above every real
key incl. NaN), so each shard's result is its sorted run followed by
pads; shard runs are globally ordered. Variable-length compaction
happens at the host boundary — the same place the reference materializes
its sorted frame (Merge.java result assembly).
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu import telemetry
from h2o3_tpu.parallel.mesh import DATA_AXIS, current_mesh, n_data_shards

_PAD = jnp.uint32(0xFFFFFFFF)       # exchange padding: sorts after all
_NAN = jnp.uint32(0xFFFFFFFE)       # NaN keys: after all reals, before PAD


def sortable_bits(x: jnp.ndarray) -> jnp.ndarray:
    """Map f32 values to uint32 whose unsigned order matches the float
    total order (sign-flip trick): positives get the sign bit set,
    negatives get all bits flipped; NaN sorts LAST (the reference sorts
    NAs last — Merge.java NA handling)."""
    b = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    flipped = jnp.where(b >> 31 == 0, b | jnp.uint32(0x80000000), ~b)
    return jnp.where(jnp.isnan(x), _NAN, flipped)


def bits_to_float(b: jnp.ndarray) -> jnp.ndarray:
    pos = (b & jnp.uint32(0x80000000)) != 0
    restored = jnp.where(pos, b & jnp.uint32(0x7FFFFFFF), ~b)
    vals = jax.lax.bitcast_convert_type(restored.astype(jnp.uint32),
                                        jnp.float32)
    return jnp.where((b == _NAN) | (b == _PAD), jnp.nan, vals)


def lexsort_device(keys: Sequence[jnp.ndarray],
                   ascending: Optional[Sequence[int]] = None) -> jnp.ndarray:
    """Device multi-key argsort: keys[0] is the PRIMARY key (sort_frame
    column order). Stable argsorts iterate minor → major so ties keep
    the prior order — the jnp analog of np.lexsort."""
    n = keys[0].shape[0]
    asc = list(ascending) if ascending is not None else [1] * len(keys)
    order = jnp.arange(n)
    for k, a in zip(reversed(list(keys)), reversed(asc)):
        kb = sortable_bits(jnp.asarray(k))
        if not a:
            # descending, NAs still last: invert finite order only
            kb = jnp.where(kb >= _NAN, kb, ~kb)
        order = order[jnp.argsort(kb[order], stable=True)]
    return order


# ---------------- distributed radix exchange ---------------------------

def _exchange_sorted(xs, payload, P: int, per: int):
    """Shard body: globally partition by key and locally sort.

    Returns (keys [P*per], payload [P*per] or None) — the shard's sorted
    run with PAD tails. ``payload`` rides the same exchange (row ids for
    argsort-style use)."""
    bits = sortable_bits(xs)
    msb = (bits >> 24).astype(jnp.int32)
    hist = jnp.zeros(256, jnp.int32).at[msb].add(1)
    hist = jax.lax.psum(hist, DATA_AXIS)             # global MSB histogram
    csum = jnp.cumsum(hist)
    total = csum[-1]
    # shard i owns MSB values (split[i-1], split[i]]: chosen so row
    # counts balance (RadixOrder.java MSB bucket balancing)
    targets = (jnp.arange(1, P) * total) // P
    split_msb = jnp.searchsorted(csum, targets, side="left")
    dst = jnp.searchsorted(split_msb, msb, side="left").astype(jnp.int32)
    dst = jnp.clip(dst, 0, P - 1)
    order = jnp.argsort(dst, stable=True)
    bits_o = bits[order]
    dst_o = dst[order]
    start = jnp.searchsorted(dst_o, jnp.arange(P), side="left")
    local_pos = jnp.arange(bits_o.shape[0]) - start[dst_o]
    send = jnp.full((P, per), _PAD)
    send = send.at[dst_o, local_pos].set(bits_o)
    recv = jax.lax.all_to_all(send, DATA_AXIS, split_axis=0,
                              concat_axis=0, tiled=False).reshape(-1)
    if payload is None:
        return jnp.sort(recv), None
    pay_o = payload[order]
    spay = jnp.full((P, per), jnp.int32(-1))
    spay = spay.at[dst_o, local_pos].set(pay_o)
    rpay = jax.lax.all_to_all(spay, DATA_AXIS, split_axis=0,
                              concat_axis=0, tiled=False).reshape(-1)
    so = jnp.argsort(recv, stable=True)
    return recv[so], rpay[so]


def distributed_sort(x: jnp.ndarray, mesh=None) -> np.ndarray:
    """Globally sort a (row-sharded) f32 array: ICI all_to_all radix
    exchange + per-shard device sorts; host compacts the variable-length
    shard runs. NaNs sort last."""
    mesh = mesh or current_mesh()
    P = n_data_shards(mesh)
    n = x.shape[0]
    if P == 1 or n % P != 0:
        return np.asarray(telemetry.device_get(jnp.sort(jnp.asarray(x))))
    per = n // P
    from jax.sharding import PartitionSpec as Ps

    fn = jax.jit(jax.shard_map(
        partial(_exchange_sorted, payload=None, P=P, per=per),
        mesh=mesh, in_specs=Ps(DATA_AXIS),
        out_specs=(Ps(DATA_AXIS), None), check_vma=False))
    keys, _ = fn(jnp.asarray(x))
    host = np.asarray(telemetry.device_get(keys)).reshape(P, P * per)
    parts = [h[h != 0xFFFFFFFF] for h in host]       # drop PAD, keep order
    bits = np.concatenate(parts)
    return np.asarray(telemetry.device_get(bits_to_float(jnp.asarray(bits))))


def distributed_argsort(x: jnp.ndarray, mesh=None) -> np.ndarray:
    """Global ORDER indices (stable within equal keys per shard run) via
    the same exchange, with row ids riding as payload — what sort_frame
    needs to gather full rows (Merge.java moves whole rows; moving ids
    and gathering once is the single-controller shortcut)."""
    mesh = mesh or current_mesh()
    P = n_data_shards(mesh)
    n = x.shape[0]
    if P == 1 or n % P != 0:
        kb = sortable_bits(jnp.asarray(x))
        return np.asarray(telemetry.device_get(jnp.argsort(kb, stable=True)))
    per = n // P
    from jax.sharding import PartitionSpec as Ps
    ids = jnp.arange(n, dtype=jnp.int32)

    def body(xs, ids_s):
        shard = jax.lax.axis_index(DATA_AXIS)
        k, p = _exchange_sorted(xs, ids_s, P, per)
        return k, p

    fn = jax.jit(jax.shard_map(body, mesh=mesh,
                               in_specs=(Ps(DATA_AXIS), Ps(DATA_AXIS)),
                               out_specs=(Ps(DATA_AXIS), Ps(DATA_AXIS)),
                               check_vma=False))
    keys, pay = fn(jnp.asarray(x), ids)
    kh = np.asarray(telemetry.device_get(keys)).reshape(P, P * per)
    ph = np.asarray(telemetry.device_get(pay)).reshape(P, P * per)
    parts = [p[k != 0xFFFFFFFF] for k, p in zip(kh, ph)]
    return np.concatenate(parts).astype(np.int64)


# ---------------- device merge (sorted-run join) -----------------------

def join_indices_unique(left_keys, right_keys, nright: int) -> np.ndarray:
    """Join row indices for UNIQUE right keys (the common FK join):
    sort right once, searchsorted the left probes — both on device
    (BinaryMerge.java's sorted-run probe without the row movement).
    Returns ri [nl] int32, -1 where unmatched."""
    rb = sortable_bits(jnp.asarray(right_keys))
    lb = sortable_bits(jnp.asarray(left_keys))

    @jax.jit
    def probe(rb, lb):
        order = jnp.argsort(rb)
        rb_s = rb[order]
        pos = jnp.searchsorted(rb_s, lb)
        pos_c = jnp.clip(pos, 0, nright - 1)
        hit = (rb_s[pos_c] == lb) & (lb != _NAN)
        return jnp.where(hit, order[pos_c].astype(jnp.int32), -1)

    return np.asarray(telemetry.device_get(probe(rb, lb)))
