"""Per-shard step timing: compute vs collective-wait attribution.

The sharded GBM/DRF chunk step is bulk-synchronous: every data shard
builds its local histograms, the per-level ``psum`` synchronizes, and
shards that finish their local compute early WAIT at the collective for
the slowest one. When the multichip bench's scaling verdict fails, the
raw rows/s number cannot say whether the loss is collective latency or
a straggling shard — this module makes that attributable.

Single-controller JAX gives no per-shard timer inside the compiled
program, so the split is HOST-OBSERVED: after a chunk is dispatched,
each addressable output shard's readiness is watched independently and
per-shard completion times are recorded relative to the dispatch.
Interpretation (standard external-observer attribution):

- a shard's step time approximates its compute + its share of the
  collectives;
- ``wait(shard) = slowest - shard`` is a lower bound on the time that
  shard idled at the final barrier for the straggler — in a perfectly
  balanced step every wait is ~0;
- ``straggler_ratio = slowest / median`` is the headline imbalance
  number (1.0 = balanced; 2.0 = the slowest shard doubles the step).

Metrics (all labeled ``{algo=...}``):

- ``h2o3_shard_step_ms``      histogram — per-shard completion times
- ``h2o3_collective_wait_ms`` histogram — per-shard barrier waits
- ``h2o3_straggler_ratio``    gauge     — slowest/median, last observed

The observation itself blocks the host on the chunk's outputs, so
callers place it where the host would block anyway (the pipelined
loop's COMMIT point, one chunk behind the dispatch frontier) and gate
it on ``n_data > 1``; with ``H2O3_TELEMETRY=0`` it is a checked no-op
(one attribute load — the sharded train path stays overhead-free).
Shards already done at the FIRST poll sweep are CENSORED — their
elapsed times measure whatever host work delayed the observation (a
cold next-bucket compile, a checkpoint commit), not their step — and
are excluded from the metrics; the slowest shards are by construction
live, so the ratio/waits over live completions stay honest lower
bounds. A chunk with fewer than two live shards is STALE: counted
(``chunks_stale``) but never recorded.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from h2o3_tpu.telemetry.registry import on_reset

# is_ready() poll cadence: start fine (preserves completion ORDER for
# sub-ms steps) and back off geometrically toward 1ms so a multi-second
# chunk costs ~thousands of polls, not a sustained 20kHz host spin that
# would steal cycles from the very compute being measured
_POLL_SLEEP_MIN_S = 2e-5
_POLL_SLEEP_MAX_S = 1e-3

# hot-path handle cache (slow path: resolve once per algo, hold the
# handles — registered with registry.on_reset like the span/collector
# caches so test isolation cannot leave stale handles)
_ALGO_HANDLES: Dict[str, tuple] = {}
on_reset(_ALGO_HANDLES.clear)


def _algo_handles(algo: str) -> tuple:
    h = _ALGO_HANDLES.get(algo)
    if h is None:
        from h2o3_tpu import telemetry
        lab = {"algo": algo}
        h = _ALGO_HANDLES[algo] = (
            telemetry.histogram(
                "h2o3_shard_step_ms", lab,
                help="per-shard chunk completion times (ms from "
                     "dispatch)",
                bounds=(0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0,
                        1000.0, 5000.0, 30_000.0)),
            telemetry.histogram(
                "h2o3_collective_wait_ms", lab,
                help="per-shard barrier wait for the slowest shard (ms)",
                bounds=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0,
                        100.0, 500.0, 5000.0)),
            telemetry.gauge(
                "h2o3_straggler_ratio", lab,
                help="slowest/median shard step time, last observed "
                     "chunk"),
        )
    return h


def _first_array(out):
    """First jax.Array leaf of a pytree (the chunk step returns dicts
    of tree arrays / margin arrays — any leaf shares the program's
    completion profile per device)."""
    import jax
    for leaf in jax.tree_util.tree_leaves(out):
        if isinstance(leaf, jax.Array):
            return leaf
    return None


def _shard_ready_times(shards, t0: float):
    """Per-shard seconds-from-dispatch until each shard's buffer was
    ready, plus the set of CENSORED shard indices. Prefers non-blocking
    ``is_ready()`` polling (preserves the true completion ORDER); falls
    back to sequential blocking (exact for the slowest shard,
    order-biased for the rest).

    A shard already ready on the first poll sweep is censored: it
    completed at some unknown point while the host was busy between
    dispatch and observation (e.g. a cold compile of the next chunk
    bucket), so its elapsed time measures that host work, not its
    step."""
    import jax
    datas = [s.data for s in shards]
    out: List[Optional[float]] = [None] * len(datas)
    censored: set = set()
    pollable = all(hasattr(d, "is_ready") for d in datas)
    if pollable:
        remaining = set(range(len(datas)))
        sleep_s = _POLL_SLEEP_MIN_S
        first_sweep = True
        try:
            while remaining:
                now = time.perf_counter() - t0
                progressed = False
                for i in list(remaining):
                    if datas[i].is_ready():
                        out[i] = now
                        remaining.discard(i)
                        progressed = True
                        if first_sweep:
                            censored.add(i)
                first_sweep = False
                if remaining:
                    time.sleep(sleep_s)
                    # adaptive backoff: any completion re-arms the fine
                    # cadence (shards often finish in a burst)
                    sleep_s = _POLL_SLEEP_MIN_S if progressed else \
                        min(sleep_s * 2, _POLL_SLEEP_MAX_S)
        except Exception:
            pollable = False
    if not pollable:
        censored = set()
        for i, d in enumerate(datas):
            if out[i] is None:
                jax.block_until_ready(d)  # h2o3-lint: allow[transfer-seam] observation fallback when shards expose no is_ready(): the block IS the measurement
                out[i] = time.perf_counter() - t0
    return [float(t) for t in out], censored


def observe_sharded_step(out, t_dispatch: float, *, algo: str = "gbm"
                         ) -> Optional[Dict[str, float]]:
    """Record per-shard step/wait metrics for one dispatched chunk whose
    output (array or pytree) is ``out``; ``t_dispatch`` is the
    ``time.perf_counter()`` reading taken right after dispatch returned.
    Returns the summary dict (also accumulated by the train drivers into
    ``model.output['spmd']``) or None when there is nothing to observe
    (telemetry off, single shard, host fallback arrays)."""
    from h2o3_tpu import telemetry
    if not telemetry.enabled():
        return None
    try:
        return _observe(out, t_dispatch, algo)
    except Exception as e:
        # observation must NEVER sink the run: an async device error
        # surfacing at is_ready()/block_until_ready here belongs to the
        # train loop's own fetch/commit point (where PR-6's
        # checkpoint-on-failure handling lives), not to telemetry —
        # swallow, let the real failure surface there
        import warnings
        warnings.warn(f"shard observation skipped: {e!r}")
        return None


def _observe(out, t_dispatch: float, algo: str
             ) -> Optional[Dict[str, float]]:
    arr = _first_array(out)
    if arr is None:
        return None
    try:
        shards = list(arr.addressable_shards)
    except Exception:
        return None
    if len(shards) <= 1 or len({s.device for s in shards}) <= 1:
        return None
    times, censored = _shard_ready_times(shards, t_dispatch)
    n_total = len(times)
    live = [t for i, t in enumerate(times) if i not in censored]
    if len(live) < 2:
        # (nearly) every shard was done before the first poll: the host
        # work between dispatch and observation (cold compile of the
        # next chunk bucket, a checkpoint commit) ate the window.
        # Censored elapsed times measure that host work, and fewer than
        # two live completions leave no order to compare — recording
        # would write host time into the step histogram and a
        # fabricated ~1.0 into the straggler gauge, exactly the masking
        # this module exists to prevent. Count it, record nothing.
        return {"n_shards": n_total, "stale": True}
    # a partially censored step still attributes honestly over the LIVE
    # shards: the slowest shards are by construction live (they were
    # still running when polling started), so slowest is genuine and
    # the ratio/waits over live completions are lower bounds on the
    # true imbalance — only the already-finished fast tail is unknown
    times_ms = sorted(t * 1e3 for t in live)
    slowest = times_ms[-1]
    n = len(times_ms)
    median = (times_ms[n // 2] if n % 2 else
              0.5 * (times_ms[n // 2 - 1] + times_ms[n // 2]))
    ratio = slowest / median if median > 0 else 1.0
    waits = [slowest - t for t in times_ms]
    step_h, wait_h, ratio_g = _algo_handles(algo)
    for t in times_ms:
        step_h.observe(t)
    for w in waits:
        wait_h.observe(w)
    ratio_g.set(ratio)
    mean_wait = sum(waits) / n
    return {
        "n_shards": n_total,
        "shards_censored": len(censored),
        "slowest_ms": round(slowest, 3),
        "median_ms": round(median, 3),
        "straggler_ratio": round(ratio, 4),
        "collective_wait_ms": round(mean_wait, 3),
        # share of the step the average shard spent waiting at the
        # barrier — the number the multichip bench surfaces next to a
        # failed scaling verdict
        "collective_wait_share": round(mean_wait / slowest, 4)
        if slowest > 0 else 0.0,
    }


def merge_observations(obs: List[Dict[str, float]]
                       ) -> Optional[Dict[str, float]]:
    """Fold per-chunk observations into one per-train summary (what
    lands in ``model.output['spmd']``): waits/steps average over
    chunks, the straggler ratio reports the worst chunk. Stale
    observations (step finished before the host could watch it) carry
    no timing signal — they are counted in ``chunks_stale`` but
    excluded from every aggregate; a train where EVERY chunk was stale
    reports only the counts."""
    obs = [o for o in obs if o]
    stale = [o for o in obs if o.get("stale")]
    obs = [o for o in obs if not o.get("stale")]
    if not obs:
        if not stale:
            return None
        return {"chunks_observed": 0, "chunks_stale": len(stale),
                "n_shards": stale[0]["n_shards"]}
    n = len(obs)
    return {
        "chunks_observed": n,
        "chunks_stale": len(stale),
        "n_shards": obs[0]["n_shards"],
        "shards_censored": sum(o.get("shards_censored", 0)
                               for o in obs),
        "straggler_ratio": round(max(o["straggler_ratio"]
                                     for o in obs), 4),
        "straggler_ratio_mean": round(
            sum(o["straggler_ratio"] for o in obs) / n, 4),
        "collective_wait_ms": round(
            sum(o["collective_wait_ms"] for o in obs) / n, 3),
        "collective_wait_share": round(
            sum(o["collective_wait_share"] for o in obs) / n, 4),
        "slowest_ms": round(max(o["slowest_ms"] for o in obs), 3),
    }
