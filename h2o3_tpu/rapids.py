"""Rapids — the frame-algebra expression engine h2o-py/R emit.

Reference: water/rapids/Rapids.java:29 (Lisp-ish AST parser),
water/rapids/Env.java + Session.java (temp-frame lifetimes), ~150 prims
under water/rapids/ast/prims/ (mungers, operators, reducers, math,
matrix, timeseries, …), distributed sort/merge via MSB radix exchange
(water/rapids/Merge.java:27, RadixOrder.java:20).

TPU re-design: the interpreter is host-side (tiny ASTs), but the frame
math runs on device — elementwise ops map over sharded column arrays,
reducers are jitted reductions, group-by aggregates are segment-sums on
device after a host factorization of the (host-resident) group keys.
Merge/sort run host-side via numpy for now (the multi-chip story is an
all_to_all radix exchange, SURVEY §2.5 — single-controller scale does
not need it below ~100M rows).

Grammar (matching h2o-py expr.py _arg_to_expr): ``(op arg…)``, lists
``[v1 v2 …]``, slices ``[start:count]`` / ``[start:count:step]``,
python-repr strings, numbers (NaN for open slice ends), bare atoms as
frame/temp keys, ``(tmp= id expr)`` assignment.
"""
from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu import dkv
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import T_ENUM, T_INT, T_REAL, T_STR, Vec


def _fetch(x):
    """Counted device fetch: Rapids' ad-hoc device_get calls land in the
    d2h byte counters as pipeline="rapids" (ROADMAP gap: transfer
    accounting beyond the frame-layer choke points)."""
    from h2o3_tpu import telemetry
    return telemetry.device_get(x, pipeline="rapids")

# ---------------- tokenizer / parser -----------------------------------

_TOKEN = re.compile(r"""
    [\s,]*(?:
        (?P<lparen>\()
      | (?P<rparen>\))
      | (?P<lbrack>\[)
      | (?P<rbrack>\])
      | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
      | (?P<atom>[^\s,()\[\]'"]+)
    )""", re.VERBOSE)
# commas are separators (python-repr lists like ['a', 'b'] arrive from
# the client's Assembly step serialization; bare Rapids never needs a
# literal comma token)


class Slice:
    def __init__(self, start: int, count: float, step: int = 1):
        self.start = int(start)
        self.count = count          # may be NaN = open-ended
        self.step = int(step)

    def resolve(self, n: int) -> np.ndarray:
        if math.isnan(self.count):
            # open-ended: count is in ELEMENTS, not rows spanned
            count = -(-(n - self.start) // self.step)
        else:
            count = int(self.count)
        return np.arange(self.start, self.start + count * self.step,
                         self.step)


def _parse(tokens: List, pos: int) -> Tuple[Any, int]:
    tok = tokens[pos]
    kind, val = tok
    if kind == "lparen":
        items = []
        pos += 1
        while tokens[pos][0] != "rparen":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("call", items), pos + 1
    if kind == "lbrack":
        items = []
        pos += 1
        while tokens[pos][0] != "rbrack":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("list", items), pos + 1
    if kind == "string":
        body = val[1:-1]
        return ("str", bytes(body, "utf-8").decode("unicode_escape")), pos + 1
    # atom: number, slice, or identifier
    if re.fullmatch(r"-?\d+:\S+", val):
        parts = val.split(":")
        start = int(parts[0])
        count = float("nan") if parts[1].lower() == "nan" else float(parts[1])
        step = int(parts[2]) if len(parts) > 2 else 1
        return ("slice", Slice(start, count, step)), pos + 1
    try:
        return ("num", float(val)), pos + 1
    except ValueError:
        return ("id", val), pos + 1


def parse_rapids(ast: str):
    tokens = []
    i = 0
    while i < len(ast):
        m = _TOKEN.match(ast, i)
        if not m:
            break
        i = m.end()
        for kind in ("lparen", "rparen", "lbrack", "rbrack", "string", "atom"):
            if m.group(kind) is not None:
                tokens.append((kind, m.group(kind)))
                break
    node, _ = _parse(tokens, 0)
    return node


# ---------------- evaluation -------------------------------------------

class Env:
    def __init__(self, session: Optional[str] = None):
        self.session = session

    def lookup(self, name: str):
        ent = dkv.get_opt(name)
        if ent and ent[0] == "frame":
            return ent[1]
        return name   # plain string/col name


def _map_elementwise(op, a, b=None) -> Any:
    """Elementwise frame/scalar op on device, columnwise."""
    def dev(v: Vec):
        return v.as_float()

    if isinstance(a, Frame) and isinstance(b, Frame):
        if b.ncol == 1 and a.ncol != 1:
            cols = [op(dev(a.vec(n)), dev(b.vec(0))) for n in a.names]
            names = a.names
        elif a.ncol == 1 and b.ncol != 1:
            cols = [op(dev(a.vec(0)), dev(b.vec(n))) for n in b.names]
            names = b.names
        else:
            assert a.ncol == b.ncol, "frame op: ncol mismatch"
            cols = [op(dev(a.vec(i)), dev(b.vec(i))) for i in range(a.ncol)]
            names = a.names
    elif isinstance(a, Frame):
        cols = [op(dev(a.vec(n))) if b is None else op(dev(a.vec(n)), b)
                for n in a.names]
        names = a.names
    elif isinstance(b, Frame):
        cols = [op(a, dev(b.vec(n))) for n in b.names]
        names = b.names
    else:
        return op(a, b) if b is not None else op(a)
    nrow = (a if isinstance(a, Frame) else b).nrow
    vecs = [Vec.from_numpy(np.asarray(_fetch(c))[:nrow]
                           .astype(np.float32)) for c in cols]
    return Frame(names, vecs)


def _reduce(fn, fr: Frame, na_rm=True) -> float:
    vals = []
    for n in fr.names:
        v = fr.vec(n)
        if v.type == T_STR:
            continue
        x = v.as_float()
        ok = ~jnp.isnan(x[: fr.nrow]) if na_rm else jnp.ones(fr.nrow, bool)
        vals.append(float(_fetch(fn(x[: fr.nrow], ok))))
    return vals[0] if len(vals) == 1 else vals


_BINOPS = {
    "+": lambda a, b: a + b, "-": lambda a, b: a - b,
    "*": lambda a, b: a * b, "/": lambda a, b: a / b,
    "intDiv": lambda a, b: jnp.floor_divide(a, b),
    "%": lambda a, b: jnp.mod(a, b), "mod": lambda a, b: jnp.mod(a, b),
    "^": lambda a, b: a ** b, "pow": lambda a, b: a ** b,
    "<": lambda a, b: (a < b).astype(jnp.float32),
    "<=": lambda a, b: (a <= b).astype(jnp.float32),
    ">": lambda a, b: (a > b).astype(jnp.float32),
    ">=": lambda a, b: (a >= b).astype(jnp.float32),
    "==": lambda a, b: (a == b).astype(jnp.float32),
    "!=": lambda a, b: (a != b).astype(jnp.float32),
    "&": lambda a, b: ((a != 0) & (b != 0)).astype(jnp.float32),
    "|": lambda a, b: ((a != 0) | (b != 0)).astype(jnp.float32),
}

_UNOPS = {
    "abs": jnp.abs, "exp": jnp.exp, "log": jnp.log, "log2": jnp.log2,
    "log10": jnp.log10, "log1p": jnp.log1p, "sqrt": jnp.sqrt,
    "floor": jnp.floor, "ceiling": jnp.ceil, "trunc": jnp.trunc,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "tanh": jnp.tanh,
    "sign": jnp.sign, "not": lambda a: (a == 0).astype(jnp.float32),
    "!!": lambda a: (a == 0).astype(jnp.float32),
    "is.na": lambda a: jnp.isnan(a).astype(jnp.float32),
}


def group_by(fr: Frame, by: Sequence[Union[int, str]],
             aggs: Sequence[Tuple[str, Optional[Union[int, str]]]],
             ) -> Frame:
    """Distributed group-by (water/rapids/ast/prims/mungers AstGroup):
    host factorizes the group keys, the aggregates are device
    segment-sums (one-hot-free jax.ops.segment_sum over sorted ids)."""
    by_names = [fr.names[int(b)] if isinstance(b, (int, float)) else b
                for b in by]
    nrow = fr.nrow
    key_cols = [np.asarray(fr.vec(n).to_numpy()[:nrow]) for n in by_names]
    keys, gid = np.unique(np.stack(key_cols, 1), axis=0, return_inverse=True)
    n_groups = keys.shape[0]
    gid_dev = jnp.asarray(gid.astype(np.int32))
    out_names = list(by_names)
    out_cols: List[np.ndarray] = []
    for j, n in enumerate(by_names):
        v = fr.vec(n)
        if v.type == T_ENUM:
            out_cols.append((keys[:, j], v.domain))
        else:
            out_cols.append((keys[:, j], None))
    for agg, col in aggs:
        if agg in ("nrow", "count"):
            cnt = jax.ops.segment_sum(jnp.ones(nrow), gid_dev, n_groups)
            out_names.append("nrow")
            out_cols.append((np.asarray(_fetch(cnt)), None))
            continue
        cn = fr.names[int(col)] if isinstance(col, (int, float)) else col
        x = fr.vec(cn).as_float()[:nrow]
        ok = ~jnp.isnan(x)
        xz = jnp.where(ok, x, 0.0)
        s = jax.ops.segment_sum(xz, gid_dev, n_groups)
        c = jax.ops.segment_sum(ok.astype(jnp.float32), gid_dev, n_groups)
        if agg == "sum":
            r = s
        elif agg == "mean":
            r = s / jnp.maximum(c, 1e-30)
        elif agg in ("min", "max"):
            big = jnp.where(ok, x, jnp.inf if agg == "min" else -jnp.inf)
            r = (jax.ops.segment_min(big, gid_dev, n_groups) if agg == "min"
                 else jax.ops.segment_max(big, gid_dev, n_groups))
        elif agg in ("sdev", "var"):
            s2 = jax.ops.segment_sum(xz * xz, gid_dev, n_groups)
            mean = s / jnp.maximum(c, 1e-30)
            var = jnp.maximum(s2 / jnp.maximum(c, 1e-30) - mean * mean, 0.0)
            var = var * c / jnp.maximum(c - 1, 1e-30)   # sample variance
            r = jnp.sqrt(var) if agg == "sdev" else var
        elif agg == "sumSquares":
            r = jax.ops.segment_sum(xz * xz, gid_dev, n_groups)
        else:
            raise ValueError(f"unsupported group-by aggregate '{agg}'")
        out_names.append(f"{agg}_{cn}")
        out_cols.append((np.asarray(_fetch(r)), None))
    vecs = []
    for (vals, domain) in out_cols:
        if domain is not None:
            vecs.append(Vec.from_numpy(vals.astype(np.int32), vtype=T_ENUM,
                                       domain=domain))
        else:
            vecs.append(Vec.from_numpy(np.asarray(vals, dtype=np.float32)))
    return Frame(out_names, vecs)


def merge(left: Frame, right: Frame, by_left: Sequence[str],
          by_right: Sequence[str], all_x: bool = False,
          all_y: bool = False) -> Frame:
    """Join (water/rapids/Merge.java semantics: radix hash join). Inner /
    left / right joins on equal keys; enum keys compare by LABEL."""
    nl, nr = left.nrow, right.nrow

    def key_col(fr, n):
        v = fr.vec(n)
        if v.type == T_ENUM:
            return np.asarray(v.to_strings()[: fr.nrow], dtype=object)
        return np.asarray(v.to_numpy()[: fr.nrow])

    # DEVICE fast path (BinaryMerge.java sorted-run probe): single
    # numeric key, unique right keys, no right-outer — sort + searchsorted
    # on device, only the index gather comes back to host
    if (len(by_left) == 1 and not all_y
            and left.vec(by_left[0]).type in (T_INT, T_REAL)
            and right.vec(by_right[0]).type in (T_INT, T_REAL)
            and getattr(left.vec(by_left[0]), "host_data", None) is None
            and getattr(right.vec(by_right[0]), "host_data", None) is None):
        rvals = np.asarray(right.vec(by_right[0]).to_numpy()[:nr])
        if len(np.unique(rvals[np.isfinite(rvals)])) == np.isfinite(rvals).sum():
            from h2o3_tpu.parallel.sortmerge import join_indices_unique
            ri_dev = join_indices_unique(
                left.vec(by_left[0]).as_float()[:nl],
                right.vec(by_right[0]).as_float()[:nr], nr)
            if all_x:
                li_a = np.arange(nl, dtype=np.int64)
                ri_a = ri_dev.astype(np.int64)
            else:
                keep = ri_dev >= 0
                li_a = np.nonzero(keep)[0].astype(np.int64)
                ri_a = ri_dev[keep].astype(np.int64)
            names = list(left.names) + [n for n in right.names
                                        if n not in by_right]
            vecs = [_take_vec(left.vec(n), li_a, left.nrow)
                    for n in left.names]
            vecs += [_take_vec(right.vec(n), ri_a, right.nrow)
                     for n in right.names if n not in by_right]
            return Frame(names, vecs)

    lk = [key_col(left, n) for n in by_left]
    rk = [key_col(right, n) for n in by_right]
    lkey = list(zip(*lk)) if lk else [()] * nl
    rkey = list(zip(*rk)) if rk else [()] * nr
    rindex: Dict[Any, List[int]] = {}
    for i, k in enumerate(rkey):
        rindex.setdefault(k, []).append(i)
    li: List[int] = []
    ri: List[int] = []
    for i, k in enumerate(lkey):
        hits = rindex.get(k)
        if hits:
            for j in hits:
                li.append(i)
                ri.append(j)
        elif all_x:
            li.append(i)
            ri.append(-1)
    if all_y:
        matched = set(ri)
        for j in range(nr):
            if j not in matched:
                li.append(-1)
                ri.append(j)
    li_a = np.asarray(li, dtype=np.int64)
    ri_a = np.asarray(ri, dtype=np.int64)
    names = list(left.names) + [n for n in right.names if n not in by_right]
    vecs = []
    for n in left.names:
        tv = _take_vec(left.vec(n), li_a, left.nrow)
        if n in by_left and all_y:
            # right-only rows (li=-1) take their key values from the
            # RIGHT frame, not NA (the reference's outer-merge keys)
            rn = by_right[by_left.index(n)]
            rv = _take_vec(right.vec(rn), ri_a, right.nrow)
            tv = _coalesce_vec(tv, rv, li_a < 0)
        vecs.append(tv)
    for n in right.names:
        if n in by_right:
            continue
        vecs.append(_take_vec(right.vec(n), ri_a, right.nrow))
    return Frame(names, vecs)


def _coalesce_vec(primary: Vec, fallback: Vec, use_fallback: np.ndarray) -> Vec:
    label_like = (T_ENUM, T_STR)
    if primary.type in label_like or fallback.type in label_like:
        a = np.asarray(primary.to_strings()[: primary.nrow], dtype=object)
        b = np.asarray(fallback.to_strings()[: fallback.nrow], dtype=object)
        return Vec.from_numpy(np.where(use_fallback, b, a))
    a = np.asarray(primary.to_numpy()[: primary.nrow], dtype=np.float64)
    b = np.asarray(fallback.to_numpy()[: fallback.nrow], dtype=np.float64)
    return Vec.from_numpy(np.where(use_fallback, b, a))


def _take_vec(v: Vec, idx: np.ndarray, nrow: int) -> Vec:
    missing = idx < 0
    safe = np.where(missing, 0, idx)
    if v.type == T_ENUM:
        codes = np.asarray(v.to_numpy()[:nrow]).astype(np.float64)
        out = codes[safe]
        out[missing] = -1
        out[~np.isfinite(out)] = -1
        return Vec.from_numpy(out.astype(np.int32), vtype=T_ENUM,
                              domain=v.domain)
    if v.type == T_STR:
        vals = np.asarray(v.to_strings()[:nrow], dtype=object)
        out = vals[safe]
        out[missing] = None
        return Vec.from_numpy(out)
    # float64 all the way: Vec.from_numpy keeps exact host copies for
    # wide ints and re-detects the type; float32 would corrupt timestamps
    # and >2^24 IDs
    from h2o3_tpu.frame.vec import T_TIME
    if v.type == T_TIME and getattr(v, "host_data", None) is not None:
        raw = np.asarray(v.host_data[:nrow], dtype=np.int64)
        out = raw[safe]
        out[missing] = Vec.TIME_NA
        return Vec.from_numpy(out, vtype=T_TIME)
    vals = np.asarray(v.to_numpy()[:nrow], dtype=np.float64)
    out = vals[safe]
    out[missing] = np.nan
    return Vec.from_numpy(out)


def sort_frame(fr: Frame, cols: Sequence[Union[int, str]],
               ascending: Optional[Sequence[int]] = None) -> Frame:
    """Sort (water/rapids/Merge.java sort → RadixOrder). Numeric keys
    sort ON DEVICE: single-key multi-shard goes through the distributed
    radix all_to_all exchange (parallel/sortmerge.py); multi-key uses
    the device lexsort. Strings fall back to host lexsort."""
    names = [fr.names[int(c)] if isinstance(c, (int, float)) else c
             for c in cols]
    nrow = fr.nrow
    asc = list(ascending) if ascending else [1] * len(names)
    numeric = all(fr.vec(n).type in (T_INT, T_REAL, "time", T_ENUM)
                  for n in names)
    # f32-exactness guard: keys wider than the f32 mantissa (big IDs,
    # epoch millis) would collide in the bit-pattern sort
    if numeric:
        for n in names:
            v = fr.vec(n)
            if getattr(v, "host_data", None) is not None:
                numeric = False
                break
    if numeric and names:
        from h2o3_tpu.parallel.sortmerge import (distributed_argsort,
                                                 lexsort_device)
        from h2o3_tpu.parallel.mesh import current_mesh, n_data_shards
        key_dev = [fr.vec(n).as_float()[:nrow] for n in names]
        if len(names) == 1 and asc[0] and n_data_shards(current_mesh()) > 1:
            order = distributed_argsort(key_dev[0])
        else:
            order = np.asarray(_fetch(
                lexsort_device(key_dev, asc)))
    else:
        keys = []
        for n, a in zip(reversed(names), reversed(asc)):
            col = np.asarray(fr.vec(n).to_numpy()[:nrow])
            keys.append(col if a else -col)
        order = np.lexsort(keys) if keys else np.arange(nrow)
    return fr.rows_by_index(order) if hasattr(fr, "rows_by_index") else \
        _take_frame(fr, order)


def _take_frame(fr: Frame, idx: np.ndarray) -> Frame:
    return Frame(list(fr.names),
                 [_take_vec(fr.vec(n), np.asarray(idx, np.int64), fr.nrow)
                  for n in fr.names])


# ---------------- interpreter ------------------------------------------

def _eval(node, env: Env):
    kind, val = node
    if kind == "num":
        return val
    if kind == "str":
        return val
    if kind == "slice":
        return val
    if kind == "id":
        if val in ("TRUE", "True"):
            return 1.0
        if val in ("FALSE", "False"):
            return 0.0
        if val in ("NA", "NaN", "nan"):
            return float("nan")
        return env.lookup(val)
    if kind == "list":
        return [_eval(c, env) for c in val]
    assert kind == "call"
    op_node = val[0]
    op = op_node[1] if op_node[0] in ("id",) else _eval(op_node, env)
    args = val[1:]
    return _apply(op, args, env)


def _sel_indices(sel, n: int, names: Optional[List[str]] = None) -> np.ndarray:
    if isinstance(sel, Slice):
        return sel.resolve(n)
    if isinstance(sel, (int, float)):
        return np.asarray([int(sel)])
    if isinstance(sel, str):
        return np.asarray([names.index(sel)])
    if isinstance(sel, list):
        if sel and isinstance(sel[0], str):
            return np.asarray([names.index(s) for s in sel])
        out = []
        for s in sel:
            out.extend(_sel_indices(s, n, names).tolist())
        return np.asarray(out, dtype=np.int64)
    raise ValueError(f"bad selector {sel!r}")


def _apply(op: str, args, env: Env):
    ev = lambda i: _eval(args[i], env)  # noqa: E731

    if op == "tmp=" or op == "assign":
        # AstTmpAssign / AstAssign: session-temp vs global assignment —
        # one keyed store here (the DKV collapses the distinction)
        name = args[0][1]
        valr = _eval(args[1], env)
        if isinstance(valr, Frame):
            dkv.put(name, "frame", valr)
        return valr
    if op == "rm":
        dkv.remove(args[0][1])
        return 1.0
    # ---- reducers / advmath (ast/prims/{reducers,advmath}) -------------
    if op in ("all", "any"):
        fr = ev(0)
        vals = [np.asarray(fr.vec(i).to_numpy()[: fr.nrow])
                for i in range(fr.ncol)]
        flat = np.concatenate(vals) if vals else np.zeros(0)
        fin = flat[np.isfinite(flat)]
        return float((fin != 0).all() if op == "all" else (fin != 0).any())
    if op == "any.na":
        fr = ev(0)
        return float(any(np.isnan(np.asarray(
            fr.vec(i).asnumeric().to_numpy()[: fr.nrow])).any()
            if fr.vec(i).type != T_STR else
            any(s is None for s in fr.vec(i).to_strings()[: fr.nrow])
            for i in range(fr.ncol)))
    if op == "naCnt":
        fr = ev(0)
        return [float(fr.vec(i).rollups().get("na_count", 0))
                for i in range(fr.ncol)]
    if op in ("sumNA", "prod.na"):
        # the NA-SKIPPING variants (AstSumNa — h2o-py emits these for
        # skipna=True; the plain sum/prod propagate NA)
        fr = ev(0)
        out = []
        for i in range(fr.ncol):
            x = np.asarray(fr.vec(i).to_numpy()[: fr.nrow], np.float64)
            out.append(float(np.nansum(x) if op == "sumNA"
                             else np.nanprod(x)))
        return out[0] if len(out) == 1 else out
    if op in ("skewness", "kurtosis", "moment"):
        fr = ev(0)
        na_rm = bool(_eval(args[1], env)) if len(args) > 1 else False
        out = []
        for i in range(fr.ncol):
            x = np.asarray(fr.vec(i).to_numpy()[: fr.nrow], np.float64)
            ok = np.isfinite(x)
            if not na_rm and not ok.all():
                out.append(float("nan"))
                continue
            v = x[ok]
            m = v.mean() if v.size else float("nan")
            s = v.std(ddof=1) if v.size > 1 else float("nan")
            k = {"skewness": 3, "kurtosis": 4, "moment": 3}[op]
            out.append(float(((v - m) ** k).mean() / (s ** k))
                       if v.size > 1 and s > 0 else float("nan"))
        return out
    if op == "entropy":
        # per-column Shannon entropy of STRING/enum values (AstEntropy
        # computes per-row character entropy for strings)
        fr = ev(0)
        out = []
        for i in range(fr.ncol):
            v = fr.vec(i)
            vals = (v.to_strings()[: fr.nrow] if v.type in (T_STR, T_ENUM)
                    else np.asarray(v.to_numpy()[: fr.nrow]).tolist())
            ent = []
            for s in vals:
                s = "" if s is None else str(s)
                if not s:
                    ent.append(float("nan"))
                    continue
                _, cnt = np.unique(list(s), return_counts=True)
                p = cnt / cnt.sum()
                ent.append(float(-(p * np.log2(p)).sum()))
            out.append(Vec.from_numpy(np.asarray(ent, np.float64)))
        return Frame(list(fr.names), out)
    if op == "quantile":
        # (quantile fr [probs] interpolation_method weights) -> frame with
        # 'Probs' + per-column quantile columns (AstQtile)
        fr = ev(0)
        probs = _eval(args[1], env)
        probs = [float(p) for p in (probs if isinstance(probs, list)
                                    else [probs])]
        names = ["Probs"]
        cols = [Vec.from_numpy(np.asarray(probs, np.float64))]
        for i in range(fr.ncol):
            v = fr.vec(i)
            if v.type not in (T_INT, T_REAL):
                continue
            qs = [float(q) for q in np.nanquantile(
                np.asarray(v.to_numpy()[: fr.nrow], np.float64), probs)]
            names.append(fr.names[i] + "Quantiles")
            cols.append(Vec.from_numpy(np.asarray(qs, np.float64)))
        return Frame(names, cols)
    if op == "sumaxis":
        # (sumaxis fr na_rm axis): frame-valued sum (AstSumAxis)
        fr = ev(0)
        na_rm = bool(_eval(args[1], env)) if len(args) > 1 else True
        axis = int(_eval(args[2], env) or 0) if len(args) > 2 else 0
        num_idx = [i for i in range(fr.ncol)
                   if fr.vec(i).type in (T_INT, T_REAL)]
        mats = [np.asarray(fr.vec(i).to_numpy()[: fr.nrow], np.float64)
                for i in num_idx]
        M = np.stack(mats) if mats else np.zeros((0, fr.nrow))
        okm = np.isfinite(M)
        Mz = np.where(okm, M, 0.0)
        if axis == 1:
            s = Mz.sum(axis=0)
            if not na_rm:
                s = np.where(okm.all(axis=0), s, np.nan)
            return Frame(["sum"], [Vec.from_numpy(s)])
        s = Mz.sum(axis=1)
        if not na_rm:
            s = np.where(okm.all(axis=1), s, np.nan)
        # names track the NUMERIC columns actually summed
        return Frame([fr.names[i] for i in num_idx],
                     [Vec.from_numpy(np.asarray([v])) for v in s])
    if op == "which.max" or op == "which.min":
        fr = ev(0)
        na_rm = bool(_eval(args[1], env)) if len(args) > 1 else True
        axis = int(_eval(args[2], env) or 0) if len(args) > 2 else 0
        M = np.stack([np.asarray(fr.vec(i).asnumeric().to_numpy()[: fr.nrow],
                                 np.float64) for i in range(fr.ncol)])
        fn = np.nanargmax if op == "which.max" else np.nanargmin
        if axis == 1:
            vals = np.asarray([float(fn(M[:, r])) if np.isfinite(
                M[:, r]).any() else np.nan for r in range(fr.nrow)])
            return Frame([op], [Vec.from_numpy(vals)])
        vals = [float(fn(M[i])) if np.isfinite(M[i]).any() else np.nan
                for i in range(fr.ncol)]
        return Frame(list(fr.names),
                     [Vec.from_numpy(np.asarray([v])) for v in vals])
    if op == "hist":
        # (hist fr breaks): counts/breaks/mids frame (AstHist)
        fr = ev(0)
        breaks = _eval(args[1], env) if len(args) > 1 else 20
        x = np.asarray(fr.vec(0).to_numpy()[: fr.nrow], np.float64)
        x = x[np.isfinite(x)]
        if isinstance(breaks, list):
            edges = np.asarray(breaks, np.float64)
        else:
            nb = int(breaks) if not isinstance(breaks, str) else 20
            edges = np.histogram_bin_edges(x, bins=max(nb, 1))
        cnt, edges = np.histogram(x, bins=edges)
        mids = 0.5 * (edges[:-1] + edges[1:])
        pad = np.concatenate([[np.nan], mids])
        cntp = np.concatenate([[np.nan], cnt.astype(np.float64)])
        return Frame(
            ["breaks", "counts", "mids_true", "mids"],
            [Vec.from_numpy(edges.astype(np.float64)),
             Vec.from_numpy(cntp),
             Vec.from_numpy(pad),
             Vec.from_numpy(pad)])
    # ---- munging (ast/prims/mungers) ----------------------------------
    if op == "match":
        # (match fr table nomatch start_index): positions of values in
        # table (AstMatch; R match semantics, 1-based by default)
        fr = ev(0)
        table = _eval(args[1], env)
        nomatch = _eval(args[2], env) if len(args) > 2 else float("nan")
        start = int(_eval(args[3], env) or 1) if len(args) > 3 else 1
        tab = [str(t) for t in (table if isinstance(table, list)
                                else [table])]
        lut = {t: i + start for i, t in enumerate(dict.fromkeys(tab))}
        v = fr.vec(0)
        vals = (v.to_strings()[: fr.nrow] if v.type in (T_STR, T_ENUM)
                else [str(x) for x in np.asarray(v.to_numpy()[: fr.nrow])])
        try:
            nm = float(nomatch)
        except (TypeError, ValueError):
            nm = np.nan
        out = np.asarray([lut.get(s, nm) for s in vals], np.float64)
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    if op in ("relevel", "relevel.by.freq"):
        fr = ev(0)
        v = fr.vec(0)
        dom = list(v.domain or [])
        codes = np.asarray(v.to_numpy()[: fr.nrow])
        if op == "relevel":
            lvl = str(_eval(args[1], env))
            if lvl not in dom:
                raise ValueError(f"level '{lvl}' not in domain {dom}")
            new_dom = [lvl] + [d for d in dom if d != lvl]
        else:
            valid = codes[np.isfinite(codes) & (codes >= 0)].astype(int)
            cnt = np.bincount(valid, minlength=len(dom))
            order = np.argsort(-cnt, kind="stable")
            new_dom = [dom[i] for i in order]
        remap = {dom.index(d): i for i, d in enumerate(new_dom)}
        new_codes = np.asarray(
            [remap.get(int(c), -1) if np.isfinite(c) and c >= 0 else -1
             for c in codes], np.int32)
        return Frame([fr.names[0]],
                     [Vec.from_numpy(new_codes, vtype=T_ENUM,
                                     domain=new_dom)])
    if op in ("setLevel", "setDomain", "appendLevels"):
        fr = ev(0)
        v = fr.vec(0)
        dom = list(v.domain or [])
        codes = np.asarray(v.to_numpy()[: fr.nrow])
        if op == "setLevel":           # constant column of one level
            lvl = str(_eval(args[1], env))
            if lvl not in dom:
                raise ValueError(f"level '{lvl}' not in domain {dom}")
            new = np.full(fr.nrow, dom.index(lvl), np.int32)
            return Frame([fr.names[0]],
                         [Vec.from_numpy(new, vtype=T_ENUM, domain=dom)])
        if op == "setDomain":
            new_dom = [str(s) for s in _eval(args[2], env)] \
                if len(args) > 2 else [str(s) for s in _eval(args[1], env)]
            return Frame([fr.names[0]],
                         [Vec.from_numpy(codes.astype(np.int32),
                                         vtype=T_ENUM, domain=new_dom)])
        extra = [str(s) for s in _eval(args[1], env)]
        new_dom = dom + [s for s in extra if s not in dom]
        return Frame([fr.names[0]],
                     [Vec.from_numpy(codes.astype(np.int32), vtype=T_ENUM,
                                     domain=new_dom)])
    if op == "cut":
        # (cut fr breaks labels include_lowest right digits) — AstCut /
        # R cut(): right=True gives (a,b] intervals; include_lowest pulls
        # values equal to the first break into the first bin
        fr = ev(0)
        breaks = [float(b) for b in _eval(args[1], env)]
        labels = _eval(args[2], env) if len(args) > 2 else None
        lowest = bool(_eval(args[3], env)) if len(args) > 3 else False
        right = bool(_eval(args[4], env)) if len(args) > 4 else True
        x = np.asarray(fr.vec(0).to_numpy()[: fr.nrow], np.float64)
        idx = np.digitize(x, breaks, right=right) - 1
        nb = len(breaks) - 1
        if lowest:
            # boundary value joins the FIRST bin (right=True: x == b0;
            # right=False: x == b_last)
            if right:
                idx = np.where(x == breaks[0], 0, idx)
            else:
                idx = np.where(x == breaks[-1], nb - 1, idx)
        valid = np.isfinite(x) & (idx >= 0) & (idx < nb)
        if not labels or labels in ([], None):
            lo_b, hi_b = ("(", "]") if right else ("[", ")")
            labels = [f"{lo_b}{breaks[i]:g},{breaks[i+1]:g}{hi_b}"
                      for i in range(nb)]
        codes = np.where(valid, idx, -1).astype(np.int32)
        return Frame([fr.names[0]],
                     [Vec.from_numpy(codes, vtype=T_ENUM,
                                     domain=[str(l) for l in labels])])
    if op == "difflag1":
        fr = ev(0)
        x = np.asarray(fr.vec(0).to_numpy()[: fr.nrow], np.float64)
        d = np.concatenate([[np.nan], np.diff(x)])
        return Frame([fr.names[0]], [Vec.from_numpy(d)])
    if op == "t":
        fr = ev(0)
        M = np.stack([np.asarray(fr.vec(i).asnumeric().to_numpy()[: fr.nrow],
                                 np.float64) for i in range(fr.ncol)])
        return Frame([f"C{j+1}" for j in range(M.shape[1])],
                     [Vec.from_numpy(M[:, j]) for j in range(M.shape[1])])
    if op == "h2o.runif":
        fr = ev(0)
        seed = int(_eval(args[1], env)) if len(args) > 1 else -1
        rng = np.random.default_rng(None if seed in (-1, None) else seed)
        return Frame(["rnd"], [Vec.from_numpy(
            rng.random(fr.nrow).astype(np.float64))])
    if op in ("h2o.fillna", "fillna"):
        # (h2o.fillna fr method axis maxlen) — forward/backward fill
        fr = ev(0)
        meth = str(_eval(args[1], env) or "forward").lower()
        axis = int(_eval(args[2], env) or 0) if len(args) > 2 else 0
        maxlen = int(_eval(args[3], env) or 1) if len(args) > 3 else 1
        if axis != 0:
            raise ValueError(
                "h2o.fillna axis=1 (fill across columns) is not "
                "implemented — use axis=0")
        vecs = []
        for i in range(fr.ncol):
            x = np.asarray(fr.vec(i).to_numpy()[: fr.nrow],
                           np.float64).copy()
            if axis == 0:
                run = 0
                rng_iter = (range(1, len(x)) if meth.startswith("f")
                            else range(len(x) - 2, -1, -1))
                step = -1 if meth.startswith("f") else 1
                for r in rng_iter:
                    if np.isnan(x[r]) and not np.isnan(x[r + step]):
                        run = run + 1 if np.isnan(x[r]) else 0
                    if np.isnan(x[r]):
                        src = x[r + step]
                        if not np.isnan(src):
                            x[r] = src
                # maxlen enforcement: re-scan limiting runs
                if maxlen > 0:
                    x2 = np.asarray(fr.vec(i).to_numpy()[: fr.nrow],
                                    np.float64)
                    filled = np.isnan(x2) & ~np.isnan(x)
                    run = 0
                    idxs = (range(len(x)) if meth.startswith("f")
                            else range(len(x) - 1, -1, -1))
                    for r in idxs:
                        if filled[r]:
                            run += 1
                            if run > maxlen:
                                x[r] = np.nan
                        else:
                            run = 0
            vecs.append(Vec.from_numpy(x))
        return Frame(list(fr.names), vecs)
    if op == "h2o.impute":
        # (h2o.impute fr col method combine_method gb values) — in-place
        # imputation; returns the imputation values (AstImpute)
        fr = ev(0)
        col = int(_eval(args[1], env)) if len(args) > 1 else -1
        meth = str(_eval(args[2], env) or "mean").lower()
        targets = ([col] if col is not None and col >= 0
                   else list(range(fr.ncol)))
        out_vals = []
        vecs = [fr.vec(i) for i in range(fr.ncol)]
        for i in targets:
            v = vecs[i]
            if v.type == T_ENUM and meth == "mode":
                codes = np.asarray(v.to_numpy()[: fr.nrow])
                fin = codes[np.isfinite(codes) & (codes >= 0)].astype(int)
                mode = int(np.bincount(fin).argmax()) if fin.size else -1
                newc = np.where(np.isfinite(codes) & (codes >= 0), codes,
                                mode).astype(np.int32)
                vecs[i] = Vec.from_numpy(newc, vtype=T_ENUM,
                                         domain=list(v.domain))
                out_vals.append(float(mode))
                continue
            x = np.asarray(v.asnumeric().to_numpy()[: fr.nrow], np.float64)
            fin = x[np.isfinite(x)]
            val = (float(np.median(fin)) if meth == "median"
                   else float(fin.mean())) if fin.size else 0.0
            vecs[i] = Vec.from_numpy(np.where(np.isfinite(x), x, val))
            out_vals.append(val)
        newfr = Frame(list(fr.names), vecs)
        if args and args[0][0] == "id":
            dkv.put(args[0][1], "frame", newfr)
        return out_vals
    if op == "columnsByType":
        # (columnsByType fr coltype): 0-based indices (AstColumnsByType)
        fr = ev(0)
        want = str(_eval(args[1], env) or "numeric").lower()
        tests = {"numeric": lambda v: v.type in (T_INT, T_REAL),
                 "categorical": lambda v: v.type == T_ENUM,
                 "string": lambda v: v.type == T_STR,
                 "time": lambda v: v.type == "time",
                 "numeric_int": lambda v: v.type == T_INT,
                 "numeric_real": lambda v: v.type == T_REAL,
                 "bad": lambda v: False,
                 "uuid": lambda v: False}
        t = tests.get(want, tests["numeric"])
        return [float(i) for i in range(fr.ncol) if t(fr.vec(i))]
    if op == "filterNACols":
        fr = ev(0)
        frac = float(_eval(args[1], env)) if len(args) > 1 else 0.1
        keep = []
        for i in range(fr.ncol):
            na = fr.vec(i).rollups().get("na_count", 0) \
                if fr.vec(i).type != T_STR else \
                sum(1 for s in fr.vec(i).to_strings()[: fr.nrow]
                    if s is None)
            if na / max(fr.nrow, 1) < frac:
                keep.append(float(i))
        return keep
    if op == "dropdup":
        # (dropdup fr cols keep) — AstDropDuplicates
        fr = ev(0)
        sel = _eval(args[1], env) if len(args) > 1 else None
        keep = str(_eval(args[2], env) or "first").lower() \
            if len(args) > 2 else "first"
        idx_cols = (_sel_indices(sel, fr.ncol, fr.names).tolist()
                    if sel not in (None, []) else list(range(fr.ncol)))
        key_rows = list(zip(*[
            (fr.vec(int(i)).to_strings()[: fr.nrow]
             if fr.vec(int(i)).type in (T_STR, T_ENUM)
             else np.asarray(fr.vec(int(i)).to_numpy()[: fr.nrow]).tolist())
            for i in idx_cols]))
        seen = {}
        for r, k in enumerate(key_rows):
            if k not in seen or keep == "last":
                seen[k] = r
        rows = sorted(seen.values())
        return _take_frame(fr, np.asarray(rows, np.int64))
    if op == "rank_within_groupby":
        # (rank_within_groupby fr groupby_cols sort_cols ascending new_col
        #  sort_cols_sorted) — AstRankWithinGroupBy
        fr = ev(0)
        gcols = [int(i) for i in (_eval(args[1], env) or [])]
        scols = [int(i) for i in (_eval(args[2], env) or [])]
        asc = _eval(args[3], env) if len(args) > 3 else []
        new_col = str(_eval(args[4], env) or "New_Rank_column") \
            if len(args) > 4 else "New_Rank_column"
        gkeys = list(zip(*[np.asarray(
            fr.vec(i).to_numpy()[: fr.nrow]).tolist() for i in gcols])) \
            if gcols else [()] * fr.nrow
        svals = [np.asarray(fr.vec(i).to_numpy()[: fr.nrow], np.float64)
                 for i in scols]
        ascl = [int(a) for a in (asc if isinstance(asc, list)
                                 else [asc])] or [1] * len(scols)
        order_keys = []
        for v, a in zip(reversed(svals), reversed(ascl)):
            order_keys.append(v if a else -v)
        order = np.lexsort(order_keys) if order_keys else np.arange(fr.nrow)
        rank = np.zeros(fr.nrow, np.float64)
        counters: Dict = {}
        for r in order:
            k = gkeys[r]
            counters[k] = counters.get(k, 0) + 1
            rank[r] = counters[k]
        names = list(fr.names) + [new_col]
        vecs = [fr.vec(i) for i in range(fr.ncol)] + [Vec.from_numpy(rank)]
        return Frame(names, vecs)
    if op == "topn":
        # (topn fr col nPercent getBottomN) — AstTopN: top/bottom n% rows
        fr = ev(0)
        col = int(_eval(args[1], env))
        pct = float(_eval(args[2], env))
        bottom = int(_eval(args[3], env) or 0) if len(args) > 3 else 0
        x = np.asarray(fr.vec(col).to_numpy()[: fr.nrow], np.float64)
        fin = np.nonzero(np.isfinite(x))[0]
        n = max(int(len(fin) * pct / 100.0), 1)
        order = fin[np.argsort(x[fin], kind="stable")]
        pick = order[:n] if bottom else order[::-1][:n]
        pos = np.sort(pick)
        return Frame(["Original_Row_Indices", fr.names[col]],
                     [Vec.from_numpy(pos.astype(np.float64)),
                      Vec.from_numpy(x[pos])])
    if op == "melt":
        # (melt fr id_vars value_vars var_name value_name skipna) — AstMelt
        fr = ev(0)
        id_vars = [int(i) for i in (_eval(args[1], env) or [])]
        value_vars = [int(i) for i in (_eval(args[2], env) or [])] or \
            [i for i in range(fr.ncol) if i not in id_vars]
        var_name = str(_eval(args[3], env) or "variable")
        value_name = str(_eval(args[4], env) or "value")
        skipna = bool(_eval(args[5], env)) if len(args) > 5 else False
        n = fr.nrow
        id_cols = {i: np.asarray(fr.vec(i).to_numpy()[:n])
                   for i in id_vars}
        # hoist value columns ONCE (Vec.to_numpy copies the whole column
        # per call — per-cell access would be O(rows² · cols))
        val_cols = {vv: np.asarray(fr.vec(vv).to_numpy()[:n], np.float64)
                    for vv in value_vars}
        out_ids = {i: [] for i in id_vars}
        out_var: List[str] = []
        out_val: List[float] = []
        for r in range(n):
            for vv in value_vars:
                val = float(val_cols[vv][r])
                if skipna and not np.isfinite(val):
                    continue
                for i in id_vars:
                    out_ids[i].append(id_cols[i][r])
                out_var.append(fr.names[vv])
                out_val.append(val)
        names = [fr.names[i] for i in id_vars] + [var_name, value_name]
        vecs = [Vec.from_numpy(np.asarray(out_ids[i], np.float64))
                for i in id_vars]
        vecs.append(Vec.from_numpy(np.asarray(out_var, dtype=object),
                                   vtype=T_STR))
        vecs.append(Vec.from_numpy(np.asarray(out_val, np.float64)))
        return Frame(names, vecs)
    if op == "pivot":
        # (pivot fr index column value) — AstPivot
        fr = ev(0)
        inames = [str(_eval(a, env)) for a in args[1:4]]
        idx_c, col_c, val_c = (fr.names.index(n) for n in inames)
        idx_v = np.asarray(fr.vec(idx_c).to_numpy()[: fr.nrow])
        col_v = fr.vec(col_c)
        col_s = (col_v.to_strings()[: fr.nrow]
                 if col_v.type in (T_STR, T_ENUM) else
                 [str(x) for x in np.asarray(col_v.to_numpy()[: fr.nrow])])
        val_v = np.asarray(fr.vec(val_c).to_numpy()[: fr.nrow], np.float64)
        uniq_idx = sorted(set(idx_v.tolist()))
        uniq_col = sorted(set(col_s))
        pos_i = {v: i for i, v in enumerate(uniq_idx)}
        pos_c = {v: i for i, v in enumerate(uniq_col)}
        M = np.full((len(uniq_idx), len(uniq_col)), np.nan)
        for r in range(fr.nrow):
            M[pos_i[idx_v[r]], pos_c[col_s[r]]] = val_v[r]
        names = [inames[0]] + [str(c) for c in uniq_col]
        vecs = [Vec.from_numpy(np.asarray(uniq_idx, np.float64))]
        vecs += [Vec.from_numpy(M[:, j]) for j in range(len(uniq_col))]
        return Frame(names, vecs)
    if op == "kfold_column":
        fr = ev(0)
        k = int(_eval(args[1], env))
        seed = int(_eval(args[2], env)) if len(args) > 2 else -1
        rng = np.random.default_rng(None if seed in (-1, None) else seed)
        return Frame(["fold"], [Vec.from_numpy(
            rng.integers(0, k, fr.nrow).astype(np.float64))])
    if op == "modulo_kfold_column":
        fr = ev(0)
        k = int(_eval(args[1], env))
        return Frame(["fold"], [Vec.from_numpy(
            (np.arange(fr.nrow) % k).astype(np.float64))])
    if op == "stratified_kfold_column":
        fr = ev(0)
        k = int(_eval(args[1], env))
        seed = int(_eval(args[2], env)) if len(args) > 2 else -1
        rng = np.random.default_rng(None if seed in (-1, None) else seed)
        y = np.asarray(fr.vec(0).to_numpy()[: fr.nrow])
        fold = np.zeros(fr.nrow, np.float64)
        for lvl in np.unique(y[np.isfinite(y)]):
            rows = np.nonzero(y == lvl)[0]
            perm = rng.permutation(len(rows))
            fold[rows[perm]] = np.arange(len(rows)) % k
        return Frame(["fold"], [Vec.from_numpy(fold)])
    if op == "rep_len":
        val = _eval(args[0], env)
        length = int(_eval(args[1], env))
        if isinstance(val, Frame):
            x = np.asarray(val.vec(0).to_numpy()[: val.nrow], np.float64)
            out = np.resize(x, length)
            return Frame([val.names[0]], [Vec.from_numpy(out)])
        return Frame(["C1"], [Vec.from_numpy(
            np.full(length, float(val), np.float64))])
    if op == "flatten":
        fr = ev(0)
        v = fr.vec(0)
        if v.type in (T_STR, T_ENUM):
            s = v.to_strings()[:1]
            return s[0] if s else None
        val = float(np.asarray(v.to_numpy()[0]))
        return val
    if op == "distance":
        # (distance fr1 fr2 measure) — AstDistance: [n1, n2] matrix
        f1, f2 = ev(0), _eval(args[1], env)
        measure = str(_eval(args[2], env) or "l2").lower()
        A = np.stack([np.asarray(f1.vec(i).to_numpy()[: f1.nrow],
                                 np.float64) for i in range(f1.ncol)], 1)
        B = np.stack([np.asarray(f2.vec(i).to_numpy()[: f2.nrow],
                                 np.float64) for i in range(f2.ncol)], 1)
        if measure in ("cosine", "cosine_sq"):
            An = A / np.maximum(np.linalg.norm(A, axis=1, keepdims=True),
                                1e-30)
            Bn = B / np.maximum(np.linalg.norm(B, axis=1, keepdims=True),
                                1e-30)
            D = An @ Bn.T
            if measure == "cosine_sq":
                D = D * D
        elif measure == "l1":
            D = np.abs(A[:, None, :] - B[None, :, :]).sum(-1)
        else:
            D = np.sqrt(((A[:, None, :] - B[None, :, :]) ** 2).sum(-1))
        return Frame([f"C{j+1}" for j in range(D.shape[1])],
                     [Vec.from_numpy(D[:, j]) for j in range(D.shape[1])])
    # ---- string prims (ast/prims/string) -------------------------------
    if op in ("lstrip", "rstrip"):
        fr = ev(0)
        chars = str(_eval(args[1], env)) if len(args) > 1 else None
        v = fr.vec(0)
        vals = v.to_strings()[: fr.nrow]
        fn = (lambda s: s.lstrip(chars)) if op == "lstrip" else \
            (lambda s: s.rstrip(chars))
        out = np.asarray([None if s is None else fn(str(s))
                          for s in vals], dtype=object)
        return Frame([fr.names[0]], [Vec.from_numpy(out, vtype=T_STR)])
    if op == "strlen":
        fr = ev(0)
        vals = fr.vec(0).to_strings()[: fr.nrow]
        out = np.asarray([np.nan if s is None else float(len(str(s)))
                          for s in vals])
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    if op == "countmatches":
        fr = ev(0)
        pats = _eval(args[1], env)
        pats = [str(p) for p in (pats if isinstance(pats, list)
                                 else [pats])]
        vals = fr.vec(0).to_strings()[: fr.nrow]
        out = np.asarray([np.nan if s is None else
                          float(sum(str(s).count(p) for p in pats))
                          for s in vals])
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    if op == "num_valid_substrings":
        fr = ev(0)
        path = str(_eval(args[1], env))
        with open(path) as f:
            words = set(w.strip() for w in f if w.strip())
        vals = fr.vec(0).to_strings()[: fr.nrow]

        def count(s):
            n = 0
            for i in range(len(s)):
                for j in range(i + 1, len(s) + 1):
                    if s[i:j] in words:
                        n += 1
            return float(n)
        out = np.asarray([np.nan if s is None else count(str(s))
                          for s in vals])
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    if op == "strsplit":
        fr = ev(0)
        pat = str(_eval(args[1], env))
        vals = fr.vec(0).to_strings()[: fr.nrow]
        parts = [re.split(pat, str(s)) if s is not None else []
                 for s in vals]
        width = max((len(p) for p in parts), default=0)
        cols = []
        for j in range(width):
            cols.append(np.asarray(
                [p[j] if j < len(p) else None for p in parts],
                dtype=object))
        return Frame([f"C{j+1}" for j in range(width)],
                     [Vec.from_numpy(c, vtype=T_STR) for c in cols])
    if op == "tf-idf":
        # (tf-idf frame doc_id_idx text_idx preprocess case_sensitive) —
        # water/rapids/ast/prims/advmath/AstTfIdf.java: tokenize on \s+
        # when preprocess, TF = per-(doc, token) count, IDF =
        # log((N_docs+1)/(DF+1)) (hex/tfidf/InverseDocumentFrequencyTask
        # .java idf()), rows sorted (Token, DocID).
        fr = ev(0)
        doc_idx = int(_eval(args[1], env))
        text_idx = int(_eval(args[2], env))
        preprocess = bool(_eval(args[3], env))
        case_sensitive = bool(_eval(args[4], env))
        dv = fr.vec(doc_idx)
        if dv.type == T_STR:
            doc_ids = [str(s) for s in dv.to_strings()[: fr.nrow]]
            doc_numeric = False
        else:
            doc_ids = np.asarray(dv.to_numpy()[: fr.nrow])
            doc_numeric = True
        texts = fr.vec(text_idx).to_strings()[: fr.nrow]
        from collections import Counter
        tf = Counter()
        docs_seen = set()
        for i in range(fr.nrow):
            d = doc_ids[i] if not doc_numeric else float(doc_ids[i])
            s = texts[i]
            if s is None:
                continue
            docs_seen.add(d)
            toks = re.split(r"\s+", str(s).strip()) if preprocess \
                else [str(s)]
            for t in toks:
                if not t:
                    continue
                if not case_sensitive:
                    t = t.lower()
                tf[(t, d)] += 1
        n_docs = len(docs_seen)
        df = Counter(t for (t, _d) in tf)
        rows = sorted(tf.items())
        out_doc = [d for ((_t, d), _c) in rows]
        out_tok = np.asarray([t for ((t, _d), _c) in rows], dtype=object)
        out_tf = np.asarray([float(c) for (_td, c) in rows])
        out_idf = np.asarray([math.log((n_docs + 1.0) / (df[t] + 1.0))
                              for ((t, _d), _c) in rows])
        dvec = (Vec.from_numpy(np.asarray(out_doc, np.float64))
                if doc_numeric else
                Vec.from_numpy(np.asarray(out_doc, dtype=object),
                               vtype=T_STR))
        return Frame(["DocID", "Token", "TF", "IDF", "TF-IDF"],
                     [dvec, Vec.from_numpy(out_tok, vtype=T_STR),
                      Vec.from_numpy(out_tf), Vec.from_numpy(out_idf),
                      Vec.from_numpy(out_tf * out_idf)])
    if op == "strDistance":
        # (strDistance fr1 fr2 measure compare_empty) — Levenshtein only
        f1, f2 = ev(0), _eval(args[1], env)
        a = f1.vec(0).to_strings()[: f1.nrow]
        b = f2.vec(0).to_strings()[: f2.nrow]

        def lev(s, t):
            if s is None or t is None:
                return np.nan
            s, t = str(s), str(t)
            prev = list(range(len(t) + 1))
            for i, cs in enumerate(s, 1):
                cur = [i]
                for j, ct in enumerate(t, 1):
                    cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                                   prev[j - 1] + (cs != ct)))
                prev = cur
            m = max(len(s), len(t))
            return 1.0 - prev[-1] / m if m else 1.0
        out = np.asarray([lev(s, t) for s, t in zip(a, b)])
        return Frame(["distance"], [Vec.from_numpy(out)])
    if op == "grep":
        # (grep fr regex ignore_case invert output_logical) — AstGrep
        fr = ev(0)
        pat = str(_eval(args[1], env))
        icase = bool(_eval(args[2], env)) if len(args) > 2 else False
        invert = bool(_eval(args[3], env)) if len(args) > 3 else False
        logical = bool(_eval(args[4], env)) if len(args) > 4 else False
        rx = re.compile(pat, re.IGNORECASE if icase else 0)
        vals = fr.vec(0).to_strings()[: fr.nrow]
        hits = np.asarray([bool(rx.search(str(s))) if s is not None
                           else False for s in vals])
        if invert:
            hits = ~hits
        if logical:
            return Frame(["grep"], [Vec.from_numpy(
                hits.astype(np.float64))])
        return Frame(["grep"], [Vec.from_numpy(
            np.nonzero(hits)[0].astype(np.float64))])
    if op == "as.character":
        fr = ev(0)
        v = fr.vec(0)
        vals = (v.to_strings()[: fr.nrow] if v.type in (T_STR, T_ENUM)
                else [None if not np.isfinite(x) else
                      (str(int(x)) if float(x).is_integer() else str(x))
                      for x in np.asarray(v.to_numpy()[: fr.nrow],
                                          np.float64)])
        return Frame([fr.names[0]],
                     [Vec.from_numpy(np.asarray(vals, dtype=object),
                                     vtype=T_STR)])
    if op == "listTimeZones":
        import zoneinfo
        tz = sorted(zoneinfo.available_timezones())
        return Frame(["Timezones"], [Vec.from_numpy(
            np.asarray(tz, dtype=object), vtype=T_STR)])
    if op == "ls":
        # AstLs (ast/prims/misc/AstLs.java): frame of DKV keys
        keys = sorted(dkv.keys())
        return Frame(["key"], [Vec.from_numpy(
            np.asarray(keys, dtype=object), vtype=T_STR)])
    if op == ":=":
        # AstRectangleAssign (ast/prims/assign/AstRectangleAssign.java):
        # (:= dst src col_expr row_expr) -> new frame with the rectangle
        # overwritten; src is a frame, scalar, or string; [] rows = all
        dst = ev(0)
        src = _eval(args[1], env)
        cols = _eval(args[2], env)
        rows = _eval(args[3], env) if len(args) > 3 else []
        cidx = _sel_indices(cols, dst.ncol, dst.names)
        if isinstance(rows, Frame):
            rmask = np.asarray(rows.vec(0).to_numpy()[: dst.nrow]) != 0
            ridx = np.nonzero(rmask)[0]
        elif rows in ([], None):
            ridx = None                       # all rows
        else:
            ridx = _sel_indices(rows, dst.nrow)
        new_vecs = [dst.vec(i) for i in range(dst.ncol)]
        for j, ci in enumerate(cidx):
            ci = int(ci)
            if isinstance(src, Frame):
                sv = src.vec(min(j, src.ncol - 1))
                if ridx is None:
                    new_vecs[ci] = sv
                    continue
                sarr = np.asarray(sv.to_numpy(), dtype=np.float64)
                dom = sv.domain
            else:
                if isinstance(src, str):
                    old = new_vecs[ci]
                    dom = list(old.domain or [])
                    if src not in dom:
                        dom.append(src)
                    code = float(dom.index(src))
                    sarr = np.full(dst.nrow if ridx is None else len(ridx),
                                   code)
                else:
                    sarr = np.full(dst.nrow if ridx is None else len(ridx),
                                   np.nan if src is None else float(src))
                    dom = new_vecs[ci].domain
            darr = np.asarray(new_vecs[ci].to_numpy(),
                              dtype=np.float64).copy()
            if ridx is None:
                darr[:] = sarr[: len(darr)]
            else:
                darr[ridx] = (sarr[: len(ridx)] if np.ndim(sarr) else sarr)
            if dom:
                codes = np.where(np.isfinite(darr), darr, -1).astype(np.int32)
                new_vecs[ci] = Vec.from_numpy(codes, vtype=T_ENUM,
                                              domain=[str(d) for d in dom])
            else:
                new_vecs[ci] = Vec.from_numpy(darr)
        return Frame(list(dst.names), new_vecs)
    if op == "append":
        # AstAppend: (append dst src colName)+ -> frame with new columns
        dst = ev(0)
        names = list(dst.names)
        vecs = [dst.vec(i) for i in range(dst.ncol)]
        i = 1
        while i + 1 < len(args):
            src = _eval(args[i], env)
            cname = _eval(args[i + 1], env)
            if isinstance(src, Frame):
                v = src.vec(0)
            else:
                arr = np.full(dst.nrow,
                              np.nan if src is None else float(src))
                v = Vec.from_numpy(arr)
            if cname in names:
                vecs[names.index(cname)] = v
            else:
                names.append(str(cname))
                vecs.append(v)
            i += 2
        return Frame(names, vecs)
    if op in _BINOPS:
        a, b = ev(0), ev(1)
        # string/enum comparisons against a string literal compare LABELS
        # (AstEq/AstNe string semantics) — the device path only holds
        # numeric codes
        if op in ("==", "!=") and (
                (isinstance(a, Frame) and isinstance(b, str))
                or (isinstance(b, Frame) and isinstance(a, str))):
            fr2, lit = (a, b) if isinstance(a, Frame) else (b, a)
            cols = []
            for i in range(fr2.ncol):
                v = fr2.vec(i)
                if v.type in (T_STR, T_ENUM):
                    vals = v.to_strings()[: fr2.nrow]
                    eq = np.asarray([1.0 if (s is not None and str(s) == lit)
                                     else 0.0 for s in vals])
                else:
                    eq = np.zeros(fr2.nrow)
                cols.append(Vec.from_numpy(
                    eq if op == "==" else 1.0 - eq))
            return Frame(list(fr2.names), cols)
        return _map_elementwise(_BINOPS[op], a, b)
    if op in _UNOPS:
        return _map_elementwise(_UNOPS[op], ev(0))
    if op == "cols_py" or op == "cols":
        fr = ev(0)
        sel = ev(1)
        idx = _sel_indices(sel, fr.ncol, fr.names)
        if len(idx) and (idx < 0).all():
            # h2o-py drop-column encoding: -(i+1) means drop column i
            dropped = {-int(i) - 1 for i in idx}
            idx = np.asarray([i for i in range(fr.ncol) if i not in dropped])
        names = [fr.names[i] for i in idx]
        return Frame(names, [fr.vec(int(i)) for i in idx])
    if op == "rows":
        fr = ev(0)
        sel = ev(1)
        if isinstance(sel, Frame):       # boolean mask frame
            mask = np.asarray(sel.vec(0).to_numpy()[: fr.nrow]) != 0
            idx = np.nonzero(mask)[0]
        else:
            idx = _sel_indices(sel, fr.nrow)
        return _take_frame(fr, idx)
    if op in ("mean", "median"):
        # frame-valued reducers (water/rapids/ast/prims/reducers/AstMean.java,
        # AstMedian.java): (op frame na_rm axis) -> [1 x ncols] frame
        # (axis=0) or [nrows x 1] frame (axis=1); enum/string columns -> NA
        fr = ev(0)
        na_rm = bool(_eval(args[1], env)) if len(args) > 1 else True
        axis = int(_eval(args[2], env) or 0) if len(args) > 2 else 0
        fn = ((lambda x, ok: jnp.where(ok, x, 0).sum() / ok.sum())
              if op == "mean" else (lambda x, ok: jnp.median(x[ok])))
        if axis == 1:
            num = [i for i in range(fr.ncol)
                   if fr.vec(i).type in (T_INT, T_REAL)]
            mat = np.stack([np.asarray(fr.vec(i).to_numpy(),
                                       dtype=np.float64) for i in num])
            ok = np.isfinite(mat)
            if op == "mean":
                s = np.where(ok, mat, 0).sum(axis=0)
                c = ok.sum(axis=0)
                vals = np.where(c > 0, s / np.maximum(c, 1), np.nan)
            else:
                vals = np.array([np.median(col[okc]) if okc.any() else np.nan
                                 for col, okc in zip(mat.T, ok.T)])
            if not na_rm:
                vals = np.where(ok.all(axis=0), vals, np.nan)
            return Frame([op], [Vec.from_numpy(vals.astype(np.float64))])
        vals = []
        for i in range(fr.ncol):
            v = fr.vec(i)
            if v.type not in (T_INT, T_REAL):
                vals.append(np.nan)
                continue
            x = np.asarray(v.to_numpy(), dtype=np.float64)
            ok = np.isfinite(x)
            if not ok.any() or (not na_rm and not ok.all()):
                vals.append(np.nan)
            else:
                vals.append(float(fn(jnp.asarray(x), jnp.asarray(ok))))
        return Frame(list(fr.names),
                     [Vec.from_numpy(np.asarray([val], dtype=np.float64))
                      for val in vals])
    if op == "getrow":
        # AstGetrow: single-row frame -> row of numbers
        fr = ev(0)
        if fr.nrow != 1:
            raise ValueError(f"getrow requires a 1-row frame, got {fr.nrow}")
        out = []
        for i in range(fr.ncol):
            val = fr.vec(i).to_numpy()[0]
            val = float(val)
            out.append(None if not math.isfinite(val) else val)
        return out
    if op in ("sum", "min", "max", "sd", "sdev", "nrow", "ncol"):
        fr = ev(0)
        if op == "nrow":
            return float(fr.nrow)
        if op == "ncol":
            return float(fr.ncol)
        na_rm = bool(_eval(args[1], env)) if len(args) > 1 else True
        fns = {
            "sum": lambda x, ok: jnp.where(ok, x, 0).sum(),
            "min": lambda x, ok: jnp.where(ok, x, jnp.inf).min(),
            "max": lambda x, ok: jnp.where(ok, x, -jnp.inf).max(),
            "sd": _sd_fn, "sdev": _sd_fn,
        }
        out = _reduce(fns[op], fr, na_rm)
        return out
    if op == "GB":
        fr = ev(0)
        by = ev(1)
        rest = [_eval(a, env) for a in args[2:]]
        aggs = []
        for i in range(0, len(rest), 3):
            agg = rest[i]
            col = rest[i + 1] if rest[i + 1] != [] else None
            aggs.append((agg, col))
        return group_by(fr, by if isinstance(by, list) else [by], aggs)
    if op == "merge":
        left, right = ev(0), ev(1)
        all_x, all_y = bool(ev(2)), bool(ev(3))
        by_x, by_y = ev(4), ev(5)
        if not by_x:
            common = [n for n in left.names if n in right.names]
            bx = by_ = common
        else:
            bx = [left.names[int(i)] for i in by_x]
            by_ = [right.names[int(i)] for i in by_y]
        return merge(left, right, bx, by_, all_x, all_y)
    if op == "sort":
        fr = ev(0)
        cols = ev(1)
        asc = ev(2) if len(args) > 2 else None
        return sort_frame(fr, cols if isinstance(cols, list) else [cols],
                          asc)
    if op == "cbind":
        frames = [_eval(a, env) for a in args]
        names, vecs = [], []
        for f in frames:
            for n in f.names:
                nm = n
                k = 1
                while nm in names:
                    nm = f"{n}{k}"
                    k += 1
                names.append(nm)
                vecs.append(f.vec(n))
        return Frame(names, vecs)
    if op == "rbind":
        frames = [_eval(a, env) for a in args]
        base = frames[0]
        vecs = []
        for n in base.names:
            vt = base.vec(n).type
            if vt in (T_ENUM, T_STR):
                # labels, not codes: domains may differ across frames
                parts = [np.asarray(f.vec(n).to_strings()[: f.nrow],
                                    dtype=object) for f in frames]
                vecs.append(Vec.from_numpy(np.concatenate(parts)))
            else:
                parts = [np.asarray(f.vec(n).to_numpy()[: f.nrow],
                                    dtype=np.float64) for f in frames]
                vecs.append(Vec.from_numpy(np.concatenate(parts)))
        return Frame(list(base.names), vecs)
    if op == "ifelse":
        cond, yes, no = ev(0), ev(1), ev(2)
        def sel3(c, a, b):
            return jnp.where(c != 0, a, b)
        if isinstance(cond, Frame):
            a = yes.vec(0).as_float() if isinstance(yes, Frame) else yes
            b = no.vec(0).as_float() if isinstance(no, Frame) else no
            out = sel3(cond.vec(0).as_float(), a, b)
            return Frame(["C1"], [Vec.from_numpy(
                np.asarray(_fetch(out))[: cond.nrow]
                .astype(np.float32))])
        return yes if cond else no
    if op == "unique":
        fr = ev(0)
        nrow = fr.nrow
        v = fr.vec(0)
        if v.type in (T_ENUM, T_STR):
            labs = [s for s in v.to_strings()[:nrow] if s is not None]
            vals = np.unique(np.asarray(labs, dtype=object))
            return Frame([fr.names[0]], [Vec.from_numpy(vals)])
        vals = np.unique(np.asarray(v.to_numpy()[:nrow], dtype=np.float64))
        vals = vals[np.isfinite(vals)]
        return Frame([fr.names[0]], [Vec.from_numpy(vals)])
    if op == "colnames=":
        fr = ev(0)
        sel = ev(1)
        names = ev(2)
        names = names if isinstance(names, list) else [names]
        idx = _sel_indices(sel, fr.ncol, fr.names)
        new_names = list(fr.names)
        for i, nm in zip(idx, names):
            new_names[int(i)] = nm
        return Frame(new_names, list(fr.vecs))
    if op in ("is.factor", "is.numeric", "is.character", "anyfactor"):
        # AstIsFactor/AstIsNumeric/AstIsCharacter/AstAnyFactor: per-column
        # 0/1 flags (single value for 1-col frames)
        fr = ev(0)
        tests = {"is.factor": lambda v: v.type == T_ENUM,
                 "is.numeric": lambda v: v.type in (T_INT, T_REAL),
                 "is.character": lambda v: v.type == T_STR}
        if op == "anyfactor":
            return float(any(fr.vec(i).type == T_ENUM
                             for i in range(fr.ncol)))
        # always a list: h2o-py iterates the result (frame.py isfactor)
        return [float(tests[op](fr.vec(i))) for i in range(fr.ncol)]
    if op == "levels":
        # AstLevels: domain values as a [card x ncol] string frame
        fr = ev(0)
        cols = []
        maxlen = max([len(fr.vec(i).domain or []) for i in range(fr.ncol)]
                     or [0])
        for i in range(fr.ncol):
            dom = list(fr.vec(i).domain or [])
            dom += [""] * (maxlen - len(dom))
            cols.append(Vec.from_numpy(np.asarray(dom, dtype=object)))
        return Frame(list(fr.names), cols)
    if op == "as.factor" or op == "asfactor":
        fr = ev(0)
        return Frame(list(fr.names), [fr.vec(n).asfactor() for n in fr.names])
    if op == "as.numeric" or op == "asnumeric":
        fr = ev(0)
        return Frame(list(fr.names),
                     [fr.vec(n).asnumeric() for n in fr.names])
    # ---- string prims (water/rapids/ast/prims/string) ------------------
    if op in ("tolower", "toupper", "trim", "nchar"):
        fr = ev(0)
        v = fr.vec(0)
        ss = list(v.to_strings()[: fr.nrow])
        if op == "nchar":
            arr = np.asarray([np.nan if s is None else float(len(s))
                              for s in ss])
            return Frame([fr.names[0]], [Vec.from_numpy(arr)])
        f = {"tolower": str.lower, "toupper": str.upper,
             "trim": str.strip}[op]
        out = np.asarray([None if s is None else f(s) for s in ss],
                         dtype=object)
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    if op in ("replacefirst", "replaceall", "sub", "gsub"):
        # reference arg order is FRAME-first: (replaceall x pattern
        # replacement ignore_case) — h2o-py H2OFrame.gsub emits
        # ExprNode("replaceall", self, pattern, replacement, ...)
        import re as _re
        fr, pat, rep = ev(0), ev(1), ev(2)
        ignore = bool(_eval(args[3], env)) if len(args) > 3 else False
        rx = _re.compile(pat, _re.IGNORECASE if ignore else 0)
        count = 1 if op in ("sub", "replacefirst") else 0
        ss = list(fr.vec(0).to_strings()[: fr.nrow])
        out = np.asarray([None if s is None else rx.sub(rep, s, count)
                          for s in ss], dtype=object)
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    if op == "substring":
        fr, start = ev(0), int(ev(1))
        end = int(ev(2)) if len(args) > 2 else None
        ss = list(fr.vec(0).to_strings()[: fr.nrow])
        out = np.asarray([None if s is None else s[start:end]
                          for s in ss], dtype=object)
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    # ---- time prims (ast/prims/time; values = epoch millis) ------------
    if op in ("year", "month", "day", "hour", "minute", "second",
              "dayOfWeek", "week"):
        fr = ev(0)
        v0 = fr.vec(0)
        ms = np.asarray(v0.to_numpy()[: fr.nrow], np.float64)
        # T_TIME NAs arrive as the int64-min sentinel, which IS finite
        # in float — mask it explicitly alongside NaN
        ok = np.isfinite(ms) & (np.abs(ms) < 4e17)  # |ms| < year ~14000
        dt = ms[ok].astype("datetime64[ms]")
        y = dt.astype("datetime64[Y]")
        mth = dt.astype("datetime64[M]")
        dd = dt.astype("datetime64[D]")
        if op == "week":
            # ISO week-of-weekyear (reference AstWeek getWeekOfWeekyear):
            # the ISO week of a date equals the ordinal week of its
            # Thursday within the Thursday's calendar year
            day_i = dd.astype(int)
            dow = (day_i + 3) % 7                      # Mon=0
            thursday = (day_i - dow + 3).astype("datetime64[D]")
            ty = thursday.astype("datetime64[Y]")
            vals = ((thursday - ty.astype("datetime64[D]")).astype(int)
                    // 7 + 1)
        else:
            vals = {
                "year": y.astype(int) + 1970,
                "month": (mth - y.astype("datetime64[M]")).astype(int) + 1,
                "day": (dd - mth.astype("datetime64[D]")).astype(int) + 1,
                "hour": (dt.astype("datetime64[h]")
                         - dd.astype("datetime64[h]")).astype(int),
                "minute": (dt.astype("datetime64[m]").astype(int) % 60),
                "second": (dt.astype("datetime64[s]").astype(int) % 60),
                # reference domain Mon=0 (AstDayOfWeek); epoch day 0 = Thu
                "dayOfWeek": (dd.astype(int) + 3) % 7,
            }[op]
        out = np.full(len(ms), np.nan)
        out[ok] = vals.astype(np.float64)
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    # ---- misc prims ----------------------------------------------------
    if op == "table":
        fr = ev(0)
        v = fr.vec(0)
        if v.type in (T_ENUM, T_STR):
            labs = [s for s in v.to_strings()[: fr.nrow] if s is not None]
            vals, cnt = np.unique(np.asarray(labs, dtype=object),
                                  return_counts=True)
            return Frame([fr.names[0], "Count"],
                         [Vec.from_numpy(vals),
                          Vec.from_numpy(cnt.astype(np.float64))])
        d = np.asarray(v.to_numpy()[: fr.nrow], np.float64)
        vals, cnt = np.unique(d[np.isfinite(d)], return_counts=True)
        return Frame([fr.names[0], "Count"],
                     [Vec.from_numpy(vals),
                      Vec.from_numpy(cnt.astype(np.float64))])
    if op == "cor":
        a, b = ev(0), ev(1)
        x = np.asarray(a.vec(0).to_numpy()[: a.nrow], np.float64)
        yv = np.asarray(b.vec(0).to_numpy()[: b.nrow], np.float64)
        ok = np.isfinite(x) & np.isfinite(yv)
        return float(np.corrcoef(x[ok], yv[ok])[0, 1])
    if op in ("round", "signif"):
        fr = ev(0)
        digits = int(ev(1)) if len(args) > 1 else 0
        def rnd(col):
            if op == "round":
                return np.round(col, digits)
            with np.errstate(all="ignore"):
                mag = np.where(col != 0, np.floor(np.log10(np.abs(col))),
                               0)
                f = 10.0 ** (digits - 1 - mag)
                return np.round(col * f) / f
        return Frame(list(fr.names),
                     [Vec.from_numpy(rnd(np.asarray(
                         fr.vec(n).to_numpy()[: fr.nrow], np.float64)))
                      for n in fr.names])
    if op in ("cumsum", "cumprod", "cummin", "cummax"):
        fr = ev(0)
        f = {"cumsum": np.cumsum, "cumprod": np.cumprod,
             "cummin": np.minimum.accumulate,
             "cummax": np.maximum.accumulate}[op]
        return Frame(list(fr.names),
                     [Vec.from_numpy(f(np.asarray(
                         fr.vec(n).to_numpy()[: fr.nrow], np.float64)))
                      for n in fr.names])
    if op == "which":
        fr = ev(0)
        d = np.asarray(fr.vec(0).to_numpy()[: fr.nrow])
        return Frame(["C1"],
                     [Vec.from_numpy(np.flatnonzero(
                         np.nan_to_num(d) != 0).astype(np.float64))])
    if op == "na.omit":
        fr = ev(0)
        keep = np.ones(fr.nrow, bool)
        for n in fr.names:
            v = fr.vec(n)
            if v.type in (T_ENUM, T_STR):
                keep &= np.asarray(
                    [s is not None for s in v.to_strings()[: fr.nrow]])
            else:
                keep &= np.isfinite(np.asarray(
                    v.to_numpy()[: fr.nrow], np.float64))
        return _take_frame(fr, np.flatnonzero(keep))
    if op == "scale":
        fr = ev(0)
        center = bool(_eval(args[1], env)) if len(args) > 1 else True
        scale_ = bool(_eval(args[2], env)) if len(args) > 2 else True
        vecs = []
        for n in fr.names:
            d = np.asarray(fr.vec(n).to_numpy()[: fr.nrow], np.float64)
            ok = np.isfinite(d)
            m = d[ok].mean() if center and ok.any() else 0.0
            s = d[ok].std(ddof=1) if scale_ and ok.sum() > 1 else 1.0
            vecs.append(Vec.from_numpy((d - m) / (s or 1.0)))
        return Frame(list(fr.names), vecs)
    raise ValueError(f"unsupported rapids op '{op}'")


def _sd_fn(x, ok):
    n = ok.sum()
    m = jnp.where(ok, x, 0).sum() / n
    return jnp.sqrt(jnp.where(ok, (x - m) ** 2, 0).sum()
                    / jnp.maximum(n - 1, 1))


def exec_rapids(ast: str, session_id: Optional[str] = None) -> Dict:
    """Execute an AST string, REST-shaped result (RapidsSchemaV3:
    {key} for frames, {scalar}, {string}, {map_keys, string_pairs}…)."""
    node = parse_rapids(ast)
    env = Env(session_id)
    result = _eval(node, env)
    if isinstance(result, Frame):
        # anonymous results need a key the client can address
        key = None
        if node[0] == "call" and node[1][0][1] == "tmp=":
            key = node[1][1][1]
        if key is None:
            key = dkv.unique_key("rapids_frame")
            dkv.put(key, "frame", result)
        return {"__meta": {"schema_version": 3,
                           "schema_name": "RapidsFrameV3"},
                "key": {"name": key}, "num_rows": result.nrow,
                "num_cols": result.ncol}
    if isinstance(result, str):
        return {"string": result}
    if isinstance(result, list):
        return {"scalar": result}
    return {"scalar": None if (isinstance(result, float)
                               and math.isnan(result)) else result}
