"""Rapids — the frame-algebra expression engine h2o-py/R emit.

Reference: water/rapids/Rapids.java:29 (Lisp-ish AST parser),
water/rapids/Env.java + Session.java (temp-frame lifetimes), ~150 prims
under water/rapids/ast/prims/ (mungers, operators, reducers, math,
matrix, timeseries, …), distributed sort/merge via MSB radix exchange
(water/rapids/Merge.java:27, RadixOrder.java:20).

TPU re-design: the interpreter is host-side (tiny ASTs), but the frame
math runs on device — elementwise ops map over sharded column arrays,
reducers are jitted reductions, group-by aggregates are segment-sums on
device after a host factorization of the (host-resident) group keys.
Merge/sort run host-side via numpy for now (the multi-chip story is an
all_to_all radix exchange, SURVEY §2.5 — single-controller scale does
not need it below ~100M rows).

Grammar (matching h2o-py expr.py _arg_to_expr): ``(op arg…)``, lists
``[v1 v2 …]``, slices ``[start:count]`` / ``[start:count:step]``,
python-repr strings, numbers (NaN for open slice ends), bare atoms as
frame/temp keys, ``(tmp= id expr)`` assignment.
"""
from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu import dkv
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import T_ENUM, T_INT, T_REAL, T_STR, Vec

# ---------------- tokenizer / parser -----------------------------------

_TOKEN = re.compile(r"""
    \s*(?:
        (?P<lparen>\()
      | (?P<rparen>\))
      | (?P<lbrack>\[)
      | (?P<rbrack>\])
      | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
      | (?P<atom>[^\s()\[\]'"]+)
    )""", re.VERBOSE)


class Slice:
    def __init__(self, start: int, count: float, step: int = 1):
        self.start = int(start)
        self.count = count          # may be NaN = open-ended
        self.step = int(step)

    def resolve(self, n: int) -> np.ndarray:
        if math.isnan(self.count):
            # open-ended: count is in ELEMENTS, not rows spanned
            count = -(-(n - self.start) // self.step)
        else:
            count = int(self.count)
        return np.arange(self.start, self.start + count * self.step,
                         self.step)


def _parse(tokens: List, pos: int) -> Tuple[Any, int]:
    tok = tokens[pos]
    kind, val = tok
    if kind == "lparen":
        items = []
        pos += 1
        while tokens[pos][0] != "rparen":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("call", items), pos + 1
    if kind == "lbrack":
        items = []
        pos += 1
        while tokens[pos][0] != "rbrack":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("list", items), pos + 1
    if kind == "string":
        body = val[1:-1]
        return ("str", bytes(body, "utf-8").decode("unicode_escape")), pos + 1
    # atom: number, slice, or identifier
    if re.fullmatch(r"-?\d+:\S+", val):
        parts = val.split(":")
        start = int(parts[0])
        count = float("nan") if parts[1].lower() == "nan" else float(parts[1])
        step = int(parts[2]) if len(parts) > 2 else 1
        return ("slice", Slice(start, count, step)), pos + 1
    try:
        return ("num", float(val)), pos + 1
    except ValueError:
        return ("id", val), pos + 1


def parse_rapids(ast: str):
    tokens = []
    i = 0
    while i < len(ast):
        m = _TOKEN.match(ast, i)
        if not m:
            break
        i = m.end()
        for kind in ("lparen", "rparen", "lbrack", "rbrack", "string", "atom"):
            if m.group(kind) is not None:
                tokens.append((kind, m.group(kind)))
                break
    node, _ = _parse(tokens, 0)
    return node


# ---------------- evaluation -------------------------------------------

class Env:
    def __init__(self, session: Optional[str] = None):
        self.session = session

    def lookup(self, name: str):
        ent = dkv.get_opt(name)
        if ent and ent[0] == "frame":
            return ent[1]
        return name   # plain string/col name


def _map_elementwise(op, a, b=None) -> Any:
    """Elementwise frame/scalar op on device, columnwise."""
    def dev(v: Vec):
        return v.as_float()

    if isinstance(a, Frame) and isinstance(b, Frame):
        if b.ncol == 1 and a.ncol != 1:
            cols = [op(dev(a.vec(n)), dev(b.vec(0))) for n in a.names]
            names = a.names
        elif a.ncol == 1 and b.ncol != 1:
            cols = [op(dev(a.vec(0)), dev(b.vec(n))) for n in b.names]
            names = b.names
        else:
            assert a.ncol == b.ncol, "frame op: ncol mismatch"
            cols = [op(dev(a.vec(i)), dev(b.vec(i))) for i in range(a.ncol)]
            names = a.names
    elif isinstance(a, Frame):
        cols = [op(dev(a.vec(n))) if b is None else op(dev(a.vec(n)), b)
                for n in a.names]
        names = a.names
    elif isinstance(b, Frame):
        cols = [op(a, dev(b.vec(n))) for n in b.names]
        names = b.names
    else:
        return op(a, b) if b is not None else op(a)
    nrow = (a if isinstance(a, Frame) else b).nrow
    vecs = [Vec.from_numpy(np.asarray(jax.device_get(c))[:nrow]
                           .astype(np.float32)) for c in cols]
    return Frame(names, vecs)


def _reduce(fn, fr: Frame, na_rm=True) -> float:
    vals = []
    for n in fr.names:
        v = fr.vec(n)
        if v.type == T_STR:
            continue
        x = v.as_float()
        ok = ~jnp.isnan(x[: fr.nrow]) if na_rm else jnp.ones(fr.nrow, bool)
        vals.append(float(jax.device_get(fn(x[: fr.nrow], ok))))
    return vals[0] if len(vals) == 1 else vals


_BINOPS = {
    "+": lambda a, b: a + b, "-": lambda a, b: a - b,
    "*": lambda a, b: a * b, "/": lambda a, b: a / b,
    "intDiv": lambda a, b: jnp.floor_divide(a, b),
    "%": lambda a, b: jnp.mod(a, b), "mod": lambda a, b: jnp.mod(a, b),
    "^": lambda a, b: a ** b, "pow": lambda a, b: a ** b,
    "<": lambda a, b: (a < b).astype(jnp.float32),
    "<=": lambda a, b: (a <= b).astype(jnp.float32),
    ">": lambda a, b: (a > b).astype(jnp.float32),
    ">=": lambda a, b: (a >= b).astype(jnp.float32),
    "==": lambda a, b: (a == b).astype(jnp.float32),
    "!=": lambda a, b: (a != b).astype(jnp.float32),
    "&": lambda a, b: ((a != 0) & (b != 0)).astype(jnp.float32),
    "|": lambda a, b: ((a != 0) | (b != 0)).astype(jnp.float32),
}

_UNOPS = {
    "abs": jnp.abs, "exp": jnp.exp, "log": jnp.log, "log2": jnp.log2,
    "log10": jnp.log10, "log1p": jnp.log1p, "sqrt": jnp.sqrt,
    "floor": jnp.floor, "ceiling": jnp.ceil, "trunc": jnp.trunc,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "tanh": jnp.tanh,
    "sign": jnp.sign, "not": lambda a: (a == 0).astype(jnp.float32),
    "!!": lambda a: (a == 0).astype(jnp.float32),
    "is.na": lambda a: jnp.isnan(a).astype(jnp.float32),
}


def group_by(fr: Frame, by: Sequence[Union[int, str]],
             aggs: Sequence[Tuple[str, Optional[Union[int, str]]]],
             ) -> Frame:
    """Distributed group-by (water/rapids/ast/prims/mungers AstGroup):
    host factorizes the group keys, the aggregates are device
    segment-sums (one-hot-free jax.ops.segment_sum over sorted ids)."""
    by_names = [fr.names[int(b)] if isinstance(b, (int, float)) else b
                for b in by]
    nrow = fr.nrow
    key_cols = [np.asarray(fr.vec(n).to_numpy()[:nrow]) for n in by_names]
    keys, gid = np.unique(np.stack(key_cols, 1), axis=0, return_inverse=True)
    n_groups = keys.shape[0]
    gid_dev = jnp.asarray(gid.astype(np.int32))
    out_names = list(by_names)
    out_cols: List[np.ndarray] = []
    for j, n in enumerate(by_names):
        v = fr.vec(n)
        if v.type == T_ENUM:
            out_cols.append((keys[:, j], v.domain))
        else:
            out_cols.append((keys[:, j], None))
    for agg, col in aggs:
        if agg in ("nrow", "count"):
            cnt = jax.ops.segment_sum(jnp.ones(nrow), gid_dev, n_groups)
            out_names.append("nrow")
            out_cols.append((np.asarray(jax.device_get(cnt)), None))
            continue
        cn = fr.names[int(col)] if isinstance(col, (int, float)) else col
        x = fr.vec(cn).as_float()[:nrow]
        ok = ~jnp.isnan(x)
        xz = jnp.where(ok, x, 0.0)
        s = jax.ops.segment_sum(xz, gid_dev, n_groups)
        c = jax.ops.segment_sum(ok.astype(jnp.float32), gid_dev, n_groups)
        if agg == "sum":
            r = s
        elif agg == "mean":
            r = s / jnp.maximum(c, 1e-30)
        elif agg in ("min", "max"):
            big = jnp.where(ok, x, jnp.inf if agg == "min" else -jnp.inf)
            r = (jax.ops.segment_min(big, gid_dev, n_groups) if agg == "min"
                 else jax.ops.segment_max(big, gid_dev, n_groups))
        elif agg in ("sdev", "var"):
            s2 = jax.ops.segment_sum(xz * xz, gid_dev, n_groups)
            mean = s / jnp.maximum(c, 1e-30)
            var = jnp.maximum(s2 / jnp.maximum(c, 1e-30) - mean * mean, 0.0)
            var = var * c / jnp.maximum(c - 1, 1e-30)   # sample variance
            r = jnp.sqrt(var) if agg == "sdev" else var
        elif agg == "sumSquares":
            r = jax.ops.segment_sum(xz * xz, gid_dev, n_groups)
        else:
            raise ValueError(f"unsupported group-by aggregate '{agg}'")
        out_names.append(f"{agg}_{cn}")
        out_cols.append((np.asarray(jax.device_get(r)), None))
    vecs = []
    for (vals, domain) in out_cols:
        if domain is not None:
            vecs.append(Vec.from_numpy(vals.astype(np.int32), vtype=T_ENUM,
                                       domain=domain))
        else:
            vecs.append(Vec.from_numpy(np.asarray(vals, dtype=np.float32)))
    return Frame(out_names, vecs)


def merge(left: Frame, right: Frame, by_left: Sequence[str],
          by_right: Sequence[str], all_x: bool = False,
          all_y: bool = False) -> Frame:
    """Join (water/rapids/Merge.java semantics: radix hash join). Inner /
    left / right joins on equal keys; enum keys compare by LABEL."""
    nl, nr = left.nrow, right.nrow

    def key_col(fr, n):
        v = fr.vec(n)
        if v.type == T_ENUM:
            return np.asarray(v.to_strings()[: fr.nrow], dtype=object)
        return np.asarray(v.to_numpy()[: fr.nrow])

    # DEVICE fast path (BinaryMerge.java sorted-run probe): single
    # numeric key, unique right keys, no right-outer — sort + searchsorted
    # on device, only the index gather comes back to host
    if (len(by_left) == 1 and not all_y
            and left.vec(by_left[0]).type in (T_INT, T_REAL)
            and right.vec(by_right[0]).type in (T_INT, T_REAL)
            and getattr(left.vec(by_left[0]), "host_data", None) is None
            and getattr(right.vec(by_right[0]), "host_data", None) is None):
        rvals = np.asarray(right.vec(by_right[0]).to_numpy()[:nr])
        if len(np.unique(rvals[np.isfinite(rvals)])) == np.isfinite(rvals).sum():
            from h2o3_tpu.parallel.sortmerge import join_indices_unique
            ri_dev = join_indices_unique(
                left.vec(by_left[0]).as_float()[:nl],
                right.vec(by_right[0]).as_float()[:nr], nr)
            if all_x:
                li_a = np.arange(nl, dtype=np.int64)
                ri_a = ri_dev.astype(np.int64)
            else:
                keep = ri_dev >= 0
                li_a = np.nonzero(keep)[0].astype(np.int64)
                ri_a = ri_dev[keep].astype(np.int64)
            names = list(left.names) + [n for n in right.names
                                        if n not in by_right]
            vecs = [_take_vec(left.vec(n), li_a, left.nrow)
                    for n in left.names]
            vecs += [_take_vec(right.vec(n), ri_a, right.nrow)
                     for n in right.names if n not in by_right]
            return Frame(names, vecs)

    lk = [key_col(left, n) for n in by_left]
    rk = [key_col(right, n) for n in by_right]
    lkey = list(zip(*lk)) if lk else [()] * nl
    rkey = list(zip(*rk)) if rk else [()] * nr
    rindex: Dict[Any, List[int]] = {}
    for i, k in enumerate(rkey):
        rindex.setdefault(k, []).append(i)
    li: List[int] = []
    ri: List[int] = []
    for i, k in enumerate(lkey):
        hits = rindex.get(k)
        if hits:
            for j in hits:
                li.append(i)
                ri.append(j)
        elif all_x:
            li.append(i)
            ri.append(-1)
    if all_y:
        matched = set(ri)
        for j in range(nr):
            if j not in matched:
                li.append(-1)
                ri.append(j)
    li_a = np.asarray(li, dtype=np.int64)
    ri_a = np.asarray(ri, dtype=np.int64)
    names = list(left.names) + [n for n in right.names if n not in by_right]
    vecs = []
    for n in left.names:
        tv = _take_vec(left.vec(n), li_a, left.nrow)
        if n in by_left and all_y:
            # right-only rows (li=-1) take their key values from the
            # RIGHT frame, not NA (the reference's outer-merge keys)
            rn = by_right[by_left.index(n)]
            rv = _take_vec(right.vec(rn), ri_a, right.nrow)
            tv = _coalesce_vec(tv, rv, li_a < 0)
        vecs.append(tv)
    for n in right.names:
        if n in by_right:
            continue
        vecs.append(_take_vec(right.vec(n), ri_a, right.nrow))
    return Frame(names, vecs)


def _coalesce_vec(primary: Vec, fallback: Vec, use_fallback: np.ndarray) -> Vec:
    label_like = (T_ENUM, T_STR)
    if primary.type in label_like or fallback.type in label_like:
        a = np.asarray(primary.to_strings()[: primary.nrow], dtype=object)
        b = np.asarray(fallback.to_strings()[: fallback.nrow], dtype=object)
        return Vec.from_numpy(np.where(use_fallback, b, a))
    a = np.asarray(primary.to_numpy()[: primary.nrow], dtype=np.float64)
    b = np.asarray(fallback.to_numpy()[: fallback.nrow], dtype=np.float64)
    return Vec.from_numpy(np.where(use_fallback, b, a))


def _take_vec(v: Vec, idx: np.ndarray, nrow: int) -> Vec:
    missing = idx < 0
    safe = np.where(missing, 0, idx)
    if v.type == T_ENUM:
        codes = np.asarray(v.to_numpy()[:nrow]).astype(np.float64)
        out = codes[safe]
        out[missing] = -1
        out[~np.isfinite(out)] = -1
        return Vec.from_numpy(out.astype(np.int32), vtype=T_ENUM,
                              domain=v.domain)
    if v.type == T_STR:
        vals = np.asarray(v.to_strings()[:nrow], dtype=object)
        out = vals[safe]
        out[missing] = None
        return Vec.from_numpy(out)
    # float64 all the way: Vec.from_numpy keeps exact host copies for
    # wide ints and re-detects the type; float32 would corrupt timestamps
    # and >2^24 IDs
    from h2o3_tpu.frame.vec import T_TIME
    if v.type == T_TIME and getattr(v, "host_data", None) is not None:
        raw = np.asarray(v.host_data[:nrow], dtype=np.int64)
        out = raw[safe]
        out[missing] = Vec.TIME_NA
        return Vec.from_numpy(out, vtype=T_TIME)
    vals = np.asarray(v.to_numpy()[:nrow], dtype=np.float64)
    out = vals[safe]
    out[missing] = np.nan
    return Vec.from_numpy(out)


def sort_frame(fr: Frame, cols: Sequence[Union[int, str]],
               ascending: Optional[Sequence[int]] = None) -> Frame:
    """Sort (water/rapids/Merge.java sort → RadixOrder). Numeric keys
    sort ON DEVICE: single-key multi-shard goes through the distributed
    radix all_to_all exchange (parallel/sortmerge.py); multi-key uses
    the device lexsort. Strings fall back to host lexsort."""
    names = [fr.names[int(c)] if isinstance(c, (int, float)) else c
             for c in cols]
    nrow = fr.nrow
    asc = list(ascending) if ascending else [1] * len(names)
    numeric = all(fr.vec(n).type in (T_INT, T_REAL, "time", T_ENUM)
                  for n in names)
    # f32-exactness guard: keys wider than the f32 mantissa (big IDs,
    # epoch millis) would collide in the bit-pattern sort
    if numeric:
        for n in names:
            v = fr.vec(n)
            if getattr(v, "host_data", None) is not None:
                numeric = False
                break
    if numeric and names:
        from h2o3_tpu.parallel.sortmerge import (distributed_argsort,
                                                 lexsort_device)
        from h2o3_tpu.parallel.mesh import current_mesh, n_data_shards
        key_dev = [fr.vec(n).as_float()[:nrow] for n in names]
        if len(names) == 1 and asc[0] and n_data_shards(current_mesh()) > 1:
            order = distributed_argsort(key_dev[0])
        else:
            order = np.asarray(jax.device_get(
                lexsort_device(key_dev, asc)))
    else:
        keys = []
        for n, a in zip(reversed(names), reversed(asc)):
            col = np.asarray(fr.vec(n).to_numpy()[:nrow])
            keys.append(col if a else -col)
        order = np.lexsort(keys) if keys else np.arange(nrow)
    return fr.rows_by_index(order) if hasattr(fr, "rows_by_index") else \
        _take_frame(fr, order)


def _take_frame(fr: Frame, idx: np.ndarray) -> Frame:
    return Frame(list(fr.names),
                 [_take_vec(fr.vec(n), np.asarray(idx, np.int64), fr.nrow)
                  for n in fr.names])


# ---------------- interpreter ------------------------------------------

def _eval(node, env: Env):
    kind, val = node
    if kind == "num":
        return val
    if kind == "str":
        return val
    if kind == "slice":
        return val
    if kind == "id":
        if val in ("TRUE", "True"):
            return 1.0
        if val in ("FALSE", "False"):
            return 0.0
        if val in ("NA", "NaN", "nan"):
            return float("nan")
        return env.lookup(val)
    if kind == "list":
        return [_eval(c, env) for c in val]
    assert kind == "call"
    op_node = val[0]
    op = op_node[1] if op_node[0] in ("id",) else _eval(op_node, env)
    args = val[1:]
    return _apply(op, args, env)


def _sel_indices(sel, n: int, names: Optional[List[str]] = None) -> np.ndarray:
    if isinstance(sel, Slice):
        return sel.resolve(n)
    if isinstance(sel, (int, float)):
        return np.asarray([int(sel)])
    if isinstance(sel, str):
        return np.asarray([names.index(sel)])
    if isinstance(sel, list):
        if sel and isinstance(sel[0], str):
            return np.asarray([names.index(s) for s in sel])
        out = []
        for s in sel:
            out.extend(_sel_indices(s, n, names).tolist())
        return np.asarray(out, dtype=np.int64)
    raise ValueError(f"bad selector {sel!r}")


def _apply(op: str, args, env: Env):
    ev = lambda i: _eval(args[i], env)  # noqa: E731

    if op == "tmp=":
        name = args[0][1]
        valr = _eval(args[1], env)
        if isinstance(valr, Frame):
            dkv.put(name, "frame", valr)
        return valr
    if op == "rm":
        dkv.remove(args[0][1])
        return 1.0
    if op == "ls":
        # AstLs (ast/prims/misc/AstLs.java): frame of DKV keys
        keys = sorted(dkv.keys())
        return Frame(["key"], [Vec.from_numpy(
            np.asarray(keys, dtype=object), vtype=T_STR)])
    if op == ":=":
        # AstRectangleAssign (ast/prims/assign/AstRectangleAssign.java):
        # (:= dst src col_expr row_expr) -> new frame with the rectangle
        # overwritten; src is a frame, scalar, or string; [] rows = all
        dst = ev(0)
        src = _eval(args[1], env)
        cols = _eval(args[2], env)
        rows = _eval(args[3], env) if len(args) > 3 else []
        cidx = _sel_indices(cols, dst.ncol, dst.names)
        if isinstance(rows, Frame):
            rmask = np.asarray(rows.vec(0).to_numpy()[: dst.nrow]) != 0
            ridx = np.nonzero(rmask)[0]
        elif rows in ([], None):
            ridx = None                       # all rows
        else:
            ridx = _sel_indices(rows, dst.nrow)
        new_vecs = [dst.vec(i) for i in range(dst.ncol)]
        for j, ci in enumerate(cidx):
            ci = int(ci)
            if isinstance(src, Frame):
                sv = src.vec(min(j, src.ncol - 1))
                if ridx is None:
                    new_vecs[ci] = sv
                    continue
                sarr = np.asarray(sv.to_numpy(), dtype=np.float64)
                dom = sv.domain
            else:
                if isinstance(src, str):
                    old = new_vecs[ci]
                    dom = list(old.domain or [])
                    if src not in dom:
                        dom.append(src)
                    code = float(dom.index(src))
                    sarr = np.full(dst.nrow if ridx is None else len(ridx),
                                   code)
                else:
                    sarr = np.full(dst.nrow if ridx is None else len(ridx),
                                   np.nan if src is None else float(src))
                    dom = new_vecs[ci].domain
            darr = np.asarray(new_vecs[ci].to_numpy(),
                              dtype=np.float64).copy()
            if ridx is None:
                darr[:] = sarr[: len(darr)]
            else:
                darr[ridx] = (sarr[: len(ridx)] if np.ndim(sarr) else sarr)
            if dom:
                codes = np.where(np.isfinite(darr), darr, -1).astype(np.int32)
                new_vecs[ci] = Vec.from_numpy(codes, vtype=T_ENUM,
                                              domain=[str(d) for d in dom])
            else:
                new_vecs[ci] = Vec.from_numpy(darr)
        return Frame(list(dst.names), new_vecs)
    if op == "append":
        # AstAppend: (append dst src colName)+ -> frame with new columns
        dst = ev(0)
        names = list(dst.names)
        vecs = [dst.vec(i) for i in range(dst.ncol)]
        i = 1
        while i + 1 < len(args):
            src = _eval(args[i], env)
            cname = _eval(args[i + 1], env)
            if isinstance(src, Frame):
                v = src.vec(0)
            else:
                arr = np.full(dst.nrow,
                              np.nan if src is None else float(src))
                v = Vec.from_numpy(arr)
            if cname in names:
                vecs[names.index(cname)] = v
            else:
                names.append(str(cname))
                vecs.append(v)
            i += 2
        return Frame(names, vecs)
    if op in _BINOPS:
        return _map_elementwise(_BINOPS[op], ev(0), ev(1))
    if op in _UNOPS:
        return _map_elementwise(_UNOPS[op], ev(0))
    if op == "cols_py" or op == "cols":
        fr = ev(0)
        sel = ev(1)
        idx = _sel_indices(sel, fr.ncol, fr.names)
        if len(idx) and (idx < 0).all():
            # h2o-py drop-column encoding: -(i+1) means drop column i
            dropped = {-int(i) - 1 for i in idx}
            idx = np.asarray([i for i in range(fr.ncol) if i not in dropped])
        names = [fr.names[i] for i in idx]
        return Frame(names, [fr.vec(int(i)) for i in idx])
    if op == "rows":
        fr = ev(0)
        sel = ev(1)
        if isinstance(sel, Frame):       # boolean mask frame
            mask = np.asarray(sel.vec(0).to_numpy()[: fr.nrow]) != 0
            idx = np.nonzero(mask)[0]
        else:
            idx = _sel_indices(sel, fr.nrow)
        return _take_frame(fr, idx)
    if op in ("mean", "median"):
        # frame-valued reducers (water/rapids/ast/prims/reducers/AstMean.java,
        # AstMedian.java): (op frame na_rm axis) -> [1 x ncols] frame
        # (axis=0) or [nrows x 1] frame (axis=1); enum/string columns -> NA
        fr = ev(0)
        na_rm = bool(_eval(args[1], env)) if len(args) > 1 else True
        axis = int(_eval(args[2], env) or 0) if len(args) > 2 else 0
        fn = ((lambda x, ok: jnp.where(ok, x, 0).sum() / ok.sum())
              if op == "mean" else (lambda x, ok: jnp.median(x[ok])))
        if axis == 1:
            num = [i for i in range(fr.ncol)
                   if fr.vec(i).type in (T_INT, T_REAL)]
            mat = np.stack([np.asarray(fr.vec(i).to_numpy(),
                                       dtype=np.float64) for i in num])
            ok = np.isfinite(mat)
            if op == "mean":
                s = np.where(ok, mat, 0).sum(axis=0)
                c = ok.sum(axis=0)
                vals = np.where(c > 0, s / np.maximum(c, 1), np.nan)
            else:
                vals = np.array([np.median(col[okc]) if okc.any() else np.nan
                                 for col, okc in zip(mat.T, ok.T)])
            if not na_rm:
                vals = np.where(ok.all(axis=0), vals, np.nan)
            return Frame([op], [Vec.from_numpy(vals.astype(np.float64))])
        vals = []
        for i in range(fr.ncol):
            v = fr.vec(i)
            if v.type not in (T_INT, T_REAL):
                vals.append(np.nan)
                continue
            x = np.asarray(v.to_numpy(), dtype=np.float64)
            ok = np.isfinite(x)
            if not ok.any() or (not na_rm and not ok.all()):
                vals.append(np.nan)
            else:
                vals.append(float(fn(jnp.asarray(x), jnp.asarray(ok))))
        return Frame(list(fr.names),
                     [Vec.from_numpy(np.asarray([val], dtype=np.float64))
                      for val in vals])
    if op == "getrow":
        # AstGetrow: single-row frame -> row of numbers
        fr = ev(0)
        if fr.nrow != 1:
            raise ValueError(f"getrow requires a 1-row frame, got {fr.nrow}")
        out = []
        for i in range(fr.ncol):
            val = fr.vec(i).to_numpy()[0]
            val = float(val)
            out.append(None if not math.isfinite(val) else val)
        return out
    if op in ("sum", "min", "max", "sd", "sdev", "nrow", "ncol"):
        fr = ev(0)
        if op == "nrow":
            return float(fr.nrow)
        if op == "ncol":
            return float(fr.ncol)
        na_rm = bool(_eval(args[1], env)) if len(args) > 1 else True
        fns = {
            "sum": lambda x, ok: jnp.where(ok, x, 0).sum(),
            "min": lambda x, ok: jnp.where(ok, x, jnp.inf).min(),
            "max": lambda x, ok: jnp.where(ok, x, -jnp.inf).max(),
            "sd": _sd_fn, "sdev": _sd_fn,
        }
        out = _reduce(fns[op], fr, na_rm)
        return out
    if op == "GB":
        fr = ev(0)
        by = ev(1)
        rest = [_eval(a, env) for a in args[2:]]
        aggs = []
        for i in range(0, len(rest), 3):
            agg = rest[i]
            col = rest[i + 1] if rest[i + 1] != [] else None
            aggs.append((agg, col))
        return group_by(fr, by if isinstance(by, list) else [by], aggs)
    if op == "merge":
        left, right = ev(0), ev(1)
        all_x, all_y = bool(ev(2)), bool(ev(3))
        by_x, by_y = ev(4), ev(5)
        if not by_x:
            common = [n for n in left.names if n in right.names]
            bx = by_ = common
        else:
            bx = [left.names[int(i)] for i in by_x]
            by_ = [right.names[int(i)] for i in by_y]
        return merge(left, right, bx, by_, all_x, all_y)
    if op == "sort":
        fr = ev(0)
        cols = ev(1)
        asc = ev(2) if len(args) > 2 else None
        return sort_frame(fr, cols if isinstance(cols, list) else [cols],
                          asc)
    if op == "cbind":
        frames = [_eval(a, env) for a in args]
        names, vecs = [], []
        for f in frames:
            for n in f.names:
                nm = n
                k = 1
                while nm in names:
                    nm = f"{n}{k}"
                    k += 1
                names.append(nm)
                vecs.append(f.vec(n))
        return Frame(names, vecs)
    if op == "rbind":
        frames = [_eval(a, env) for a in args]
        base = frames[0]
        vecs = []
        for n in base.names:
            vt = base.vec(n).type
            if vt in (T_ENUM, T_STR):
                # labels, not codes: domains may differ across frames
                parts = [np.asarray(f.vec(n).to_strings()[: f.nrow],
                                    dtype=object) for f in frames]
                vecs.append(Vec.from_numpy(np.concatenate(parts)))
            else:
                parts = [np.asarray(f.vec(n).to_numpy()[: f.nrow],
                                    dtype=np.float64) for f in frames]
                vecs.append(Vec.from_numpy(np.concatenate(parts)))
        return Frame(list(base.names), vecs)
    if op == "ifelse":
        cond, yes, no = ev(0), ev(1), ev(2)
        def sel3(c, a, b):
            return jnp.where(c != 0, a, b)
        if isinstance(cond, Frame):
            a = yes.vec(0).as_float() if isinstance(yes, Frame) else yes
            b = no.vec(0).as_float() if isinstance(no, Frame) else no
            out = sel3(cond.vec(0).as_float(), a, b)
            return Frame(["C1"], [Vec.from_numpy(
                np.asarray(jax.device_get(out))[: cond.nrow]
                .astype(np.float32))])
        return yes if cond else no
    if op == "unique":
        fr = ev(0)
        nrow = fr.nrow
        v = fr.vec(0)
        if v.type in (T_ENUM, T_STR):
            labs = [s for s in v.to_strings()[:nrow] if s is not None]
            vals = np.unique(np.asarray(labs, dtype=object))
            return Frame([fr.names[0]], [Vec.from_numpy(vals)])
        vals = np.unique(np.asarray(v.to_numpy()[:nrow], dtype=np.float64))
        vals = vals[np.isfinite(vals)]
        return Frame([fr.names[0]], [Vec.from_numpy(vals)])
    if op == "colnames=":
        fr = ev(0)
        sel = ev(1)
        names = ev(2)
        names = names if isinstance(names, list) else [names]
        idx = _sel_indices(sel, fr.ncol, fr.names)
        new_names = list(fr.names)
        for i, nm in zip(idx, names):
            new_names[int(i)] = nm
        return Frame(new_names, list(fr.vecs))
    if op in ("is.factor", "is.numeric", "is.character", "anyfactor"):
        # AstIsFactor/AstIsNumeric/AstIsCharacter/AstAnyFactor: per-column
        # 0/1 flags (single value for 1-col frames)
        fr = ev(0)
        tests = {"is.factor": lambda v: v.type == T_ENUM,
                 "is.numeric": lambda v: v.type in (T_INT, T_REAL),
                 "is.character": lambda v: v.type == T_STR}
        if op == "anyfactor":
            return float(any(fr.vec(i).type == T_ENUM
                             for i in range(fr.ncol)))
        # always a list: h2o-py iterates the result (frame.py isfactor)
        return [float(tests[op](fr.vec(i))) for i in range(fr.ncol)]
    if op == "levels":
        # AstLevels: domain values as a [card x ncol] string frame
        fr = ev(0)
        cols = []
        maxlen = max([len(fr.vec(i).domain or []) for i in range(fr.ncol)]
                     or [0])
        for i in range(fr.ncol):
            dom = list(fr.vec(i).domain or [])
            dom += [""] * (maxlen - len(dom))
            cols.append(Vec.from_numpy(np.asarray(dom, dtype=object)))
        return Frame(list(fr.names), cols)
    if op == "as.factor" or op == "asfactor":
        fr = ev(0)
        return Frame(list(fr.names), [fr.vec(n).asfactor() for n in fr.names])
    if op == "as.numeric" or op == "asnumeric":
        fr = ev(0)
        return Frame(list(fr.names),
                     [fr.vec(n).asnumeric() for n in fr.names])
    # ---- string prims (water/rapids/ast/prims/string) ------------------
    if op in ("tolower", "toupper", "trim", "nchar"):
        fr = ev(0)
        v = fr.vec(0)
        ss = list(v.to_strings()[: fr.nrow])
        if op == "nchar":
            arr = np.asarray([np.nan if s is None else float(len(s))
                              for s in ss])
            return Frame([fr.names[0]], [Vec.from_numpy(arr)])
        f = {"tolower": str.lower, "toupper": str.upper,
             "trim": str.strip}[op]
        out = np.asarray([None if s is None else f(s) for s in ss],
                         dtype=object)
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    if op in ("replacefirst", "replaceall", "sub", "gsub"):
        # reference arg order is FRAME-first: (replaceall x pattern
        # replacement ignore_case) — h2o-py H2OFrame.gsub emits
        # ExprNode("replaceall", self, pattern, replacement, ...)
        import re as _re
        fr, pat, rep = ev(0), ev(1), ev(2)
        ignore = bool(_eval(args[3], env)) if len(args) > 3 else False
        rx = _re.compile(pat, _re.IGNORECASE if ignore else 0)
        count = 1 if op in ("sub", "replacefirst") else 0
        ss = list(fr.vec(0).to_strings()[: fr.nrow])
        out = np.asarray([None if s is None else rx.sub(rep, s, count)
                          for s in ss], dtype=object)
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    if op == "substring":
        fr, start = ev(0), int(ev(1))
        end = int(ev(2)) if len(args) > 2 else None
        ss = list(fr.vec(0).to_strings()[: fr.nrow])
        out = np.asarray([None if s is None else s[start:end]
                          for s in ss], dtype=object)
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    # ---- time prims (ast/prims/time; values = epoch millis) ------------
    if op in ("year", "month", "day", "hour", "minute", "second",
              "dayOfWeek", "week"):
        fr = ev(0)
        v0 = fr.vec(0)
        ms = np.asarray(v0.to_numpy()[: fr.nrow], np.float64)
        # T_TIME NAs arrive as the int64-min sentinel, which IS finite
        # in float — mask it explicitly alongside NaN
        ok = np.isfinite(ms) & (np.abs(ms) < 4e17)  # |ms| < year ~14000
        dt = ms[ok].astype("datetime64[ms]")
        y = dt.astype("datetime64[Y]")
        mth = dt.astype("datetime64[M]")
        dd = dt.astype("datetime64[D]")
        if op == "week":
            # ISO week-of-weekyear (reference AstWeek getWeekOfWeekyear):
            # the ISO week of a date equals the ordinal week of its
            # Thursday within the Thursday's calendar year
            day_i = dd.astype(int)
            dow = (day_i + 3) % 7                      # Mon=0
            thursday = (day_i - dow + 3).astype("datetime64[D]")
            ty = thursday.astype("datetime64[Y]")
            vals = ((thursday - ty.astype("datetime64[D]")).astype(int)
                    // 7 + 1)
        else:
            vals = {
                "year": y.astype(int) + 1970,
                "month": (mth - y.astype("datetime64[M]")).astype(int) + 1,
                "day": (dd - mth.astype("datetime64[D]")).astype(int) + 1,
                "hour": (dt.astype("datetime64[h]")
                         - dd.astype("datetime64[h]")).astype(int),
                "minute": (dt.astype("datetime64[m]").astype(int) % 60),
                "second": (dt.astype("datetime64[s]").astype(int) % 60),
                # reference domain Mon=0 (AstDayOfWeek); epoch day 0 = Thu
                "dayOfWeek": (dd.astype(int) + 3) % 7,
            }[op]
        out = np.full(len(ms), np.nan)
        out[ok] = vals.astype(np.float64)
        return Frame([fr.names[0]], [Vec.from_numpy(out)])
    # ---- misc prims ----------------------------------------------------
    if op == "table":
        fr = ev(0)
        v = fr.vec(0)
        if v.type in (T_ENUM, T_STR):
            labs = [s for s in v.to_strings()[: fr.nrow] if s is not None]
            vals, cnt = np.unique(np.asarray(labs, dtype=object),
                                  return_counts=True)
            return Frame([fr.names[0], "Count"],
                         [Vec.from_numpy(vals),
                          Vec.from_numpy(cnt.astype(np.float64))])
        d = np.asarray(v.to_numpy()[: fr.nrow], np.float64)
        vals, cnt = np.unique(d[np.isfinite(d)], return_counts=True)
        return Frame([fr.names[0], "Count"],
                     [Vec.from_numpy(vals),
                      Vec.from_numpy(cnt.astype(np.float64))])
    if op == "cor":
        a, b = ev(0), ev(1)
        x = np.asarray(a.vec(0).to_numpy()[: a.nrow], np.float64)
        yv = np.asarray(b.vec(0).to_numpy()[: b.nrow], np.float64)
        ok = np.isfinite(x) & np.isfinite(yv)
        return float(np.corrcoef(x[ok], yv[ok])[0, 1])
    if op in ("round", "signif"):
        fr = ev(0)
        digits = int(ev(1)) if len(args) > 1 else 0
        def rnd(col):
            if op == "round":
                return np.round(col, digits)
            with np.errstate(all="ignore"):
                mag = np.where(col != 0, np.floor(np.log10(np.abs(col))),
                               0)
                f = 10.0 ** (digits - 1 - mag)
                return np.round(col * f) / f
        return Frame(list(fr.names),
                     [Vec.from_numpy(rnd(np.asarray(
                         fr.vec(n).to_numpy()[: fr.nrow], np.float64)))
                      for n in fr.names])
    if op in ("cumsum", "cumprod", "cummin", "cummax"):
        fr = ev(0)
        f = {"cumsum": np.cumsum, "cumprod": np.cumprod,
             "cummin": np.minimum.accumulate,
             "cummax": np.maximum.accumulate}[op]
        return Frame(list(fr.names),
                     [Vec.from_numpy(f(np.asarray(
                         fr.vec(n).to_numpy()[: fr.nrow], np.float64)))
                      for n in fr.names])
    if op == "which":
        fr = ev(0)
        d = np.asarray(fr.vec(0).to_numpy()[: fr.nrow])
        return Frame(["C1"],
                     [Vec.from_numpy(np.flatnonzero(
                         np.nan_to_num(d) != 0).astype(np.float64))])
    if op == "na.omit":
        fr = ev(0)
        keep = np.ones(fr.nrow, bool)
        for n in fr.names:
            v = fr.vec(n)
            if v.type in (T_ENUM, T_STR):
                keep &= np.asarray(
                    [s is not None for s in v.to_strings()[: fr.nrow]])
            else:
                keep &= np.isfinite(np.asarray(
                    v.to_numpy()[: fr.nrow], np.float64))
        return _take_frame(fr, np.flatnonzero(keep))
    if op == "scale":
        fr = ev(0)
        center = bool(_eval(args[1], env)) if len(args) > 1 else True
        scale_ = bool(_eval(args[2], env)) if len(args) > 2 else True
        vecs = []
        for n in fr.names:
            d = np.asarray(fr.vec(n).to_numpy()[: fr.nrow], np.float64)
            ok = np.isfinite(d)
            m = d[ok].mean() if center and ok.any() else 0.0
            s = d[ok].std(ddof=1) if scale_ and ok.sum() > 1 else 1.0
            vecs.append(Vec.from_numpy((d - m) / (s or 1.0)))
        return Frame(list(fr.names), vecs)
    raise ValueError(f"unsupported rapids op '{op}'")


def _sd_fn(x, ok):
    n = ok.sum()
    m = jnp.where(ok, x, 0).sum() / n
    return jnp.sqrt(jnp.where(ok, (x - m) ** 2, 0).sum()
                    / jnp.maximum(n - 1, 1))


def exec_rapids(ast: str, session_id: Optional[str] = None) -> Dict:
    """Execute an AST string, REST-shaped result (RapidsSchemaV3:
    {key} for frames, {scalar}, {string}, {map_keys, string_pairs}…)."""
    node = parse_rapids(ast)
    env = Env(session_id)
    result = _eval(node, env)
    if isinstance(result, Frame):
        # anonymous results need a key the client can address
        key = None
        if node[0] == "call" and node[1][0][1] == "tmp=":
            key = node[1][1][1]
        if key is None:
            key = dkv.unique_key("rapids_frame")
            dkv.put(key, "frame", result)
        return {"__meta": {"schema_version": 3,
                           "schema_name": "RapidsFrameV3"},
                "key": {"name": key}, "num_rows": result.nrow,
                "num_cols": result.ncol}
    if isinstance(result, str):
        return {"string": result}
    if isinstance(result, list):
        return {"scalar": result}
    return {"scalar": None if (isinstance(result, float)
                               and math.isnan(result)) else result}
