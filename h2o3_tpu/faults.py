"""Deterministic fault injection — the chaos seam for resilience tests.

Reference: h2o-3 has no first-class fault injection; its robustness
surface is the cloud runtime's heartbeats + job supervision (SURVEY
L1/L2). This rebuild gets the production substrate those provide by
making failure REPRODUCIBLE: seeded, countable failure points threaded
through the transfer paths (H2D/D2H), the XLA compile/execute call
sites, persist reads and the serve batcher's device stage, so the
retry/degrade/circuit machinery can be asserted by tests and the chaos
bench instead of waited for in production.

Spec grammar (``H2O3_FAULTS`` env var or ``POST /3/Faults?spec=...``)::

    H2O3_FAULTS="site[@pipeline]:every=N[:exc=Name][:times=M][:after=K][:key=K],..."

- ``site``      — one of the instrumented points: ``h2d``, ``d2h``,
                  ``compile``, ``execute``, ``persist``, ``collective``
                  (the ICI histogram-psum seam — checked at the train
                  chunk dispatch whenever the mesh has >1 data shard),
                  ``boot`` (the restart-recovery resume path — checked
                  per manifest in recovery.recover_at_boot; an injected
                  boot fault must WARN and continue, never wedge
                  startup — tests/test_restart_recovery.py)
                  (free-form strings; unknown sites simply never fire).
- ``@pipeline`` — optional filter on the calling pipeline label
                  (``ingest``/``train``/``serve``); omitted = any.
- ``every=N``   — fire on every Nth matching check (the Nth, 2Nth, …).
- ``exc=Name``  — exception class: ``Unavailable`` (default, transient),
                  ``Internal``, ``DataLoss`` (transient),
                  ``ResourceExhausted`` (device OOM — NOT retried, it
                  triggers graceful degradation), ``Fatal`` (kills the
                  job — the mid-train-kill probe), ``IOError``.
- ``times=M``   — fire at most M times, then the rule is exhausted.
- ``after=K``   — skip the first K matching checks before counting.
- ``key=K``     — fire only for a matching object key (e.g. one serve
                  deployment), leaving other traffic healthy.

Gating idiom matches ``H2O3_TELEMETRY=0``: call sites guard with
``if faults.ACTIVE: faults.check(...)`` — when no spec is configured
the whole machinery is ONE module-attribute load + branch (asserted by
tests/test_resilience.py's no-op budget guard, same method as the PR-4
telemetry overhead guard).

Every fired fault increments ``h2o3_fault_injected_total{site=...}`` so
chaos rounds can account exactly for what they injected.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional


# ---------------- injected exception taxonomy --------------------------
#
# Messages carry the grpc/XLA status-code spellings (RESOURCE_EXHAUSTED,
# UNAVAILABLE, …) so the message-marker classifier in resilience.py
# treats injected faults exactly like the real XlaRuntimeError ones.

class InjectedFault(RuntimeError):
    """Base for every injected failure (lets tests and the chaos bench
    distinguish injected from organic errors)."""


class Unavailable(InjectedFault):
    """Transient device/transfer hiccup — retryable."""


class Internal(InjectedFault):
    """Transient internal runtime error — retryable."""


class DataLoss(InjectedFault):
    """Transient corrupted-transfer error — retryable."""


class ResourceExhausted(InjectedFault):
    """Device OOM — NOT retryable; triggers dense→streamed degrade."""


class Fatal(InjectedFault):
    """Unrecoverable failure — neither retried nor degraded (the
    mid-train-kill probe for checkpoint/resume tests)."""


class InjectedIOError(InjectedFault, IOError):
    """Flaky-storage read failure — retried by the persist layer."""


# ---------------- site registry ----------------------------------------
#
# The instrumented failure points. Sites are free-form strings at the
# matching layer (unknown spec sites simply never fire), but every
# site CHECKED in code must be registered here and every registered
# site must be checked somewhere — enforced by h2o3-lint's fault-seam
# rule, so a typo'd site can't silently punch a hole in chaos coverage
# and a dead registry entry can't make a chaos spec target nothing.
KNOWN_SITES = frozenset({
    "h2d",          # host→device transfers (resilience.resilient_*)
    "d2h",          # device→host fetches (telemetry.device_get)
    "compile",      # XLA executable build (train chunk dispatch)
    "execute",      # device execution (train chunk + serve batch)
    "collective",   # ICI histogram psum (multi-shard train dispatch)
    "persist",      # storage reads (persist.load_model, URI cache)
    "boot",         # restart-recovery resume (recovery.recover_at_boot)
    "decompress",   # compressed-ingest inflate (ingest/compress.py)
})


_EXC_BY_NAME = {
    "unavailable": (Unavailable, "UNAVAILABLE: injected fault"),
    "internal": (Internal, "INTERNAL: injected fault"),
    "dataloss": (DataLoss, "DATA_LOSS: injected fault"),
    "resourceexhausted": (ResourceExhausted,
                          "RESOURCE_EXHAUSTED: injected device OOM"),
    "oom": (ResourceExhausted, "RESOURCE_EXHAUSTED: injected device OOM"),
    "fatal": (Fatal, "FATAL: injected kill"),
    "ioerror": (InjectedIOError, "IO error: injected flaky storage"),
}


class _Rule:
    __slots__ = ("site", "pipeline", "key", "every", "times", "after",
                 "exc_cls", "exc_msg", "seen", "fired")

    def __init__(self, site: str, pipeline: Optional[str],
                 key: Optional[str], every: int, times: Optional[int],
                 after: int, exc_name: str):
        self.site = site
        self.pipeline = pipeline
        self.key = key
        self.every = max(int(every), 1)
        self.times = times          # None = unlimited
        self.after = max(int(after), 0)
        if exc_name.lower() not in _EXC_BY_NAME:
            # a typo'd exc= must not silently become a different fault
            # class — a chaos probe for OOM-degrade would then exercise
            # the retry path and report the wrong machinery as covered
            raise ValueError(
                f"unknown fault exc '{exc_name}' (one of "
                f"{sorted(_EXC_BY_NAME)})")
        cls, msg = _EXC_BY_NAME[exc_name.lower()]
        self.exc_cls = cls
        self.exc_msg = msg
        self.seen = 0               # matching checks observed
        self.fired = 0              # faults actually raised

    def matches(self, site: str, pipeline: Optional[str],
                key: Optional[str]) -> bool:
        if self.site != site:
            return False
        if self.pipeline is not None and self.pipeline != pipeline:
            return False
        if self.key is not None and self.key != key:
            return False
        return True

    def should_fire(self) -> bool:
        """Advance the deterministic counter; True when this check is a
        firing one. Caller holds the module lock."""
        if self.times is not None and self.fired >= self.times:
            return False
        self.seen += 1
        n = self.seen - self.after
        if n <= 0:
            return False
        if n % self.every != 0:
            return False
        self.fired += 1
        return True

    def describe(self) -> Dict[str, object]:
        return {"site": self.site, "pipeline": self.pipeline,
                "key": self.key, "every": self.every,
                "times": self.times, "after": self.after,
                "exc": self.exc_cls.__name__,
                "seen": self.seen, "fired": self.fired}


def _parse(spec: str) -> List[_Rule]:
    rules: List[_Rule] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        head = fields[0]
        pipeline = None
        if "@" in head:
            head, pipeline = head.split("@", 1)
        kw = {"every": 1, "times": None, "after": 0, "exc": "unavailable",
              "key": None}
        for f in fields[1:]:
            if "=" not in f:
                raise ValueError(
                    f"bad fault clause '{f}' in '{part}' — expected "
                    f"key=value")
            k, v = f.split("=", 1)
            k = k.strip().lower()
            if k not in kw:
                raise ValueError(f"unknown fault option '{k}' in '{part}'")
            kw[k] = v
        rules.append(_Rule(
            head.strip(), pipeline.strip() if pipeline else None,
            kw["key"], int(kw["every"]),
            None if kw["times"] is None else int(kw["times"]),
            int(kw["after"]), str(kw["exc"])))
    return rules


# ---------------- module state -----------------------------------------

# ACTIVE is the call-site gate: None when no spec is configured, so the
# unset-path cost is one attribute load + branch (H2O3_TELEMETRY idiom).
ACTIVE: Optional[List[_Rule]] = None
_SPEC: Optional[str] = None
_LOCK = threading.Lock()


def configure(spec: Optional[str]) -> None:
    """(Re)configure fault injection from a spec string; ``None`` or an
    empty string disables it and restores the checked-no-op path."""
    global ACTIVE, _SPEC
    if not spec:
        with _LOCK:
            ACTIVE = None
            _SPEC = None
        return
    rules = _parse(spec)            # validate BEFORE swapping in
    with _LOCK:
        ACTIVE = rules if rules else None
        _SPEC = spec if rules else None


def spec() -> Optional[str]:
    return _SPEC


def describe() -> List[Dict[str, object]]:
    with _LOCK:
        return [r.describe() for r in (ACTIVE or [])]


def check(site: str, pipeline: Optional[str] = None,
          key: Optional[str] = None) -> None:
    """Raise the configured exception when a rule for this site fires.

    Call sites MUST pre-gate with ``if faults.ACTIVE:`` so the unset
    path never enters this function."""
    rules = ACTIVE
    if rules is None:
        return
    with _LOCK:
        fire = None
        for r in rules:
            if r.matches(site, pipeline, key) and r.should_fire():
                fire = r
                break
    if fire is None:
        return
    from h2o3_tpu import telemetry
    telemetry.counter(
        "h2o3_fault_injected_total", {"site": site},
        help="faults raised by the injection layer").inc()
    try:
        from h2o3_tpu.telemetry import blackbox
        blackbox.record("fault_fired", member=str(key or site),
                        payload=f"site={site}"
                                + (f"@{pipeline}" if pipeline else "")
                                + f" exc={fire.exc_cls.__name__}")
    except Exception:   # noqa: BLE001 — flight recorder is advisory
        pass
    from h2o3_tpu.log import warn
    warn("fault injected at %s%s: %s", site,
         f"@{pipeline}" if pipeline else "", fire.exc_cls.__name__)
    raise fire.exc_cls(
        f"{fire.exc_msg} (site={site}"
        + (f"@{pipeline}" if pipeline else "") + ")")


def fired_total() -> int:
    with _LOCK:
        return sum(r.fired for r in (ACTIVE or []))


# env configuration at import (the bench/chaos tool path); REST can
# reconfigure at runtime via POST /3/Faults. A malformed env spec must
# not poison `import h2o3_tpu` (every other H2O3_* knob parses
# defensively) — warn and run without injection instead.
try:
    configure(os.environ.get("H2O3_FAULTS"))
except ValueError as _e:
    import warnings
    warnings.warn(f"ignoring malformed H2O3_FAULTS: {_e}")
