"""Training scheduler (ISSUE 15): priority run queues between the
REST/Job layer and the model builders, device-memory-aware admission,
checkpoint-based preemption. See sched/core.py for the design."""
from h2o3_tpu.sched.admission import (Estimate,  # noqa: F401
                                      admission_headroom,
                                      estimate_submission)
from h2o3_tpu.sched.core import (BACKGROUND, BULK,  # noqa: F401
                                 CHECKPOINTABLE_ALGOS, INTERACTIVE,
                                 PRIORITY_LEVELS, PRIORITY_NAMES, Entry,
                                 Scheduler, SchedulerSaturatedError,
                                 context_priority, context_share,
                                 enabled, in_scheduled_run, inline_run,
                                 reset, scheduler, submit_context)

__all__ = [
    "BACKGROUND", "BULK", "INTERACTIVE", "CHECKPOINTABLE_ALGOS",
    "PRIORITY_LEVELS", "PRIORITY_NAMES", "Entry", "Estimate",
    "Scheduler", "SchedulerSaturatedError", "admission_headroom",
    "context_priority", "context_share", "enabled",
    "estimate_submission",
    "in_scheduled_run", "inline_run", "reset", "scheduler",
    "submit_context",
]
