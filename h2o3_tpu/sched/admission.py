"""Device-memory admission estimates for the training scheduler.

Reference: water/MemoryManager.java's allocation gate blocks a request
until heap is available; H2O's FJ ladder then keeps the node from
accepting more concurrent work than it can hold. Here the gate moves
BEFORE dispatch: a train's device footprint is estimated from what the
platform already knows and the scheduler only releases the entry when
the memman budget holds it — an oversubscribed submission WAITS in the
queue with a reason instead of allocating, OOMing, or silently
degrading a peer.

Estimate provenance (recorded on the entry and on /3/Scheduler):

- ``costmodel+shape`` — a cached executable exists for the algo's chunk
  seam (telemetry/costmodel.py): its per-iteration HBM bytes-accessed
  bound the resident working set from above. The hint is clamped to
  [1x, 4x] of the shape estimate so a stale cache entry from a much
  larger train cannot starve admission (the idle-admit rule below keeps
  even a wild over-estimate live-locked-free).
- ``shape`` — conservative fallback: the dense design matrix at the
  spec's padded row count times a per-algo working-set factor (margins,
  histograms, optimizer state), plus the y/w/margin vectors.
- ``stream-window`` — the frame will not fit dense (the same
  ``fits_device`` test build_training_spec applies), so the train takes
  the host-chunked streaming path and admits at its budget-sized
  resident WINDOW, not the full matrix.

Double-count honesty: the estimate includes the training frame's own
resident bytes, and two entries over the same frame each count it —
conservative by design (shared-frame accounting would need per-Vec
refcounts across preemption). The scheduler's idle-admit rule (an entry
always admits when nothing else runs) guarantees progress regardless of
over-estimation.
"""
from __future__ import annotations

import os
from typing import NamedTuple, Optional

# rough working-set multipliers over the dense [rows, F] f32 design:
# trees hold X + per-row margin/residual + level histograms (small);
# GLM expands categoricals and keeps gram/optimizer state; DL keeps
# activations per layer. Deliberately coarse — the costmodel hint
# refines them once a real executable has been lowered.
ALGO_WORKING_FACTOR = {
    "gbm": 1.7, "xgboost": 1.7, "drf": 1.7, "isolationforest": 1.7,
    "glm": 2.5, "gam": 2.5, "deeplearning": 3.0, "kmeans": 2.0,
    "pca": 2.5,
}
DEFAULT_WORKING_FACTOR = 2.0

# the streamed paths size their resident window off the budget and
# double-buffer overflow chunks; admit at this budget fraction plus the
# always-resident y/w/margin vectors
STREAM_WINDOW_FRACTION = float(
    os.environ.get("H2O3_SCHED_STREAM_FRACTION", "0.5") or 0.5)

# algo -> the costmodel executable-cache key prefix of its chunk seam
_COSTMODEL_PREFIX = {"gbm": "gbm.chunk", "xgboost": "gbm.chunk",
                     "drf": "drf.chunk"}


class Estimate(NamedTuple):
    bytes: int
    streamed: bool
    source: str


def admission_headroom(reserved_bytes: int) -> int:
    """Admission budget minus the scheduler's reserved ledger; -1 means
    an unlimited backend. This single number is what heartbeats gossip
    into the fleet member table — a remote placement decision admits
    against it exactly as the local gate would."""
    from h2o3_tpu import memman
    mm = memman.manager()
    if mm.unlimited:
        return -1
    return max(mm.admission_budget() - int(reserved_bytes), 0)


def _response_classes(frame, y: Optional[str]) -> int:
    try:
        from h2o3_tpu.frame.vec import T_ENUM
        if y and y in frame and frame.vec(y).type == T_ENUM:
            return max(int(frame.vec(y).cardinality), 1)
    except Exception:   # noqa: BLE001 — estimation must never fail a train
        pass
    return 1


def estimate_submission(builder, frame, y=None, x=None,
                        validation_frame=None) -> Estimate:
    """Device-footprint estimate for one ModelBuilder submission,
    computed from frame shape + params only (the spec — and its device
    allocations — do not exist yet; admission is the point)."""
    from h2o3_tpu import memman
    from h2o3_tpu.frame.vec import T_STR

    try:
        names = list(x) if x else [n for n in frame.names if n != y]
        ignored = set(builder.params.get("ignored_columns") or ())
        for aux in ("weights_column", "offset_column", "fold_column"):
            c = builder.params.get(aux)
            if c:
                ignored.add(c)
        names = [n for n in names
                 if n not in ignored and frame.vec(n).type != T_STR]
        F = max(len(names), 1)
        nrow = int(frame.nrow)
    except Exception:   # noqa: BLE001 — degenerate frame: admit small
        F, nrow = 1, 0
    padded = nrow + 256          # mirrors build_training_spec's estimate
    K = _response_classes(frame, y)
    x_bytes = padded * F * 4
    # y/w + a margin per class (trees/GLM keep one; DL activations ride
    # the working factor instead)
    aux_bytes = padded * 4 * (2 + K)
    valid_bytes = 0
    if validation_frame is not None:
        try:
            valid_bytes = (int(validation_frame.nrow) + 256) * F * 4
        except Exception:   # noqa: BLE001
            pass

    mm = memman.manager()
    # the streamed/dense PREDICTION must mirror build_training_spec's
    # gate exactly — TRAINING bytes only. Folding validation bytes in
    # here once mis-classified dense trains as streamed, reserving the
    # small window while the real footprint ran dense and letting a
    # second train admit into memory that was already spoken for.
    if not mm.fits_device(x_bytes + mm.stats()["device_resident_bytes"]):
        # streamed-mode admission: the design stays on host and only the
        # resident window + working vectors occupy HBM
        win = int(mm.budget * STREAM_WINDOW_FRACTION) + aux_bytes
        return Estimate(win, True, "stream-window")

    factor = ALGO_WORKING_FACTOR.get(
        getattr(builder, "algo", ""), DEFAULT_WORKING_FACTOR)
    # validation matrix is resident but carries no histogram/optimizer
    # working set — added outside the factor
    base = int(x_bytes * factor) + valid_bytes + aux_bytes
    prefix = _COSTMODEL_PREFIX.get(getattr(builder, "algo", ""))
    if prefix:
        from h2o3_tpu.telemetry import costmodel
        hint = costmodel.per_iteration_bytes_hint(prefix)
        if hint:
            # the hint is bytes accessed per TREE; a tree pass streams
            # the design once per LEVEL, so dividing by depth
            # approximates the resident working set rather than the
            # traffic. Clamped to [1x, 4x] shape so a cached cost from
            # a much larger train cannot dominate admission.
            depth = max(int(builder.params.get("max_depth", 6) or 6), 1)
            working = hint / depth
            return Estimate(int(min(max(working, base), 4.0 * base)),
                            False, "costmodel+shape")
    return Estimate(base, False, "shape")
