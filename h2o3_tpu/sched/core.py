"""Cluster training scheduler: priority run queues, device-memory-aware
admission, checkpoint-based preemption (ISSUE 15).

Reference: H2O's priority ForkJoin ladder (water/H2O.java submitTask /
H2OCountedCompleter priority levels, SURVEY L1/L4) — interactive work
preempts bulk work and the node degrades gracefully under load instead
of thrashing. The TPU re-design moves the ladder OUT of the thread pool
and in front of the device: the scarce resource is HBM, so the queue is
ordered by priority class and released by a memory admission gate
(sched/admission.py), and "preempt" means a checkpointable train
commits its in-training checkpoint (PR 6/9 machinery) and gets requeued
rather than a thread losing its core.

Shape:

- Three priority classes — ``interactive`` (direct user trains) >
  ``bulk`` (grid/AutoML children) > ``background`` (restart-recovery
  resumes) — FIFO within a class, round-robin across fair-share groups
  (one grid cannot starve another tenant's children in the same class).
- Strict priority, no backfill: a blocked head does NOT let smaller
  entries behind it jump — they would steal exactly the headroom the
  blocked train is waiting for.
- Admission: an entry runs while the reserved-bytes ledger stays under
  ``memman.admission_budget()``. An entry ALWAYS admits when nothing
  else runs (progress is guaranteed under any over-estimate). A
  predicted-streamed entry admits at its resident-window size.
- Preemption: when the head of a HIGHER class cannot admit, the
  youngest checkpointable train of the LOWEST running class is asked to
  yield (``Job.preempt()``); its loop commits a DKV in-training
  checkpoint at the next chunk boundary and unwinds with
  ``JobPreempted``; the entry requeues at the FRONT of its share with
  ``checkpoint=<key>_ckpt`` injected, so the resumed train reproduces
  the uninterrupted one bit-for-bit (the checkpoint carries the exact
  f32 margin).

Nested builds (CV folds, ensemble metalearners, calibration trains)
run INLINE on the admitted parent's worker — queueing them would
deadlock the parent against its own children; their memory already
rides the parent's estimate.

``H2O3_SCHED=0`` restores the pre-scheduler spawn-a-thread path.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from h2o3_tpu.sched.admission import Estimate, estimate_submission

INTERACTIVE = 0
BULK = 1
BACKGROUND = 2

PRIORITY_NAMES = {INTERACTIVE: "interactive", BULK: "bulk",
                  BACKGROUND: "background"}
PRIORITY_LEVELS = {v: k for k, v in PRIORITY_NAMES.items()}

# algos whose train loops honor Job.preempt() by committing a resumable
# in-training checkpoint and unwinding with JobPreempted
CHECKPOINTABLE_ALGOS = frozenset({"gbm", "xgboost", "drf"})

_TLS = threading.local()

# Fleet scheduler hooks (h2o3_tpu/fleet/sched.py installs these; None →
# PR 15's per-process behavior, bit-for-bit).
# PLACER(builder, job, kwargs, pr_name, share, est, caller_runs) →
#   (entry, snapshot): a fully-proxied remote Entry (submit() returns
#   it without queueing) or (None, snapshot-or-None) for the local
#   path; a non-None snapshot is the no-headroom-anywhere fleet
#   evidence recorded on the queued entry.
# MIGRATOR(entry) → bool: called OUTSIDE the scheduler cv after a
#   preempted entry unwinds; True hands the train to another replica
#   (the entry proxies it), False requeues locally.
PLACER = None
MIGRATOR = None


def _bb(kind: str, member: str = "", payload: str = "",
        trace_id: Optional[str] = None) -> None:
    """Flight-recorder append (ISSUE 19): enqueue/admit/preempt/requeue
    decisions — the local scheduler's side of the cluster timeline.
    ``member`` carries the subject job key. Advisory."""
    try:
        from h2o3_tpu.telemetry import blackbox
        blackbox.record(kind, member=member, payload=payload,
                        trace_id=trace_id)
    except Exception:   # noqa: BLE001 — flight recorder is advisory
        pass


class SchedulerSaturatedError(RuntimeError):
    """The run queue is at H2O3_SCHED_MAX_QUEUE — the submission is
    REJECTED (counted on h2o3_sched_rejected_total) rather than growing
    the queue without bound."""


def _max_queue() -> int:
    try:
        return int(os.environ.get("H2O3_SCHED_MAX_QUEUE", "4096") or 4096)
    except ValueError:
        return 4096


def _max_concurrent() -> int:
    """0 = unlimited (admission is the gate); a positive value caps
    concurrently RUNNING entries regardless of memory headroom."""
    try:
        return int(os.environ.get("H2O3_SCHED_MAX_CONCURRENT", "0") or 0)
    except ValueError:
        return 0


def enabled() -> bool:
    return os.environ.get("H2O3_SCHED", "1") not in ("0", "false", "")


def in_scheduled_run() -> bool:
    """True on a scheduler worker thread (or any thread a scheduled
    build fanned out to via inherited context): train() calls here are
    NESTED builds that ride the parent's admission."""
    return bool(getattr(_TLS, "inline", False))


@contextmanager
def inline_run():
    """Mark the current thread as executing an admitted build."""
    prev = getattr(_TLS, "inline", False)
    _TLS.inline = True
    try:
        yield
    finally:
        _TLS.inline = prev


@contextmanager
def submit_context(priority: Optional[str] = None,
                   share: Optional[str] = None):
    """Tag train() submissions made inside the block (grid/AutoML wrap
    their children in ``priority="bulk", share=<grid id>``; recovery
    resumes in ``priority="background"``)."""
    prev = (getattr(_TLS, "ctx_priority", None),
            getattr(_TLS, "ctx_share", None))
    if priority is not None:
        if priority not in PRIORITY_LEVELS:
            raise ValueError(f"unknown scheduler priority '{priority}' "
                             f"(one of {sorted(PRIORITY_LEVELS)})")
        _TLS.ctx_priority = priority
    if share is not None:
        _TLS.ctx_share = share
    try:
        yield
    finally:
        _TLS.ctx_priority, _TLS.ctx_share = prev


def context_priority() -> Optional[str]:
    return getattr(_TLS, "ctx_priority", None)


def context_share() -> Optional[str]:
    return getattr(_TLS, "ctx_share", None)


class Entry:
    """One queued/running training submission."""

    __slots__ = ("builder", "job", "kwargs", "priority", "share",
                 "estimate", "seq", "enqueue_mono", "dispatch_mono",
                 "done", "wait_reason", "preempt_cycles", "caller_runs",
                 "granted", "fleet_snapshot", "remote_member")

    def __init__(self, builder, job, kwargs: Dict[str, Any],
                 priority: int, share: str, estimate: Estimate, seq: int,
                 caller_runs: bool = False):
        self.builder = builder
        self.job = job
        self.kwargs = kwargs
        self.priority = priority
        self.share = share
        self.estimate = estimate
        self.seq = seq
        self.enqueue_mono = time.monotonic()
        self.dispatch_mono: Optional[float] = None
        self.done = threading.Event()
        self.wait_reason: Optional[str] = None
        self.preempt_cycles = 0
        # foreground submissions execute on the SUBMITTER's thread once
        # admitted (the dispatcher GRANTS instead of spawning a worker):
        # XLA compiles measure ~35% slower on freshly-spawned threads,
        # and a foreground caller blocks anyway — its thread is free
        self.caller_runs = caller_runs
        self.granted = False            # toggled under the scheduler cv
        # fleet scheduler state: the no-headroom-anywhere evidence
        # recorded when the fleet could not take this entry, and the
        # member id this entry currently proxies for (None = local)
        self.fleet_snapshot: Optional[Dict[str, Any]] = None
        self.remote_member: Optional[str] = None

    @property
    def checkpointable(self) -> bool:
        return getattr(self.builder, "algo", "") in CHECKPOINTABLE_ALGOS

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class Scheduler:
    def __init__(self):
        self._cv = threading.Condition()
        self._queues: Dict[int, "OrderedDict[str, deque]"] = {
            INTERACTIVE: OrderedDict(), BULK: OrderedDict(),
            BACKGROUND: OrderedDict()}
        self._running: Dict[Entry, int] = {}    # entry -> reserved bytes
        self._reserved = 0
        self._paused = False
        self._stop = False
        self._seq = 0
        self._thread: Optional[threading.Thread] = None
        # high-watermarks since reset — the oversubscription tests'
        # witnesses: peak_reserved is the admitted-estimate ledger's
        # max (can exceed the budget only via the idle-admit rule, i.e.
        # a SINGLE over-budget train running alone); peak_running is
        # the max concurrent admissions
        self.peak_reserved = 0
        self.peak_running = 0
        from h2o3_tpu import telemetry
        self._m_queued = telemetry.counter(
            "h2o3_sched_queued_total",
            help="training submissions accepted into the run queue")
        self._m_admitted = telemetry.counter(
            "h2o3_sched_admitted_total",
            help="training submissions dispatched past admission")
        self._m_preempted = telemetry.counter(
            "h2o3_sched_preempted_total",
            help="checkpoint-based preemptions requested")
        self._m_rejected = telemetry.counter(
            "h2o3_sched_rejected_total",
            help="submissions rejected at the queue cap")
        self._m_wait = telemetry.histogram(
            "h2o3_sched_queue_wait_ms",
            help="queue wait per dispatch (ms)",
            bounds=(1.0, 5.0, 10.0, 50.0, 100.0, 500.0, 1000.0,
                    5000.0, 10000.0, 60000.0, 300000.0))
        self._g_depth = telemetry.gauge(
            "h2o3_sched_queue_depth", help="entries waiting in the queue")
        self._g_running = telemetry.gauge(
            "h2o3_sched_running", help="entries past admission, running")
        self._g_headroom = telemetry.gauge(
            "h2o3_sched_admission_headroom_bytes",
            help="admission budget minus reserved bytes (-1: unlimited "
                 "backend)")
        self._update_gauges_locked()

    # ---------------- submission --------------------------------------

    def submit(self, builder, job, kwargs: Dict[str, Any],
               priority: Optional[str] = None,
               share: Optional[str] = None,
               caller_runs: bool = False) -> Entry:
        pr_name = (priority or builder.params.get("scheduler_priority")
                   or context_priority() or "interactive")
        if pr_name not in PRIORITY_LEVELS:
            raise ValueError(f"unknown scheduler priority '{pr_name}' "
                             f"(one of {sorted(PRIORITY_LEVELS)})")
        share = share or context_share() or "default"
        est = estimate_submission(
            builder, kwargs.get("training_frame"), y=kwargs.get("y"),
            x=kwargs.get("x"),
            validation_frame=kwargs.get("validation_frame"))
        fleet_snapshot = None
        if PLACER is not None:
            try:
                placed, fleet_snapshot = PLACER(
                    builder, job, kwargs, pr_name, share, est,
                    caller_runs)
            except Exception as e:   # noqa: BLE001 — local queue wins
                placed, fleet_snapshot = None, None
                from h2o3_tpu.log import warn
                warn("sched: fleet placement failed for %s — running "
                     "locally: %r", job.key, e)
            if placed is not None:
                return placed        # proxied remotely; never queued here
        with self._cv:
            depth = sum(len(dq) for od in self._queues.values()
                        for dq in od.values())
            if depth >= _max_queue():
                self._m_rejected.inc()
                _bb("sched_reject", job.key,
                    payload=f"queue_full depth={depth}",
                    trace_id=getattr(job, "trace_id", None))
                raise SchedulerSaturatedError(
                    f"training queue is full ({depth} entries, cap "
                    f"{_max_queue()}) — raise H2O3_SCHED_MAX_QUEUE or "
                    f"wait for running work to drain")
            self._seq += 1
            entry = Entry(builder, job, kwargs, PRIORITY_LEVELS[pr_name],
                          share, est, self._seq,
                          caller_runs=caller_runs)
            entry.fleet_snapshot = fleet_snapshot
            job.mark_queued()
            if getattr(builder, "_resuming", False):
                # a restart-recovery resume surfaces as RECOVERING on
                # /3/Jobs from submission on (ISSUE 9 contract), even
                # while it waits in the queue
                from h2o3_tpu import jobs as jobs_mod
                job.status = jobs_mod.RECOVERING
            self._queues[entry.priority].setdefault(
                share, deque()).append(entry)
            self._m_queued.inc()
            self._update_gauges_locked()
            self._ensure_thread_locked()
            self._cv.notify_all()
        _bb("sched_enqueue", job.key,
            payload=f"pr={pr_name} share={share} "
                    f"need={est.bytes}",
            trace_id=getattr(job, "trace_id", None))
        return entry

    # ---------------- dispatcher --------------------------------------

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop = False  # h2o3-lint: allow[lock-discipline] caller holds self._cv (the _locked suffix contract); a submission revives a retired instance
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="sched-dispatch")
            self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cv:
                if self._stop:
                    return
                entry = None
                if not self._paused:
                    entry = self._try_dispatch_locked()
                if entry is None:
                    # periodic wake covers queued-entry cancellation and
                    # the interval between a preempt request and the
                    # victim's next chunk commit
                    self._cv.wait(timeout=0.25)
                    continue
                if entry.caller_runs:
                    # GRANT: the blocked foreground submitter executes
                    # the admitted build on its own thread (see
                    # run_to_completion) — no worker spawn
                    entry.granted = True
                    self._cv.notify_all()
                    continue
            threading.Thread(target=self._run_entry, args=(entry,),
                             daemon=True,
                             name=f"sched-{entry.job.key}").start()

    def run_to_completion(self, entry: Entry) -> None:
        """Foreground caller's side of a ``caller_runs`` submission:
        block until the dispatcher grants admission, execute the build
        on THIS thread, and loop across preempt/requeue cycles until
        the job is terminal."""
        while True:
            with self._cv:
                self._cv.wait_for(lambda: entry.granted
                                  or entry.done.is_set())
                if entry.done.is_set():
                    return
                entry.granted = False       # grant consumed
            self._run_entry(entry)
            if entry.done.is_set():
                return

    def _purge_cancelled_locked(self) -> None:
        """Drop user-cancelled entries from EVERY share — a cancel must
        turn terminal on the next dispatcher pass even when the entry
        sits behind a blocked head in another share."""
        for od in self._queues.values():
            for share in list(od):
                dq = od[share]
                for e in [e for e in dq if e.job.cancel_requested]:
                    dq.remove(e)
                    self._finalize_cancelled_locked(e)
                if not dq:
                    del od[share]

    def _try_dispatch_locked(self) -> Optional[Entry]:
        self._purge_cancelled_locked()
        for prio in (INTERACTIVE, BULK, BACKGROUND):
            od = self._queues[prio]
            for share in list(od):
                dq = od[share]
                if not dq:
                    del od[share]
                    continue
                cand = dq[0]
                if self._admissible_locked(cand):
                    dq.popleft()
                    if dq:
                        od.move_to_end(share)   # fair-share rotation
                    else:
                        del od[share]
                    self._reserve_locked(cand)
                    return cand
                # strict priority, no backfill: entries behind a blocked
                # head (same or lower class) would steal the headroom it
                # is waiting for
                self._maybe_preempt_locked(cand)
                return None
        return None

    def _admissible_locked(self, entry: Entry) -> bool:
        cap = _max_concurrent()
        if cap and len(self._running) >= cap:
            entry.wait_reason = (f"concurrency cap "
                                 f"H2O3_SCHED_MAX_CONCURRENT={cap}")
            return False
        if not self._running:
            return True          # idle-admit: progress under any estimate
        from h2o3_tpu import memman
        mm = memman.manager()
        if mm.unlimited:
            return True
        if self._reserved + entry.estimate.bytes <= mm.admission_budget():
            return True
        entry.wait_reason = (
            f"device memory: needs ~{entry.estimate.bytes} B "
            f"({entry.estimate.source}), {self._reserved} B already "
            f"admitted of {mm.admission_budget()} B budget")
        return False

    def _maybe_preempt_locked(self, cand: Entry) -> None:
        if any(v.job.preempt_requested for v in self._running):
            return               # one preemption in flight — wait for it
        victims = [v for v in self._running
                   if v.priority > cand.priority and v.checkpointable]
        if not victims:
            return
        # youngest train of the LOWEST-priority running class: it has
        # the least committed work to re-load and its class loses the
        # least standing
        victim = max(victims,
                     key=lambda v: (v.priority, v.dispatch_mono or 0.0))
        from h2o3_tpu import memman
        mm = memman.manager()
        freed_ok = (len(self._running) == 1
                    or mm.unlimited
                    or self._reserved - self._running[victim]
                    + cand.estimate.bytes <= mm.admission_budget())
        if not freed_ok:
            return
        reason = (f"preempted for higher-priority "
                  f"{PRIORITY_NAMES[cand.priority]} job {cand.job.key}")
        victim.job.preempt(reason)
        self._m_preempted.inc()
        _bb("sched_preempt", victim.job.key,
            payload=f"for={cand.job.key} "
                    f"cls={PRIORITY_NAMES[victim.priority]}",
            trace_id=getattr(victim.job, "trace_id", None))
        from h2o3_tpu.log import info
        info("sched: preempting %s (%s, priority=%s) for %s",
             victim.job.key, victim.builder.algo,
             PRIORITY_NAMES[victim.priority], cand.job.key)

    # ---------------- execution ---------------------------------------

    def _run_entry(self, entry: Entry) -> None:
        job = entry.job
        wait_s = max(time.monotonic() - job.start_mono, 0.0)
        job.mark_dispatched()
        entry.dispatch_mono = time.monotonic()
        entry.wait_reason = None
        self._m_admitted.inc()
        self._m_wait.observe(wait_s * 1000.0)
        _bb("sched_admit", job.key,
            payload=f"wait_ms={wait_s * 1000.0:.0f} "
                    f"cycles={entry.preempt_cycles}",
            trace_id=getattr(job, "trace_id", None))
        try:
            with inline_run():
                terminal = job.execute_scheduled(
                    lambda j: entry.builder._run_build(j, **entry.kwargs))
        except BaseException:   # noqa: BLE001 — ledger must not leak
            terminal = True
            raise
        finally:
            migrate = None
            with self._cv:
                self._release_locked(entry)
                if terminal:
                    from h2o3_tpu import jobs as jobs_mod
                    if job.status not in jobs_mod._TERMINAL:
                        # worker unwound on a BaseException that
                        # execute_scheduled does not catch — the job
                        # must still turn terminal or its waiters hang
                        job.status = jobs_mod.FAILED
                        job.exception_msg = ("scheduler worker died "
                                             "unexpectedly")
                        job.end_time = time.time()
                        job._end_mono = time.monotonic()
                        job._done_evt.set()
                    entry.done.set()
                elif MIGRATOR is None:
                    self._requeue_locked(entry)
                else:
                    migrate = MIGRATOR   # hand-off HTTP runs off-lock
                self._update_gauges_locked()
                self._cv.notify_all()
            if migrate is not None:
                migrated = False
                try:
                    migrated = bool(migrate(entry))
                except Exception as e:   # noqa: BLE001 — local requeue
                    from h2o3_tpu.log import warn
                    warn("sched: preempt-migrate of %s failed — "
                         "requeueing locally: %r", job.key, e)
                if not migrated:
                    with self._cv:
                        self._requeue_locked(entry)
                        self._update_gauges_locked()
                        self._cv.notify_all()

    def _reserve_locked(self, entry: Entry) -> None:
        self._running[entry] = entry.estimate.bytes
        self._reserved += entry.estimate.bytes
        self.peak_reserved = max(self.peak_reserved, self._reserved)
        self.peak_running = max(self.peak_running, len(self._running))
        self._update_gauges_locked()

    def _release_locked(self, entry: Entry) -> None:
        nbytes = self._running.pop(entry, 0)
        self._reserved -= nbytes

    def _requeue_locked(self, entry: Entry) -> None:
        """Preempted: back at the FRONT of its share (it was running —
        later arrivals must not overtake it) with the in-training
        checkpoint injected so the next dispatch RESUMES."""
        job = entry.job
        job.mark_requeued()
        entry.preempt_cycles += 1
        entry.dispatch_mono = None
        _bb("sched_requeue", job.key,
            payload=f"cycles={entry.preempt_cycles} resume=ckpt",
            trace_id=getattr(job, "trace_id", None))
        try:
            key = entry.builder._model_key()
            from h2o3_tpu import dkv
            if dkv.get_opt(f"{key}_ckpt") is not None:
                # resume from the committed prefix; model_id pins the
                # resumed artifacts (and further checkpoints) under the
                # original key
                entry.builder.params["model_id"] = key
                entry.builder.params["checkpoint"] = f"{key}_ckpt"
        except Exception:   # noqa: BLE001 — clean rerun is the fallback
            pass
        self._queues[entry.priority].setdefault(
            entry.share, deque()).appendleft(entry)

    def _finalize_cancelled_locked(self, entry: Entry) -> None:
        """A queued entry whose job was cancelled before dispatch: it
        never ran, terminal immediately."""
        from h2o3_tpu import jobs as jobs_mod
        job = entry.job
        job.status = jobs_mod.CANCELLED  # h2o3-lint: allow[lock-discipline] every caller holds self._cv (the _locked suffix contract); the job was never dispatched so no other writer exists
        job.end_time = time.time()  # h2o3-lint: allow[lock-discipline] caller holds self._cv (the _locked suffix contract)
        job._end_mono = time.monotonic()  # h2o3-lint: allow[lock-discipline] caller holds self._cv (the _locked suffix contract)
        job._done_evt.set()
        entry.done.set()
        # a caller_runs submitter may be blocked on the cv waiting for
        # a grant — wake it to observe the terminal state
        self._cv.notify_all()

    # ---------------- control / introspection -------------------------

    def shutdown(self) -> None:
        """Stop the dispatcher thread (reset() retires the old instance
        through this — an orphaned loop would otherwise spin at 4 Hz
        forever and pin the instance)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def pause(self) -> None:
        with self._cv:
            self._paused = True

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    @property
    def paused(self) -> bool:
        return self._paused

    def reprioritize(self, job_key: str, priority: str) -> bool:
        """Move a QUEUED entry to another priority class (POST
        /3/Scheduler). Running entries are not touched."""
        if priority not in PRIORITY_LEVELS:
            raise ValueError(f"unknown scheduler priority '{priority}'")
        target = PRIORITY_LEVELS[priority]
        with self._cv:
            for prio, od in self._queues.items():
                for share, dq in od.items():
                    for entry in dq:
                        if entry.job.key != job_key:
                            continue
                        if prio == target:
                            return True    # already there — no demotion
                        dq.remove(entry)
                        if not dq:
                            del od[share]
                        entry.priority = target
                        tq = self._queues[target].setdefault(
                            entry.share, deque())
                        if entry.preempt_cycles > 0:
                            # a preempt-requeued entry keeps its
                            # front-of-share standing in the new class:
                            # later arrivals must not overtake the
                            # half-finished train
                            tq.appendleft(entry)
                        else:
                            tq.append(entry)
                        self._cv.notify_all()
                        return True
        return False

    def wait_any(self, entries: List[Entry],
                 timeout: Optional[float] = None) -> bool:
        """Block until ANY of ``entries`` is terminal (grid/AutoML wave
        draining)."""
        with self._cv:
            return self._cv.wait_for(
                lambda: any(e.done.is_set() for e in entries),
                timeout=timeout)

    def queue_depth(self) -> int:
        with self._cv:
            return sum(len(dq) for od in self._queues.values()
                       for dq in od.values())

    def running_count(self) -> int:
        with self._cv:
            return len(self._running)

    def class_depths(self) -> Dict[str, int]:
        """Queue depth per priority class (gossiped on heartbeats)."""
        with self._cv:
            return {PRIORITY_NAMES[p]: sum(len(dq) for dq in od.values())
                    for p, od in self._queues.items()}

    def headroom_bytes(self) -> int:
        """Admission headroom in bytes; -1 = unlimited backend."""
        from h2o3_tpu.sched.admission import admission_headroom
        with self._cv:
            return admission_headroom(self._reserved)

    def poke(self) -> None:
        """Wake cv waiters after an EXTERNAL ``entry.done.set()`` (the
        fleet proxy finalizing a remote result) — run_to_completion and
        wait_any block on the cv, not the entry event."""
        with self._cv:
            self._cv.notify_all()

    def requeue(self, entry: Entry) -> None:
        """Return a fleet-proxied entry to the local queue (remote
        replica unreachable, or a hand-off that did not stick)."""
        from h2o3_tpu import jobs as jobs_mod
        job = entry.job
        # read/cleared OUTSIDE the cv like every other dispatch_mono
        # write in this module — requeue() races nothing: the entry is
        # proxied (not queued, not running) until re-injected below
        was_dispatched = entry.dispatch_mono is not None
        entry.dispatch_mono = None
        with self._cv:
            if job.status in jobs_mod._TERMINAL:
                entry.done.set()
                self._cv.notify_all()
                return
            if job.status in (jobs_mod.RUNNING, jobs_mod.RECOVERING) \
                    and was_dispatched:
                self._requeue_locked(entry)    # banks the run segment
            else:
                # still QUEUED (hand-off failed before any dispatch):
                # re-inject without double-counting a preempt cycle
                self._queues[entry.priority].setdefault(
                    entry.share, deque()).appendleft(entry)
            self._update_gauges_locked()
            self._ensure_thread_locked()
            self._cv.notify_all()

    def steal_queued(self, eligible, limit: Optional[int] = None
                     ) -> List[Entry]:
        """Remove queued entries matching ``eligible`` for fleet
        hand-off (a replica joining mid-grid absorbs queued children).
        caller_runs and cancelled entries keep their local standing."""
        taken: List[Entry] = []
        with self._cv:
            for od in self._queues.values():
                for share in list(od):
                    dq = od[share]
                    keep: deque = deque()
                    while dq:
                        e = dq.popleft()
                        if (limit is None or len(taken) < limit) \
                                and not e.caller_runs \
                                and not e.job.cancel_requested \
                                and eligible(e):
                            taken.append(e)
                        else:
                            keep.append(e)
                    if keep:
                        od[share] = keep
                    else:
                        del od[share]
            self._update_gauges_locked()
        return taken

    def _update_gauges_locked(self) -> None:
        from h2o3_tpu import memman
        self._g_depth.set(sum(len(dq) for od in self._queues.values()
                              for dq in od.values()))
        self._g_running.set(len(self._running))
        mm = memman.manager()
        self._g_headroom.set(
            -1 if mm.unlimited
            else max(mm.admission_budget() - self._reserved, 0))

    def snapshot(self) -> Dict[str, Any]:
        """Queue state for GET /3/Scheduler."""
        from h2o3_tpu import memman
        mm = memman.manager()
        now = time.monotonic()
        with self._cv:
            running = [{
                "job": e.job.key, "algo": getattr(e.builder, "algo", "?"),
                "priority": PRIORITY_NAMES[e.priority], "share": e.share,
                "estimate_bytes": e.estimate.bytes,
                "estimate_source": e.estimate.source,
                "streamed": e.estimate.streamed,
                "preempt_requested": e.job.preempt_requested,
                "preempt_cycles": e.preempt_cycles,
                "running_s": round(now - e.dispatch_mono, 3)
                if e.dispatch_mono else None,
            } for e in sorted(self._running,
                              key=lambda e: e.dispatch_mono or 0.0)]
            queued = [{
                "job": e.job.key, "algo": getattr(e.builder, "algo", "?"),
                "priority": PRIORITY_NAMES[prio], "share": share,
                "estimate_bytes": e.estimate.bytes,
                "estimate_source": e.estimate.source,
                "streamed": e.estimate.streamed,
                "wait_s": round(now - e.enqueue_mono, 3),
                "wait_reason": e.wait_reason,
                "preempt_cycles": e.preempt_cycles,
                "fleet": e.fleet_snapshot,
            } for prio, od in sorted(self._queues.items())
                for share, dq in od.items() for e in dq]
            return {
                "paused": self._paused,
                "budget_bytes": (-1 if mm.unlimited
                                 else mm.admission_budget()),
                "reserved_bytes": self._reserved,
                "peak_reserved_bytes": self.peak_reserved,
                "peak_running_entries": self.peak_running,
                "headroom_bytes": (-1 if mm.unlimited else
                                   max(mm.admission_budget()
                                       - self._reserved, 0)),
                "queued": queued,
                "running": running,
                "counters": {
                    "queued_total": self._m_queued.value,
                    "admitted_total": self._m_admitted.value,
                    "preempted_total": self._m_preempted.value,
                    "rejected_total": self._m_rejected.value,
                },
            }


_SCHEDULER: Optional[Scheduler] = None
_SCHED_LOCK = threading.Lock()


def scheduler() -> Scheduler:
    global _SCHEDULER
    with _SCHED_LOCK:
        if _SCHEDULER is None:
            _SCHEDULER = Scheduler()
        return _SCHEDULER


def reset() -> Scheduler:
    """Tests: fresh scheduler state. Call only when idle — running
    entries of the old instance finish against its ledger; its
    dispatcher thread is shut down."""
    global _SCHEDULER
    with _SCHED_LOCK:
        old = _SCHEDULER
        _SCHEDULER = Scheduler()
        if old is not None:
            old.shutdown()
        return _SCHEDULER
