"""Global quantile binning — feature values → small int bin codes.

Reference: the tree algos bin features per-node with DHistogram
(hex/tree/DHistogram.java:48; QuantilesGlobal/UniformAdaptive histogram
types in GBM), and the vendored XGBoost's ``tree_method=hist`` builds a
global quantile sketch once. The TPU design follows the global-sketch
shape: one pass computes per-feature quantile edges, a second digitises
every value into a uint8/int16 code. All later tree work touches only the
code matrix — int codes stream through HBM at 1-2 bytes/value and feed the
MXU one-hot histogram kernel (SURVEY.md §7.3).

Layout: codes[rows, F] with values in [0, n_bins_f); the NA bin is a
dedicated last index ``n_bins`` shared across features (uniform shape for
XLA). Split "bin t" means: left ⇔ code < t ⇔ raw < edges[t-1].
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class PackedCodes(NamedTuple):
    """Kernel-ready PACKED bin codes — the representation the training
    hot path computes on (ops/hist_adaptive binned kernels). ``rm``
    [rows, F] int8/int16 with the NA code remapped from ``n_bins`` to
    the kernel's RESERVED LAST LANE ``W-1`` (predict_binned walks it
    with na_bin=W-1); ``t`` [F, rows_p] same dtype, transposed and
    tile-padded PER SHARD (pad value W-1 = all-NA rows) — the pallas
    hot-loop operand, built once per train so the 1-2 byte/value codes
    are what streams through HBM every level. ``t`` is None off-TPU
    (the scatter reference reads ``rm``)."""
    rm: jax.Array
    t: Optional[jax.Array]
    W: int

    @property
    def na_bin(self) -> int:
        return self.W - 1

    @property
    def itemsize(self) -> int:
        return jnp.dtype(self.rm.dtype).itemsize


class CodesView(NamedTuple):
    """Bin codes in both layouts. ``rm`` [rows, F] (compact, for routing/
    predict gathers); ``t`` [Fp, rows_p] int32 (transposed + padded, the
    pallas histogram kernel operand — transposing once here instead of per
    level saves ~40ms/level at 1M rows). ``t`` may be None off-TPU."""
    rm: jax.Array
    t: Optional[jax.Array]

    @property
    def shape(self):
        return self.rm.shape

    @property
    def dtype(self):
        return self.rm.dtype


@dataclass
class BinnedMatrix:
    codes: CodesView           # NA bin = n_bins
    n_bins: int                # bins per feature excluding the NA bin
    edges: List[np.ndarray]    # per-feature raw-value split edges (len <= n_bins-1)
    names: List[str]
    is_categorical: List[bool]
    nrow: int

    @property
    def n_features(self) -> int:
        return self.codes.rm.shape[1]

    @property
    def na_bin(self) -> int:
        return self.n_bins


def quantile_edges(col: np.ndarray, nbins: int) -> np.ndarray:
    """Unique quantile cut points for one numeric feature (host-side; the
    sketch is O(sample) — full exact quantiles are fine at these scales)."""
    vals = col[np.isfinite(col)]
    if vals.size == 0:
        return np.empty(0, dtype=np.float32)
    qs = np.quantile(vals, np.linspace(0.0, 1.0, nbins + 1)[1:-1])
    return np.unique(qs.astype(np.float32))


def uniform_edges(col: np.ndarray, nbins: int) -> np.ndarray:
    """Equal-width cut points (histogram_type='uniform_adaptive' analog:
    the reference re-adapts ranges per tree level; a global uniform grid is
    the static-shape equivalent)."""
    vals = col[np.isfinite(col)]
    if vals.size == 0:
        return np.empty(0, dtype=np.float32)
    lo, hi = float(vals.min()), float(vals.max())
    if lo == hi:
        return np.empty(0, dtype=np.float32)
    return np.linspace(lo, hi, nbins + 1)[1:-1].astype(np.float32)


@jax.jit
def _sketch_stats(X, nrow):
    """Device half of the global sketch: finite-masked sort per feature
    plus the tiny per-feature stats the host edge rules need. Pad rows
    (index >= nrow) and ±inf are masked to NaN so they sort last and drop
    out of the finite count — matching the host path's
    ``col[np.isfinite(col)]`` filter."""
    inrow = (jnp.arange(X.shape[0]) < nrow)[:, None]
    Xf = jnp.where(inrow & jnp.isfinite(X), X.astype(jnp.float32), jnp.nan)
    Xs = jnp.sort(Xf, axis=0)                       # finite asc, NaN last
    nfin = jnp.sum(~jnp.isnan(Xf), axis=0).astype(jnp.int32)
    fmax = jnp.take_along_axis(Xs, jnp.maximum(nfin - 1, 0)[None, :],
                               axis=0)[0]
    return Xs, nfin, Xs[0], fmax


@jax.jit
def _gather_rank_pairs(Xs, lo_idx, hi_idx):
    """Pure gathers of the quantile neighbour ranks — the float64 lerp
    happens on host so the result is bit-identical to np.quantile."""
    a = jnp.take_along_axis(Xs, lo_idx, axis=0)
    b = jnp.take_along_axis(Xs, hi_idx, axis=0)
    return a, b


def _np_quantile_lerp(a: np.ndarray, b: np.ndarray, t: np.ndarray) -> np.ndarray:
    """numpy's _lerp on float32 neighbours with float64 t — replicated so
    device-sketch edges match ``np.quantile(vals, qs)`` bit-for-bit
    (verified by tests/test_train_perf.py parity tests)."""
    diff = np.subtract(b, a)                 # float32, like numpy's _lerp
    out = np.add(a, diff * t)                # promotes to float64
    hi = t >= 0.5
    if hi.any():
        out[hi] = (b - diff * (1.0 - t))[hi]
    return out


def bin_matrix_device(X, names: Sequence[str], is_cat: Sequence[bool],
                      nrow: int, nbins: int = 255, nbins_cats: int = 1024,
                      histogram_type: str = "quantiles_global",
                      with_t: bool = True) -> BinnedMatrix:
    """Device-side global sketch: the same edges as :func:`bin_matrix`
    (bit-exact — parity-tested) WITHOUT a ``device_get`` of the full X.

    The device sorts each feature once and the host fetches only O(F)
    stats plus the 2·(nbins-1) quantile neighbour values per feature; the
    float64 lerp and the unique/truncate bookkeeping stay on host where
    they are exact and cheap. Digitisation then runs on device as usual.
    This is the "no host round-trips" rule applied to binning itself —
    the sketch half of XGBoost's ``tree_method=hist``.

    Multi-accelerator caveat: XLA lowers the cross-shard column sort to
    an all-gather, so every chip would need to hold the FULL [padded, F]
    matrix (plus its sorted copy) — a frame sized for the aggregate HBM
    of a data-sharded mesh would OOM. On any multi-shard accelerator
    mesh this falls back to the host-side sketch (device_get +
    np.quantile, the pre-device-sketch behavior; identical edges); the
    CPU test mesh's virtual shards share one host RAM, so it keeps the
    device path. A per-shard sketch merged with a psum would scale but
    is not bit-exact — the future lever."""
    import jax as _jax
    from h2o3_tpu import telemetry
    from h2o3_tpu.parallel.mesh import current_mesh, n_data_shards
    if (_jax.default_backend() != "cpu"
            and n_data_shards(current_mesh()) > 1):
        return bin_matrix(np.asarray(telemetry.device_get(
            X, pipeline="train")), names, is_cat,
            nrow, nbins=nbins, nbins_cats=nbins_cats,
            histogram_type=histogram_type, with_t=with_t)
    F = X.shape[1]
    Xs, nfin_d, fmin_d, fmax_d = _sketch_stats(X, jnp.int32(nrow))
    # ONE counted fetch of the O(F) sketch stats (transfer-seam)
    nfin, fmin, fmax = (np.asarray(v) for v in telemetry.device_get(
        (nfin_d, fmin_d, fmax_d), pipeline="train"))
    uniform = histogram_type in ("uniform_adaptive", "uniform")
    # per-feature quantile grids (numeric: nbins; over-wide cats:
    # nbins_cats) — build one padded rank-index matrix for a single gather
    qgrids: List[Optional[np.ndarray]] = [None] * F
    for f in range(F):
        n = int(nfin[f])
        if n == 0:
            continue
        if is_cat[f]:
            card = int(fmax[f]) + 1
            if card <= nbins_cats:
                continue                     # identity bins — no quantiles
            qs = np.linspace(0.0, 1.0, nbins_cats + 1)[1:-1]
        elif uniform:
            continue                         # min/max only
        else:
            qs = np.linspace(0.0, 1.0, nbins + 1)[1:-1]
        qgrids[f] = qs * (n - 1)             # float64 virtual indexes
    qmax = max((len(v) for v in qgrids if v is not None), default=0)
    quant_vals: List[Optional[np.ndarray]] = [None] * F
    if qmax:
        lo_idx = np.zeros((qmax, F), np.int32)
        hi_idx = np.zeros((qmax, F), np.int32)
        for f, virt in enumerate(qgrids):
            if virt is None:
                continue
            lo_idx[: len(virt), f] = np.floor(virt).astype(np.int32)
            hi_idx[: len(virt), f] = np.ceil(virt).astype(np.int32)
        a, b = (np.asarray(v) for v in telemetry.device_get(
            _gather_rank_pairs(Xs, jnp.asarray(lo_idx),
                               jnp.asarray(hi_idx)), pipeline="train"))
        for f, virt in enumerate(qgrids):
            if virt is None:
                continue
            t = virt - np.floor(virt)
            quant_vals[f] = _np_quantile_lerp(a[: len(virt), f],
                                              b[: len(virt), f], t)
    del Xs  # release the sorted full-matrix copy before digitize allocates
    edges: List[np.ndarray] = []
    for f in range(F):
        n = int(nfin[f])
        if is_cat[f]:
            card = int(fmax[f]) + 1 if n > 0 else 1
            if card <= nbins_cats:
                e = (np.arange(1, card, dtype=np.float32) - 0.5)
            else:
                e = np.unique(quant_vals[f].astype(np.float32))
        elif n == 0:
            e = np.empty(0, dtype=np.float32)
        elif uniform:
            lo, hi = float(fmin[f]), float(fmax[f])
            e = (np.empty(0, dtype=np.float32) if lo == hi
                 else np.linspace(lo, hi, nbins + 1)[1:-1].astype(np.float32))
            e = e[: nbins - 1]
        else:
            e = np.unique(quant_vals[f].astype(np.float32))
            e = e[: nbins - 1]
        edges.append(e)
    n_bins_eff = max(nbins, max((len(e) + 1 for e in edges), default=2))
    if n_bins_eff > 16382:
        raise ValueError(
            f"effective bin count {n_bins_eff} exceeds the 14-bit routing "
            f"limit; lower nbins_cats (reference default is 1024)")
    codes = make_codes_view(digitize_with_edges(X, edges, n_bins_eff),
                            with_t=with_t)
    return BinnedMatrix(codes=codes, n_bins=n_bins_eff, edges=edges,
                        names=list(names), is_categorical=list(is_cat),
                        nrow=nrow)


def bin_matrix(X, names: Sequence[str], is_cat: Sequence[bool], nrow: int,
               nbins: int = 255, nbins_cats: int = 1024,
               histogram_type: str = "quantiles_global",
               with_t: bool = True) -> BinnedMatrix:
    """Digitise a dense [padded_rows, F] float matrix (NaN = NA) into codes.

    Categorical columns with cardinality <= nbins_cats use identity binning
    (code = category id) — group-per-category splits, the reference's
    nbins_cats semantics (hex/tree/DHistogram nbins_cats=1024). When a
    categorical needs more bins than ``nbins``, the matrix-wide bin count
    grows to fit it (histograms are [*, F, B+1, *] with one shared B;
    numeric features simply leave the extra bins empty). Cardinalities
    beyond nbins_cats fall back to quantile grouping of the code space.
    """
    X_host = np.asarray(X, dtype=np.float32)
    edges, n_bins_eff = _edges_host(X_host, nrow, is_cat, nbins,
                                    nbins_cats, histogram_type)
    codes = make_codes_view(digitize_with_edges(X, edges, n_bins_eff),
                            with_t=with_t)
    return BinnedMatrix(codes=codes, n_bins=n_bins_eff, edges=edges,
                        names=list(names), is_categorical=list(is_cat),
                        nrow=nrow)


def _edges_host(X_host: np.ndarray, nrow: int, is_cat: Sequence[bool],
                nbins: int, nbins_cats: int, histogram_type: str):
    """The host edge rules shared by :func:`bin_matrix` and the
    memory-pressure sketch (:func:`digitize_codes_host`). Returns
    (edges, n_bins_eff)."""
    F = X_host.shape[1]
    edge_fn = (uniform_edges if histogram_type in ("uniform_adaptive", "uniform")
               else quantile_edges)
    edges: List[np.ndarray] = []
    for f in range(F):
        col = X_host[:nrow, f]
        if is_cat[f]:
            card = int(np.nanmax(col)) + 1 if np.isfinite(col).any() else 1
            if card <= nbins_cats:
                e = (np.arange(1, card, dtype=np.float32) - 0.5)
            else:
                e = quantile_edges(col, nbins_cats)
        else:
            e = edge_fn(col, nbins)
            e = e[: nbins - 1]
        edges.append(e)
    # shared bin count = the widest feature's need (>= nbins only if a
    # categorical demands group-per-category resolution). Capped by the
    # 14-bit packed-word routing field (models/tree.py BIN_BITS).
    n_bins_eff = max(nbins, max((len(e) + 1 for e in edges), default=2))
    if n_bins_eff > 16382:
        raise ValueError(
            f"effective bin count {n_bins_eff} exceeds the 14-bit routing "
            f"limit; lower nbins_cats (reference default is 1024)")
    return edges, n_bins_eff


def digitize_codes_host(X_host, edges: List[np.ndarray], n_bins_eff: int):
    """Host digitise of precomputed edges straight to the packed kernel
    convention (NA = reserved bin W-1, dtype from
    hist_adaptive.code_dtype so host and device packing can never
    diverge) — the memory-pressure half of the streamed packed path:
    the full X never uploads. Searchsorts the same inf-PADDED edge
    matrix as the device :func:`digitize_with_edges`, so +inf values
    land in the shared lane ``max_e`` on every feature (bit-matching
    the dense packed codes — a per-feature unpadded searchsorted would
    merge +inf with the top finite bin on short-edge features and
    break streamed-vs-dense parity AND train-vs-score routing).
    Column-at-a-time so the temporaries stay O(rows). Returns
    (codes [rows, F], W)."""
    from h2o3_tpu.ops.hist_adaptive import code_dtype, pick_W
    X_host = np.asarray(X_host, dtype=np.float32)
    W = pick_W(n_bins_eff)
    np_dt = np.dtype(code_dtype(W))
    rows, F = X_host.shape
    max_e = max((len(e) for e in edges), default=0)
    emat = np.full((F, max(max_e, 1)), np.inf, dtype=np.float32)
    for f, e in enumerate(edges):
        emat[f, : len(e)] = e
    codes = np.empty((rows, F), np_dt)
    for f in range(F):
        col = X_host[:, f]
        c = np.searchsorted(emat[f], col, side="right")
        codes[:, f] = np.where(np.isnan(col), W - 1, c).astype(np_dt)
    return codes, W


def packed_codes_record(enabled: bool, dtype=None, W: int = None,
                        bytes_per_value: int = None,
                        n_bins: int = None) -> dict:
    """The ONE spelling of ``model.output['packed_codes']`` — GBM dense,
    GBM streamed and DRF all emit it through here so bench.py /
    profile_train.py key parsing can never meet a drifted copy."""
    if not enabled:
        return {"enabled": False}
    return {"enabled": True, "dtype": str(np.dtype(dtype)), "W": int(W),
            "bytes_per_value": int(bytes_per_value), "n_bins": int(n_bins),
            "kernel": "binned_level"}


def make_codes_view(codes_rm, tile: int = 2048, mesh=None,
                    with_t: bool = True) -> CodesView:
    """Build both layouts; the transposed int32 copy only on TPU (it only
    serves the pallas kernel). Both layouts are sharded over the mesh
    'data' axis (rows): rm as [rows@data, F]; t as [Fp, rows_p@data],
    transposed and tile-padded PER SHARD (shard i's t columns are shard
    i's rm rows — a global end-pad would misalign the row sets).
    ``with_t=False`` skips the transposed build — the packed hot path
    (pack_codes) supersedes it with the int8/int16 operand, and
    building the rows*F*4-byte int32 copy just to drop it would cost
    the very HBM the packing saves."""
    from h2o3_tpu.parallel.mesh import current_mesh, n_data_shards
    from jax.sharding import NamedSharding, PartitionSpec as P

    from h2o3_tpu.resilience import resilient_device_put

    mesh = mesh or current_mesh()
    nd = n_data_shards(mesh)
    rows, F = codes_rm.shape
    if rows % nd == 0:
        codes_rm = resilient_device_put(
            codes_rm, NamedSharding(mesh, P("data")), pipeline="train")
    if not with_t or jax.default_backend() != "tpu":
        return CodesView(rm=codes_rm, t=None)
    from h2o3_tpu.ops.hist_pallas import FBLK

    def build_t(rm_local):
        rows_l = rm_local.shape[0]
        pad_r = (-rows_l) % tile
        pad_f = (-F) % FBLK
        return jnp.pad(rm_local.astype(jnp.int32).T, ((0, pad_f), (0, pad_r)))

    if rows % nd == 0 and nd > 1:
        t = jax.jit(jax.shard_map(build_t, mesh=mesh, in_specs=P("data"),
                                  out_specs=P(None, "data")))(codes_rm)
    else:
        t = build_t(codes_rm)
        t = resilient_device_put(t, NamedSharding(mesh, P(None, "data")),
                                 pipeline="train")
    return CodesView(rm=codes_rm, t=t)


@partial(jax.jit, static_argnames=("na", "W", "dt"))
def _repack_codes(c, *, na: int, W: int, dt):
    """NA code n_bins -> reserved lane W-1, narrowed to the kernel
    dtype. Module-level jit (static na/W/dt) so a warm retrain reuses
    the executable — no per-call wrapper, no stray recompile."""
    ci = c.astype(jnp.int32)
    return jnp.where(ci == na, W - 1, ci).astype(dt)


@partial(jax.jit, static_argnames=("W", "tile"))
def _pack_t_single(rm, *, W: int, tile: int):
    rows_l = rm.shape[0]
    pad_r = (-rows_l) % tile
    return jnp.pad(rm.T, ((0, 0), (0, pad_r)), constant_values=W - 1)


@lru_cache(maxsize=32)
def _pack_t_sharded(mesh, W: int, tile: int):
    """Cached shard_map transpose builder per (mesh, W): shard i's t
    columns are shard i's rm rows, padded per shard."""
    from jax.sharding import PartitionSpec as P

    def build_t(rm_local):
        rows_l = rm_local.shape[0]
        pad_r = (-rows_l) % tile
        return jnp.pad(rm_local.T, ((0, 0), (0, pad_r)),
                       constant_values=W - 1)

    return jax.jit(jax.shard_map(build_t, mesh=mesh, in_specs=P("data"),
                                 out_specs=P(None, "data")))


def pack_codes(bm: "BinnedMatrix", mesh=None) -> PackedCodes:
    """Pack a BinnedMatrix's codes for the binned pallas level kernel:
    remap NA (code == n_bins) to the reserved lane W-1, narrow to the
    smallest kernel dtype (int8 for W <= 128, else int16), and build
    the transposed tile-padded hot-loop operand on TPU (or under the
    interpret escape). Sharding mirrors make_codes_view: rm stays
    [rows@data, F]; t is [F, rows_p@data] padded PER SHARD so shard
    i's t columns are shard i's rm rows."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from h2o3_tpu.ops.hist_adaptive import (TILE, code_dtype,
                                            pallas_interpret, pick_W)
    from h2o3_tpu.parallel.mesh import current_mesh, n_data_shards

    W = pick_W(bm.n_bins)
    dt = code_dtype(W)
    rm = _repack_codes(bm.codes.rm, na=bm.n_bins, W=W, dt=dt)
    if not (jax.default_backend() == "tpu" or pallas_interpret()):
        return PackedCodes(rm=rm, t=None, W=W)
    mesh = mesh or current_mesh()
    nd = n_data_shards(mesh)
    rows = rm.shape[0]
    if rows % nd == 0 and nd > 1:
        t = _pack_t_sharded(mesh, W, TILE)(rm)
    else:
        from h2o3_tpu.resilience import resilient_device_put
        t = _pack_t_single(rm, W=W, tile=TILE)
        t = resilient_device_put(t, NamedSharding(mesh, P(None, "data")),
                                 pipeline="train")
    return PackedCodes(rm=rm, t=t, W=W)


def stripe_pair_codes(ct, W: int):
    """Stripe-aware relayout of the transposed packed operand for the
    W=16 stripe kernel (ops/hist_adaptive._kernel_bt_stripe): features
    pair up two-per-32-lane stripe, so an ODD feature count pads one
    all-NA feature row (code W-1 — zero split mass; the kernel slices
    its histogram columns away). Even F passes through untouched — the
    pairing itself needs no data movement, adjacent rows already form
    the stripes."""
    F = ct.shape[0]
    if F % 2 == 0:
        return ct
    return jnp.pad(ct, ((0, 1), (0, 0)), constant_values=W - 1)


def pack_codes_for(X, bm: "BinnedMatrix", W: Optional[int] = None):
    """Digitise a NEW matrix (validation / scoring frame) with the
    training sketch's edges and pack it to the kernel convention
    (NA = reserved bin W-1, kernel dtype). Row-major only —
    predict_binned walks it with na_bin = W-1."""
    from h2o3_tpu.ops.hist_adaptive import code_dtype, pick_W
    W = W or pick_W(bm.n_bins)
    c = digitize_with_edges(X, bm.edges, bm.n_bins)
    return _repack_codes(c, na=bm.n_bins, W=W, dt=code_dtype(W))


@jax.jit
def _searchsorted_cols(emat, x):
    # vmap over features: edges [F, E], x [rows, F] → codes [rows, F]
    return jax.vmap(lambda e, c: jnp.searchsorted(e, c, side="right"),
                    in_axes=(0, 1), out_axes=1)(emat, x)


def _digitize(x, emat, nbins, dtype):
    codes = _searchsorted_cols(emat, x)
    codes = jnp.where(jnp.isnan(x), nbins, codes)
    return codes.astype(dtype)


def digitize_with_edges(X, edges: List[np.ndarray], nbins: int) -> jax.Array:
    """Digitise a new matrix with previously-computed edges (validation /
    scoring frames share the training sketch, like XGBoost's global hist)."""
    F = len(edges)
    max_e = max((len(e) for e in edges), default=0)
    emat = np.full((F, max(max_e, 1)), np.inf, dtype=np.float32)
    for f, e in enumerate(edges):
        emat[f, : len(e)] = e
    dtype = jnp.uint8 if nbins < 256 else jnp.int32
    return _digitize(jnp.asarray(X, dtype=jnp.float32), jnp.asarray(emat),
                     nbins, dtype)


def split_threshold(bm: BinnedMatrix, feature: int, bin_idx: int) -> float:
    """Raw-value threshold for 'left ⇔ code < bin_idx'. A split bin beyond
    the edge list means 'all non-NA left' → +inf (see
    models.tree.bins_to_thresholds)."""
    e = bm.edges[feature]
    if len(e) == 0 or bin_idx - 1 >= len(e):
        return float("inf")
    return float(e[bin_idx - 1])
