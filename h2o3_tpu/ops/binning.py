"""Global quantile binning — feature values → small int bin codes.

Reference: the tree algos bin features per-node with DHistogram
(hex/tree/DHistogram.java:48; QuantilesGlobal/UniformAdaptive histogram
types in GBM), and the vendored XGBoost's ``tree_method=hist`` builds a
global quantile sketch once. The TPU design follows the global-sketch
shape: one pass computes per-feature quantile edges, a second digitises
every value into a uint8/int16 code. All later tree work touches only the
code matrix — int codes stream through HBM at 1-2 bytes/value and feed the
MXU one-hot histogram kernel (SURVEY.md §7.3).

Layout: codes[rows, F] with values in [0, n_bins_f); the NA bin is a
dedicated last index ``n_bins`` shared across features (uniform shape for
XLA). Split "bin t" means: left ⇔ code < t ⇔ raw < edges[t-1].
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class CodesView(NamedTuple):
    """Bin codes in both layouts. ``rm`` [rows, F] (compact, for routing/
    predict gathers); ``t`` [Fp, rows_p] int32 (transposed + padded, the
    pallas histogram kernel operand — transposing once here instead of per
    level saves ~40ms/level at 1M rows). ``t`` may be None off-TPU."""
    rm: jax.Array
    t: Optional[jax.Array]

    @property
    def shape(self):
        return self.rm.shape

    @property
    def dtype(self):
        return self.rm.dtype


@dataclass
class BinnedMatrix:
    codes: CodesView           # NA bin = n_bins
    n_bins: int                # bins per feature excluding the NA bin
    edges: List[np.ndarray]    # per-feature raw-value split edges (len <= n_bins-1)
    names: List[str]
    is_categorical: List[bool]
    nrow: int

    @property
    def n_features(self) -> int:
        return self.codes.rm.shape[1]

    @property
    def na_bin(self) -> int:
        return self.n_bins


def quantile_edges(col: np.ndarray, nbins: int) -> np.ndarray:
    """Unique quantile cut points for one numeric feature (host-side; the
    sketch is O(sample) — full exact quantiles are fine at these scales)."""
    vals = col[np.isfinite(col)]
    if vals.size == 0:
        return np.empty(0, dtype=np.float32)
    qs = np.quantile(vals, np.linspace(0.0, 1.0, nbins + 1)[1:-1])
    return np.unique(qs.astype(np.float32))


def uniform_edges(col: np.ndarray, nbins: int) -> np.ndarray:
    """Equal-width cut points (histogram_type='uniform_adaptive' analog:
    the reference re-adapts ranges per tree level; a global uniform grid is
    the static-shape equivalent)."""
    vals = col[np.isfinite(col)]
    if vals.size == 0:
        return np.empty(0, dtype=np.float32)
    lo, hi = float(vals.min()), float(vals.max())
    if lo == hi:
        return np.empty(0, dtype=np.float32)
    return np.linspace(lo, hi, nbins + 1)[1:-1].astype(np.float32)


def bin_matrix(X, names: Sequence[str], is_cat: Sequence[bool], nrow: int,
               nbins: int = 255, nbins_cats: int = 1024,
               histogram_type: str = "quantiles_global") -> BinnedMatrix:
    """Digitise a dense [padded_rows, F] float matrix (NaN = NA) into codes.

    Categorical columns with cardinality <= nbins use identity binning
    (code = category id), mirroring nbins_cats group-per-category splits
    (hex/tree/DHistogram nbins_cats); larger cardinalities fall back to
    quantile grouping of the code space.
    """
    X_host = np.asarray(X, dtype=np.float32)
    F = X_host.shape[1]
    edge_fn = (uniform_edges if histogram_type in ("uniform_adaptive", "uniform")
               else quantile_edges)
    edges: List[np.ndarray] = []
    for f in range(F):
        col = X_host[:nrow, f]
        if is_cat[f]:
            card = int(np.nanmax(col)) + 1 if np.isfinite(col).any() else 1
            if card <= nbins:
                e = (np.arange(1, card, dtype=np.float32) - 0.5)
            else:
                e = quantile_edges(col, nbins)
        else:
            e = edge_fn(col, nbins)
        edges.append(e[: nbins - 1])
    codes = make_codes_view(digitize_with_edges(X, edges, nbins))
    return BinnedMatrix(codes=codes, n_bins=nbins, edges=edges, names=list(names),
                        is_categorical=list(is_cat), nrow=nrow)


def make_codes_view(codes_rm, tile: int = 2048) -> CodesView:
    """Build both layouts; the transposed int32 copy only on TPU (it only
    serves the pallas kernel)."""
    if jax.default_backend() != "tpu":
        return CodesView(rm=codes_rm, t=None)
    from h2o3_tpu.ops.hist_pallas import FBLK
    rows, F = codes_rm.shape
    pad_r = (-rows) % tile
    pad_f = (-F) % FBLK
    t = jnp.pad(codes_rm.astype(jnp.int32).T, ((0, pad_f), (0, pad_r)))
    return CodesView(rm=codes_rm, t=t)


@jax.jit
def _searchsorted_cols(emat, x):
    # vmap over features: edges [F, E], x [rows, F] → codes [rows, F]
    return jax.vmap(lambda e, c: jnp.searchsorted(e, c, side="right"),
                    in_axes=(0, 1), out_axes=1)(emat, x)


def _digitize(x, emat, nbins, dtype):
    codes = _searchsorted_cols(emat, x)
    codes = jnp.where(jnp.isnan(x), nbins, codes)
    return codes.astype(dtype)


def digitize_with_edges(X, edges: List[np.ndarray], nbins: int) -> jax.Array:
    """Digitise a new matrix with previously-computed edges (validation /
    scoring frames share the training sketch, like XGBoost's global hist)."""
    F = len(edges)
    max_e = max((len(e) for e in edges), default=0)
    emat = np.full((F, max(max_e, 1)), np.inf, dtype=np.float32)
    for f, e in enumerate(edges):
        emat[f, : len(e)] = e
    dtype = jnp.uint8 if nbins < 256 else jnp.int32
    return _digitize(jnp.asarray(X, dtype=jnp.float32), jnp.asarray(emat),
                     nbins, dtype)


def split_threshold(bm: BinnedMatrix, feature: int, bin_idx: int) -> float:
    """Raw-value threshold for 'left ⇔ code < bin_idx'."""
    e = bm.edges[feature]
    return float(e[min(bin_idx, len(e)) - 1])
