"""Per-(node, feature, bin) gradient histograms — the make-or-break kernel.

Reference hot loop: hex/tree/ScoreBuildHistogram2.java:121-301 — per row,
look up the row's leaf, then for every column bump (w, wY, wYY) in a
thread-private DHistogram bin array; private copies merge node-locally,
then elementwise-add up the MRTask reduce tree (DHistogram.java:432).
XGBoost's gpu_hist does the same with atomics + a Rabit allreduce.

TPUs have no fast random scatter, so the TPU-native formulation is a
matmul: one-hot encode each row's (node, bin) pair and contract with the
per-row (g, h, w) on the MXU (SURVEY.md §7.3 angle). Cross-device
reduction is a single ``psum`` over the 'data' mesh axis (replacing the
serialize-and-merge tree / Rabit ring).

Contract: ``build_histograms(codes, seg_ids, ghw, n_nodes, n_bins1)``
returns a ``(g_hist, h_hist, w_hist)`` triple, each [n_nodes, F', B']
float32 with F' >= F and B' >= n_bins1 (the pallas path returns its
padded widths; trailing features/bins are zero). Rows whose seg_id is
outside [0, n_nodes) are excluded — callers route dead rows out-of-band
instead of multiplying weights by masks.

Three code paths:
- 'pallas'  — fused VMEM one-hot matmul (ops/hist_pallas.py); TPU default;
- 'matmul'  — lax.scan over features of an XLA one-hot matmul;
- 'scatter' — XLA scatter-add; wins on CPU and for very small shapes.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import DATA_AXIS


def _hist_scatter3(codes, seg, ghw, n_nodes, n_bins1):
    """Triple of [n_nodes, F, B1] via scatter-add (CPU path)."""
    rows, F = codes.shape
    valid = (seg >= 0) & (seg < n_nodes)
    s = jnp.clip(seg, 0, n_nodes - 1)
    flat = (s[:, None] * F + jnp.arange(F)) * n_bins1 + codes.astype(jnp.int32)
    out = jnp.zeros((n_nodes * F * n_bins1, 3), dtype=jnp.float32)
    vw = jnp.where(valid, 1.0, 0.0)
    out = out.at[flat, 0].add((ghw[0] * vw)[:, None])
    out = out.at[flat, 1].add((ghw[1] * vw)[:, None])
    out = out.at[flat, 2].add((ghw[2] * vw)[:, None])
    h = out.reshape(n_nodes, F, n_bins1, 3)
    return h[..., 0], h[..., 1], h[..., 2]


def _hist_matmul3(codes, seg, ghw, n_nodes, n_bins1):
    """Triple of [n_nodes, F, B1] via one-hot matmul (XLA fallback)."""
    rows, F = codes.shape
    ghw_t = ghw.T                        # [rows, 3]
    base = seg * n_bins1                 # [rows]; OOB seg → no one-hot match
    nb = n_nodes * n_bins1

    def one_feature(_, f):
        idx = base + codes[:, f].astype(jnp.int32)
        onehot = (idx[:, None] == jnp.arange(nb)[None, :]).astype(jnp.float32)
        part = jax.lax.dot_general(
            onehot, ghw_t, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [nb, 3]
        return _, part

    _, hists = jax.lax.scan(one_feature, None, jnp.arange(F))
    h = hists.reshape(F, n_nodes, n_bins1, 3).transpose(1, 0, 2, 3)
    return h[..., 0], h[..., 1], h[..., 2]


def build_histograms(codes, seg_ids, ghw, n_nodes: int, n_bins1: int,
                     method: str = "auto"):
    """Local (per-shard or single-device) histogram build; see module
    docstring for the (g,h,w) triple contract. Caller is responsible for
    the cross-device psum when run under shard_map.

    ``codes`` may be a plain [rows, F] int array or a binning.CodesView
    (whose pre-transposed layout feeds the pallas kernel directly)."""
    from h2o3_tpu.ops.binning import CodesView
    rm = codes.rm if isinstance(codes, CodesView) else codes
    codes_t = codes.t if isinstance(codes, CodesView) else None
    if method == "auto":
        method = "pallas" if jax.default_backend() == "tpu" else "scatter"
    seg = seg_ids.astype(jnp.int32)
    if method == "pallas":
        from h2o3_tpu.ops.hist_pallas import FBLK, TILE, hist_pallas3
        if codes_t is None:
            rows, F = rm.shape
            pad_r = (-rows) % TILE
            pad_f = (-F) % FBLK
            codes_t = jnp.pad(rm.astype(jnp.int32).T,
                              ((0, pad_f), (0, pad_r)))
        rows_p = codes_t.shape[1]
        if rows_p != seg.shape[0]:
            seg = jnp.pad(seg, (0, rows_p - seg.shape[0]), constant_values=-1)
            ghw = jnp.pad(ghw, ((0, 0), (0, rows_p - ghw.shape[1])))
        return hist_pallas3(codes_t, seg, ghw, n_nodes, n_bins1)
    fn = _hist_matmul3 if method == "matmul" else _hist_scatter3
    return fn(rm, seg, ghw, n_nodes, n_bins1)


def build_histograms_sharded(codes, seg_ids, ghw, n_nodes: int,
                             n_bins1: int, mesh, method: str = "auto"):
    """Distributed histogram: per-shard build + ICI all-reduce.

    This is the TPU replacement for XGBoost's Rabit histogram allreduce
    (hex/tree/xgboost/rabit/RabitTrackerH2O.java bootstraps the ring; here
    it's one lax.psum over the 'data' axis).
    """
    from jax.sharding import PartitionSpec as P

    def local(c, s, gh):
        trip = build_histograms(c, s, gh, n_nodes, n_bins1, method)
        return jax.lax.psum(trip, DATA_AXIS)

    f = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(None, DATA_AXIS)),
        out_specs=(P(), P(), P()))
    return f(codes, seg_ids, ghw)
