"""Per-(node, feature, bin) gradient histograms — the make-or-break kernel.

Reference hot loop: hex/tree/ScoreBuildHistogram2.java:121-301 — per row,
look up the row's leaf, then for every column bump (w, wY, wYY) in a
thread-private DHistogram bin array; private copies merge node-locally,
then elementwise-add up the MRTask reduce tree (DHistogram.java:432).
XGBoost's gpu_hist does the same with atomics + a Rabit allreduce.

TPUs have no fast random scatter, so the TPU-native formulation is a
matmul: one-hot encode each row's (node, bin) pair and contract with the
per-row (g, h, w) on the MXU — ``hist = onehot^T @ ghw`` per feature
(SURVEY.md §7.3 angle). Cross-device reduction is a single ``psum`` over
the 'data' mesh axis (replacing the serialize-and-merge tree / Rabit ring).

Two code paths:
- 'matmul'  — lax.scan over features of a [rows, n_nodes*(B+1)] one-hot
  matmul; MXU-bound, the TPU default;
- 'scatter' — XLA scatter-add; wins on CPU and for very small shapes.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import DATA_AXIS


def _hist_scatter(codes, node_ids, g, h, w, n_nodes, n_bins1):
    """[n_nodes, F, B+1, 3] via scatter-add."""
    rows, F = codes.shape
    flat = (node_ids[:, None] * F + jnp.arange(F)[None, :]) * n_bins1 + codes
    out = jnp.zeros((n_nodes * F * n_bins1, 3), dtype=jnp.float32)
    out = out.at[flat, 0].add(g[:, None])
    out = out.at[flat, 1].add(h[:, None])
    out = out.at[flat, 2].add(w[:, None])
    return out.reshape(n_nodes, F, n_bins1, 3)


def _hist_matmul(codes, node_ids, g, h, w, n_nodes, n_bins1):
    """[n_nodes, F, B+1, 3] via one-hot matmul on the MXU."""
    rows, F = codes.shape
    ghw = jnp.stack([g, h, w], axis=1)  # [rows, 3]
    base = node_ids * n_bins1           # [rows]
    nb = n_nodes * n_bins1

    def one_feature(_, f):
        idx = base + codes[:, f]
        onehot = (idx[:, None] == jnp.arange(nb)[None, :]).astype(jnp.float32)
        part = jax.lax.dot_general(
            onehot, ghw, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [nb, 3]
        return _, part

    _, hists = jax.lax.scan(one_feature, None, jnp.arange(F))
    # hists: [F, nb, 3] → [n_nodes, F, B+1, 3]
    return hists.reshape(F, n_nodes, n_bins1, 3).transpose(1, 0, 2, 3)


def build_histograms(codes, node_ids, g, h, w, n_nodes: int, n_bins1: int,
                     method: str = "auto"):
    """Local (per-shard or single-device) histogram build. Caller is
    responsible for the cross-device psum when run under shard_map.

    Methods: 'pallas' (fused VMEM one-hot matmul, ~13x the XLA matmul on
    v5e — see ops/hist_pallas.py), 'matmul' (XLA one-hot dot), 'scatter'
    (XLA scatter-add; CPU default), 'auto'.

    ``codes`` may be a plain [rows, F] int array or a binning.CodesView
    (whose pre-transposed layout feeds the pallas kernel directly)."""
    from h2o3_tpu.ops.binning import CodesView
    rm = codes.rm if isinstance(codes, CodesView) else codes
    codes_t = codes.t if isinstance(codes, CodesView) else None
    if method == "auto":
        method = "pallas" if jax.default_backend() == "tpu" else "scatter"
    if method == "pallas":
        from h2o3_tpu.ops.hist_pallas import hist_pallas_from_rowmajor
        return hist_pallas_from_rowmajor(rm, node_ids, g, h, w, n_nodes,
                                         n_bins1, codes_t=codes_t)
    fn = _hist_matmul if method == "matmul" else _hist_scatter
    return fn(rm, node_ids.astype(jnp.int32), g, h, w, n_nodes, n_bins1)


def build_histograms_sharded(codes, node_ids, g, h, w, n_nodes: int,
                             n_bins1: int, mesh, method: str = "auto"):
    """Distributed histogram: per-shard build + ICI all-reduce.

    This is the TPU replacement for XGBoost's Rabit histogram allreduce
    (hex/tree/xgboost/rabit/RabitTrackerH2O.java bootstraps the ring; here
    it's one lax.psum over the 'data' axis).
    """
    from jax.sharding import PartitionSpec as P

    def local(c, nid, gg, hh, ww):
        hist = build_histograms(c, nid, gg, hh, ww, n_nodes, n_bins1, method)
        return jax.lax.psum(hist, DATA_AXIS)

    f = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P())
    return f(codes, node_ids, g, h, w)
