"""Fused route + bin + histogram level kernel with per-node ADAPTIVE
uniform bins — the r3 flagship tree kernel.

Reference semantics: hex/tree/DHistogram.java — H2O's default
``histogram_type=UniformAdaptive`` re-bins every feature PER NODE over
the node's value range with ``nbins`` uniform bins, refining resolution
as the tree descends (DHistogram.java:48 ``_min/_maxEx`` per node;
ScoreBuildHistogram2.java:121-301 builds (w, wY, wYY) per bin). This is
unlike XGBoost's global 256-bin sketch: after d levels a feature's
effective resolution is ~nbins·2^d.

TPU re-design (one pallas kernel call per tree level):
  1. ROUTE: each row steps through the previous level's split tables
     (feat/thr/na_left/can per node). Table lookups are one-hot matmuls
     at HIGHEST precision (no vector gathers on TPU); the split-feature
     value is selected by compare-accumulate over the F lanes.
  2. BIN:  b = isnan(x) ? W-1 : clip((x - lo[n,f]) * inv[n,f], 0, W-2)
     with per-(node, feature) range tables — again via one-hot matmul.
  3. HIST: acc[(k,n), (f,b)] += ghw[k,r] as a node-onehot × bin-onehot
     MXU contraction, accumulated in VMEM across row tiles.

The cross-shard reduction (MRTask reduce tree / Rabit ring analog,
water/MRTask.java:871, hex/tree/xgboost/rabit/RabitTrackerH2O.java) is a
single ``lax.psum`` of the returned histogram by the caller.

Deviation from the reference, documented: child ranges are derived from
the parent's split point (split feature — exact) and the parent's
occupied-bin range (other features — within one bin width), instead of
re-measuring exact per-child min/max; and routing compares raw
``x >= thr`` so training-time routing is bit-identical to scoring-time
tree walks.

W (bin lanes per feature) is static per compile: 64 / 128 / 256 covering
nbins ≤ 62 / 126 / 254; the last lane is the NA bin.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 2048


def _kernel(x_ref, nid_ref, ghw_ref, feat_ref, thr_ref, nal_ref, can_ref,
            lo_ref, inv_ref, nid_out, hist_out, acc_ref, *, n_prev: int,
            n_nodes: int, F: int, W: int, tile: int, n_row_tiles: int,
            level_base: int, mxu_dtype):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                   # [tile, F] f32
    nid = nid_ref[0, :]                              # [tile] i32 global ids
    HI = jax.lax.Precision.HIGHEST

    if n_prev > 0:
        prev_base = level_base - n_prev
        lid_p = nid - prev_base
        onp = (jax.lax.broadcasted_iota(jnp.int32, (n_prev, tile), 0)
               == lid_p[None, :]).astype(jnp.float32)

        def lut(tbl_ref):
            # HIGHEST precision: a bf16-rounded threshold flips routing
            # for rows near the split boundary
            t = tbl_ref[0, :n_prev].astype(jnp.float32)
            return jax.lax.dot_general(
                t[None, :], onp, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=HI)[0]

        f_r = lut(feat_ref)
        t_r = lut(thr_ref)
        nl_r = lut(nal_ref)
        cn_r = lut(can_ref)
        # x[r, feat_r] via compare-accumulate (f_r is an exact int-valued
        # float: one-hot matmul of ints < 2^24)
        fi = jax.lax.broadcasted_iota(jnp.int32, (tile, F), 1)
        xsel = jnp.sum(jnp.where(fi == f_r.astype(jnp.int32)[:, None],
                                 x, 0.0), axis=1)
        # float selects only: bool-branch select_n lowers to an i8→i1
        # truncation Mosaic rejects
        gr_f = jnp.where(jnp.isnan(xsel), 1.0 - nl_r,
                         (xsel >= t_r).astype(jnp.float32))
        in_prev = (lid_p >= 0) & (lid_p < n_prev)
        child = 2 * nid + 1 + gr_f.astype(jnp.int32)
        nid = jnp.where(in_prev & (cn_r > 0.5), child, nid)
    nid_out[0, :] = nid

    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    onh = (jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
           == lidc[None, :])
    onh_f = onh.astype(jnp.float32) * in_lvl.astype(jnp.float32)[None, :]
    # per-row ranges [tile, F] = onhᵀ @ lo (exact f32 so bin boundaries
    # match the split-side threshold arithmetic)
    lo_r = jax.lax.dot_general(onh_f, lo_ref[...], (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=HI)
    inv_r = jax.lax.dot_general(onh_f, inv_ref[...], (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=HI)
    bin_f = jnp.clip((x - lo_r) * inv_r, 0.0, float(W - 2))
    bin_i = jnp.where(jnp.isnan(x), W - 1, bin_f.astype(jnp.int32))
    b_all = jnp.concatenate(
        [jnp.broadcast_to(bin_i[:, f:f + 1], (tile, W)) for f in range(F)],
        axis=1)                                           # [tile, F*W]
    lane = jax.lax.broadcasted_iota(jnp.int32, (tile, F * W), 1)
    oh = ((lane % W) == b_all).astype(mxu_dtype)
    ghw = ghw_ref[...]
    left = jnp.concatenate(
        [onh_f.astype(mxu_dtype) * ghw[k, :][None, :].astype(mxu_dtype)
         for k in range(3)], axis=0)                      # [3N, tile]
    acc_ref[...] += jax.lax.dot_general(
        left, oh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=(HI if mxu_dtype == jnp.float32
                   else jax.lax.Precision.DEFAULT))       # [3N, F*W]

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        hist_out[...] = acc_ref[...]


def adaptive_level_tpu(x, nid, ghw, tables, lo, inv, n_prev: int,
                       n_nodes: int, level_base: int, W: int,
                       tile: int = TILE, interpret: bool = False,
                       mxu_dtype=jnp.bfloat16):
    """One tree level on one shard. x [rows, F] f32 (NaN=NA; rows % tile
    == 0), nid [rows] i32, ghw [3, rows] f32, tables = (feat, thr,
    na_left, can) each [max(n_prev,1)] f32, lo/inv [n_nodes, F] f32.
    Returns (nid' [rows] i32, hist [3, n_nodes, F, W] f32 — caller psums
    across shards)."""
    rows, F = x.shape
    assert rows % tile == 0, (rows, tile)
    n_row_tiles = rows // tile
    feat, thr, nal, can = tables
    np1 = max(n_prev, 1)
    kern = functools.partial(_kernel, n_prev=n_prev, n_nodes=n_nodes, F=F,
                             W=W, tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base, mxu_dtype=mxu_dtype)
    nid2, hist = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((tile, F), lambda r: (r, 0)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3, tile), lambda r: (0, r)),
            pl.BlockSpec((1, np1), lambda r: (0, 0)),
            pl.BlockSpec((1, np1), lambda r: (0, 0)),
            pl.BlockSpec((1, np1), lambda r: (0, 0)),
            pl.BlockSpec((1, np1), lambda r: (0, 0)),
            pl.BlockSpec((n_nodes, F), lambda r: (0, 0)),
            pl.BlockSpec((n_nodes, F), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, F * W), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, F * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, F * W), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * n_nodes * F * W * rows,
            bytes_accessed=rows * F * 4 + rows * 16, transcendentals=0),
        interpret=interpret,
    )(x, nid[None, :], ghw, feat[None, :], thr[None, :], nal[None, :],
      can[None, :], lo, inv)
    return nid2[0], hist.reshape(3, n_nodes, F, W)


def adaptive_level_xla(x, nid, ghw, tables, lo, inv, n_prev: int,
                       n_nodes: int, level_base: int, W: int):
    """Pure-XLA reference/CPU path with identical semantics (scatter-add
    histogram). Used off-TPU and by parity tests."""
    rows, F = x.shape
    feat, thr, nal, can = tables
    if n_prev > 0:
        prev_base = level_base - n_prev
        lid_p = jnp.clip(nid - prev_base, 0, n_prev - 1)
        in_prev = (nid >= prev_base) & (nid < prev_base + n_prev)
        f_r = feat[lid_p].astype(jnp.int32)
        t_r = thr[lid_p]
        nl_r = nal[lid_p]
        cn_r = can[lid_p]
        xsel = jnp.take_along_axis(x, f_r[:, None], axis=1)[:, 0]
        go_right = jnp.where(jnp.isnan(xsel), nl_r < 0.5, xsel >= t_r)
        child = 2 * nid + 1 + go_right.astype(jnp.int32)
        nid = jnp.where(in_prev & (cn_r > 0.5), child, nid)
    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    lo_r = lo[lidc]                                   # [rows, F]
    inv_r = inv[lidc]
    bin_f = jnp.clip((x - lo_r) * inv_r, 0.0, float(W - 2))
    bin_i = jnp.where(jnp.isnan(x), W - 1, bin_f.astype(jnp.int32))
    flat = (lidc[:, None] * F + jnp.arange(F)[None, :]) * W + bin_i
    vw = jnp.where(in_lvl, 1.0, 0.0)
    out = jnp.zeros((n_nodes * F * W, 3), jnp.float32)
    out = out.at[flat.reshape(-1), :].add(
        (ghw.T * vw[:, None])[:, None, :].repeat(F, axis=1).reshape(-1, 3))
    hist = out.reshape(n_nodes, F, W, 3)
    return nid, jnp.moveaxis(hist, -1, 0)


def adaptive_level(x, nid, ghw, tables, lo, inv, n_prev: int, n_nodes: int,
                   level_base: int, W: int, method: str = "auto"):
    """Dispatch: pallas on TPU (padding rows to the tile size), scatter-XLA
    elsewhere."""
    if method == "auto":
        method = "pallas" if jax.default_backend() == "tpu" else "scatter"
    if method == "pallas":
        rows = x.shape[0]
        pad = (-rows) % TILE
        if pad:
            # pad rows: NaN features (NA bin) with zero ghw mass — they
            # route but contribute nothing
            x = jnp.pad(x, ((0, pad), (0, 0)), constant_values=jnp.nan)
            nid = jnp.pad(nid, (0, pad))
            ghw = jnp.pad(ghw, ((0, 0), (0, pad)))
        nid2, hist = adaptive_level_tpu(x, nid, ghw, tables, lo, inv, n_prev,
                                        n_nodes, level_base, W)
        return nid2[:rows], hist
    return adaptive_level_xla(x, nid, ghw, tables, lo, inv, n_prev,
                              n_nodes, level_base, W)


def pick_W(nbins: int) -> int:
    """Smallest supported lane width for nbins real bins (+1 NA lane)."""
    for w in (64, 128, 256):
        if nbins <= w - 2:
            return w
    raise ValueError(f"nbins {nbins} exceeds the adaptive kernel's 254-bin "
                     f"cap; use histogram_type='quantiles_global'")


def _totals_kernel(x_ref, nid_ref, ghw_ref, feat_ref, thr_ref, nal_ref,
                   can_ref, nid_out, tot_out, acc_ref, *, n_prev: int,
                   n_nodes: int, F: int, tile: int, n_row_tiles: int,
                   level_base: int):
    """Route one level then accumulate exact f32 (g,h,w) sums per node —
    the deepest-level leaf statistics (no bin histogram, no bf16)."""
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    nid = nid_ref[0, :]
    HI = jax.lax.Precision.HIGHEST
    if n_prev > 0:
        prev_base = level_base - n_prev
        lid_p = nid - prev_base
        onp = (jax.lax.broadcasted_iota(jnp.int32, (n_prev, tile), 0)
               == lid_p[None, :]).astype(jnp.float32)

        def lut(tbl_ref):
            t = tbl_ref[0, :n_prev].astype(jnp.float32)
            return jax.lax.dot_general(
                t[None, :], onp, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=HI)[0]

        f_r = lut(feat_ref)
        t_r = lut(thr_ref)
        nl_r = lut(nal_ref)
        cn_r = lut(can_ref)
        fi = jax.lax.broadcasted_iota(jnp.int32, (tile, F), 1)
        xsel = jnp.sum(jnp.where(fi == f_r.astype(jnp.int32)[:, None],
                                 x, 0.0), axis=1)
        gr_f = jnp.where(jnp.isnan(xsel), 1.0 - nl_r,
                         (xsel >= t_r).astype(jnp.float32))
        in_prev = (lid_p >= 0) & (lid_p < n_prev)
        child = 2 * nid + 1 + gr_f.astype(jnp.int32)
        nid = jnp.where(in_prev & (cn_r > 0.5), child, nid)
    nid_out[0, :] = nid
    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    onh = (jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
           == lidc[None, :])
    onh_f = onh.astype(jnp.float32) * in_lvl.astype(jnp.float32)[None, :]
    ghw = ghw_ref[...]
    left = jnp.concatenate([onh_f * ghw[k, :][None, :] for k in range(3)],
                           axis=0)                       # [3N, tile] f32
    # all 128 lanes carry the same sum (single-lane stores are awkward in
    # Mosaic); the caller reads lane 0
    acc_ref[...] += jnp.broadcast_to(
        jnp.sum(left, axis=1, keepdims=True), acc_ref.shape)

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        tot_out[...] = acc_ref[...]


def leaf_totals_tpu(x, nid, ghw, tables, n_prev: int, n_nodes: int,
                    level_base: int, tile: int = TILE,
                    interpret: bool = False):
    """Final-level route + exact per-leaf (g,h,w) totals.
    Returns (nid', totals [3, n_nodes])."""
    rows, F = x.shape
    assert rows % tile == 0
    n_row_tiles = rows // tile
    feat, thr, nal, can = tables
    np1 = max(n_prev, 1)
    kern = functools.partial(_totals_kernel, n_prev=n_prev, n_nodes=n_nodes,
                             F=F, tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base)
    nid2, tot = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((tile, F), lambda r: (r, 0)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3, tile), lambda r: (0, r)),
            pl.BlockSpec((1, np1), lambda r: (0, 0)),
            pl.BlockSpec((1, np1), lambda r: (0, 0)),
            pl.BlockSpec((1, np1), lambda r: (0, 0)),
            pl.BlockSpec((1, np1), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, 128), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, 128), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, 128), jnp.float32)],
        interpret=interpret,
    )(x, nid[None, :], ghw, feat[None, :], thr[None, :], nal[None, :],
      can[None, :])
    return nid2[0], tot[:, 0].reshape(3, n_nodes)


def leaf_totals_xla(x, nid, ghw, tables, n_prev: int, n_nodes: int,
                    level_base: int):
    rows, F = x.shape
    feat, thr, nal, can = tables
    if n_prev > 0:
        prev_base = level_base - n_prev
        lid_p = jnp.clip(nid - prev_base, 0, n_prev - 1)
        in_prev = (nid >= prev_base) & (nid < prev_base + n_prev)
        f_r = feat[lid_p].astype(jnp.int32)
        xsel = jnp.take_along_axis(x, f_r[:, None], axis=1)[:, 0]
        go_right = jnp.where(jnp.isnan(xsel), nal[lid_p] < 0.5,
                             xsel >= thr[lid_p])
        child = 2 * nid + 1 + go_right.astype(jnp.int32)
        nid = jnp.where(in_prev & (can[lid_p] > 0.5), child, nid)
    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    vw = jnp.where(in_lvl, 1.0, 0.0)
    tot = jnp.zeros((n_nodes, 3), jnp.float32).at[lidc].add(
        (ghw * vw[None, :]).T)
    return nid, tot.T


def leaf_totals(x, nid, ghw, tables, n_prev: int, n_nodes: int,
                level_base: int, method: str = "auto"):
    if method == "auto":
        method = "pallas" if jax.default_backend() == "tpu" else "scatter"
    if method == "pallas":
        rows = x.shape[0]
        pad = (-rows) % TILE
        if pad:
            x = jnp.pad(x, ((0, pad), (0, 0)), constant_values=jnp.nan)
            nid = jnp.pad(nid, (0, pad))
            ghw = jnp.pad(ghw, ((0, 0), (0, pad)))
        nid2, tot = leaf_totals_tpu(x, nid, ghw, tables, n_prev, n_nodes,
                                    level_base)
        return nid2[:rows], tot
    return leaf_totals_xla(x, nid, ghw, tables, n_prev, n_nodes, level_base)
