"""Fused route + bin + histogram level kernel with per-node ADAPTIVE
uniform bins — the r3 flagship tree kernel.

Reference semantics: hex/tree/DHistogram.java — H2O's default
``histogram_type=UniformAdaptive`` re-bins every feature PER NODE over
the node's value range with ``nbins`` uniform bins, refining resolution
as the tree descends (DHistogram.java:48 ``_min/_maxEx`` per node;
ScoreBuildHistogram2.java:121-301 builds (w, wY, wYY) per bin). This is
unlike XGBoost's global 256-bin sketch: after d levels a feature's
effective resolution is ~nbins·2^d.

TPU re-design (one pallas kernel call per tree level), in the
TRANSPOSED layout x_t [F, rows] — rows ride the 128-lane axis:
  1. ROUTE: each row steps through the previous level's split tables
     (bf16-split [12, n_prev] = feat/thr/na_left/can, exact via
     _split3_bf16). The lookup is ONE merged one-hot matmul; the
     split-feature value is selected by compare-accumulate over F
     sublanes.
  2. BIN:  b = isnan(x) ? W-1 : floor(clip((x - lo[n,f]) * inv[n,f]))
     with per-(node, feature) range tables — one merged [6F, N] lookup
     matmul against the node one-hot.
  3. HIST: the bin row broadcasts to [F*W, tile] with a SUBLANE repeat
     (cheap relayout; the row-major layout needed a selector matmul
     and a 14MB f32 intermediate here), one-hots against a sublane
     iota, then contracts against node-onehot × (g,h,w) on the MXU
     (lane-dim contraction both sides), accumulating in VMEM.

Why transposed: a [rows, F] device array tiles F onto the 128-lane
minor axis, so F=28 reads waste 100/128 of HBM bandwidth (measured 30
GB/s useful vs 126 GB/s packed on v5e). [F, rows] packs rows into
lanes; F pads only 28→32 sublanes. Layout + sublane-repeat together
took the 10M-row bench from 21.7M to 68.1M rows/s/chip (vs_baseline
0.87 → 2.72) at identical AUC. The row-major kernels are retained for
parity tests.

The cross-shard reduction (MRTask reduce tree / Rabit ring analog,
water/MRTask.java:871, hex/tree/xgboost/rabit/RabitTrackerH2O.java) is a
single ``lax.psum`` of the returned histogram by the caller.

Deviation from the reference, documented: child ranges are derived from
the parent's split point (split feature — exact) and the parent's
occupied-bin range (other features — within one bin width), instead of
re-measuring exact per-child min/max; and routing compares raw
``x >= thr`` so training-time routing is bit-identical to scoring-time
tree walks.

W (bin lanes per feature) is static per compile: 32 / 64 / 128 / 256
covering nbins <= 30 / 62 / 126 / 254; the last lane is the NA bin.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from h2o3_tpu.ops.pallas_compat import CompilerParams as _CompilerParams

import os as _os

TILE = int(_os.environ.get("H2O3_HIST_TILE", 8192))
# default scoped-vmem stack limit is 16MB; the accumulator + one-hot want
# more at deeper levels / larger tiles (v5e has 128MB VMEM)
_VMEM_LIMIT = 100 * 1024 * 1024


_SPLIT_S1 = 256.0        # 2^8  — exact bf16 scaling
_SPLIT_S2 = 65536.0      # 2^16


def _split3_bf16(t: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Exact 3-term bf16 decomposition of an f32 array, concatenated along
    ``axis``: t == hi + mid/2^8 + lo/2^16 bit-for-bit (8+8+8 mantissa bits
    >= f32's 24; the residual after two splits has <= 8 significant bits so
    the third term is exact). A one-hot matmul against the concatenated
    bf16 table then reproduces the f32 lookup EXACTLY with one 1-pass bf16
    MXU product per term — ~6x cheaper than a HIGHEST (f32 6-pass) matmul.

    The mid/lo terms are PRE-SCALED by 2^8 / 2^16 (exact power-of-two
    bf16 ops) and the kernel multiplies the partial results back down
    before summing. The residuals are computed with lax.reduce_precision,
    NOT astype(bf16).astype(f32): under jit, XLA's default
    --xla_allow_excess_precision legally elides f32->bf16->f32 round
    trips, which would zero the residuals and collapse every table entry
    to its bf16 rounding (observed on v5e: t_r == bf16(thr), flipping
    routing for rows within a bf16 ulp of a split threshold)."""
    t = t.astype(jnp.float32)
    hi_v = jax.lax.reduce_precision(t, 8, 7)          # bf16-valued f32
    r1 = (t - hi_v) * _SPLIT_S1
    mid_v = jax.lax.reduce_precision(r1, 8, 7)
    lo_v = (r1 - mid_v) * _SPLIT_S1                   # exact in bf16 already
    return jnp.concatenate([hi_v.astype(jnp.bfloat16),
                            mid_v.astype(jnp.bfloat16),
                            lo_v.astype(jnp.bfloat16)], axis=axis)


def _unsplit3(p_hi, p_mid, p_lo):
    """Recombine partial one-hot lookups of a _split3_bf16 table (f32)."""
    return p_hi + (p_mid * (1.0 / _SPLIT_S1) + p_lo * (1.0 / _SPLIT_S2))


def _route(x, nid, tabs_ref, n_prev, level_base, tile, F):
    """Shared routing block: step rows through the previous level's split
    tables (bf16-split [12, np] = 3 exact terms x feat/thr/na_left/can)
    with ONE merged 1-pass bf16 LUT matmul. The one-hot RHS makes the
    3-term reconstruction exact (see _split3_bf16) — a plain bf16-rounded
    threshold WOULD flip routing for rows near the split boundary."""
    prev_base = level_base - n_prev
    lid_p = nid - prev_base
    onp = (jax.lax.broadcasted_iota(jnp.int32, (n_prev, tile), 0)
           == lid_p[None, :]).astype(jnp.bfloat16)
    t12 = tabs_ref[:, :n_prev]                        # [12, n_prev] bf16
    lut3 = jax.lax.dot_general(t12, onp, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)  # [12, tile]
    lut = _unsplit3(lut3[0:4], lut3[4:8], lut3[8:12])  # exact f32 rebuild
    f_r, t_r, nl_r, cn_r = lut[0], lut[1], lut[2], lut[3]
    # x[r, feat_r] via compare-accumulate (f_r is an exact int-valued
    # float: one-hot matmul of ints < 2^24)
    fi = jax.lax.broadcasted_iota(jnp.int32, (tile, F), 1)
    xsel = jnp.sum(jnp.where(fi == f_r.astype(jnp.int32)[:, None],
                             x, 0.0), axis=1)
    # float selects only: bool-branch select_n lowers to an i8->i1
    # truncation Mosaic rejects
    gr_f = jnp.where(jnp.isnan(xsel), 1.0 - nl_r,
                     (xsel >= t_r).astype(jnp.float32))
    in_prev = (lid_p >= 0) & (lid_p < n_prev)
    child = 2 * nid + 1 + gr_f.astype(jnp.int32)
    return jnp.where(in_prev & (cn_r > 0.5), child, nid)


def _kernel(x_ref, nid_ref, ghw_ref, tabs_ref, loinv_ref, nid_out, hist_out,
            acc_ref, *, n_prev: int, n_nodes: int, F: int, W: int, tile: int,
            n_row_tiles: int, level_base: int, mxu_dtype):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                   # [tile, F] f32
    nid = nid_ref[0, :]                              # [tile] i32 global ids
    if n_prev > 0:
        nid = _route(x, nid, tabs_ref, n_prev, level_base, tile, F)
    nid_out[0, :] = nid

    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    onh = (jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
           == lidc[None, :])
    onh_f = onh.astype(jnp.float32) * in_lvl.astype(jnp.float32)[None, :]
    # per-row ranges in ONE merged [N, 6F] bf16-split lookup matmul. Bin
    # boundaries must match the split-side threshold arithmetic exactly;
    # the 3-term bf16 reconstruction against the one-hot LHS is exact
    # (see _split3_bf16) while a rounded lo breaks deep narrowed ranges
    # (|lo| >> span).
    onh_b = onh_f.astype(jnp.bfloat16)
    loinv_r3 = jax.lax.dot_general(onh_b, loinv_ref[...],
                                   (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)  # [tile, 6F]
    loinv_r = _unsplit3(loinv_r3[:, :2 * F], loinv_r3[:, 2 * F:4 * F],
                        loinv_r3[:, 4 * F:])
    lo_r = loinv_r[:, :F]
    inv_r = loinv_r[:, F:]
    bin_f = jnp.floor(jnp.clip((x - lo_r) * inv_r, 0.0, float(W - 2)))
    bin_v = jnp.where(jnp.isnan(x), float(W - 1), bin_f)   # [tile, F] f32
    # bin one-hot via a selector matmul: b_all[r, j] = bin of feature j//W
    # (an F-way lane-offset concatenate costs ~20% of the level at F=28).
    # Exact in ONE bf16 pass: bins and the 0/1 selector are integers
    # <= 254, within bf16's exact-integer range (<= 256).
    sel = (jax.lax.broadcasted_iota(jnp.int32, (F, F * W), 1) // W
           == jax.lax.broadcasted_iota(jnp.int32, (F, F * W), 0)
           ).astype(jnp.bfloat16)
    b_all = jax.lax.dot_general(bin_v.astype(jnp.bfloat16), sel,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (tile, F * W), 1)
    oh = ((lane % W).astype(jnp.float32) == b_all).astype(mxu_dtype)
    ghw = ghw_ref[...]
    left = jnp.concatenate(
        [onh_f.astype(mxu_dtype) * ghw[k, :][None, :].astype(mxu_dtype)
         for k in range(3)], axis=0)                      # [3N, tile]
    acc_ref[...] += jax.lax.dot_general(
        left, oh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=(jax.lax.Precision.HIGHEST if mxu_dtype == jnp.float32
                   else jax.lax.Precision.DEFAULT))       # [3N, FW]

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        hist_out[...] = acc_ref[...]


def _pack_tables(tables):
    feat, thr, nal, can = tables
    t4 = jnp.stack([feat, thr, nal, can], axis=0)         # [4, np1] f32
    return _split3_bf16(t4, axis=0)                       # [12, np1] bf16


def adaptive_level_tpu(x, nid, ghw, tables, lo, inv, n_prev: int,
                       n_nodes: int, level_base: int, W: int,
                       tile: int = TILE, interpret: bool = False,
                       mxu_dtype=jnp.bfloat16):
    """One tree level on one shard. x [rows, F] f32 (NaN=NA; rows % tile
    == 0), nid [rows] i32, ghw [3, rows] f32, tables = (feat, thr,
    na_left, can) each [max(n_prev,1)] f32, lo/inv [n_nodes, F] f32.
    Returns (nid' [rows] i32, hist [3, n_nodes, F, W] f32 — caller psums
    across shards)."""
    rows, F = x.shape
    assert rows % tile == 0, (rows, tile)
    n_row_tiles = rows // tile
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    loinv = _split3_bf16(jnp.concatenate([lo, inv], axis=1),
                         axis=1)                          # [N, 6F] bf16
    kern = functools.partial(_kernel, n_prev=n_prev, n_nodes=n_nodes, F=F,
                             W=W, tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base, mxu_dtype=mxu_dtype)
    nid2, hist = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((tile, F), lambda r: (r, 0)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3, tile), lambda r: (0, r)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
            pl.BlockSpec((n_nodes, 6 * F), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, F * W), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, F * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, F * W), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * n_nodes * F * W * rows,
            bytes_accessed=rows * F * 4 + rows * 16, transcendentals=0),
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(x, nid[None, :], ghw, tabs, loinv)
    return nid2[0], hist.reshape(3, n_nodes, F, W)


def adaptive_level_xla(x, nid, ghw, tables, lo, inv, n_prev: int,
                       n_nodes: int, level_base: int, W: int):
    """Pure-XLA reference/CPU path with identical semantics (scatter-add
    histogram). Used off-TPU and by parity tests."""
    rows, F = x.shape
    feat, thr, nal, can = tables
    if n_prev > 0:
        prev_base = level_base - n_prev
        lid_p = jnp.clip(nid - prev_base, 0, n_prev - 1)
        in_prev = (nid >= prev_base) & (nid < prev_base + n_prev)
        f_r = feat[lid_p].astype(jnp.int32)
        t_r = thr[lid_p]
        nl_r = nal[lid_p]
        cn_r = can[lid_p]
        xsel = jnp.take_along_axis(x, f_r[:, None], axis=1)[:, 0]
        go_right = jnp.where(jnp.isnan(xsel), nl_r < 0.5, xsel >= t_r)
        child = 2 * nid + 1 + go_right.astype(jnp.int32)
        nid = jnp.where(in_prev & (cn_r > 0.5), child, nid)
    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    lo_r = lo[lidc]                                   # [rows, F]
    inv_r = inv[lidc]
    bin_f = jnp.floor(jnp.clip((x - lo_r) * inv_r, 0.0, float(W - 2)))
    bin_i = jnp.where(jnp.isnan(x), W - 1, bin_f.astype(jnp.int32))
    flat = (lidc[:, None] * F + jnp.arange(F)[None, :]) * W + bin_i
    vw = jnp.where(in_lvl, 1.0, 0.0)
    out = jnp.zeros((n_nodes * F * W, 3), jnp.float32)
    out = out.at[flat.reshape(-1), :].add(
        (ghw.T * vw[:, None])[:, None, :].repeat(F, axis=1).reshape(-1, 3))
    hist = out.reshape(n_nodes, F, W, 3)
    return nid, jnp.moveaxis(hist, -1, 0)


def pallas_interpret() -> bool:
    """H2O3_PALLAS_INTERPRET=1 runs the pallas kernels through the
    interpreter — lets the multichip dryrun execute the FLAGSHIP kernel
    path (routing + histogram + cross-shard psum) on the virtual CPU
    mesh, where compiled Mosaic is TPU-only (read at trace time)."""
    return _os.environ.get("H2O3_PALLAS_INTERPRET", "") == "1"


def _resolve_method(method: str) -> str:
    if method != "auto":
        return method
    return "pallas" if (jax.default_backend() == "tpu"
                        or pallas_interpret()) else "scatter"


def adaptive_level(x, nid, ghw, tables, lo, inv, n_prev: int, n_nodes: int,
                   level_base: int, W: int, method: str = "auto",
                   mxu_dtype=jnp.bfloat16, xt=None, qs=None):
    """Dispatch: pallas on TPU (padding rows to the tile size), scatter-XLA
    elsewhere. ``mxu_dtype`` picks the histogram contraction precision —
    see the bf16 deviation bound in the module docstring. ``xt`` ([F,
    rows], rows in LANES) selects the bandwidth-packed transposed kernel
    (callers materialize the transpose once per tree loop). ``qs``
    (optional (q [6, rows] int8, scales [3]) from quantize_ghw_i8)
    enables the exact 2-term int8 fixed-point contraction for levels
    with 6·n_nodes <= 128 — ~1.3x faster AND tighter error than bf16."""
    method = _resolve_method(method)
    if method == "pallas":
        if xt is not None:
            rows = xt.shape[1]
            pad = (-rows) % TILE
            if pad:
                xt = jnp.pad(xt, ((0, 0), (0, pad)),
                             constant_values=jnp.nan)
                nid = jnp.pad(nid, (0, pad))
                ghw = jnp.pad(ghw, ((0, 0), (0, pad)))
            if (qs is not None and qs[0].shape[0] * n_nodes <= 128
                    and mxu_dtype == jnp.bfloat16):
                q, scales = qs
                if pad:
                    q = jnp.pad(q, ((0, 0), (0, pad)))
                nid2, hist = adaptive_level_tpu_i8(
                    xt, nid, q, scales, tables, lo, inv, n_prev, n_nodes,
                    level_base, W, interpret=pallas_interpret())
                return nid2[:rows], hist
            nid2, hist = adaptive_level_tpu_t(xt, nid, ghw, tables, lo, inv,
                                              n_prev, n_nodes, level_base,
                                              W, mxu_dtype=mxu_dtype,
                                              interpret=pallas_interpret())
            return nid2[:rows], hist
        rows = x.shape[0]
        pad = (-rows) % TILE
        if pad:
            # pad rows: NaN features (NA bin) with zero ghw mass — they
            # route but contribute nothing
            x = jnp.pad(x, ((0, pad), (0, 0)), constant_values=jnp.nan)
            nid = jnp.pad(nid, (0, pad))
            ghw = jnp.pad(ghw, ((0, 0), (0, pad)))
        nid2, hist = adaptive_level_tpu(x, nid, ghw, tables, lo, inv, n_prev,
                                        n_nodes, level_base, W,
                                        mxu_dtype=mxu_dtype,
                                        interpret=pallas_interpret())
        return nid2[:rows], hist
    return adaptive_level_xla(x, nid, ghw, tables, lo, inv, n_prev,
                              n_nodes, level_base, W)


def pick_W(nbins: int) -> int:
    """Smallest supported lane width for nbins real bins (+1 NA lane).
    W=32 covers the reference's default nbins=20 at half the one-hot
    build cost of W=64; W=16 (nbins<=14) additionally halves the MXU
    passes (F*W drops below one 512-lane stripe at F=28) — per-node
    adaptive re-binning recovers the resolution with depth (AUC parity
    measured on the HIGGS bench, see bench.py)."""
    for w in (16, 32, 64, 128, 256):
        if nbins <= w - 2:
            return w
    raise ValueError(f"nbins {nbins} exceeds the adaptive kernel's 254-bin "
                     f"cap; use histogram_type='quantiles_global'")


def _totals_kernel(x_ref, nid_ref, ghw_ref, tabs_ref, nid_out, tot_out,
                   acc_ref, *, n_prev: int, n_nodes: int, F: int, tile: int,
                   n_row_tiles: int, level_base: int):
    """Route one level then accumulate exact f32 (g,h,w) sums per node —
    the deepest-level leaf statistics (no bin histogram, no bf16)."""
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    nid = nid_ref[0, :]
    if n_prev > 0:
        nid = _route(x, nid, tabs_ref, n_prev, level_base, tile, F)
    nid_out[0, :] = nid
    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    onh = (jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
           == lidc[None, :])
    onh_f = onh.astype(jnp.float32) * in_lvl.astype(jnp.float32)[None, :]
    ghw = ghw_ref[...]
    left = jnp.concatenate([onh_f * ghw[k, :][None, :] for k in range(3)],
                           axis=0)                       # [3N, tile] f32
    # all 128 lanes carry the same sum (single-lane stores are awkward in
    # Mosaic); the caller reads lane 0
    acc_ref[...] += jnp.broadcast_to(
        jnp.sum(left, axis=1, keepdims=True), acc_ref.shape)

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        tot_out[...] = acc_ref[...]


def leaf_totals_tpu(x, nid, ghw, tables, n_prev: int, n_nodes: int,
                    level_base: int, tile: int = TILE,
                    interpret: bool = False):
    """Final-level route + exact per-leaf (g,h,w) totals.
    Returns (nid', totals [3, n_nodes])."""
    rows, F = x.shape
    assert rows % tile == 0
    n_row_tiles = rows // tile
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    kern = functools.partial(_totals_kernel, n_prev=n_prev, n_nodes=n_nodes,
                             F=F, tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base)
    nid2, tot = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((tile, F), lambda r: (r, 0)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3, tile), lambda r: (0, r)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, 128), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, 128), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, 128), jnp.float32)],
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(x, nid[None, :], ghw, tabs)
    return nid2[0], tot[:, 0].reshape(3, n_nodes)


def leaf_totals_xla(x, nid, ghw, tables, n_prev: int, n_nodes: int,
                    level_base: int):
    rows, F = x.shape
    feat, thr, nal, can = tables
    if n_prev > 0:
        prev_base = level_base - n_prev
        lid_p = jnp.clip(nid - prev_base, 0, n_prev - 1)
        in_prev = (nid >= prev_base) & (nid < prev_base + n_prev)
        f_r = feat[lid_p].astype(jnp.int32)
        xsel = jnp.take_along_axis(x, f_r[:, None], axis=1)[:, 0]
        go_right = jnp.where(jnp.isnan(xsel), nal[lid_p] < 0.5,
                             xsel >= thr[lid_p])
        child = 2 * nid + 1 + go_right.astype(jnp.int32)
        nid = jnp.where(in_prev & (can[lid_p] > 0.5), child, nid)
    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    vw = jnp.where(in_lvl, 1.0, 0.0)
    tot = jnp.zeros((n_nodes, 3), jnp.float32).at[lidc].add(
        (ghw * vw[None, :]).T)
    return nid, tot.T


# ---------------- int8 fixed-point histogram path ----------------------
#
# The hist contraction's MXU time is ~independent of the M (=3N row)
# dimension below 128 and scales with K·ceil(FW/512): every level costs
# the same as the deepest one (measured: [6,8192]x[8192,896] takes 73%
# of the [126,...] time — tools/kern_mxu_probe.py). int8 mode streams
# ~1.33x faster than bf16, and the unused M rows are free — so levels
# with 6N <= 128 run an EXACT 2-term int8 fixed-point contraction:
#   q16 = clip(round(v/s), ±32639);  a = round(q16/256);  b = q16 - 256a
#   hist = s·(256·Σ a·oh + Σ b·oh)      (both sums exact in int32)
# Quantization error ≤ s/2 = max|v|/65278 ABSOLUTE per row — tighter
# than the bf16 path's ~2^-9 RELATIVE per-product rounding for any
# |v| ≳ max|v|/100. int32 accumulators cap shard rows at 16M for the
# worst case (all rows in one bin at |a|=127); the caller gates on it.


def quantize_ghw_i8(ghw, terms: int = 1):
    """Per-tree int8 fixed-point encoding of (g, h, w) rows.

    terms=1: q = round(v/s), s = max|v|/127 — error ≤ max|v|/254
    absolute per row, comparable to bf16's 8-bit-mantissa relative
    rounding; rows per component: 1 (M = 3N, same as bf16).
    terms=2: 16-bit (a, b) pairs — error ≤ max|v|/65278, M = 6N.
    Returns (q [3·terms, rows] int8, scales [3] f32)."""
    amax = jnp.maximum(jnp.max(jnp.abs(ghw), axis=1), 1e-30)   # [3]
    if terms == 1:
        s = amax / 127.0
        q = jnp.clip(jnp.round(ghw / s[:, None]), -127, 127
                     ).astype(jnp.int8)
        return q, s.astype(jnp.float32)
    s = amax / 32639.0
    q16 = jnp.clip(jnp.round(ghw / s[:, None]), -32639, 32639)
    # floor((q16+128)/256) keeps b strictly in [-128, 127]: round-half-
    # to-even on positive half-ties would give b=+128 → int8 saturation
    a = jnp.floor((q16 + 128.0) / 256.0)
    b = q16 - 256.0 * a
    q = jnp.stack([a[0], b[0], a[1], b[1], a[2], b[2]]).astype(jnp.int8)
    return q, s.astype(jnp.float32)


def _kernel_t_i8(x_ref, nid_ref, q_ref, s_ref, tabs_ref, loinv_ref,
                 nid_out, hist_out, acc_ref, *, n_prev: int, n_nodes: int,
                 F: int, W: int, tile: int, n_row_tiles: int,
                 level_base: int, terms: int):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xt = x_ref[...]                                  # [F, tile] f32
    nid = nid_ref[0, :]
    if n_prev > 0:
        nid = _route_t(xt, nid, tabs_ref, n_prev, level_base, tile, F)
    nid_out[0, :] = nid

    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidm = jnp.where(in_lvl, lid, -1)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
    onh_m = iota_n == lidm[None, :]                            # [N, tile] i1
    onh_b = onh_m.astype(jnp.bfloat16)
    if n_nodes == 1:
        lr1 = loinv_ref[...].astype(jnp.float32)
        lr = _unsplit3(lr1[:2 * F], lr1[2 * F:4 * F], lr1[4 * F:])
        lo_r = jnp.broadcast_to(lr[:F], (F, tile))
        inv_r = jnp.broadcast_to(lr[F:], (F, tile))
    else:
        lr3 = jax.lax.dot_general(loinv_ref[...], onh_b,
                                  (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        lr = _unsplit3(lr3[:2 * F], lr3[2 * F:4 * F], lr3[4 * F:])
        lo_r = lr[:F]
        inv_r = lr[F:]
    bin_f = jnp.floor(jnp.clip((xt - lo_r) * inv_r, 0.0, float(W - 2)))
    bin_v = jnp.where(jnp.isnan(xt), float(W - 1), bin_f)      # [F, tile]
    b_all = jnp.repeat(bin_v, W, axis=0)
    brow = jax.lax.broadcasted_iota(jnp.int32, (F * W, tile), 0)
    oh_i = ((brow % W).astype(jnp.float32) == b_all).astype(jnp.int8)
    q = q_ref[...].astype(jnp.int32)                 # [3·terms, tile] widened
    # int8 vector multiply/select don't legalize in Mosaic (arith.muli /
    # i1 relayout to the 32-sublane i8 tiling): mask in i32 where both
    # patterns are legal, then narrow the result once
    left32 = jnp.concatenate(
        [jnp.where(onh_m, q[c, :][None, :], 0) for c in range(3 * terms)],
        axis=0)                                      # [3·terms·N, tile] i32
    left = left32.astype(jnp.int8)
    acc_ref[...] += jax.lax.dot_general(
        left, oh_i, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)            # [6N, FW] exact

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        acc = acc_ref[...].astype(jnp.float32)
        s = s_ref[...]                               # [1, 3] f32
        N = n_nodes
        rows = []
        for c in range(3):
            if terms == 1:
                rows.append(s[0, c] * acc[c * N:(c + 1) * N])
            else:
                hi = acc[2 * c * N:(2 * c + 1) * N]
                lo = acc[(2 * c + 1) * N:(2 * c + 2) * N]
                rows.append(s[0, c] * (256.0 * hi + lo))
        hist_out[...] = jnp.concatenate(rows, axis=0)  # [3N, FW] f32


def adaptive_level_tpu_i8(xt, nid, q, scales, tables, lo, inv, n_prev: int,
                          n_nodes: int, level_base: int, W: int,
                          tile: int = TILE, interpret: bool = False):
    """int8 fixed-point transposed level (3·terms·n_nodes must be <= 128)."""
    F, rows = xt.shape
    terms = q.shape[0] // 3
    assert rows % tile == 0, (rows, tile)
    assert 3 * terms * n_nodes <= 128, (n_nodes, terms)
    n_row_tiles = rows // tile
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    loinv = _split3_bf16(jnp.concatenate([lo, inv], axis=1).T, axis=0)
    kern = functools.partial(_kernel_t_i8, n_prev=n_prev, n_nodes=n_nodes,
                             F=F, W=W, tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base, terms=terms)
    nid2, hist = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((F, tile), lambda r: (0, r)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * terms, tile), lambda r: (0, r)),
            pl.BlockSpec((1, 3), lambda r: (0, 0)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
            pl.BlockSpec((6 * F, n_nodes), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, F * W), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, F * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * terms * n_nodes, F * W),
                                   jnp.int32)],
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(xt, nid[None, :], q, scales[None, :], tabs, loinv)
    return nid2[0], hist.reshape(3, n_nodes, F, W)


# ---------------- TRANSPOSED-LAYOUT kernels ----------------------------
#
# The row-major [rows, F] layout wastes HBM bandwidth at small F: device
# arrays tile the MINOR dim to 128 lanes, so F=28 reads move 128/28 =
# 4.6x the useful bytes (measured: 30 GB/s useful on v5e vs 126 GB/s at
# F=128 — tools/ probes). The transposed [F, rows] layout puts ROWS in
# lanes (full utilization; F pads only 28→32 sublanes) and maps the
# kernel MORE naturally: the routing/range lookups already treat rows as
# lanes, the bin one-hot becomes [F*W, tile] vs a sublane iota, and the
# histogram contraction contracts the lane dim on both operands.

def _route_t(xt, nid, tabs_ref, n_prev, level_base, tile, F):
    """Transposed routing: xt [F, tile] (rows in lanes)."""
    prev_base = level_base - n_prev
    lid_p = nid - prev_base
    onp = (jax.lax.broadcasted_iota(jnp.int32, (n_prev, tile), 0)
           == lid_p[None, :]).astype(jnp.bfloat16)
    t12 = tabs_ref[:, :n_prev]
    lut3 = jax.lax.dot_general(t12, onp, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    lut = _unsplit3(lut3[0:4], lut3[4:8], lut3[8:12])
    f_r, t_r, nl_r, cn_r = lut[0], lut[1], lut[2], lut[3]
    fi = jax.lax.broadcasted_iota(jnp.int32, (F, tile), 0)
    xsel = jnp.sum(jnp.where(fi == f_r.astype(jnp.int32)[None, :], xt, 0.0),
                   axis=0)
    gr_f = jnp.where(jnp.isnan(xsel), 1.0 - nl_r,
                     (xsel >= t_r).astype(jnp.float32))
    in_prev = (lid_p >= 0) & (lid_p < n_prev)
    child = 2 * nid + 1 + gr_f.astype(jnp.int32)
    return jnp.where(in_prev & (cn_r > 0.5), child, nid)


def _kernel_t(x_ref, nid_ref, ghw_ref, tabs_ref, loinv_ref, nid_out,
              hist_out, acc_ref, *, n_prev: int, n_nodes: int, F: int,
              W: int, tile: int, n_row_tiles: int, level_base: int,
              mxu_dtype):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xt = x_ref[...]                                  # [F, tile] f32
    nid = nid_ref[0, :]
    if n_prev > 0:
        nid = _route_t(xt, nid, tabs_ref, n_prev, level_base, tile, F)
    nid_out[0, :] = nid

    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    # fold the in-level mask into the index (-1 matches no iota row), so
    # ONE fused compare+select builds the masked one-hot directly in the
    # MXU dtype (the old path went compare → f32 astype → mask multiply →
    # bf16 astype: three extra [N, tile] passes; an explicit `& in_lvl`
    # broadcast trips a Mosaic i1 relayout error)
    lidm = jnp.where(in_lvl, lid, -1)
    onh_m = (jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
             == lidm[None, :]).astype(mxu_dtype)
    if n_nodes == 1:
        # root level: every row shares ONE range row — recombine the
        # [6F, 1] table first and broadcast, skipping the per-row lookup
        # matmul and the [2F, tile] three-term recombine entirely
        lr1 = loinv_ref[...].astype(jnp.float32)           # [6F, 1]
        lr = _unsplit3(lr1[:2 * F], lr1[2 * F:4 * F], lr1[4 * F:])
        lo_r = jnp.broadcast_to(lr[:F], (F, tile))
        inv_r = jnp.broadcast_to(lr[F:], (F, tile))
    else:
        onh_b = onh_m.astype(jnp.bfloat16) if mxu_dtype != jnp.bfloat16 \
            else onh_m
        # per-row ranges: [6F, N] @ [N, tile] -> [6F, tile] (exact 3-term
        # bf16 split, see _split3_bf16)
        lr3 = jax.lax.dot_general(loinv_ref[...], onh_b,
                                  (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        lr = _unsplit3(lr3[:2 * F], lr3[2 * F:4 * F], lr3[4 * F:])
        lo_r = lr[:F]
        inv_r = lr[F:]
    bin_f = jnp.floor(jnp.clip((xt - lo_r) * inv_r, 0.0, float(W - 2)))
    bin_v = jnp.where(jnp.isnan(xt), float(W - 1), bin_f)  # [F, tile]
    # bin broadcast to [F*W, tile]: in the transposed layout this is a
    # SUBLANE repeat (each feature row replicated W times) — a cheap
    # Mosaic relayout, vs the row-major layout where the same broadcast
    # needed a selector MATMUL writing a [tile, F*W] f32 intermediate
    # (the repeat alone was worth ~1.5x end-to-end on the bench)
    b_all = jnp.repeat(bin_v, W, axis=0)
    brow = jax.lax.broadcasted_iota(jnp.int32, (F * W, tile), 0)
    oh_t = ((brow % W).astype(jnp.float32) == b_all).astype(mxu_dtype)
    ghw = ghw_ref[...]
    ghw_m = ghw.astype(mxu_dtype)
    left = jnp.concatenate(
        [onh_m * ghw_m[k, :][None, :] for k in range(3)], axis=0)  # [3N, tile]
    # contraction over LANES on both sides: [3N, tile] x [FW, tile]^T
    acc_ref[...] += jax.lax.dot_general(
        left, oh_t, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=(jax.lax.Precision.HIGHEST if mxu_dtype == jnp.float32
                   else jax.lax.Precision.DEFAULT))       # [3N, FW]

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        hist_out[...] = acc_ref[...]


def adaptive_level_tpu_t(xt, nid, ghw, tables, lo, inv, n_prev: int,
                         n_nodes: int, level_base: int, W: int,
                         tile: int = TILE, interpret: bool = False,
                         mxu_dtype=jnp.bfloat16):
    """Transposed-layout level: xt is [F, rows] (rows % tile == 0)."""
    F, rows = xt.shape
    assert rows % tile == 0, (rows, tile)
    n_row_tiles = rows // tile
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    # loinv stored [6F, N]: 3-term split of [2F, N]
    loinv = _split3_bf16(jnp.concatenate([lo, inv], axis=1).T, axis=0)
    kern = functools.partial(_kernel_t, n_prev=n_prev, n_nodes=n_nodes, F=F,
                             W=W, tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base, mxu_dtype=mxu_dtype)
    nid2, hist = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((F, tile), lambda r: (0, r)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3, tile), lambda r: (0, r)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
            pl.BlockSpec((6 * F, n_nodes), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, F * W), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, F * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, F * W), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * n_nodes * F * W * rows,
            bytes_accessed=rows * F * 4 + rows * 16, transcendentals=0),
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(xt, nid[None, :], ghw, tabs, loinv)
    return nid2[0], hist.reshape(3, n_nodes, F, W)


def _route_kernel_t(x_ref, nid_ref, tabs_ref, nid_out, *, n_prev: int,
                    level_base: int, F: int, tile: int):
    xt = x_ref[...]
    nid = nid_ref[0, :]
    nid = _route_t(xt, nid, tabs_ref, n_prev, level_base, tile, F)
    nid_out[0, :] = nid


def route_only_tpu_t(xt, nid, tables, n_prev: int, level_base: int,
                     tile: int = TILE, interpret: bool = False):
    F, rows = xt.shape
    assert rows % tile == 0
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    kern = functools.partial(_route_kernel_t, n_prev=n_prev,
                             level_base=level_base, F=F, tile=tile)
    nid2 = pl.pallas_call(
        kern,
        grid=(rows // tile,),
        in_specs=[
            pl.BlockSpec((F, tile), lambda r: (0, r)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda r: (0, r)),
        out_shape=jax.ShapeDtypeStruct((1, rows), jnp.int32),
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(xt, nid[None, :], tabs)
    return nid2[0]


def _route_kernel(x_ref, nid_ref, tabs_ref, nid_out, *, n_prev: int,
                  level_base: int, F: int, tile: int):
    """Route one level, nothing else — the deepest-level pass when leaf
    values come from the last histogram's selected splits (no totals
    kernel; ~3x cheaper than a full level since the whole [tile, F*W]
    one-hot stage is skipped)."""
    x = x_ref[...]
    nid = nid_ref[0, :]
    nid = _route(x, nid, tabs_ref, n_prev, level_base, tile, F)
    nid_out[0, :] = nid


def route_only_tpu(x, nid, tables, n_prev: int, level_base: int,
                   tile: int = TILE, interpret: bool = False):
    rows, F = x.shape
    assert rows % tile == 0
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    kern = functools.partial(_route_kernel, n_prev=n_prev,
                             level_base=level_base, F=F, tile=tile)
    nid2 = pl.pallas_call(
        kern,
        grid=(rows // tile,),
        in_specs=[
            pl.BlockSpec((tile, F), lambda r: (r, 0)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda r: (0, r)),
        out_shape=jax.ShapeDtypeStruct((1, rows), jnp.int32),
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(x, nid[None, :], tabs)
    return nid2[0]


def route_only_xla(x, nid, tables, n_prev: int, level_base: int):
    feat, thr, nal, can = tables
    prev_base = level_base - n_prev
    lid_p = jnp.clip(nid - prev_base, 0, n_prev - 1)
    in_prev = (nid >= prev_base) & (nid < prev_base + n_prev)
    f_r = feat[lid_p].astype(jnp.int32)
    xsel = jnp.take_along_axis(x, f_r[:, None], axis=1)[:, 0]
    go_right = jnp.where(jnp.isnan(xsel), nal[lid_p] < 0.5,
                         xsel >= thr[lid_p])
    child = 2 * nid + 1 + go_right.astype(jnp.int32)
    return jnp.where(in_prev & (can[lid_p] > 0.5), child, nid)


def route_only(x, nid, tables, n_prev: int, level_base: int,
               method: str = "auto", xt=None):
    method = _resolve_method(method)
    if method == "pallas":
        if xt is not None:
            rows = xt.shape[1]
            pad = (-rows) % TILE
            if pad:
                xt = jnp.pad(xt, ((0, 0), (0, pad)),
                             constant_values=jnp.nan)
                nid = jnp.pad(nid, (0, pad))
            return route_only_tpu_t(xt, nid, tables, n_prev, level_base,
                                    interpret=pallas_interpret())[:rows]
        rows = x.shape[0]
        pad = (-rows) % TILE
        if pad:
            x = jnp.pad(x, ((0, pad), (0, 0)), constant_values=jnp.nan)
            nid = jnp.pad(nid, (0, pad))
        return route_only_tpu(x, nid, tables, n_prev, level_base,
                              interpret=pallas_interpret())[:rows]
    return route_only_xla(x, nid, tables, n_prev, level_base)


def leaf_totals(x, nid, ghw, tables, n_prev: int, n_nodes: int,
                level_base: int, method: str = "auto"):
    method = _resolve_method(method)
    if method == "pallas":
        rows = x.shape[0]
        pad = (-rows) % TILE
        if pad:
            x = jnp.pad(x, ((0, pad), (0, 0)), constant_values=jnp.nan)
            nid = jnp.pad(nid, (0, pad))
            ghw = jnp.pad(ghw, ((0, 0), (0, pad)))
        nid2, tot = leaf_totals_tpu(x, nid, ghw, tables, n_prev, n_nodes,
                                    level_base,
                                    interpret=pallas_interpret())
        return nid2[:rows], tot
    return leaf_totals_xla(x, nid, ghw, tables, n_prev, n_nodes, level_base)


# ---------------- PACKED BINNED-CODE kernels ---------------------------
#
# The global-sketch path bins features ONCE per train (ops/binning.py)
# into small integer codes, so the level kernel no longer needs the
# per-node lo/inv range machinery at all: the bin IS the code. Streaming
# int8/int16 codes instead of f32 features cuts the hot loop's HBM
# traffic 4x/2x — the lever the roofline data says matters in the
# memory-bound regime — and the whole [6F, N] range-table stage (one
# bf16 LUT matmul + 3-term recombine per level) drops out of the
# kernel body. Conventions:
#   - codes ride TRANSPOSED [F, rows] like the f32 kernels (rows in
#     lanes; int8 tiles 32x128, so F=28 pads to 32 sublanes either
#     way); values in [0, W-2], NA = the RESERVED LAST LANE W-1 (pad
#     rows are all-NA with zero ghw mass);
#   - split tables carry the split BIN as an integer-valued f32
#     (left <=> code < bin), packed through the same exact 3-term bf16
#     split as the raw-threshold tables (_pack_tables): integers
#     reconstruct exactly, so in-kernel routing is bit-identical to
#     the scatter reference and to predict_binned's host walk;
#   - the histogram contraction is byte-for-byte the f32 kernel's
#     (same [3N, tile] x [FW, tile]^T lane contraction), so the
#     bf16 / f32-HIGHEST (histogram_precision) and opt-in int8-ghw
#     fixed-point paths compose unchanged.


def code_dtype(W: int):
    """Smallest kernel-legal integer dtype for codes in [0, W-1]:
    int8 holds W <= 128 (max code 127), int16 the 256-lane case."""
    return jnp.int8 if W <= 128 else jnp.int16


def _route_bt(cf, nid, tabs_ref, n_prev, level_base, tile, F, W):
    """Transposed binned routing: cf [F, tile] f32-valued CODES (NA =
    W-1). The split-bin compare ``code >= bin`` happens on exact
    integer-valued floats — no lo/inv rebinning anywhere."""
    prev_base = level_base - n_prev
    lid_p = nid - prev_base
    onp = (jax.lax.broadcasted_iota(jnp.int32, (n_prev, tile), 0)
           == lid_p[None, :]).astype(jnp.bfloat16)
    t12 = tabs_ref[:, :n_prev]                        # [12, n_prev] bf16
    lut3 = jax.lax.dot_general(t12, onp, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    lut = _unsplit3(lut3[0:4], lut3[4:8], lut3[8:12])  # exact ints
    f_r, b_r, nl_r, cn_r = lut[0], lut[1], lut[2], lut[3]
    fi = jax.lax.broadcasted_iota(jnp.int32, (F, tile), 0)
    csel = jnp.sum(jnp.where(fi == f_r.astype(jnp.int32)[None, :], cf, 0.0),
                   axis=0)
    gr_f = jnp.where(csel == float(W - 1), 1.0 - nl_r,
                     (csel >= b_r).astype(jnp.float32))
    in_prev = (lid_p >= 0) & (lid_p < n_prev)
    child = 2 * nid + 1 + gr_f.astype(jnp.int32)
    return jnp.where(in_prev & (cn_r > 0.5), child, nid)


def _kernel_bt(c_ref, nid_ref, ghw_ref, tabs_ref, nid_out, hist_out,
               acc_ref, *, n_prev: int, n_nodes: int, F: int, W: int,
               tile: int, n_row_tiles: int, level_base: int, mxu_dtype):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # int8/int16 -> f32 once per tile in VMEM (int->float is legal in
    # Mosaic via the i32 widening the i8-ghw path already uses)
    cf = c_ref[...].astype(jnp.int32).astype(jnp.float32)    # [F, tile]
    nid = nid_ref[0, :]
    if n_prev > 0:
        nid = _route_bt(cf, nid, tabs_ref, n_prev, level_base, tile, F, W)
    nid_out[0, :] = nid

    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidm = jnp.where(in_lvl, lid, -1)
    onh_m = (jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
             == lidm[None, :]).astype(mxu_dtype)
    # the code IS the bin: the one-hot builds straight off the sublane
    # repeat — no range lookup, no floor/clip stage
    b_all = jnp.repeat(cf, W, axis=0)                        # [F*W, tile]
    brow = jax.lax.broadcasted_iota(jnp.int32, (F * W, tile), 0)
    oh_t = ((brow % W).astype(jnp.float32) == b_all).astype(mxu_dtype)
    ghw_m = ghw_ref[...].astype(mxu_dtype)
    left = jnp.concatenate(
        [onh_m * ghw_m[k, :][None, :] for k in range(3)], axis=0)  # [3N, tile]
    acc_ref[...] += jax.lax.dot_general(
        left, oh_t, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=(jax.lax.Precision.HIGHEST if mxu_dtype == jnp.float32
                   else jax.lax.Precision.DEFAULT))       # [3N, FW]

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        hist_out[...] = acc_ref[...]


def binned_level_tpu_t(ct, nid, ghw, tables, n_prev: int, n_nodes: int,
                       level_base: int, W: int, tile: int = TILE,
                       interpret: bool = False, mxu_dtype=jnp.bfloat16):
    """Packed binned level: ct is [F, rows] int8/int16 codes (rows %
    tile == 0; NA/pad = W-1). Returns (nid' [rows] i32, hist
    [3, n_nodes, F, W] f32 — caller psums across shards)."""
    F, rows = ct.shape
    assert rows % tile == 0, (rows, tile)
    n_row_tiles = rows // tile
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    kern = functools.partial(_kernel_bt, n_prev=n_prev, n_nodes=n_nodes,
                             F=F, W=W, tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base, mxu_dtype=mxu_dtype)
    itemsize = jnp.dtype(ct.dtype).itemsize
    nid2, hist = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((F, tile), lambda r: (0, r)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3, tile), lambda r: (0, r)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, F * W), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, F * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, F * W), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * n_nodes * F * W * rows,
            bytes_accessed=rows * F * itemsize + rows * 16,
            transcendentals=0),
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(ct, nid[None, :], ghw, tabs)
    return nid2[0], hist.reshape(3, n_nodes, F, W)


def _kernel_bt_stripe(c_ref, nid_ref, ghw_ref, tabs_ref, nid_out, hist_out,
                      acc_ref, *, n_prev: int, n_nodes: int, F2: int,
                      W: int, tile: int, n_row_tiles: int, level_base: int,
                      mxu_dtype):
    """STRIPE-PACKED binned level (W=16): two features share one 32-lane
    stripe of the one-hot — feature 2p's bins in sub-lanes 0..W-1,
    feature 2p+1's in W..2W-1 (codes offset by +W in-register). The
    resulting selector matrix is ELEMENT-IDENTICAL to _kernel_bt's
    (row q = W·f + b holds the same {0,1} for every lane), so the MXU
    contraction produces bit-identical histograms; what changes is the
    lowering — the iota compare runs modulo 2W = 32 aligned to the int8
    (32, 128) native tile, so each compare stripe is a full sublane
    group instead of two half-filled W=16 groups. Capability-gated
    (stripe_supported): Mosaic builds that lack the aligned i8 select
    fall back to _kernel_bt."""
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    cf = c_ref[...].astype(jnp.int32).astype(jnp.float32)    # [2*F2, tile]
    nid = nid_ref[0, :]
    if n_prev > 0:
        nid = _route_bt(cf, nid, tabs_ref, n_prev, level_base, tile,
                        2 * F2, W)
    nid_out[0, :] = nid

    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidm = jnp.where(in_lvl, lid, -1)
    onh_m = (jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
             == lidm[None, :]).astype(mxu_dtype)
    # stripe offset: the pair's odd feature lives in the upper W lanes —
    # one add on the [2*F2, tile] codes, then a single repeat builds
    # both features' lanes of every stripe at once
    frow = jax.lax.broadcasted_iota(jnp.int32, (2 * F2, tile), 0)
    cs = cf + ((frow % 2) * W).astype(jnp.float32)
    b_all = jnp.repeat(cs, W, axis=0)                        # [F2*2W, tile]
    brow = jax.lax.broadcasted_iota(jnp.int32, (2 * F2 * W, tile), 0)
    oh_t = ((brow % (2 * W)).astype(jnp.float32) == b_all).astype(mxu_dtype)
    ghw_m = ghw_ref[...].astype(mxu_dtype)
    left = jnp.concatenate(
        [onh_m * ghw_m[k, :][None, :] for k in range(3)], axis=0)
    acc_ref[...] += jax.lax.dot_general(
        left, oh_t, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=(jax.lax.Precision.HIGHEST if mxu_dtype == jnp.float32
                   else jax.lax.Precision.DEFAULT))        # [3N, F2*2W]

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        hist_out[...] = acc_ref[...]


def binned_level_tpu_stripe(ct, nid, ghw, tables, n_prev: int,
                            n_nodes: int, level_base: int, W: int,
                            tile: int = TILE, interpret: bool = False,
                            mxu_dtype=jnp.bfloat16, F: int = None):
    """Stripe-packed binned level: ct is the stripe operand [2*F2, rows]
    (ops/binning.stripe_pair_codes — an odd F pads one all-NA feature
    row). ``F`` is the REAL feature count; the returned hist is sliced
    back to [3, n_nodes, F, W]."""
    F_op, rows = ct.shape
    assert F_op % 2 == 0, F_op
    F2 = F_op // 2
    F = F_op if F is None else F
    assert rows % tile == 0, (rows, tile)
    n_row_tiles = rows // tile
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    kern = functools.partial(_kernel_bt_stripe, n_prev=n_prev,
                             n_nodes=n_nodes, F2=F2, W=W, tile=tile,
                             n_row_tiles=n_row_tiles,
                             level_base=level_base, mxu_dtype=mxu_dtype)
    itemsize = jnp.dtype(ct.dtype).itemsize
    nid2, hist = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((2 * F2, tile), lambda r: (0, r)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3, tile), lambda r: (0, r)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, 2 * F2 * W), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, 2 * F2 * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, 2 * F2 * W),
                                   jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * n_nodes * 2 * F2 * W * rows,
            bytes_accessed=rows * 2 * F2 * itemsize + rows * 16,
            transcendentals=0),
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(ct, nid[None, :], ghw, tabs)
    return nid2[0], hist.reshape(3, n_nodes, 2 * F2, W)[:, :, :F, :]


@functools.lru_cache(maxsize=1)
def _stripe_probe() -> bool:
    """Hardware capability probe for the stripe kernel, run ONCE: the
    interpreter always supports it; on a real TPU a tiny stripe kernel
    is compiled and executed, and any Mosaic lowering failure (builds
    lacking the aligned i8 select the stripe compare needs) demotes to
    the _kernel_bt layout."""
    if pallas_interpret():
        return True
    if jax.default_backend() != "tpu":
        return False
    try:
        ct = jnp.full((2, TILE), 15, jnp.int8)
        nid = jnp.zeros(TILE, jnp.int32)
        ghw = jnp.zeros((3, TILE), jnp.float32)
        z1 = jnp.zeros(1, jnp.float32)
        nid2, hist = binned_level_tpu_stripe(
            ct, nid, ghw, (z1, z1, z1, z1), 0, 1, 0, 16)
        jax.block_until_ready((nid2, hist))  # h2o3-lint: allow[transfer-seam] once-per-process capability probe: the block IS the probe (Mosaic lowering failures surface at execute)
        return True
    except Exception:
        return False


def stripe_supported() -> bool:
    """Whether binned W=16 levels use the stripe-packed one-hot kernel.
    H2O3_STRIPE=0/1 overrides the probe (tests, A/B ablation)."""
    env = _os.environ.get("H2O3_STRIPE", "")
    if env == "0":
        return False
    if env == "1":
        return True
    return _stripe_probe()


def _kernel_bt_i8(c_ref, nid_ref, q_ref, s_ref, tabs_ref, nid_out,
                  hist_out, acc_ref, *, n_prev: int, n_nodes: int, F: int,
                  W: int, tile: int, n_row_tiles: int, level_base: int,
                  terms: int):
    """Binned level with the exact int8 fixed-point ghw contraction —
    the _kernel_t_i8 composition minus the range-lookup stage."""
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    cf = c_ref[...].astype(jnp.int32).astype(jnp.float32)
    nid = nid_ref[0, :]
    if n_prev > 0:
        nid = _route_bt(cf, nid, tabs_ref, n_prev, level_base, tile, F, W)
    nid_out[0, :] = nid

    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidm = jnp.where(in_lvl, lid, -1)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
    onh_m = iota_n == lidm[None, :]
    b_all = jnp.repeat(cf, W, axis=0)
    brow = jax.lax.broadcasted_iota(jnp.int32, (F * W, tile), 0)
    oh_i = ((brow % W).astype(jnp.float32) == b_all).astype(jnp.int8)
    q = q_ref[...].astype(jnp.int32)
    left32 = jnp.concatenate(
        [jnp.where(onh_m, q[c, :][None, :], 0) for c in range(3 * terms)],
        axis=0)
    left = left32.astype(jnp.int8)
    acc_ref[...] += jax.lax.dot_general(
        left, oh_i, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        acc = acc_ref[...].astype(jnp.float32)
        s = s_ref[...]
        N = n_nodes
        rows_ = []
        for c in range(3):
            if terms == 1:
                rows_.append(s[0, c] * acc[c * N:(c + 1) * N])
            else:
                hi = acc[2 * c * N:(2 * c + 1) * N]
                lo = acc[(2 * c + 1) * N:(2 * c + 2) * N]
                rows_.append(s[0, c] * (256.0 * hi + lo))
        hist_out[...] = jnp.concatenate(rows_, axis=0)


def binned_level_tpu_i8(ct, nid, q, scales, tables, n_prev: int,
                        n_nodes: int, level_base: int, W: int,
                        tile: int = TILE, interpret: bool = False):
    """int8 fixed-point binned level (3·terms·n_nodes must be <= 128)."""
    F, rows = ct.shape
    terms = q.shape[0] // 3
    assert rows % tile == 0, (rows, tile)
    assert 3 * terms * n_nodes <= 128, (n_nodes, terms)
    n_row_tiles = rows // tile
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    kern = functools.partial(_kernel_bt_i8, n_prev=n_prev, n_nodes=n_nodes,
                             F=F, W=W, tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base, terms=terms)
    nid2, hist = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((F, tile), lambda r: (0, r)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * terms, tile), lambda r: (0, r)),
            pl.BlockSpec((1, 3), lambda r: (0, 0)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, F * W), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, F * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * terms * n_nodes, F * W),
                                   jnp.int32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * terms * n_nodes * F * W * rows,
            bytes_accessed=(rows * F * jnp.dtype(ct.dtype).itemsize
                            + rows * (4 + 3 * terms)),
            transcendentals=0),
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(ct, nid[None, :], q, scales[None, :], tabs)
    return nid2[0], hist.reshape(3, n_nodes, F, W)


def binned_level_xla(codes, nid, ghw, tables, n_prev: int, n_nodes: int,
                     level_base: int, W: int):
    """Pure-XLA reference/CPU path for the binned level (scatter-add
    histogram, [rows, F] int codes, NA = W-1). Accumulation order
    matches ops/histogram._hist_scatter3 row order, so the packed and
    unpacked global-sketch paths are BIT-identical on CPU."""
    rows, F = codes.shape
    feat, sbin, nal, can = tables
    ci = codes.astype(jnp.int32)
    if n_prev > 0:
        prev_base = level_base - n_prev
        lid_p = jnp.clip(nid - prev_base, 0, n_prev - 1)
        in_prev = (nid >= prev_base) & (nid < prev_base + n_prev)
        f_r = feat[lid_p].astype(jnp.int32)
        csel = jnp.take_along_axis(ci, f_r[:, None], axis=1)[:, 0]
        is_na = csel == W - 1
        go_right = jnp.where(is_na, nal[lid_p] < 0.5,
                             csel.astype(jnp.float32) >= sbin[lid_p])
        child = 2 * nid + 1 + go_right.astype(jnp.int32)
        nid = jnp.where(in_prev & (can[lid_p] > 0.5), child, nid)
    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    flat = (lidc[:, None] * F + jnp.arange(F)[None, :]) * W + ci
    vw = jnp.where(in_lvl, 1.0, 0.0)
    out = jnp.zeros((n_nodes * F * W, 3), jnp.float32)
    out = out.at[flat.reshape(-1), :].add(
        (ghw.T * vw[:, None])[:, None, :].repeat(F, axis=1).reshape(-1, 3))
    hist = out.reshape(n_nodes, F, W, 3)
    return nid, jnp.moveaxis(hist, -1, 0)


def _route_kernel_bt(c_ref, nid_ref, tabs_ref, nid_out, *, n_prev: int,
                     level_base: int, F: int, W: int, tile: int):
    cf = c_ref[...].astype(jnp.int32).astype(jnp.float32)
    nid = nid_ref[0, :]
    nid = _route_bt(cf, nid, tabs_ref, n_prev, level_base, tile, F, W)
    nid_out[0, :] = nid


def binned_route_only_tpu_t(ct, nid, tables, n_prev: int, level_base: int,
                            W: int, tile: int = TILE,
                            interpret: bool = False):
    F, rows = ct.shape
    assert rows % tile == 0
    tabs = _pack_tables(tables)
    np1 = tabs.shape[1]
    kern = functools.partial(_route_kernel_bt, n_prev=n_prev,
                             level_base=level_base, F=F, W=W, tile=tile)
    nid2 = pl.pallas_call(
        kern,
        grid=(rows // tile,),
        in_specs=[
            pl.BlockSpec((F, tile), lambda r: (0, r)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((12, np1), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda r: (0, r)),
        out_shape=jax.ShapeDtypeStruct((1, rows), jnp.int32),
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(ct, nid[None, :], tabs)
    return nid2[0]


def binned_route_only_xla(codes, nid, tables, n_prev: int, level_base: int,
                          W: int):
    feat, sbin, nal, can = tables
    ci = codes.astype(jnp.int32)
    prev_base = level_base - n_prev
    lid_p = jnp.clip(nid - prev_base, 0, n_prev - 1)
    in_prev = (nid >= prev_base) & (nid < prev_base + n_prev)
    f_r = feat[lid_p].astype(jnp.int32)
    csel = jnp.take_along_axis(ci, f_r[:, None], axis=1)[:, 0]
    go_right = jnp.where(csel == W - 1, nal[lid_p] < 0.5,
                         csel.astype(jnp.float32) >= sbin[lid_p])
    child = 2 * nid + 1 + go_right.astype(jnp.int32)
    return jnp.where(in_prev & (can[lid_p] > 0.5), child, nid)


def _binned_pad(ct, nid, ghw, W):
    """Pad the kernel operands to the tile width: pad rows are all-NA
    (code W-1) with nid 0 — at the root they one-hot into node 0 but
    carry zero ghw mass, at deeper levels they fall outside the level
    window, exactly like the f32 kernels' NaN pad rows."""
    padc = (-ct.shape[1]) % TILE
    if padc:
        ct = jnp.pad(ct, ((0, 0), (0, padc)), constant_values=W - 1)
    pad = ct.shape[1] - nid.shape[0]
    if pad:
        nid = jnp.pad(nid, (0, pad))
        if ghw is not None:
            ghw = jnp.pad(ghw, ((0, 0), (0, pad)))
    return ct, nid, ghw


def binned_level(codes_rm, nid, ghw, tables, n_prev: int, n_nodes: int,
                 level_base: int, W: int, method: str = "auto",
                 mxu_dtype=jnp.bfloat16, ct=None, qs=None):
    """Dispatch the packed binned level: pallas on TPU (or interpret),
    scatter-XLA elsewhere. ``ct`` is the pre-transposed [F, rows_p]
    code matrix (built once per train by ops/binning.pack_codes);
    without it the pallas path transposes on the fly (streamed
    chunks). ``qs`` enables the exact int8-ghw contraction for levels
    with 3·terms·n_nodes <= 128, same contract as adaptive_level."""
    method = _resolve_method(method)
    if method == "pallas":
        if ct is None:
            ct = codes_rm.T
        rows = nid.shape[0]
        ct, nid, ghw = _binned_pad(ct, nid, ghw, W)
        pad = nid.shape[0] - rows
        if (qs is not None and qs[0].shape[0] * n_nodes <= 128
                and mxu_dtype == jnp.bfloat16):
            q, scales = qs
            if pad:
                q = jnp.pad(q, ((0, 0), (0, pad)))
            nid2, hist = binned_level_tpu_i8(
                ct, nid, q, scales, tables, n_prev, n_nodes, level_base,
                W, interpret=pallas_interpret())
            return nid2[:rows], hist
        if W == 16 and ct.shape[0] >= 2 and stripe_supported():
            from h2o3_tpu.ops.binning import stripe_pair_codes
            nid2, hist = binned_level_tpu_stripe(
                stripe_pair_codes(ct, W), nid, ghw, tables, n_prev,
                n_nodes, level_base, W, mxu_dtype=mxu_dtype,
                interpret=pallas_interpret(), F=ct.shape[0])
            return nid2[:rows], hist
        nid2, hist = binned_level_tpu_t(ct, nid, ghw, tables, n_prev,
                                        n_nodes, level_base, W,
                                        mxu_dtype=mxu_dtype,
                                        interpret=pallas_interpret())
        return nid2[:rows], hist
    return binned_level_xla(codes_rm, nid, ghw, tables, n_prev, n_nodes,
                            level_base, W)


def binned_route_only(codes_rm, nid, tables, n_prev: int, level_base: int,
                      W: int, method: str = "auto", ct=None):
    method = _resolve_method(method)
    if method == "pallas":
        if ct is None:
            ct = codes_rm.T
        rows = nid.shape[0]
        ct, nid, _ = _binned_pad(ct, nid, None, W)
        return binned_route_only_tpu_t(ct, nid, tables, n_prev, level_base,
                                       W, interpret=pallas_interpret()
                                       )[:rows]
    return binned_route_only_xla(codes_rm, nid, tables, n_prev, level_base,
                                 W)
