"""Pallas TPU API compatibility aliases.

jax renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``;
resolve whichever spelling this jaxlib ships so kernel code works on
both sides of the rename.
"""
from jax.experimental.pallas import tpu as _pltpu

CompilerParams = (getattr(_pltpu, "CompilerParams", None)
                  or _pltpu.TPUCompilerParams)
