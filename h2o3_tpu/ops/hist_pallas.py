"""Pallas TPU histogram kernel — fused one-hot matmul accumulation.

The XLA formulations of the gradient histogram (ops/histogram.py) are
HBM-bound: they materialise a [rows, nodes*bins] or [rows, 3*bins] one-hot
operand per feature (~GBs per level). This kernel builds both one-hot
operands in VMEM per row-tile and contracts them on the MXU, so HBM
traffic is just codes (4 B/row/feature) + (g,h,w) (12 B/row) + the tiny
histogram output.

    acc[k*N+n, f*Bp + b] += Σ_rows [seg==n] · ghw[k] · [code_f==b]

Layout notes (r3 rewrite — measured on v5e at 1M×32×256 shapes):
- The LEFT operand is the transposed node-one-hot times (g,h,w) —
  [3N, tile] — built once per row-tile; the RIGHT operand is ONE bin
  one-hot for a whole FBLK-feature block, [tile, FBLK*Bp], so each
  row-tile issues a single big MXU contraction whose output N-dim
  (FBLK*Bp = 2048) fully occupies the 128-wide MXU; 3N rides the
  cheaply-padded sublane dim. The previous per-feature matmul put 3N on
  the MXU N-dim, wasting 128/3N of the array: 37ms/level → 7ms.
- The output [3N, F*Bp] reshapes to [3, N, F, Bp] for FREE (row-major
  compatible), so split finding consumes separate g/h/w histograms with
  bins minor — no minor-dim-3 transposes anywhere downstream.
- Rows with seg outside [0, n_nodes) match no node one-hot column and
  are excluded at zero cost — callers pass OOB ids instead of w=0 masks.

Grid: (feature_blocks, row_tiles); the row dimension accumulates into a
VMEM scratch, flushed to the output block on the last row-tile. This is
the TPU-native equivalent of the reference's two-stage per-thread private
histograms + merge (hex/tree/ScoreBuildHistogram2.java:121-301) and of
gpu_hist's shared-memory atomics.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

FBLK = 8     # features per grid step
TILE = 2048  # rows per grid step (sweep: 2048 beats 1024/4096 on v5e)


def _kernel(codes_ref, seg_ref, ghw_ref, out_ref, acc_ref, *,
            n_nodes: int, n_bins_p: int, tile: int, n_row_tiles: int,
            mxu_dtype):
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # left operand, built ONCE per row-tile: R_t[k*N+n, row] = [seg==n]·ghw[k]
    seg = seg_ref[0, :]                                       # [tile] int32
    nodes_t = jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
    node_oh_t = (nodes_t == seg[None, :]).astype(mxu_dtype)   # [N, tile]
    R_t = jnp.concatenate(
        [node_oh_t * ghw_ref[k, :][None, :].astype(mxu_dtype)
         for k in range(3)], axis=0)                          # [3N, tile]
    # right operand: bin one-hot for the whole feature block, lane-dim iota
    FB = FBLK * n_bins_p
    bins = jax.lax.broadcasted_iota(jnp.int32, (tile, FB), 1) % n_bins_p
    c_all = jnp.concatenate(
        [jnp.broadcast_to(codes_ref[fi, :][:, None], (tile, n_bins_p))
         for fi in range(FBLK)], axis=1)                      # [tile, FB]
    oh = (bins == c_all).astype(mxu_dtype)
    acc_ref[...] += jax.lax.dot_general(
        R_t, oh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [3N, FB]

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        out_ref[...] = acc_ref[...]


def _hist_pallas_raw(codes_t, seg, ghw, n_nodes: int, n_bins_p: int,
                     tile: int, mxu_dtype, interpret: bool):
    """→ [3N, F*Bp]; see module docstring for the layout contract."""
    F, rows = codes_t.shape
    assert rows % tile == 0, (rows, tile)
    assert F % FBLK == 0, F
    n_row_tiles = rows // tile
    kern = functools.partial(_kernel, n_nodes=n_nodes, n_bins_p=n_bins_p,
                             tile=tile, n_row_tiles=n_row_tiles,
                             mxu_dtype=mxu_dtype)
    flops = 2 * F * rows * 3 * n_nodes * n_bins_p
    return pl.pallas_call(
        kern,
        grid=(F // FBLK, n_row_tiles),
        in_specs=[
            pl.BlockSpec((FBLK, tile), lambda f, r: (f, r)),    # codes_t
            pl.BlockSpec((1, tile), lambda f, r: (0, r)),       # seg ids
            pl.BlockSpec((3, tile), lambda f, r: (0, r)),       # ghw
        ],
        out_specs=pl.BlockSpec((3 * n_nodes, FBLK * n_bins_p),
                               lambda f, r: (0, f)),
        out_shape=jax.ShapeDtypeStruct((3 * n_nodes, F * n_bins_p),
                                       jnp.float32),
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, FBLK * n_bins_p),
                                   jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=flops, bytes_accessed=codes_t.size * 4 + rows * 16,
            transcendentals=0),
        interpret=interpret,
    )(codes_t, seg, ghw)


def hist_pallas3(codes_t, seg, ghw, n_nodes: int, n_bins1: int,
                 tile: int = TILE, mxu_dtype=jnp.bfloat16,
                 interpret: bool = False):
    """codes_t [F, rows] int32 (F % 8 == 0, rows % tile == 0; pad rows
    with seg=-1), seg [rows] int32 node ids (OOB = excluded row),
    ghw [3, rows] float32 → (g_hist, h_hist, w_hist), each
    [n_nodes, F, Bp] float32 with Bp = n_bins1 rounded up to 128; trailing
    bins beyond n_bins1 are zero (codes never land there) and are ignored
    by split finding.

    ``mxu_dtype`` bfloat16 runs the MXU at full rate; one-hots are exact
    in bf16, only the (g,h,w) values round (~3 decimal digits) before
    exact f32 accumulation — set float32 for strict parity.
    """
    F = codes_t.shape[0]
    n_bins_p = int(np.ceil(n_bins1 / 128) * 128)
    out = _hist_pallas_raw(codes_t, seg[None, :], ghw, n_nodes, n_bins_p,
                           tile, mxu_dtype, interpret)
    hist = out.reshape(3, n_nodes, F, n_bins_p)   # free: row-major reshape
    return hist[0], hist[1], hist[2]


def hist_pallas_from_rowmajor(codes, node_ids, g, h, w, n_nodes: int,
                              n_bins1: int, tile: int = TILE,
                              mxu_dtype=jnp.bfloat16,
                              interpret: bool = False, codes_t=None):
    """Compat adapter (tests / one-off callers): codes [rows, F] →
    [n_nodes, F, n_bins1, 3]. The training loop uses hist_pallas3 and
    never materialises this layout."""
    rows, F = codes.shape
    ghw = jnp.stack([g, h, w], axis=0).astype(jnp.float32)
    seg = node_ids.astype(jnp.int32)
    if codes_t is None:
        pad_r = (-rows) % tile
        pad_f = (-F) % FBLK
        codes_t = codes.astype(jnp.int32).T
        if pad_r:
            codes_t = jnp.pad(codes_t, ((0, 0), (0, pad_r)))
        if pad_f:
            codes_t = jnp.pad(codes_t, ((0, pad_f), (0, 0)))
    rows_p = codes_t.shape[1]
    if rows_p != rows:
        seg = jnp.pad(seg, (0, rows_p - rows), constant_values=-1)
        ghw = jnp.pad(ghw, ((0, 0), (0, rows_p - rows)))
    gh, hh, wh = hist_pallas3(codes_t, seg, ghw, n_nodes, n_bins1,
                              tile=tile, mxu_dtype=mxu_dtype,
                              interpret=interpret)
    hist = jnp.stack([gh, hh, wh], axis=-1)
    return hist[:, :F, :n_bins1, :]
