"""Pallas TPU histogram kernel — fused one-hot matmul accumulation.

The XLA formulations of the gradient histogram (ops/histogram.py) are
HBM-bound: they materialise a [rows, nodes*bins] or [rows, 3*bins] one-hot
operand per feature (~GBs per level). This kernel builds both one-hot
operands in VMEM per row-tile and contracts them on the MXU, so HBM
traffic is just codes (4 B/row/feature) + (g,h,w) (12 B/row) + the tiny
histogram output.

    acc[f, n, k*Bp + b] += Σ_rows  [nid==n] · [code_f==b] · ghw[k]

Grid: (feature_blocks, row_tiles); FBLK=8 features are processed per grid
step (TPU block-shape constraint: second-to-last dim divisible by 8); the
row dimension accumulates into a VMEM scratch, flushed to the output block
on the last row-tile. This is the TPU-native equivalent of the reference's
two-stage per-thread private histograms + merge
(hex/tree/ScoreBuildHistogram2.java:121-301) and of gpu_hist's
shared-memory atomics.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

FBLK = 8  # features per grid step


def _kernel(codes_ref, nid_ref, ghw_ref, out_ref, acc_ref, *,
            n_nodes: int, n_bins_p: int, tile: int, n_row_tiles: int,
            mxu_dtype):
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # right operand, built ONCE per row-tile: R[r, k*N+n] = [nid==n]·ghw[k]
    # (bins ride the MXU M axis — n_nodes alone would waste 3/4 of it)
    nid = nid_ref[0, :]                                   # [tile] int32
    nodes = jax.lax.broadcasted_iota(jnp.int32, (tile, n_nodes), 1)
    node_oh = (nodes == nid[:, None]).astype(mxu_dtype)   # [tile, N]
    R = jnp.concatenate(
        [node_oh * ghw_ref[k, :][:, None].astype(mxu_dtype) for k in range(3)],
        axis=1)                                           # [tile, 3*N]
    bins_t = jax.lax.broadcasted_iota(jnp.int32, (n_bins_p, tile), 0)
    for fi in range(FBLK):
        c = codes_ref[fi, :]                              # [tile] int32
        bin_oh_t = (bins_t == c[None, :]).astype(mxu_dtype)  # [Bp, tile]
        # canonical [Bp, tile] @ [tile, 3N] — no operand transposition
        acc_ref[fi] += jax.lax.dot_general(
            bin_oh_t, R, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        out_ref[...] = acc_ref[...]


def hist_pallas(codes_t, nid, ghw, n_nodes: int, n_bins1: int,
                tile: int = 2048, mxu_dtype=jnp.bfloat16,
                interpret: bool = False):
    """codes_t [F, rows] int32 (F % 8 == 0), nid [1, rows] int32,
    ghw [3, rows] float32 → hist [n_nodes, F, n_bins1, 3] float32.

    rows must be a multiple of ``tile`` (pad with w=0 rows). ``mxu_dtype``
    bfloat16 runs the MXU at full rate; one-hots are exact in bf16, only
    the (g,h,w) values round (~3 decimal digits) before exact f32
    accumulation — set float32 for strict parity.
    """
    F, rows = codes_t.shape
    assert rows % tile == 0, (rows, tile)
    assert F % FBLK == 0, F
    n_row_tiles = rows // tile
    n_bins_p = int(np.ceil(n_bins1 / 128) * 128)
    kern = functools.partial(_kernel, n_nodes=n_nodes, n_bins_p=n_bins_p,
                             tile=tile, n_row_tiles=n_row_tiles,
                             mxu_dtype=mxu_dtype)
    flops = 2 * F * rows * n_nodes * 3 * n_bins_p
    out = pl.pallas_call(
        kern,
        grid=(F // FBLK, n_row_tiles),
        in_specs=[
            pl.BlockSpec((FBLK, tile), lambda f, r: (f, r)),    # codes_t
            pl.BlockSpec((1, tile), lambda f, r: (0, r)),       # nid
            pl.BlockSpec((3, tile), lambda f, r: (0, r)),       # ghw
        ],
        out_specs=pl.BlockSpec((FBLK, n_bins_p, n_nodes * 3),
                               lambda f, r: (f, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, n_bins_p, n_nodes * 3),
                                       jnp.float32),
        scratch_shapes=[pltpu.VMEM((FBLK, n_bins_p, n_nodes * 3),
                                   jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=flops, bytes_accessed=codes_t.size * 4 + rows * 16,
            transcendentals=0),
        interpret=interpret,
    )(codes_t, nid, ghw)
    # [F, Bp, 3*N] (k-major) → [N, F, B1, 3]
    hist = out.reshape(F, n_bins_p, 3, n_nodes).transpose(3, 0, 1, 2)
    return hist[:, :, :n_bins1, :]


def hist_pallas_from_rowmajor(codes, node_ids, g, h, w, n_nodes: int,
                              n_bins1: int, tile: int = 2048,
                              mxu_dtype=jnp.bfloat16,
                              interpret: bool = False, codes_t=None):
    """Adapter matching ops.histogram.build_histograms signature
    (codes [rows, F]); pads rows/features and transposes. Pass a
    pre-transposed/padded ``codes_t`` [Fp, rows_p] to skip the per-call
    transpose (it costs ~40ms at 1M rows — hoist it per training run)."""
    rows, F = codes.shape
    ghw = jnp.stack([g, h, w], axis=0).astype(jnp.float32)
    nid = node_ids.astype(jnp.int32)
    if codes_t is None:
        pad_r = (-rows) % tile
        pad_f = (-F) % FBLK
        codes_t = codes.astype(jnp.int32).T
        if pad_r:
            codes_t = jnp.pad(codes_t, ((0, 0), (0, pad_r)))
        if pad_f:
            codes_t = jnp.pad(codes_t, ((0, pad_f), (0, 0)))
    rows_p = codes_t.shape[1]
    if rows_p != rows:
        nid = jnp.pad(nid, (0, rows_p - rows))
        ghw = jnp.pad(ghw, ((0, 0), (0, rows_p - rows)))
    hist = hist_pallas(codes_t, nid[None, :], ghw, n_nodes, n_bins1,
                       tile=tile, mxu_dtype=mxu_dtype, interpret=interpret)
    return hist[:, :F, :, :]
