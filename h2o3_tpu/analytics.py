"""Misc analytics — partial dependence, frame synthesis, tabulation.

Reference: h2o-core hex/* misc analytics (SURVEY §2.2): PartialDependence
(water/api + hex/PartialDependence), CreateFrame/FrameCreator (random
frame synthesis), Tabulate (2-D grouped aggregation), plus h2o-py's
varimp-driven explain helpers.

TPU re-design: partial dependence batches the whole grid as one stacked
scoring pass (grid × rows rides the device in blocks instead of the
reference's per-bin MRTask); tabulate is two scatter-adds."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from h2o3_tpu import telemetry
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import T_ENUM, Vec


def _fetch(x):
    """Counted device fetch: analytics' ad-hoc device_get calls show up
    in the d2h byte counters as pipeline="analytics" (ROADMAP gap:
    transfer accounting beyond the frame-layer choke points)."""
    return telemetry.device_get(x, pipeline="analytics")


def partial_dependence(model, frame: Frame, cols: Sequence[str],
                       nbins: int = 20,
                       row_cap: int = 5000) -> Dict[str, Dict]:
    """Per-column partial dependence: mean prediction over the data with
    the column clamped to each grid value (hex/PartialDependence)."""
    from h2o3_tpu.models.model_base import adapt_test_matrix
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    X = np.asarray(_fetch(adapt_test_matrix(model, frame)))
    X = X[: frame.nrow]
    if len(X) > row_cap:
        X = X[rng.choice(len(X), row_cap, replace=False)]
    out: Dict[str, Dict] = {}
    for col in cols:
        if col not in model.feature_names:
            raise ValueError(f"'{col}' is not a model feature")
        j = model.feature_names.index(col)
        is_cat = model.feature_is_cat[j]
        if is_cat:
            dom = model.cat_domains.get(col, ())
            grid = np.arange(len(dom), dtype=np.float64)
            labels = list(dom)
        else:
            v = X[:, j]
            v = v[~np.isnan(v)]
            grid = np.quantile(v, np.linspace(0.025, 0.975, nbins))
            grid = np.unique(grid)
            labels = grid.tolist()
        means, stds = [], []
        for g in grid:
            Xg = X.copy()
            Xg[:, j] = g
            pred = np.asarray(_fetch(
                model._predict_matrix(jnp.asarray(Xg))))
            if pred.ndim == 2:          # classification → p(last class)
                pred = pred[:, -1]
            means.append(float(pred.mean()))
            stds.append(float(pred.std()))
        out[col] = {"grid": labels, "mean_response": means,
                    "stddev_response": stds, "n_rows": int(len(X))}
    return out


def create_frame(rows: int = 10000, cols: int = 10,
                 categorical_fraction: float = 0.2,
                 integer_fraction: float = 0.2,
                 binary_fraction: float = 0.1,
                 missing_fraction: float = 0.0,
                 factors: int = 5, real_range: float = 100.0,
                 integer_range: int = 100, seed: int = -1,
                 has_response: bool = False, mesh=None) -> Frame:
    """Random frame synthesis (water/rapids CreateFrame/FrameCreator)."""
    rng = np.random.default_rng(None if seed in (-1, None) else seed)
    n_cat = int(round(cols * categorical_fraction))
    n_int = int(round(cols * integer_fraction))
    n_bin = int(round(cols * binary_fraction))
    n_real = max(cols - n_cat - n_int - n_bin, 0)
    names: List[str] = []
    vecs: List[Vec] = []

    def miss(arr, enum=False):
        if missing_fraction > 0:
            m = rng.random(rows) < missing_fraction
            if enum:
                arr = np.where(m, -1, arr)
            else:
                arr = np.where(m, np.nan, arr)
        return arr

    ci = 1
    for _ in range(n_real):
        names.append(f"C{ci}"); ci += 1
        vecs.append(Vec.from_numpy(
            miss(rng.uniform(-real_range, real_range, rows)), mesh=mesh))
    for _ in range(n_int):
        names.append(f"C{ci}"); ci += 1
        vecs.append(Vec.from_numpy(
            miss(rng.integers(-integer_range, integer_range,
                              rows).astype(np.float64)), mesh=mesh))
    for _ in range(n_bin):
        names.append(f"C{ci}"); ci += 1
        vecs.append(Vec.from_numpy(
            miss(rng.integers(0, 2, rows).astype(np.float64)), mesh=mesh))
    for _ in range(n_cat):
        names.append(f"C{ci}"); ci += 1
        dom = tuple(f"{names[-1]}.l{k}" for k in range(factors))
        codes = rng.integers(0, factors, rows).astype(np.int32)
        codes = miss(codes, enum=True).astype(np.int32)
        vecs.append(Vec.from_numpy(codes, vtype=T_ENUM,
                                   domain=dom, mesh=mesh))
    if has_response:
        names.append("response")
        vecs.append(Vec.from_numpy(rng.normal(size=rows), mesh=mesh))
    return Frame(names, vecs)


def tabulate(frame: Frame, x: str, y: str, nbins_x: int = 20,
             nbins_y: int = 20) -> Dict:
    """2-D histogram + per-x-bin y means (hex/Tabulate)."""
    import jax.numpy as jnp
    vx = frame.vec(x)
    vy = frame.vec(y)

    def codes_of(v, nbins):
        if v.is_categorical:
            c = np.asarray(_fetch(v.as_float()))[: frame.nrow]
            labels = list(v.domain)
            return np.where(np.isnan(c), -1, c).astype(int), labels
        d = v.to_numpy()
        ok = ~np.isnan(d)
        edges = np.quantile(d[ok], np.linspace(0, 1, nbins + 1)[1:-1]) \
            if ok.any() else np.array([])
        edges = np.unique(edges)
        c = np.where(ok, np.searchsorted(edges, d), -1)
        labels = ([f"<= {e:.4g}" for e in edges] + ["> last"]
                  if len(edges) else ["all"])
        return c.astype(int), labels

    cx, lx = codes_of(vx, nbins_x)
    cy, ly = codes_of(vy, nbins_y)
    nx, ny = len(lx), len(ly)
    ok = (cx >= 0) & (cy >= 0)
    counts = np.zeros((nx, ny), np.int64)
    np.add.at(counts, (cx[ok], cy[ok]), 1)
    # per-x-bin mean of y (numeric y only)
    means = None
    if not vy.is_categorical:
        yv = vy.to_numpy()
        s = np.zeros(nx); c = np.zeros(nx)
        okx = (cx >= 0) & ~np.isnan(yv)
        np.add.at(s, cx[okx], yv[okx])
        np.add.at(c, cx[okx], 1)
        means = np.where(c > 0, s / np.maximum(c, 1), np.nan).tolist()
    return {"x_labels": lx, "y_labels": ly,
            "counts": counts.tolist(), "mean_y_per_x": means}


def feature_interaction(model, frame: Frame, max_pairs: int = 10) -> List:
    """Pairwise H-statistic-flavoured interaction screen
    (hex/FeatureInteraction, FriedmanPopescusH): variance of the joint
    partial dependence not explained by the additive marginals."""
    import itertools
    vi = model.output.get("variable_importances") or {}
    top = (vi.get("variable") or list(model.feature_names))[:5]
    rows = []
    from h2o3_tpu.models.model_base import adapt_test_matrix
    import jax.numpy as jnp
    X = np.asarray(_fetch(
        adapt_test_matrix(model, frame)))[: frame.nrow]
    if len(X) > 2000:
        X = X[np.random.default_rng(0).choice(len(X), 2000, replace=False)]

    def grid_of(col):
        # grid values straight from the data quantiles / enum codes —
        # no scoring pass needed just to enumerate grid points
        j = model.feature_names.index(col)
        if model.feature_is_cat[j]:
            card = len(model.cat_domains.get(col, ()))
            return list(range(max(card, 1)))[:6]
        v = X[:, j]
        v = v[~np.isnan(v)]
        if len(v) == 0:          # all-NA sample: single neutral point
            return [0.0]
        return np.unique(np.quantile(
            v, np.linspace(0.05, 0.95, 6))).tolist()

    for a, b in itertools.islice(itertools.combinations(top, 2), max_pairs):
        ja, jb = model.feature_names.index(a), model.feature_names.index(b)
        ga = grid_of(a)
        gb = grid_of(b)
        joint = np.zeros((len(ga), len(gb)))
        for i, va in enumerate(ga):
            for j2, vb in enumerate(gb):
                Xg = X.copy()
                Xg[:, ja] = va
                Xg[:, jb] = vb
                pred = np.asarray(_fetch(
                    model._predict_matrix(jnp.asarray(Xg))))
                if pred.ndim == 2:
                    pred = pred[:, -1]
                joint[i, j2] = pred.mean()
        # H²: fraction of joint PD variance beyond the additive parts
        ma = joint.mean(axis=1, keepdims=True)
        mb = joint.mean(axis=0, keepdims=True)
        additive = ma + mb - joint.mean()
        denom = max(joint.var(), 1e-30)
        h2 = float(((joint - additive) ** 2).mean() / denom)
        rows.append({"pair": (a, b), "h_squared": h2})
    rows.sort(key=lambda r: -r["h_squared"])
    return rows


def interaction_frame(frame: Frame, factors: Sequence, pairwise: bool = False,
                      max_factors: int = 100, min_occurrence: int = 1) -> Frame:
    """Categorical interaction features (hex/Interaction + water/rapids
    InteractionWrappedVec; h2o.interaction): combine the given factor
    columns into new enum column(s) whose levels are the observed value
    combinations, keeping the ``max_factors`` most frequent levels (the
    rest collapse into 'other') and dropping levels seen fewer than
    ``min_occurrence`` times."""
    cols = [frame.names[i] if isinstance(i, int) else i for i in factors]
    for c in cols:
        if c not in frame.names:
            raise ValueError(f"unknown column '{c}'")
    pairs = ([(a, b) for i, a in enumerate(cols) for b in cols[i + 1:]]
             if pairwise else [tuple(cols)])
    names, vecs = [], []
    for group in pairs:
        labels_per_col = []
        codes_per_col = []
        for c in group:
            v = frame.vec(c)
            if v.is_categorical:
                dom = list(v.domain)
                codes = np.asarray(_fetch(v.as_float()))[: frame.nrow]
                codes = np.where(np.isnan(codes), -1, codes).astype(int)
                labels_per_col.append(dom)
                codes_per_col.append(codes)
            else:
                d = v.to_numpy()
                vals = sorted({x for x in d[~np.isnan(d)]})
                lut = {x: i for i, x in enumerate(vals)}
                codes = np.array([lut.get(x, -1) if not np.isnan(x) else -1
                                  for x in d], dtype=int)
                labels_per_col.append([repr(float(x)) for x in vals])
                codes_per_col.append(codes)
        # vectorized combo encoding: np.unique over the stacked code
        # matrix finds observed combinations + frequencies in one pass
        # (a per-row Python loop takes minutes at 10M rows)
        stacked = np.stack(codes_per_col)               # [G, rows]
        valid = (stacked >= 0).all(axis=0)
        vcols = stacked[:, valid]
        uniq, inverse, counts = np.unique(
            vcols, axis=1, return_inverse=True, return_counts=True)
        combo_codes = np.full(stacked.shape[1], -1, np.int64)
        combo_codes[valid] = inverse
        # rank by frequency; keep max_factors, honor min_occurrence
        order_k = np.argsort(-counts, kind="stable")
        keep = [int(k) for k in order_k
                if counts[k] >= min_occurrence][:max_factors]
        remap = {k: i for i, k in enumerate(keep)}
        other = len(keep)
        has_other = len(keep) < uniq.shape[1]
        dom = ["_".join(labels_per_col[j][int(uniq[j, k])]
                        for j in range(len(group))) for k in keep]
        if has_other:
            dom.append("other")
        lut = np.full(uniq.shape[1], other, np.int32)
        lut[np.asarray(keep, int)] = np.arange(len(keep), dtype=np.int32)
        out = np.where(combo_codes >= 0, lut[np.maximum(combo_codes, 0)],
                       -1).astype(np.int32)
        names.append("_".join(group))
        vecs.append(Vec.from_numpy(out, vtype=T_ENUM, domain=tuple(dom)))
    return Frame(names, vecs)
